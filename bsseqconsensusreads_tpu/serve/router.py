"""graftfleet router tier: tenant routing over a replica fleet.

The router speaks the same serve protocol as a single replica — `cli
submit --socket tcp:host:port` cannot tell a fleet from one engine —
and owns three jobs a single process never had:

* **placement** — each submit is fingerprinted by its input identity
  (serve.jobs.input_fingerprint, the PR 5 checkpoint digest). A repeat
  input is routed back to the replica that saw it last (`affinity_hits`
  — warm guard state, warm page cache, warm per-input compile shapes);
  a fresh input lands on the replica with the fewest outstanding jobs
  (`jobs_routed` counts every placement). Forwarding is retried under
  the `fleet_route` failpoint, so a transient route-path I/O error is a
  retry, not a refused tenant.
* **drain/handoff** — a monitor thread watches replica liveness. When
  a replica dies (crash, kill -9, chaos `fleet_replica_exit`), every
  job placed on it that the router has not yet seen retire is
  resubmitted to a survivor (`jobs_requeued`). Jobs are idempotent —
  a replica writes output via tmp+rename at job finish — so a requeued
  job's bytes are identical whether the dead replica had done none,
  half, or all of the work. Supervised replicas are respawned under
  the same id (`replica_restarts`) and rejoin placement warm via the
  shared compile cache.
* **reconciliation** — `stats` aggregates router counters with every
  live replica's own counters, and the fleet ledger carries
  `fleet_route`/`fleet_requeue` lines, so
  jobs_routed + jobs_requeued == sum of per-replica admissions is a
  checkable invariant, not a hope (tests/test_fleet.py).

Client-visible job ids are router-scoped (`f0001`, ...); the mapping
to (replica, replica-local id) is router state and survives handoff —
a tenant's `wait` parked across a replica death completes against the
survivor without the tenant ever reconnecting.
"""

from __future__ import annotations

import hashlib
import subprocess
import threading
import time

from bsseqconsensusreads_tpu.faults import failpoints as _failpoints
from bsseqconsensusreads_tpu.serve import fleet as _fleet
from bsseqconsensusreads_tpu.serve import jobs as _jobs
from bsseqconsensusreads_tpu.serve import transport as _transport
from bsseqconsensusreads_tpu.serve.server import ProtocolServer
from bsseqconsensusreads_tpu.utils import observe

#: Terminal replica-side job states (serve.jobs.DONE / FAILED).
_TERMINAL = frozenset({"done", "failed"})


class RoutedJob:
    """Router-side view of one tenant job: the spec (kept verbatim for
    requeue), its affinity digest, and the current placement."""

    def __init__(self, rid: str, spec: dict, digest: str):
        self.rid = rid
        self.spec = spec
        self.digest = digest
        self.replica_id: str | None = None
        self.remote_id: str | None = None
        self.state = "routed"
        self.last: dict = {}
        self.requeues = 0
        self.submitted_s = time.monotonic()
        #: causal trace context minted at router admission; requeues and
        #: the replica-side job adopt it, so one tree spans every attempt
        self.trace: dict | None = None

    def snapshot(self) -> dict:
        out = dict(self.last)
        out.update(
            {
                "id": self.rid,
                "state": self.state if self.state in _TERMINAL
                else self.last.get("state", self.state),
                "replica": self.replica_id,
                "remote_id": self.remote_id,
                "requeues": self.requeues,
            }
        )
        if self.trace is not None:
            out["trace"] = self.trace["trace"]
        return out


class Router:
    """Placement + handoff over a fleet.ReplicaSet. Thread-safe: the
    server front dispatches from per-connection threads, the monitor
    runs on its own thread, all placement state sits under one lock."""

    def __init__(
        self,
        replicas: _fleet.ReplicaSet,
        *,
        affinity: bool = True,
        respawn: bool = True,
        forward_retries: int = 3,
        forward_timeout: float = 60.0,
        monitor_interval: float = 0.25,
    ):
        self.fleet = replicas
        self.affinity_enabled = affinity
        self.respawn = respawn
        self.forward_retries = forward_retries
        self.forward_timeout = forward_timeout
        self.monitor_interval = monitor_interval
        self._lock = threading.Lock()
        self._jobs: dict[str, RoutedJob] = {}
        self._affinity: dict[str, str] = {}  # digest -> replica id
        self._seq = 0
        self.counters = {
            "jobs_routed": 0,
            "jobs_requeued": 0,
            "affinity_hits": 0,
            "replica_restarts": 0,
            "jobs_shed": 0,
        }
        self._stop = threading.Event()
        self._monitor_thread: threading.Thread | None = None

    # -- lifecycle -------------------------------------------------------

    def launch(self, ready_timeout: float = 180.0) -> "Router":
        self.fleet.launch()
        self.fleet.wait_ready(timeout=ready_timeout)
        # graftlint: owned-thread -- single liveness monitor: it owns
        # requeue/respawn and takes self._lock for every shared mutation
        self._monitor_thread = threading.Thread(
            target=self._monitor, name="fleet-monitor", daemon=True
        )
        self._monitor_thread.start()
        return self

    def shutdown(self, drain_timeout: float = 120.0) -> None:
        self._stop.set()
        if self._monitor_thread is not None:
            self._monitor_thread.join(timeout=5.0)
        self.fleet.stop(drain_timeout=drain_timeout)
        observe.emit("fleet_counters", dict(self.counters))
        observe.flush_sinks()

    # -- placement -------------------------------------------------------

    @staticmethod
    def _digest(spec: dict) -> str:
        """The affinity key: the PR 5 input-fingerprint identity
        (path+bytes+mtime), digested. Unstat-able inputs still route
        (admission will refuse them at the replica, with the reason)."""
        try:
            fp = _jobs.input_fingerprint(str(spec.get("input", "")))
        except OSError:
            fp = {"path": str(spec.get("input", ""))}
        text = f"{fp.get('path')}|{fp.get('bytes')}|{fp.get('mtime')}"
        return hashlib.sha256(text.encode()).hexdigest()[:16]

    def _outstanding(self, replica_id: str) -> int:
        return sum(
            1
            for j in self._jobs.values()
            if j.replica_id == replica_id and j.state not in _TERMINAL
        )

    def _place(self, digest: str) -> tuple[_fleet.Replica, bool]:
        """Choose a live replica under the lock: affinity first, else
        least outstanding. Raises FleetError with no survivors."""
        alive = self.fleet.alive()
        if not alive:
            raise _fleet.FleetError("no live replicas")
        if self.affinity_enabled:
            want = self._affinity.get(digest)
            if want is not None:
                for replica in alive:
                    if replica.rid == want:
                        return replica, True
        replica = min(
            alive, key=lambda r: (self._outstanding(r.rid), r.rid)
        )
        return replica, False

    def submit(self, spec: dict) -> dict:
        self._shed_check()
        digest = self._digest(spec)
        with self._lock:
            self._seq += 1
            job = RoutedJob(f"f{self._seq:04d}", dict(spec), digest)
            self._jobs[job.rid] = job
        # router admission is THE mint point for a fleet job's trace:
        # the context rides every forward (original and requeued) so the
        # replica-side job joins the same causal tree
        job.trace = observe.current_trace() or observe.mint_trace(
            "job", job.rid
        )
        resp = self._route(job, exclude=None)
        if not resp.get("ok"):
            with self._lock:
                job.state = "failed"
                job.last = {"error": resp.get("error")}
            return resp
        return {"ok": True, "job": job.snapshot()}

    def _shed_check(self) -> None:
        """Router-tier overload watermark (BSSEQ_TPU_ADMIT_WATERMARK,
        disabled when unset): at or above `watermark` open routed jobs,
        admission sheds with the typed `overloaded` refusal + a backlog-
        proportional retry hint instead of piling more forwards onto a
        fleet that is already behind."""
        watermark = _jobs.admit_watermark(0)
        if not watermark:
            return
        with self._lock:
            depth = sum(
                1 for j in self._jobs.values() if j.state not in _TERMINAL
            )
            if depth < watermark:
                return
            self.counters["jobs_shed"] += 1
        retry = round(min(5.0, max(0.05, 0.02 * depth)), 3)
        observe.emit(
            "jobs_shed",
            {"depth": depth, "watermark": watermark,
             "retry_after_s": retry},
        )
        err = _transport.TransportError(
            f"router at depth {depth} >= watermark {watermark}; job shed",
            reason="overloaded",
        )
        err.retry_after_s = retry
        raise err

    def _route(self, job: RoutedJob, exclude: str | None) -> dict:
        """Place + forward one job, retrying transient route errors and
        falling through to other replicas on hard ones."""
        last_error = "no live replicas"
        last_shed: dict | None = None
        tried: set[str] = set([exclude] if exclude else [])
        for _ in range(max(1, len(self.fleet.replicas)) * 2):
            with self._lock:
                try:
                    replica, was_affinity = self._place(job.digest)
                except _fleet.FleetError as exc:
                    return {"ok": False, "error": str(exc)}
                if replica.rid in tried:
                    # every untried survivor refused: give up with the
                    # last refusal (admission errors are the tenant's)
                    alive = {r.rid for r in self.fleet.alive()}
                    if alive <= tried:
                        if last_shed is not None:
                            return last_shed
                        return {"ok": False, "error": last_error}
                    # fall through the affinity pin to a fresh replica
                    fresh = [
                        r for r in self.fleet.alive() if r.rid not in tried
                    ]
                    replica = min(
                        fresh,
                        key=lambda r: (self._outstanding(r.rid), r.rid),
                    )
                    was_affinity = False
            resp = self._forward(job, replica)
            if resp.get("ok"):
                remote = resp["job"]
                with self._lock:
                    job.replica_id = replica.rid
                    job.remote_id = remote.get("id")
                    job.state = "placed"
                    job.last = remote
                    self.counters["jobs_routed"] += 1
                    if was_affinity:
                        self.counters["affinity_hits"] += 1
                    if self.affinity_enabled:
                        self._affinity[job.digest] = replica.rid
                with observe.bind_trace(job.trace):
                    observe.emit(
                        "fleet_route",
                        {
                            "rjob": job.rid,
                            "replica_id": replica.rid,
                            "remote_id": job.remote_id,
                            "affinity": was_affinity,
                        },
                    )
                return resp
            last_error = str(resp.get("error"))
            if resp.get("guard") == "overloaded":
                # keep the TYPED refusal: the client's backoff loop
                # keys on `guard`/`retry_after_s`, not the message
                last_shed = resp
            tried.add(replica.rid)
        if last_shed is not None:
            return last_shed
        return {"ok": False, "error": last_error}

    def _forward(self, job: RoutedJob, replica: _fleet.Replica) -> dict:
        """One bounded-retry submit against one replica. The
        `fleet_route` failpoint sits inside the retry loop: an injected
        transient I/O error exercises exactly the retry the grammar
        promises (chaos: fleet_router_transient_io)."""
        last: Exception | None = None
        shed_resp: dict | None = None
        with observe.bind_trace(job.trace) as trace_ctx:
            for _ in range(self.forward_retries):
                try:
                    _failpoints.fire(
                        "fleet_route", stage="fleet", job=job.rid
                    )
                    # trace_ctx bound above rides the wire as `_trace`
                    resp = _transport.request(
                        replica.address,
                        {"op": "submit", "spec": job.spec},
                        timeout=self.forward_timeout,
                    )
                except _transport.TransportError as exc:
                    return {"ok": False, "error": f"refused: {exc}"}
                except (OSError, ConnectionError) as exc:
                    last = exc
                    if not replica.alive():
                        break
                    time.sleep(0.05)
                    continue
                if (not resp.get("ok")
                        and resp.get("guard") == "overloaded"):
                    # typed shed: back off by the replica's own hint
                    # (bounded by the retry budget), then try again —
                    # exhaustion falls back to _route's re-placement
                    shed_resp = resp
                    time.sleep(
                        min(2.0, float(resp.get("retry_after_s") or 0.1))
                    )
                    continue
                return resp
        if shed_resp is not None:
            return shed_resp
        return {"ok": False, "error": f"forward to {replica.rid}: {last}"}

    # -- tenant-facing ops ----------------------------------------------

    def job_status(self, rid: str) -> dict | None:
        with self._lock:
            job = self._jobs.get(rid)
            if job is None:
                return None
            replica_id, remote_id = job.replica_id, job.remote_id
            if job.state in _TERMINAL:
                return job.snapshot()
        replica = self.fleet.lookup(replica_id) if replica_id else None
        if replica is not None and replica.alive() and remote_id:
            try:
                resp = _transport.request(
                    replica.address,
                    {"op": "status", "job": remote_id},
                    timeout=10.0,
                )
                if resp.get("ok"):
                    self._absorb(job, resp["job"])
            except (OSError, ConnectionError):
                pass  # monitor will requeue; report the router's view
        with self._lock:
            return job.snapshot()

    def _absorb(self, job: RoutedJob, remote_status: dict) -> None:
        with self._lock:
            job.last = remote_status
            if remote_status.get("state") in _TERMINAL:
                job.state = remote_status["state"]

    def wait_job(self, rid: str, timeout: float | None = None) -> dict | None:
        with self._lock:
            if rid not in self._jobs:
                return None
        deadline = (
            None if timeout is None else time.monotonic() + timeout
        )
        while True:
            with self._lock:
                job = self._jobs[rid]
                state = job.state
                replica_id, remote_id = job.replica_id, job.remote_id
            if state in _TERMINAL:
                return self.job_status(rid)
            replica = (
                self.fleet.lookup(replica_id) if replica_id else None
            )
            if replica is not None and replica.alive() and remote_id:
                slice_s = 1.0
                if deadline is not None:
                    slice_s = min(
                        slice_s, max(deadline - time.monotonic(), 0.05)
                    )
                try:
                    resp = _transport.request(
                        replica.address,
                        {
                            "op": "wait", "job": remote_id,
                            "timeout": slice_s,
                        },
                        timeout=slice_s + 10.0,
                    )
                    if resp.get("job", None):
                        self._absorb(job, resp["job"])
                except (OSError, ConnectionError):
                    # replica died under our wait: the monitor requeues,
                    # we keep waiting against the new placement
                    self._stop.wait(0.1)
            else:
                self._stop.wait(0.1)
            if deadline is not None and time.monotonic() >= deadline:
                return self.job_status(rid)

    def fleet_stats(self) -> dict:
        with self._lock:
            jobs = [j.snapshot() for j in self._jobs.values()]
            counters = dict(self.counters)
            affinity_size = len(self._affinity)
        per_replica: dict[str, dict] = {}
        for replica in self.fleet.replicas:
            entry: dict = {
                "address": replica.address,
                "alive": replica.alive(),
                "generation": replica.generation,
            }
            if replica.alive() and replica.address:
                try:
                    resp = _transport.request(
                        replica.address, {"op": "stats"}, timeout=10.0
                    )
                    if resp.get("ok"):
                        stats = resp["stats"]
                        entry["jobs"] = len(stats.get("jobs", []))
                        entry["counters"] = stats.get("counters", {})
                except (OSError, ConnectionError):
                    pass
            per_replica[replica.rid] = entry
        return {
            "jobs": jobs,
            "counters": counters,
            "affinity_entries": affinity_size,
            "replicas": per_replica,
        }

    def metrics_dict(self) -> dict:
        """Live gauges/counters for the `metrics` protocol op: placement
        state the router already owns — no replica round-trips, so a
        poller can hit this at high frequency without perturbing the
        fleet."""
        with self._lock:
            jobs = list(self._jobs.values())
            counters = dict(self.counters)
            affinity_size = len(self._affinity)
            inflight = {
                r.rid: self._outstanding(r.rid)
                for r in self.fleet.replicas
            }
        states: dict[str, int] = {}
        for j in jobs:
            st = j.state if j.state in _TERMINAL else "open"
            states[st] = states.get(st, 0) + 1
        return {
            "component": "router",
            "jobs_total": len(jobs),
            "jobs_open": states.get("open", 0),
            "jobs_by_state": states,
            "per_replica_inflight": inflight,
            "replicas_alive": len(self.fleet.alive()),
            "replicas_total": len(self.fleet.replicas),
            "affinity_entries": affinity_size,
            "counters": counters,
        }

    def drain(self, timeout: float | None = None) -> bool:
        """Wait until every routed job is terminal (requeues included),
        then drain the replicas themselves."""
        deadline = (
            None if timeout is None else time.monotonic() + timeout
        )
        while True:
            with self._lock:
                open_jobs = [
                    j for j in self._jobs.values()
                    if j.state not in _TERMINAL
                ]
            if not open_jobs:
                break
            for job in open_jobs:
                self.job_status(job.rid)
            if deadline is not None and time.monotonic() >= deadline:
                return False
            self._stop.wait(0.2)
        return True

    # -- the monitor: liveness -> requeue -> respawn ---------------------

    def _monitor(self) -> None:
        while not self._stop.is_set():
            for replica in list(self.fleet.replicas):
                if replica.alive() or not replica.supervised:
                    continue
                self._handle_death(replica)
            self._stop.wait(timeout=self.monitor_interval)

    def _handle_death(self, replica: _fleet.Replica) -> None:
        rc = replica.proc.returncode if replica.proc else None
        observe.emit(
            "fleet_replica_down",
            {"replica_id": replica.rid, "returncode": rc},
        )
        with self._lock:
            orphans = [
                j for j in self._jobs.values()
                if j.replica_id == replica.rid and j.state not in _TERMINAL
            ]
            for job in orphans:
                job.state = "requeued"
                job.remote_id = None
        # requeue BEFORE respawn: survivors take the work now, the
        # respawned replica rejoins placement for future jobs only
        for job in orphans:
            with self._lock:
                job.requeues += 1
                self.counters["jobs_requeued"] += 1
                from_replica = replica.rid
            resp = self._route(job, exclude=replica.rid)
            # same trace id across attempts: the killed attempt's trace
            # ends in THIS requeue line, and the survivor's spans are
            # children of the same tree — `observe check` requires it
            with observe.bind_trace(job.trace):
                observe.emit(
                    "fleet_requeue",
                    {
                        "rjob": job.rid,
                        "from_replica": from_replica,
                        "to_replica": job.replica_id,
                        "ok": bool(resp.get("ok")),
                    },
                )
            if not resp.get("ok"):
                with self._lock:
                    job.state = "failed"
                    job.last = {"error": resp.get("error")}
        if self.respawn and not self._stop.is_set():
            # counted at initiation, not completion: the counter must
            # already reconcile while the new process is still booting
            with self._lock:
                self.counters["replica_restarts"] += 1
            try:
                self.fleet.restart(replica)
            except _fleet.FleetError as exc:
                observe.emit(
                    "fleet_restart_failed",
                    {"replica_id": replica.rid, "error": str(exc)},
                )

    # -- voluntary replica preemption ------------------------------------

    def preempt_replica(self, replica_id: str,
                        grace_s: float = 30.0) -> dict:
        """Voluntary drain of one replica: take it out of placement,
        migrate its non-retired jobs to survivors (the SAME requeue
        machinery a death uses — but loudly, `worker_preempted`, and
        with no respawn), then terminate and reap the process. The
        monitor never books this exit as a death because the replica is
        detached from supervision before the process goes down."""
        replica = self.fleet.lookup(replica_id)
        if replica is None:
            return {"ok": False, "error": f"unknown replica {replica_id!r}"}
        if not replica.alive():
            return {"ok": False,
                    "error": f"replica {replica_id} is not alive"}
        # detach FIRST: supervised -> False and alive() -> False, so the
        # monitor skips it and placement stops choosing it — without
        # this, the kill below would race _handle_death into a double
        # requeue plus an unwanted respawn
        proc = replica.proc
        address = replica.address
        replica.proc = None
        replica.address = ""
        with self._lock:
            orphans = [
                j for j in self._jobs.values()
                if j.replica_id == replica_id and j.state not in _TERMINAL
            ]
            for job in orphans:
                job.state = "requeued"
                job.remote_id = None
            # affinity pins to a leaving replica would re-place repeat
            # inputs onto nothing
            self._affinity = {
                d: r for d, r in self._affinity.items() if r != replica_id
            }
        observe.emit(
            "worker_preempted",
            {"worker": replica_id, "reason": "drain",
             "jobs_migrated": len(orphans)},
        )
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=grace_s)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10.0)
        elif proc is None and address:
            # attached (unsupervised) replica: ask it to drain itself;
            # its own supervisor owns the process
            try:
                _transport.request(
                    address,
                    {"op": "drain", "timeout": grace_s,
                     "sent_s": time.time()},
                    timeout=grace_s + 10.0,
                )
            except (OSError, ConnectionError):
                pass
        for job in orphans:
            with self._lock:
                job.requeues += 1
                self.counters["jobs_requeued"] += 1
            resp = self._route(job, exclude=replica_id)
            with observe.bind_trace(job.trace):
                observe.emit(
                    "fleet_requeue",
                    {
                        "rjob": job.rid,
                        "from_replica": replica_id,
                        "to_replica": job.replica_id,
                        "ok": bool(resp.get("ok")),
                    },
                )
            if not resp.get("ok"):
                with self._lock:
                    job.state = "failed"
                    job.last = {"error": resp.get("error")}
        return {"ok": True, "replica": replica_id,
                "migrated": len(orphans)}


class RouterServer(ProtocolServer):
    """The router's socket front: same ops as a single replica, plus
    `fleet` (router counters + per-replica reconciliation view)."""

    def __init__(self, router: Router, socket_path=None, *,
                 addresses=None, ready_file: str | None = None):
        super().__init__(socket_path, addresses=addresses,
                         ready_file=ready_file)
        self.router = router

    def _on_drain(self) -> None:
        self.router.drain(timeout=None)
        self.router.shutdown()

    def _dispatch(self, req: dict) -> dict:
        op = req.get("op")
        if op == "ping":
            return {"ok": True, "pong": True, "router": True}
        if op == "submit":
            return self.router.submit(req.get("spec") or {})
        if op == "status":
            st = self.router.job_status(str(req.get("job")))
            if st is None:
                return {
                    "ok": False,
                    "error": f"unknown job {req.get('job')!r}",
                }
            return {"ok": True, "job": st}
        if op == "wait":
            timeout = req.get("timeout")
            st = self.router.wait_job(
                str(req.get("job")),
                timeout=float(timeout) if timeout is not None else None,
            )
            if st is None:
                return {
                    "ok": False,
                    "error": f"unknown job {req.get('job')!r}",
                }
            return {"ok": st.get("state") in _TERMINAL, "job": st}
        if op in ("stats", "fleet"):
            return {"ok": True, "stats": self.router.fleet_stats()}
        if op == "metrics":
            return {"ok": True, "metrics": self.router.metrics_dict()}
        if op == "preempt":
            return self.router.preempt_replica(
                str(req.get("replica") or "")
            )
        if op == "drain":
            return self._drain_op(req)
        return {"ok": False, "error": f"unknown op {op!r}"}
