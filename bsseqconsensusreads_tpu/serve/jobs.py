"""Serve job queue: specs, graftguard admission, fingerprints.

A job is one BAM in → one consensus BAM out, exactly the unit a
standalone `cli molecular` run processes — the serve engine's identity
contract is stated per job. Submission is two-phase:

    admit    cheap, synchronous, in the submitter's thread: the spec is
             validated, the guard policy resolved, the input's header
             structurally probed (graftguard admission — a BAM whose
             header doesn't parse is refused with AdmissionError before
             it can occupy a scheduler slot), and the job fingerprinted
             like a checkpoint (input {path, bytes, mtime} + config
             digest) so a ledger line proves WHAT was served.
    run      asynchronous: the scheduler claims the job, streams its
             families through a per-tenant guard, and retires its
             output (serve/scheduler.py).

The pending queue is BOUNDED (maxsize) and every blocking wait carries
a timeout — the blocking-scheduler-loop lint rule (analysis/
rules_serve.py) holds this package to that discipline.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from dataclasses import dataclass

from bsseqconsensusreads_tpu.faults import failpoints as _failpoints
from bsseqconsensusreads_tpu.faults import guard as _guard
from bsseqconsensusreads_tpu.utils import observe


class AdmissionError(ValueError):
    """Submission refused at the door: bad spec, unreadable input, or a
    header that fails the structural probe."""


class QueueClosed(RuntimeError):
    """Submission refused because the engine is draining or stopped."""


#: admission watermark: queue depth at or above which submit sheds
#: instead of blocking (default: the queue's own maxsize — shedding
#: engages exactly where the blocking put would have stalled)
ENV_ADMIT_WATERMARK = "BSSEQ_TPU_ADMIT_WATERMARK"


def admit_watermark(default: int) -> int:
    """Queue-depth shed threshold; 0 disables shedding (legacy
    blocking-put behavior)."""
    raw = os.environ.get(ENV_ADMIT_WATERMARK)
    if raw is None:
        return default
    try:
        return max(0, int(raw))
    except ValueError:
        return default


class OverloadedError(RuntimeError):
    """Submission shed at the admission watermark. Carries the
    `retry_after_s` hint the typed `overloaded` transport refusal
    forwards to the client — backlog-proportional, so a storm's
    retries spread out instead of re-synchronizing."""

    def __init__(self, message: str, retry_after_s: float = 0.25):
        super().__init__(message)
        self.retry_after_s = retry_after_s


#: Job lifecycle states (monotonic: queued → running → done|failed).
QUEUED = "queued"
RUNNING = "running"
DONE = "done"
FAILED = "failed"


@dataclass
class JobSpec:
    """What a tenant asks for. Per-job knobs are the *ingest-side* ones
    (guard policy, grouping, ingest engine) — device-side parameters
    (ConsensusParams, batch size, kernels) are engine-wide, because
    families from different jobs share device batches and a batch has
    one parameter set. A tenant needing different params runs a
    standalone `cli molecular`."""

    input: str
    output: str
    #: graftguard policy for THIS job's ingest (None → engine default /
    #: BSSEQ_TPU_INPUT_POLICY). One tenant reading under quarantine
    #: never loosens another tenant's strict admission.
    policy: str | None = None
    #: MI-group streaming strategy (None → engine default).
    grouping: str | None = None
    #: record ingest engine. Default python: the serve scheduler tags
    #: each family's MI with job provenance (scheduler.JobMi), which
    #: requires the Python group shape end-to-end.
    ingest: str = "python"
    #: library chemistry, per job (None → engine default). The serve
    #: engine runs the MOLECULAR stage, which is chemistry-invariant
    #: (conversion engages at the duplex stage) — so mixed-chemistry
    #: tenants share device batches safely and the field is admission
    #: validation + provenance: it joins the job fingerprint and the
    #: retire stats, so a ledger line proves what chemistry each
    #: tenant's downstream duplex/methyl run should declare.
    chemistry: str | None = None

    def as_dict(self) -> dict:
        return {
            "input": self.input,
            "output": self.output,
            "policy": self.policy,
            "grouping": self.grouping,
            "ingest": self.ingest,
            "chemistry": self.chemistry,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "JobSpec":
        try:
            spec = cls(
                input=str(d["input"]),
                output=str(d["output"]),
                policy=d.get("policy") or None,
                grouping=d.get("grouping") or None,
                ingest=str(d.get("ingest") or "python"),
                chemistry=d.get("chemistry") or None,
            )
        except KeyError as exc:
            raise AdmissionError(f"job spec missing {exc.args[0]!r}") from None
        return spec


def input_fingerprint(path: str) -> dict:
    """{path, bytes, mtime} — the checkpoint manifest's input identity
    (faults.guard.InputChangedError uses the same shape)."""
    st = os.stat(path)
    return {
        "path": os.path.abspath(path),
        "bytes": st.st_size,
        "mtime": int(st.st_mtime),
    }


class Job:
    """One admitted job: spec + fingerprint + lifecycle + the per-tenant
    accounting the scheduler fills in. State transitions happen under
    the owning Scheduler's lock; readers (server status threads) see a
    consistent snapshot via status()."""

    def __init__(self, job_id: str, spec: JobSpec, fingerprint: dict):
        self.id = job_id
        self.spec = spec
        self.fingerprint = fingerprint
        self.state = QUEUED
        self.error: str | None = None
        self.submitted_s = time.monotonic()
        self.started_s: float | None = None
        self.finished_s: float | None = None
        #: wall latency submit → retire (the SERVE_HEAD.json p50/p99 unit)
        self.latency_s: float | None = None
        self.families = 0
        self.consensus_out = 0
        #: signalled on done/failed — ServeEngine.wait() blocks on it
        self.done = threading.Event()
        #: causal trace context {trace, span}: adopted from the wire
        #: (router-minted, via the bound `_trace`) or minted at admission
        self.trace: dict | None = None
        # -- scheduler-owned plumbing (set when the job goes RUNNING) --
        self.stats = None          # per-job StageStats
        self.q: queue.Queue | None = None  # bounded family queue
        self.header = None         # input BAM header (reader thread)
        self.exhausted = False     # EOS dequeued by the merged source
        self.last_chunk: int | None = None  # highest chunk index holding
        #                                     one of this job's families

    def status(self) -> dict:
        d = {
            "id": self.id,
            "state": self.state,
            "input": self.spec.input,
            "output": self.spec.output,
            "families": self.families,
            "consensus_out": self.consensus_out,
            "fingerprint": self.fingerprint,
        }
        if self.spec.chemistry is not None:
            d["chemistry"] = self.spec.chemistry
        if self.error is not None:
            d["error"] = self.error
        if self.latency_s is not None:
            d["latency_s"] = round(self.latency_s, 3)
        if self.trace is not None:
            d["trace"] = self.trace["trace"]
        return d


class JobQueue:
    """Bounded admission queue shared by submitters (server connection
    threads) and the scheduler (claims). Also the job registry — every
    job ever admitted stays resolvable by id for status/wait."""

    def __init__(self, max_pending: int = 64):
        self._pending: queue.Queue = queue.Queue(maxsize=max_pending)
        self._jobs: dict[str, Job] = {}
        self._lock = threading.Lock()
        self._seq = 0
        self._closed = False
        #: overload-shed accounting: every watermark refusal increments
        #: jobs_shed, and the `jobs_shed` ledger event count must
        #: reconcile against it (chaos drill overload_shed scenario)
        self.counters = {"jobs_shed": 0}

    # -- submission ------------------------------------------------------

    def submit(self, spec: JobSpec) -> Job:
        """Admit one job (or raise AdmissionError/QueueClosed). Runs in
        the submitter's thread: validation and the header probe cost the
        tenant who submitted, never the scheduler loop."""
        shed: tuple[int, int] | None = None
        with self._lock:
            if self._closed:
                raise QueueClosed("serve engine is draining; job refused")
            # overload watermark: shed ABOVE capacity instead of
            # blocking the submitter's connection thread against a full
            # queue — the typed refusal carries a backlog-proportional
            # retry hint, so a storm spreads out instead of stacking up
            depth = self._pending.qsize()
            watermark = admit_watermark(self._pending.maxsize)
            if watermark and depth >= watermark:
                self.counters["jobs_shed"] += 1
                shed = (depth, watermark)
            else:
                self._seq += 1
                job_id = f"j{self._seq:04d}"
        if shed is not None:
            depth, watermark = shed
            retry = round(min(5.0, max(0.05, 0.02 * depth)), 3)
            observe.emit(
                "jobs_shed",
                {"depth": depth, "watermark": watermark,
                 "retry_after_s": retry},
            )
            raise OverloadedError(
                f"admission queue at depth {depth} >= watermark "
                f"{watermark}; job shed", retry_after_s=retry,
            )
        _failpoints.fire("serve_submit", stage="serve", job=job_id)
        self._admit(spec)
        fp = {
            "input": input_fingerprint(spec.input),
            "config": observe.config_digest(spec.as_dict()),
        }
        job = Job(job_id, spec, fp)
        # trace admission: adopt the submitter's context (a router-minted
        # trace that rode the wire and was bound around dispatch) or mint
        # a fresh job trace — either way the job carries ONE causal tree
        # id for its whole life across processes
        trace_ctx = observe.current_trace()
        if trace_ctx is None:
            trace_ctx = observe.mint_trace("job", job_id, job=job_id)
        job.trace = trace_ctx
        with self._lock:
            if self._closed:
                raise QueueClosed("serve engine is draining; job refused")
            self._jobs[job_id] = job
        with observe.bind_trace(trace_ctx):
            observe.emit(
                "job_admitted",
                {
                    "input": spec.input,
                    "output": spec.output,
                    "policy": _guard.resolve_policy(spec.policy),
                    "fingerprint": fp,
                },
                job=job_id,
            )
        while True:
            try:
                self._pending.put(job, timeout=0.25)
                return job
            except queue.Full:
                with self._lock:
                    closed = self._closed
                if closed:
                    raise QueueClosed(
                        "serve engine is draining; job refused"
                    ) from None

    def _admit(self, spec: JobSpec) -> None:
        """graftguard admission: resolve the policy (typo'd policies are
        refused here, not deep in a reader thread) and structurally
        probe the input header. Mid-file corruption is NOT probed — that
        is the per-tenant guard's job during ingest, under the job's own
        policy (strict fails the job; quarantine sidecars and
        proceeds)."""
        try:
            _guard.resolve_policy(spec.policy)
        except ValueError as exc:
            raise AdmissionError(str(exc)) from None
        if spec.ingest not in ("auto", "native", "python"):
            raise AdmissionError(f"unknown ingest {spec.ingest!r}")
        if spec.chemistry not in (None, "bisulfite", "emseq", "none"):
            raise AdmissionError(f"unknown chemistry {spec.chemistry!r}")
        if spec.grouping not in (None, "gather", "adjacent", "coordinate"):
            raise AdmissionError(f"unknown grouping {spec.grouping!r}")
        if not spec.output:
            raise AdmissionError("job spec needs an output path")
        try:
            st = os.stat(spec.input)
        except OSError as exc:
            raise AdmissionError(f"input unreadable: {exc}") from None
        if st.st_size == 0:
            raise AdmissionError(f"input empty: {spec.input}")
        from bsseqconsensusreads_tpu.io.bam import BamReader

        try:
            reader = BamReader(spec.input)
        except Exception as exc:  # any header parse failure is refusal
            raise AdmissionError(
                f"input header failed admission: {exc}"
            ) from None
        try:
            reader.close()
        except Exception:
            pass

    # -- scheduler side --------------------------------------------------

    def claim(self) -> Job | None:
        """Pop the next queued job, or None (never blocks — the
        scheduler polls between batches)."""
        try:
            return self._pending.get_nowait()
        except queue.Empty:
            return None

    def close(self) -> None:
        """Stop admitting (drain). Already-queued jobs still run."""
        with self._lock:
            self._closed = True

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def pending_count(self) -> int:
        return self._pending.qsize()

    # -- registry --------------------------------------------------------

    def get(self, job_id: str) -> Job | None:
        with self._lock:
            return self._jobs.get(job_id)

    def jobs(self) -> list[Job]:
        with self._lock:
            return list(self._jobs.values())
