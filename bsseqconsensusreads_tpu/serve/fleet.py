"""graftfleet replica tier: spawn/supervise N serve processes.

A fleet is N `cli serve` replicas, each a full PR 8 resident engine on
its own TCP address, plus the supervision the router tier leans on:

* **spawn** — same-host replicas get kernel-assigned ports
  (``tcp:host:0`` → resolved at bind; the replica prints nothing, the
  supervisor learns the port from the replica's ready file). Every
  replica shares ``BSSEQ_TPU_COMPILE_CACHE_DIR`` (replica N+1 starts
  warm from replica 1's compiles) and carries its identity in
  ``BSSEQ_TPU_REPLICA_ID``, which utils.observe stamps onto every
  ledger line the replica writes — one fleet ledger, per-replica
  sub-streams (`observe summarize --replica rN`).
* **attach** — multihost-ready addressing: `attach_addresses` skips
  spawning entirely and treats the given ``tcp:host:port`` list as
  already-running replicas (a fleet spread over a mesh looks identical
  to the router; only this module's spawn half is same-host).
* **restart** — a dead replica can be respawned under the same id
  (the router counts `replica_restarts`). A one-shot per-replica
  failpoint override (`fail_once`) arms BSSEQ_TPU_FAILPOINTS in ONE
  replica's environment for exactly its first life — how the chaos
  drill kills r0 mid-job without the respawned r0 inheriting the same
  death sentence.

Ready protocol: a spawned replica writes its bound addresses to
``<rundir>/<rid>.addr`` (cli serve `--ready-file`) once listening;
`wait_ready` polls that plus a ping. No replica output is parsed.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time

from bsseqconsensusreads_tpu.serve import transport as _transport
from bsseqconsensusreads_tpu.utils import observe

ENV_REPLICA_ID = "BSSEQ_TPU_REPLICA_ID"


class FleetError(RuntimeError):
    pass


class Replica:
    """One serve replica: identity + address + (when spawned here) the
    child process handle. Attached replicas have proc None and are
    never restarted by this supervisor."""

    def __init__(self, rid: str, address: str = "", proc=None):
        self.rid = rid
        self.address = address
        self.proc = proc
        self.generation = 0

    @property
    def supervised(self) -> bool:
        return self.proc is not None

    def alive(self) -> bool:
        if self.proc is not None:
            return self.proc.poll() is None
        return bool(self.address)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Replica({self.rid}, {self.address}, alive={self.alive()})"


class ReplicaSet:
    """The supervised set. Construct with either `n` (spawn that many
    same-host replicas) or `attach_addresses` (adopt running ones)."""

    def __init__(
        self,
        n: int = 2,
        *,
        host: str = "127.0.0.1",
        rundir: str | None = None,
        serve_args: list[str] | None = None,
        env: dict | None = None,
        attach_addresses: list[str] | None = None,
        compile_cache_dir: str | None = None,
        fail_once: dict | None = None,
    ):
        self.host = host
        self.rundir = rundir or os.path.join(
            os.environ.get("TMPDIR", "/tmp"), f"bsseq-fleet-{os.getpid()}"
        )
        self.serve_args = list(serve_args or [])
        self.base_env = dict(env) if env is not None else dict(os.environ)
        self.compile_cache_dir = compile_cache_dir
        if compile_cache_dir:
            self.base_env["BSSEQ_TPU_COMPILE_CACHE_DIR"] = compile_cache_dir
        #: rid -> failpoint schedule armed for that replica's FIRST life
        self._fail_once = dict(fail_once or {})
        #: readiness-poll pacing; an Event so a future supervisor can
        #: interrupt the wait (sanctioned shape vs. a bare sleep)
        self._poll = threading.Event()
        self.replicas: list[Replica] = []
        if attach_addresses:
            for i, addr in enumerate(attach_addresses):
                _transport.parse_address(addr)  # validate early
                self.replicas.append(Replica(f"r{i}", address=addr))
        else:
            self.replicas = [Replica(f"r{i}") for i in range(n)]

    # -- lifecycle -------------------------------------------------------

    def launch(self) -> "ReplicaSet":
        os.makedirs(self.rundir, exist_ok=True)
        for replica in self.replicas:
            if not replica.address and replica.proc is None:
                self._spawn(replica)
        return self

    def _spawn(self, replica: Replica) -> None:
        addr_file = os.path.join(
            self.rundir, f"{replica.rid}.g{replica.generation}.addr"
        )
        try:
            os.unlink(addr_file)
        except OSError:
            pass
        env = dict(self.base_env)
        env[ENV_REPLICA_ID] = replica.rid
        schedule = self._fail_once.pop(replica.rid, None)
        if schedule:
            env["BSSEQ_TPU_FAILPOINTS"] = schedule
        cmd = [
            sys.executable, "-m", "bsseqconsensusreads_tpu.cli", "serve",
            "--address", f"tcp:{self.host}:0",
            "--ready-file", addr_file,
            *self.serve_args,
        ]
        replica.proc = subprocess.Popen(
            cmd, env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        replica.address = ""
        replica._addr_file = addr_file
        replica._spawned_at = time.time()
        observe.emit(
            "fleet_replica_spawn",
            {"replica_id": replica.rid, "generation": replica.generation,
             "pid": replica.proc.pid},
        )

    def wait_ready(self, timeout: float = 120.0) -> None:
        """Block until every replica is listening and answers a ping."""
        deadline = time.monotonic() + timeout
        for replica in self.replicas:
            self._wait_one(replica, deadline)

    def _wait_one(self, replica: Replica, deadline: float) -> None:
        while time.monotonic() < deadline:
            if replica.proc is not None and replica.proc.poll() is not None:
                raise FleetError(
                    f"replica {replica.rid} exited rc="
                    f"{replica.proc.returncode} before becoming ready"
                )
            addr = replica.address or self._read_addr(replica)
            if addr:
                try:
                    resp = _transport.request(
                        addr, {"op": "ping"}, timeout=5.0
                    )
                    if resp.get("ok", False):
                        replica.address = addr
                        spawned = getattr(replica, "_spawned_at", None)
                        if spawned is not None and (
                            observe.stats_sink() is not None
                        ):
                            # the worker_spawn overhead bucket: spawn →
                            # first answered ping, booked on the proc
                            # trace (a per-process cost, not one job's)
                            observe.emit_span(
                                "worker_spawn", spawned, time.time(),
                                ctx=observe.proc_trace(),
                                replica_id=replica.rid,
                                generation=replica.generation,
                            )
                        return
                except (OSError, ConnectionError):
                    pass  # still booting; the deadline bounds the poll
            self._poll.wait(0.05)
        raise FleetError(f"replica {replica.rid} not ready in time")

    def _read_addr(self, replica: Replica) -> str:
        addr_file = getattr(replica, "_addr_file", None)
        if not addr_file or not os.path.exists(addr_file):
            return ""
        try:
            text = open(addr_file).read().strip()
        except OSError:
            return ""
        for line in text.splitlines():
            if line.startswith("tcp:"):
                return line.strip()
        return ""

    # -- supervision -----------------------------------------------------

    def restart(self, replica: Replica, timeout: float = 120.0) -> None:
        """Respawn a dead supervised replica under the same id (shared
        compile cache makes the new process a warm start)."""
        if not replica.supervised:
            raise FleetError(
                f"replica {replica.rid} is attached, not supervised — "
                "cannot restart it from here"
            )
        replica.generation += 1
        self._spawn(replica)
        self._wait_one(replica, time.monotonic() + timeout)

    def alive(self) -> list[Replica]:
        return [r for r in self.replicas if r.alive()]

    def lookup(self, rid: str) -> Replica | None:
        for r in self.replicas:
            if r.rid == rid:
                return r
        return None

    # -- teardown --------------------------------------------------------

    def stop(self, drain_timeout: float = 60.0) -> None:
        """Drain every live replica, then reap the processes."""
        for replica in self.replicas:
            if not replica.alive() or not replica.address:
                continue
            try:
                _transport.request(
                    replica.address,
                    # sent_s: the replica accounts its drain deadline
                    # from frame-send time, not receipt — a slow accept
                    # queue must not extend the budget
                    {"op": "drain", "timeout": drain_timeout,
                     "sent_s": time.time()},
                    timeout=drain_timeout + 10.0,
                )
            except (OSError, ConnectionError):
                pass
        for replica in self.replicas:
            if replica.proc is None:
                continue
            try:
                replica.proc.wait(timeout=drain_timeout)
            except subprocess.TimeoutExpired:
                replica.proc.kill()
                replica.proc.wait(timeout=10.0)
