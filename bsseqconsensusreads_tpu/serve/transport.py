"""Serve transport: address grammar + guarded wire framing (unix/TCP/TLS).

graftserve's protocol is one JSON object each way per connection. This
module owns how those objects cross a socket, so the server, the
router, and every client agree on exactly one framing per transport:

* ``unix:<path>`` (or a bare filesystem path) — the PR 8 wire format
  unchanged: one newline-terminated JSON line each way. The reader here
  is *bounded*: a line that exceeds ``MAX_FRAME`` bytes without a
  newline is refused, so a hostile peer cannot balloon the resident
  process by never sending ``\\n``.
* ``tcp:<host>:<port>`` — the same JSON payloads, length-framed: a u32
  big-endian byte count, then exactly that many bytes of JSON. TCP is
  a byte stream with no natural record boundary and (unlike the unix
  socket) no filesystem permission wall, so the frame header is the
  admission gate: a declared length of zero or beyond ``MAX_FRAME``
  refuses the frame *before* a single payload byte is buffered.
* TLS rides the tcp transport when ``BSSEQ_TPU_SERVE_TLS_CERT`` /
  ``BSSEQ_TPU_SERVE_TLS_KEY`` name a PEM cert/key: the server wraps
  each accepted connection, clients verify against the cert as its own
  CA (self-signed single-cert deployments; a real PKI just points the
  env at its chain).

Failure policy is graftguard's: garbage frames, oversized payloads,
truncated streams, and non-JSON bodies surface as `TransportError` — a
typed `GuardError` — never a crash and never an unbounded read. The
server answers what it can and closes; the client raises the typed
error to its caller. The ``unframed-socket-read`` lint rule holds the
rest of the package to this module's readers: raw ``recv``/``readline``
on a socket belongs here and nowhere else.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import time

from bsseqconsensusreads_tpu.faults import netchaos
from bsseqconsensusreads_tpu.faults.guard import GuardError
from bsseqconsensusreads_tpu.utils import observe

#: Hard ceiling on one protocol message (either direction, both
#: transports). Large enough for any stats payload; small enough that a
#: hostile length header cannot make the server allocate real memory.
MAX_FRAME = 8 * 1024 * 1024

_LEN = struct.Struct("!I")

ENV_TLS_CERT = "BSSEQ_TPU_SERVE_TLS_CERT"
ENV_TLS_KEY = "BSSEQ_TPU_SERVE_TLS_KEY"


class TransportError(GuardError, ConnectionError):
    """A wire-level refusal: bad frame, oversized payload, truncation,
    or non-JSON body. GuardError ancestry keeps the fuzz contract
    (hostile bytes -> typed error, never a crash); ConnectionError
    ancestry keeps existing callers that catch socket failures
    working."""

    def __init__(self, message: str, reason: str = "transport"):
        super().__init__(message)
        self.reason = reason


# ---------------------------------------------------------------------------
# Address grammar.


def parse_address(address: str) -> tuple:
    """('unix', path) or ('tcp', host, port). A bare path (no scheme)
    is a unix socket — every PR 8 call site keeps working verbatim."""
    if not isinstance(address, str) or not address:
        raise TransportError(
            f"bad serve address {address!r}", reason="bad_address"
        )
    if address.startswith("unix:"):
        path = address[len("unix:"):]
        if not path:
            raise TransportError(
                f"bad unix address {address!r} (empty path)",
                reason="bad_address",
            )
        return ("unix", path)
    if address.startswith("tcp:"):
        rest = address[len("tcp:"):]
        host, sep, port_s = rest.rpartition(":")
        if not sep or not host or not port_s:
            raise TransportError(
                f"bad tcp address {address!r} (want tcp:host:port)",
                reason="bad_address",
            )
        try:
            port = int(port_s)
        except ValueError:
            raise TransportError(
                f"bad tcp port {port_s!r} in {address!r}",
                reason="bad_address",
            ) from None
        if not 0 <= port <= 65535:
            raise TransportError(
                f"tcp port {port} out of range in {address!r}",
                reason="bad_address",
            )
        return ("tcp", host, port)
    return ("unix", address)


def is_tcp(address: str) -> bool:
    return parse_address(address)[0] == "tcp"


# ---------------------------------------------------------------------------
# TLS (env-driven; tcp only).


def tls_server_context():
    """An SSLContext when the TLS env pair is set, else None."""
    cert = os.environ.get(ENV_TLS_CERT)
    if not cert:
        return None
    import ssl

    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert, os.environ.get(ENV_TLS_KEY) or None)
    return ctx


def tls_client_context():
    """Client context verifying against the server cert as its own CA
    (the self-signed single-cert deployment); None when TLS is off."""
    cert = os.environ.get(ENV_TLS_CERT)
    if not cert:
        return None
    import ssl

    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.check_hostname = False
    ctx.load_verify_locations(cafile=cert)
    return ctx


# ---------------------------------------------------------------------------
# Sockets.


def listen(address: str, backlog: int = 16, timeout: float = 0.25):
    """Bind + listen. Returns (sock, kind, resolved_address) —
    resolved_address substitutes the kernel-assigned port when the
    caller bound port 0 (how the fleet allocates replica ports). TLS
    wrapping happens per accepted connection (`server_wrap`), not on
    the listener, so one bad handshake can never wedge the accept
    loop."""
    parsed = parse_address(address)
    if parsed[0] == "unix":
        path = parsed[1]
        try:
            os.unlink(path)
        except OSError:
            pass
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.bind(path)
        resolved = f"unix:{path}"
    else:
        _, host, port = parsed
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind((host, port))
        resolved = f"tcp:{host}:{sock.getsockname()[1]}"
    sock.listen(backlog)
    sock.settimeout(timeout)
    return sock, parsed[0], resolved


def server_wrap(conn: socket.socket, kind: str) -> socket.socket:
    """TLS-wrap one accepted tcp connection when the env pair is set.
    Handshake failures raise OSError (ssl.SSLError) — the per-
    connection handler treats them as a refused client."""
    if kind != "tcp":
        return conn
    ctx = tls_server_context()
    if ctx is None:
        return conn
    return ctx.wrap_socket(conn, server_side=True)


def connect(address: str, timeout: float = 600.0):
    """Connect a client socket. Returns (sock, kind)."""
    parsed = parse_address(address)
    if parsed[0] == "unix":
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(timeout)
        sock.connect(parsed[1])
        return sock, "unix"
    _, host, port = parsed
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.settimeout(timeout)
    sock.connect((host, port))
    ctx = tls_client_context()
    if ctx is not None:
        sock = ctx.wrap_socket(sock, server_hostname=host)
    return sock, "tcp"


# ---------------------------------------------------------------------------
# The guarded readers/writers — the only sanctioned socket I/O in the
# package (lint rule: unframed-socket-read).


def _recv_exact(conn: socket.socket, n: int, what: str) -> bytes:
    """Exactly n bytes or a typed truncation error; b'' only when the
    peer closed cleanly before the FIRST byte of `what`."""
    chunks: list[bytes] = []
    got = 0
    while got < n:
        # graftlint: disable=unframed-socket-read -- this IS the framed
        # reader: the byte count was admitted against MAX_FRAME first
        chunk = conn.recv(min(n - got, 1 << 16))
        if not chunk:
            if not chunks:
                return b""
            raise TransportError(
                f"truncated {what}: peer closed after {got}/{n} bytes",
                reason="truncated_frame",
            )
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def _decode(data: bytes, max_bytes: int) -> dict:
    if len(data) > max_bytes:
        raise TransportError(
            f"oversized message: {len(data)} bytes > {max_bytes}",
            reason="oversized_frame",
        )
    try:
        obj = json.loads(data)
    except (UnicodeDecodeError, json.JSONDecodeError) as exc:
        raise TransportError(
            f"garbage frame: not JSON ({exc})", reason="bad_json"
        ) from None
    if not isinstance(obj, dict):
        raise TransportError(
            f"garbage frame: JSON {type(obj).__name__}, want object",
            reason="bad_json",
        )
    return obj


def recv_message(
    conn: socket.socket, kind: str, max_bytes: int = MAX_FRAME
) -> dict | None:
    """One guarded protocol message, or None on clean EOF before any
    byte. All refusals are TransportError (typed GuardError)."""
    if kind == "tcp":
        header = _recv_exact(conn, _LEN.size, "frame header")
        if not header:
            return None
        (length,) = _LEN.unpack(header)
        if length == 0 or length > max_bytes:
            raise TransportError(
                f"refused frame: declared length {length} "
                f"(admissible 1..{max_bytes})",
                reason="oversized_frame" if length else "empty_frame",
            )
        return _decode(_recv_exact(conn, length, "frame body"), max_bytes)
    # unix: newline-delimited JSON, read BOUNDED — a peer that never
    # sends '\n' is refused at max_bytes, not buffered forever
    buf = bytearray()
    while True:
        # graftlint: disable=unframed-socket-read -- this IS the
        # bounded line reader the rest of the package must call
        chunk = conn.recv(1 << 16)
        if not chunk:
            if not buf:
                return None
            break  # EOF terminates the line (lenient: PR 8 clients)
        buf.extend(chunk)
        if b"\n" in chunk:
            break
        if len(buf) > max_bytes:
            raise TransportError(
                f"unframed line exceeds {max_bytes} bytes with no "
                "newline", reason="oversized_frame",
            )
    line, _, _ = bytes(buf).partition(b"\n")
    return _decode(line, max_bytes)


def send_message(
    conn: socket.socket, kind: str, obj: dict, _corrupt: bool = False
) -> None:
    data = json.dumps(obj).encode()
    if len(data) > MAX_FRAME:
        raise TransportError(
            f"refusing to send oversized message ({len(data)} bytes)",
            reason="oversized_frame",
        )
    if _corrupt:
        # netchaos `corrupt`: flip body bytes AFTER the length header —
        # length stays truthful, so the peer buffers the frame and must
        # refuse it at decode (bad_json), proving garbage never parses
        data = netchaos.mangle(data)
    if kind == "tcp":
        conn.sendall(_LEN.pack(len(data)) + data)
    else:
        conn.sendall(data + b"\n")


def mint_rid() -> str:
    """A request id (nonce) for duplicate-delivery detection: stamped by
    `request()` as the reserved `_rid` key, echoed nowhere, consumed by
    the server's dedup cache. Random, not sequential — two processes
    sharing a worker id must never collide."""
    return os.urandom(8).hex()


def request(address: str, payload: dict, timeout: float = 600.0) -> dict:
    """One client request/response against a serve or router process.
    Raises TransportError on wire refusals, ConnectionError/OSError on
    plain socket failures.

    Trace carriage: when the calling thread has a bound trace context
    (observe.bind_trace), it rides as the reserved `_trace` key of the
    request object — identical on both framings, since each is one JSON
    object per message — and the round-trip is booked as a 'transport'
    span in that trace. The payload the caller passed is never mutated.

    Duplicate-delivery protection: every request is stamped with a
    reserved `_rid` nonce; servers answer a re-delivered rid from their
    reply cache without re-running the op (`frame_dup_ignored`).

    Wire faults (faults/netchaos.py, sites net_send/net_recv armed via
    BSSEQ_TPU_FAILPOINTS): partition refuses the connection, delay
    sleeps, drop closes without delivering, corrupt mangles the frame
    body (the peer must refuse it), dup re-issues the identical frame —
    same _rid, same _trace — on a fresh connection and discards the
    second reply."""
    trace_ctx = observe.current_trace()
    if trace_ctx is not None and "_trace" not in payload:
        payload = dict(payload, _trace=trace_ctx)
    if "_rid" not in payload:
        payload = dict(payload, _rid=mint_rid())
    fault = netchaos.plan("net_send", peer=address)
    if fault.partition:
        raise ConnectionError(
            f"injected partition: refusing connection to {address}"
        )
    if fault.delay_s:
        time.sleep(fault.delay_s)
    t0 = time.time()
    sock, kind = connect(address, timeout=timeout)
    try:
        if fault.drop:
            # connected, then the frame never arrives: the peer sees a
            # clean EOF, this client a dead exchange
            raise ConnectionError(
                f"injected drop: frame to {address} not delivered"
            )
        send_message(sock, kind, payload, _corrupt=fault.corrupt)
        rfault = netchaos.plan("net_recv", peer=address)
        if rfault.delay_s:
            time.sleep(rfault.delay_s)
        if rfault.drop:
            raise ConnectionError(
                f"injected drop: reply from {address} discarded"
            )
        resp = recv_message(sock, kind)
    finally:
        try:
            sock.close()
        except OSError:
            pass
        if trace_ctx is not None:
            observe.emit_span(
                "transport", t0, time.time(), ctx=trace_ctx,
                op=str(payload.get("op", "")),
            )
    if resp is None:
        raise ConnectionError(f"no response from {address}")
    if fault.dup:
        # second delivery of the SAME frame: fresh connection, identical
        # payload (same _rid); the reply is discarded — the server's
        # dedup cache must answer it without a second state transition
        sock2, kind2 = connect(address, timeout=timeout)
        try:
            send_message(sock2, kind2, payload)
            recv_message(sock2, kind2)
        except (TransportError, OSError):
            pass  # the duplicate best-efforts; the first reply stands
        finally:
            try:
                sock2.close()
            except OSError:
                pass
    return resp
