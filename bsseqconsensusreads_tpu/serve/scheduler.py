"""Continuous-batching scheduler: many jobs, one resident engine.

The ambitious core of graftserve. One `call_molecular_batches` generator
stays alive for the life of the server — its jitted kernels, transport
buffers, and hostpool are compiled/warmed ONCE — and is fed by a
multi-job GroupSource that packs MI families *from different jobs* into
the same device chunks:

    per-job reader threads          the merged source (engine thread)
    ───────────────────────         ──────────────────────────────────
    guarded ingest → families  ──►  bounded queue ─┐
    guarded ingest → families  ──►  bounded queue ─┼─► round-robin pull
    guarded ingest → families  ──►  bounded queue ─┘   → tag JobMi
                                                       → yield family

Provenance: each family's MI is wrapped in JobMi — a str subclass, so
every downstream byte (wire planes, emitted qname) is identical to a
standalone run — carrying `.job`, read back at retire to demultiplex
the batch's records into per-job writers.

Identity: per-job output is byte-identical to a standalone
`cli molecular --batching sequential` run because (a) composition is
pinned sequential, so each job's families dispatch in its own input
order; (b) consensus is a pure per-family function (no cross-family
state), so neighbours from other jobs cannot perturb a family's
records; (c) emission is pinned to the Python emitter, whose per-family
record building is order-local.

Completion: the scheduler mirrors the sequential batcher's chunk
arithmetic (cut at batch_families, cut at FLUSH_BATCH) into a chunk →
{job} log, so "job J is done" is provable as "J's reader hit EOS and
every chunk holding a J family has retired" — exactly-once, no
sentinel records on the wire.

Isolation: one tenant's corrupt input fails only its own reader thread
(per-job Guard, per-job policy); a stalled tenant (failpoint
serve_ingest=stall@job=…) leaves its queue empty and the round-robin
simply passes it by; a family bomb is capped by the tenant's own
guard. Idle periods cut partial chunks (FLUSH_BATCH) and then emit an
empty sync chunk so in-flight batches retire instead of waiting for
load — a lone job's latency is bounded by its own work.

Every queue here is bounded and every blocking wait carries a timeout
(analysis/rules_serve.py `blocking-scheduler-loop` enforces this).
"""

from __future__ import annotations

import os
import queue
import threading
import time

from bsseqconsensusreads_tpu.faults import failpoints as _failpoints
from bsseqconsensusreads_tpu.faults import guard as _guard
from bsseqconsensusreads_tpu.pipeline import calling as _calling
from bsseqconsensusreads_tpu.serve import jobs as _jobs
from bsseqconsensusreads_tpu.utils import compilecache as _compilecache
from bsseqconsensusreads_tpu.utils import observe


class JobMi(str):
    """An MI string tagged with the job that owns its family.

    str subclass: hashing, equality, slicing, and — decisively — the
    emitted consensus qname serialize identically to the plain MI, so
    provenance costs zero bytes on the wire and in the output BAM.
    ops.encode's Python path threads the object through FamilyMeta.mi
    into the emitted record's qname, where the retire demux reads
    `.job` back."""

    __slots__ = ("job",)


class _Shutdown(Exception):
    """Internal: a reader pump aborted because the engine is stopping."""


class Scheduler:
    """Owns the resident engine thread, the per-job reader pumps, and
    the chunk mirror that turns batch retirement into job completion.

    Device-side knobs (params, batch_families, max_window, kernels) are
    engine-wide; per-job knobs (guard policy, grouping, ingest) ride
    JobSpec. Composition is pinned `batching="sequential"` and emission
    `emit="python"` — the two pins the identity contract needs."""

    def __init__(
        self,
        job_queue: _jobs.JobQueue,
        params,
        *,
        mode: str = "unaligned",
        batch_families: int = 64,
        max_window: int = 4096,
        grouping: str = "coordinate",
        indel_policy: str = "drop",
        vote_kernel: str | None = None,
        transport: str = "auto",
        mesh="auto",
        level: int = 6,
        max_active: int = 4,
        stride: int = 8,
        idle_wait_s: float = 0.02,
        family_queue_depth: int = 256,
    ):
        self.queue = job_queue
        self.params = params
        self.mode = mode
        self.batch_families = batch_families
        self.max_window = max_window
        self.grouping = grouping
        self.indel_policy = indel_policy
        self.vote_kernel = vote_kernel
        self.transport = transport
        self.mesh = mesh
        self.level = level
        self.max_active = max_active
        self.stride = max(1, stride)
        self.idle_wait_s = idle_wait_s
        self.family_queue_depth = family_queue_depth
        self.stats = _calling.StageStats(stage="serve")
        self._lock = threading.Lock()
        self._running: list[_jobs.Job] = []
        # chunk mirror: _chunks[i] = job ids whose families rode chunk i
        self._chunks: list[set] = []
        self._open_chunk: list[str] = []
        self._retired = 0
        self._drain = threading.Event()
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: threading.Thread | None = None
        self.engine_error: str | None = None

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        with self._lock:
            # graftlint: owned-thread -- the one resident engine thread;
            # scheduler batching state is engine-thread-owned for its life
            self._thread = threading.Thread(
                target=self._run, name="serve-engine", daemon=True
            )
        self._thread.start()

    def drain(self, timeout: float | None = None) -> bool:
        """Stop admitting, run every already-admitted job to completion,
        stop the engine. Returns True when fully drained (False: the
        deadline passed with work still in flight — nothing is lost,
        the engine keeps running)."""
        self.queue.close()
        self._drain.set()
        self._wake.set()
        if self._thread is None:
            return True
        deadline = None if timeout is None else time.monotonic() + timeout
        while self._thread.is_alive():
            self._thread.join(timeout=0.25)
            if deadline is not None and time.monotonic() >= deadline:
                return not self._thread.is_alive()
        return True

    def stop(self, timeout: float | None = 10.0) -> bool:
        """Drain, but also abort reader pumps blocked on full family
        queues (their jobs fail with 'engine shutdown')."""
        self._stop.set()
        return self.drain(timeout=timeout)

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def counters(self) -> dict:
        return dict(self.stats.metrics.counters)

    # -- per-job reader pump --------------------------------------------

    def _start_job(self, job: _jobs.Job) -> None:
        with self._lock:
            job.state = _jobs.RUNNING
            job.started_s = time.monotonic()
        job.stats = _calling.StageStats(stage="molecular")
        job.q = queue.Queue(maxsize=self.family_queue_depth)
        job._eos = False
        job._writer = None
        job._spool = []
        job._tmp = job.spec.output + ".serve-tmp"
        job._dropped = 0
        self._running.append(job)
        # graftlint: owned-thread -- per-job reader pump; it owns this
        # job's guard/reader/queue alone and hands off via the bounded q
        t = threading.Thread(
            target=self._pump, args=(job,),
            name=f"serve-ingest-{job.id}", daemon=True,
        )
        t.start()

    def _pump(self, job: _jobs.Job) -> None:
        """Reader thread: guarded ingest → tagged families → the job's
        bounded queue. Any failure is THIS tenant's failure: the error
        is recorded on the job and the engine never sees an exception,
        only an exhausted queue. The job's trace context is bound for
        the ingest so every line — and the 'ingest' span covering the
        guarded read — lands in the tenant's causal tree."""
        from bsseqconsensusreads_tpu.pipeline.stages import (
            molecular_ingest_stream,
            open_guarded_reader,
        )

        guard = None
        reader = None
        err = None
        try:
            with observe.bind_trace(job.trace), \
                    observe.span("ingest", job=job.id):
                guard = _guard.Guard(
                    policy=job.spec.policy, stats=job.stats, job=job.id
                )
                reader = open_guarded_reader(job.spec.input, guard)
                job.header = reader.header
                grouping = job.spec.grouping or self.grouping
                records = molecular_ingest_stream(
                    job.spec.input, reader, job.stats,
                    ingest_choice=job.spec.ingest, grouping=grouping,
                    indel_policy=self.indel_policy, guard=guard,
                )
                groups = _guard.guard_groups(
                    _calling.stream_mi_groups(
                        records, grouping=grouping, stats=job.stats
                    ),
                    guard,
                )
                seq = 0
                for fam in groups:
                    if isinstance(fam, tuple):
                        mi, recs = fam
                    else:  # native FamilyRun: materialize the Python shape
                        mi, recs = fam.mi, list(fam.records)
                    seq += 1
                    _failpoints.fire(
                        "serve_ingest", stage="serve", job=job.id, batch=seq
                    )
                    tag = JobMi(mi)
                    tag.job = job.id
                    self._offer(job, (tag, recs))
        except _Shutdown:
            err = "engine shutdown"
        except BaseException as exc:  # tenant-scoped: never escapes
            err = f"{type(exc).__name__}: {exc}"
        finally:
            for closer in (guard, reader):
                try:
                    if closer is not None:
                        closer.close()
                except Exception:
                    pass
            if err is not None:
                with self._lock:
                    if job.error is None:
                        job.error = err
                with observe.bind_trace(job.trace):
                    observe.emit("job_failed", {"error": err}, job=job.id)
            job._eos = True
            self._wake.set()

    def _offer(self, job: _jobs.Job, item) -> None:
        while True:
            try:
                job.q.put(item, timeout=0.25)
                self._wake.set()
                return
            except queue.Full:
                if self._stop.is_set():
                    raise _Shutdown() from None

    # -- the merged multi-job source (engine thread) --------------------

    def _merged(self):
        """The GroupSource generator: round-robin over active jobs,
        `stride` families per job per pass, FLUSH_BATCH on idle. Runs in
        the engine thread — every mutation of the chunk mirror and job
        lifecycle it makes is single-threaded with the retire loop."""
        while True:
            self._admit()
            progressed = False
            for job in list(self._running):
                pulled = 0
                while pulled < self.stride:
                    try:
                        item = job.q.get_nowait()
                    except queue.Empty:
                        if job._eos and not job.exhausted:
                            job.exhausted = True
                            self._sweep()
                        break
                    pulled += 1
                    progressed = True
                    self._track(job)
                    yield item
            if progressed:
                continue
            if self._open_chunk:
                # cut the partial chunk: families stop waiting for load
                self._cut()
                yield _calling.FLUSH_BATCH
                continue
            if self._retired < len(self._chunks):
                # in-flight batches and nothing new arriving: an empty
                # sync chunk drains the deferred-retire pipeline so
                # waiting tenants complete NOW
                self._chunks.append(set())
                yield _calling.FLUSH_BATCH
                continue
            if (
                self._drain.is_set()
                and not self._running
                and self.queue.pending_count() == 0
            ):
                return
            self._wake.wait(self.idle_wait_s)
            self._wake.clear()

    def _admit(self) -> None:
        while len(self._running) < self.max_active:
            job = self.queue.claim()
            if job is None:
                return
            self._start_job(job)

    def _track(self, job: _jobs.Job) -> None:
        self._open_chunk.append(job.id)
        job.last_chunk = len(self._chunks)
        job.families += 1
        if len(self._open_chunk) >= self.batch_families:
            self._cut()

    def _cut(self) -> None:
        self._chunks.append(set(self._open_chunk))
        self._open_chunk = []

    # -- retire / demux (engine thread) ---------------------------------

    def _run(self) -> None:
        t0 = time.monotonic()
        try:
            batches = _calling.call_molecular_batches(
                _calling.GroupSource(self._merged()),
                params=self.params,
                mode=self.mode,
                batch_families=self.batch_families,
                max_window=self.max_window,
                grouping=self.grouping,
                stats=self.stats,
                emit="python",      # identity pin: JobMi must survive emit
                batching="sequential",  # identity pin: per-job input order
                transport=self.transport,
                mesh=self.mesh,
                indel_policy=self.indel_policy,
                vote_kernel=self.vote_kernel,
                guard=None,         # guarding is per-tenant, in the pumps
            )
            for bi, recs in enumerate(batches):
                _failpoints.fire("serve_retire", stage="serve", batch=bi)
                # the fleet kill switch: exit:9@batch=N here is a
                # replica dying MID-JOB with retired batches unswept —
                # exactly the handoff the router must survive
                _failpoints.fire(
                    "fleet_replica_exit", stage="serve", batch=bi
                )
                self._demux(bi, recs)
                self._sweep()
        except BaseException as exc:
            with self._lock:
                self.engine_error = f"{type(exc).__name__}: {exc}"
        finally:
            self._finish_all()
            if not self.stats.wall_seconds:
                self.stats.wall_seconds = time.monotonic() - t0
            observe.emit_stage_stats({"serve": self.stats})
            observe.flush_sinks()

    def _demux(self, bi: int, recs: list) -> None:
        per_job: dict[str | None, list] = {}
        for rec in recs:
            per_job.setdefault(getattr(rec.qname, "job", None), []).append(rec)
        delivered = 0
        for jid, rl in per_job.items():
            job = self.queue.get(jid) if jid is not None else None
            if job is None or job.state != _jobs.RUNNING:
                # a failed tenant's in-flight families: records are
                # dropped, counted, never written
                self.stats.metrics.count("records_dropped", len(rl))
                continue
            self._write(job, rl)
            delivered += 1
        if delivered > 1:
            self.stats.metrics.count("batches_shared_jobs")
        if recs:
            self.stats.metrics.count("serve_batches")
            if observe.stats_sink() is not None:
                # link the shared device chunk into the span forest: a
                # point span under the process overhead trace naming the
                # tenants whose families rode it (the armed-sink guard
                # keeps the untraced hot path at one branch)
                now = time.time()
                observe.emit_span(
                    "chunk_retire", now, now, ctx=observe.proc_trace(),
                    batch=bi, jobs=sorted(
                        j for j in per_job if j is not None
                    ),
                )
        self._retired = bi + 1

    def _write(self, job: _jobs.Job, recs: list) -> None:
        if self.mode == "self":
            job._spool.extend(recs)
        else:
            if job._writer is None:
                from bsseqconsensusreads_tpu.io.bam import BamWriter

                job._writer = BamWriter(
                    job._tmp, job.header, level=self.level
                )
            for rec in recs:
                job._writer.write(rec)
        job.consensus_out += len(recs)

    def _sweep(self) -> None:
        """Complete every job whose stream ended and whose last chunk
        retired (engine thread only). Failed jobs finalize immediately —
        their remaining in-flight records will be dropped at demux."""
        for job in list(self._running):
            if not job.exhausted:
                continue
            if job.error is not None:
                self._fail_job(job)
                continue
            if job.last_chunk is not None and job.last_chunk >= self._retired:
                continue  # families still in flight
            self._finish_job(job)

    def _finish_job(self, job: _jobs.Job) -> None:
        try:
            if self.mode == "self":
                from bsseqconsensusreads_tpu.pipeline.extsort import (
                    write_batch_stream,
                )

                write_batch_stream(
                    iter([job._spool]), job.spec.output, job.header,
                    self.mode, level=self.level,
                )
                job._spool = []
            else:
                if job._writer is None:
                    from bsseqconsensusreads_tpu.io.bam import BamWriter

                    job._writer = BamWriter(
                        job._tmp, job.header, level=self.level
                    )
                job._writer.close()
                job._writer = None
                os.replace(job._tmp, job.spec.output)
        except BaseException as exc:
            with self._lock:
                job.error = f"{type(exc).__name__}: {exc}"
            with observe.bind_trace(job.trace):
                observe.emit("job_failed", {"error": job.error}, job=job.id)
            self._fail_job(job)
            return
        self._running.remove(job)
        with self._lock:
            job.state = _jobs.DONE
            job.finished_s = time.monotonic()
            job.latency_s = job.finished_s - job.submitted_s
        with observe.bind_trace(job.trace):
            self._emit_job_stats(job)
            observe.emit(
                "job_complete",
                {
                    "output": job.spec.output,
                    "families": job.families,
                    "consensus_out": job.consensus_out,
                    "latency_s": round(job.latency_s, 3),
                },
                job=job.id,
            )
        job.done.set()

    def _fail_job(self, job: _jobs.Job) -> None:
        if job._writer is not None:
            try:
                job._writer.close()
            except Exception:
                pass
            job._writer = None
        try:
            if os.path.exists(job._tmp):
                os.remove(job._tmp)
        except OSError:
            pass
        job._spool = []
        if job in self._running:
            self._running.remove(job)
        with self._lock:
            job.state = _jobs.FAILED
            job.finished_s = time.monotonic()
            job.latency_s = job.finished_s - job.submitted_s
        self.stats.metrics.count("jobs_failed")
        with observe.bind_trace(job.trace):
            self._emit_job_stats(job)
        job.done.set()

    def _emit_job_stats(self, job: _jobs.Job) -> None:
        """One standalone-shaped 'stage_stats' ledger line per tenant,
        tagged job=<id> (mirrored to the BSSEQ_TPU_STATS_JOBS sub-sink).
        wall_seconds is the job's submit→done latency; phase seconds are
        deliberately absent — device time is shared engine property and
        lives on the stage='serve' line — so closure checks skip the
        unattributable split instead of failing it."""
        latency = job.latency_s or 0.0
        s = job.stats
        payload = {
            "stage": "molecular",
            "state": job.state,
            "records_in": s.records_in,
            "records_seen": s.records_seen,
            "records_quarantined": s.records_quarantined,
            "records_repaired": s.records_repaired,
            "families_quarantined": s.families_quarantined,
            "family_records_quarantined": s.family_records_quarantined,
            "families": job.families,
            "consensus_out": job.consensus_out,
            "wall_seconds": round(latency, 3),
            "families_per_second": (
                round(job.families / latency, 1) if latency else 0.0
            ),
            "queue_wait_s": round(
                (job.started_s or job.submitted_s) - job.submitted_s, 3
            ),
        }
        if job.spec.chemistry is not None:
            # provenance only: the molecular stage is chemistry-invariant
            # (conversion engages at the duplex stage), but the ledger
            # line records what each tenant's downstream run declared
            payload["chemistry"] = job.spec.chemistry
        observe.emit("stage_stats", payload, job=job.id)

    def _finish_all(self) -> None:
        """Engine end: on a clean drain every job already finalized; on
        an engine crash, fail whatever is left so no submitter blocks on
        a done-event that would never fire."""
        self._retired = len(self._chunks)
        self._sweep()
        err = self.engine_error or "serve engine stopped"
        for job in self.queue.jobs():
            if job.state in (_jobs.DONE, _jobs.FAILED):
                continue
            with self._lock:
                if job.error is None:
                    job.error = err
            with observe.bind_trace(job.trace):
                observe.emit("job_failed", {"error": job.error}, job=job.id)
            job.exhausted = True
            if job.state == _jobs.QUEUED:
                with self._lock:
                    job.state = _jobs.RUNNING  # so _fail_job books it
                job.stats = _calling.StageStats(stage="molecular")
                job._writer = None
                job._spool = []
                job._tmp = job.spec.output + ".serve-tmp"
            self._fail_job(job)
        _compilecache.publish(self.stats.metrics)
