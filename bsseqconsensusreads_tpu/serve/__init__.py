"""graftserve — the resident consensus engine (ISSUE 8).

A long-lived process holds the expensive capital a one-shot CLI run
rebuilds every time — warm jitted kernels, the persistent compile
cache, the hostpool — and amortizes it across many BAM jobs submitted
over a local socket. The three layers:

    jobs.py       job specs, graftguard admission, fingerprinting,
                  the bounded submission queue
    scheduler.py  continuous batching: families from DIFFERENT jobs
                  packed into the same device batch, demultiplexed at
                  retire by per-family job provenance (JobMi)
    server.py     ServeEngine (in-process API) + ServeServer (unix
                  socket JSONL protocol) + client helpers

Identity contract: each job's output BAM is byte-identical to a
standalone `cli molecular --batching sequential` run of the same
input (README "Serving"); isolation contract: one tenant's corrupt
input, family bomb, or stall never blocks another tenant's retirement
(tools/chaos_drill.py serve scenarios).
"""

from bsseqconsensusreads_tpu.serve.jobs import (  # noqa: F401
    AdmissionError,
    Job,
    JobQueue,
    JobSpec,
    QueueClosed,
)
from bsseqconsensusreads_tpu.serve.scheduler import (  # noqa: F401
    JobMi,
    Scheduler,
)
from bsseqconsensusreads_tpu.serve.server import (  # noqa: F401
    ServeEngine,
    ServeServer,
    request,
)
