"""ServeEngine (in-process API) + ServeServer (unix-socket protocol).

The engine is the embeddable form — tests and the tier-1 smoke drive
it directly: submit/wait/drain with no sockets. The server wraps it in
a local unix-socket JSONL protocol for `cli submit` / `cli serve-ctl`:

    one connection = one request = one JSON line each way

    {"op": "ping"}                          → {"ok": true, "pong": true}
    {"op": "submit", "spec": {...JobSpec}}  → {"ok": true, "job": {...}}
    {"op": "status", "job": "j0001"}        → {"ok": true, "job": {...}}
    {"op": "wait", "job": "j0001",
     "timeout": 600}                        → {"ok": true, "job": {...}}
    {"op": "stats"}                         → {"ok": true, "stats": {...}}
    {"op": "drain", "timeout": 600}         → {"ok": true, "drained": b}
                                              (server exits afterwards)

Admission failures answer {"ok": false, "error": ...} — a refused job
is the submitter's problem, never the server's. SIGTERM/SIGINT request
a graceful drain: stop admitting, finish every admitted job, exit 0
(tests/test_serve.py proves no job is lost).

The accept loop polls with a socket timeout and each connection rides
its own daemon thread, so a tenant parked on a long `wait` never
blocks another tenant's submit (and the blocking-scheduler-loop lint
rule holds the loop itself to bounded waits).
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time

from bsseqconsensusreads_tpu.serve import jobs as _jobs
from bsseqconsensusreads_tpu.serve import scheduler as _scheduler
from bsseqconsensusreads_tpu.utils import compilecache as _compilecache
from bsseqconsensusreads_tpu.utils import observe


class ServeEngine:
    """The resident engine: one JobQueue + one Scheduler, warm across
    jobs. Construct, `start()`, then submit/wait from any thread."""

    def __init__(
        self,
        params=None,
        *,
        mode: str = "unaligned",
        batch_families: int = 64,
        max_window: int = 4096,
        grouping: str = "coordinate",
        indel_policy: str = "drop",
        vote_kernel: str | None = None,
        transport: str = "auto",
        mesh="auto",
        level: int = 6,
        max_active: int = 4,
        stride: int = 8,
        idle_wait_s: float = 0.02,
        max_pending: int = 64,
    ):
        if params is None:
            from bsseqconsensusreads_tpu.models.params import ConsensusParams

            params = ConsensusParams(min_reads=1)
        _compilecache.maybe_enable()
        self.queue = _jobs.JobQueue(max_pending=max_pending)
        self.scheduler = _scheduler.Scheduler(
            self.queue,
            params,
            mode=mode,
            batch_families=batch_families,
            max_window=max_window,
            grouping=grouping,
            indel_policy=indel_policy,
            vote_kernel=vote_kernel,
            transport=transport,
            mesh=mesh,
            level=level,
            max_active=max_active,
            stride=stride,
            idle_wait_s=idle_wait_s,
        )
        self._started = False
        self._start_lock = threading.Lock()

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "ServeEngine":
        with self._start_lock:
            if not self._started:
                self._started = True
                self.scheduler.start()
        return self

    def warmup(self) -> None:
        """Compile the engine's kernels on a throwaway synthetic family
        BEFORE the first tenant arrives (with BSSEQ_TPU_COMPILE_CACHE_DIR
        set this is a cache load, not a compile). Runs a separate
        one-shot engine call; the resident generator itself stays
        untouched."""
        import numpy as np

        from bsseqconsensusreads_tpu.pipeline import calling as _calling
        from bsseqconsensusreads_tpu.utils.testing import (
            make_grouped_bam_records,
        )

        rng = np.random.default_rng(0)
        genome = "".join(
            "ACGT"[i] for i in rng.integers(0, 4, size=400)
        )
        _, records = make_grouped_bam_records(
            rng, "warm", genome, n_families=2, reads_per_strand=(2, 2),
            read_len=30,
        )
        stats = _calling.StageStats(stage="warmup")
        for _ in _calling.call_molecular_batches(
            records,
            params=self.scheduler.params,
            mode="unaligned",
            batch_families=4,
            max_window=self.scheduler.max_window,
            grouping="gather",
            stats=stats,
            emit="python",
            batching="sequential",
            transport=self.scheduler.transport,
            indel_policy=self.scheduler.indel_policy,
            vote_kernel=self.scheduler.vote_kernel,
        ):
            pass
        observe.emit(
            "serve_warmup",
            {"families": stats.families, "batches": stats.batches},
        )

    def drain(self, timeout: float | None = None) -> bool:
        return self.scheduler.drain(timeout=timeout)

    def stop(self, timeout: float | None = 10.0) -> bool:
        return self.scheduler.stop(timeout=timeout)

    # -- job API ---------------------------------------------------------

    def submit(self, spec) -> _jobs.Job:
        if isinstance(spec, dict):
            spec = _jobs.JobSpec.from_dict(spec)
        job = self.queue.submit(spec)
        self.scheduler._wake.set()
        return job

    def status(self, job_id: str) -> dict | None:
        job = self.queue.get(job_id)
        return None if job is None else job.status()

    def wait(self, job_id: str, timeout: float | None = None) -> dict | None:
        job = self.queue.get(job_id)
        if job is None:
            return None
        deadline = None if timeout is None else time.monotonic() + timeout
        while not job.done.is_set():
            job.done.wait(timeout=0.25)
            if deadline is not None and time.monotonic() >= deadline:
                break
        return job.status()

    def stats_dict(self) -> dict:
        jobs = self.queue.jobs()
        return {
            "jobs": [j.status() for j in jobs],
            "pending": self.queue.pending_count(),
            "counters": self.scheduler.counters(),
            "engine_alive": self.scheduler.alive,
            "engine_error": self.scheduler.engine_error,
        }


class ServeServer:
    """Unix-socket front of a ServeEngine. `serve_forever()` owns the
    calling thread until a drain request (socket op or request_drain(),
    e.g. from a SIGTERM handler) completes."""

    def __init__(self, engine: ServeEngine, socket_path: str):
        self.engine = engine
        self.socket_path = socket_path
        self._drain_requested = threading.Event()
        self._drained = threading.Event()

    def request_drain(self) -> None:
        """Signal-handler safe: ask the accept loop to drain and exit."""
        self._drain_requested.set()

    def serve_forever(self) -> None:
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            sock.bind(self.socket_path)
            sock.listen(16)
            sock.settimeout(0.25)
            observe.emit("serve_listening", {"socket": self.socket_path})
            while not self._drain_requested.is_set():
                try:
                    conn, _ = sock.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                # graftlint: owned-thread -- one connection = one
                # request; the handler owns conn and only calls the
                # lock-guarded engine API
                threading.Thread(
                    target=self._handle, args=(conn,),
                    name="serve-conn", daemon=True,
                ).start()
        finally:
            sock.close()
            try:
                os.unlink(self.socket_path)
            except OSError:
                pass
        # graceful drain: every admitted job completes before we return
        self.engine.drain(timeout=None)
        self._drained.set()
        observe.emit("serve_drained", {"socket": self.socket_path})
        observe.flush_sinks()

    # -- one connection = one request ------------------------------------

    def _handle(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(10.0)
            fh = conn.makefile("rwb")
            line = fh.readline()
            if not line:
                return
            try:
                req = json.loads(line)
                resp = self._dispatch(req)
            except Exception as exc:  # protocol errors answer, not crash
                resp = {"ok": False, "error": f"{type(exc).__name__}: {exc}"}
            conn.settimeout(10.0)
            fh.write((json.dumps(resp) + "\n").encode())
            fh.flush()
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, req: dict) -> dict:
        op = req.get("op")
        if op == "ping":
            return {"ok": True, "pong": True}
        if op == "submit":
            try:
                job = self.engine.submit(req.get("spec") or {})
            except (_jobs.AdmissionError, _jobs.QueueClosed) as exc:
                return {"ok": False, "error": str(exc)}
            return {"ok": True, "job": job.status()}
        if op == "status":
            st = self.engine.status(str(req.get("job")))
            if st is None:
                return {"ok": False, "error": f"unknown job {req.get('job')!r}"}
            return {"ok": True, "job": st}
        if op == "wait":
            timeout = req.get("timeout")
            st = self.engine.wait(
                str(req.get("job")),
                timeout=float(timeout) if timeout is not None else None,
            )
            if st is None:
                return {"ok": False, "error": f"unknown job {req.get('job')!r}"}
            return {"ok": st["state"] in (_jobs.DONE, _jobs.FAILED), "job": st}
        if op == "stats":
            return {"ok": True, "stats": self.engine.stats_dict()}
        if op == "drain":
            self._drain_requested.set()
            timeout = req.get("timeout")
            deadline = (
                None if timeout is None
                else time.monotonic() + float(timeout)
            )
            while not self._drained.is_set():
                self._drained.wait(timeout=0.25)
                if deadline is not None and time.monotonic() >= deadline:
                    break
            return {"ok": True, "drained": self._drained.is_set()}
        return {"ok": False, "error": f"unknown op {op!r}"}


def request(socket_path: str, payload: dict, timeout: float = 600.0) -> dict:
    """One client request against a running ServeServer."""
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        s.settimeout(timeout)
        s.connect(socket_path)
        fh = s.makefile("rwb")
        fh.write((json.dumps(payload) + "\n").encode())
        fh.flush()
        line = fh.readline()
    finally:
        s.close()
    if not line:
        raise ConnectionError(f"no response from {socket_path}")
    return json.loads(line)
