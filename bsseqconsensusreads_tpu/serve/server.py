"""ServeEngine (in-process API) + ServeServer (socket protocol front).

The engine is the embeddable form — tests and the tier-1 smoke drive
it directly: submit/wait/drain with no sockets. The server wraps it in
the serve protocol for `cli submit` / `cli serve-ctl`:

    one connection = one request = one JSON message each way

    {"op": "ping"}                          → {"ok": true, "pong": true}
    {"op": "submit", "spec": {...JobSpec}}  → {"ok": true, "job": {...}}
    {"op": "status", "job": "j0001"}        → {"ok": true, "job": {...}}
    {"op": "wait", "job": "j0001",
     "timeout": 600}                        → {"ok": true, "job": {...}}
    {"op": "stats"}                         → {"ok": true, "stats": {...}}
    {"op": "metrics"}                       → {"ok": true, "metrics": {...}}
                                              (live gauges/counters — the
                                              `observe top` poll surface)
    {"op": "drain", "timeout": 600}         → {"ok": true, "drained": b}
                                              (server exits afterwards)

Requests may carry a reserved ``_trace`` field (a {trace, span} context
injected by transport.request): the server binds it around dispatch so
every ledger line the op emits joins the sender's causal tree.

How the message crosses the wire is serve/transport.py's business: a
server listens on one or more addresses — ``unix:<path>`` (newline
JSONL, the PR 8 wire format) and/or ``tcp:host:port`` (length-framed,
optional TLS) — with identical semantics on every transport. Garbage
frames and oversized payloads are refused with typed GuardErrors and
ledgered (`serve_frame_refused`), never a crash.

Admission failures answer {"ok": false, "error": ...} — a refused job
is the submitter's problem, never the server's. SIGTERM/SIGINT request
a graceful drain: stop admitting, finish every admitted job, exit 0
(tests/test_serve.py proves no job is lost).

Each accept loop polls with a socket timeout and each connection rides
its own daemon thread, so a tenant parked on a long `wait` never
blocks another tenant's submit (and the blocking-scheduler-loop lint
rule holds the loop itself to bounded waits).
"""

from __future__ import annotations

import os
import socket
import threading
import time
from collections import OrderedDict

from bsseqconsensusreads_tpu.faults import netchaos
from bsseqconsensusreads_tpu.serve import jobs as _jobs
from bsseqconsensusreads_tpu.serve import scheduler as _scheduler
from bsseqconsensusreads_tpu.serve import transport as _transport
from bsseqconsensusreads_tpu.utils import compilecache as _compilecache
from bsseqconsensusreads_tpu.utils import observe


class ServeEngine:
    """The resident engine: one JobQueue + one Scheduler, warm across
    jobs. Construct, `start()`, then submit/wait from any thread."""

    def __init__(
        self,
        params=None,
        *,
        mode: str = "unaligned",
        batch_families: int = 64,
        max_window: int = 4096,
        grouping: str = "coordinate",
        indel_policy: str = "drop",
        vote_kernel: str | None = None,
        transport: str = "auto",
        mesh="auto",
        level: int = 6,
        max_active: int = 4,
        stride: int = 8,
        idle_wait_s: float = 0.02,
        max_pending: int = 64,
    ):
        if params is None:
            from bsseqconsensusreads_tpu.models.params import ConsensusParams

            params = ConsensusParams(min_reads=1)
        _compilecache.maybe_enable()
        self.queue = _jobs.JobQueue(max_pending=max_pending)
        self.scheduler = _scheduler.Scheduler(
            self.queue,
            params,
            mode=mode,
            batch_families=batch_families,
            max_window=max_window,
            grouping=grouping,
            indel_policy=indel_policy,
            vote_kernel=vote_kernel,
            transport=transport,
            mesh=mesh,
            level=level,
            max_active=max_active,
            stride=stride,
            idle_wait_s=idle_wait_s,
        )
        self._started = False
        self._started_monotonic: float | None = None
        self._start_lock = threading.Lock()

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "ServeEngine":
        with self._start_lock:
            if not self._started:
                self._started = True
                self._started_monotonic = time.monotonic()
                self.scheduler.start()
        return self

    def warmup(self) -> None:
        """Compile the engine's kernels on a throwaway synthetic family
        BEFORE the first tenant arrives (with BSSEQ_TPU_COMPILE_CACHE_DIR
        set this is a cache load, not a compile). Runs a separate
        one-shot engine call; the resident generator itself stays
        untouched."""
        import numpy as np

        from bsseqconsensusreads_tpu.pipeline import calling as _calling
        from bsseqconsensusreads_tpu.utils.testing import (
            make_grouped_bam_records,
        )

        rng = np.random.default_rng(0)
        genome = "".join(
            "ACGT"[i] for i in rng.integers(0, 4, size=400)
        )
        _, records = make_grouped_bam_records(
            rng, "warm", genome, n_families=2, reads_per_strand=(2, 2),
            read_len=30,
        )
        stats = _calling.StageStats(stage="warmup")
        for _ in _calling.call_molecular_batches(
            records,
            params=self.scheduler.params,
            mode="unaligned",
            batch_families=4,
            max_window=self.scheduler.max_window,
            grouping="gather",
            stats=stats,
            emit="python",
            batching="sequential",
            transport=self.scheduler.transport,
            indel_policy=self.scheduler.indel_policy,
            vote_kernel=self.scheduler.vote_kernel,
        ):
            pass
        observe.emit(
            "serve_warmup",
            {"families": stats.families, "batches": stats.batches},
        )

    def drain(self, timeout: float | None = None) -> bool:
        return self.scheduler.drain(timeout=timeout)

    def stop(self, timeout: float | None = 10.0) -> bool:
        return self.scheduler.stop(timeout=timeout)

    # -- job API ---------------------------------------------------------

    def submit(self, spec) -> _jobs.Job:
        if isinstance(spec, dict):
            spec = _jobs.JobSpec.from_dict(spec)
        job = self.queue.submit(spec)
        self.scheduler._wake.set()
        return job

    def status(self, job_id: str) -> dict | None:
        job = self.queue.get(job_id)
        return None if job is None else job.status()

    def wait(self, job_id: str, timeout: float | None = None) -> dict | None:
        job = self.queue.get(job_id)
        if job is None:
            return None
        deadline = None if timeout is None else time.monotonic() + timeout
        while not job.done.is_set():
            job.done.wait(timeout=0.25)
            if deadline is not None and time.monotonic() >= deadline:
                break
        return job.status()

    def stats_dict(self) -> dict:
        jobs = self.queue.jobs()
        return {
            "jobs": [j.status() for j in jobs],
            "pending": self.queue.pending_count(),
            "counters": {**self.scheduler.counters(), **self.queue.counters},
            "engine_alive": self.scheduler.alive,
            "engine_error": self.scheduler.engine_error,
        }

    def metrics_dict(self) -> dict:
        """The live-metrics gauges/counters (protocol op `metrics`) — the
        sensor surface the future autoscaling scheduler polls. Gauges are
        instantaneous (queue depth, running jobs); counters are monotonic
        (the scheduler's Metrics counters, retries/degrades included);
        rates are derived against engine uptime so a poller needs no
        state."""
        jobs = self.queue.jobs()
        states: dict[str, int] = {}
        for j in jobs:
            states[j.state] = states.get(j.state, 0) + 1
        counters = self.scheduler.counters()
        with self.scheduler.stats.metrics._lock:
            secs = dict(self.scheduler.stats.metrics.seconds)
        device_s = sum(
            v for k, v in secs.items() if k in observe.DEVICE_PHASES
        )
        uptime = (
            time.monotonic() - self._started_monotonic
            if self._started_monotonic is not None else 0.0
        )
        return {
            "component": "serve",
            "uptime_s": round(uptime, 3),
            "queue_depth": self.queue.pending_count(),
            "jobs_total": len(jobs),
            "jobs_by_state": states,
            "engine_alive": self.scheduler.alive,
            "chip_busy": round(device_s / uptime, 4) if uptime else 0.0,
            "batches_shared_jobs_rate": (
                round(counters.get("batches_shared_jobs", 0) / uptime, 4)
                if uptime else 0.0
            ),
            "counters": counters,
        }


class ProtocolServer:
    """Accept/frame/refuse machinery for the serve protocol on one or
    more transport addresses. Subclasses supply `_dispatch` (the op
    table) and `_on_drain` (what "stop serving" means for their
    backend) — ServeServer fronts one engine, router.RouterServer
    fronts a replica fleet, same wire behavior. `serve_forever()` owns
    the calling thread until a drain request (socket op or
    request_drain(), e.g. from a SIGTERM handler) completes."""

    def __init__(self, socket_path=None, *, addresses=None,
                 ready_file: str | None = None):
        self.ready_file = ready_file
        addrs: list[str] = []
        if socket_path is not None:
            addrs.append(str(socket_path))
        if addresses:
            addrs.extend(str(a) for a in addresses)
        if not addrs:
            raise ValueError("server needs at least one address")
        self.addresses = addrs
        # back-compat attribute: the first unix path, if any
        self.socket_path = next(
            (
                _transport.parse_address(a)[1]
                for a in addrs
                if _transport.parse_address(a)[0] == "unix"
            ),
            addrs[0],
        )
        #: resolved listen addresses (port-0 binds get the real port)
        self.bound: list[str] = []
        self._drain_requested = threading.Event()
        self._drained = threading.Event()
        #: in-flight connection handlers; _idle is set while zero so
        #: shutdown can wait for the drain op's own response to flush
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._idle = threading.Event()
        self._idle.set()
        #: duplicate-delivery protection: `_rid` nonce -> the reply the
        #: first delivery earned. A re-delivered frame (netchaos `dup`,
        #: or any at-least-once retry that reuses its rid) answers from
        #: here with NO second dispatch — `frame_dup_ignored` — so
        #: lease/publish/heartbeat never double a state transition.
        self._rid_cache: OrderedDict[str, dict] = OrderedDict()
        self._rid_lock = threading.Lock()

    #: bounded reply cache — old rids age out; a duplicate arriving
    #: later than 1024 requests re-dispatches (the ledger-level
    #: duplicate-commit path still holds for publish)
    RID_CACHE_SIZE = 1024

    def request_drain(self) -> None:
        """Signal-handler safe: ask the accept loops to drain and exit."""
        self._drain_requested.set()

    def serve_forever(self) -> None:
        listeners = []
        try:
            for address in self.addresses:
                sock, kind, resolved = _transport.listen(address)
                listeners.append((sock, kind, resolved))
                self.bound.append(resolved)
                observe.emit("serve_listening", {"socket": resolved})
            if self.ready_file:
                # the fleet supervisor's ready protocol: bound addresses,
                # one per line, atomically visible (port 0 is resolved)
                tmp = self.ready_file + ".tmp"
                with open(tmp, "w") as fh:
                    fh.write("\n".join(self.bound) + "\n")
                os.replace(tmp, self.ready_file)
            threads = [
                # graftlint: owned-thread -- accept pump per listener:
                # it only polls its own socket and hands each conn to a
                # per-connection handler; shared state stays lock-guarded
                threading.Thread(
                    target=self._accept_loop, args=(sock, kind),
                    name=f"serve-accept-{i}", daemon=True,
                )
                for i, (sock, kind, _) in enumerate(listeners)
            ]
            for t in threads:
                t.start()
            while not self._drain_requested.is_set():
                self._drain_requested.wait(timeout=0.25)
        finally:
            self._drain_requested.set()
            for sock, kind, resolved in listeners:
                sock.close()
                if kind == "unix":
                    try:
                        os.unlink(_transport.parse_address(resolved)[1])
                    except OSError:
                        pass
        # graceful drain: every admitted job completes before we return
        self._on_drain()
        self._drained.set()
        # let in-flight handlers (the drain op itself included) write
        # their responses before the process goes away
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if self._idle.wait(timeout=0.1):
                break
        observe.emit("serve_drained", {"socket": self.bound or self.addresses})
        observe.flush_sinks()

    def _accept_loop(self, sock: socket.socket, kind: str) -> None:
        while not self._drain_requested.is_set():
            try:
                conn, addr = sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            peer = f"{addr[0]}:{addr[1]}" if isinstance(addr, tuple) else ""
            # graftlint: owned-thread -- one connection = one
            # request; the handler owns conn and only calls the
            # lock-guarded engine API
            threading.Thread(
                target=self._handle, args=(conn, kind, peer),
                name="serve-conn", daemon=True,
            ).start()

    # -- one connection = one request ------------------------------------

    def _handle(self, conn: socket.socket, kind: str, peer: str = "") -> None:
        with self._inflight_lock:
            self._inflight += 1
            self._idle.clear()
        try:
            afault = netchaos.plan("net_accept", peer=peer)
            if afault.partition or afault.drop:
                return  # injected: connection reset at accept
            if afault.delay_s:
                time.sleep(afault.delay_s)
            if afault.half_open:
                # accept, then stall: never read, never answer — the
                # client's own timeout is its only way out
                time.sleep(afault.half_open_s)
                return
            conn.settimeout(10.0)
            try:
                conn = _transport.server_wrap(conn, kind)
            except OSError:
                return  # failed TLS handshake: refused client
            rfault = netchaos.plan("net_recv", peer=peer)
            if rfault.delay_s:
                time.sleep(rfault.delay_s)
            if rfault.drop:
                return  # injected: the request frame never arrives
            try:
                req = _transport.recv_message(conn, kind)
            except _transport.TransportError as exc:
                # hostile/garbage frame: typed refusal, ledgered, answered
                observe.emit(
                    "serve_frame_refused",
                    {"reason": exc.reason, "error": str(exc)},
                )
                self._answer(conn, kind, {
                    "ok": False, "error": f"refused: {exc}",
                    "guard": exc.reason,
                })
                return
            if req is None:
                return
            # trace carriage: the client's causal context (if any) rides
            # the reserved `_trace` key — bind it so every ledger line the
            # dispatch emits lands in the sender's trace tree
            trace_ctx = req.pop("_trace", None)
            rid = req.pop("_rid", None)
            cached = None
            if rid is not None:
                with self._rid_lock:
                    cached = self._rid_cache.get(rid)
            if cached is not None:
                # duplicate delivery: same reply, no second dispatch —
                # the idempotency contract for lease/publish/heartbeat
                with observe.bind_trace(trace_ctx):
                    observe.emit(
                        "frame_dup_ignored",
                        {"rid": rid, "op": str(req.get("op", ""))},
                    )
                resp = cached
            else:
                try:
                    with observe.bind_trace(trace_ctx):
                        resp = self._dispatch(req)
                except _transport.TransportError as exc:
                    # typed dispatch refusal (overload shed, drain
                    # lapse): same answer shape as a refused frame, so
                    # clients branch on `guard`, not on error strings
                    resp = {
                        "ok": False, "error": f"refused: {exc}",
                        "guard": exc.reason,
                    }
                    retry_after = getattr(exc, "retry_after_s", None)
                    if retry_after is not None:
                        resp["retry_after_s"] = retry_after
                    with observe.bind_trace(trace_ctx):
                        observe.emit(
                            "serve_frame_refused",
                            {"reason": exc.reason, "error": str(exc)},
                        )
                except Exception as exc:  # protocol errors answer, not crash
                    resp = {
                        "ok": False,
                        "error": f"{type(exc).__name__}: {exc}",
                    }
                # bulk replies (slice chunks) opt out via the reserved
                # `_nocache` key: re-dispatching a read-only fetch is
                # safe and keeps the cache memory-bounded
                nocache = bool(resp.pop("_nocache", False)) if isinstance(
                    resp, dict
                ) else False
                if rid is not None and not nocache:
                    with self._rid_lock:
                        self._rid_cache[rid] = resp
                        while len(self._rid_cache) > self.RID_CACHE_SIZE:
                            self._rid_cache.popitem(last=False)
            sfault = netchaos.plan("net_send", peer=peer)
            if sfault.delay_s:
                time.sleep(sfault.delay_s)
            if sfault.drop:
                return  # injected: the answer never leaves the host
            conn.settimeout(10.0)
            self._answer(conn, kind, resp, corrupt=sfault.corrupt)
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._inflight_lock:
                self._inflight -= 1
                if self._inflight == 0:
                    self._idle.set()

    @staticmethod
    def _answer(
        conn: socket.socket, kind: str, resp: dict, corrupt: bool = False
    ) -> None:
        try:
            _transport.send_message(conn, kind, resp, _corrupt=corrupt)
        except OSError:
            pass

    # -- subclass surface ------------------------------------------------

    def _drain_op(self, req: dict) -> dict:
        """The shared `drain` protocol op. The wait deadline accounts
        from the instant the client SENT the frame (`sent_s`, same-host
        wall clock) when the request carries it — wire and accept delay
        spend the caller's budget rather than extending it, the same
        send-time discipline the lease-renewal pump applies. A lapse is
        a TYPED `drain_timeout` refusal, never an ambiguous ok."""
        self._drain_requested.set()
        timeout = req.get("timeout")
        budget = None if timeout is None else float(timeout)
        sent_s = req.get("sent_s")
        if budget is not None and sent_s is not None:
            try:
                budget -= max(0.0, time.time() - float(sent_s))
            except (TypeError, ValueError):
                pass  # unparseable stamp: fall back to receipt-time
        deadline = None if budget is None else time.monotonic() + budget
        while not self._drained.is_set():
            if deadline is not None and time.monotonic() >= deadline:
                raise _transport.TransportError(
                    f"drain incomplete after {timeout}s from frame send",
                    reason="drain_timeout",
                )
            self._drained.wait(timeout=0.25)
        return {"ok": True, "drained": True}

    def _dispatch(self, req: dict) -> dict:
        raise NotImplementedError

    def _on_drain(self) -> None:
        raise NotImplementedError


class ServeServer(ProtocolServer):
    """Socket front of one ServeEngine."""

    def __init__(self, engine: ServeEngine, socket_path=None, *,
                 addresses=None, ready_file: str | None = None):
        super().__init__(socket_path, addresses=addresses,
                         ready_file=ready_file)
        self.engine = engine

    def _on_drain(self) -> None:
        self.engine.drain(timeout=None)

    def _dispatch(self, req: dict) -> dict:
        op = req.get("op")
        if op == "ping":
            return {"ok": True, "pong": True}
        if op == "submit":
            try:
                job = self.engine.submit(req.get("spec") or {})
            except _jobs.OverloadedError as exc:
                err = _transport.TransportError(
                    str(exc), reason="overloaded"
                )
                err.retry_after_s = exc.retry_after_s
                raise err from None
            except (_jobs.AdmissionError, _jobs.QueueClosed) as exc:
                return {"ok": False, "error": str(exc)}
            return {"ok": True, "job": job.status()}
        if op == "status":
            st = self.engine.status(str(req.get("job")))
            if st is None:
                return {"ok": False, "error": f"unknown job {req.get('job')!r}"}
            return {"ok": True, "job": st}
        if op == "wait":
            timeout = req.get("timeout")
            st = self.engine.wait(
                str(req.get("job")),
                timeout=float(timeout) if timeout is not None else None,
            )
            if st is None:
                return {"ok": False, "error": f"unknown job {req.get('job')!r}"}
            return {"ok": st["state"] in (_jobs.DONE, _jobs.FAILED), "job": st}
        if op == "stats":
            return {"ok": True, "stats": self.engine.stats_dict()}
        if op == "metrics":
            return {"ok": True, "metrics": self.engine.metrics_dict()}
        if op == "drain":
            return self._drain_op(req)
        return {"ok": False, "error": f"unknown op {op!r}"}


def request(socket_path: str, payload: dict, timeout: float = 600.0) -> dict:
    """One client request against a running ServeServer (or router).
    `socket_path` is any transport address — a bare unix path (PR 8
    callers), ``unix:<path>``, or ``tcp:host:port``."""
    return _transport.request(socket_path, payload, timeout=timeout)
