"""bsseqconsensusreads_tpu — a TPU-native duplex-consensus framework for BS-seq / EM-seq.

A from-scratch re-design of the capabilities of Wubeizhongxinghua/BSSeqConsensusReads
(reference mounted read-only at /root/reference) for TPU hardware:

* ``io``        — first-party BGZF/BAM/FASTA/FASTQ codecs (pure Python + native C++),
                  replacing the reference's pysam/samtools dependency
                  (reference: tools/1.convert_AG_to_CT.py:25, main.snake.py:93).
* ``ops``       — pure-JAX array transforms and consensus math: tensorization,
                  AG->CT B-strand conversion (reference: tools/1.convert_AG_to_CT.py),
                  gap extension (reference: tools/2.extend_gap.py), Pallas kernels.
* ``models``    — the consensus "model family": molecular (single-strand) and duplex
                  callers with the fgbio error model surface used by the reference
                  (reference: main.snake.py:54,163).
* ``parallel``  — jax.sharding Mesh / shard_map sharding of the MI-family axis and
                  segmented reductions for deep families.
* ``pipeline``  — host-side record ops (SamToFastq / ZipperBams / sorts / filters
                  equivalents) and a file-DAG workflow engine with mtime-based rerun
                  (the reference uses Snakemake; reference: main.snake.py:40-189).
"""

__version__ = "0.1.0"


def pin_host_backend(warn: bool = True) -> bool:
    """Pin jax to the host CPU backend. Returns True if the pin took.

    Platform pinning must go through the jax *config*: on tunneled-TPU
    hosts the site plugin hook wraps jax's backend selection and ignores
    the JAX_PLATFORMS env var in both directions (the shell may even carry
    a site-injected value), and a dead tunnel then hangs the first
    ``jax.device_count()`` call — e.g. at mesh resolution
    (pipeline.calling._resolve_mesh). The config route is the one the
    hooks respect, but it only works before any backend initializes; a
    failed pin is warned about (the run would otherwise proceed on a
    device the operator configured against)."""
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
        return True
    except Exception as e:
        if warn:
            import warnings

            warnings.warn(
                f"could not pin jax to the host backend ({e}); "
                "device selection is fixed once backends initialize",
                stacklevel=2,
            )
        return False


def _honor_backend_env() -> None:
    """Honor BSSEQ_TPU_BACKEND=cpu|tpu (case-insensitive) at import time.
    'cpu' pins the host backend before any backend init; unset or 'tpu'
    leaves jax's default selection. The config file's `backend:` key does
    the same per run (pipeline.stages._apply_backend)."""
    import os

    val = os.environ.get("BSSEQ_TPU_BACKEND", "")
    if not val:
        return
    norm = val.strip().lower()
    if norm == "cpu":
        pin_host_backend()
    elif norm != "tpu":
        import warnings

        warnings.warn(
            f"BSSEQ_TPU_BACKEND={val!r} not recognized (want 'cpu'|'tpu'); "
            "leaving jax's default backend selection",
            stacklevel=2,
        )


_honor_backend_env()
