"""bsseqconsensusreads_tpu — a TPU-native duplex-consensus framework for BS-seq / EM-seq.

A from-scratch re-design of the capabilities of Wubeizhongxinghua/BSSeqConsensusReads
(reference mounted read-only at /root/reference) for TPU hardware:

* ``io``        — first-party BGZF/BAM/FASTA/FASTQ codecs (pure Python + native C++),
                  replacing the reference's pysam/samtools dependency
                  (reference: tools/1.convert_AG_to_CT.py:25, main.snake.py:93).
* ``ops``       — pure-JAX array transforms and consensus math: tensorization,
                  AG->CT B-strand conversion (reference: tools/1.convert_AG_to_CT.py),
                  gap extension (reference: tools/2.extend_gap.py), Pallas kernels.
* ``models``    — the consensus "model family": molecular (single-strand) and duplex
                  callers with the fgbio error model surface used by the reference
                  (reference: main.snake.py:54,163).
* ``parallel``  — jax.sharding Mesh / shard_map sharding of the MI-family axis and
                  segmented reductions for deep families.
* ``pipeline``  — host-side record ops (SamToFastq / ZipperBams / sorts / filters
                  equivalents) and a file-DAG workflow engine with mtime-based rerun
                  (the reference uses Snakemake; reference: main.snake.py:40-189).
"""

__version__ = "0.1.0"
