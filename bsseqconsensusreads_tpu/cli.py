"""Command-line interface: `python -m bsseqconsensusreads_tpu <cmd>`.

Subcommands mirror the reference's entry points (SURVEY.md §1 L4) plus
the steps its users run around it:

* run       — the whole pipeline for one sample (the reference's
              `snakemake -s main.snake.py --config bam=…`, README.md:62)
* group     — fgbio GroupReadsByUmi equivalent (the reference's input
              contract, README.md:51-55; auto-prepended by `run` when
              the input has RX but no MI)
* metrics   — fgbio CollectDuplexSeqMetrics equivalent (family sizes,
              duplex yield) over an MI-grouped BAM
* molecular — just the molecular consensus stage (fgbio
              CallMolecularConsensusReads equivalent, main.snake.py:54)
* duplex    — just the fused duplex stage (the reference's convert ->
              extend -> sort -> callduplex chain, main.snake.py:121-164)
* filter-consensus — fgbio FilterConsensusReads equivalent (the
              filtered variant of the reference's dead rule,
              main.snake.py:70-80)
* sort / zipper / sam-to-fastq / filter-mapped — the standalone
              fgbio SortBam / ZipperBams / Picard SamToFastq /
              `samtools view -F 4` equivalents
* observe   — run-ledger consumer (utils.ledger_tools): `summarize` a
              BSSEQ_TPU_STATS ledger into per-stage host/device/stall
              tables, `diff` two ledgers, `check` schema + the
              ledger-closure invariant (non-zero exit on violation)
* lint      — graftlint static analysis (analysis/): eight AST checkers
              for TPU-hostile and thread-unsafe code; exit 1 on any
              unsuppressed finding, so the tier-1 suite gates every PR
              on a clean self-application
"""

from __future__ import annotations

import argparse
import json
import time

from bsseqconsensusreads_tpu.config import FrameworkConfig
from bsseqconsensusreads_tpu.faults import guard as _guard
from bsseqconsensusreads_tpu.models.params import ConsensusParams
from bsseqconsensusreads_tpu.utils import observe


def _add_params(p: argparse.ArgumentParser, min_reads_default: int) -> None:
    p.add_argument("--error-rate-pre-umi", type=float, default=45.0)
    p.add_argument("--error-rate-post-umi", type=float, default=30.0)
    p.add_argument("--min-input-base-quality", type=int, default=0)
    p.add_argument("--min-consensus-base-quality", type=int, default=0)
    p.add_argument("--min-reads", type=int, default=min_reads_default)
    p.add_argument(
        "--no-consensus-call-overlapping-bases",
        action="store_true",
        help="disable R1/R2 overlap co-calling",
    )
    p.add_argument("--batch-families", type=int, default=512)
    p.add_argument("--max-window", type=int, default=4096)
    p.add_argument(
        "--vote-kernel", choices=("xla", "pallas"), default=None,
        help="consensus vote kernel (default: BSSEQ_TPU_VOTE_KERNEL or "
        "xla); pallas = the fused Mosaic VMEM-streaming reduction",
    )
    p.add_argument(
        "--ingest", choices=("auto", "native", "python"), default="auto",
        help="record ingest engine: the C++ columnar decoder (with C-side "
        "grouping + encode digest on coordinate input) or pure-Python "
        "BamReader — byte-identical output either way",
    )
    p.add_argument(
        "--transport", choices=("auto", "wire", "unpacked"), default="auto",
        help="device transport: ONE packed u32 array per direction "
        "(+ device-resident genome on duplex; round-robin across devices "
        "on multi-device runs), or plain tensors — byte-identical output "
        "either way; 'auto' = wire on single-device accelerators, "
        "unpacked on CPU and on meshes (say 'wire' explicitly for the "
        "multi-device round-robin wire)",
    )
    p.add_argument(
        "--grouping",
        choices=("gather", "adjacent", "coordinate"),
        default="coordinate",
        help="MI-group streaming strategy (coordinate = bounded memory on sorted input)",
    )
    p.add_argument(
        "--batching",
        choices=("bucketed", "sequential"),
        default="bucketed",
        help="molecular chunk composition: depth-homogeneous buckets "
        "(bounded pad waste) vs input order",
    )
    p.add_argument(
        "--emit",
        choices=("auto", "native", "python"),
        default="auto",
        help="record emission: native C++ batch serializer vs per-record "
        "Python objects (auto = native when built)",
    )
    _add_failpoints(p)


def _add_failpoints(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--failpoints", default="",
        help="fault-injection schedule, e.g. "
        "'dispatch_kernel=raise:RuntimeError@batch=7;"
        "extsort_spill=io_error:p=0.01:seed=42' (README Robustness; "
        "overrides BSSEQ_TPU_FAILPOINTS)",
    )


def _arm_failpoints(args) -> None:
    if getattr(args, "failpoints", ""):
        from bsseqconsensusreads_tpu.faults import failpoints

        try:
            failpoints.arm(args.failpoints)
        except failpoints.FailpointError as exc:
            observe.stderr_line(f"--failpoints: {exc}")
            raise SystemExit(2) from None


def _params(args, **kw) -> ConsensusParams:
    return ConsensusParams(
        error_rate_pre_umi=args.error_rate_pre_umi,
        error_rate_post_umi=args.error_rate_post_umi,
        min_input_base_quality=args.min_input_base_quality,
        min_consensus_base_quality=args.min_consensus_base_quality,
        consensus_call_overlapping_bases=not args.no_consensus_call_overlapping_bases,
        min_reads=args.min_reads,
        **kw,
    )


def cmd_run(args) -> int:
    from bsseqconsensusreads_tpu.pipeline.stages import run_pipeline

    _arm_failpoints(args)
    cfg = (
        FrameworkConfig.from_yaml(args.config)
        if args.config
        else FrameworkConfig()
    )
    if args.aligner:
        cfg.aligner = args.aligner
    if args.reference:
        import os

        cfg.genome_dir = os.path.dirname(args.reference) or "."
        cfg.genome_fasta_file_name = os.path.basename(args.reference)
    if args.chemistry:
        cfg.chemistry = args.chemistry
    if args.methyl:
        cfg.methyl = args.methyl
    if args.methyl_out:
        cfg.methyl_out = args.methyl_out
    if args.single_strand:
        cfg.single_strand = True
    if args.sort_engine:
        cfg.sort_engine = args.sort_engine
    if args.sort_buckets:
        cfg.sort_buckets = args.sort_buckets
    if args.stream_interstage:
        cfg.stream_interstage = True
    target, results, stats = run_pipeline(
        cfg, args.bam, outdir=args.outdir, force=args.force
    )
    for r in results:
        status = "ran" if r.ran else "skip"
        observe.stderr_line(f"[{status}] {r.name} ({r.seconds:.2f}s) {r.reason}")
    print(
        json.dumps(
            {
                "target": target,
                "stats": {k: s.as_dict() for k, s in stats.items()},
            }
        )
    )
    return 0


def cmd_molecular(args) -> int:
    from bsseqconsensusreads_tpu.faults import guard as _guard
    from bsseqconsensusreads_tpu.pipeline.calling import (
        StageStats,
        call_molecular_batches,
    )
    from bsseqconsensusreads_tpu.pipeline.stages import (
        molecular_ingest_stream,
        open_guarded_reader,
    )

    _arm_failpoints(args)
    observe.open_ledger(component="molecular-cli")
    stats = StageStats(stage="molecular")
    g = _guard.Guard.from_env(stats)
    try:
        with open_guarded_reader(args.input, g) as reader:
            batches = call_molecular_batches(
                molecular_ingest_stream(
                    args.input, reader, stats,
                    ingest_choice=args.ingest, grouping=args.grouping,
                    indel_policy=args.indel_policy,
                    guard=g,
                ),
                params=_params(args),
                mode=args.mode,
                batch_families=args.batch_families,
                max_window=args.max_window,
                grouping=args.grouping,
                stats=stats,
                emit=args.emit,
                batching=args.batching,
                transport=args.transport,
                indel_policy=args.indel_policy,
                vote_kernel=args.vote_kernel,
                guard=g,
            )
            from bsseqconsensusreads_tpu.pipeline.extsort import write_batch_stream

            write_batch_stream(batches, args.output, reader.header, args.mode)
    finally:
        g.close()
    observe.emit_stage_stats({"molecular": stats})
    observe.flush_sinks()
    observe.stderr_line(json.dumps(stats.as_dict()))
    return 0


def cmd_duplex(args) -> int:
    from bsseqconsensusreads_tpu.faults import guard as _guard
    from bsseqconsensusreads_tpu.io.fasta import FastaFile
    from bsseqconsensusreads_tpu.pipeline.calling import (
        StageStats,
        call_duplex_batches,
    )

    from bsseqconsensusreads_tpu.pipeline.stages import (
        duplex_ingest_stream,
        open_guarded_reader,
    )

    _arm_failpoints(args)
    observe.open_ledger(component="duplex-cli")
    stats = StageStats(stage="duplex")
    fasta = FastaFile(args.reference)
    methyl_acc = None
    store = args.reference  # FASTA path; loaded only if the wire engages
    if args.methyl != "off":
        from bsseqconsensusreads_tpu.methyl.tally import MethylAccumulator
        from bsseqconsensusreads_tpu.ops.refstore import RefStore

        base = args.methyl_out or args.output
        methyl_acc = MethylAccumulator(
            RefStore.from_fasta(args.reference),
            base + ".bedmethyl" if args.methyl in ("bedmethyl", "both")
            else None,
            base + ".CX_report.txt" if args.methyl in ("cx", "both")
            else None,
            metrics=stats.metrics,
        )
        store = methyl_acc.refstore
    g = _guard.Guard.from_env(stats)
    try:
        with open_guarded_reader(args.input, g) as reader:
            names = [n for n, _ in reader.header.references]
            batches = call_duplex_batches(
                duplex_ingest_stream(
                    args.input, reader, stats,
                    ingest_choice=args.ingest, grouping=args.grouping,
                    passthrough=args.passthrough,
                    guard=g,
                ),
                fasta.fetch,
                names,
                params=_params(args),
                mode=args.mode,
                batch_families=args.batch_families,
                max_window=args.max_window,
                grouping=args.grouping,
                stats=stats,
                emit=args.emit,
                refstore=store,
                transport=args.transport,
                passthrough=args.passthrough,
                vote_kernel=args.vote_kernel,
                pos0=args.pos0,
                guard=g,
                methyl=methyl_acc,
                chemistry=args.chemistry,
            )
            from bsseqconsensusreads_tpu.pipeline.extsort import write_batch_stream

            write_batch_stream(batches, args.output, reader.header, args.mode)
            if methyl_acc is not None:
                report = methyl_acc.finalize()
                observe.stderr_line(json.dumps({"methyl": report}))
    finally:
        g.close()
    observe.emit_stage_stats({"duplex": stats})
    observe.flush_sinks()
    observe.stderr_line(json.dumps(stats.as_dict()))
    return 0


def cmd_sort(args) -> int:
    """`fgbio SortBam` / `samtools sort` equivalent (main.snake.py:106,152):
    external-merge sort in bounded memory, order from --order."""
    from bsseqconsensusreads_tpu.io.bam import BamReader
    from bsseqconsensusreads_tpu.pipeline.extsort import sorted_write
    from bsseqconsensusreads_tpu.pipeline.record_ops import (
        coordinate_key,
        name_key,
        template_coordinate_key,
    )

    key, so, ss = {
        "coordinate": (coordinate_key, "coordinate", None),
        "name": (name_key, "queryname", None),
        # fgbio SortBam -s TemplateCoordinate declares the sub-sort
        "template-coordinate": (
            template_coordinate_key, "unsorted", "template-coordinate"
        ),
    }[args.order]
    with BamReader(args.input) as reader:
        header = reader.header.with_sort_order(so, ss)
        n = sorted_write(reader, key, args.output, header)
    observe.stderr_line(json.dumps({"records": n, "order": args.order}))
    return 0


def cmd_group(args) -> int:
    """`fgbio GroupReadsByUmi` equivalent (the step producing the
    reference's input contract, README.md:51-55): assign MI molecule ids
    from RX UMIs, /A|/B duplex strand suffixes under -s paired, bounded
    memory via two external passes."""
    from bsseqconsensusreads_tpu.io.bam import BamReader, BamWriter
    from bsseqconsensusreads_tpu.pipeline.group_umi import (
        GroupStats,
        group_reads_by_umi_raw,
        grouped_header,
    )

    stats = GroupStats()
    with BamReader(args.input) as reader:
        header = grouped_header(reader.header)
        with BamWriter(args.output, header) as w:
            w.write_raw_many(
                group_reads_by_umi_raw(
                    reader, reader.header,
                    strategy=args.strategy, edits=args.edits,
                    raw_tag=args.raw_tag, min_map_q=args.min_map_q,
                    stats=stats,
                )
            )
    observe.stderr_line(json.dumps(stats.as_dict()))
    return 0


def cmd_metrics(args) -> int:
    """`fgbio CollectDuplexSeqMetrics` equivalent (pipeline.metrics):
    family-size histograms and duplex yield from an MI-grouped BAM, one
    streaming pass, JSON on stdout."""
    from bsseqconsensusreads_tpu.io.bam import BamReader
    from bsseqconsensusreads_tpu.pipeline import ingest
    from bsseqconsensusreads_tpu.pipeline.metrics import duplex_seq_metrics

    if ingest.available():  # columnar views carry qname+MI — all this needs
        m = duplex_seq_metrics(ingest.columnar_records(args.input))
    else:
        with BamReader(args.input) as reader:
            m = duplex_seq_metrics(reader)
    print(json.dumps(m.as_dict(), indent=None if args.compact else 1))
    return 0


def cmd_filter_consensus(args) -> int:
    """`fgbio FilterConsensusReads` equivalent (pipeline.filter): the
    filtered variant the reference's dead rule hints at
    (main.snake.py:70-80) — read-level drops on depth/error rate,
    per-base masking, template-atomic."""
    from bsseqconsensusreads_tpu.io.bam import BamReader, BamWriter
    from bsseqconsensusreads_tpu.pipeline.filter import (
        FilterParams,
        FilterStats,
        filter_consensus,
        filtered_header,
        probe_strand_tag_support,
    )

    params = FilterParams(
        min_reads=tuple(args.min_reads),
        max_read_error_rate=args.max_read_error_rate,
        max_base_error_rate=args.max_base_error_rate,
        min_base_quality=args.min_base_quality,
        max_no_call_fraction=args.max_no_call_fraction,
        min_mean_base_quality=args.min_mean_base_quality,
        require_single_strand_agreement=args.require_single_strand_agreement,
    )
    stats = FilterStats()
    probe_strand_tag_support(args.input, params)  # fail before any write
    with BamReader(args.input) as reader:
        header = filtered_header(reader.header)
        with BamWriter(args.output, header) as w:
            for rec in filter_consensus(reader, params, stats=stats):
                w.write(rec)
    observe.stderr_line(json.dumps(stats.as_dict()))
    return 0


def cmd_zipper(args) -> int:
    """`fgbio ZipperBams --unmapped UNALIGNED --sort Coordinate` equivalent
    (main.snake.py:106): graft consensus tags from the unaligned BAM onto
    the aligned records, coordinate-sorted, bounded memory."""
    from bsseqconsensusreads_tpu.io.bam import BamReader, BamWriter
    from bsseqconsensusreads_tpu.pipeline.record_ops import zipper_bams_stream

    with BamReader(args.input) as aligned, BamReader(args.unmapped) as unaligned:
        n = 0
        header = aligned.header.with_sort_order("coordinate")
        with BamWriter(args.output, header) as w:
            for rec in zipper_bams_stream(aligned, unaligned, header):
                w.write(rec)
                n += 1
    observe.stderr_line(json.dumps({"records": n}))
    return 0


def cmd_sam_to_fastq(args) -> int:
    """`picard SamToFastq` equivalent (main.snake.py:67,176): paired
    gzipped FASTQs with in-step pairing. Records stream through the
    external name sort first, so mates are adjacent and the pairing
    buffer stays O(1) even on coordinate-sorted input (where mates can be
    megabases apart — an unsorted pairing dict would hold half the
    file)."""
    from bsseqconsensusreads_tpu.io.bam import BamReader
    from bsseqconsensusreads_tpu.io.fastq import sam_to_fastq
    from bsseqconsensusreads_tpu.pipeline.extsort import external_sort
    from bsseqconsensusreads_tpu.pipeline.record_ops import name_key

    with BamReader(args.input) as reader:
        n1, n2 = sam_to_fastq(
            external_sort(reader, name_key, reader.header),
            args.fq1, args.fq2,
        )
    observe.stderr_line(json.dumps({"r1": n1, "r2": n2}))
    return 0


def cmd_filter_mapped(args) -> int:
    """`samtools view -h -b -F 4` equivalent (main.snake.py:118)."""
    from bsseqconsensusreads_tpu.io.bam import BamReader, BamWriter
    from bsseqconsensusreads_tpu.pipeline.record_ops import filter_mapped

    with BamReader(args.input) as reader:
        n = 0
        with BamWriter(args.output, reader.header) as w:
            for rec in filter_mapped(reader):
                w.write(rec)
                n += 1
    observe.stderr_line(json.dumps({"records": n}))
    return 0


def _poll_metrics(args) -> int:
    """Live metrics plane: poll a running serve/router/coordinator over
    the framed transport ('metrics' op) and print one JSON line per
    sample — a `top` you can pipe. --count 0 polls until interrupted."""
    import time as _time

    from bsseqconsensusreads_tpu.serve.server import request

    n = 0
    while True:
        try:
            resp = request(args.address, {"op": "metrics"}, timeout=10.0)
        except (OSError, ConnectionError) as exc:
            observe.stderr_line(f"observe top: {exc}")
            return 1
        if not resp.get("ok") or "metrics" not in resp:
            observe.stderr_line(
                f"observe top: {args.address} does not export metrics "
                f"({resp})"
            )
            return 1
        print(json.dumps(resp["metrics"], sort_keys=True), flush=True)
        n += 1
        if args.count and n >= args.count:
            return 0
        _time.sleep(args.interval)


def cmd_observe(args) -> int:
    """Run-ledger consumer (utils.ledger_tools + utils.trace_tools):
    summarize / diff / check over BSSEQ_TPU_STATS JSONL ledgers, plus
    the grafttrace tier — `trace` reassembles the cross-process span
    forest of a rundir (router + N replicas, or coordinator + N
    workers), prints the ranked overhead-bucket table and per-trace
    critical paths, and exits non-zero on orphan spans or unterminated
    traces (a truncated ledger set cannot pass); `top` polls a live
    process's metrics; `check` on a DIRECTORY runs the same
    cross-process validation, on a file the per-ledger schema +
    closure invariants.

    --job (summarize) / --job-a/--job-b (diff) scope the view to one
    serve tenant's lines, so a job served from a shared ledger can be
    compared 1:1 against its standalone-run ledger."""
    import os

    from bsseqconsensusreads_tpu.utils import ledger_tools

    if args.op == "top":
        return _poll_metrics(args)
    if args.op == "trace":
        from bsseqconsensusreads_tpu.utils import trace_tools

        target = (
            args.target[0] if len(args.target) == 1 else list(args.target)
        )
        report = trace_tools.assemble(target)
        problems = trace_tools.check_traces(report)
        print(trace_tools.format_report(report))
        if problems:
            for p in problems:
                observe.stderr_line(f"observe trace: {p}")
            return 1
        return 0
    try:
        if args.op == "summarize":
            s = ledger_tools.summarize_ledger(
                args.ledger, rel_tol=args.tolerance,
                job=args.job or None,
                replica=getattr(args, "replica", "") or None,
                worker=getattr(args, "worker", "") or None,
            )
            print(ledger_tools.format_summary(s))
            return 0 if s.ok else 1
        if args.op == "diff":
            a = ledger_tools.summarize_ledger(
                args.ledger_a, job=args.job_a or None
            )
            b = ledger_tools.summarize_ledger(
                args.ledger_b, job=args.job_b or None
            )
            print(ledger_tools.format_diff(a, b))
            return 0
        if os.path.isdir(args.ledger):
            # a rundir: cross-process trace validation (orphan spans,
            # unterminated job/slice trees, trace-vs-counter
            # reconciliation) — per-ledger schema checks stay the
            # single-file form, since a shared fleet/elastic ledger
            # interleaves several processes' manifests
            from bsseqconsensusreads_tpu.utils import trace_tools

            problems = trace_tools.check_traces(
                trace_tools.assemble(args.ledger)
            )
        else:
            problems = ledger_tools.check_ledger(
                args.ledger, rel_tol=args.tolerance
            )
    except ledger_tools.LedgerError as exc:
        observe.stderr_line(f"observe {args.op}: {exc}")
        return 2
    if problems:
        for p in problems:
            observe.stderr_line(f"observe check: {p}")
        print(json.dumps({"ok": False, "problems": len(problems)}))
        return 1
    print(json.dumps({"ok": True, "problems": 0}))
    return 0


def cmd_elastic(args) -> int:
    """graftswarm elastic execution (elastic/): `run` is the
    one-command local launch — split the grouped input into base-family
    slices, spawn N worker subprocesses against an in-process
    coordinator, merge byte-identical to single-process; `worker
    --join` is the real-multihost leg, one process joining a remote
    coordinator over the framed transport."""
    import os

    _arm_failpoints(args)
    observe.install_flight_signal()  # SIGUSR1 → dump recent spans/events
    if args.op == "worker":
        from bsseqconsensusreads_tpu.elastic import worker as _worker

        n = _worker.work_loop(args.join, worker_id=args.worker_id or None)
        print(json.dumps({
            "worker": os.environ.get("BSSEQ_TPU_WORKER_ID", ""),
            "slices": n,
        }))
        return 0
    from bsseqconsensusreads_tpu import elastic

    cfg = (
        FrameworkConfig.from_yaml(args.config)
        if args.config
        else FrameworkConfig()
    )
    if args.reference:
        cfg.genome_dir = os.path.dirname(args.reference) or "."
        cfg.genome_fasta_file_name = os.path.basename(args.reference)
    if args.sort_buckets:
        cfg.sort_buckets = args.sort_buckets
    worker_failpoints = {}
    for term in args.worker_failpoints:
        wid, sep, schedule = term.partition(":")
        if not sep or not wid or not schedule:
            observe.stderr_line(
                f"--worker-failpoints: bad term {term!r} (want wid:schedule)"
            )
            return 2
        worker_failpoints[wid] = schedule
    try:
        target, report = elastic.run_elastic(
            cfg, args.bam, outdir=args.outdir,
            workers=args.workers, slices=args.slices,
            address=args.address, inline=args.inline,
            worker_failpoints=worker_failpoints,
            max_restarts=args.max_restarts, timeout_s=args.timeout,
            ship=args.ship,
        )
    except elastic.ElasticError as exc:
        observe.stderr_line(f"elastic: {exc}")
        return 1
    print(json.dumps({"target": target, "report": report}))
    return 0


def cmd_lint(args) -> int:
    """graftlint driver: lint the package (default) or the given paths.

    Exit codes: 0 clean, 1 unsuppressed findings, 2 usage error
    (unknown rule name — in --rules or a suppression comment — or an
    unparseable file). The tier-1 self-application test shells exactly
    `... lint --json` and asserts exit 0."""
    import os

    from bsseqconsensusreads_tpu import analysis

    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(analysis.__file__)))
    paths = args.paths or [pkg_dir]
    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
    if args.contracts:
        from bsseqconsensusreads_tpu.analysis import contracts

        try:
            report = contracts.verify_package(args.paths or None)
        except analysis.LintError as exc:
            if args.json:
                print(json.dumps({"error": str(exc)}))
            else:
                observe.stderr_line(f"lint: {exc}")
            return 2
        if args.json:
            print(json.dumps(report.as_dict()))
        else:
            for d in report.drifts:
                print(d.format())
            print(
                f"{len(report.drifts)} drift(s), "
                f"{len(report.waived)} waived"
            )
        return 0 if report.ok else 1
    registry = analysis.all_rules()
    if args.list_rules:
        if args.json:
            print(json.dumps(
                {name: rule.summary for name, rule in sorted(registry.items())}
            ))
        else:
            for name, rule in sorted(registry.items()):
                print(f"{name}: {rule.summary}")
        return 0
    try:
        findings = analysis.run_lint(
            paths, rules=rules, include_suppressed=args.include_suppressed
        )
    except analysis.LintError as exc:
        if args.json:
            print(json.dumps({"error": str(exc)}))
        else:
            observe.stderr_line(f"lint: {exc}")
        return 2
    if args.json:
        print(json.dumps(
            {
                "findings": [f.as_dict() for f in findings],
                "count": len(findings),
                "rules": sorted(r.name for r in registry.values()
                                if rules is None or r.name in rules),
                "paths": [str(p) for p in paths],
            }
        ))
    else:
        for f in findings:
            print(f.format())
        print(f"{len(findings)} finding(s)")
    return 1 if findings else 0


def cmd_serve(args) -> int:
    """graftserve: the resident consensus engine (serve/). Holds warm
    jitted kernels + the hostpool across jobs, accepts BAM jobs over a
    local unix socket (`cli submit`), packs families from different
    jobs into shared device batches, and demultiplexes at retire so
    each job's output is byte-identical to a standalone
    `cli molecular --batching sequential` run. SIGTERM/SIGINT drain
    gracefully: admitted jobs finish, then the process exits 0."""
    import signal

    from bsseqconsensusreads_tpu.serve.server import ServeEngine, ServeServer

    if not args.socket and not args.address:
        observe.stderr_line("serve: need --socket and/or --address")
        return 2
    _arm_failpoints(args)
    observe.open_ledger(component="serve")
    observe.install_flight_signal()  # SIGUSR1 → dump recent spans/events
    engine = ServeEngine(
        params=_params(args),
        mode=args.mode,
        batch_families=args.batch_families,
        max_window=args.max_window,
        grouping=args.grouping,
        indel_policy=args.indel_policy,
        vote_kernel=args.vote_kernel,
        transport=args.transport,
        max_active=args.max_active,
        stride=args.stride,
        idle_wait_s=args.idle_flush_ms / 1000.0,
        max_pending=args.max_pending,
    )
    if args.warmup:
        engine.warmup()
    engine.start()
    server = ServeServer(
        engine,
        args.socket or None,
        addresses=args.address or None,
        ready_file=args.ready_file or None,
    )
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: server.request_drain())
    server.serve_forever()
    observe.emit_stage_stats({"serve-cli": engine.scheduler.stats})
    observe.flush_sinks()
    states: dict[str, int] = {}
    for j in engine.queue.jobs():
        states[j.state] = states.get(j.state, 0) + 1
    observe.stderr_line(json.dumps(
        {"jobs": states, **engine.scheduler.counters()}
    ))
    return 0


def cmd_route(args) -> int:
    """graftfleet router (serve/router + serve/fleet): supervise N
    serve replicas (spawned same-host on kernel-assigned TCP ports, or
    attached anywhere via --replica-address) and front them with the
    same serve protocol a single replica speaks. Placement is input-
    fingerprint affinity first, queue depth otherwise; a replica dying
    mid-job has its unfinished jobs requeued to survivors (byte-
    identical — jobs are idempotent) and is respawned warm off the
    shared compile cache. SIGTERM/SIGINT drain the whole fleet."""
    import os as _os
    import signal

    from bsseqconsensusreads_tpu.serve.fleet import ReplicaSet
    from bsseqconsensusreads_tpu.serve.router import Router, RouterServer

    if not args.socket and not args.address:
        observe.stderr_line("route: need --socket and/or --address")
        return 2
    _arm_failpoints(args)
    observe.open_ledger(component="route")
    observe.install_flight_signal()  # SIGUSR1 → dump recent spans/events
    serve_args = [
        "--batch-families", str(args.batch_families),
        "--max-active", str(args.max_active),
        "--stride", str(args.stride),
        "--idle-flush-ms", str(args.idle_flush_ms),
        "--max-pending", str(args.max_pending),
        "--min-reads", str(args.min_reads),
    ]
    if args.warmup:
        serve_args.append("--warmup")
    fail_once: dict[str, str] = {}
    for term in args.replica_failpoints:
        rid, sep, schedule = term.partition(":")
        if not sep or not rid or not schedule:
            observe.stderr_line(
                f"route: bad --replica-failpoints {term!r} "
                "(want rid:schedule)"
            )
            return 2
        fail_once[rid] = schedule
    fleet = ReplicaSet(
        n=args.replicas,
        host=args.replica_host,
        rundir=args.rundir or None,
        serve_args=serve_args,
        attach_addresses=args.replica_address or None,
        compile_cache_dir=(
            _os.environ.get("BSSEQ_TPU_COMPILE_CACHE_DIR") or None
        ),
        fail_once=fail_once,
    )
    router = Router(
        fleet,
        affinity=not args.no_affinity,
        respawn=not args.no_respawn,
    )
    try:
        router.launch()
    except Exception as exc:  # a dead fleet at boot is an exit, not a hang
        observe.stderr_line(f"route: fleet failed to start: {exc}")
        fleet.stop(drain_timeout=5.0)
        return 2
    server = RouterServer(
        router,
        args.socket or None,
        addresses=args.address or None,
        ready_file=args.ready_file or None,
    )
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: server.request_drain())
    server.serve_forever()
    observe.flush_sinks()
    observe.stderr_line(json.dumps(router.counters))
    return 0


def cmd_submit(args) -> int:
    """Client half of the serve protocol: submit one BAM job to a
    running `cli serve` engine; --wait blocks until the job retires and
    exits non-zero if it failed."""
    from bsseqconsensusreads_tpu.serve.server import request

    spec = {
        "input": args.input,
        "output": args.output,
        "policy": args.policy or None,
        "grouping": args.grouping or None,
        "ingest": args.ingest,
        "chemistry": args.chemistry or None,
    }
    try:
        # overload shedding is a *retry* signal, not a failure: honor
        # the server's retry_after_s hint with bounded backoff until
        # either admission succeeds or the submit budget lapses
        deadline = time.monotonic() + args.timeout
        while True:
            resp = request(args.socket, {"op": "submit", "spec": spec})
            if resp.get("ok") or resp.get("guard") != "overloaded":
                break
            delay = min(2.0, max(0.05, float(
                resp.get("retry_after_s") or 0.1)))
            if time.monotonic() + delay >= deadline:
                break
            time.sleep(delay)
        if not resp.get("ok"):
            observe.stderr_line(f"submit refused: {resp.get('error')}")
            return 3
        job = resp["job"]
        if args.wait:
            resp = request(
                args.socket,
                {"op": "wait", "job": job["id"], "timeout": args.timeout},
                timeout=args.timeout + 30.0,
            )
            job = resp.get("job", job)
    except OSError as exc:
        observe.stderr_line(f"submit: cannot reach {args.socket}: {exc}")
        return 2
    print(json.dumps(job))
    if args.wait:
        return 0 if job.get("state") == "done" else 1
    return 0


def cmd_serve_ctl(args) -> int:
    """Operator half of the serve protocol: ping / stats / status /
    drain / preempt against a running engine or router."""
    from bsseqconsensusreads_tpu.serve.server import request

    payload: dict = {"op": args.op}
    if args.op == "status":
        if not args.job:
            observe.stderr_line("serve-ctl status needs --job")
            return 2
        payload["job"] = args.job
    if args.op == "drain":
        payload["timeout"] = args.timeout
        payload["sent_s"] = time.time()
    if args.op == "preempt":
        if not args.replica:
            observe.stderr_line("serve-ctl preempt needs --replica")
            return 2
        payload["replica"] = args.replica
    try:
        resp = request(args.socket, payload, timeout=args.timeout + 30.0)
    except OSError as exc:
        observe.stderr_line(f"serve-ctl: cannot reach {args.socket}: {exc}")
        return 2
    print(json.dumps(resp))
    return 0 if resp.get("ok") else 1


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="bsseqconsensusreads_tpu")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("run", help="run the full pipeline for one sample")
    p.add_argument("--config", default="", help="YAML config (reference-compatible)")
    p.add_argument("--bam", required=True, help="GroupReadsByUmi output BAM")
    p.add_argument("--outdir", default="output")
    p.add_argument("--aligner", choices=("self", "bwameth", "none"), default="")
    p.add_argument("--reference", default="", help="genome FASTA (overrides config)")
    p.add_argument("--force", action="store_true")
    p.add_argument(
        "--chemistry", choices=("bisulfite", "emseq", "none"), default="",
        help="library chemistry (overrides config; see `duplex --help`)",
    )
    p.add_argument(
        "--methyl", choices=("off", "bedmethyl", "cx", "both"), default="",
        help="fused methylation extraction at the duplex stage "
        "(overrides config)",
    )
    p.add_argument(
        "--methyl-out", default="",
        help="base path for the methylation outputs (overrides config)",
    )
    p.add_argument(
        "--single-strand", action="store_true",
        help="molecular emit without duplex pairing: stop after the "
        "molecular consensus stage",
    )
    p.add_argument(
        "--sort-engine", choices=("auto", "native", "python", "bucket"),
        default="",
        help="raw coordinate-sort engine for stage outputs (overrides "
        "config; byte-identical output across engines)",
    )
    p.add_argument(
        "--sort-buckets", type=int, default=0,
        help="bucket count for --sort-engine bucket (0 = engine default)",
    )
    p.add_argument(
        "--stream-interstage", action="store_true",
        help="with the bucket engine, stream molecular consensus records "
        "straight into duplex grouping per bucket (falls back loudly "
        "when the configuration does not support fusion)",
    )
    _add_failpoints(p)
    p.set_defaults(fn=cmd_run)

    p = sub.add_parser("molecular", help="molecular consensus stage only")
    p.add_argument("-i", "--input", required=True)
    p.add_argument("-o", "--output", required=True)
    p.add_argument("--mode", choices=("unaligned", "self"), default="unaligned")
    p.add_argument(
        "--indel-policy", choices=("drop", "align"), default="drop",
        help="indel reads: 'drop' = reference parity "
        "(tools/1.convert_AG_to_CT.py:79-80), 'align' = recover them via "
        "the banded intra-family aligner (above-parity)",
    )
    _add_params(p, min_reads_default=1)
    p.set_defaults(fn=cmd_molecular)

    p = sub.add_parser("duplex", help="fused duplex stage only")
    p.add_argument("-i", "--input", required=True)
    p.add_argument("-o", "--output", required=True)
    p.add_argument("--reference", required=True, help="genome FASTA")
    p.add_argument("--mode", choices=("unaligned", "self"), default="unaligned")
    p.add_argument(
        "--passthrough", action="store_true",
        help="reference-parity emission of off-vocabulary records (the "
        "convert-stage treatment of tools/1.convert_AG_to_CT.py applied "
        "to leftovers; default drops them, counted in stats)",
    )
    p.add_argument(
        "--pos0", choices=("skip", "shift"), default="skip",
        help="conversion prepend for reads at reference position 0: "
        "'skip' (default, documented deviation) or 'shift' = exact "
        "reference parity incl. the one-base register shift "
        "(tools/1.convert_AG_to_CT.py:87-92)",
    )
    p.add_argument(
        "--chemistry", choices=("bisulfite", "emseq", "none"),
        default="bisulfite",
        help="library chemistry: bisulfite/emseq run the conversion-aware "
        "engine (identical C->T readout; emseq is provenance), 'none' "
        "declares an unconverted plain (fgbio-style) duplex library — "
        "the convert transform is disabled, same engine otherwise",
    )
    p.add_argument(
        "--methyl", choices=("off", "bedmethyl", "cx", "both"),
        default="off",
        help="fused methylation extraction: per-column classify-and-count "
        "epilogue on the vote kernels, bedMethyl and/or CX cytosine "
        "report next to the output (methyl/ subsystem)",
    )
    p.add_argument(
        "--methyl-out", default="",
        help="base path for the methylation outputs (default: the duplex "
        "output path)",
    )
    _add_params(p, min_reads_default=0)
    p.set_defaults(fn=cmd_duplex)

    p = sub.add_parser(
        "sort", help="SortBam equivalent (external-merge, bounded memory)"
    )
    p.add_argument("-i", "--input", required=True)
    p.add_argument("-o", "--output", required=True)
    p.add_argument(
        "--order",
        choices=("coordinate", "name", "template-coordinate"),
        default="coordinate",
        help="template-coordinate = fgbio SortBam -s TemplateCoordinate "
        "(main.snake.py:152); name = samtools sort -n",
    )
    p.set_defaults(fn=cmd_sort)

    p = sub.add_parser(
        "group", help="GroupReadsByUmi equivalent (RX -> MI, duplex /A|/B)"
    )
    p.add_argument("-i", "--input", required=True, help="aligned BAM with RX tags")
    p.add_argument("-o", "--output", required=True)
    p.add_argument(
        "-s", "--strategy",
        choices=("identity", "edit", "adjacency", "paired"),
        default="paired",
        help="paired = duplex: strand-canonicalized UMI pairs, MI gets "
        "/A|/B suffixes (the reference's input contract, README.md:51-55)",
    )
    p.add_argument("-e", "--edits", type=int, default=1,
                   help="max UMI mismatches merged within a position group")
    p.add_argument("-t", "--raw-tag", default="RX")
    p.add_argument("-m", "--min-map-q", type=int, default=1)
    p.set_defaults(fn=cmd_group)

    p = sub.add_parser(
        "metrics",
        help="CollectDuplexSeqMetrics equivalent (family sizes, duplex yield)",
    )
    p.add_argument("-i", "--input", required=True, help="MI-grouped BAM")
    p.add_argument("--compact", action="store_true", help="one-line JSON")
    p.set_defaults(fn=cmd_metrics)

    p = sub.add_parser(
        "filter-consensus",
        help="FilterConsensusReads equivalent (depth/error filters + masking)",
    )
    p.add_argument("-i", "--input", required=True, help="consensus BAM")
    p.add_argument("-o", "--output", required=True)
    p.add_argument(
        "-M", "--min-reads", type=int, nargs="+", default=[1],
        help="M [A B]: total / larger-strand / smaller-strand depth floors",
    )
    p.add_argument("-E", "--max-read-error-rate", type=float, default=0.025)
    p.add_argument("-e", "--max-base-error-rate", type=float, default=0.1)
    p.add_argument("-N", "--min-base-quality", type=int, default=1)
    p.add_argument("-n", "--max-no-call-fraction", type=float, default=0.1)
    p.add_argument("-q", "--min-mean-base-quality", type=float, default=None)
    p.add_argument(
        "-s", "--require-single-strand-agreement", action="store_true",
        help="mask duplex bases where the two single-strand calls "
        "disagree (consumes the ac/bc tags this framework's duplex "
        "output carries)",
    )
    p.set_defaults(fn=cmd_filter_consensus)

    p = sub.add_parser(
        "zipper", help="ZipperBams equivalent (tag graft + coordinate sort)"
    )
    p.add_argument("-i", "--input", required=True, help="aligned BAM")
    p.add_argument("--unmapped", required=True, help="unaligned BAM with tags")
    p.add_argument("-o", "--output", required=True)
    p.set_defaults(fn=cmd_zipper)

    p = sub.add_parser("sam-to-fastq", help="SamToFastq equivalent")
    p.add_argument("-i", "--input", required=True)
    p.add_argument("--fq1", required=True)
    p.add_argument("--fq2", required=True)
    p.set_defaults(fn=cmd_sam_to_fastq)

    p = sub.add_parser(
        "filter-mapped", help="samtools view -F 4 equivalent"
    )
    p.add_argument("-i", "--input", required=True)
    p.add_argument("-o", "--output", required=True)
    p.set_defaults(fn=cmd_filter_mapped)

    p = sub.add_parser(
        "serve",
        help="resident consensus engine: warm kernels across jobs, "
        "cross-job continuous batching, unix-socket/TCP submit protocol",
    )
    p.add_argument(
        "--socket", default="",
        help="unix socket path (optional when --address is given)",
    )
    p.add_argument(
        "--address", action="append", default=[],
        help="additional listen address (repeatable): unix:<path> or "
        "tcp:host:port (port 0 = kernel-assigned; TLS via "
        "BSSEQ_TPU_SERVE_TLS_CERT/KEY)",
    )
    p.add_argument(
        "--ready-file", default="",
        help="write resolved bound addresses here once listening "
        "(the fleet supervisor's ready protocol)",
    )
    p.add_argument("--mode", choices=("unaligned", "self"), default="unaligned")
    p.add_argument(
        "--indel-policy", choices=("drop", "align"), default="drop"
    )
    p.add_argument(
        "--max-active", type=int, default=4,
        help="jobs ingesting concurrently (each holds one reader thread)",
    )
    p.add_argument(
        "--stride", type=int, default=8,
        help="families pulled per job per round-robin pass",
    )
    p.add_argument(
        "--idle-flush-ms", type=float, default=20.0,
        help="idle wait before a partial chunk is flushed to the device "
        "(continuous batching: latency under low load)",
    )
    p.add_argument(
        "--max-pending", type=int, default=64,
        help="bounded admission queue depth (submits beyond it block)",
    )
    p.add_argument(
        "--warmup", action="store_true",
        help="compile kernels on a synthetic family before accepting jobs",
    )
    _add_params(p, min_reads_default=1)
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser(
        "route",
        help="graftfleet router: N serve replicas behind affinity "
        "placement, drain/handoff, shared compile cache",
    )
    p.add_argument(
        "--socket", default="", help="router unix socket path"
    )
    p.add_argument(
        "--address", action="append", default=[],
        help="router listen address (repeatable): unix:<path> or "
        "tcp:host:port",
    )
    p.add_argument(
        "--ready-file", default="",
        help="write the router's bound addresses here once listening",
    )
    p.add_argument(
        "--replicas", type=int, default=2,
        help="serve replicas to spawn on this host",
    )
    p.add_argument("--replica-host", default="127.0.0.1")
    p.add_argument(
        "--replica-address", action="append", default=[],
        help="attach to an already-running replica at tcp:host:port "
        "instead of spawning (repeatable; multihost addressing)",
    )
    p.add_argument(
        "--replica-failpoints", action="append", default=[],
        help="rid:schedule — arm BSSEQ_TPU_FAILPOINTS in ONE replica's "
        "first life (chaos drills: r0:fleet_replica_exit=exit:9@batch=1)",
    )
    p.add_argument(
        "--no-respawn", action="store_true",
        help="do not restart dead replicas (requeue-only handoff)",
    )
    p.add_argument(
        "--no-affinity", action="store_true",
        help="place purely by queue depth",
    )
    p.add_argument(
        "--rundir", default="",
        help="supervision scratch dir (ready files; default under TMPDIR)",
    )
    p.add_argument("--batch-families", type=int, default=64)
    p.add_argument("--max-active", type=int, default=4)
    p.add_argument("--stride", type=int, default=8)
    p.add_argument("--idle-flush-ms", type=float, default=20.0)
    p.add_argument("--max-pending", type=int, default=64)
    p.add_argument("--min-reads", type=int, default=1)
    p.add_argument(
        "--warmup", action="store_true",
        help="each replica compiles kernels before accepting jobs",
    )
    _add_failpoints(p)
    p.set_defaults(fn=cmd_route)

    p = sub.add_parser(
        "submit", help="submit one BAM job to a running serve engine "
        "or router (--socket accepts unix paths and tcp:host:port)"
    )
    p.add_argument("--socket", required=True)
    p.add_argument("-i", "--input", required=True)
    p.add_argument("-o", "--output", required=True)
    p.add_argument(
        "--policy", choices=("strict", "quarantine", "lenient", "off"),
        default="",
        help="graftguard policy for THIS job's ingest (default: the "
        "server's BSSEQ_TPU_INPUT_POLICY)",
    )
    p.add_argument(
        "--grouping", choices=("gather", "adjacent", "coordinate"),
        default="", help="MI-group streaming strategy (default: server's)",
    )
    p.add_argument(
        "--ingest", choices=("auto", "native", "python"), default="python"
    )
    p.add_argument(
        "--chemistry", choices=("bisulfite", "emseq", "none"), default="",
        help="THIS job's library chemistry (admission validation + "
        "provenance: the molecular stage is chemistry-invariant, so "
        "mixed-chemistry tenants share device batches safely)",
    )
    p.add_argument("--wait", action="store_true", help="block until done")
    p.add_argument("--timeout", type=float, default=600.0)
    p.set_defaults(fn=cmd_submit)

    p = sub.add_parser(
        "serve-ctl",
        help="ping/stats/status/drain/preempt a running serve engine "
        "or router",
    )
    p.add_argument(
        "op", choices=("ping", "stats", "status", "drain", "preempt")
    )
    p.add_argument("--socket", required=True)
    p.add_argument("--job", default="")
    p.add_argument(
        "--replica", default="",
        help="replica id for `preempt`: voluntarily drain one router "
        "replica — migrate its jobs to survivors, then reap it",
    )
    p.add_argument("--timeout", type=float, default=600.0)
    p.set_defaults(fn=cmd_serve_ctl)

    p = sub.add_parser(
        "elastic",
        help="graftswarm: coordinator/worker sharded runs with loss "
        "recovery, byte-identical to single-process",
    )
    eop = p.add_subparsers(dest="op", required=True)
    r = eop.add_parser(
        "run",
        help="one-command elastic run: split the grouped input into "
        "base-family slices, lease them to N local worker "
        "subprocesses, merge byte-identical to single-process",
    )
    r.add_argument("--config", default="", help="YAML config")
    r.add_argument("--bam", required=True, help="GroupReadsByUmi output BAM")
    r.add_argument("--outdir", default="output")
    r.add_argument("--reference", default="", help="genome FASTA (overrides config)")
    r.add_argument("--workers", type=int, default=2)
    r.add_argument(
        "--slices", type=int, default=0,
        help="work-unit count (default: workers*4 — small slices keep "
        "requeue cheap and the tail short)",
    )
    r.add_argument(
        "--address", default="tcp:127.0.0.1:0",
        help="coordinator listen address, tcp:host:port (port 0 = "
        "kernel-assigned; TLS via BSSEQ_TPU_SERVE_TLS_CERT/KEY)",
    )
    r.add_argument(
        "--inline", action="store_true",
        help="process every slice sequentially in this process (no "
        "subprocesses/sockets; same bytes — the debug/test mode)",
    )
    r.add_argument(
        "--ship", action="store_true",
        help="shared-nothing mode: workers fetch slice inputs and ship "
        "outputs over the wire as CRC-verified resumable chunks "
        "(chunk size BSSEQ_TPU_ELASTIC_CHUNK_B) instead of touching "
        "the shared rundir; same bytes as the shared-FS run",
    )
    r.add_argument(
        "--worker-failpoints", action="append", default=[],
        help="wid:schedule — arm BSSEQ_TPU_FAILPOINTS in ONE worker's "
        "first life (chaos drills: w0:elastic_slice=exit:9@hit=2)",
    )
    r.add_argument(
        "--max-restarts", type=int, default=2,
        help="respawn budget per worker id",
    )
    r.add_argument("--timeout", type=float, default=3600.0)
    r.add_argument(
        "--sort-buckets", type=int, default=0,
        help="bucket count for the merge reconciliation geometry "
        "(0 = engine default)",
    )
    _add_failpoints(r)
    r.set_defaults(fn=cmd_elastic)
    w = eop.add_parser(
        "worker",
        help="join a (possibly remote) coordinator and process leased "
        "slices until it reports done",
    )
    w.add_argument(
        "--join", required=True, help="coordinator address tcp:host:port"
    )
    w.add_argument(
        "--worker-id", default="",
        help="identity stamped into ledger sub-streams (default: "
        "BSSEQ_TPU_WORKER_ID or pid<N>)",
    )
    _add_failpoints(w)
    w.set_defaults(fn=cmd_elastic)

    p = sub.add_parser(
        "lint",
        help="graftlint static analysis: TPU-hostile / thread-unsafe "
        "code checkers over the package (or given paths)",
    )
    p.add_argument(
        "paths", nargs="*",
        help="files or directories to lint (default: the installed "
        "bsseqconsensusreads_tpu package)",
    )
    p.add_argument("--json", action="store_true", help="machine output")
    p.add_argument(
        "--rules", default="",
        help="comma-separated rule subset (default: all; see --list-rules)",
    )
    p.add_argument(
        "--list-rules", action="store_true", help="print the rule table"
    )
    p.add_argument(
        "--include-suppressed", action="store_true",
        help="report findings even where a graftlint disable comment "
        "covers them (audit mode)",
    )
    p.add_argument(
        "--contracts", action="store_true",
        help="run the whole-program graftcontract drift pass instead of "
        "the per-file rules (registry vs extracted uses of env vars, "
        "failpoints, ledger events, counters, protocol ops, CLI surface)",
    )
    p.set_defaults(fn=cmd_lint)

    p = sub.add_parser(
        "observe",
        help="run-ledger tools: summarize / diff / check a "
        "BSSEQ_TPU_STATS JSONL ledger",
    )
    op = p.add_subparsers(dest="op", required=True)
    s = op.add_parser(
        "summarize",
        help="per-stage host/device/stall/chip_busy table + rule walls "
        "+ closure verdict",
    )
    s.add_argument("ledger", help="ledger JSONL path")
    s.add_argument(
        "--tolerance", type=float, default=0.15,
        help="relative closure tolerance (unattributed share of the wall)",
    )
    s.add_argument(
        "--job", default="",
        help="scope to one serve tenant's lines (job id)",
    )
    s.add_argument(
        "--replica", default="",
        help="scope to one fleet replica's sub-stream (replica id, "
        "e.g. r0 — fleet ledgers interleave N replica processes)",
    )
    s.add_argument(
        "--worker", default="",
        help="scope to one elastic worker's sub-stream (worker id, "
        "e.g. w0 — elastic ledgers interleave N worker processes)",
    )
    s.set_defaults(fn=cmd_observe)
    d = op.add_parser(
        "diff", help="two ledgers side by side with B/A ratios"
    )
    d.add_argument("ledger_a")
    d.add_argument("ledger_b")
    d.add_argument(
        "--job-a", default="",
        help="scope ledger A to one serve tenant (job id)",
    )
    d.add_argument(
        "--job-b", default="",
        help="scope ledger B to one serve tenant (job id)",
    )
    d.set_defaults(fn=cmd_observe)
    c = op.add_parser(
        "check",
        help="schema + ledger-closure validation (a directory runs the "
        "cross-process trace checks instead); non-zero exit on "
        "violation",
    )
    c.add_argument("ledger", help="ledger JSONL path, or a rundir")
    c.add_argument("--tolerance", type=float, default=0.15)
    c.set_defaults(fn=cmd_observe)
    t = op.add_parser(
        "trace",
        help="grafttrace: reassemble the cross-process span forest of a "
        "rundir's ledgers, print overhead buckets + critical paths; "
        "non-zero exit on orphan/unterminated traces",
    )
    t.add_argument(
        "target", nargs="+",
        help="a rundir (all *.jsonl inside) or explicit ledger paths",
    )
    t.set_defaults(fn=cmd_observe)
    tp = op.add_parser(
        "top",
        help="poll a live serve/router/coordinator's metrics op; one "
        "JSON line per sample",
    )
    tp.add_argument(
        "--address", required=True,
        help="transport address (unix:/path or tcp:host:port)",
    )
    tp.add_argument("--interval", type=float, default=1.0)
    tp.add_argument(
        "--count", type=int, default=1,
        help="samples to print (0 = until interrupted)",
    )
    tp.set_defaults(fn=cmd_observe)

    args = ap.parse_args(argv)
    from bsseqconsensusreads_tpu.utils import compilecache

    compilecache.maybe_enable()  # BSSEQ_TPU_COMPILE_CACHE_DIR, if set
    try:
        return args.fn(args)
    except _guard.GuardError as e:
        # typed input-hardening failure (strict policy fail-fast,
        # refused checkpoint resume, ...): the diagnostic already
        # carries record #N / block @voffset — a traceback would bury
        # it and read as a crash, violating the fuzz contract's "clean
        # typed error" leg. The flight recorder dumps the recent
        # span/event ring first, so the ledger keeps the causal context
        # of the refusal (a no-op when no ledger is armed).
        observe.flight_dump(f"guard_error:{e.reason}")
        observe.stderr_line(
            f"bsseqconsensusreads_tpu: input error [{e.reason}]: {e}"
        )
        return 3


if __name__ == "__main__":
    raise SystemExit(main())
