"""Durable-state integrity: streaming CRC32 over files.

Checkpoint shards (pipeline.checkpoint) and external-sort spill runs
(pipeline.extsort) are the run's durable state — a corrupt one must be
detected and quarantined/recomputed, never spliced silently into the
output (BGZF's per-block CRC catches in-block corruption at inflate
time, but not a truncated tail, a zero-filled page, or a swapped file).
The CRC is over the raw file bytes, so it also pins the exact container
framing the manifest registered.
"""

from __future__ import annotations

import os
import zlib

from bsseqconsensusreads_tpu.utils import observe

_CHUNK = 1 << 20


class IntegrityError(OSError):
    """A durable artifact failed its recorded CRC (or is missing)."""


def file_crc32(path: str) -> int:
    """CRC32 (unsigned) over the file's raw bytes, streaming."""
    crc = 0
    with open(path, "rb") as fh:
        while True:
            chunk = fh.read(_CHUNK)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


def verify_file_crc32(path: str, expected: int, what: str = "") -> None:
    """Raise IntegrityError (ledgered as 'integrity_mismatch') when the
    file's bytes no longer match the recorded CRC, or the file is gone."""
    label = what or os.path.basename(path)
    try:
        actual = file_crc32(path)
    except OSError as exc:
        observe.emit(
            "integrity_mismatch",
            {"path": path, "what": label, "error": str(exc)},
        )
        raise IntegrityError(f"{label}: unreadable: {exc}") from exc
    if actual != expected:
        observe.emit(
            "integrity_mismatch",
            {
                "path": path,
                "what": label,
                "expected_crc": expected,
                "actual_crc": actual,
            },
        )
        raise IntegrityError(
            f"{label}: CRC mismatch (expected {expected:#010x}, "
            f"got {actual:#010x})"
        )
