"""Failpoint registry: named, scheduled fault-injection sites.

A failpoint is a named site on the hot path (`SITES`) where a scheduled
fault can be provoked on demand — the mechanism every recovery claim in
this framework is proven against (tools/chaos_drill.py). Sites are
armed with a schedule string, from `BSSEQ_TPU_FAILPOINTS` or
`--failpoints`:

    site=action[:arg][:k=v...][@pred=value...][;site=action...]

Actions
    raise[:ExcName]     raise the named exception (default RuntimeError)
    io_error            raise OSError("injected I/O error")
    stall[:<dur>s]      time.sleep(dur) (default 30s) — a wedged call
    exit[:code]         os._exit(code) (default 9) — a hard crash, no
                        cleanup, for kill-at-batch-N drills

Network actions (net_send / net_recv / net_accept sites ONLY — they do
not raise; the transport shim in faults/netchaos.py interprets them
via `evaluate()`):
    delay[:<dur>s]      sleep before the wire op (default 0.2s)
    drop                close the connection without sending the frame
    dup                 deliver the same frame twice (a second identical
                        request on a fresh connection)
    corrupt             flip payload bytes after the length header — the
                        peer's framing must refuse, never parse, it
    half_open           accept, then stall and close without answering
    partition           refuse the connection outright (pairs with
                        @peer= for one-sided partitions)

Arguments (colon-separated `k=v` after the action)
    p=<float>           fire probability per eligible hit (default 1.0)
    seed=<int>          seed of the failpoint's own RNG — a p< 1
                        schedule is DETERMINISTIC given the seed and the
                        hit sequence
    times=<int>         stop firing after this many fires (default
                        unlimited)

Predicates (each `@k=v` must match the fire() call's context)
    @batch=<int>        only when the site reports that batch index
    @stage=<name>       only when the site reports that stage
    @job=<id>           only when the site reports that serve job id
                        (the serve_* sites — targets ONE tenant)
    @hit=<int>          only on the Nth predicate-matching hit
    @peer=<substr>      only when the site's peer address CONTAINS the
                        value (ports are dynamic, so exact match is
                        useless — `@peer=127.0.0.1` or a socket path
                        fragment)

Examples (the grammar of ISSUE 3):
    wire_transfer-style transient:  dispatch_kernel=raise:RuntimeError@batch=7
    probabilistic spill errors:     extsort_spill=io_error:p=0.01:seed=42
    a wedged fetch:                 fetch_out=stall:30s@batch=3

Zero-cost when unarmed: `fire()` returns immediately on a module-level
flag, and per-block hot paths (io.bgzf) additionally guard on `ARMED`
before building the call. Every fired failpoint is ledgered
('failpoint_fired') and counted — an unarmed run emits nothing.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from random import Random

from bsseqconsensusreads_tpu.utils import observe

ENV_FLAG = "BSSEQ_TPU_FAILPOINTS"

#: Every registered injection site. arm() rejects unknown names so a
#: typo'd schedule fails loudly instead of silently injecting nothing.
SITES = frozenset(
    {
        # pipeline.calling — the batch loop
        "dispatch_kernel",
        "fetch_out",
        "retire_future",
        # parallel.hostpool — host-parallel encode/rawize/emit tasks
        "hostpool_task",
        # pipeline.extsort — spill runs + merge passes
        "extsort_spill",
        "extsort_merge",
        # pipeline.bucketemit — bucket run spills + per-bucket finalize
        # writes (the two durable windows of sort_engine=bucket)
        "bucket_spill",
        "bucket_finalize",
        # pipeline.checkpoint — durable state
        "ckpt_shard_write",
        "ckpt_manifest_rename",
        "ckpt_finalize",
        # io — codec + native loader
        "bgzf_inflate",
        "bgzf_write",
        "native_load",
        # parallel.multihost — liveness + collectives
        "multihost_heartbeat",
        "multihost_collective",
        # serve — resident engine: job admission, per-job ingest pump,
        # shared-batch retire/demux (predicate @job=<id> targets one
        # tenant, proving cross-tenant isolation in the chaos drill)
        "serve_submit",
        "serve_ingest",
        "serve_retire",
        # serve.router / fleet — the routed-submit forward path (armed
        # in the router process) and the replica retire loop (armed in
        # ONE replica's env via `cli route --replica-failpoints`, so a
        # chaos drill can kill a replica mid-job: exit:9@batch=N)
        "fleet_route",
        "fleet_replica_exit",
        # elastic — coordinator/worker sharded runs: the start of slice
        # processing in a worker (exit:9@hit=N kills a worker mid-run,
        # the slice_requeued drill), the publish edge (work durable but
        # unpublished), the coordinator's manifest commit (crash after
        # output verified but before durable commit — the
        # coordinator-restart drill window), and the final merge.
        "elastic_slice",
        "elastic_publish",
        "elastic_manifest_commit",
        "elastic_merge",
        # serve.transport / faults.netchaos — the wire itself: the send
        # edge (client or server answering), the recv edge, and the
        # server accept loop. These sites take the network actions
        # (delay/drop/dup/corrupt/half_open/partition) and are
        # interpreted by the transport shim via evaluate(), not fire().
        "net_send",
        "net_recv",
        "net_accept",
    }
)

#: Sites whose faults live on the wire — the only sites that accept the
#: network actions below.
NET_SITES = frozenset({"net_send", "net_recv", "net_accept"})

_ACTIONS = frozenset({"raise", "io_error", "stall", "exit"})

#: Actions interpreted by the transport shim (faults/netchaos.py)
#: rather than raised by _act(); valid only at NET_SITES.
NET_ACTIONS = frozenset(
    {"delay", "drop", "dup", "corrupt", "half_open", "partition"}
)

#: Exceptions an injected `raise` may name — a restricted table, not a
#: builtins lookup, so a schedule cannot conjure arbitrary types.
_EXCEPTIONS = {
    "RuntimeError": RuntimeError,
    "OSError": OSError,
    "IOError": OSError,
    "TimeoutError": TimeoutError,
    "ConnectionError": ConnectionError,
    "ValueError": ValueError,
}


class FailpointError(ValueError):
    """Bad schedule grammar or an unknown site/action/exception name."""


@dataclass
class FailPoint:
    """One armed schedule term. Mutable hit/fire counters are guarded by
    the module lock — fire() is called from overlap-pool worker
    threads concurrently with the main thread."""

    site: str
    action: str
    exc_name: str = "RuntimeError"
    prob: float = 1.0
    seed: int = 0
    duration_s: float = 30.0
    exit_code: int = 9
    times: int | None = None
    batch: int | None = None
    stage: str | None = None
    job: str | None = None
    hit: int | None = None
    peer: str | None = None
    spec: str = ""
    _hits: int = 0
    _fires: int = 0
    _rng: Random = field(default_factory=lambda: Random(0))

    def __post_init__(self) -> None:
        self._rng = Random(self.seed)

    def matches(self, ctx: dict) -> bool:
        if self.batch is not None and ctx.get("batch") != self.batch:
            return False
        if self.stage is not None and ctx.get("stage") != self.stage:
            return False
        if self.job is not None and ctx.get("job") != self.job:
            return False
        if self.peer is not None and self.peer not in str(ctx.get("peer", "")):
            return False
        return True

    def should_fire(self, ctx: dict) -> bool:
        """Called under the module lock: advances the hit counter and
        the RNG deterministically, returns whether to fire."""
        if not self.matches(ctx):
            return False
        if self.times is not None and self._fires >= self.times:
            return False
        # graftlint: disable=thread-unsafe-mutation -- should_fire is
        # called ONLY under the module _LOCK held by fire()
        self._hits += 1
        if self.hit is not None and self._hits != self.hit:
            return False
        if self.prob < 1.0 and self._rng.random() >= self.prob:
            return False
        # graftlint: disable=thread-unsafe-mutation -- under fire()'s _LOCK
        self._fires += 1
        return True


#: Module-level armed flag — the one branch an unarmed hot path pays.
ARMED: bool = False
_SCHEDULE: list[FailPoint] = []
_FIRED: dict[str, int] = {}
_LOCK = threading.Lock()


def _parse_float(name: str, value: str, spec: str) -> float:
    try:
        return float(value)
    except ValueError:
        raise FailpointError(f"bad {name}={value!r} in {spec!r}") from None


def _parse_int(name: str, value: str, spec: str) -> int:
    try:
        return int(value)
    except ValueError:
        raise FailpointError(f"bad {name}={value!r} in {spec!r}") from None


def _parse_duration(value: str, spec: str) -> float:
    return _parse_float("stall duration", value.rstrip("s"), spec)


def parse_schedule(spec: str) -> list[FailPoint]:
    """Parse a schedule string into FailPoints; raises FailpointError on
    any grammar problem (unknown site, action, exception, predicate)."""
    points: list[FailPoint] = []
    for raw in spec.replace(";", ",").split(","):
        term = raw.strip()
        if not term:
            continue
        chunks = term.split("@")
        head, preds = chunks[0], chunks[1:]
        site, sep, action_part = head.partition("=")
        site = site.strip()
        if not sep or not action_part:
            raise FailpointError(
                f"bad failpoint term {term!r} (want site=action[...])"
            )
        if site not in SITES:
            raise FailpointError(
                f"unknown failpoint site {site!r} (known: "
                f"{', '.join(sorted(SITES))})"
            )
        parts = action_part.split(":")
        action = parts[0].strip()
        if action not in _ACTIONS and action not in NET_ACTIONS:
            raise FailpointError(
                f"unknown failpoint action {action!r} in {term!r} "
                f"(want {'|'.join(sorted(_ACTIONS | NET_ACTIONS))})"
            )
        if action in NET_ACTIONS and site not in NET_SITES:
            raise FailpointError(
                f"network action {action!r} is only valid at net_* sites "
                f"({term!r})"
            )
        fp = FailPoint(site=site, action=action, spec=term)
        if action == "delay":
            fp.duration_s = 0.2
        for arg in parts[1:]:
            arg = arg.strip()
            if not arg:
                continue
            k, eq, v = arg.partition("=")
            if eq:
                if k == "p":
                    fp.prob = _parse_float("p", v, term)
                elif k == "seed":
                    fp.seed = _parse_int("seed", v, term)
                elif k == "times":
                    fp.times = _parse_int("times", v, term)
                else:
                    raise FailpointError(
                        f"unknown failpoint argument {k!r} in {term!r}"
                    )
                continue
            # positional argument: meaning depends on the action
            if action == "raise":
                if arg not in _EXCEPTIONS:
                    raise FailpointError(
                        f"unknown exception {arg!r} in {term!r} (known: "
                        f"{', '.join(sorted(_EXCEPTIONS))})"
                    )
                fp.exc_name = arg
            elif action in ("stall", "delay", "half_open"):
                fp.duration_s = _parse_duration(arg, term)
            elif action == "exit":
                fp.exit_code = _parse_int("exit code", arg, term)
            else:
                raise FailpointError(
                    f"action {action!r} takes no positional argument "
                    f"({arg!r} in {term!r})"
                )
        for pred in preds:
            k, eq, v = pred.partition("=")
            if not eq:
                raise FailpointError(f"bad predicate {pred!r} in {term!r}")
            if k == "batch":
                fp.batch = _parse_int("batch", v, term)
            elif k == "stage":
                fp.stage = v
            elif k == "job":
                fp.job = v
            elif k == "hit":
                fp.hit = _parse_int("hit", v, term)
            elif k == "peer":
                fp.peer = v
            else:
                raise FailpointError(
                    f"unknown predicate {k!r} in {term!r} "
                    "(want batch|stage|job|hit|peer)"
                )
        fp.__post_init__()  # re-seed after arg parse set .seed
        points.append(fp)
    return points


def arm(spec: str) -> None:
    """Arm the schedule (replacing any previous one). An empty spec
    disarms."""
    global ARMED, _SCHEDULE
    points = parse_schedule(spec or "")
    with _LOCK:
        _SCHEDULE = points
        _FIRED.clear()
        ARMED = bool(points)


def disarm() -> None:
    arm("")


def arm_from_env() -> None:
    """Arm from BSSEQ_TPU_FAILPOINTS (done once at import, so schedules
    set in the environment cover library use, the CLI, and every
    subprocess a drill spawns)."""
    spec = os.environ.get(ENV_FLAG, "")
    if spec:
        arm(spec)


def fired_counts() -> dict[str, int]:
    """{site: fires} so far (across the whole schedule)."""
    with _LOCK:
        return dict(_FIRED)


def fired_total() -> int:
    with _LOCK:
        return sum(_FIRED.values())


def evaluate(site: str, **ctx) -> list[FailPoint]:
    """Evaluate the armed schedule at one site WITHOUT acting: every
    matching failpoint is counted and ledgered ('failpoint_fired', with
    trace context via the ambient ledger binding), then returned for
    the caller to interpret. This is the shim API for the network
    actions, whose behaviours (drop/dup/corrupt/...) only the transport
    layer can enact. Returns [] when unarmed."""
    if not ARMED:
        return []
    to_run: list[FailPoint] = []
    with _LOCK:
        for fp in _SCHEDULE:
            if fp.site == site and fp.should_fire(ctx):
                _FIRED[site] = _FIRED.get(site, 0) + 1
                to_run.append(fp)
    for fp in to_run:
        observe.emit(
            "failpoint_fired",
            {
                "site": site,
                "action": fp.action,
                "spec": fp.spec,
                **{
                    k: v for k, v in ctx.items()
                    if k in ("batch", "stage", "job", "peer")
                },
            },
        )
    return to_run


def fire(site: str, **ctx) -> None:
    """Evaluate the armed schedule at one site. No-op (one branch) when
    unarmed. A firing failpoint is ledgered and counted BEFORE its
    action runs, so even an `exit` crash leaves evidence."""
    if not ARMED:
        return
    for fp in evaluate(site, **ctx):
        _act(fp, site)


def _act(fp: FailPoint, site: str) -> None:
    if fp.action == "raise":
        raise _EXCEPTIONS[fp.exc_name](
            f"failpoint {site!r} injected {fp.exc_name} ({fp.spec})"
        )
    if fp.action == "io_error":
        raise OSError(f"failpoint {site!r} injected I/O error ({fp.spec})")
    if fp.action == "stall":
        time.sleep(fp.duration_s)
        return
    if fp.action == "exit":
        # dump the flight-recorder ring first: the chaos drill reads the
        # killed process's recent spans/events out of the shared ledger
        observe.flight_dump(f"failpoint:{site}")
        observe.flush_sinks()  # the crash must not eat the evidence
        os._exit(fp.exit_code)


arm_from_env()
