"""graftguard — untrusted-input hardening for the ingest path.

PR 3 made the pipeline self-healing against *internal* faults; every
byte of *input* was still trusted: a truncated BGZF stream, a record
whose l_seq disagrees with its block size or CIGAR, a million-read
family bomb, or a qual plane of garbage would crash the run, wedge the
encoder, or silently poison the consensus. This module is the policy
layer that makes hostile input degrade loudly and recoverably
(SURVEY §5.3 failure-detection contract; the property fgbio inherits
from htslib's validation layers).

Three layers, one policy knob (``BSSEQ_TPU_INPUT_POLICY``):

* **strict** (default) — fail fast with a precise typed error
  (`record #N`, BGZF voffset where known). Validation is on; nothing
  is ever silently dropped or repaired.
* **quarantine** — the offending record (or whole family, for
  family-level violations) is written to a sidecar
  ``<input>.quarantined.bam`` with a ``qr:Z:<reason>`` tag, a
  ``record_quarantined``/``family_quarantined`` ledger event is
  emitted, counters land in StageStats, and the run continues.
  Stream-level corruption resyncs to the next valid BGZF block
  (io.bgzf) and the next plausible record boundary (io.bam).
* **lenient** — quarantine semantics plus best-effort repair where
  provably safe (today: out-of-range quals clamped to the Phred-93
  emit ceiling, ledgered as ``record_repaired``). Unrepairable
  violations quarantine exactly as above.

``off`` disables the guard entirely (the A/B leg of the byte-identity
contract: on well-formed input every policy, including ``off``,
produces byte-identical output — asserted by tests/test_guard.py).

Layering:

* record-level *structural* validation (field lengths vs block size)
  lives in the decode paths themselves — io.bam for Python,
  native/bamio.cpp for C — with one shared reason string
  (`REASON_RECORD_CORRUPT`) so both engines fail identically at the
  same record index (`check_record_body` mirrors the C check).
* record-level *semantic* validation (`record_violation` /
  `batch_violations`) runs per record on the Python path and
  vectorized per columnar batch on the native path.
* family-level admission control (`guard_groups`) caps family-size
  bombs (``BSSEQ_TPU_MAX_FAMILY_RECORDS``) and read-length outliers
  (``BSSEQ_TPU_MAX_READ_LEN``). Under the segment-packed kernel
  layout (the default) an outlier family no longer inflates the whole
  batch's envelope — it only adds its own rows — so these caps are
  resource *policy* (bounded host memory per family, bounded device
  rows per batch), not the layout self-defense they were when one deep
  family padded every family to [families x reads x len x 4].

tools/fuzz_ingest.py drives seeded mutations of golden inputs through
all three policies and asserts the contract: never crash, never
silently corrupt — every mutated input ends in a clean typed error or
quarantine events whose counts reconcile with the output record count.
"""

from __future__ import annotations

import os
import struct
from typing import Iterable, Iterator

import numpy as np

from bsseqconsensusreads_tpu.utils import observe

# ---------------------------------------------------------------------------
# typed error taxonomy

#: the one shared reason string for a record whose declared field
#: lengths cannot fit its block size — native/bamio.cpp raises the
#: byte-identical message (parity pinned by tests/test_guard.py)
REASON_RECORD_CORRUPT = "corrupt record body (field/length mismatch)"

#: Phred ceiling of every emitted quality (ops.phred.MAX_PHRED as int);
#: input quals above it are out of the SAM printable range.
QUAL_MAX = 93

#: sentinel the native tag extractor writes into a fixed-width MI/RX
#: slot when the tag is PRESENT but malformed — wrong type, empty, or
#: non-printable (native/bamio.cpp kTagMalformed). Distinguishes
#: "absent" ("") from "present and hostile" so the native strict path
#: refuses the same records the Python engine does.
TAG_MALFORMED = b"\x01"


class GuardError(Exception):
    """Base of every typed input-hardening error. The fuzz contract
    ('never crash') means: any failure caused by input bytes must be an
    instance of this (or a subclass) — a bare struct.error/IndexError
    escaping the ingest path is a bug."""

    reason: str = "guard"


class StreamGuardError(GuardError, IOError):
    """Stream-level corruption or truncation (BGZF framing, BAM record
    framing, header). IOError ancestry keeps existing callers that
    catch IOError working."""

    def __init__(self, message: str, reason: str | None = None,
                 record_index: int | None = None,
                 voffset: int | None = None):
        where = []
        if record_index is not None:
            where.append(f"record #{record_index}")
        if voffset is not None:
            where.append(f"block @{voffset}")
        if where:
            message = f"{message} ({' in '.join(where)})"
        super().__init__(message)
        self.reason = reason or canonical_reason(message)
        self.record_index = record_index
        self.voffset = voffset


class RecordGuardError(GuardError, ValueError):
    """One record failed semantic validation under the strict policy."""

    def __init__(self, message: str, reason: str,
                 record_index: int | None = None,
                 qname: str | None = None):
        where = []
        if record_index is not None:
            where.append(f"record #{record_index}")
        if qname:
            where.append(f"qname {qname!r}")
        if where:
            message = f"{message} ({', '.join(where)})"
        super().__init__(message)
        self.reason = reason
        self.record_index = record_index
        self.qname = qname


class FamilyGuardError(GuardError, ValueError):
    """One MI family failed admission control under the strict policy."""

    def __init__(self, message: str, reason: str, mi: str = ""):
        super().__init__(message)
        self.reason = reason
        self.mi = mi


class MissingTagError(RecordGuardError):
    """Record without the MI tag the grouping contract requires.
    Message matches the historical ValueError byte-for-byte (reference
    parity: tools/2.extend_gap.py:180)."""

    def __init__(self, qname: str):
        ValueError.__init__(self, f"{qname} does not have MI tag.")
        self.reason = "missing-mi"
        self.record_index = None
        self.qname = qname


class InputChangedError(GuardError, RuntimeError):
    """Checkpoint resume refused: the input BAM changed (size/mtime)
    since the manifest was written — resuming would splice consensus
    from two different inputs (pipeline.checkpoint)."""

    def __init__(self, target: str, manifest_fp: dict, run_fp: dict):
        super().__init__(
            f"checkpoint for {target} was computed from a different "
            f"input (manifest {manifest_fp} != current {run_fp}); "
            "refusing to splice consensus from two inputs — delete the "
            f"manifest ({target}.ckpt.json) to recompute from scratch"
        )
        self.reason = "input-changed"
        self.manifest_fingerprint = manifest_fp
        self.run_fingerprint = run_fp


# ---------------------------------------------------------------------------
# error classification (python <-> native message parity)

#: ordered (substring, canonical reason) table — first match wins.
#: Python (io.bgzf / io.bam) and native (bamio.cpp) wordings both land
#: on the same canonical reason; the parity tests compare these.
_CANONICAL = (
    ("corrupt record body", "record-corrupt"),
    ("corrupt record size", "record-corrupt"),
    ("corrupt record tags", "record-corrupt"),
    ("corrupt record qname", "record-corrupt"),
    ("truncated record", "record-truncated"),
    ("truncated BAM record", "record-truncated"),
    ("does not have MI tag", "missing-mi"),
    ("CRC mismatch", "bgzf-corrupt"),
    ("ISIZE mismatch", "bgzf-corrupt"),
    ("inflate failed", "bgzf-corrupt"),
    ("corrupt BGZF", "bgzf-corrupt"),
    ("not a BGZF stream", "bgzf-corrupt"),
    ("missing BC extra subfield", "bgzf-corrupt"),
    ("truncated BGZF", "bgzf-truncated"),
    ("EOF marker missing", "bgzf-truncated"),
    ("corrupt BAM header", "header-corrupt"),
    ("not a BAM file", "not-bam"),
)


def canonical_reason(message: str) -> str:
    for needle, reason in _CANONICAL:
        if needle in message:
            return reason
    return "stream-error"


def classify_stream_error(
    message: str, record_index: int | None = None,
    voffset: int | None = None,
) -> StreamGuardError:
    """Wrap a raw decode-path error message (python or native wording)
    into the typed stream error both engines share."""
    return StreamGuardError(
        message, reason=canonical_reason(message),
        record_index=record_index, voffset=voffset,
    )


# ---------------------------------------------------------------------------
# record-level structural validation (mirror of native/bamio.cpp)

_LSEQ_NCIG = struct.Struct("<H")  # n_cigar at +12; l_seq read as i32 at +16


def check_record_body(data: bytes) -> str | None:
    """Reason string when a record body's declared field lengths cannot
    fit its block size, else None. Byte-for-byte the same rule (and the
    same REASON_RECORD_CORRUPT message) as native/bamio.cpp's
    body_check — the two decode engines must refuse the same records.

    `data` is the record body WITHOUT its leading block_size prefix.
    """
    bs = len(data)
    if bs < 32:
        return REASON_RECORD_CORRUPT
    l_qname = data[8]
    (n_cigar,) = _LSEQ_NCIG.unpack_from(data, 12)
    (l_seq,) = struct.unpack_from("<i", data, 16)
    if l_qname < 1 or l_seq < 0:
        return REASON_RECORD_CORRUPT
    need = 32 + l_qname + 4 * n_cigar + (l_seq + 1) // 2 + l_seq
    if need > bs:
        return REASON_RECORD_CORRUPT
    return None


# ---------------------------------------------------------------------------
# record-level semantic validation

#: CIGAR ops that consume query bases (M I S = X) — io.bam order.
_CONSUMES_QUERY = (1, 1, 0, 0, 1, 0, 0, 1, 1)


def _printable(s: str) -> bool:
    return all(0x21 <= ord(c) <= 0x7E for c in s)


def record_violation(
    rec, n_ref: int | None = None,
    ref_lens=None, max_read_len: int = 1 << 16,
) -> tuple[str, bool] | None:
    """(reason, repairable) for a decoded BamRecord that fails semantic
    validation, else None. `repairable` marks the violation classes the
    lenient policy may fix in place (repair_record)."""
    l_seq = len(rec.seq)
    if l_seq > max_read_len:
        return ("read-too-long", False)
    if rec.cigar and l_seq > 0:
        qlen = sum(ln for op, ln in rec.cigar if _CONSUMES_QUERY[op])
        if qlen != l_seq:
            return ("cigar-seq-mismatch", False)
    if rec.pos < -1 or rec.next_pos < -1:
        return ("pos-out-of-range", False)
    if n_ref is not None:
        if rec.ref_id < -1 or rec.ref_id >= n_ref:
            return ("ref-out-of-range", False)
        if rec.next_ref_id < -1 or rec.next_ref_id >= n_ref:
            return ("ref-out-of-range", False)
    if (
        ref_lens is not None
        and 0 <= rec.ref_id < len(ref_lens)
        and rec.pos >= ref_lens[rec.ref_id]
    ):
        return ("pos-out-of-range", False)
    for key in ("MI", "RX"):
        if rec.has_tag(key):
            v = rec.get_tag(key)
            if not isinstance(v, str) or not v or not _printable(v):
                return ("tag-shape", False)
    if rec.qual and max(rec.qual) > QUAL_MAX:
        return ("qual-out-of-range", True)
    return None


def repair_record(rec) -> str | None:
    """Apply the provably-safe lenient repairs in place; returns the
    repair reason or None. Today: clamp out-of-range quals to the
    Phred-93 emit ceiling (ordering-preserving; every emitted qual is
    capped there anyway, ops.phred.MAX_PHRED)."""
    if rec.qual and max(rec.qual) > QUAL_MAX:
        rec.qual = bytes(min(q, QUAL_MAX) for q in rec.qual)
        return "qual-out-of-range"
    return None


def batch_violations(
    batch, n_ref: int | None = None, ref_lens=None,
    max_read_len: int = 1 << 16,
) -> dict[int, tuple[str, bool]]:
    """Vectorized record_violation over one io.native.ColumnarBatch:
    {record index -> (reason, repairable)}. Empty on well-formed input
    — the native hot path pays a handful of numpy passes per 64K-record
    batch and nothing per record."""
    out: dict[int, tuple[str, bool]] = {}
    n = batch.n
    if n == 0:
        return out

    def mark(idx_array, reason, repairable=False):
        for i in idx_array:
            out.setdefault(int(i), (reason, repairable))

    l_seq = batch.l_seq
    mark(np.nonzero((l_seq < 0) | (l_seq > max_read_len))[0], "read-too-long")
    # MI/RX present-but-malformed (native extractor sentinel); absent
    # RX stays legal, absent MI errors at the grouper before batching
    for col in (getattr(batch, "mi", None), getattr(batch, "rx", None)):
        if col is not None:
            mark(np.nonzero(col == TAG_MALFORMED)[0], "tag-shape")
    bad_pos = (batch.pos < -1) | (batch.next_pos < -1)
    if ref_lens is not None and len(ref_lens):
        lens = np.asarray(ref_lens, dtype=np.int64)
        rid = batch.ref_id
        valid = (rid >= 0) & (rid < len(lens))
        over = np.zeros(n, dtype=bool)
        over[valid] = batch.pos[valid].astype(np.int64) >= lens[rid[valid]]
        bad_pos |= over
    mark(np.nonzero(bad_pos)[0], "pos-out-of-range")
    if n_ref is not None:
        bad_ref = (
            (batch.ref_id < -1) | (batch.ref_id >= n_ref)
            | (batch.next_ref < -1) | (batch.next_ref >= n_ref)
        )
        mark(np.nonzero(bad_ref)[0], "ref-out-of-range")
    # CIGAR query length vs l_seq (records with a CIGAR only)
    ncig = batch.n_cigar.astype(np.int64)
    has_cigar = np.nonzero((ncig > 0) & (l_seq > 0))[0]
    if len(has_cigar):
        co = batch.cigar_off
        cused = int(co[-1] + ncig[-1])
        cg = batch.cigar[:cused]
        contrib = np.where(
            np.asarray(_CONSUMES_QUERY, dtype=np.uint8)[cg & 0xF] != 0,
            (cg >> 4).astype(np.int64), 0,
        )
        cum = np.concatenate([[0], np.cumsum(contrib)])
        qlen = cum[co[has_cigar] + ncig[has_cigar]] - cum[co[has_cigar]]
        mark(
            has_cigar[qlen != l_seq[has_cigar].astype(np.int64)],
            "cigar-seq-mismatch",
        )
    # qual range (vectorized over the var plane; 0xFF-first = missing)
    vused = int(batch.var_off[-1] + l_seq[-1]) if int(l_seq[-1]) >= 0 else 0
    if vused > 0:
        bad_q = np.nonzero(batch.qual[:vused] > QUAL_MAX)[0]
        if len(bad_q):
            owner = np.searchsorted(batch.var_off, bad_q, side="right") - 1
            for i in np.unique(owner):
                i = int(i)
                off = int(batch.var_off[i])
                ls = int(l_seq[i])
                if ls > 0 and batch.qual[off] != 0xFF:
                    out.setdefault(i, ("qual-out-of-range", True))
    return out


# ---------------------------------------------------------------------------
# the Guard: policy + sidecar + counters

POLICIES = ("strict", "quarantine", "lenient", "off")
ENV_POLICY = "BSSEQ_TPU_INPUT_POLICY"
ENV_MAX_FAMILY = "BSSEQ_TPU_MAX_FAMILY_RECORDS"
ENV_MAX_READ_LEN = "BSSEQ_TPU_MAX_READ_LEN"
ENV_EVENT_CAP = "BSSEQ_TPU_GUARD_EVENT_CAP"

DEFAULT_MAX_FAMILY_RECORDS = 1 << 20
DEFAULT_MAX_READ_LEN = 1 << 16


def resolve_policy(policy: str | None = None) -> str:
    policy = policy or os.environ.get(ENV_POLICY, "strict")
    if policy not in POLICIES:
        raise ValueError(
            f"unknown {ENV_POLICY} {policy!r} (want "
            f"{'|'.join(POLICIES)})"
        )
    return policy


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


class Guard:
    """One stage's input-hardening context: policy, limits, the lazy
    quarantine sidecar, and counters (merged into the stage's locked
    Metrics so they surface as first-class StageStats fields).

    Construct per stage (stages.PipelineBuilder / the CLI subcommands)
    and `bind()` it to the input path + header once the reader is open;
    an unbound guard still validates and counts, it just cannot write a
    sidecar (records are counted + ledgered only).
    """

    def __init__(self, policy: str | None = None, stats=None,
                 max_family_records: int | None = None,
                 max_read_len: int | None = None,
                 job: str | None = None):
        self.policy = resolve_policy(policy)
        self.stats = stats
        #: serve tenancy: a job-bound guard tags its ledger events so a
        #: shared serve ledger attributes quarantines to the right tenant
        self.job = job
        self.max_family_records = (
            max_family_records
            if max_family_records is not None
            else _env_int(ENV_MAX_FAMILY, DEFAULT_MAX_FAMILY_RECORDS)
        )
        self.max_read_len = (
            max_read_len
            if max_read_len is not None
            else _env_int(ENV_MAX_READ_LEN, DEFAULT_MAX_READ_LEN)
        )
        self.input_path: str | None = None
        self.header = None
        self.n_ref: int | None = None
        self.ref_lens: list[int] | None = None
        #: set by the guarded reader wrap so guard_groups does not
        #: re-validate records a record-level pass already cleared
        self.records_prevalidated = False
        self._sidecar = None
        self._event_budget = _env_int(ENV_EVENT_CAP, 100)
        self._events_dropped = 0

    # -- policy predicates ----------------------------------------------

    @property
    def active(self) -> bool:
        return self.policy != "off"

    @property
    def strict(self) -> bool:
        return self.policy == "strict"

    @property
    def resilient(self) -> bool:
        """True when stream/record corruption is survivable (quarantine
        + resync instead of fail-fast)."""
        return self.policy in ("quarantine", "lenient")

    @property
    def lenient(self) -> bool:
        return self.policy == "lenient"

    @classmethod
    def from_env(cls, stats=None) -> "Guard":
        return cls(stats=stats)

    # -- wiring ----------------------------------------------------------

    def bind(self, input_path: str | None, header=None) -> "Guard":
        self.input_path = input_path
        if header is not None:
            self.header = header
            self.n_ref = len(header.references)
            self.ref_lens = [ln for _, ln in header.references]
        return self

    @property
    def sidecar_path(self) -> str | None:
        return (
            self.input_path + ".quarantined.bam" if self.input_path else None
        )

    def count(self, name: str, n: int = 1) -> None:
        if self.stats is not None and n:
            self.stats.metrics.count(name, n)

    def _emit(self, event: str, payload: dict) -> None:
        if self._event_budget > 0:
            self._event_budget -= 1
            observe.emit(event, payload, job=self.job)
        else:
            self._events_dropped += 1

    # -- quarantine -------------------------------------------------------

    def _sidecar_writer(self):
        if self._sidecar is None and self.sidecar_path and self.header:
            from bsseqconsensusreads_tpu.io.bam import BamWriter

            # fresh file per run: a checkpoint resume replays the whole
            # group stream, so the sidecar is deterministically
            # rewritten — counts match an uninterrupted run
            self._sidecar = BamWriter(
                self.sidecar_path, self.header, level=1
            )
        return self._sidecar

    def _write_sidecar(self, rec, reason: str) -> None:
        w = self._sidecar_writer()
        if w is None:
            return
        from bsseqconsensusreads_tpu.io.bam import BamRecord

        if not isinstance(rec, BamRecord):
            # a columnar view (or anything view-shaped): reconstruct the
            # fields the view exposes (MI/RX only — documented lossy)
            rec = BamRecord(
                qname=rec.qname, flag=rec.flag, ref_id=rec.ref_id,
                pos=rec.pos, mapq=rec.mapq, cigar=list(rec.cigar),
                next_ref_id=rec.next_ref_id, next_pos=rec.next_pos,
                tlen=rec.tlen, seq=rec.seq, qual=rec.qual,
                tags=dict(rec.tags),
            )
        else:
            rec = rec.copy()
        rec.set_tag("qr", reason, "Z")
        w.write(rec)

    def quarantine_blob(self, blob: bytes, index: int, reason: str,
                        voffset: int | None = None) -> None:
        """Quarantine a structurally corrupt record blob (cannot be
        decoded): preserved verbatim — capped at 4 KiB — in the `qb`
        hex tag of a placeholder unmapped record."""
        self.count("records_quarantined")
        self._emit("record_quarantined", {
            "input": self.input_path, "record_index": index,
            "reason": reason, "voffset": voffset, "bytes": len(blob),
        })
        w = self._sidecar_writer()
        if w is None:
            return
        from bsseqconsensusreads_tpu.io.bam import BamRecord, FUNMAP

        ph = BamRecord(qname=f"quarantined.{index}", flag=FUNMAP)
        ph.set_tag("qr", reason, "Z")
        ph.set_tag("qb", blob[:4096].hex().upper(), "H")
        w.write(ph)

    def quarantine_record(self, rec, index: int | None, reason: str) -> None:
        self.count("records_quarantined")
        self._emit("record_quarantined", {
            "input": self.input_path, "record_index": index,
            "qname": getattr(rec, "qname", None), "reason": reason,
        })
        self._write_sidecar(rec, reason)

    def quarantine_family(self, mi: str, records, reason: str) -> None:
        self.count("families_quarantined")
        self.count("family_records_quarantined", len(records))
        self._emit("family_quarantined", {
            "input": self.input_path, "mi": mi, "records": len(records),
            "reason": reason,
        })
        for rec in records:
            self._write_sidecar(rec, reason)

    def repaired(self, rec, index: int | None, reason: str) -> None:
        self.count("records_repaired")
        self._emit("record_repaired", {
            "input": self.input_path, "record_index": index,
            "qname": getattr(rec, "qname", None), "reason": reason,
        })

    def stream_event(self, kind: str, payload: dict) -> None:
        """Ledger a stream-resilience event (bgzf resync gap, truncated
        tail) and count it under the same name."""
        self.count(kind)
        self._emit(kind, {"input": self.input_path, **payload})

    def close(self) -> None:
        if self._events_dropped:
            observe.emit("guard_events_truncated", {
                "input": self.input_path, "dropped": self._events_dropped,
            }, job=self.job)
            self._events_dropped = 0
        if self._sidecar is not None:
            self._sidecar.close()
            self._sidecar = None


# ---------------------------------------------------------------------------
# group-level admission control

def _family_run_violations(fam, guard: Guard) -> dict[int, tuple[str, bool]]:
    """Batch-cached vectorized violations for an ingest.FamilyRun (or a
    list of ColumnarRecordViews sharing one batch): {absolute batch
    index -> (reason, repairable)}."""
    batch = fam.batch
    cache = getattr(batch, "guard_bad", None)
    if cache is None:
        cache = batch_violations(
            batch, n_ref=guard.n_ref, ref_lens=guard.ref_lens,
            max_read_len=guard.max_read_len,
        )
        try:
            batch.guard_bad = cache
        except AttributeError:  # foreign batch type without the slot
            pass
    return cache


def guard_groups(
    groups: Iterable, guard: Guard | None,
) -> Iterator:
    """Wrap a (mi, records) / ingest.FamilyRun group stream with the
    guard's family-level admission control and (when the records were
    not already validated record-by-record upstream) semantic record
    validation. Pass-through when the guard is off/None.

    Family-level rules, all policies:
    * more than guard.max_family_records records -> strict: raise
      FamilyGuardError; else quarantine the family whole. This cap is
      admission *policy*, not envelope self-defense: the segment-packed
      kernel layout already keeps a giant family from padding its
      batchmates (it contributes only its own rows to the dense axis),
      but an unbounded family still costs unbounded host memory during
      grouping and unbounded device rows in its batch — the >=100 GB
      failure mode of the reference is bounded here by choice, at a
      configurable line, rather than by layout necessity.
    * any record in the family failing semantic validation -> strict:
      raise RecordGuardError; lenient: repair when repairable; else
      quarantine the family whole (a corrupt member poisons the
      consensus, and family-granular drops keep the python and native
      engines byte-identical on the same corrupt input).
    """
    if guard is None or not guard.active:
        yield from groups
        return
    for fam in groups:
        n = getattr(fam, "n", None)
        if n is None:
            mi, records = fam
            n = len(records)
        else:
            mi = fam.mi
        if n > guard.max_family_records:
            if guard.strict:
                raise FamilyGuardError(
                    f"family {mi!r} has {n} records "
                    f"(cap {guard.max_family_records}; raise "
                    f"{ENV_MAX_FAMILY} if this input is trusted)",
                    reason="family-too-large", mi=mi,
                )
            records = fam.records if hasattr(fam, "records") else fam[1]
            guard.quarantine_family(mi, records, "family-too-large")
            continue
        if hasattr(fam, "batch"):  # ingest.FamilyRun: vectorized check
            bad = _family_run_violations(fam, guard)
            if bad:
                hit = [
                    i for i in range(fam.start, fam.start + fam.n)
                    if i in bad
                ]
                if hit:
                    if guard.strict:
                        reason, _ = bad[hit[0]]
                        raise RecordGuardError(
                            f"record failed input validation: {reason}",
                            reason=reason, record_index=hit[0],
                        )
                    if guard.lenient and all(
                        bad[i][1] for i in hit
                    ):
                        # repairable-only family: clamp in the shared
                        # qual plane (views read through to it)
                        for i in hit:
                            off = int(fam.batch.var_off[i])
                            ls = int(fam.batch.l_seq[i])
                            q = fam.batch.qual[off:off + ls]
                            np.minimum(q, QUAL_MAX, out=q)
                            guard.repaired(None, i, bad[i][0])
                        yield fam
                        continue
                    guard.quarantine_family(
                        mi, fam.records, bad[hit[0]][0]
                    )
                    continue
            yield fam
            continue
        if guard.records_prevalidated:
            yield mi, records
            continue
        # python-object groups: per-record semantic validation
        viol = None
        for rec in records:
            if hasattr(rec, "_b"):  # columnar views w/o FamilyRun
                bad = _family_run_violations(
                    type("F", (), {"batch": rec._b})(), guard
                )
                v = bad.get(rec._i)
            else:
                v = record_violation(
                    rec, n_ref=guard.n_ref, ref_lens=guard.ref_lens,
                    max_read_len=guard.max_read_len,
                )
            if v is not None:
                viol = (rec, v)
                if not (guard.lenient and v[1]):
                    break
        if viol is None:
            yield mi, records
            continue
        rec, (reason, repairable) = viol
        if guard.strict:
            raise RecordGuardError(
                f"record failed input validation: {reason}",
                reason=reason, qname=getattr(rec, "qname", None),
            )
        if guard.lenient:
            repaired_all = True
            for r in records:
                v = record_violation(
                    r, n_ref=guard.n_ref, ref_lens=guard.ref_lens,
                    max_read_len=guard.max_read_len,
                )
                if v is None:
                    continue
                if v[1] and not hasattr(r, "_b"):
                    fixed = repair_record(r)
                    if fixed:
                        guard.repaired(r, None, fixed)
                        continue
                repaired_all = False
                break
            if repaired_all:
                yield mi, records
                continue
        guard.quarantine_family(mi, records, reason)
