"""netchaos: the wire-fault shim for the framed transport.

The 25 process failpoint sites fire *inside* functions; nothing before
this module could fault the wire itself. netchaos interprets the three
net_* sites (faults.failpoints: ``net_send`` / ``net_recv`` /
``net_accept``) whose actions are network behaviours no ``raise`` can
model:

    delay[:Ns]   sleep before the wire op (latency injection)
    drop         close the connection without delivering the frame —
                 the peer sees a clean EOF, the sender a dead socket
    dup          deliver the same frame twice: the client re-issues the
                 identical request on a fresh connection and discards
                 the second reply, proving server-side idempotency
    corrupt      flip bytes after the length header — the receiving
                 framing layer must REFUSE the frame (bad_json), never
                 parse it
    half_open    accept, then stall and close without answering — the
                 client's read blocks until its own timeout
    partition    refuse the connection outright; pair with @peer= for
                 one-sided partitions (``net_send=partition@peer=...``)

The shim lives in serve/transport.py (client edges) and
serve/server.py (accept edge); this module only evaluates the schedule
into a `WirePlan` and supplies the byte-mangler. Every fired point is
ledgered (``failpoint_fired`` with the peer address and the ambient
trace binding) by failpoints.evaluate, so ``cli observe trace`` shows
the fault on the critical path. Zero-cost when unarmed.
"""

from __future__ import annotations

from dataclasses import dataclass

from bsseqconsensusreads_tpu.faults import failpoints


@dataclass
class WirePlan:
    """The folded network faults scheduled for ONE wire operation."""

    delay_s: float = 0.0
    drop: bool = False
    dup: bool = False
    corrupt: bool = False
    half_open: bool = False
    half_open_s: float = 30.0
    partition: bool = False

    def __bool__(self) -> bool:
        return bool(
            self.delay_s > 0.0
            or self.drop
            or self.dup
            or self.corrupt
            or self.half_open
            or self.partition
        )


#: The plan evaluate() returns when unarmed — immutable by convention;
#: callers only read it.
_QUIET = WirePlan()


def plan(site: str, peer: str = "") -> WirePlan:
    """Evaluate the armed schedule at one net_* site against this peer
    and fold every fired point into a WirePlan for the transport to
    enact. Fired points were already counted and ledgered
    (failpoint_fired) by failpoints.evaluate. One branch when unarmed.
    """
    if not failpoints.ARMED:
        return _QUIET
    fired = failpoints.evaluate(site, peer=peer)
    if not fired:
        return _QUIET
    p = WirePlan()
    for fp in fired:
        if fp.action == "delay":
            p.delay_s += fp.duration_s
        elif fp.action == "drop":
            p.drop = True
        elif fp.action == "dup":
            p.dup = True
        elif fp.action == "corrupt":
            p.corrupt = True
        elif fp.action == "half_open":
            p.half_open = True
            p.half_open_s = fp.duration_s
        elif fp.action == "partition":
            p.partition = True
        elif fp.action == "stall":
            # process actions remain legal at net sites; stall folds
            # into the delay budget rather than wedging inside the shim
            p.delay_s += fp.duration_s
    return p


def mangle(body: bytes) -> bytes:
    """Corrupt a frame BODY (the bytes after any length header): XOR the
    first 8 bytes with 0xA5. A JSON body starts with ``{"`` — the flip
    yields non-UTF-8 garbage the receiving framing layer must refuse
    (reason bad_json), never parse. Newlines later in the body are
    untouched so the unix JSONL framing still delimits one line."""
    if not body:
        return body
    n = min(8, len(body))
    return bytes(b ^ 0xA5 for b in body[:n]) + body[n:]
