"""Batch-level retry executor: bounded backoff + graceful degradation.

One recovery policy for the whole hot path (pipeline.calling wraps each
dispatch+fetch unit; pipeline.extsort and pipeline.checkpoint wrap
their durable writes):

* transient failures (`RETRYABLE`: OSError — which covers BGZF/CRC
  integrity errors — RuntimeError — which covers XLA runtime errors —
  and TimeoutError) are retried with bounded exponential backoff;
* a unit that keeps failing degrades to the caller-provided fallback
  (the consensus stages pass the host-XLA CPU twin of the same kernel,
  bit-identical output with no device in the loop) instead of killing
  the run;
* everything is ledgered ('batch_retry' / 'batch_recovered' /
  'batch_degraded') and counted ('batches_retried' / 'batches_recovered'
  / 'batches_degraded' / 'retry_attempts' on the stage metrics), so a
  run that limped home says so — degraded batches are NOT free
  (BASELINE.md: host-twin batches count against reads/sec/chip).

Programming errors (ValueError, TypeError, KeyError, assertion
failures) are deliberately NOT retryable: retrying a deterministic bug
just burns the attempt budget before failing anyway.

Env knobs:
  BSSEQ_TPU_RETRY_MAX        total attempts per unit (default 3)
  BSSEQ_TPU_RETRY_BACKOFF_S  first backoff, doubling per retry
                             (default 0.05, capped at 2s)
  BSSEQ_TPU_STALL_TIMEOUT_S  overlap-pool stall watchdog: main-thread
                             seconds to wait on an in-flight future
                             before cancelling and re-dispatching
                             inline (default 0 = disabled)
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass

from bsseqconsensusreads_tpu.utils import observe

#: Exception classes the executor treats as transient.
RETRYABLE = (OSError, RuntimeError, TimeoutError)


@dataclass(frozen=True)
class RetryPolicy:
    max_attempts: int = 3
    backoff_s: float = 0.05
    backoff_cap_s: float = 2.0


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def policy_from_env() -> RetryPolicy:
    return RetryPolicy(
        max_attempts=max(1, int(_env_float("BSSEQ_TPU_RETRY_MAX", 3))),
        backoff_s=max(0.0, _env_float("BSSEQ_TPU_RETRY_BACKOFF_S", 0.05)),
    )


def stall_timeout() -> float:
    """Watchdog timeout for overlap-pool futures; 0 disables."""
    return max(0.0, _env_float("BSSEQ_TPU_STALL_TIMEOUT_S", 0.0))


def _backoff(policy: RetryPolicy, attempt: int) -> float:
    return min(
        policy.backoff_s * (2 ** (attempt - 1)), policy.backoff_cap_s
    )


def _note_retry(exc, metrics, stage, batch, attempt: int) -> None:
    if metrics is not None:
        if attempt == 1:
            metrics.count("batches_retried")
        metrics.count("retry_attempts")
    observe.emit(
        "batch_retry",
        {
            "stage": stage,
            "batch": batch,
            "attempt": attempt,
            "error": f"{type(exc).__name__}: {exc}",
        },
    )


def _degrade_or_raise(exc, degrade, metrics, stage, batch, attempts: int):
    if degrade is None:
        raise exc
    if metrics is not None:
        metrics.count("batches_degraded")
    observe.emit(
        "batch_degraded",
        {
            "stage": stage,
            "batch": batch,
            "attempts": attempts,
            "error": f"{type(exc).__name__}: {exc}",
        },
    )
    return degrade()


def guarded(
    unit,
    *,
    degrade=None,
    metrics=None,
    stage: str = "",
    batch: int | None = None,
    policy: RetryPolicy | None = None,
    sleep=time.sleep,
    failed: BaseException | None = None,
):
    """Run `unit()` under the bounded retrier.

    RETRYABLE failures re-run the unit after exponential backoff; the
    policy's final failure degrades to `degrade()` (or re-raises when no
    fallback exists). `failed` seeds the loop with a failure that
    already happened elsewhere (the inline dispatch path catches the
    dispatch exception itself, then hands recovery here — that failure
    is attempt 1). `metrics` is an observe.Metrics (locked counters —
    this runs on overlap-pool worker threads).
    """
    pol = policy or policy_from_env()
    attempt = 0  # failed attempts so far
    if failed is not None:
        attempt = 1
        if attempt >= pol.max_attempts:
            _note_retry(failed, metrics, stage, batch, attempt)
            return _degrade_or_raise(
                failed, degrade, metrics, stage, batch, attempt
            )
        _note_retry(failed, metrics, stage, batch, attempt)
        sleep(_backoff(pol, attempt))
    while True:
        try:
            out = unit()
        except RETRYABLE as exc:
            attempt += 1
            if attempt >= pol.max_attempts:
                return _degrade_or_raise(
                    exc, degrade, metrics, stage, batch, attempt
                )
            _note_retry(exc, metrics, stage, batch, attempt)
            sleep(_backoff(pol, attempt))
        else:
            if attempt:
                if metrics is not None:
                    metrics.count("batches_recovered")
                observe.emit(
                    "batch_recovered",
                    {"stage": stage, "batch": batch, "attempts": attempt + 1},
                )
            return out
