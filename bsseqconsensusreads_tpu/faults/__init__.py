"""Fault injection, bounded retries, and durable-state integrity.

The crash-only contract (ROADMAP north-star: a 100M-read run on a v4-8
must not lose hours to one flaky transfer) needs three things the rest
of the framework provides hooks for but nothing exercises:

* `failpoints` — named, deterministically-scheduled injection sites
  threaded through the whole hot path (dispatch/fetch/retire, host-pool
  tasks, extsort spill/merge, checkpoint shard/manifest/finalize, BGZF
  inflate/write, native library load, multihost heartbeat/collective).
  Armed via `BSSEQ_TPU_FAILPOINTS` / `--failpoints`; zero-cost when
  unarmed.
* `retry` — the batch-level retry executor: bounded exponential backoff
  for transient device/transfer errors, a stall watchdog for wedged
  overlap-pool futures, and graceful degradation to the host XLA twin
  on persistent kernel failure.
* `integrity` — streaming CRC32 over durable artifacts (checkpoint
  shards, extsort spill runs) so a corrupt file is quarantined and
  recomputed instead of crashing the run or silently merging garbage.

`tools/chaos_drill.py` drives the whole surface against a mini
pipeline and asserts byte-identical output under every fault class.
"""

from bsseqconsensusreads_tpu.faults.failpoints import (  # noqa: F401
    FailpointError,
    arm,
    arm_from_env,
    disarm,
    fire,
    fired_counts,
)
from bsseqconsensusreads_tpu.faults.integrity import (  # noqa: F401
    IntegrityError,
    file_crc32,
    verify_file_crc32,
)
from bsseqconsensusreads_tpu.faults.retry import (  # noqa: F401
    RETRYABLE,
    RetryPolicy,
    guarded,
    policy_from_env,
    stall_timeout,
)
