"""graftswarm: elastic multi-process orchestration.

Coordinator/worker sharded runs over the PR 11 framed transport, with
loss recovery (lease expiry → checkpoint-prefix requeue) and a merge
byte-identical to the single-process pipeline. See coordinator.py for
the ledger/durability design and merge.py for the determinism proof.
"""

from bsseqconsensusreads_tpu.elastic import fencing, merge
from bsseqconsensusreads_tpu.elastic.coordinator import (
    ENV_CHUNK_B,
    DEFAULT_LEASE_S,
    ENV_COORDINATOR_ADDR,
    ENV_LEASE_S,
    ENV_WORKER_ID,
    Coordinator,
    ElasticError,
    SliceLedger,
    base_mi,
    config_doc,
    config_from_doc,
    lease_seconds,
    run_elastic,
    slice_name,
    split_input,
)
from bsseqconsensusreads_tpu.elastic.fencing import EpochBook, FencedError
from bsseqconsensusreads_tpu.elastic.worker import (
    process_slice,
    slice_config,
    work_loop,
)

__all__ = [
    "DEFAULT_LEASE_S",
    "ENV_CHUNK_B",
    "ENV_COORDINATOR_ADDR",
    "ENV_LEASE_S",
    "ENV_WORKER_ID",
    "Coordinator",
    "ElasticError",
    "EpochBook",
    "FencedError",
    "fencing",
    "SliceLedger",
    "base_mi",
    "config_doc",
    "config_from_doc",
    "lease_seconds",
    "merge",
    "process_slice",
    "run_elastic",
    "slice_config",
    "slice_name",
    "split_input",
    "work_loop",
]
