"""graftswarm finalize: merge per-slice outputs into the single-process
bytes, and refuse to call the run ok until the counters reconcile.

Why the merge is exact (the determinism proof, README "Elastic
execution"):

* slices are CONTIGUOUS base-family ordinal ranges, so concatenating
  the slice emission streams in slice order reproduces the exact
  family order of the single-process emission stream;
* each slice output is coordinate-sorted by the same near-total
  `raw_coordinate_key` (ref, pos, qname, flag) the single-process sort
  uses, and `heapq.merge` is stable, so merging the slice streams in
  slice order breaks residual key ties by emission order — exactly the
  tie-break the single-process stable sort applies;
* consensus record bytes depend only on family content (qnames come
  from the MI), never the sample name or the process that computed
  them;
* the final header is rebuilt from the ORIGINAL input header + sample
  through the same @PG chain `stages.run_duplex` writes, and the final
  BGZF stream is one continuous level-6 writer — the same compressor
  state path as single-process.

Reconciliation (the "counters reconcile" acceptance gate) cross-checks
three independent ledgers before the ok: split counts (records in),
per-slice StageStats sums (what the pipelines saw), and the merged
stream itself (records out + the per-bucket vectors from the PR 12
bucket geometry).
"""

from __future__ import annotations

import heapq
import os

from bsseqconsensusreads_tpu.config import FrameworkConfig
from bsseqconsensusreads_tpu.faults import failpoints as _failpoints
from bsseqconsensusreads_tpu.faults import integrity as _integrity
from bsseqconsensusreads_tpu.io.bam import BamHeader, BamReader, BamWriter
from bsseqconsensusreads_tpu.pipeline.bucketemit import (
    BucketPlan,
    blob_bucket_key,
    resolve_buckets,
)
from bsseqconsensusreads_tpu.pipeline.extsort import raw_coordinate_key
from bsseqconsensusreads_tpu.pipeline.stages import sample_name
from bsseqconsensusreads_tpu.utils import observe

from bsseqconsensusreads_tpu.elastic.coordinator import (
    ElasticError,
    slice_name,
)

from bsseqconsensusreads_tpu import __version__

#: StageStats count keys that sum across slices to the single-process
#: value (time/ratio keys like wall_seconds or pad_waste do not).
SUMMABLE_STATS = (
    "records_in",
    "records_seen",
    "records_quarantined",
    "records_repaired",
    "families_quarantined",
    "family_records_quarantined",
    "families",
    "consensus_out",
    "skipped_families",
    "leftover_records",
    "refragmented_families",
    "batches",
    "indel_aligned",
    "indel_dropped",
)


def final_header(input_header: BamHeader, sample: str) -> BamHeader:
    """The exact header chain stages.run_molecular + run_duplex (self
    mode) apply to the original input header."""
    h = input_header.with_pg(
        "bsseqconsensusreads_tpu", __version__, f"molecular sample={sample}"
    )
    h = h.with_pg(
        "bsseqconsensusreads_tpu", __version__, f"duplex sample={sample}"
    )
    return h.with_sort_order("coordinate")


def _sum_stats(manifests: dict[int, dict]) -> dict[str, dict]:
    """Per-stage sums of the summable StageStats counters across all
    slice manifests."""
    out: dict[str, dict] = {}
    for m in manifests.values():
        for stage, stats in (m.get("stats") or {}).items():
            acc = out.setdefault(stage, {k: 0 for k in SUMMABLE_STATS})
            for k in SUMMABLE_STATS:
                acc[k] += int(stats.get(k, 0))
    return out


def reconcile(
    specs: list[dict],
    manifests: dict[int, dict],
    merged_records: int,
    merged_buckets: list[int],
) -> dict:
    """Cross-check split / per-slice / merged ledgers. Returns the
    report; report['ok'] gates the run."""
    stats = _sum_stats(manifests)
    records_split = sum(sl["records"] for sl in specs)
    records_out = sum(int(m.get("records_out", 0)) for m in manifests.values())
    slice_buckets = [0] * len(merged_buckets)
    for m in manifests.values():
        for i, n in enumerate(m.get("buckets") or []):
            if i < len(slice_buckets):
                slice_buckets[i] += int(n)
    molecular = stats.get("molecular", {})
    checks = {
        "slices_complete": len(manifests) == len(specs),
        "records_out_match_merge": records_out == merged_records,
        "buckets_match": slice_buckets == list(merged_buckets),
        # records in == out + quarantined, measured at the ingest stage:
        # every split record was either consumed by the molecular stage
        # or loudly quarantined — none vanished between processes.
        "records_in_match_split": (
            molecular.get("records_in", 0)
            + molecular.get("records_quarantined", 0)
            == records_split
        ),
    }
    return {
        "ok": all(checks.values()),
        "checks": checks,
        "records": merged_records,
        "records_split": records_split,
        "stats": stats,
    }


def finalize(
    cfg: FrameworkConfig,
    bam_path: str,
    outdir: str,
    specs: list[dict],
    manifests: dict[int, dict],
) -> tuple[str, dict]:
    """K-way merge of the committed slice outputs into the final
    coordinate-sorted BAM, then reconcile. Returns (target, report)."""
    missing = [slice_name(sl["sid"]) for sl in specs
               if sl["sid"] not in manifests]
    if missing:
        raise ElasticError(f"cannot finalize: missing slices {missing}")
    sample = sample_name(bam_path)
    target = os.path.join(outdir, f"{sample}_consensus_duplex_unfiltered.bam")
    _failpoints.fire("elastic_merge", slices=len(specs))

    with BamReader(bam_path) as reader:
        header = final_header(reader.header, sample)
    plan = BucketPlan.from_header(header, resolve_buckets(cfg.sort_buckets))
    bucket_counts = [0] * plan.nbuckets
    merged = 0

    readers = []
    streams = []
    try:
        for sl in sorted(specs, key=lambda s: s["sid"]):
            m = manifests[sl["sid"]]
            out = os.path.join(
                outdir, "elastic", "slices", slice_name(sl["sid"]),
                str(m["output"]),
            )
            _integrity.verify_file_crc32(
                out, int(m["crc"]),
                what=f"slice {slice_name(sl['sid'])} output at merge",
            )
            r = BamReader(out, threads=1)
            readers.append(r)
            streams.append(r.raw_records())

        def counted(blobs):
            nonlocal merged
            for blob in blobs:
                bucket_counts[plan.bucket_of(blob_bucket_key(blob))] += 1
                merged += 1
                yield blob

        tmp = target + ".merge.tmp"
        writer = BamWriter(tmp, header, level=6)
        try:
            writer.write_raw_many(
                counted(heapq.merge(*streams, key=raw_coordinate_key))
            )
        finally:
            writer.close()
        os.replace(tmp, target)
    finally:
        for r in readers:
            r.close()

    report = reconcile(specs, manifests, merged, bucket_counts)
    observe.emit(
        "elastic_merged",
        {"records": merged, "slices": len(specs), "ok": report["ok"]},
    )
    return target, report
