"""graftpreempt — first-class voluntary preemption for elastic workers.

Spot-style eviction is a *protocol event*, not a crash. Until this
module, the only way a worker gave up a slice was by dying: the
coordinator waited out ``lease_s`` expiry and the successor restarted
from whatever checkpoint prefix had survived. graftpreempt makes the
cheap path explicit:

* **latch** — SIGTERM (or a test's explicit :meth:`PreemptFlag.request`)
  sets a process-wide latch. Nothing is interrupted; the in-flight
  batch keeps running.
* **batch gate** — `pipeline.checkpoint.write_batches` consults an
  installed gate after every consumed batch. Once the latch is set the
  gate raises :class:`PreemptedError`; ``write_batches`` flushes the
  pending buffer *first*, so the interrupting batch is durable (shard +
  manifest + methyl watermark aligned) before control unwinds. Handoff
  latency is therefore bounded by ONE batch, not one lease.
* **handoff** — the worker writes a ``handoff.json`` manifest next to
  the slice checkpoints (durable prefix, ``batches_kept``, methyl
  watermark), sends a ``preempt`` op releasing its lease voluntarily,
  and exits 0. The coordinator requeues the slice immediately — no
  ``lease_s`` wait — and the next grant's fence epoch revokes the
  departed holder exactly like a crash would (PR 18 precedence: a
  straggling publish under the old epoch is refused ``fenced`` before
  any lease bookkeeping runs).

The grace budget ``BSSEQ_TPU_PREEMPT_GRACE_S`` bounds how long the
handoff may take end-to-end; a worker that cannot finish its in-flight
batch inside the budget abandons the handoff op and exits anyway — the
durable prefix is already on disk, and lease expiry remains the
backstop, so grace lapse degrades to exactly the old crash path.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time

from bsseqconsensusreads_tpu.utils import observe

ENV_GRACE_S = "BSSEQ_TPU_PREEMPT_GRACE_S"
DEFAULT_GRACE_S = 30.0

HANDOFF_NAME = "handoff.json"


def grace_s() -> float:
    """The end-to-end handoff budget: latch → lease released."""
    try:
        return float(os.environ.get(ENV_GRACE_S, DEFAULT_GRACE_S))
    except ValueError:
        return DEFAULT_GRACE_S


class PreemptedError(RuntimeError):
    """Raised from the batch gate once a preemption is pending: the
    batch that was executing when the latch fired is durable, the
    remainder of the slice is abandoned to the successor."""

    def __init__(self, batches_kept: int = 0):
        super().__init__(
            f"preempted with {batches_kept} durable batch(es)"
        )
        self.batches_kept = batches_kept


class PreemptFlag:
    """Process-wide preemption latch.

    Sticky by design: a second SIGTERM while the handoff is in flight
    must not restart the clock (the grid sends them in salvos). Tests
    construct private flags; production uses the module-level FLAG the
    signal handler targets."""

    def __init__(self):
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._t_request = 0.0

    def request(self) -> bool:
        """Latch a preemption. Returns True on the first request,
        False when one was already pending (salvo duplicate)."""
        with self._lock:
            if self._event.is_set():
                return False
            self._t_request = time.monotonic()
            self._event.set()
            return True

    def pending(self) -> bool:
        return self._event.is_set()

    def requested_at(self) -> float:
        """Monotonic timestamp of the first request (0.0 if none) —
        the start of the handoff-latency clock."""
        with self._lock:
            return self._t_request

    def deadline(self) -> float:
        """Monotonic deadline the grace budget imposes on the handoff."""
        with self._lock:
            return self._t_request + grace_s()

    def clear(self) -> None:
        """Re-arm (tests and the worker loop between slices)."""
        with self._lock:
            self._event.clear()
            self._t_request = 0.0


#: the process-wide latch the SIGTERM handler sets
FLAG = PreemptFlag()


def install_signal_handler(flag: PreemptFlag | None = None) -> bool:
    """Route SIGTERM to the latch. Returns False (and installs
    nothing) off the main thread — inline elastic runs process slices
    from worker threads where signal.signal raises ValueError; those
    runs preempt via the supervisor path instead."""
    target = FLAG if flag is None else flag

    def _handler(signum, frame):  # pragma: no cover - signal context
        target.request()

    try:
        signal.signal(signal.SIGTERM, _handler)
    except ValueError:
        return False
    return True


def batch_gate(flag: PreemptFlag | None = None):
    """Build the gate `pipeline.checkpoint.install_batch_gate` accepts:
    called with the would-be durable batch count after every consumed
    batch, raises PreemptedError once the latch is set. The checkpoint
    layer flushes the pending buffer before letting the error unwind,
    so ``batches_kept`` on the raised error is a *durable* count."""
    target = FLAG if flag is None else flag

    def _gate(batches_done: int) -> None:
        if target.pending():
            raise PreemptedError(batches_kept=batches_done)

    return _gate


def write_handoff(slice_dir: str, *, slice_name: str, worker: str,
                  batches_kept: int) -> str:
    """Persist the handoff manifest next to the slice checkpoints.

    The successor does not *need* it to resume (the ``*.ckpt.json``
    manifests are the durable truth) — it exists so the requeue is
    attributable: ledger reconciliation can distinguish a voluntary
    handoff from a crash, and the drill asserts the watermark here
    matches what the coordinator granted the successor."""
    manifest = {
        "slice": slice_name,
        "worker": worker,
        "batches_kept": int(batches_kept),
        # methyl tallies flush inside BatchCheckpoint.on_flush BEFORE
        # the manifest advances, so the durable batch count IS the
        # methyl watermark — recorded separately anyway because the
        # alignment is an invariant worth asserting, not assuming
        "methyl_watermark": int(batches_kept),
        "written_at": time.time(),
    }
    os.makedirs(slice_dir, exist_ok=True)
    path = os.path.join(slice_dir, HANDOFF_NAME)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(manifest, fh, indent=2, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    return path


def read_handoff(slice_dir: str) -> dict | None:
    path = os.path.join(slice_dir, HANDOFF_NAME)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def emit_handoff_published(*, slice_name: str, worker: str,
                           batches_kept: int,
                           handoff_latency_s: float) -> None:
    observe.emit(
        "handoff_published",
        {"slice": slice_name, "worker": worker,
         "batches_kept": int(batches_kept),
         "handoff_latency_s": round(float(handoff_latency_s), 6)},
    )
