"""graftswarm worker: the thin process side of `cli elastic`.

A worker is deliberately boring — it joins a coordinator, then loops
lease → run the EXISTING pipeline/stages.py chain over the leased
slice → publish a manifest. All elastic intelligence (splitting,
requeue, merge, reconciliation) lives coordinator-side; this module
adds nothing to the science path, which is the whole byte-identity
argument: the records a slice emits are the records the single-process
run emits for those families, produced by the same code.

Per slice the worker runs `run_pipeline` in a SLICE-KEYED work dir
(`<rundir>/slices/s<NNNN>/`). Keying by slice rather than worker is
the loss-recovery mechanism: when a lease lapses and the slice is
requeued, the next holder resumes from the same dir, where
BatchCheckpoint keeps the longest verified CRC shard prefix and
recomputes only the remainder — the dead worker's finished batches are
never redone and never double-emitted.

The published manifest carries the slice's family fingerprint, output
CRC, per-stage StageStats, and the per-bucket record counts of its
coordinate-bucketed output (BucketPlan over the final header), which
the coordinator's merge reconciles against the merged stream before
the run may call itself ok.
"""

from __future__ import annotations

import dataclasses
import os
import shutil
import threading
import time

from bsseqconsensusreads_tpu.config import FrameworkConfig
from bsseqconsensusreads_tpu.faults import failpoints as _failpoints
from bsseqconsensusreads_tpu.faults import integrity as _integrity
from bsseqconsensusreads_tpu.io.bam import BamReader
from bsseqconsensusreads_tpu.parallel.multihost import WorkerHeartbeat
from bsseqconsensusreads_tpu.pipeline.bucketemit import (
    BucketPlan,
    blob_bucket_key,
    resolve_buckets,
)
from bsseqconsensusreads_tpu.serve import transport
from bsseqconsensusreads_tpu.utils import observe

from bsseqconsensusreads_tpu.elastic.coordinator import (
    ENV_COORDINATOR_ADDR,
    ENV_SPAWNED_AT,
    ENV_WORKER_ID,
    ElasticError,
    config_from_doc,
    slice_name,
)


def slice_config(cfg: FrameworkConfig) -> FrameworkConfig:
    """The per-slice pipeline config. Grouping is forced off (slices
    are cut FROM grouped input; regrouping a shard could renumber
    families), interstage streaming off (checkpointing requires the
    materialized interstage, stages._interstage_blocked), and
    checkpoints on — they are what makes requeue cheap."""
    return dataclasses.replace(
        cfg,
        group_umis="never",
        stream_interstage=False,
        checkpoint_every=cfg.checkpoint_every if cfg.checkpoint_every >= 1
        else 4,
    )


def _bucket_manifest(path: str, buckets: int) -> tuple[list[int], int]:
    """Per-bucket record counts of one coordinate-sorted slice output
    (the PR 12 bucket geometry over the output's own header). The merge
    recomputes the same vector over the merged stream; equality means
    no record moved buckets and none vanished."""
    with BamReader(path, threads=1) as reader:
        plan = BucketPlan.from_header(reader.header, buckets)
        counts = [0] * plan.nbuckets
        total = 0
        for blob in reader.raw_records():
            counts[plan.bucket_of(blob_bucket_key(blob))] += 1
            total += 1
    return counts, total


def _reset_stale_finals(sdir: str, sname: str, worker: str) -> None:
    """A leased slice has NO committed manifest (the coordinator only
    leases unverified slices), so a durable stage FINAL in its work dir
    is the orphan of a holder that died between a stage finalize and
    the manifest commit. Stage stats are not durable: resuming past
    such a final would skip the stage whole (mtime rerun semantics) and
    the published manifest could never reconcile its ingest counters
    against the split. Finals appear atomically (tmp+rename at
    ckpt_finalize), so their presence is exact — clear the work dir and
    recompute the slice. Mid-stage deaths leave .ckpt/.part shards,
    never a final, so the cheap batches_kept resume path is untouched."""
    stale = sorted(
        f for f in os.listdir(sdir)
        if f.endswith(".bam") and ".ckpt" not in f and ".part" not in f
    )
    if not stale:
        return
    for f in os.listdir(sdir):
        path = os.path.join(sdir, f)
        if os.path.isdir(path):
            shutil.rmtree(path)
        else:
            os.unlink(path)
    observe.emit(
        "elastic_slice_reset",
        {"slice": sname, "worker": worker, "stale": stale},
    )


def process_slice(cfg: FrameworkConfig, rundir: str, sl: dict,
                  worker: str = "") -> dict:
    """Run the standard pipeline chain over one leased slice; returns
    the publishable manifest. Work dir is keyed by SLICE id so a
    requeued slice resumes its own checkpoints."""
    sname = slice_name(sl["sid"])
    _failpoints.fire("elastic_slice", slice=sname, worker=worker)
    sdir = os.path.join(rundir, "slices", sname)
    os.makedirs(sdir, exist_ok=True)
    _reset_stale_finals(sdir, sname, worker)
    scfg = dataclasses.replace(slice_config(cfg), tmp=sdir)
    slice_bam = os.path.join(rundir, sl["path"])
    _integrity.verify_file_crc32(
        slice_bam, sl["input_crc"], what=f"slice input {sname}"
    )
    # deferred: run_pipeline pulls the jax stack in; workers that only
    # join/poll must stay cheap to import
    import_t0 = time.time()
    from bsseqconsensusreads_tpu.pipeline.stages import run_pipeline

    if observe.stats_sink() is not None:
        # jax_import overhead bucket: near-zero after the first slice
        # (sys.modules cache), so summing dur_s still reads as the
        # one-time per-process import cost
        observe.emit_span(
            "jax_import", import_t0, time.time(),
            ctx=observe.proc_trace(), worker=worker,
        )
    t0 = time.monotonic()
    with observe.span("slice_pipeline", slice=sname, worker=worker):
        target, _results, stats = run_pipeline(scfg, slice_bam, outdir=sdir)
    wall_s = time.monotonic() - t0
    buckets, records_out = _bucket_manifest(
        target, resolve_buckets(cfg.sort_buckets)
    )
    manifest = {
        "slice": sname,
        "worker": worker,
        "output": os.path.basename(target),
        "crc": _integrity.file_crc32(target),
        "family_crc": sl["family_crc"],
        "records_in": sl["records"],
        "records_out": records_out,
        "buckets": buckets,
        "wall_s": round(wall_s, 3),
        "stats": {stage: s.as_dict() for stage, s in stats.items()},
    }
    observe.emit(
        "elastic_slice_processed",
        {"slice": sname, "worker": worker, "records_out": records_out,
         "wall_s": manifest["wall_s"]},
    )
    return manifest


def _renew_lease(address: str, worker: str, lease_id: str, lease_s: float,
                 stop: threading.Event, hb: WorkerHeartbeat) -> None:
    """Renewal pump for one held lease: a third of the lease period, so
    only a hung or dead process lets the lease lapse. A refused renewal
    means the coordinator already requeued us — stop renewing and let
    the publish refusal surface it."""
    interval = max(0.05, lease_s / 3.0)
    while not stop.wait(interval):
        hb.beat(phase="lease_renew", lease_id=lease_id)
        try:
            resp = transport.request(
                address,
                {"op": "heartbeat", "worker": worker, "lease_id": lease_id},
                timeout=max(5.0, lease_s),
            )
        except (OSError, transport.TransportError):
            continue  # transient: the next tick retries; expiry is the floor
        if not resp.get("ok"):
            return


def work_loop(address: str, worker_id: str | None = None,
              poll_s: float = 0.2) -> int:
    """Join a coordinator and process leased slices until it says done.
    Returns the number of slices this process published."""
    wid = worker_id or os.environ.get(ENV_WORKER_ID) or f"pid{os.getpid()}"
    os.environ[ENV_WORKER_ID] = wid
    os.environ[ENV_COORDINATOR_ADDR] = address
    joined = transport.request(
        address, {"op": "elastic_join", "worker": wid}, timeout=60.0
    )
    if not joined.get("ok"):
        raise ElasticError(f"join refused by {address}: {joined}")
    cfg = config_from_doc(joined["cfg"])
    rundir = joined["rundir"]
    lease_default = float(joined.get("lease_s") or 30.0)
    spawned_env = os.environ.pop(ENV_SPAWNED_AT, None)
    if spawned_env is not None and observe.stats_sink() is not None:
        # the supervisor stamped wall-clock spawn time into our env;
        # spawn → successful join is this process's worker_spawn bucket
        try:
            observe.emit_span(
                "worker_spawn", float(spawned_env), time.time(),
                ctx=observe.proc_trace(), worker=wid,
            )
        except ValueError:
            pass  # unparseable stamp: skip the span, never the worker
    hb = WorkerHeartbeat(component="elastic")
    hb.start()
    processed = 0
    wait_t0: float | None = None
    try:
        while True:
            hb.beat(phase="lease_poll")
            grant = transport.request(
                address, {"op": "lease", "worker": wid}, timeout=60.0
            )
            if grant.get("done"):
                return processed
            if grant.get("wait") or "slice" not in grant:
                if wait_t0 is None:
                    wait_t0 = time.time()
                time.sleep(poll_s)
                continue
            if wait_t0 is not None:
                if observe.stats_sink() is not None:
                    # lease_wait overhead bucket: idle span between the
                    # last grant and this one (backlog starvation)
                    observe.emit_span(
                        "lease_wait", wait_t0, time.time(),
                        ctx=observe.proc_trace(), worker=wid,
                    )
                wait_t0 = None
            sl = grant["slice"]
            lease_id = grant["lease_id"]
            lease_s = float(grant.get("lease_s") or lease_default)
            stop = threading.Event()
            # graftlint: owned-thread -- lease-renewal pump for the
            # slice this loop iteration is processing; joined below
            renewer = threading.Thread(
                target=_renew_lease,
                args=(address, wid, lease_id, lease_s, stop, hb),
                name=f"lease-renew-{lease_id}", daemon=True,
            )
            renewer.start()
            # the slice's trace ctx rode in on the grant; binding it here
            # puts process_slice's spans and the publish request (via the
            # wire's `_trace`) on the slice's causal tree
            slice_trace = sl.get("trace")
            with observe.bind_trace(slice_trace):
                try:
                    manifest = process_slice(cfg, rundir, sl, worker=wid)
                finally:
                    stop.set()
                    renewer.join(timeout=5.0)
                _failpoints.fire("elastic_publish",
                                 slice=manifest["slice"], worker=wid)
                resp = transport.request(
                    address,
                    {"op": "publish", "worker": wid, "lease_id": lease_id,
                     "slice": sl["sid"], "manifest": manifest},
                    timeout=600.0,
                )
                if resp.get("ok"):
                    processed += 1
                    continue
                if resp.get("reason") == "lease_expired":
                    # our lease lapsed mid-slice and the slice was
                    # requeued; the durable checkpoints keep the work —
                    # go get a new lease (possibly for this same slice)
                    observe.emit(
                        "elastic_publish_refused",
                        {"slice": manifest["slice"], "worker": wid,
                         "reason": "lease_expired"},
                    )
                    continue
                raise ElasticError(f"publish refused: {resp}")
    finally:
        hb.stop()
        observe.flush_sinks()
