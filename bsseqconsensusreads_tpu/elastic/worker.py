"""graftswarm worker: the thin process side of `cli elastic`.

A worker is deliberately boring — it joins a coordinator, then loops
lease → run the EXISTING pipeline/stages.py chain over the leased
slice → publish a manifest. All elastic intelligence (splitting,
requeue, merge, reconciliation) lives coordinator-side; this module
adds nothing to the science path, which is the whole byte-identity
argument: the records a slice emits are the records the single-process
run emits for those families, produced by the same code.

Per slice the worker runs `run_pipeline` in a SLICE-KEYED work dir
(`<rundir>/slices/s<NNNN>/`). Keying by slice rather than worker is
the loss-recovery mechanism: when a lease lapses and the slice is
requeued, the next holder resumes from the same dir, where
BatchCheckpoint keeps the longest verified CRC shard prefix and
recomputes only the remainder — the dead worker's finished batches are
never redone and never double-emitted.

The published manifest carries the slice's family fingerprint, output
CRC, per-stage StageStats, and the per-bucket record counts of its
coordinate-bucketed output (BucketPlan over the final header), which
the coordinator's merge reconciles against the merged stream before
the run may call itself ok.
"""

from __future__ import annotations

import base64
import dataclasses
import os
import random
import shutil
import tempfile
import threading
import time
import zlib

from bsseqconsensusreads_tpu.config import FrameworkConfig
from bsseqconsensusreads_tpu.faults import failpoints as _failpoints
from bsseqconsensusreads_tpu.faults import integrity as _integrity
from bsseqconsensusreads_tpu.io.bam import BamReader
from bsseqconsensusreads_tpu.parallel.multihost import WorkerHeartbeat
from bsseqconsensusreads_tpu.pipeline import checkpoint as _checkpoint
from bsseqconsensusreads_tpu.pipeline.bucketemit import (
    BucketPlan,
    blob_bucket_key,
    resolve_buckets,
)
from bsseqconsensusreads_tpu.serve import transport
from bsseqconsensusreads_tpu.utils import observe

from bsseqconsensusreads_tpu.elastic import fencing as _fencing
from bsseqconsensusreads_tpu.elastic import preempt as _preempt
from bsseqconsensusreads_tpu.elastic.coordinator import (
    ENV_COORDINATOR_ADDR,
    ENV_SPAWNED_AT,
    ENV_WORKER_ID,
    ElasticError,
    chunk_bytes,
    config_from_doc,
    slice_name,
)

#: bounded per-chunk retries for the ship-mode transfers (each chunk is
#: one request on its own connection — a dropped connection costs one
#: chunk, not the stream)
CHUNK_RETRIES = 5

# where ship mode lands the fetched slice input inside the private work
# dir; _reset_stale_finals must never mistake it for a stage final
SHIP_INPUT = "input.bam"


def slice_config(cfg: FrameworkConfig) -> FrameworkConfig:
    """The per-slice pipeline config. Grouping is forced off (slices
    are cut FROM grouped input; regrouping a shard could renumber
    families), interstage streaming off (checkpointing requires the
    materialized interstage, stages._interstage_blocked), and
    checkpoints on — they are what makes requeue cheap."""
    return dataclasses.replace(
        cfg,
        group_umis="never",
        stream_interstage=False,
        checkpoint_every=cfg.checkpoint_every if cfg.checkpoint_every >= 1
        else 4,
    )


def _bucket_manifest(path: str, buckets: int) -> tuple[list[int], int]:
    """Per-bucket record counts of one coordinate-sorted slice output
    (the PR 12 bucket geometry over the output's own header). The merge
    recomputes the same vector over the merged stream; equality means
    no record moved buckets and none vanished."""
    with BamReader(path, threads=1) as reader:
        plan = BucketPlan.from_header(reader.header, buckets)
        counts = [0] * plan.nbuckets
        total = 0
        for blob in reader.raw_records():
            counts[plan.bucket_of(blob_bucket_key(blob))] += 1
            total += 1
    return counts, total


def _reset_stale_finals(sdir: str, sname: str, worker: str) -> None:
    """A leased slice has NO committed manifest (the coordinator only
    leases unverified slices), so a durable stage FINAL in its work dir
    is the orphan of a holder that died between a stage finalize and
    the manifest commit. Stage stats are not durable: resuming past
    such a final would skip the stage whole (mtime rerun semantics) and
    the published manifest could never reconcile its ingest counters
    against the split. Finals appear atomically (tmp+rename at
    ckpt_finalize), so their presence is exact — clear the work dir and
    recompute the slice. Mid-stage deaths leave .ckpt/.part shards,
    never a final, so the cheap batches_kept resume path is untouched."""
    stale = sorted(
        f for f in os.listdir(sdir)
        if f.endswith(".bam") and ".ckpt" not in f and ".part" not in f
        and f != SHIP_INPUT
    )
    if not stale:
        return
    for f in os.listdir(sdir):
        if f == SHIP_INPUT:
            # ship-mode fetched input: raw bytes, never a stage final
            continue
        path = os.path.join(sdir, f)
        if os.path.isdir(path):
            shutil.rmtree(path)
        else:
            os.unlink(path)
    observe.emit(
        "elastic_slice_reset",
        {"slice": sname, "worker": worker, "stale": stale},
    )


def process_slice(cfg: FrameworkConfig, rundir: str, sl: dict,
                  worker: str = "", workdir: str | None = None,
                  input_path: str | None = None) -> dict:
    """Run the standard pipeline chain over one leased slice; returns
    the publishable manifest. Work dir is keyed by SLICE id so a
    requeued slice resumes its own checkpoints — unless ship mode hands
    in a private `workdir` (and a locally fetched `input_path`), in
    which case nothing here touches the shared rundir at all."""
    sname = slice_name(sl["sid"])
    _failpoints.fire("elastic_slice", slice=sname, worker=worker)
    sdir = workdir or os.path.join(rundir, "slices", sname)
    os.makedirs(sdir, exist_ok=True)
    _reset_stale_finals(sdir, sname, worker)
    scfg = dataclasses.replace(slice_config(cfg), tmp=sdir)
    slice_bam = input_path or os.path.join(rundir, sl["path"])
    _integrity.verify_file_crc32(
        slice_bam, sl["input_crc"], what=f"slice input {sname}"
    )
    # deferred: run_pipeline pulls the jax stack in; workers that only
    # join/poll must stay cheap to import
    import_t0 = time.time()
    from bsseqconsensusreads_tpu.pipeline.stages import run_pipeline

    if observe.stats_sink() is not None:
        # jax_import overhead bucket: near-zero after the first slice
        # (sys.modules cache), so summing dur_s still reads as the
        # one-time per-process import cost
        observe.emit_span(
            "jax_import", import_t0, time.time(),
            ctx=observe.proc_trace(), worker=worker,
        )
    t0 = time.monotonic()
    with observe.span("slice_pipeline", slice=sname, worker=worker):
        target, _results, stats = run_pipeline(scfg, slice_bam, outdir=sdir)
    wall_s = time.monotonic() - t0
    buckets, records_out = _bucket_manifest(
        target, resolve_buckets(cfg.sort_buckets)
    )
    manifest = {
        "slice": sname,
        "worker": worker,
        "output": os.path.basename(target),
        "crc": _integrity.file_crc32(target),
        "family_crc": sl["family_crc"],
        "records_in": sl["records"],
        "records_out": records_out,
        "buckets": buckets,
        "wall_s": round(wall_s, 3),
        "stats": {stage: s.as_dict() for stage, s in stats.items()},
    }
    observe.emit(
        "elastic_slice_processed",
        {"slice": sname, "worker": worker, "records_out": records_out,
         "wall_s": manifest["wall_s"]},
    )
    return manifest


def _renew_lease(address: str, worker: str, lease_id: str, lease_s: float,
                 stop: threading.Event, hb: WorkerHeartbeat) -> None:
    """Renewal pump for one held lease, with deadline accounting that
    closes the delayed-heartbeat race: renewal extends the LOCAL
    deadline from the instant the frame was SENT, never from the reply
    — wire delay counts against this worker, so a heartbeat that lands
    coordinator-side after expiry can never leave the worker believing
    it still holds the lease. The cadence is a jittered third of the
    lease (±20%, seeded by the lease id) so a fleet's renewals never
    synchronize, and each request times out well inside the remaining
    lease instead of blocking past it.

    Losing the lease — a `lease_expired` renewal reply, or the local
    deadline passing with no successful renewal (partition) — revokes
    the adopted fence epoch: the compute thread aborts at its next
    durable write (FencedError) instead of racing the requeued holder."""
    rng = random.Random(lease_id)
    deadline = time.monotonic() + lease_s
    while True:
        interval = max(0.05, lease_s / 3.0 * (0.8 + 0.4 * rng.random()))
        if stop.wait(interval):
            return
        hb.beat(phase="lease_renew", lease_id=lease_id)
        t_send = time.monotonic()
        if t_send >= deadline:
            # nothing renewed inside the whole lease window: presume
            # requeued — self-fence without waiting to hear it refused
            _fencing.revoke(f"lease {lease_id} deadline passed unrenewed",
                            lease_id=lease_id)
            return
        try:
            resp = transport.request(
                address,
                {"op": "heartbeat", "worker": worker, "lease_id": lease_id},
                timeout=max(1.0, min(lease_s / 2.0, deadline - t_send)),
            )
        # graftlint: disable=unbounded-retry -- bounded by the local lease
        # deadline: the next tick self-fences and returns once it passes
        except (OSError, transport.TransportError):
            continue  # transient: retry, but the local deadline still runs
        if resp.get("ok"):
            deadline = t_send + lease_s
            continue
        # the coordinator says the lease is gone: immediate local abort
        _fencing.revoke(f"lease {lease_id} expired at the coordinator",
                        lease_id=lease_id)
        return


# ------------------------------------------------- shared-nothing shipping


def _fetch_slice(address: str, sl: dict, dest: str, worker: str = "") -> str:
    """Pull one slice input BAM over the wire as CRC-verified bounded
    chunks (`slice_fetch`). The op is stateless coordinator-side, so
    resume after any failure is simply re-asking for the same offset —
    each retry ledgers `slice_chunk_resent`. The assembled file lands
    via tmp+rename and process_slice re-verifies the whole-file CRC
    against the split manifest, so a torn fetch can never be computed."""
    sname = slice_name(sl["sid"])
    tmp = dest + ".part"
    offset = 0
    with open(tmp, "wb") as out:
        while True:
            attempt = 0
            while True:
                data = None
                try:
                    # graftlint: disable=unleased-work-dispatch,untraced-transport-send -- read-only
                    # chunk pull under the CALLER's lease (work_loop holds
                    # lease_id + the renewal pump) and the caller's bound
                    # slice trace (request ships `_trace` from the ambient
                    # context); nothing here dispatches work
                    resp = transport.request(
                        address,
                        {"op": "slice_fetch", "slice": sl["sid"],
                         "offset": offset, "worker": worker},
                        timeout=120.0,
                    )
                # graftlint: disable=unbounded-retry -- bounded: attempt
                # caps at CHUNK_RETRIES (raise) with linear backoff
                except (OSError, transport.TransportError):
                    resp = None
                if resp is not None and resp.get("ok"):
                    got = base64.b64decode(str(resp.get("data") or ""))
                    if (zlib.crc32(got) & 0xFFFFFFFF) == int(
                            resp.get("crc", -1)):
                        data = got
                if data is not None:
                    break
                attempt += 1
                if attempt >= CHUNK_RETRIES:
                    raise ElasticError(
                        f"slice_fetch for {sname} failed at offset {offset} "
                        f"after {CHUNK_RETRIES} attempts"
                    )
                observe.emit(
                    "slice_chunk_resent",
                    {"slice": sname, "offset": offset, "attempt": attempt},
                )
                time.sleep(0.05 * attempt)
            out.write(data)
            offset += len(data)
            if resp.get("eof"):
                break
    os.replace(tmp, dest)
    return dest


def _push_output(address: str, sid: int, lease_id: str, epoch,
                 target: str, worker: str = "") -> None:
    """Ship one slice output back as a strictly sequential chunk stream
    (`slice_push`). The coordinator answers its authoritative received
    byte count on any offset mismatch (resync), which makes retried and
    duplicated chunks idempotent at chunk granularity; a `fenced` reply
    means a newer holder owns the slice — raise FencedError so the loop
    aborts locally instead of racing it."""
    sname = slice_name(sid)
    name = os.path.basename(target)
    size = os.path.getsize(target)
    chunk = chunk_bytes()
    offset = 0
    with open(target, "rb") as fh:
        while True:
            fh.seek(offset)
            data = fh.read(chunk)
            eof = offset + len(data) >= size
            payload = {
                "op": "slice_push", "slice": sid, "lease_id": lease_id,
                "epoch": epoch, "name": name, "offset": offset,
                "data": base64.b64encode(data).decode("ascii"),
                "crc": zlib.crc32(data) & 0xFFFFFFFF, "eof": eof,
                "worker": worker,
            }
            attempt = 0
            while True:
                try:
                    resp = transport.request(address, payload, timeout=120.0)
                # graftlint: disable=unbounded-retry -- bounded: attempt
                # caps at CHUNK_RETRIES (raise) with linear backoff
                except (OSError, transport.TransportError):
                    resp = None
                if resp is not None and resp.get("reason") != "chunk_integrity":
                    break
                attempt += 1
                if attempt >= CHUNK_RETRIES:
                    raise ElasticError(
                        f"slice_push for {sname} failed at offset {offset} "
                        f"after {CHUNK_RETRIES} attempts"
                    )
                observe.emit(
                    "slice_chunk_resent",
                    {"slice": sname, "offset": offset, "attempt": attempt},
                )
                time.sleep(0.05 * attempt)
            if resp.get("ok"):
                if resp.get("resync"):
                    # the coordinator already holds bytes we don't think
                    # we sent (reply lost in flight): trust its count
                    offset = int(resp.get("received", 0))
                    continue
                if eof:
                    return
                offset += len(data)
                continue
            if resp.get("reason") == "fenced":
                raise _fencing.FencedError(
                    f"slice_push for {sname} refused: epoch {epoch} is "
                    f"stale (current {resp.get('epoch')})",
                    epoch=epoch if epoch is None else int(epoch),
                )
            raise ElasticError(f"slice_push refused: {resp}")


def _handoff(address: str, *, wid: str, sl: dict, lease_id: str, epoch,
             batches_kept: int, rundir: str, ship: bool,
             flag: "_preempt.PreemptFlag") -> None:
    """Voluntary drain-and-handoff after a PreemptedError: persist the
    handoff manifest (shared-rundir mode; ship successors refetch and
    resume nothing local), then release the lease with a `preempt` op
    so the coordinator requeues IMMEDIATELY instead of waiting out
    `lease_s`. Every step is best-effort under the grace budget — a
    lapse degrades to the crash path (lease expiry), never a hang."""
    sname = slice_name(sl["sid"])
    if not ship:
        _preempt.write_handoff(
            os.path.join(rundir, "slices", sname),
            slice_name=sname, worker=wid, batches_kept=batches_kept,
        )
    budget = flag.deadline() - time.monotonic()
    try:
        # bind the slice's trace so the preempt frame ships `_trace`
        # and the coordinator's requeue joins this attempt's causal tree
        slice_trace = sl.get("trace")
        with observe.bind_trace(slice_trace):
            resp = transport.request(
                address,
                {"op": "preempt", "worker": wid, "lease_id": lease_id,
                 "slice": sl["sid"], "epoch": epoch,
                 "batches_kept": batches_kept},
                timeout=max(1.0, min(30.0, budget)),
            )
    except (OSError, transport.TransportError):
        # the wire is gone too: exit anyway — the durable prefix is on
        # disk and lease expiry requeues the slice coordinator-side
        resp = {"ok": False, "reason": "unreachable"}
    latency = time.monotonic() - flag.requested_at()
    if resp.get("ok"):
        _preempt.emit_handoff_published(
            slice_name=sname, worker=wid, batches_kept=batches_kept,
            handoff_latency_s=latency,
        )
    else:
        observe.emit(
            "elastic_publish_refused",
            {"slice": sname, "worker": wid, "reason": "preempt_" + str(
                resp.get("reason") or "refused")},
        )


def work_loop(address: str, worker_id: str | None = None,
              poll_s: float = 0.2) -> int:
    """Join a coordinator and process leased slices until it says done.
    Returns the number of slices this process published.

    graftpreempt: SIGTERM latches a preemption instead of killing the
    process. Mid-slice, the checkpoint batch gate aborts at the next
    batch boundary (the interrupting batch flushed durable first) and
    the worker hands the slice back via the `preempt` op; idle or
    between slices, the worker simply stops leasing and exits 0."""
    _preempt.install_signal_handler()
    _checkpoint.install_batch_gate(_preempt.batch_gate())
    wid = worker_id or os.environ.get(ENV_WORKER_ID) or f"pid{os.getpid()}"
    os.environ[ENV_WORKER_ID] = wid
    os.environ[ENV_COORDINATOR_ADDR] = address
    joined = transport.request(
        address, {"op": "elastic_join", "worker": wid}, timeout=60.0
    )
    if not joined.get("ok"):
        raise ElasticError(f"join refused by {address}: {joined}")
    cfg = config_from_doc(joined["cfg"])
    rundir = joined["rundir"]
    lease_default = float(joined.get("lease_s") or 30.0)
    spawned_env = os.environ.pop(ENV_SPAWNED_AT, None)
    if spawned_env is not None and observe.stats_sink() is not None:
        # the supervisor stamped wall-clock spawn time into our env;
        # spawn → successful join is this process's worker_spawn bucket
        try:
            observe.emit_span(
                "worker_spawn", float(spawned_env), time.time(),
                ctx=observe.proc_trace(), worker=wid,
            )
        except ValueError:
            pass  # unparseable stamp: skip the span, never the worker
    ship = bool(joined.get("ship"))
    private_root: str | None = None
    if ship:
        # shared-nothing: every byte of slice input/output crosses the
        # wire; this tmpdir is the worker's ONLY filesystem footprint
        private_root = tempfile.mkdtemp(prefix=f"bsseq-ship-{wid}-")
    hb = WorkerHeartbeat(component="elastic")
    hb.start()
    processed = 0
    wait_t0: float | None = None
    try:
        while True:
            if _preempt.FLAG.pending():
                # preempted while holding nothing: no handoff to
                # publish, just stop leasing and exit clean
                return processed
            hb.beat(phase="lease_poll")
            grant = transport.request(
                address, {"op": "lease", "worker": wid}, timeout=60.0
            )
            if grant.get("done"):
                return processed
            if grant.get("wait") or "slice" not in grant:
                if wait_t0 is None:
                    wait_t0 = time.time()
                time.sleep(poll_s)
                continue
            if wait_t0 is not None:
                if observe.stats_sink() is not None:
                    # lease_wait overhead bucket: idle span between the
                    # last grant and this one (backlog starvation)
                    observe.emit_span(
                        "lease_wait", wait_t0, time.time(),
                        ctx=observe.proc_trace(), worker=wid,
                    )
                wait_t0 = None
            sl = grant["slice"]
            lease_id = grant["lease_id"]
            epoch = grant.get("fence_epoch")
            lease_s = float(grant.get("lease_s") or lease_default)
            sname = slice_name(sl["sid"])
            # adopt the grant's fence BEFORE any work: from here every
            # durable write goes through the fence gate, and losing the
            # lease turns into a local FencedError instead of a race
            _fencing.adopt(epoch, lease_id)
            stop = threading.Event()
            # graftlint: owned-thread -- lease-renewal pump for the
            # slice this loop iteration is processing; joined below
            renewer = threading.Thread(
                target=_renew_lease,
                args=(address, wid, lease_id, lease_s, stop, hb),
                name=f"lease-renew-{lease_id}", daemon=True,
            )
            renewer.start()
            # the slice's trace ctx rode in on the grant; binding it here
            # puts process_slice's spans and the publish request (via the
            # wire's `_trace`) on the slice's causal tree
            slice_trace = sl.get("trace")
            with observe.bind_trace(slice_trace):
                try:
                    try:
                        if ship:
                            workdir = os.path.join(private_root, sname)
                            os.makedirs(workdir, exist_ok=True)
                            local_bam = _fetch_slice(
                                address, sl,
                                os.path.join(workdir, SHIP_INPUT),
                                worker=wid,
                            )
                            manifest = process_slice(
                                cfg, rundir, sl, worker=wid,
                                workdir=workdir, input_path=local_bam,
                            )
                        else:
                            manifest = process_slice(
                                cfg, rundir, sl, worker=wid
                            )
                    finally:
                        stop.set()
                        renewer.join(timeout=5.0)
                    if ship:
                        _push_output(
                            address, sl["sid"], lease_id, epoch,
                            os.path.join(workdir, manifest["output"]),
                            worker=wid,
                        )
                    # last local gate before publish: the renewer may
                    # have revoked after the final durable write
                    _fencing.check("publish")
                    _failpoints.fire("elastic_publish",
                                     slice=sname, worker=wid)
                    resp = transport.request(
                        address,
                        {"op": "publish", "worker": wid,
                         "lease_id": lease_id, "slice": sl["sid"],
                         "manifest": manifest, "epoch": epoch},
                        timeout=600.0,
                    )
                # graftlint: disable=unbounded-retry -- not a retry: the
                # slice is ABANDONED (the requeued holder owns it) and the
                # loop leases different work; the coordinator's `done`
                # reply is the bound
                except _fencing.FencedError as exc:
                    # the lease is gone (renewal refused, deadline lapsed
                    # behind a partition, or the coordinator fenced our
                    # push): abort the slice locally — the requeued
                    # holder owns it now — and lease fresh work
                    observe.emit(
                        "elastic_publish_refused",
                        {"slice": sname, "worker": wid,
                         "reason": "fence_revoked", "detail": str(exc)},
                    )
                    _fencing.release()
                    continue
                except _preempt.PreemptedError as exc:
                    # voluntary eviction: the batch gate stopped the
                    # slice at a durable batch boundary — hand the
                    # lease back explicitly and exit 0. Fencing keeps
                    # precedence: a revoked epoch raises FencedError
                    # from the handoff flush itself (caught above)
                    _handoff(
                        address, wid=wid, sl=sl, lease_id=lease_id,
                        epoch=epoch, batches_kept=exc.batches_kept,
                        rundir=rundir, ship=ship, flag=_preempt.FLAG,
                    )
                    _fencing.release()
                    return processed
                if resp.get("ok"):
                    _fencing.release()
                    processed += 1
                    continue
                if resp.get("reason") in ("lease_expired", "fenced"):
                    # our lease lapsed mid-slice and the slice was
                    # requeued; the durable checkpoints keep the work —
                    # go get a new lease (possibly for this same slice)
                    observe.emit(
                        "elastic_publish_refused",
                        {"slice": sname, "worker": wid,
                         "reason": str(resp.get("reason"))},
                    )
                    _fencing.release()
                    continue
                raise ElasticError(f"publish refused: {resp}")
    finally:
        _fencing.release()
        hb.stop()
        observe.flush_sinks()
        if private_root is not None:
            shutil.rmtree(private_root, ignore_errors=True)
