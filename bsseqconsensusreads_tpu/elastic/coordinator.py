"""graftswarm coordinator: the work-unit ledger behind `cli elastic run`.

An elastic run shards one grouped input across worker processes and
merges their outputs back into bytes identical to the single-process
pipeline. The coordinator owns three things:

* **the split** — `split_input` partitions the input into contiguous
  base-family (MI with the /A|/B strand suffix stripped) ordinal
  ranges, one slice BAM per range, same header bytes. Contiguity is
  what makes the merge exact: per-slice coordinate-sorted outputs
  merged in slice order reproduce the stable global sort the
  single-process run performs over the same emission stream. Each
  slice carries a family-hash fingerprint (CRC over its member base-MI
  ids) that every downstream commit must echo back.
* **the lease table** — slices are leased to workers over the PR 11
  framed transport (`tcp:` with optional TLS; hostile frames get the
  same typed `TransportError` refusal matrix every serve front has).
  Leases expire against worker heartbeats; an expired lease or a dead
  worker requeues the slice (`slice_requeued` / `worker_lost` ledger
  events). Requeue loses nothing recomputable: the slice's work dir is
  keyed by slice id, not worker id, so the next holder's
  BatchCheckpoint resume keeps the longest verified CRC shard prefix
  and recomputes only the remainder — exactly-once emit per family.
* **durable truth** — the filesystem, not this process. A slice is
  done iff its dir holds a committed `manifest.json` whose fingerprint
  matches and whose output CRC verifies. The in-memory lease table is
  volatile by design: a restarted coordinator rescans the slice dirs
  and re-enqueues only the incomplete slices (the
  `elastic_coordinator_restart` chaos scenario drills this window).

`run_elastic` is the one-command front: split, serve, supervise N
local workers (`BSSEQ_TPU_WORKER_ID=w<i>`, fleet-style respawn with a
one-shot first-life failpoint override for the chaos drill), then
finalize through elastic.merge and refuse to declare the run ok until
the counters reconcile.
"""

from __future__ import annotations

import base64
import bisect
import dataclasses
import json
import os
import signal
import subprocess
import sys
import threading
import time
import zlib
from collections import deque

from bsseqconsensusreads_tpu.config import FrameworkConfig
from bsseqconsensusreads_tpu.elastic import fencing as _fencing
from bsseqconsensusreads_tpu.elastic import preempt as _preempt
from bsseqconsensusreads_tpu.faults import failpoints as _failpoints
from bsseqconsensusreads_tpu.faults import integrity as _integrity
from bsseqconsensusreads_tpu.io.bam import BamReader, BamWriter
from bsseqconsensusreads_tpu.serve.server import ProtocolServer
from bsseqconsensusreads_tpu.utils import observe

ENV_WORKER_ID = "BSSEQ_TPU_WORKER_ID"
ENV_COORDINATOR_ADDR = "BSSEQ_TPU_COORDINATOR_ADDR"
ENV_LEASE_S = "BSSEQ_TPU_ELASTIC_LEASE_S"
#: wall-clock spawn instant, stamped by the supervisor into each worker's
#: environment so the worker can book its own spawn→join overhead span
ENV_SPAWNED_AT = "BSSEQ_TPU_SPAWNED_AT"
#: ship-mode wire chunk size (raw bytes per slice_fetch/slice_push
#: frame; the base64 envelope must stay under transport.MAX_FRAME)
ENV_CHUNK_B = "BSSEQ_TPU_ELASTIC_CHUNK_B"

#: Default lease duration. Workers renew at a third of this, so only a
#: hung or dead worker lets a lease lapse.
DEFAULT_LEASE_S = 30.0

DEFAULT_CHUNK_B = 1 << 20


def chunk_bytes(default: int = DEFAULT_CHUNK_B) -> int:
    """Raw bytes per slice-shipping chunk. Clamped so the base64
    envelope (4/3 inflation + JSON overhead) stays under MAX_FRAME;
    tests shrink it to force multi-chunk transfers on tiny slices."""
    try:
        n = int(os.environ.get(ENV_CHUNK_B, default))
    except ValueError:
        n = default
    return max(1, min(n, 4 * 1024 * 1024))

SLICES_DOC = "slices.json"
CFG_DOC = "cfg.json"
MANIFEST_NAME = "manifest.json"


class ElasticError(RuntimeError):
    """Unrunnable elastic configuration, exhausted workers, or a merge
    whose counters refuse to reconcile."""


def lease_seconds(default: float = DEFAULT_LEASE_S) -> float:
    try:
        return float(os.environ.get(ENV_LEASE_S, default))
    except ValueError:
        return default


def base_mi(mi: str) -> str:
    """Duplex family id: the MI with its /A | /B strand suffix stripped
    (the fgbio convention). Slicing on the BASE id keeps both strands
    of a duplex family in one slice, so per-slice duplex calling sees
    exactly the families the single-process run sees."""
    return mi.split("/", 1)[0]


def slice_name(sid: int) -> str:
    return f"s{sid:04d}"


def config_doc(cfg: FrameworkConfig) -> dict:
    """JSON-serializable form of a FrameworkConfig, shipped to workers
    at join time (and written to `<rundir>/cfg.json` for `--join`
    workers on another host reading the shared rundir)."""
    return dataclasses.asdict(cfg)


def config_from_doc(doc: dict) -> FrameworkConfig:
    from bsseqconsensusreads_tpu.models.params import ConsensusParams

    d = dict(doc)
    for key in ("molecular", "duplex"):
        if isinstance(d.get(key), dict):
            d[key] = ConsensusParams(**d[key])
    return FrameworkConfig(**d)


def _load_json(path: str) -> dict | None:
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def _save_json_atomic(path: str, doc: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def _input_fingerprint(path: str) -> dict:
    st = os.stat(path)
    return {
        "path": os.path.abspath(path),
        "size": st.st_size,
        "mtime": st.st_mtime,
    }


# --------------------------------------------------------------------- split


def split_input(bam_path: str, rundir: str, n_slices: int) -> list[dict]:
    """Partition a grouped BAM into contiguous base-family ordinal
    ranges, one slice BAM per range (same header bytes). Idempotent: a
    rerun over an unchanged input with intact slice files reuses them
    (the coordinator-restart resume path); anything stale is rebuilt.

    Returns the slice descriptors: sid, rundir-relative path, record /
    family counts, the family-hash fingerprint (CRC over member base-MI
    ids in ordinal order), and the slice file's own CRC.
    """
    slicedir = os.path.join(rundir, "slices")
    os.makedirs(slicedir, exist_ok=True)
    fp = _input_fingerprint(bam_path)
    doc_path = os.path.join(rundir, SLICES_DOC)
    doc = _load_json(doc_path)
    if (
        doc
        and doc.get("input_fingerprint") == fp
        and doc.get("n_slices_requested") == n_slices
    ):
        try:
            for sl in doc["slices"]:
                _integrity.verify_file_crc32(
                    os.path.join(rundir, sl["path"]), sl["input_crc"],
                    what=f"slice input {slice_name(sl['sid'])}",
                )
        except OSError:
            pass  # damaged or missing slice file: rebuild the split
        else:
            # resumed slices keep their original trace ids (their root
            # spans live in the same rundir ledger); docs from before
            # tracing get fresh ones so no slice ever runs untraced
            for sl in doc["slices"]:
                if not sl.get("trace"):
                    sl["trace"] = observe.mint_trace(
                        "slice", slice_name(sl["sid"])
                    )
            observe.emit(
                "elastic_split",
                {"slices": len(doc["slices"]), "families": doc["families"],
                 "records": doc["records"], "resumed": True},
            )
            return doc["slices"]

    # a rebuild over the same input reuses each slice's prior trace
    # context when the rebuilt slice is byte-identical — the rebuilt
    # file is the same unit of work, and its earlier root span already
    # lives in this rundir's ledger
    prior_traces: dict = {}
    if doc and doc.get("input_fingerprint") == fp:
        prior_traces = {
            (sl["path"], sl["family_crc"], sl["input_crc"]): sl["trace"]
            for sl in doc.get("slices", [])
            if sl.get("trace")
        }

    # pass 1: base-family ordinals in first-seen order (= the order the
    # single-process grouped stream meets them)
    ordinals: dict[str, int] = {}
    records = 0
    with BamReader(bam_path) as reader:
        header = reader.header
        for rec in reader:
            if not rec.has_tag("MI"):
                raise ElasticError(
                    "elastic runs shard by MI family and need grouped "
                    f"input (record {rec.qname!r} carries no MI tag) — "
                    "run the grouping pre-stage first (group_umis=always) "
                    "and hand the grouped BAM to `cli elastic run`"
                )
            fam = base_mi(str(rec.get_tag("MI")))
            if fam not in ordinals:
                ordinals[fam] = len(ordinals)
            records += 1
    families = len(ordinals)
    if not families:
        raise ElasticError(f"no records in {bam_path!r} — nothing to shard")
    n = max(1, min(n_slices, families))
    bounds = [families * i // n for i in range(n + 1)]

    # pass 2: write each record to the slice owning its family ordinal
    paths = [os.path.join(slicedir, f"{slice_name(s)}.bam") for s in range(n)]
    counts = [0] * n
    writers = [BamWriter(p + ".tmp", header, level=1) for p in paths]
    try:
        with BamReader(bam_path) as reader:
            for rec in reader:
                o = ordinals[base_mi(str(rec.get_tag("MI")))]
                s = bisect.bisect_right(bounds, o) - 1
                writers[s].write(rec)
                counts[s] += 1
    finally:
        for w in writers:
            w.close()
    for p in paths:
        os.replace(p + ".tmp", p)

    fam_ids = sorted(ordinals, key=ordinals.get)
    slices = []
    for s in range(n):
        members = fam_ids[bounds[s]:bounds[s + 1]]
        rel_path = os.path.join("slices", f"{slice_name(s)}.bam")
        family_crc = (
            zlib.crc32("\x00".join(members).encode()) & 0xFFFFFFFF
        )
        input_crc = _integrity.file_crc32(paths[s])
        slices.append({
            "sid": s,
            "path": rel_path,
            "records": counts[s],
            "families": len(members),
            "family_crc": family_crc,
            "input_crc": input_crc,
            # the split is the slice's admission: its trace context is
            # minted here, persisted in slices.json, and shipped inside
            # every lease grant — one causal tree per slice across
            # coordinator, every holder, and the merge; a byte-identical
            # rebuild keeps the prior context
            "trace": prior_traces.get((rel_path, family_crc, input_crc))
            or observe.mint_trace("slice", slice_name(s)),
        })
    _save_json_atomic(doc_path, {
        "input_fingerprint": fp,
        "n_slices_requested": n_slices,
        "records": records,
        "families": families,
        "slices": slices,
    })
    observe.emit(
        "elastic_split",
        {"slices": n, "families": families, "records": records,
         "resumed": False},
    )
    return slices


# -------------------------------------------------------------------- ledger


class SliceLedger:
    """Lease table over the durable slice state. Every mutation holds
    the one lock; durable commits (manifest writes) happen outside it.
    Restart-safe by construction: __init__ rescans the slice dirs and
    enqueues only slices without a verified committed manifest."""

    def __init__(self, rundir: str, slices: list[dict],
                 lease_s: float | None = None):
        self.rundir = rundir
        self.slices = {sl["sid"]: sl for sl in slices}
        self.lease_s = lease_s if lease_s is not None else lease_seconds()
        self._lock = threading.Lock()
        self._pending: deque[int] = deque()
        self._leases: dict[str, dict] = {}
        self._done: dict[int, dict] = {}
        self._seq = 0
        #: fence epochs: one minted (and persisted) per lease grant, so
        #: a slice's CURRENT holder always outranks every prior holder —
        #: and a restarted coordinator resumes above all of them
        self.book = _fencing.EpochBook(rundir)
        self._slice_epoch: dict[int, int] = {}
        self.requeues = 0
        self.workers_lost = 0
        self.preempts = 0
        self.workers: set[str] = set()
        for sl in slices:
            m = self._verified_manifest(sl)
            if m is not None:
                self._done[sl["sid"]] = m
            else:
                self._pending.append(sl["sid"])
        if self._done:
            observe.emit(
                "elastic_ledger_resumed",
                {"done": len(self._done), "pending": len(self._pending)},
            )

    def _slice_dir(self, sid: int) -> str:
        return os.path.join(self.rundir, "slices", slice_name(sid))

    def _manifest_path(self, sid: int) -> str:
        return os.path.join(self._slice_dir(sid), MANIFEST_NAME)

    def _verified_manifest(self, sl: dict) -> dict | None:
        """A committed manifest counts only if its family fingerprint
        matches this split AND its output bytes still verify."""
        m = _load_json(self._manifest_path(sl["sid"]))
        if not m or m.get("family_crc") != sl["family_crc"]:
            return None
        out = os.path.join(self._slice_dir(sl["sid"]), m.get("output", ""))
        try:
            _integrity.verify_file_crc32(
                out, int(m.get("crc", -1)),
                what=f"slice {slice_name(sl['sid'])} output",
            )
        except (OSError, ValueError):
            return None
        return m

    # -- worker-facing ops ----------------------------------------------

    def join(self, worker: str) -> None:
        with self._lock:
            fresh = worker not in self.workers
            self.workers.add(worker)
        if fresh:
            observe.emit("elastic_join", {"worker": worker})

    def lease(self, worker: str) -> dict:
        """Grant the next pending slice, or report wait/done. The grant
        carries the lease id + duration the holder must renew against —
        and echo back at publish."""
        with self._lock:
            if not self._pending:
                if not self._leases and len(self._done) == len(self.slices):
                    return {"done": True}
                return {"wait": True}
            sid = self._pending.popleft()
            self._seq += 1
            lease_id = f"{slice_name(sid)}.{self._seq}"
            # the fence epoch is durable BEFORE the grant leaves: a
            # restarted coordinator can never re-mint an epoch some
            # zombie already holds
            epoch = self.book.mint()
            self._slice_epoch[sid] = epoch
            self._leases[lease_id] = {
                "sid": sid,
                "worker": worker,
                "epoch": epoch,
                "expires": time.monotonic() + self.lease_s,
            }
            grant = {
                "slice": dict(self.slices[sid]),
                "lease_id": lease_id,
                "lease_s": self.lease_s,
                "fence_epoch": epoch,
            }
        # the slice's trace context ships inside the grant (the slice
        # dict carries it); the lease line itself is stamped so the
        # grant joins the slice's causal tree
        with observe.bind_trace(grant["slice"].get("trace")):
            observe.emit(
                "elastic_lease",
                {"slice": slice_name(sid), "worker": worker,
                 "lease_id": lease_id, "epoch": epoch},
            )
        return grant

    def slice_epoch(self, sid: int) -> int | None:
        """The epoch of the slice's CURRENT (latest) grant."""
        with self._lock:
            return self._slice_epoch.get(sid)

    def heartbeat(self, worker: str, lease_id: str) -> bool:
        with self._lock:
            lease = self._leases.get(lease_id)
            if lease is None or lease["worker"] != worker:
                return False
            lease["expires"] = time.monotonic() + self.lease_s
            return True

    def commit(self, lease_id: str, sid: int, manifest: dict,
               worker: str = "", epoch: int | None = None) -> dict:
        """Publish: validate the fence epoch, the lease, and the
        fingerprint, verify the output bytes, then commit the manifest
        atomically. A publish carrying an epoch below the slice's
        current grant is a ZOMBIE — refused with `publish_fenced` even
        when its bytes happen to match (precedence over the duplicate
        path: a superseded holder gets a typed refusal, not an "ok"
        that invites it to keep writing). A publish under a merely
        lapsed lease is refused (its slice was requeued; the durable
        checkpoint keeps the work) unless the requeued twin already
        committed identical output."""
        fenced_current: int | None = None
        with self._lock:
            current = self._slice_epoch.get(sid)
            if (epoch is not None and current is not None
                    and int(epoch) < current):
                fenced_current = current
            else:
                lease = self._leases.get(lease_id)
                if lease is None or lease["sid"] != sid:
                    done = self._done.get(sid)
                    if (done is not None
                            and done.get("crc") == manifest.get("crc")):
                        return {"ok": True, "duplicate": True}
                    return {"ok": False, "reason": "lease_expired"}
                sl = self.slices.get(sid)
        if fenced_current is not None:
            _fencing.emit_publish_fenced(
                slice_name(sid), worker, int(epoch), fenced_current,
                trace=(self.slices.get(sid) or {}).get("trace"),
            )
            return {"ok": False, "reason": "fenced", "epoch": fenced_current}
        if sl is None:
            return {"ok": False, "reason": "unknown_slice"}
        if manifest.get("family_crc") != sl["family_crc"]:
            return {"ok": False, "reason": "fingerprint_mismatch"}
        out = os.path.join(self._slice_dir(sid), str(manifest.get("output")))
        try:
            _integrity.verify_file_crc32(
                out, int(manifest.get("crc", -1)),
                what=f"slice {slice_name(sid)} output",
            )
        except (OSError, ValueError) as exc:
            return {"ok": False, "reason": f"output_integrity: {exc}"}
        _failpoints.fire("elastic_manifest_commit", slice=slice_name(sid))
        _save_json_atomic(self._manifest_path(sid), manifest)
        with self._lock:
            self._leases.pop(lease_id, None)
            self._done[sid] = manifest
        # the slice trace's terminal event: `observe check` requires
        # every slice tree to reach one of these
        with observe.bind_trace(sl.get("trace")):
            observe.emit(
                "elastic_slice_done",
                {"slice": slice_name(sid),
                 "worker": worker or str(manifest.get("worker", "")),
                 "records": manifest.get("records_out")},
            )
        return {"ok": True}

    # -- liveness --------------------------------------------------------

    def _requeue_locked(self, lease: dict, reason: str) -> None:
        sid = lease["sid"]
        self._pending.appendleft(sid)
        self.requeues += 1
        # the killed holder's trace continues, not dangles: this requeue
        # line carries the SAME slice trace, and the next holder's spans
        # join the same tree (chaos-drill trace-completeness gate)
        with observe.bind_trace((self.slices.get(sid) or {}).get("trace")):
            observe.emit(
                "slice_requeued",
                {"slice": slice_name(sid), "worker": lease["worker"],
                 "reason": reason, "batches_kept": self._batches_kept(sid)},
            )

    def _batches_kept(self, sid: int) -> int:
        """Batches the lost worker left durable in the slice's stage
        checkpoints — the prefix the next holder keeps (its resume
        re-verifies every shard CRC; a corrupt shard truncates the
        prefix further, pipeline.checkpoint._verify_shards)."""
        total = 0
        try:
            names = os.listdir(self._slice_dir(sid))
        except OSError:
            return 0
        for name in names:
            if name.endswith(".ckpt.json"):
                m = _load_json(os.path.join(self._slice_dir(sid), name))
                total += int((m or {}).get("batches_done") or 0)
        return total

    def expire_scan(self) -> int:
        """Requeue every lapsed lease; returns how many. A lapsed lease
        means the holder stopped renewing — hung or dead either way, it
        is presumed lost."""
        now = time.monotonic()
        with self._lock:
            expired = [
                (lid, lease) for lid, lease in self._leases.items()
                if lease["expires"] <= now
            ]
            for lid, lease in expired:
                self._leases.pop(lid)
                self.workers_lost += 1
                observe.emit(
                    "worker_lost",
                    {"worker": lease["worker"], "reason": "lease_expired",
                     "leases": 1},
                )
                self._requeue_locked(lease, "lease_expired")
        return len(expired)

    def preempt(self, worker: str, lease_id: str, sid: int,
                batches_kept: int = 0, epoch: int | None = None) -> dict:
        """Voluntary drain-and-handoff: the holder finished its
        in-flight batch, flushed the checkpoint prefix durable, and is
        handing the lease back BEFORE exiting — so the slice requeues
        immediately instead of waiting out `lease_s` expiry. The next
        grant mints a higher fence epoch, which revokes the departed
        holder exactly as a crash would; a handoff carrying a stale
        epoch is itself a zombie and is refused `fenced` with the same
        precedence the publish path enforces (fence before lease
        bookkeeping)."""
        fenced_current: int | None = None
        with self._lock:
            current = self._slice_epoch.get(sid)
            if (epoch is not None and current is not None
                    and int(epoch) < current):
                fenced_current = current
            else:
                lease = self._leases.get(lease_id)
                if (lease is None or lease["sid"] != sid
                        or lease["worker"] != worker):
                    # lapsed (or already requeued) before the handoff
                    # landed: nothing to release — the expiry path
                    # already did the work this op would have
                    return {"ok": False, "reason": "lease_expired"}
                self._leases.pop(lease_id)
                self.preempts += 1
                with observe.bind_trace(
                    (self.slices.get(sid) or {}).get("trace")
                ):
                    observe.emit(
                        "worker_preempted",
                        {"worker": worker, "reason": "handoff",
                         "slice": slice_name(sid),
                         "batches_kept": int(batches_kept)},
                    )
                self._requeue_locked(lease, "preempted")
        if fenced_current is not None:
            _fencing.emit_publish_fenced(
                slice_name(sid), worker, int(epoch), fenced_current,
                trace=(self.slices.get(sid) or {}).get("trace"),
            )
            return {"ok": False, "reason": "fenced", "epoch": fenced_current}
        return {"ok": True}

    def note_worker_dead(self, worker: str) -> None:
        """Supervisor fast path: a reaped worker process requeues its
        leases immediately instead of waiting out the lease clock."""
        with self._lock:
            held = [
                (lid, lease) for lid, lease in self._leases.items()
                if lease["worker"] == worker
            ]
            self.workers_lost += 1
            observe.emit(
                "worker_lost",
                {"worker": worker, "reason": "process_exit",
                 "leases": len(held)},
            )
            for lid, lease in held:
                self._leases.pop(lid)
                self._requeue_locked(lease, "worker_lost")

    # -- progress --------------------------------------------------------

    def all_done(self) -> bool:
        with self._lock:
            return len(self._done) == len(self.slices)

    def has_pending(self) -> bool:
        with self._lock:
            return bool(self._pending)

    def counts(self) -> dict:
        with self._lock:
            return {
                "slices": len(self.slices),
                "done": len(self._done),
                "pending": len(self._pending),
                "leased": len(self._leases),
                "requeues": self.requeues,
                "workers_lost": self.workers_lost,
                "preempts": self.preempts,
                "workers": len(self.workers),
            }

    def manifests(self) -> dict[int, dict]:
        with self._lock:
            return dict(self._done)


# -------------------------------------------------------------------- server


class Coordinator(ProtocolServer):
    """Framed-transport front of one SliceLedger: the elastic op table
    over the same accept/refuse machinery every serve front shares
    (typed TransportError refusals, TLS via the serve env vars)."""

    def __init__(self, ledger: SliceLedger, cfg_doc: dict, *,
                 addresses, ready_file: str | None = None,
                 ship: bool = False):
        super().__init__(addresses=addresses, ready_file=ready_file)
        self.ledger = ledger
        self.cfg_doc = cfg_doc
        #: shared-nothing mode: workers fetch slice input and push
        #: output over the wire instead of touching the rundir — the
        #: flag rides the elastic_join reply, so `--join` workers on
        #: another host need no local configuration
        self.ship = ship
        #: in-flight pushed-output streams: sid -> {epoch, name,
        #: received}; a higher-epoch holder restarts the stream, a
        #: mismatched offset answers a resync instead of corrupting it
        self._push: dict[int, dict] = {}
        self._push_lock = threading.Lock()
        self._monitor_stop = threading.Event()
        self._monitor_thread: threading.Thread | None = None

    def start_monitor(self, interval_s: float = 0.25) -> None:
        if self._monitor_thread is not None:
            return
        # graftlint: owned-thread -- lease-expiry pump: it only calls
        # the lock-guarded ledger API on a fixed cadence
        self._monitor_thread = threading.Thread(
            target=self._monitor, args=(interval_s,),
            name="elastic-lease-monitor", daemon=True,
        )
        self._monitor_thread.start()

    def _monitor(self, interval_s: float) -> None:
        while not self._monitor_stop.wait(interval_s):
            self.ledger.expire_scan()

    def _on_drain(self) -> None:
        self._monitor_stop.set()
        if self._monitor_thread is not None:
            self._monitor_thread.join(timeout=5.0)

    def _dispatch(self, req: dict) -> dict:
        op = req.get("op")
        if op == "ping":
            return {"ok": True, "pong": True}
        if op == "elastic_join":
            worker = str(req.get("worker") or "")
            self.ledger.join(worker)
            return {
                "ok": True,
                "rundir": self.ledger.rundir,
                "cfg": self.cfg_doc,
                "slices": len(self.ledger.slices),
                "lease_s": self.ledger.lease_s,
                "ship": self.ship,
            }
        if op == "lease":
            return {"ok": True, **self.ledger.lease(str(req.get("worker") or ""))}
        if op == "heartbeat":
            ok = self.ledger.heartbeat(
                str(req.get("worker") or ""), str(req.get("lease_id") or "")
            )
            if not ok:
                return {"ok": False, "reason": "lease_expired"}
            return {"ok": True, "lease_s": self.ledger.lease_s}
        if op == "publish":
            epoch = req.get("epoch")
            return self.ledger.commit(
                str(req.get("lease_id") or ""),
                int(req.get("slice", -1)),
                req.get("manifest") or {},
                worker=str(req.get("worker") or ""),
                epoch=int(epoch) if epoch is not None else None,
            )
        if op == "preempt":
            epoch = req.get("epoch")
            return self.ledger.preempt(
                str(req.get("worker") or ""),
                str(req.get("lease_id") or ""),
                int(req.get("slice", -1)),
                batches_kept=int(req.get("batches_kept") or 0),
                epoch=int(epoch) if epoch is not None else None,
            )
        if op == "slice_fetch":
            return self._slice_fetch(req)
        if op == "slice_push":
            return self._slice_push(req)
        if op == "status":
            return {"ok": True, **self.ledger.counts()}
        if op == "metrics":
            c = self.ledger.counts()
            return {"ok": True, "metrics": {
                "component": "coordinator",
                "slices": c["slices"],
                "slices_done": c["done"],
                "lease_backlog": c["pending"],
                "outstanding_leases": c["leased"],
                "workers": c["workers"],
                "counters": {
                    "requeues": c["requeues"],
                    "workers_lost": c["workers_lost"],
                    "preempts": c["preempts"],
                },
            }}
        return {"ok": False, "error": f"unknown op {op!r}"}

    # -- shared-nothing slice shipping -----------------------------------

    def _slice_fetch(self, req: dict) -> dict:
        """One bounded chunk of a slice input BAM, CRC'd per chunk. The
        op is stateless and read-only: resume after a dropped connection
        is the client re-asking for the same offset. Replies opt out of
        the rid reply cache (`_nocache`) — re-fetching is safe and the
        cache must stay small."""
        sid = int(req.get("slice", -1))
        sl = self.ledger.slices.get(sid)
        if sl is None:
            return {"ok": False, "error": f"unknown slice {sid}"}
        offset = max(0, int(req.get("offset", 0)))
        path = os.path.join(self.ledger.rundir, sl["path"])
        try:
            size = os.path.getsize(path)
            with open(path, "rb") as fh:
                fh.seek(offset)
                data = fh.read(chunk_bytes())
        except OSError as exc:
            return {"ok": False, "error": f"slice_fetch: {exc}"}
        return {
            "ok": True,
            "offset": offset,
            "size": size,
            "eof": offset + len(data) >= size,
            "crc": zlib.crc32(data) & 0xFFFFFFFF,
            "data": base64.b64encode(data).decode("ascii"),
            "_nocache": True,
        }

    def _slice_push(self, req: dict) -> dict:
        """One bounded chunk of a slice OUTPUT, shipped back by the
        holder. Fenced like publish: a chunk carrying a stale epoch is
        refused (`publish_fenced`) so a zombie can never race the
        requeued holder's stream. The stream is strictly sequential —
        a chunk at the wrong offset answers the authoritative
        `received` byte count (resync) instead of writing, which makes
        duplicate and retried chunks idempotent at chunk granularity."""
        sid = int(req.get("slice", -1))
        worker = str(req.get("worker") or "")
        sl = self.ledger.slices.get(sid)
        if sl is None:
            return {"ok": False, "error": f"unknown slice {sid}"}
        epoch = req.get("epoch")
        current = self.ledger.slice_epoch(sid)
        if epoch is not None and current is not None and int(epoch) < current:
            _fencing.emit_publish_fenced(
                slice_name(sid), worker, int(epoch), current,
                trace=sl.get("trace"),
            )
            return {"ok": False, "reason": "fenced", "epoch": current}
        name = os.path.basename(str(req.get("name") or ""))
        if not name:
            return {"ok": False, "error": "slice_push without a name"}
        try:
            data = base64.b64decode(str(req.get("data") or ""))
        except ValueError:
            return {"ok": False, "reason": "chunk_integrity"}
        if (zlib.crc32(data) & 0xFFFFFFFF) != int(req.get("crc", -1)):
            return {"ok": False, "reason": "chunk_integrity"}
        offset = int(req.get("offset", 0))
        sdir = self.ledger._slice_dir(sid)
        os.makedirs(sdir, exist_ok=True)
        part = os.path.join(sdir, f".push.{name}")
        with self._push_lock:
            st = self._push.get(sid)
            if st is None or st.get("epoch") != epoch or st.get("name") != name:
                # a new holder (or a new attempt) restarts the stream
                st = {"epoch": epoch, "name": name, "received": 0}
                self._push[sid] = st
                with open(part, "wb"):
                    pass
            if offset != st["received"]:
                return {"ok": True, "received": st["received"],
                        "resync": True}
            with open(part, "ab") as fh:
                fh.write(data)
            st["received"] += len(data)
            received = st["received"]
            if req.get("eof"):
                os.replace(part, os.path.join(sdir, name))
                self._push.pop(sid, None)
        return {"ok": True, "received": received}


# ----------------------------------------------------------------- run front


def _check_runnable(cfg: FrameworkConfig) -> None:
    """Loud scope refusals: elastic covers the self-mode molecular →
    duplex chain; anything narrower must say so instead of producing
    output that silently differs from the single-process run."""
    problems = []
    if cfg.aligner != "self":
        problems.append(
            f"aligner={cfg.aligner!r} (elastic runs the self-mode "
            "molecular->duplex chain only)"
        )
    if getattr(cfg, "filter", None):
        problems.append("the filter stage is single-process only")
    if getattr(cfg, "single_strand", False):
        problems.append("single_strand consensus is single-process only")
    if getattr(cfg, "methyl", "off") != "off":
        problems.append(
            "methyl tallies are per-process accumulators with no "
            "cross-worker merge yet (methyl=off to run elastic)"
        )
    if problems:
        raise ElasticError("elastic run refused: " + "; ".join(problems))


def _run_inline(cfg: FrameworkConfig, ledger: SliceLedger) -> None:
    """Sequential in-process execution of every pending slice — the
    tier-1 test mode. Byte-identity is concurrency-independent (the
    merge consumes committed slice outputs in slice order), so inline
    runs pin exactly the bytes the subprocess fleet produces."""
    from bsseqconsensusreads_tpu.elastic import worker as _worker

    wid = os.environ.get(ENV_WORKER_ID) or "inline"
    while True:
        grant = ledger.lease(wid)
        if grant.get("done"):
            return
        if grant.get("wait"):
            ledger.expire_scan()
            time.sleep(0.01)
            continue
        # same trace discipline as the subprocess worker: the slice's
        # spans land on its causal tree even in inline mode
        slice_trace = grant["slice"].get("trace")
        with observe.bind_trace(slice_trace):
            manifest = _worker.process_slice(
                cfg, ledger.rundir, grant["slice"], worker=wid
            )
        resp = ledger.commit(
            grant["lease_id"], grant["slice"]["sid"], manifest, worker=wid,
            epoch=grant.get("fence_epoch"),
        )
        if not resp.get("ok"):
            # lapsed lease: the slice went back to pending and the next
            # loop pass resumes it from its checkpoint
            if resp.get("reason") == "lease_expired":
                continue
            raise ElasticError(f"inline commit refused: {resp}")


def _run_fleet(
    ledger: SliceLedger,
    cfg_doc_: dict,
    *,
    workers: int,
    address: str,
    worker_failpoints: dict,
    max_restarts: int,
    timeout_s: float,
    ship: bool = False,
) -> None:
    """Coordinator in-process + N worker subprocesses (the fleet spawn
    idiom: identity env var, one-shot first-life failpoint override,
    respawn budget).

    The supervisor is itself preemptible: SIGTERM/SIGINT latch an
    interrupt; the loop then SIGTERMs every worker (each does its own
    voluntary drain-and-handoff), reaps the children inside the grace
    budget (kill on lapse — no orphans either way), stops respawning,
    and raises with the ledger counts. The ledger is durable truth, so
    the interrupted run is resumable: rerunning against the same outdir
    rescans committed manifests and requeues only unfinished slices."""
    server = Coordinator(ledger, cfg_doc_, addresses=[address], ship=ship)
    server.start_monitor()
    # graftlint: owned-thread -- the accept loop owns the socket; this
    # thread exists so the supervisor below can poll worker processes
    thread = threading.Thread(
        target=server.serve_forever, name="elastic-coordinator", daemon=True
    )
    thread.start()
    deadline = time.monotonic() + timeout_s
    interrupted = threading.Event()
    prev_handlers: dict[int, object] = {}

    def _on_signal(signum, frame):  # pragma: no cover - signal context
        interrupted.set()

    for _sig in (signal.SIGTERM, signal.SIGINT):
        try:
            prev_handlers[_sig] = signal.signal(_sig, _on_signal)
        except ValueError:
            # not the main thread (library/test embedding): the caller
            # owns signal routing; drain still works via the ledger API
            break
    try:
        while not server.bound:
            if time.monotonic() > deadline:
                raise ElasticError("coordinator failed to bind in time")
            time.sleep(0.02)
        addr = server.bound[0]
        fail_once = dict(worker_failpoints)
        procs: dict[str, subprocess.Popen | None] = {}
        restarts: dict[str, int] = {}

        def spawn(wid: str) -> None:
            env = dict(os.environ)
            env[ENV_WORKER_ID] = wid
            env[ENV_COORDINATOR_ADDR] = addr
            # the worker books its own spawn→join 'worker_spawn' span
            # against this instant (same-host wall clock)
            env[ENV_SPAWNED_AT] = repr(time.time())
            # failpoints arm per worker FIRST LIFE only (the chaos
            # drill's kill must not be inherited by the respawn — or by
            # every worker when the parent itself is under failpoints)
            schedule = fail_once.pop(wid, None)
            if schedule:
                env["BSSEQ_TPU_FAILPOINTS"] = schedule
            else:
                env.pop("BSSEQ_TPU_FAILPOINTS", None)
            proc = subprocess.Popen(
                [sys.executable, "-m", "bsseqconsensusreads_tpu.cli",
                 "elastic", "worker", "--join", addr],
                env=env,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            )
            procs[wid] = proc
            observe.emit(
                "elastic_worker_spawn",
                {"worker": wid, "pid": proc.pid,
                 "generation": restarts.get(wid, 0)},
            )

        for i in range(workers):
            wid = f"w{i}"
            restarts[wid] = 0
            spawn(wid)

        def drain_children() -> None:
            """SIGTERM every live worker (voluntary handoff), then reap
            inside the grace budget — kill on lapse. No orphans."""
            for proc in procs.values():
                if proc is not None and proc.poll() is None:
                    proc.terminate()
            reap_by = time.monotonic() + _preempt.grace_s()
            for wid, proc in list(procs.items()):
                if proc is None:
                    continue
                try:
                    proc.wait(timeout=max(0.5, reap_by - time.monotonic()))
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=10.0)
                procs[wid] = None

        while not ledger.all_done():
            if interrupted.is_set():
                drain_children()
                raise ElasticError(
                    "elastic run interrupted: workers drained and "
                    f"reaped, ledger resumable at {ledger.rundir} — "
                    f"{ledger.counts()}"
                )
            if time.monotonic() > deadline:
                raise ElasticError(
                    f"elastic run timed out ({timeout_s:.0f}s) with "
                    f"{ledger.counts()}"
                )
            for wid, proc in list(procs.items()):
                if proc is None or proc.poll() is None:
                    continue
                rc = proc.returncode
                procs[wid] = None
                if rc != 0:
                    ledger.note_worker_dead(wid)
                if ledger.all_done():
                    continue
                if restarts[wid] < max_restarts and not interrupted.is_set():
                    restarts[wid] += 1
                    spawn(wid)
            if all(p is None for p in procs.values()) and not ledger.all_done():
                raise ElasticError(
                    "all workers exited with work pending "
                    f"(restart budget {max_restarts} exhausted): "
                    f"{ledger.counts()}"
                )
            time.sleep(0.05)

        # every slice durable: live workers see done=True and exit 0
        for proc in procs.values():
            if proc is None:
                continue
            try:
                proc.wait(timeout=60.0)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10.0)
    finally:
        for _sig, prev in prev_handlers.items():
            try:
                signal.signal(_sig, prev)
            except (ValueError, TypeError):
                pass
        server.request_drain()
        thread.join(timeout=10.0)


def run_elastic(
    cfg: FrameworkConfig,
    bam_path: str,
    outdir: str = "output",
    *,
    workers: int = 2,
    slices: int = 0,
    address: str = "tcp:127.0.0.1:0",
    inline: bool = False,
    worker_failpoints: dict | None = None,
    max_restarts: int = 2,
    lease_s: float | None = None,
    timeout_s: float = 3600.0,
    ship: bool = False,
) -> tuple[str, dict]:
    """One elastic run end to end: split → lease/execute → merge →
    reconcile. Returns (final target path, reconciliation report).
    Raises ElasticError when the counters refuse to reconcile — a
    faster wrong answer is not a result."""
    _check_runnable(cfg)
    os.makedirs(outdir, exist_ok=True)
    rundir = os.path.join(outdir, "elastic")
    os.makedirs(rundir, exist_ok=True)
    n_slices = slices if slices >= 1 else max(1, workers) * 4
    t0 = time.monotonic()
    specs = split_input(bam_path, rundir, n_slices)
    doc = config_doc(cfg)
    _save_json_atomic(os.path.join(rundir, CFG_DOC), doc)
    ledger = SliceLedger(rundir, specs, lease_s=lease_s)
    if inline or workers < 1:
        if ship:
            raise ElasticError(
                "--ship needs a worker fleet: shared-nothing shipping "
                "is meaningless inside one process (drop --inline)"
            )
        _run_inline(cfg, ledger)
    else:
        _run_fleet(
            ledger, doc,
            workers=workers, address=address,
            worker_failpoints=worker_failpoints or {},
            max_restarts=max_restarts, timeout_s=timeout_s,
            ship=ship,
        )
    from bsseqconsensusreads_tpu.elastic import merge as _merge

    # merge is a run-level overhead bucket: booked on the proc trace so
    # `observe trace` can rank it against spawn/import/compile
    with observe.span("merge", ctx=observe.proc_trace()):
        target, report = _merge.finalize(cfg, bam_path, outdir, specs,
                                         ledger.manifests())
    report["requeues"] = ledger.requeues
    report["workers_lost"] = ledger.workers_lost
    report["preempts"] = ledger.preempts
    report["wall_s"] = round(time.monotonic() - t0, 3)
    observe.emit(
        "elastic_run_complete",
        {"slices": len(specs), "records": report["records"],
         "requeues": ledger.requeues, "workers_lost": ledger.workers_lost,
         "ok": report["ok"]},
    )
    observe.flush_sinks()
    if not report["ok"]:
        raise ElasticError(
            f"elastic run did not reconcile: {report['checks']}"
        )
    return target, report
