"""Epoch fencing: the zombie-writer gate for elastic runs.

Lease expiry alone leaves a window open: a worker partitioned away
from the coordinator keeps computing a slice it no longer holds, and
when the partition heals it publishes — after the requeued twin already
committed. Two writers, one slice. Fencing closes the window with a
monotonically increasing **fence epoch**:

* the coordinator mints one epoch per lease grant (`EpochBook`),
  persisted in the rundir BEFORE the grant leaves — a restarted
  coordinator resumes strictly above every epoch it ever granted
  (epoch continuity across the coordinator-restart drill);
* every grant carries its `fence_epoch`; publish echoes it back, and a
  publish whose epoch is below the slice's current grant is refused
  with the typed reason ``fenced`` and a ``publish_fenced`` ledger
  event — even when its bytes happen to match (a zombie is a zombie);
* the worker **adopts** the fence while it holds the lease. When the
  renewal pump learns the lease is gone — a ``lease_expired`` renewal
  reply, or its own local deadline passing unrenewed behind a
  partition — it **revokes** the fence, and the next durable write
  (checkpoint shard / manifest rename / stage finalize, via the write
  gate installed into pipeline.checkpoint) raises `FencedError`
  instead of touching disk. The worker aborts the slice locally and
  leases fresh work; the requeued twin's files are never raced.

The write gate costs one ``is None`` branch per durable write outside
elastic workers; nothing here imports jax or the pipeline eagerly.
"""

from __future__ import annotations

import json
import os
import threading

from bsseqconsensusreads_tpu.utils import observe

FENCE_DOC = "fence.json"


class FencedError(RuntimeError):
    """A durable write or publish attempted under a stale (revoked or
    superseded) fence epoch. Typed so holders abort locally instead of
    retrying their way into a second writer."""

    def __init__(self, message: str, epoch: int | None = None):
        super().__init__(message)
        self.epoch = epoch


# --------------------------------------------------------------- coordinator


class EpochBook:
    """Coordinator-side epoch mint. The counter is persisted (atomic
    tmp+rename+fsync) BEFORE a minted epoch is returned, so no grant
    can ever carry an epoch a restarted coordinator would re-mint."""

    def __init__(self, rundir: str):
        self.path = os.path.join(rundir, FENCE_DOC)
        self._lock = threading.Lock()
        self.current = 0
        try:
            with open(self.path) as fh:
                self.current = int(json.load(fh).get("epoch", 0))
        except (OSError, ValueError):
            pass

    def mint(self) -> int:
        with self._lock:
            self.current += 1
            tmp = self.path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump({"epoch": self.current}, fh)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, self.path)
            return self.current


# -------------------------------------------------------------------- worker

#: The one adopted fence of this worker process (a worker holds at most
#: one lease at a time; hostpool threads inherit the same fence, which
#: is why this is module state, not thread-local).
_LOCK = threading.Lock()
_EPOCH: int | None = None
_LEASE_ID: str = ""
_REVOKED: bool = False
_REVOKE_REASON: str = ""


def adopt(epoch: int | None, lease_id: str = "") -> None:
    """Adopt the fence a lease grant carried. Installs the durable-write
    gate into pipeline.checkpoint on first use (lazy: non-elastic runs
    never import this module, let alone pay more than the gate's None
    branch)."""
    global _EPOCH, _LEASE_ID, _REVOKED, _REVOKE_REASON
    with _LOCK:
        _EPOCH = int(epoch) if epoch is not None else None
        _LEASE_ID = lease_id
        _REVOKED = False
        _REVOKE_REASON = ""
    from bsseqconsensusreads_tpu.pipeline import checkpoint as _ckpt

    _ckpt.install_write_gate(check)


def release() -> None:
    """Drop the adopted fence (slice published or abandoned)."""
    global _EPOCH, _LEASE_ID, _REVOKED, _REVOKE_REASON
    with _LOCK:
        _EPOCH = None
        _LEASE_ID = ""
        _REVOKED = False
        _REVOKE_REASON = ""


def revoke(reason: str = "lease lost", lease_id: str | None = None) -> None:
    """Mark the adopted fence stale: every later durable write refuses
    with FencedError. Called by the renewal pump on a ``lease_expired``
    reply or when its local deadline lapses unrenewed. When `lease_id`
    is given, only the fence adopted FOR that lease is revoked — a
    renewal pump that outlived its slice (stuck in a timed-out request
    past the joiner's patience) must not fence the worker's next lease."""
    global _REVOKED, _REVOKE_REASON
    with _LOCK:
        if _EPOCH is None:
            return
        if lease_id is not None and lease_id != _LEASE_ID:
            return
        _REVOKED = True
        _REVOKE_REASON = reason


def current() -> int | None:
    with _LOCK:
        return _EPOCH


def is_revoked() -> bool:
    with _LOCK:
        return _REVOKED


def check(what: str = "durable write") -> None:
    """The durable-write gate: no-op under a live (or absent) fence,
    FencedError under a revoked one. pipeline.checkpoint calls this at
    its three durable seams via the installed gate."""
    with _LOCK:
        if not _REVOKED:
            return
        epoch, lease_id, reason = _EPOCH, _LEASE_ID, _REVOKE_REASON
    raise FencedError(
        f"{what} refused: fence epoch {epoch} (lease {lease_id!r}) "
        f"revoked — {reason}",
        epoch=epoch,
    )


def emit_publish_fenced(
    slice_: str, worker: str, epoch, current_epoch, trace=None
) -> None:
    """The coordinator-side refusal event — one helper so the field
    tuple has exactly one writer."""
    with observe.bind_trace(trace):
        observe.emit(
            "publish_fenced",
            {"slice": slice_, "worker": worker,
             "epoch": epoch, "current": current_epoch},
        )
