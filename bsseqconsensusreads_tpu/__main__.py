from bsseqconsensusreads_tpu.cli import main

raise SystemExit(main())
