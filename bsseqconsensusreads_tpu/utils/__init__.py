"""Shared utilities: flag vocabulary, observability, testing helpers."""
