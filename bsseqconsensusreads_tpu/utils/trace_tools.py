"""grafttrace consumers: cross-process causal-trace reassembly.

utils.observe writes spans ("span" ledger lines: name, trace, span,
parent, t0, t1, dur_s) and stamps ordinary events with the bound trace
ctx. One job or slice therefore leaves lines in SEVERAL processes'
ledgers (router + replicas, or coordinator + workers), all sharing one
trace id. This module puts them back together:

* assemble  — load a rundir's ledgers (or explicit paths) and rebuild
  the span forest: every trace's spans keyed by id, parent links
  resolved ACROSS files, stamped non-span events attached.
* checks    — orphan spans (a parent id never seen anywhere: a
  truncated or missing ledger), job/slice traces that never reached a
  terminal event (job_complete/job_failed, elastic_slice_done), and
  trace-vs-counter reconciliation (distinct job traces against
  admitted jobs, distinct slice traces against the split) — the
  `observe check` cross-process tier and the chaos drill's
  killed-process assertion (a killed holder's trace carries a
  fleet_requeue/slice_requeued line and STILL terminates).
* critical path — per trace, the root→leaf chain ending at the
  latest-finishing span (wall-clock t0/t1: monotonic clocks do not
  compare across processes); for the run, the longest such chain.
* overhead buckets — dur_s summed per span name (worker_spawn,
  jax_import, compile, lease_wait, transport, ingest, merge, ...),
  ranked: the table that turns an ELASTIC_HEAD wall-clock loss into
  named, ordered causes.

Everything here is read-only over ledger files; `cli observe trace`
and the bench/chaos tooling are thin callers.
"""

from __future__ import annotations

import glob
import os
from dataclasses import dataclass, field

from bsseqconsensusreads_tpu.utils import ledger_tools as _lt
from bsseqconsensusreads_tpu.utils.observe import TRACE_TERMINAL_KINDS

#: Events that close a trace of each terminal-requiring kind. A job is
#: done when it retired or failed; a slice when the coordinator
#: committed its manifest (elastic_merged additionally closes every
#: slice at once, but commit is the per-slice truth).
TERMINAL_EVENTS: dict[str, frozenset] = {
    "job": frozenset({"job_complete", "job_failed"}),
    "slice": frozenset({"elastic_slice_done"}),
}

#: Events that mark a kill/lapse being RESOLVED back onto the queue —
#: a chaos-killed holder's trace must carry one of these before its
#: eventual terminal, never dangle.
REQUEUE_EVENTS = frozenset({"fleet_requeue", "slice_requeued"})


@dataclass
class Span:
    sid: str
    parent: str | None
    name: str
    trace: str
    t0: float
    t1: float
    dur_s: float
    raw: dict = field(default_factory=dict)


@dataclass
class Trace:
    tid: str
    kind: str
    spans: dict[str, Span] = field(default_factory=dict)
    #: stamped non-span ledger lines carrying this trace id
    events: list[dict] = field(default_factory=list)

    @property
    def roots(self) -> list[Span]:
        return [s for s in self.spans.values() if s.parent is None]

    @property
    def t0(self) -> float | None:
        return min((s.t0 for s in self.spans.values()), default=None)

    @property
    def t1(self) -> float | None:
        return max((s.t1 for s in self.spans.values()), default=None)

    def terminal(self) -> bool:
        """True when a terminal event for this kind is attached (or the
        kind never requires one — proc traces live as long as their
        process and are exempt by TRACE_TERMINAL_KINDS)."""
        if self.kind not in TRACE_TERMINAL_KINDS:
            return True
        closing = TERMINAL_EVENTS.get(self.kind, frozenset())
        return any(e.get("event") in closing for e in self.events)

    def requeued(self) -> bool:
        return any(e.get("event") in REQUEUE_EVENTS for e in self.events)

    def critical_path(self) -> list[Span]:
        """Root→leaf chain ending at the latest-finishing span. A
        truncated chain (orphan leaf) walks up as far as the links go —
        the orphan check reports the break separately."""
        if not self.spans:
            return []
        leaf = max(self.spans.values(), key=lambda s: s.t1)
        path = [leaf]
        seen = {leaf.sid}
        cur = leaf
        while cur.parent is not None and cur.parent in self.spans:
            cur = self.spans[cur.parent]
            if cur.sid in seen:  # defensive: a cycle would hang here
                break
            seen.add(cur.sid)
            path.append(cur)
        path.reverse()
        return path


@dataclass
class TraceReport:
    paths: list[str] = field(default_factory=list)
    lines: int = 0
    traces: dict[str, Trace] = field(default_factory=dict)
    #: (trace id, span id, missing parent id, span name)
    orphans: list[tuple] = field(default_factory=list)
    #: malformed-line / unreadable-file strings from parsing
    parse_problems: list[str] = field(default_factory=list)
    #: all raw ledger lines, for counter reconciliation
    raw: list[dict] = field(default_factory=list)

    def by_kind(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for t in self.traces.values():
            out[t.kind] = out.get(t.kind, 0) + 1
        return out

    def span_count(self) -> int:
        return sum(len(t.spans) for t in self.traces.values())

    def buckets(self) -> list[tuple[str, int, float]]:
        """(name, span count, total dur_s) ranked by total, descending —
        the overhead attribution table."""
        agg: dict[str, list] = {}
        for t in self.traces.values():
            for s in t.spans.values():
                slot = agg.setdefault(s.name, [0, 0.0])
                slot[0] += 1
                slot[1] += s.dur_s
        return sorted(
            ((n, c, d) for n, (c, d) in agg.items()),
            key=lambda x: (-x[2], x[0]),
        )

    def longest(self) -> Trace | None:
        """The trace whose critical path spans the most wall — the run's
        critical path. Proc traces compete too: a run dominated by one
        process's spawn+import+compile should SAY so."""
        best, best_wall = None, -1.0
        for t in self.traces.values():
            t0, t1 = t.t0, t.t1
            if t0 is None or t1 is None:
                continue
            if t1 - t0 > best_wall:
                best, best_wall = t, t1 - t0
        return best


def resolve_ledgers(target: str | list[str]) -> list[str]:
    """A rundir (every *.jsonl inside, sorted), a single ledger file, or
    an explicit list of paths."""
    if isinstance(target, (list, tuple)):
        return [str(p) for p in target]
    if os.path.isdir(target):
        return sorted(glob.glob(os.path.join(target, "*.jsonl")))
    return [target]


def assemble(target: str | list[str]) -> TraceReport:
    """Load ledgers and rebuild the cross-process span forest."""
    report = TraceReport(paths=resolve_ledgers(target))
    if not report.paths:
        report.parse_problems.append(
            f"no ledgers found under {target!r} (expected *.jsonl)"
        )
        return report
    lines: list[dict] = []
    for path in report.paths:
        try:
            got, problems = _lt.parse_ledger(path)
        except _lt.LedgerError as exc:
            report.parse_problems.append(str(exc))
            continue
        lines.extend(got)
        report.parse_problems.extend(
            f"{os.path.basename(path)}: {p}" for p in problems
        )
    report.lines = len(lines)
    report.raw = lines
    for d in lines:
        tid = d.get("trace")
        if not isinstance(tid, str):
            continue
        trace = report.traces.get(tid)
        if trace is None:
            trace = report.traces[tid] = Trace(
                tid=tid, kind=tid.split("-", 1)[0]
            )
        if d.get("event") == "span":
            sid = d.get("span")
            if not isinstance(sid, str):
                report.parse_problems.append(
                    f"span line in trace {tid} without a span id"
                )
                continue
            parent = d.get("parent")
            trace.spans[sid] = Span(
                sid=sid,
                parent=parent if isinstance(parent, str) else None,
                name=str(d.get("name", "?")),
                trace=tid,
                t0=float(d.get("t0", 0.0)),
                t1=float(d.get("t1", 0.0)),
                dur_s=float(d.get("dur_s", 0.0)),
                raw=d,
            )
        else:
            trace.events.append(d)
    for trace in report.traces.values():
        for s in trace.spans.values():
            if s.parent is not None and s.parent not in trace.spans:
                report.orphans.append((trace.tid, s.sid, s.parent, s.name))
    return report


# ---------------------------------------------------------------------------
# Checks: the `observe check` cross-process tier / chaos-drill gate.


def _reconcile_problems(report: TraceReport) -> list[str]:
    """Distinct trace counts against the run's own counters: every
    admitted job and every split slice must own exactly one trace."""
    problems: list[str] = []
    kinds = report.by_kind()
    # admissions are keyed by TRACE, not job id: queue-local ids
    # ("j0001") collide across replicas in a shared ledger, and a
    # requeued job is re-admitted under a new remote id but the SAME
    # trace — the invariant is one admission stream per job trace.
    untraced = sum(
        1 for d in report.raw
        if d.get("event") == "job_admitted" and "trace" not in d
    )
    if untraced:
        problems.append(
            f"reconcile: {untraced} job admission(s) carry no trace id"
        )
    admitted_traces = {
        str(d["trace"])
        for d in report.raw
        if d.get("event") == "job_admitted" and "trace" in d
    }
    job_traces = {
        t.tid for t in report.traces.values() if t.kind == "job"
    }
    never_admitted = job_traces - admitted_traces
    if admitted_traces and never_admitted:
        problems.append(
            f"reconcile: {len(never_admitted)} job trace(s) with no "
            f"admission event: {', '.join(sorted(never_admitted))}"
        )
    split = max(
        (
            d.get("slices")
            for d in report.raw
            if d.get("event") == "elastic_split"
            and isinstance(d.get("slices"), int)
        ),
        default=None,
    )
    if split is not None and kinds.get("slice", 0) != split:
        problems.append(
            f"reconcile: split produced {split} slices but "
            f"{kinds.get('slice', 0)} slice traces"
        )
    # the router counter `jobs_routed` counts PLACEMENTS (a requeued
    # job is re-routed under the same trace), so totals don't compare
    # against distinct traces — the invariant is that every route event
    # is stamped: a stamped route materialises its job trace, and a
    # routed-but-never-admitted or never-terminated trace is then
    # caught by the admission and terminal checks above.
    unrouted = sum(
        1 for d in report.raw
        if d.get("event") == "fleet_route" and "trace" not in d
    )
    if unrouted:
        problems.append(
            f"reconcile: {unrouted} fleet_route event(s) carry no "
            "trace id"
        )
    return problems


def check_traces(report: TraceReport) -> list[str]:
    """All cross-process trace problems (empty = the forest is whole):
    parse/truncation damage, orphan spans, job/slice traces that never
    reached a terminal state, counter mismatches."""
    problems = list(report.parse_problems)
    for tid, sid, parent, name in report.orphans:
        problems.append(
            f"orphan span {sid} ({name}) in trace {tid}: parent "
            f"{parent} never seen in any loaded ledger"
        )
    for trace in report.traces.values():
        if not trace.terminal():
            problems.append(
                f"trace {trace.tid} ({trace.kind}) never reached a "
                "terminal state"
                + (" (requeued, then lost)" if trace.requeued() else "")
            )
    problems.extend(_reconcile_problems(report))
    return problems


# ---------------------------------------------------------------------------
# Rendering + artifact embedding.


def _fmt_s(v: float) -> str:
    return f"{v:.3f}"


def format_report(report: TraceReport) -> str:
    kinds = report.by_kind()
    out = [
        f"ledgers: {len(report.paths)} file(s), {report.lines} lines",
        "traces: "
        + ", ".join(f"{kinds.get(k, 0)} {k}" for k in ("job", "slice", "proc"))
        + f"; spans: {report.span_count()}; orphans: {len(report.orphans)}",
    ]
    buckets = report.buckets()
    if buckets:
        total = sum(d for _, _, d in buckets) or 1.0
        out.append("")
        out.append("overhead buckets (dur_s summed per span name, ranked)")
        out.append(
            _lt._table(
                ["bucket", "spans", "total_s", "share"],
                [
                    [n, str(c), _fmt_s(d), f"{d / total:.0%}"]
                    for n, c, d in buckets
                ],
            )
        )
    longest = report.longest()
    if longest is not None:
        path = longest.critical_path()
        wall = (longest.t1 or 0.0) - (longest.t0 or 0.0)
        out.append("")
        out.append(
            f"critical path — longest trace {longest.tid} "
            f"({_fmt_s(wall)}s wall)"
        )
        out.append(
            _lt._table(
                ["span", "dur_s", "t0+"],
                [
                    [s.name, _fmt_s(s.dur_s), _fmt_s(s.t0 - (longest.t0 or 0.0))]
                    for s in path
                ],
            )
        )
    rows = []
    for trace in sorted(
        report.traces.values(), key=lambda t: (t.kind, t.tid)
    ):
        if trace.kind not in TRACE_TERMINAL_KINDS:
            continue
        t0, t1 = trace.t0, trace.t1
        wall = (t1 - t0) if t0 is not None and t1 is not None else 0.0
        rows.append(
            [
                trace.tid,
                _fmt_s(wall),
                str(len(trace.spans)),
                "yes" if trace.terminal() else "NO",
                ">".join(s.name for s in trace.critical_path()) or "-",
            ]
        )
    if rows:
        out.append("")
        out.append("per-trace critical paths")
        out.append(
            _lt._table(["trace", "wall_s", "spans", "terminal", "path"], rows)
        )
    return "\n".join(out)


def trace_summary(target: str | list[str]) -> dict:
    """JSON-able trace digest for run artifacts (ELASTIC_HEAD.json /
    FLEET_HEAD.json): the overhead-bucket table, the run's critical
    path, and the check verdict — a fleet/elastic wall-clock number
    without this table attached names a cost it cannot attribute."""
    report = assemble(target)
    problems = check_traces(report)
    longest = report.longest()
    crit = []
    if longest is not None:
        crit = [
            {"span": s.name, "dur_s": round(s.dur_s, 4)}
            for s in longest.critical_path()
        ]
    return {
        "ledgers": len(report.paths),
        "traces": report.by_kind(),
        "spans": report.span_count(),
        "orphans": len(report.orphans),
        "problems": problems,
        "ok": not problems,
        "buckets": {
            name: {"spans": count, "total_s": round(dur, 4)}
            for name, count, dur in report.buckets()
        },
        "critical_path": {
            "trace": longest.tid if longest is not None else None,
            "spans": crit,
        },
    }
