"""Persistent XLA compile cache: the BSSEQ_TPU_COMPILE_CACHE_DIR knob.

Every cold process pays XLA compilation for each kernel shape it
touches — the dominant share of serve warm-start and of short CLI
reruns. When BSSEQ_TPU_COMPILE_CACHE_DIR is set, compiled executables
persist there (jax's compilation cache) and are reloaded by any later
process with the same backend + jaxlib + shape, so the serve engine's
restart and ordinary `cli molecular`/`duplex` reruns skip compilation
entirely.

Accounting rides the run ledger: jax announces persistent-cache
outcomes on its monitoring bus ('/jax/compilation_cache/cache_hits' /
'cache_misses'); a listener registered at enable time tallies them and
`publish(metrics)` books the delta into the active stage's counters as
`compile_cache_hit` / `compile_cache_miss` — so a ledger can prove a
rerun actually reused its capital (hit > 0, miss == 0) instead of
silently recompiling.

The knob is environment-driven like the rest of the framework
(BSSEQ_TPU_STATS, BSSEQ_TPU_FAILPOINTS): `maybe_enable()` is called by
the CLI entry point and the serve engine, is idempotent, and is a no-op
when the variable is unset.
"""

from __future__ import annotations

import os
import threading

ENV_DIR = "BSSEQ_TPU_COMPILE_CACHE_DIR"

_LOCK = threading.Lock()
_STATE = {
    "enabled": False,
    "hits": 0,
    "misses": 0,
    # already booked into some Metrics by publish() — the bus counters
    # are process-global, stage bookings must not double-count
    "published_hits": 0,
    "published_misses": 0,
}

_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_MISS_EVENT = "/jax/compilation_cache/cache_misses"


def _on_event(event: str, **kw) -> None:
    if event == _HIT_EVENT:
        with _LOCK:
            _STATE["hits"] += 1
    elif event == _MISS_EVENT:
        with _LOCK:
            _STATE["misses"] += 1


def maybe_enable() -> str | None:
    """Point jax's persistent compilation cache at BSSEQ_TPU_COMPILE_CACHE_DIR
    (created if missing) and start tallying hit/miss events. Idempotent;
    returns the cache dir, or None when the knob is unset."""
    directory = os.environ.get(ENV_DIR) or None
    if directory is None:
        return None
    with _LOCK:
        already = _STATE["enabled"]
        _STATE["enabled"] = True
    if already:
        return directory
    os.makedirs(directory, exist_ok=True)
    import jax

    jax.config.update("jax_compilation_cache_dir", directory)
    # cache every executable: the tier-1/CPU kernels compile in
    # milliseconds and the default min-compile-time floor would skip
    # them, making warm-start unobservable (and untestable) off-TPU
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    from jax._src import monitoring

    monitoring.register_event_listener(_on_event)
    return directory


def enabled() -> bool:
    with _LOCK:
        return _STATE["enabled"]


def counts() -> tuple[int, int]:
    """(hits, misses) tallied so far in this process."""
    with _LOCK:
        return _STATE["hits"], _STATE["misses"]


def publish(metrics) -> None:
    """Book the unpublished hit/miss delta into `metrics` counters
    (compile_cache_hit / compile_cache_miss). Called at stage end by the
    batch callers and the serve engine; no-op while disabled, so the
    counters only appear in ledgers of cache-enabled runs."""
    with _LOCK:
        if not _STATE["enabled"]:
            return
        dh = _STATE["hits"] - _STATE["published_hits"]
        dm = _STATE["misses"] - _STATE["published_misses"]
        _STATE["published_hits"] = _STATE["hits"]
        _STATE["published_misses"] = _STATE["misses"]
    metrics.count("compile_cache_hit", dh)
    metrics.count("compile_cache_miss", dm)
