"""Synthetic data generators for tests and benchmarks.

The reference ships no tests and no fixtures (SURVEY.md §4); its author
smoke-tested on an `input/test.bam`. These generators produce the same shape
of data: a reference genome, raw UMI-grouped read families (the output contract
of `fgbio GroupReadsByUmi -s Paired`, reference: README.md:51-55 — RX = UMI,
MI = group id with /A | /B strand suffixes), and aligned consensus-read duplex
groups with flags {99, 163, 83, 147}.
"""

from __future__ import annotations

import numpy as np

from bsseqconsensusreads_tpu.io.bam import (
    BamHeader,
    BamRecord,
    CMATCH,
    CSOFT_CLIP,
)
from bsseqconsensusreads_tpu.io.fastq import reverse_complement

BASES = "ACGT"


def random_genome(rng: np.random.Generator, length: int = 5000, name: str = "chr1") -> tuple[str, str]:
    seq = "".join(BASES[i] for i in rng.integers(0, 4, size=length))
    return name, seq


def write_fasta(path: str, name: str, seq: str, width: int = 60) -> None:
    with open(path, "w") as fh:
        fh.write(f">{name}\n")
        for i in range(0, len(seq), width):
            fh.write(seq[i : i + width] + "\n")


def simulate_read(
    rng: np.random.Generator,
    genome: str,
    start: int,
    length: int,
    error_rate: float = 0.01,
) -> tuple[str, bytes]:
    """Draw a read from genome[start:start+length] with random substitutions."""
    frag = list(genome[start : start + length])
    quals = rng.integers(20, 41, size=len(frag)).astype(np.uint8)
    for i in range(len(frag)):
        if rng.random() < error_rate:
            frag[i] = BASES[rng.integers(0, 4)]
    return "".join(frag), bytes(quals)


def bisulfite_convert(seq: str, genome: str, start: int, strand: str, meth_cpg: bool = True) -> str:
    """Apply bisulfite chemistry to a fragment in top-strand coordinates.

    Top ('A') strand: unmethylated C -> T; CpG Cs stay C when methylated.
    Bottom ('B') strand: the complementary strand converts, which reads out on
    the top-strand coordinates as G -> A (except methylated CpG Gs).
    """
    out = list(seq)
    n = len(genome)
    for i, b in enumerate(out):
        gpos = start + i
        if strand == "A" and b == "C":
            in_cpg = gpos + 1 < n and genome[gpos + 1] == "G"
            if not (meth_cpg and in_cpg):
                out[i] = "T"
        elif strand == "B" and b == "G":
            in_cpg = gpos - 1 >= 0 and genome[gpos - 1] == "C"
            if not (meth_cpg and in_cpg):
                out[i] = "A"
    return "".join(out)


def make_grouped_bam_records(
    rng: np.random.Generator,
    genome_name: str,
    genome: str,
    n_families: int = 8,
    reads_per_strand: tuple[int, int] = (2, 4),
    read_len: int = 50,
    error_rate: float = 0.01,
) -> tuple[BamHeader, list[BamRecord]]:
    """Simulate the GroupReadsByUmi -s Paired output BAM: raw paired reads,
    RX tag = umi pair, MI tag = '<group>/A' or '<group>/B'."""
    header = BamHeader("@HD\tVN:1.6\tSO:coordinate\n", [(genome_name, len(genome))])
    records: list[BamRecord] = []
    for fam in range(n_families):
        frag_start = int(rng.integers(10, len(genome) - 3 * read_len))
        frag_len = int(rng.integers(read_len + 10, 2 * read_len))
        umi = "".join(BASES[i] for i in rng.integers(0, 4, size=8))
        umi2 = "".join(BASES[i] for i in rng.integers(0, 4, size=8))
        r2_start = frag_start + frag_len - read_len
        for strand in "AB":
            depth = int(rng.integers(reads_per_strand[0], reads_per_strand[1] + 1))
            for d in range(depth):
                left_seq, left_qual = simulate_read(rng, genome, frag_start, read_len, error_rate)
                right_seq, right_qual = simulate_read(rng, genome, r2_start, read_len, error_rate)
                left_seq = bisulfite_convert(left_seq, genome, frag_start, strand)
                right_seq = bisulfite_convert(right_seq, genome, r2_start, strand)
                qname = f"fam{fam}:{strand}:{d}"
                # A strand: left read is R1 forward (99), right is R2 reverse (147).
                # B strand: left read is R2 forward (163), right is R1 reverse (83).
                left_flag, right_flag = (99, 147) if strand == "A" else (163, 83)
                rx = f"{umi}-{umi2}"
                mi = f"{fam}/{strand}"
                left = BamRecord(
                    qname=qname, flag=left_flag, ref_id=0, pos=frag_start,
                    mapq=60, cigar=[(CMATCH, read_len)], next_ref_id=0,
                    next_pos=r2_start, tlen=frag_len, seq=left_seq, qual=left_qual,
                )
                right = BamRecord(
                    qname=qname, flag=right_flag, ref_id=0, pos=r2_start, mapq=60,
                    cigar=[(CMATCH, read_len)], next_ref_id=0,
                    next_pos=frag_start, tlen=-frag_len, seq=right_seq, qual=right_qual,
                )
                for rec in (left, right):
                    rec.set_tag("RX", rx, "Z")
                    rec.set_tag("MI", mi, "Z")
                    records.append(rec)
    records.sort(key=lambda r: (r.ref_id, r.pos))
    return header, records


def make_aligned_duplex_group(
    rng: np.random.Generator,
    genome_name: str,
    genome: str,
    mi: int,
    start: int,
    length: int,
    softclip: int = 0,
) -> list[BamRecord]:
    """One aligned duplex group of 4 single-strand consensus reads with flags
    99/163/83/147 spanning [start, start+length) — the input shape of the
    convert/extend/duplex stages (reference: main.snake.py:121-164)."""
    recs = []
    frag = genome[start : start + length]
    a_seq = bisulfite_convert(frag, genome, start, "A")
    b_seq = bisulfite_convert(frag, genome, start, "B")
    qual = bytes(rng.integers(30, 41, size=length).astype(np.uint8))
    for flag, strand, seq in ((99, "A", a_seq), (163, "B", b_seq), (83, "B", b_seq), (147, "A", a_seq)):
        cigar = [(CMATCH, length)]
        out_seq, out_qual, pos = seq, qual, start
        if softclip and flag in (99, 163):
            clip = "".join(BASES[i] for i in rng.integers(0, 4, size=softclip))
            out_seq = clip + seq
            out_qual = bytes([2] * softclip) + qual
            cigar = [(CSOFT_CLIP, softclip), (CMATCH, length)]
        rec = BamRecord(
            qname=f"mi{mi}:{flag}", flag=flag, ref_id=0, pos=pos, mapq=60,
            cigar=cigar, next_ref_id=0, next_pos=start, tlen=length,
            seq=out_seq, qual=out_qual,
        )
        rec.set_tag("MI", f"{mi}/{'A' if strand == 'A' else 'B'}", "Z")
        rec.set_tag("RX", "ACGTACGT-TGCATGCA", "Z")
        recs.append(rec)
    return recs


def stream_duplex_families(
    codes: np.ndarray,
    n_families: int,
    *,
    read_len: int = 100,
    frag_extra: int = 30,
    templates_for=None,
    qual_for=None,
    mutate=None,
    rx: str = "ACGTACGT-TGCATGCA",
    bisulfite: bool = False,
    raw_umis: bool = False,
):
    """Stream a coordinate-sorted synthetic grouped-duplex record stream.

    One MI family per `fam` index: A/B strands x both mates (flags
    99/147/163/83), `templates_for(fam)` read pairs per strand (default 1).
    Family start positions are MONOTONE NON-DECREASING —
    ``10 + (fam * span) // n_families`` — so the stream satisfies the
    'coordinate' grouping contract (pipeline.calling.stream_mi_groups) for
    ANY family count; a stride-modulo scheme would wrap and silently break
    the sort once n_families * stride exceeds the genome span.

    Memory is O(1 family): records are built lazily. Shared by
    tests/memhelper.py (peak-RSS tests) and tools/scale_rehearsal.py so the
    generation scheme has one source of truth.

    qual_for(fam, ti, flag) -> bytes[read_len]; mutate(seq, fam, ti, flag)
    -> str lets callers inject sequencing errors without paying per-record
    rng costs here.

    bisulfite=True emits each strand's reads in that strand's bisulfite
    space (bisulfite_convert A/B, CpGs methylated) — the chemistry the
    duplex convert stage is built for (reference tools/1 semantics); raw
    genome reads fed through the convert stage would trip its
    content-dependent rewrite rules pseudo-randomly.

    raw_umis=True emits the stream one step EARLIER than the reference's
    input contract: per-family duplex UMIs in RX (B-strand halves
    swapped, as sequenced) and NO MI tag — the input shape of
    pipeline.group_umi. UMIs are fam-deterministic with pairwise
    mismatch distance >= 2, so edits<=1 grouping can never merge two
    families that happen to share a position bucket.
    """
    from bsseqconsensusreads_tpu.ops.encode import codes_to_seq

    genome_len = len(codes)
    frag_len = read_len + frag_extra
    span = genome_len - frag_len - 30
    if span <= 0:
        raise ValueError(f"genome too short: {genome_len} for {frag_len}-bp fragments")
    genome_str = codes_to_seq(codes) if bisulfite else None
    default_qual = bytes([35] * read_len)

    if raw_umis and n_families > 4 ** 12:
        raise ValueError(
            f"raw_umis encodes fam in 12 base-4 digits; {n_families} "
            f"families would wrap and repeat UMIs"
        )

    def _fam_umi(fam: int) -> tuple[str, str]:
        # base-4 digits of fam, and the same digits +1 mod 4: two distinct
        # fams differ in >=1 position of EACH half => pair distance >= 2
        digits = [(fam >> (2 * i)) & 3 for i in range(12)]
        u1 = "".join(BASES[d] for d in digits)
        u2 = "".join(BASES[(d + 1) & 3] for d in digits)
        return u1, u2

    for fam in range(n_families):
        start = 10 + (fam * span) // n_families
        r2 = start + frag_len - read_len
        if not bisulfite:
            left = codes_to_seq(codes[start : start + read_len])
            right = codes_to_seq(codes[r2 : r2 + read_len])
        t = templates_for(fam) if templates_for else 1
        for strand, (lf, rf) in (("A", (99, 147)), ("B", (163, 83))):
            if bisulfite:
                left = bisulfite_convert(
                    genome_str[start : start + read_len], genome_str, start, strand
                )
                right = bisulfite_convert(
                    genome_str[r2 : r2 + read_len], genome_str, r2, strand
                )
            for ti in range(t):
                for flag, pos, mate, seq, tl in (
                    (lf, start, r2, left, frag_len),
                    (rf, r2, start, right, -frag_len),
                ):
                    if mutate is not None:
                        seq = mutate(seq, fam, ti, flag)
                    rec = BamRecord(
                        qname=f"f{fam}:{strand}:{ti}", flag=flag, ref_id=0,
                        pos=pos, mapq=60, cigar=[(CMATCH, read_len)],
                        next_ref_id=0, next_pos=mate, tlen=tl, seq=seq,
                        qual=qual_for(fam, ti, flag) if qual_for else default_qual,
                    )
                    if raw_umis:
                        u1, u2 = _fam_umi(fam)
                        a, b = (u1, u2) if strand == "A" else (u2, u1)
                        rec.set_tag("RX", f"{a}-{b}", "Z")
                    else:
                        rec.set_tag("RX", rx, "Z")
                        rec.set_tag("MI", f"{fam}/{strand}", "Z")
                    yield rec
