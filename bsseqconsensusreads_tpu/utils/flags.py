"""The bisulfite flag vocabulary the pipeline dispatches on.

bwameth emits paired-end bisulfite alignments whose strand identity is carried
by the SAM flag. The reference's conversion tool switches on exactly these
values (reference: tools/1.convert_AG_to_CT.py:70,73) and its gap-extension
tool pairs them (reference: tools/2.extend_gap.py:61,123,129):

* 99  (paired, proper, mate-reverse, read1, forward)  — A-strand R1, already C/T space
* 147 (paired, proper, reverse, read2)                — A-strand R2, already C/T space
* 163 (paired, proper, mate-reverse, read2, forward)  — B-strand R2, needs A/G->C/T conversion
* 83  (paired, proper, reverse, read1)                — B-strand R1, needs A/G->C/T conversion
* 0 / 1 — degenerate unpaired cases the reference passes through / converts.

Duplex pairing is by mapped orientation: (99, 163) both map forward and merge
into the duplex R1; (83, 147) both map reverse and merge into the duplex R2.
"""

PASSTHROUGH_FLAGS = frozenset({0, 99, 147})
CONVERT_FLAGS = frozenset({1, 83, 163})
KEEP_FLAGS = PASSTHROUGH_FLAGS | CONVERT_FLAGS

FORWARD_PAIR = (99, 163)   # duplex R1 sources (top-strand window)
REVERSE_PAIR = (83, 147)   # duplex R2 sources
GROUP_ORDER = (99, 163, 83, 147)  # output order inside a duplex group
                                  # (reference: tools/2.extend_gap.py:136)
