"""Run-ledger consumers: summarize / diff / check (the `observe` CLI).

A ledger is the JSONL stream utils.observe writes when BSSEQ_TPU_STATS is
set: a run_manifest line first, then events (stage_stats, rule_complete,
pipeline_complete, spill, overlap_pool_disabled, worker_heartbeat, ...).
This module turns ledgers back into the numbers round verdicts kept
re-deriving by hand:

* summarize — per-stage host_s / device_s / stall_s / chip_busy table,
  the rule wall table, and the closure verdict;
* diff      — two summaries side by side (e.g. a cpu-backend run vs an
  on-chip run of the same config);
* check     — schema + invariant validation, non-zero exit on violation,
  so CI can gate on ledger integrity.

The ledger-closure invariant: per-rule wall seconds must sum to the
pipeline wall (pipeline_complete.pipeline_s) within tolerance, and each
stage's owner-thread timeline must be attributed to phases
(stage_stats.unattributed_s small relative to wall_seconds) — together
they prove no share of the run is hiding outside the ledger's numbers.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

#: Required keys per known event type (unknown events only need ts+event).
EVENT_SCHEMA: dict[str, tuple[str, ...]] = {
    "run_manifest": ("git_rev", "version", "backend", "device_count"),
    "stage_stats": ("stage",),
    "rule_complete": ("rule", "seconds", "ran"),
    "pipeline_complete": ("pipeline_s",),
    "spill": ("records", "seconds"),
    "merge_pass": ("pass", "runs"),
    "overlap_pool_disabled": ("reason",),
    "overlap_pool_enabled": ("workers",),
    "overlap_pool_composed": ("stage", "workers", "devices"),
    "host_pool_enabled": ("stage", "workers"),
    "host_pool_disabled": ("stage", "reason"),
    "worker_heartbeat": ("process_index", "seq", "phase"),
    # batch recovery (faults/retry + pipeline/calling): retries, degrades
    # and the stall watchdog — chaos drills count these
    "batch_retry": ("stage", "batch", "attempt"),
    "batch_recovered": ("stage", "batch", "attempts"),
    "batch_degraded": ("stage", "batch", "attempts", "error"),
    "batch_stall_redispatch": ("stage", "batch", "timeout_s"),
    "interstage_fallback": ("reason",),
    "failpoint_fired": ("site", "action"),
    # sort/checkpoint durability (pipeline/bucketemit + pipeline/checkpoint)
    "bucket_plan": ("buckets", "records_per_spill"),
    "bucket_spill": ("bucket", "records", "run", "seconds"),
    "bucket_replayed": ("buckets", "target"),
    "bucket_manifest_resumed": ("replayed", "target"),
    "bucket_manifest_discarded": ("reason", "target"),
    "checkpoint_input_changed": (
        "target", "run_input", "manifest_input", "batches_at_stake",
    ),
    "checkpoint_discarded": (
        "target", "reason", "dropped_batches", "dropped_shards",
    ),
    "shard_quarantined": (
        "target", "shard", "error", "dropped_batches", "dropped_shards",
    ),
    # methyl tally durability (methyl/tally)
    "methyl_spill": ("run", "sites", "upto"),
    "methyl_resume": ("watermark", "runs_kept", "runs_dropped"),
    "methyl_finalize": (),
    # input guard + stream resilience (faults/guard, io/bam, io/bgzf)
    "record_quarantined": ("input", "reason", "record_index"),
    "record_repaired": ("input", "qname", "reason", "record_index"),
    "family_quarantined": ("input", "mi", "reason", "records"),
    "guard_events_truncated": ("input", "dropped"),
    "stream_gap": ("input", "gap_start", "resumed_at", "skipped_bytes"),
    "stream_truncated": ("input", "error"),
    "frame_resync": ("input", "voffset", "discarded_bytes"),
    "frame_lost": ("input", "error"),
    "integrity_mismatch": ("what", "path"),
    # graftserve (serve/): per-tenant lines carry a 'job' field and are
    # mirrored to BSSEQ_TPU_STATS_JOBS sub-sinks
    "job_admitted": ("input", "output", "fingerprint"),
    "job_complete": ("output", "families", "consensus_out"),
    "job_failed": ("error",),
    "serve_listening": ("socket",),
    "serve_drained": ("socket",),
    "serve_warmup": ("families",),
    "serve_frame_refused": ("reason",),
    # graftfleet (serve/fleet + serve/router): replica processes stamp
    # every line with a 'replica' field (BSSEQ_TPU_REPLICA_ID); the
    # router's own lines reconcile placement with per-replica counts
    "fleet_replica_spawn": ("replica_id", "generation"),
    "fleet_replica_down": ("replica_id",),
    "fleet_restart_failed": ("replica_id", "error"),
    "fleet_route": ("rjob", "replica_id"),
    "fleet_requeue": ("rjob", "from_replica", "to_replica"),
    "fleet_counters": (
        "jobs_routed", "jobs_requeued", "affinity_hits",
        "replica_restarts",
    ),
    # graftswarm (elastic/): worker processes stamp every line with a
    # 'worker' field (BSSEQ_TPU_WORKER_ID); the coordinator's ledger
    # events carry the lease/requeue evidence the chaos drills assert on
    "elastic_split": ("slices", "families", "records"),
    "elastic_lease": ("slice", "worker", "lease_id"),
    "elastic_join": ("worker",),
    "elastic_slice_processed": ("slice", "worker"),
    "elastic_slice_done": ("slice",),
    "elastic_publish_refused": ("slice", "worker", "reason"),
    "elastic_slice_reset": ("slice", "worker"),
    "slice_requeued": ("slice", "worker", "reason"),
    "worker_lost": ("worker", "reason"),
    "elastic_worker_spawn": ("worker", "generation"),
    "elastic_ledger_resumed": ("done", "pending"),
    "elastic_merged": ("records", "slices", "ok"),
    "elastic_run_complete": ("slices", "records", "requeues", "ok"),
    # graftnet: epoch fencing + shared-nothing slice shipping
    "publish_fenced": ("slice", "worker", "epoch", "current"),
    "frame_dup_ignored": ("rid", "op"),
    "slice_chunk_resent": ("slice", "offset", "attempt"),
    # graftpreempt: voluntary drain-and-handoff + overload shedding
    "worker_preempted": ("worker", "reason"),
    "handoff_published": ("slice", "worker", "batches_kept",
                          "handoff_latency_s"),
    "jobs_shed": ("depth", "watermark", "retry_after_s"),
    # grafttrace (observability): completed causal spans (root spans
    # carry no 'parent' key; trace/span ids also stamp ordinary events)
    # and the crash-path flight-recorder dump
    "span": ("name", "trace", "span", "t0", "t1", "dur_s"),
    "flight_record": ("reason", "count", "events"),
}

#: Default closure tolerance: relative share of the wall allowed to go
#: unattributed (plus a small absolute floor for sub-second runs).
CLOSURE_REL_TOL = 0.15
CLOSURE_ABS_TOL = 0.75


class LedgerError(RuntimeError):
    pass


@dataclass
class LedgerSummary:
    path: str = ""
    job: str | None = None  # serve tenant the view is scoped to
    replica: str | None = None  # fleet replica the view is scoped to
    worker: str | None = None  # elastic worker the view is scoped to
    manifest: dict = field(default_factory=dict)
    stages: dict = field(default_factory=dict)  # stage -> stage_stats line
    rules: list = field(default_factory=list)  # rule_complete lines
    pipeline: dict = field(default_factory=dict)  # pipeline_complete line
    events: dict = field(default_factory=dict)  # event -> count
    notes: list = field(default_factory=list)  # overlap disables etc.
    problems: list = field(default_factory=list)  # schema/invariant breaks
    jobs: dict = field(default_factory=dict)  # job id -> tagged-line count
    replicas: dict = field(default_factory=dict)  # replica -> line count
    workers: dict = field(default_factory=dict)  # worker -> line count

    @property
    def ok(self) -> bool:
        return not self.problems


def parse_ledger(path: str) -> tuple[list[dict], list[str]]:
    """(lines, problems): every syntactically valid line, plus a problem
    string per malformed one. An unreadable file raises LedgerError."""
    try:
        raw = open(path).read()
    except OSError as exc:
        raise LedgerError(f"cannot read ledger {path}: {exc}") from exc
    lines: list[dict] = []
    problems: list[str] = []
    for i, text in enumerate(raw.splitlines(), 1):
        if not text.strip():
            continue
        try:
            d = json.loads(text)
        except json.JSONDecodeError as exc:
            problems.append(f"line {i}: not JSON ({exc.msg})")
            continue
        if not isinstance(d, dict):
            problems.append(f"line {i}: not a JSON object")
            continue
        lines.append(d)
    return lines, problems


def _schema_problems(lines: list[dict]) -> list[str]:
    problems: list[str] = []
    if not lines:
        problems.append("empty ledger")
        return problems
    if lines[0].get("event") != "run_manifest":
        problems.append(
            "first event is "
            f"{lines[0].get('event')!r}, expected 'run_manifest' "
            "(every ledger opens with the run manifest)"
        )
    for i, d in enumerate(lines, 1):
        ev = d.get("event")
        if not isinstance(ev, str):
            problems.append(f"event {i}: missing 'event'")
            continue
        if not isinstance(d.get("ts"), (int, float)):
            problems.append(f"event {i} ({ev}): missing numeric 'ts'")
        for key in EVENT_SCHEMA.get(ev, ()):
            if key not in d:
                problems.append(f"event {i} ({ev}): missing required {key!r}")
    return problems


def _closure_problems(
    summary: "LedgerSummary",
    rel_tol: float = CLOSURE_REL_TOL,
    abs_tol: float = CLOSURE_ABS_TOL,
) -> list[str]:
    problems: list[str] = []
    pipeline_s = summary.pipeline.get("pipeline_s")
    # The rule-sum closure invariant is per pipeline run. A view holding
    # several runs (an elastic worker's sub-stream is one run per
    # processed slice) has no single pipeline_s denominator, so only the
    # per-stage phase coverage below is checkable there.
    runs = summary.events.get("pipeline_complete", 0)
    if runs == 1 and isinstance(pipeline_s, (int, float)) and summary.rules:
        rule_sum = sum(
            r.get("seconds", 0.0)
            for r in summary.rules
            if isinstance(r.get("seconds"), (int, float))
        )
        gap = abs(pipeline_s - rule_sum)
        if gap > max(rel_tol * pipeline_s, abs_tol):
            problems.append(
                f"closure: rule seconds sum to {rule_sum:.3f}s but "
                f"pipeline_s is {pipeline_s:.3f}s (gap {gap:.3f}s > "
                f"tolerance)"
            )
    for stage, st in summary.stages.items():
        wall = st.get("wall_seconds")
        unatt = st.get("unattributed_s")
        if not isinstance(wall, (int, float)) or not isinstance(
            unatt, (int, float)
        ):
            continue
        if unatt > max(rel_tol * wall, abs_tol):
            problems.append(
                f"closure: stage {stage!r} has {unatt:.3f}s unattributed "
                f"of a {wall:.3f}s wall (> tolerance) — phases do not "
                "cover the stage"
            )
    return problems


def summarize_ledger(
    path: str,
    rel_tol: float = CLOSURE_REL_TOL,
    abs_tol: float = CLOSURE_ABS_TOL,
    job: str | None = None,
    replica: str | None = None,
    worker: str | None = None,
) -> LedgerSummary:
    """Summarize one ledger.

    job: scope the view to one serve tenant — only lines tagged with
    that job id count (the run_manifest is kept for context). The
    scoped view is a comparison surface, not a validation one, so the
    whole-ledger schema checks are skipped (a BSSEQ_TPU_STATS_JOBS
    sub-sink, which has no run_manifest, summarizes cleanly too).

    replica: scope the view to one fleet replica's sub-stream the same
    way (a shared fleet ledger interleaves N replica processes; each
    stamps its lines via BSSEQ_TPU_REPLICA_ID). Composable with job —
    `--replica r1 --job j0003` is one tenant as served by one replica.

    Untargeted (job=None) views of a shared serve ledger tally
    job-tagged lines per tenant in `.jobs` (and replica-tagged lines
    per replica in `.replicas`) instead of merging them into the
    engine's stages — one tenant's or one replica's numbers never
    masquerade as the run's.

    worker: scope the view to one elastic worker's sub-stream exactly
    like replica (a shared elastic ledger interleaves the coordinator
    and N worker processes; each worker stamps its lines via
    BSSEQ_TPU_WORKER_ID)."""
    lines, problems = parse_ledger(path)
    s = LedgerSummary(path=path, job=job, replica=replica, worker=worker,
                      problems=problems)
    if job is None and replica is None and worker is None:
        s.problems.extend(_schema_problems(lines))
    for d in lines:
        ev = d.get("event")
        if not isinstance(ev, str):
            continue
        line_job = d.get("job")
        line_replica = d.get("replica")
        line_worker = d.get("worker")
        if replica is not None:
            if line_replica != replica:
                if ev == "run_manifest" and not s.manifest:
                    s.manifest = d
                continue
        elif line_replica is not None:
            s.replicas[str(line_replica)] = (
                s.replicas.get(str(line_replica), 0) + 1
            )
            s.events[ev] = s.events.get(ev, 0) + 1
            continue
        if worker is not None:
            if line_worker != worker:
                if ev == "run_manifest" and not s.manifest:
                    s.manifest = d
                continue
        elif line_worker is not None:
            s.workers[str(line_worker)] = (
                s.workers.get(str(line_worker), 0) + 1
            )
            s.events[ev] = s.events.get(ev, 0) + 1
            continue
        if job is not None:
            if ev == "run_manifest":
                if not s.manifest:
                    s.manifest = d
                continue
            if line_job != job:
                continue
        elif line_job is not None:
            s.jobs[str(line_job)] = s.jobs.get(str(line_job), 0) + 1
            s.events[ev] = s.events.get(ev, 0) + 1
            continue
        s.events[ev] = s.events.get(ev, 0) + 1
        if ev == "run_manifest" and not s.manifest:
            s.manifest = d
        elif ev == "stage_stats":
            s.stages[str(d.get("stage"))] = d
        elif ev == "rule_complete":
            s.rules.append(d)
        elif ev == "pipeline_complete":
            s.pipeline = d
        elif ev in ("overlap_pool_disabled", "host_pool_disabled"):
            pool = "overlap" if ev == "overlap_pool_disabled" else "host"
            s.notes.append(
                f"{pool} pool disabled ({d.get('stage', '?')}): "
                f"{d.get('reason', '?')}"
            )
    if job is not None and not s.events:
        s.problems.append(f"no ledger lines tagged job={job!r}")
    if replica is not None and not s.events:
        s.problems.append(f"no ledger lines tagged replica={replica!r}")
    if worker is not None and not s.events:
        s.problems.append(f"no ledger lines tagged worker={worker!r}")
    s.problems.extend(_closure_problems(s, rel_tol, abs_tol))
    return s


def check_ledger(
    path: str,
    rel_tol: float = CLOSURE_REL_TOL,
    abs_tol: float = CLOSURE_ABS_TOL,
) -> list[str]:
    """All schema + invariant problems for one ledger (empty = valid)."""
    return summarize_ledger(path, rel_tol, abs_tol).problems


# ---------------------------------------------------------------------------
# Formatting.

_STAGE_COLS = (
    ("wall_seconds", "wall_s"),
    ("host_s", "host_s"),
    ("device_s", "device_s"),
    ("stall_s", "stall_s"),
    ("chip_busy", "chip_busy"),
    ("unattributed_s", "unattr_s"),
    ("families_per_second", "fam/s"),
)


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:.3f}"
    if v is None:
        return "-"
    return str(v)


def _table(headers: list[str], rows: list[list[str]]) -> str:
    widths = [
        max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
        for i, h in enumerate(headers)
    ]
    def line(cells):
        return "  ".join(c.ljust(w) for c, w in zip(cells, widths)).rstrip()

    out = [line(headers), line(["-" * w for w in widths])]
    out.extend(line(r) for r in rows)
    return "\n".join(out)


_SW_PREFIX = "sort_write."


def _emit_breakdown_rows(s: LedgerSummary) -> list[list[str]]:
    """Per-stage sort_write sub-phase rows (bucket_route/bucket_sort/
    bucket_concat, deflate, merge...) plus a deflate-worker utilization
    row when the parallel codec tier ran — busy worker-seconds over
    workers x active span, so a 4-worker tier compressing 10% of the
    time reads 10%, not "4 workers". Empty when no stage attributed
    emit sub-phases (old ledgers stay byte-stable)."""
    rows: list[list[str]] = []
    for stage, st in sorted(s.stages.items()):
        subs = {
            k[len(_SW_PREFIX):-len("_seconds")]: st[k]
            for k in st
            if k.startswith(_SW_PREFIX)
            and k.endswith("_seconds")
            and isinstance(st[k], (int, float))
        }
        workers = st.get("pbgzf_workers")
        if not subs and not workers:
            continue
        for name in sorted(subs, key=lambda n: -subs[n]):
            rows.append([stage, name, _fmt(float(subs[name]))])
        if isinstance(workers, (int, float)) and workers:
            busy = subs.get("deflate", 0.0)
            span = subs.get("deflate_span", 0.0)
            util = f"{busy / (span * workers):.0%}" if span else "-"
            blocks = st.get("pbgzf_blocks")
            rows.append([
                stage,
                f"deflate workers={int(workers)} blocks={blocks or 0}",
                f"util {util}",
            ])
        buckets = st.get("bucket_count")
        if isinstance(buckets, (int, float)) and buckets:
            detail = f"buckets={int(buckets)}"
            if st.get("bucket_spill_runs"):
                detail += f" spill_runs={int(st['bucket_spill_runs'])}"
            if st.get("bucket_replayed"):
                detail += f" replayed={int(st['bucket_replayed'])}"
            rows.append([stage, detail, ""])
    return rows


def format_summary(s: LedgerSummary) -> str:
    out: list[str] = []
    m = s.manifest
    if m:
        out.append(
            f"run: rev={m.get('git_rev', '?')} backend={m.get('backend', '?')}"
            f" devices={m.get('device_count', '?')}"
            f" config={m.get('config_digest') or '-'}"
            f" component={m.get('component') or '-'}"
        )
    if s.job is not None:
        out.append(f"scoped to job: {s.job}")
    if s.replica is not None:
        out.append(f"scoped to replica: {s.replica}")
    if s.worker is not None:
        out.append(f"scoped to worker: {s.worker}")
    if s.jobs:
        out.append(
            f"serve jobs in ledger: {len(s.jobs)} "
            f"({', '.join(sorted(s.jobs))}) — scope with --job"
        )
    if s.replicas:
        out.append(
            f"fleet replicas in ledger: {len(s.replicas)} "
            f"({', '.join(sorted(s.replicas))}) — scope with --replica"
        )
    if s.workers:
        out.append(
            f"elastic workers in ledger: {len(s.workers)} "
            f"({', '.join(sorted(s.workers))}) — scope with --worker"
        )
    if s.stages:
        rows = []
        for stage, st in sorted(s.stages.items()):
            rows.append(
                [stage] + [_fmt(st.get(k)) for k, _ in _STAGE_COLS]
            )
        out.append("")
        out.append(_table(["stage"] + [h for _, h in _STAGE_COLS], rows))
    emit_rows = _emit_breakdown_rows(s)
    if emit_rows:
        out.append("")
        out.append(
            _table(["stage", "sort_write sub-phase", "seconds"], emit_rows)
        )
    if s.rules:
        rows = [
            [
                r.get("rule", "?"),
                _fmt(r.get("seconds")),
                "ran" if r.get("ran") else "skip",
            ]
            for r in s.rules
        ]
        out.append("")
        out.append(_table(["rule", "seconds", "status"], rows))
        if s.pipeline:
            out.append(f"pipeline_s: {_fmt(s.pipeline.get('pipeline_s'))}")
    for note in s.notes:
        out.append(f"note: {note}")
    out.append("")
    if s.problems:
        out.append(f"INVALID: {len(s.problems)} problem(s)")
        out.extend(f"  - {p}" for p in s.problems)
    else:
        out.append("ledger OK (schema valid, closure invariant holds)")
    return "\n".join(out)


def format_diff(a: LedgerSummary, b: LedgerSummary) -> str:
    """Two ledgers side by side, per stage and phase, with the B/A ratio —
    the shape of the SCALECPU-vs-SCALE_TPU comparison the verdicts make."""
    out = [
        f"A: {a.path} (backend={a.manifest.get('backend', '?')})",
        f"B: {b.path} (backend={b.manifest.get('backend', '?')})",
        "",
    ]
    stages = sorted(set(a.stages) | set(b.stages))
    rows = []
    for stage in stages:
        sa, sb = a.stages.get(stage, {}), b.stages.get(stage, {})
        for key, label in _STAGE_COLS:
            va, vb = sa.get(key), sb.get(key)
            if va is None and vb is None:
                continue
            ratio = "-"
            if isinstance(va, (int, float)) and isinstance(vb, (int, float)):
                ratio = f"{vb / va:.2f}x" if va else "-"
            rows.append([f"{stage}.{label}", _fmt(va), _fmt(vb), ratio])
    pa = a.pipeline.get("pipeline_s")
    pb = b.pipeline.get("pipeline_s")
    if pa is not None or pb is not None:
        ratio = (
            f"{pb / pa:.2f}x"
            if isinstance(pa, (int, float))
            and isinstance(pb, (int, float))
            and pa
            else "-"
        )
        rows.append(["pipeline_s", _fmt(pa), _fmt(pb), ratio])
    out.append(_table(["metric", "A", "B", "B/A"], rows))
    return "\n".join(out)
