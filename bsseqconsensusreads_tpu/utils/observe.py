"""Observability: the run ledger — spans, device-time accounting, manifests.

The reference's only observability is tqdm bars and one bwameth stderr log
(reference: main.snake.py:88-89; SURVEY.md §5.1/§5.5). This module is the
framework's observability subsystem:

* a **run ledger**: one JSONL stream per run, opened by a `run_manifest`
  line (git rev, backend, device count, config digest, env flags) so an
  artifact can never be separated from the run that produced it. Every
  line flows through ONE locked, line-flushed writer per sink — worker
  threads (the overlap engine times dispatch/fetch/retire off the main
  thread, pipeline.calling) and the main thread interleave whole lines,
  never bytes, and a crash loses at most the line being written
  (pairs with tests/test_crash_resume_pipeline.py).
* **nested, thread-aware spans**: `Metrics.timed` maintains a per-thread
  span stack, so concurrent accumulation from >=4 overlap workers and
  nested entry both land exactly once (`Metrics.spans` keys are
  slash-joined paths; `span_tree()` rebuilds the hierarchy).
* **device-time accounting**: phases are classified host / device / stall
  (`phase_summary`) so every stage reports `host_s` / `device_s` /
  `stall_s` and a derived `chip_busy` — the on-chip evidence VERDICT.md
  rounds 3-5 kept asking for. The per-batch device share is measured by
  timestamps around `block_until_ready` (pipeline.calling._device_wait).
* a **digest** per sink (`ledger_digest`): SHA-256 over the bytes this
  process wrote, embedded by bench.py in its artifact so a cpu-fallback
  number cannot masquerade as an on-chip one.

Activation is environment-driven so the CLI and library paths share it:

  BSSEQ_TPU_STATS=-            emit ledger JSON lines to stderr
  BSSEQ_TPU_STATS=/path.jsonl  append them to a file
  BSSEQ_TPU_TRACE=/path/dir    wrap stages in jax.profiler.trace(dir)
                               (view with tensorboard / xprof)

grafttrace adds two more planes on the same sinks:

* **causal trace contexts**: `mint_trace` creates {trace, span} at an
  admission point (router submit, serve admit, elastic split/lease);
  `bind_trace` installs it thread-locally so every `emit` in the dynamic
  extent is stamped with the trace/span ids; `span(name)` times a child
  span and emits one completed 'span' line {name, trace, span, parent,
  t0, t1, dur_s}. Contexts cross processes as the reserved `_trace` key
  of a framed-transport request (serve.transport.request injects it,
  serve.server binds it around dispatch), so `observe trace` can
  reassemble one causal tree per job/slice across router, replicas,
  coordinator, and workers.
* a **flight recorder**: a bounded ring of the most recent ledger
  records per process (BSSEQ_TPU_FLIGHT_RING, default 256), dumped as
  one 'flight_record' line on SIGUSR1, on GuardError exits, and on
  chaos-drill kills — post-mortem evidence beyond the last flushed line.

`python -m bsseqconsensusreads_tpu observe summarize|diff|check|trace|top`
consumes the ledgers (utils.ledger_tools, utils.trace_tools).
"""

from __future__ import annotations

import atexit
import collections
import contextlib
import hashlib
import json
import os
import sys
import threading
import time
from dataclasses import dataclass, field


def stats_sink() -> str | None:
    """Where ledger lines go: '-' (stderr), a path, or None (disabled)."""
    return os.environ.get("BSSEQ_TPU_STATS") or None


def job_sink_dir() -> str | None:
    """Directory for per-job ledger sub-sinks (BSSEQ_TPU_STATS_JOBS):
    when set, every job-tagged emit is mirrored to <dir>/<job>.jsonl —
    one standalone-shaped ledger per tenant — in addition to carrying a
    'job' field in the shared serve ledger."""
    return os.environ.get("BSSEQ_TPU_STATS_JOBS") or None


def job_sink(job: str) -> str | None:
    """The sub-sink path for one job id, or None when sub-sinks are off.
    Job ids are serve-assigned ([A-Za-z0-9_.-]); anything else is
    sanitized so a hostile tag cannot traverse out of the directory."""
    directory = job_sink_dir()
    if directory is None:
        return None
    safe = "".join(
        c if c.isalnum() or c in "._-" else "_" for c in str(job)
    ) or "_"
    return os.path.join(directory, f"{safe}.jsonl")


def replica_id() -> str | None:
    """This process's fleet replica identity (BSSEQ_TPU_REPLICA_ID, set
    by serve.fleet when it spawns a replica). When present, every emit
    is stamped with a 'replica' field — one shared fleet ledger carries
    N replicas as separable sub-streams (`observe summarize
    --replica`)."""
    return os.environ.get("BSSEQ_TPU_REPLICA_ID") or None


def replica_sink_dir() -> str | None:
    """Directory for per-replica ledger sub-sinks
    (BSSEQ_TPU_STATS_REPLICAS): when set, every replica-tagged emit is
    mirrored to <dir>/<replica>.jsonl — one standalone-shaped ledger
    per replica — in addition to the tag in the shared fleet ledger."""
    return os.environ.get("BSSEQ_TPU_STATS_REPLICAS") or None


def replica_sink(replica: str) -> str | None:
    """The sub-sink path for one replica id, sanitized like job_sink."""
    directory = replica_sink_dir()
    if directory is None:
        return None
    safe = "".join(
        c if c.isalnum() or c in "._-" else "_" for c in str(replica)
    ) or "_"
    return os.path.join(directory, f"{safe}.jsonl")


def worker_id() -> str | None:
    """This process's elastic worker identity (BSSEQ_TPU_WORKER_ID, set
    by elastic.coordinator when it spawns a worker, or by the worker
    itself on `cli elastic worker --join`). When present, every emit is
    stamped with a 'worker' field — one shared elastic ledger carries N
    workers as separable sub-streams (`observe summarize --worker`)."""
    return os.environ.get("BSSEQ_TPU_WORKER_ID") or None


def worker_sink_dir() -> str | None:
    """Directory for per-worker ledger sub-sinks
    (BSSEQ_TPU_STATS_WORKERS): when set, every worker-tagged emit is
    mirrored to <dir>/<worker>.jsonl — one standalone-shaped ledger per
    worker — in addition to the tag in the shared elastic ledger."""
    return os.environ.get("BSSEQ_TPU_STATS_WORKERS") or None


def worker_sink(worker: str) -> str | None:
    """The sub-sink path for one worker id, sanitized like job_sink."""
    directory = worker_sink_dir()
    if directory is None:
        return None
    safe = "".join(
        c if c.isalnum() or c in "._-" else "_" for c in str(worker)
    ) or "_"
    return os.path.join(directory, f"{safe}.jsonl")


def trace_dir() -> str | None:
    return os.environ.get("BSSEQ_TPU_TRACE") or None


def stderr_line(text: str) -> None:
    """THE sanctioned stderr escape hatch for human/CLI-facing summary
    lines. Package source outside this module must not print to stderr
    directly (lint guard: tests/test_observe.py) — diagnostics belong in
    the ledger, user-facing summaries go through here."""
    sys.stderr.write(text + "\n")
    sys.stderr.flush()


# ---------------------------------------------------------------------------
# The ledger writer: one locked, line-flushed, digesting writer per sink.


class LedgerWriter:
    """Serializes whole JSONL lines to one sink ('-' = stderr, else a
    file opened once in append mode). Concurrent worker-thread emits
    (the overlap engine) interleave lines, never bytes; every line is
    flushed so a hard crash (os._exit) loses at most the in-flight line.
    A running SHA-256 over the bytes THIS process wrote backs
    `ledger_digest` — the artifact-to-run binding bench.py embeds."""

    def __init__(self, sink: str):
        self.sink = sink
        self._lock = threading.Lock()
        self._fh = None  # lazy: no file until the first line
        self._sha = hashlib.sha256()
        self.lines = 0
        self.manifest_written = False

    def write_line(self, line: str) -> None:
        data = line + "\n"
        with self._lock:
            self._sha.update(data.encode())
            self.lines += 1
            if self.sink == "-":
                sys.stderr.write(data)
                sys.stderr.flush()
                return
            if self._fh is None:
                self._fh = open(self.sink, "a")
            self._fh.write(data)
            self._fh.flush()

    def digest(self) -> str:
        with self._lock:
            return self._sha.hexdigest()

    def flush(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.flush()

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


_WRITERS: dict[str, LedgerWriter] = {}
_WRITERS_LOCK = threading.Lock()


def _writer(sink: str) -> LedgerWriter:
    with _WRITERS_LOCK:
        w = _WRITERS.get(sink)
        if w is None:
            w = _WRITERS[sink] = LedgerWriter(sink)
        return w


def flush_sinks() -> None:
    """Flush every open ledger (registered atexit; also call at run
    boundaries so ledgers survive crashes of whatever follows)."""
    with _WRITERS_LOCK:
        writers = list(_WRITERS.values())
    for w in writers:
        w.flush()


def close_sinks() -> None:
    """Close and forget every writer (test isolation; a later emit to the
    same sink reopens it in append mode)."""
    with _WRITERS_LOCK:
        writers = list(_WRITERS.values())
        _WRITERS.clear()
    for w in writers:
        w.close()


atexit.register(flush_sinks)


def ledger_digest(sink: str | None = None) -> str | None:
    """SHA-256 (hex) over the ledger bytes THIS process wrote to `sink`,
    or None when no ledger is active / nothing was written."""
    sink = sink if sink is not None else stats_sink()
    if sink is None:
        return None
    with _WRITERS_LOCK:
        w = _WRITERS.get(sink)
    return w.digest() if w is not None and w.lines else None


def emit(
    event: str, payload: dict, sink: str | None = None,
    job: str | None = None,
) -> None:
    """Write one JSON line {ts, event, **payload} to the configured sink.
    Worker-thread emits carry a 'thread' field so span/phase lines stay
    attributable after the fact.

    job: tag the line with a job id (the serve engine's per-tenant
    sub-stream key — `observe summarize --job` / `diff` filter on it)
    and mirror it to the job's sub-sink when BSSEQ_TPU_STATS_JOBS is
    set. Job-tagged lines in the shared ledger are ignored by untargeted
    summaries, so one serve ledger carries every tenant without
    cross-talk.

    Fleet replicas (BSSEQ_TPU_REPLICA_ID in the environment) stamp
    every line with a 'replica' field the same way — the shared fleet
    ledger separates per replica (`observe summarize --replica`), and
    BSSEQ_TPU_STATS_REPLICAS mirrors each replica's lines to its own
    sub-sink. Elastic workers (BSSEQ_TPU_WORKER_ID) stamp 'worker'
    identically (`observe summarize --worker`,
    BSSEQ_TPU_STATS_WORKERS)."""
    sink = sink if sink is not None else stats_sink()
    sub = job_sink(job) if job is not None else None
    replica = replica_id()
    rsub = replica_sink(replica) if replica is not None else None
    worker = worker_id()
    wsub = worker_sink(worker) if worker is not None else None
    if sink is None and sub is None and rsub is None and wsub is None:
        return
    record = {"ts": round(time.time(), 3), "event": event}
    cur = threading.current_thread()
    if cur is not threading.main_thread():
        record["thread"] = cur.name
    record.update(payload)
    if job is not None:
        record["job"] = job
    if replica is not None:
        record["replica"] = replica
    if worker is not None:
        record["worker"] = worker
    ctx = getattr(_TRACE_TLS, "ctx", None)
    if ctx is not None and "trace" not in record:
        # stamp the bound causal context; explicit payload keys win so
        # 'span' lines (which carry their own ids) pass through untouched
        record["trace"] = ctx["trace"]
        record.setdefault("span", ctx["span"])
    if event != "flight_record":
        with _FLIGHT_LOCK:
            _flight_ring().append(record)
    line = json.dumps(record)
    if sink is not None:
        _writer(sink).write_line(line)
    for mirror in (sub, rsub, wsub):
        if mirror is not None:
            os.makedirs(os.path.dirname(mirror), exist_ok=True)
            _writer(mirror).write_line(line)


# ---------------------------------------------------------------------------
# grafttrace: cross-process causal contexts and completed-span emission.
#
# A trace context is a two-key dict {trace, span}: `trace` is the causal
# tree id ("<kind>-<key>-<6 hex>", kind in {job, slice, proc}), `span`
# the CURRENT node in that tree. Contexts are minted once per job/slice
# at admission, bound thread-locally for the dynamic extent of work on
# that job/slice, and shipped across processes as the `_trace` field of
# a framed-transport request. Span durations use wall-clock time.time()
# (not monotonic) because cross-process monotonic clocks do not compare;
# the analysis layer (utils.trace_tools) orders and subtracts them.

_TRACE_TLS = threading.local()
_SPAN_LOCK = threading.Lock()
_SPAN_SEQ = [0]
_FLIGHT_LOCK = threading.Lock()
_FLIGHT: collections.deque | None = None
_PROC_TRACE: dict | None = None

#: trace-id kinds whose trees must reach a terminal event (job retired /
#: slice merged) — `observe check` treats other kinds (proc overhead
#: roots) as terminal-exempt.
TRACE_TERMINAL_KINDS = frozenset({"job", "slice"})


def _next_span_id() -> str:
    """Process-unique span id: '<pid hex>.<seq hex>' — two processes can
    never collide, and within a process the locked sequence is total."""
    with _SPAN_LOCK:
        _SPAN_SEQ[0] += 1
        n = _SPAN_SEQ[0]
    return f"{os.getpid():x}.{n:x}"


def mint_trace(kind: str, key: str, job: str | None = None, **fields) -> dict:
    """Mint a new trace context at an admission point and emit its root
    span (zero duration, no parent) so every later child resolves. Returns
    the context dict; the caller persists/ships it (`_trace` on the wire,
    a field in slices.json, an attribute on the job object)."""
    ctx = {
        "trace": f"{kind}-{key}-{os.urandom(3).hex()}",
        "span": _next_span_id(),
    }
    now = round(time.time(), 3)
    emit(
        "span",
        {
            "name": f"{kind}_admit", "trace": ctx["trace"],
            "span": ctx["span"], "t0": now, "t1": now, "dur_s": 0.0,
            **fields,
        },
        job=job,
    )
    return ctx


def current_trace() -> dict | None:
    """The thread's bound trace context (a copy), or None."""
    ctx = getattr(_TRACE_TLS, "ctx", None)
    return dict(ctx) if ctx is not None else None


def trace_kind(trace_id: str) -> str:
    """The kind segment of a trace id ('job-j0001-a1b2c3' -> 'job')."""
    return str(trace_id).split("-", 1)[0]


@contextlib.contextmanager
def bind_trace(ctx: dict | None):
    """Install `ctx` as the thread's trace context for the block. A falsy
    or malformed ctx (no 'trace'/'span') binds nothing and yields None —
    callers at trust boundaries (server dispatch) pass whatever arrived.
    The previous binding is restored on exit."""
    if not isinstance(ctx, dict) or "trace" not in ctx or "span" not in ctx:
        yield None
        return
    bound = {"trace": str(ctx["trace"]), "span": str(ctx["span"])}
    prev = getattr(_TRACE_TLS, "ctx", None)
    _TRACE_TLS.ctx = bound
    try:
        yield bound
    finally:
        _TRACE_TLS.ctx = prev


@contextlib.contextmanager
def span(
    name: str, ctx: dict | None = None, job: str | None = None, **fields
):
    """Time a child span of `ctx` (default: the bound context). Binds the
    child for the body — nested spans and emits inside parent correctly —
    and emits ONE completed 'span' line on exit. With no context in scope
    this is a no-op yielding None: unarmed/untraced paths stay one branch."""
    parent = ctx if ctx is not None else getattr(_TRACE_TLS, "ctx", None)
    if not isinstance(parent, dict) or "trace" not in parent:
        yield None
        return
    child = {"trace": parent["trace"], "span": _next_span_id()}
    t0 = time.time()
    prev = getattr(_TRACE_TLS, "ctx", None)
    _TRACE_TLS.ctx = child
    try:
        yield child
    finally:
        _TRACE_TLS.ctx = prev
        t1 = time.time()
        emit(
            "span",
            {
                "name": name, "trace": child["trace"], "span": child["span"],
                "parent": parent["span"], "t0": round(t0, 3),
                "t1": round(t1, 3), "dur_s": round(t1 - t0, 6), **fields,
            },
            job=job,
        )


def emit_span(
    name: str, t0: float, t1: float, ctx: dict | None = None,
    job: str | None = None, **fields,
) -> str | None:
    """Emit a completed span for an EXTERNALLY measured wall-clock window
    (e.g. replica spawn→ready, measured around a subprocess). Returns the
    span id, or None when no context is in scope."""
    parent = ctx if ctx is not None else getattr(_TRACE_TLS, "ctx", None)
    if not isinstance(parent, dict) or "trace" not in parent:
        return None
    sid = _next_span_id()
    emit(
        "span",
        {
            "name": name, "trace": parent["trace"], "span": sid,
            "parent": parent["span"], "t0": round(t0, 3), "t1": round(t1, 3),
            "dur_s": round(t1 - t0, 6), **fields,
        },
        job=job,
    )
    return sid


def proc_trace() -> dict:
    """The lazily minted per-process overhead trace ('proc-pid<N>-…'):
    the parent for spans not owned by any one job/slice — worker spawn,
    jax import, merge. Proc trees are exempt from the terminal-state
    check but feed the overhead bucket table like any other span."""
    global _PROC_TRACE
    with _SPAN_LOCK:
        ctx = _PROC_TRACE
    if ctx is None:
        ctx = mint_trace("proc", f"pid{os.getpid()}")
        with _SPAN_LOCK:
            if _PROC_TRACE is None:
                _PROC_TRACE = ctx
            else:
                ctx = _PROC_TRACE
    return dict(ctx)


# ---------------------------------------------------------------------------
# Flight recorder: the last N ledger records, dumped on demand/crash.


def _flight_ring() -> collections.deque:
    global _FLIGHT
    if _FLIGHT is None:
        try:
            cap = int(os.environ.get("BSSEQ_TPU_FLIGHT_RING", "256"))
        except ValueError:
            cap = 256
        _FLIGHT = collections.deque(maxlen=max(cap, 1))
    return _FLIGHT


def flight_dump(reason: str) -> int:
    """Dump the ring as ONE 'flight_record' ledger line {reason, count,
    events} and flush. Called from SIGUSR1 handlers, the CLI GuardError
    path, and failpoint kill actions; safe (a no-op count of 0) when the
    ring is empty or no sink is armed. Returns the event count dumped."""
    with _FLIGHT_LOCK:
        recent = list(_flight_ring())
    emit("flight_record", {"reason": reason, "count": len(recent),
                           "events": recent})
    flush_sinks()
    return len(recent)


def install_flight_signal() -> None:
    """Install the SIGUSR1 → flight_dump handler (long-lived serve /
    router / worker processes). Best-effort: non-main-thread or platform
    refusal leaves the process untouched."""
    try:
        import signal

        signal.signal(
            signal.SIGUSR1, lambda _sig, _frm: flight_dump("sigusr1")
        )
    except (ValueError, OSError, AttributeError):
        pass


# ---------------------------------------------------------------------------
# Run manifest: the line that opens every ledger.


_GIT_REV_CACHE: list[str] = []


def _git_rev() -> str:
    if not _GIT_REV_CACHE:
        rev = "unknown"
        try:
            import subprocess

            repo = os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))
            ))
            cp = subprocess.run(
                ["git", "-C", repo, "rev-parse", "--short", "HEAD"],
                capture_output=True, text=True, timeout=5,
            )
            if cp.returncode == 0 and cp.stdout.strip():
                rev = cp.stdout.strip()
        except Exception:  # noqa: BLE001 — manifest must never fail a run
            pass
        _GIT_REV_CACHE.append(rev)
    return _GIT_REV_CACHE[0]


def config_digest(obj) -> str:
    """Stable short digest of a config object (dataclass or anything
    repr-able) for the run manifest — two ledgers with the same digest ran
    the same configuration."""
    import dataclasses

    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        text = json.dumps(
            dataclasses.asdict(obj), sort_keys=True, default=repr
        )
    else:
        text = repr(obj)
    return hashlib.sha256(text.encode()).hexdigest()[:12]


def _env_flags() -> dict:
    keys = sorted(
        k for k in os.environ
        if k.startswith("BSSEQ_TPU_") or k in ("JAX_PLATFORMS", "XLA_FLAGS")
    )
    return {k: os.environ[k] for k in keys}


def run_manifest(
    config_digest: str | None = None,
    component: str = "",
    query_devices: bool = True,
    extra: dict | None = None,
) -> dict:
    """The manifest payload. query_devices=False skips the jax backend
    probe — callers that must never risk initializing a dead-tunnel
    backend from the parent process (bench.py's attempt ladder) pass
    False and record the measured backend as a later event instead."""
    from bsseqconsensusreads_tpu import __version__

    backend, device_count = "unqueried", 0
    if query_devices:
        try:
            import jax

            backend = jax.default_backend()
            device_count = jax.device_count()
        except Exception:  # noqa: BLE001 — manifest must never fail a run
            backend, device_count = "unknown", 0
    payload = {
        "git_rev": _git_rev(),
        "version": __version__,
        "backend": backend,
        "device_count": device_count,
        "config_digest": config_digest or "",
        "component": component,
        "pid": os.getpid(),
        "argv": " ".join(sys.argv[:6]),
        "env": _env_flags(),
    }
    # elastic identity: stamped so `observe diff` can line up worker
    # sub-streams across hosts (the replica id gets the same treatment
    # implicitly via _env_flags; these two are first-class because the
    # worker/coordinator pairing is what the diff joins on)
    if worker_id() is not None:
        payload["worker_id"] = worker_id()
    coord = os.environ.get("BSSEQ_TPU_COORDINATOR_ADDR")
    if coord:
        payload["coordinator_addr"] = coord
    if extra:
        payload.update(extra)
    return payload


def open_ledger(
    sink: str | None = None,
    config_digest: str | None = None,
    component: str = "",
    query_devices: bool = True,
    **extra,
) -> bool:
    """Write the run-manifest line that opens a ledger (once per sink per
    process — re-entrant callers share the manifest). Returns whether a
    sink is active at all."""
    sink = sink if sink is not None else stats_sink()
    if sink is None:
        return False
    w = _writer(sink)
    with w._lock:
        if w.manifest_written:
            return True
        w.manifest_written = True
    emit(
        "run_manifest",
        run_manifest(config_digest, component, query_devices, extra or None),
        sink=sink,
    )
    return True


# ---------------------------------------------------------------------------
# Metrics: counters + nested thread-aware span timers.

#: Phase names whose wall is DEVICE time: the kernel dispatch (H2D +
#: enqueue), the block_until_ready wait (device/tunnel still owns the
#: batch — pipeline.calling._device_wait), and the D2H fetch. Everything
#: else is host work except 'stall' (main thread blocked on an overlap
#: worker — the pipeline's unhidden remainder).
DEVICE_PHASES = frozenset({"kernel", "device_wait", "fetch"})
STALL_PHASES = frozenset({"stall"})


@dataclass
class Metrics:
    """Named counters + nested, thread-aware span timers for one run.

    Counters accumulate (records moved, bytes packed); timers accumulate
    seconds per named phase via the `timed` context manager. as_dict()
    flattens to one JSON-able payload; rates are derived, not stored.

    Thread-safe accumulation: the overlap pipeline (pipeline.calling)
    times phases from worker threads concurrently with the main thread —
    the read-modify-write on a shared key must not lose seconds. Each
    thread keeps its own span stack (nested `timed` calls record
    slash-joined paths in `spans`); `owner_seconds` additionally tracks
    OUTERMOST spans on the thread that created the Metrics, which is what
    the ledger-closure invariant sums against the stage wall (worker and
    nested seconds would double-count the owner's timeline).
    """

    counters: dict = field(default_factory=dict)
    seconds: dict = field(default_factory=dict)
    #: slash-joined span path -> [seconds, calls]
    spans: dict = field(default_factory=dict)
    #: outermost-span seconds on the owning thread only (closure checks)
    owner_seconds: dict = field(default_factory=dict)
    clock: object = field(default=time.monotonic, repr=False, compare=False)
    _lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False, compare=False
    )
    _owner: int = field(
        default_factory=threading.get_ident, repr=False, compare=False
    )
    _tls: threading.local = field(
        default_factory=threading.local, repr=False, compare=False
    )

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    @contextlib.contextmanager
    def timed(self, name: str):
        stack = self._stack()
        path = "/".join(stack + [name])
        outermost = not stack
        stack.append(name)
        t0 = self.clock()
        try:
            yield
        finally:
            dt = self.clock() - t0
            stack.pop()
            self._accumulate(name, path, dt, outermost)

    def _accumulate(
        self, name: str, path: str, dt: float, outermost: bool
    ) -> None:
        """The ONE locked read-modify-write for all timer entry points —
        `timed` and `add_seconds` share it, so the concurrency contract is
        tested in one place."""
        with self._lock:
            self.seconds[name] = self.seconds.get(name, 0.0) + dt
            rec = self.spans.get(path)
            if rec is None:
                self.spans[path] = [dt, 1]
            else:
                rec[0] += dt
                rec[1] += 1
            if outermost and threading.get_ident() == self._owner:
                self.owner_seconds[name] = (
                    self.owner_seconds.get(name, 0.0) + dt
                )

    def add_seconds(self, name: str, dt: float) -> None:
        """Accumulate an externally measured duration (e.g. the stage
        writers' post-stream merge share, computed as rule wall minus
        stream-active wall — pipeline.stages)."""
        self._accumulate(name, name, dt, outermost=not self._stack())

    def add_sub_seconds(self, name: str, dt: float) -> None:
        """Accumulate a SUB-PHASE attribution: a dotted name
        ('emit.pack', 'sort_write.merge_bgzf') measuring a share of time
        already booked under its parent phase. Dotted names are excluded
        from phase_summary's host/device/stall sums and never touch
        owner_seconds, so they can never double-count the timeline —
        they exist purely so the artifact can say WHERE inside a phase
        the seconds went (the PR-6 emit/sort_write sub-attribution)."""
        if "." not in name:
            raise ValueError(
                f"sub-phase name must be dotted (parent.child), got {name!r}"
            )
        self._accumulate(name, name, dt, outermost=False)

    def rate(self, counter: str, timer: str) -> float:
        dt = self.seconds.get(timer, 0.0)
        return self.counters.get(counter, 0) / dt if dt else 0.0

    def span_tree(self) -> dict:
        """The span hierarchy: {name: {seconds, calls, children: {...}}},
        rebuilt from the slash-joined paths. Concurrent same-name spans
        from different threads merge into one node (their seconds sum —
        utilization, not wall)."""
        with self._lock:
            snapshot = dict(self.spans)
        tree: dict = {}
        for path, (secs, calls) in sorted(snapshot.items()):
            node_map = tree
            parts = path.split("/")
            for i, part in enumerate(parts):
                node = node_map.setdefault(
                    part, {"seconds": 0.0, "calls": 0, "children": {}}
                )
                if i == len(parts) - 1:
                    node["seconds"] = round(node["seconds"] + secs, 6)
                    node["calls"] += calls
                node_map = node["children"]
        return tree

    def phase_summary(self, wall: float) -> dict:
        """Classify accumulated phases into the stage report the ledger
        carries: host_s / device_s / stall_s, the derived chip_busy
        (device seconds per wall second — can exceed 1 with multiple
        in-flight batches), and unattributed_s (the owner thread's
        timeline not covered by any outermost span — the closure
        invariant bounds this: `observe check`)."""
        with self._lock:
            secs = dict(self.seconds)
            owner = dict(self.owner_seconds)
        # dotted names are sub-phase attributions INSIDE a parent phase
        # (Metrics.add_sub_seconds) — summing them alongside the parent
        # would double-count the same wall
        device_s = sum(v for k, v in secs.items() if k in DEVICE_PHASES)
        stall_s = sum(v for k, v in secs.items() if k in STALL_PHASES)
        host_s = sum(
            v for k, v in secs.items()
            if k not in DEVICE_PHASES and k not in STALL_PHASES
            and "." not in k
        )
        attributed = sum(owner.values())
        return {
            "host_s": round(host_s, 3),
            "device_s": round(device_s, 3),
            "stall_s": round(stall_s, 3),
            "chip_busy": round(device_s / wall, 4) if wall > 0 else 0.0,
            "unattributed_s": round(max(wall - attributed, 0.0), 3),
        }

    def as_dict(self) -> dict:
        with self._lock:
            out = dict(self.counters)
            out.update(
                {f"{k}_seconds": round(v, 3) for k, v in self.seconds.items()}
            )
        return out


@contextlib.contextmanager
def maybe_trace(label: str, directory: str | None = None):
    """jax.profiler.trace when BSSEQ_TPU_TRACE (or `directory`) is set, else a
    no-op — stages call this unconditionally."""
    directory = directory if directory is not None else trace_dir()
    if not directory:
        yield
        return
    import jax

    path = os.path.join(directory, label)
    os.makedirs(path, exist_ok=True)
    with jax.profiler.trace(path):
        yield


def emit_stage_stats(
    stage_stats: dict, sample: str | None = None, job: str | None = None
) -> None:
    """Emit one 'stage_stats' line per pipeline stage (StageStats.as_dict)."""
    for stage, stats in stage_stats.items():
        payload = {"stage": stage, **stats.as_dict()}
        if sample:
            payload["sample"] = sample
        emit("stage_stats", payload, job=job)
