"""Observability: structured per-stage stats, counters, and profiler traces.

The reference's only observability is tqdm bars and one bwameth stderr log
(reference: main.snake.py:88-89; SURVEY.md §5.1/§5.5). This framework emits
structured JSON-line stats per pipeline stage (families/sec, pad waste,
batches, leftovers — pipeline.calling.StageStats) plus arbitrary named
counters, and can wrap any stage in a JAX profiler trace for kernel-level
timing.

Activation is environment-driven so the CLI and library paths share it:

  BSSEQ_TPU_STATS=-            emit stats JSON lines to stderr
  BSSEQ_TPU_STATS=/path.jsonl  append them to a file
  BSSEQ_TPU_TRACE=/path/dir    wrap stages in jax.profiler.trace(dir)
                               (view with tensorboard / xprof)
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import threading
import time
from dataclasses import dataclass, field


def stats_sink() -> str | None:
    """Where stats lines go: '-' (stderr), a path, or None (disabled)."""
    return os.environ.get("BSSEQ_TPU_STATS") or None


def trace_dir() -> str | None:
    return os.environ.get("BSSEQ_TPU_TRACE") or None


def emit(event: str, payload: dict, sink: str | None = None) -> None:
    """Write one JSON line {ts, event, **payload} to the configured sink."""
    sink = sink if sink is not None else stats_sink()
    if sink is None:
        return
    line = json.dumps({"ts": round(time.time(), 3), "event": event, **payload})
    if sink == "-":
        print(line, file=sys.stderr)
    else:
        with open(sink, "a") as fh:
            fh.write(line + "\n")


@dataclass
class Metrics:
    """Named counters + wall-clock timers for one run.

    Counters accumulate (records moved, bytes packed); timers accumulate
    seconds per named phase via the `timed` context manager. as_dict()
    flattens to one JSON-able payload; rates are derived, not stored.

    Thread-safe accumulation: the overlap pipeline (pipeline.calling) times
    phases from worker threads concurrently with the main thread — the
    read-modify-write on a shared key must not lose seconds.
    """

    counters: dict = field(default_factory=dict)
    seconds: dict = field(default_factory=dict)
    _lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False, compare=False
    )

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    @contextlib.contextmanager
    def timed(self, name: str):
        t0 = time.monotonic()
        try:
            yield
        finally:
            dt = time.monotonic() - t0
            with self._lock:
                self.seconds[name] = self.seconds.get(name, 0.0) + dt

    def add_seconds(self, name: str, dt: float) -> None:
        """Accumulate an externally measured duration (e.g. the stage
        writers' post-stream merge share, computed as rule wall minus
        stream-active wall — pipeline.stages)."""
        with self._lock:
            self.seconds[name] = self.seconds.get(name, 0.0) + dt

    def rate(self, counter: str, timer: str) -> float:
        dt = self.seconds.get(timer, 0.0)
        return self.counters.get(counter, 0) / dt if dt else 0.0

    def as_dict(self) -> dict:
        out = {k: v for k, v in self.counters.items()}
        out.update({f"{k}_seconds": round(v, 3) for k, v in self.seconds.items()})
        return out


@contextlib.contextmanager
def maybe_trace(label: str, directory: str | None = None):
    """jax.profiler.trace when BSSEQ_TPU_TRACE (or `directory`) is set, else a
    no-op — stages call this unconditionally."""
    directory = directory if directory is not None else trace_dir()
    if not directory:
        yield
        return
    import jax

    path = os.path.join(directory, label)
    os.makedirs(path, exist_ok=True)
    with jax.profiler.trace(path):
        yield


def emit_stage_stats(stage_stats: dict, sample: str | None = None) -> None:
    """Emit one 'stage_stats' line per pipeline stage (StageStats.as_dict)."""
    for stage, stats in stage_stats.items():
        payload = {"stage": stage, **stats.as_dict()}
        if sample:
            payload["sample"] = sample
        emit("stage_stats", payload)
