"""Scalar-Python oracles for differential testing of the JAX kernels.

Deliberately written as naive per-base loops with stdlib floats — an
independent transcription of the documented model semantics (SURVEY.md §4:
"unit tests of the pure-JAX transforms against scalar-Python oracles").
These are also the measured "CPU reference path" stand-in for benchmarks,
playing the role of the reference's pysam/JVM per-read loops.
"""

from __future__ import annotations

import math

NBASE = 4


def _perr(q: float) -> float:
    return 10.0 ** (-q / 10.0)


def _two_trials(p1: float, p2: float) -> float:
    return p1 * (1 - p2) + (1 - p1) * p2 + (2.0 / 3.0) * p1 * p2


def _to_phred(p: float) -> float:
    p = min(max(p, 1e-12), 1.0)
    return min(max(-10.0 * math.log10(p), 2.0), 93.0)


def oracle_overlap_cocall(bases, quals):
    """bases/quals: nested lists [T][2][W]. Returns updated copies."""
    T = len(bases)
    W = len(bases[0][0])
    out_b = [[list(bases[t][r]) for r in range(2)] for t in range(T)]
    out_q = [[list(quals[t][r]) for r in range(2)] for t in range(T)]
    for t in range(T):
        for w in range(W):
            b1, b2 = bases[t][0][w], bases[t][1][w]
            q1, q2 = float(quals[t][0][w]), float(quals[t][1][w])
            if b1 == NBASE or b2 == NBASE:
                continue
            if b1 == b2:
                for r in range(2):
                    out_q[t][r][w] = q1 + q2
            else:
                if q1 == q2:
                    for r in range(2):
                        out_b[t][r][w] = NBASE
                        out_q[t][r][w] = 0.0
                else:
                    win = b1 if q1 > q2 else b2
                    for r in range(2):
                        out_b[t][r][w] = win
                        out_q[t][r][w] = abs(q1 - q2)
    return out_b, out_q


def oracle_column_vote(
    column_bases,
    column_quals,
    error_rate_pre_umi=45.0,
    error_rate_post_umi=30.0,
    min_input_base_quality=0,
    min_consensus_base_quality=0,
):
    """One window column: lists of base codes / phred quals (one per read).

    Returns (base, qual, depth, errors) with base==4 when uncalled.
    """
    p_post = _perr(error_rate_post_umi)
    ll = [0.0, 0.0, 0.0, 0.0]
    obs = []
    for b, q in zip(column_bases, column_quals):
        if b == NBASE or q < min_input_base_quality:
            continue
        p = _two_trials(_perr(float(q)), p_post)
        p = min(max(p, 1e-12), 1.0 - 1e-7)
        obs.append(b)
        for cand in range(4):
            ll[cand] += math.log1p(-p) if cand == b else math.log(p / 3.0)
    depth = len(obs)
    if depth == 0:
        return NBASE, 2, 0, 0
    cons = max(range(4), key=lambda c: ll[c])
    m = max(ll)
    denom = sum(math.exp(v - m) for v in ll)
    p_cons = 1.0 - math.exp(ll[cons] - m) / denom
    p_final = _two_trials(p_cons, _perr(error_rate_pre_umi))
    qual = _to_phred(p_final)
    if qual < min_consensus_base_quality:
        return NBASE, 2, depth, 0
    errors = sum(1 for b in obs if b != cons)
    return cons, int(round(qual)), depth, errors


def oracle_molecular_family(bases, quals, params) -> dict:
    """Whole family [T][2][W] -> {'base','qual','depth','errors'}: [2][W]."""
    if params.consensus_call_overlapping_bases:
        bases, quals = oracle_overlap_cocall(bases, quals)
    T = len(bases)
    W = len(bases[0][0])
    out = {k: [[0] * W, [0] * W] for k in ("base", "qual", "depth", "errors")}
    for role in range(2):
        for w in range(W):
            col_b = [bases[t][role][w] for t in range(T)]
            col_q = [quals[t][role][w] for t in range(T)]
            b, q, d, e = oracle_column_vote(
                col_b,
                col_q,
                params.error_rate_pre_umi,
                params.error_rate_post_umi,
                params.min_input_base_quality,
                params.min_consensus_base_quality,
            )
            out["base"][role][w] = b
            out["qual"][role][w] = q
            out["depth"][role][w] = d
            out["errors"][role][w] = e
    return out
