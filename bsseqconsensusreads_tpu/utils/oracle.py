"""Scalar-Python oracles for differential testing of the JAX kernels.

Deliberately written as naive per-base loops with stdlib floats — an
independent transcription of the documented model semantics (SURVEY.md §4:
"unit tests of the pure-JAX transforms against scalar-Python oracles").
These are also the measured "CPU reference path" stand-in for benchmarks,
playing the role of the reference's pysam/JVM per-read loops.
"""

from __future__ import annotations

import math

NBASE = 4


def _perr(q: float) -> float:
    return 10.0 ** (-q / 10.0)


def _two_trials(p1: float, p2: float) -> float:
    return p1 * (1 - p2) + (1 - p1) * p2 + (2.0 / 3.0) * p1 * p2


def _to_phred(p: float) -> float:
    p = min(max(p, 1e-12), 1.0)
    return min(max(-10.0 * math.log10(p), 2.0), 93.0)


def oracle_overlap_cocall(bases, quals):
    """bases/quals: nested lists [T][2][W]. Returns updated copies."""
    T = len(bases)
    W = len(bases[0][0])
    out_b = [[list(bases[t][r]) for r in range(2)] for t in range(T)]
    out_q = [[list(quals[t][r]) for r in range(2)] for t in range(T)]
    for t in range(T):
        for w in range(W):
            b1, b2 = bases[t][0][w], bases[t][1][w]
            q1, q2 = float(quals[t][0][w]), float(quals[t][1][w])
            if b1 == NBASE or b2 == NBASE:
                continue
            if b1 == b2:
                for r in range(2):
                    out_q[t][r][w] = q1 + q2
            else:
                if q1 == q2:
                    for r in range(2):
                        out_b[t][r][w] = NBASE
                        out_q[t][r][w] = 0.0
                else:
                    win = b1 if q1 > q2 else b2
                    for r in range(2):
                        out_b[t][r][w] = win
                        out_q[t][r][w] = abs(q1 - q2)
    return out_b, out_q


def oracle_column_vote(
    column_bases,
    column_quals,
    error_rate_pre_umi=45.0,
    error_rate_post_umi=30.0,
    min_input_base_quality=0,
    min_consensus_base_quality=0,
):
    """One window column: lists of base codes / phred quals (one per read).

    Returns (base, qual, depth, errors) with base==4 when uncalled.
    """
    p_post = _perr(error_rate_post_umi)
    ll = [0.0, 0.0, 0.0, 0.0]
    obs = []
    for b, q in zip(column_bases, column_quals):
        if b == NBASE or q < min_input_base_quality:
            continue
        p = _two_trials(_perr(float(q)), p_post)
        p = min(max(p, 1e-12), 1.0 - 1e-7)
        obs.append(b)
        for cand in range(4):
            ll[cand] += math.log1p(-p) if cand == b else math.log(p / 3.0)
    depth = len(obs)
    if depth == 0:
        return NBASE, 2, 0, 0
    cons = max(range(4), key=lambda c: ll[c])
    m = max(ll)
    # canonical ascending-order denominator, matching the kernels'
    # permutation-invariant summation (models/molecular.vote_finalize)
    e = sorted(math.exp(v - m) for v in ll)
    denom = ((e[0] + e[1]) + e[2]) + e[3]
    p_cons = 1.0 - math.exp(ll[cons] - m) / denom
    p_final = _two_trials(p_cons, _perr(error_rate_pre_umi))
    qual = _to_phred(p_final)
    if qual < min_consensus_base_quality:
        return NBASE, 2, depth, 0
    errors = sum(1 for b in obs if b != cons)
    return cons, int(round(qual)), depth, errors


def oracle_convert_read(seq: str, quals, pos: int, genome: str,
                        pos0: str = "skip"):
    """Scalar oracle for the B-strand AG->CT conversion (SURVEY.md §3.2).

    seq is the softclip-trimmed read (genome-forward orientation), quals a
    list of Phred ints, pos its 0-based mapped position. Returns
    (seq, quals, pos, la, rd). Mirrors the reference loop exactly — mutable
    list, skip after a CpG pair rewrite — except at pos==0 under the
    default pos0='skip', where the framework deliberately skips the prepend
    (see ops/convert.py docstring) instead of shifting the read out of
    register; pos0='shift' reproduces the reference's
    prepend-and-clamp register shift (tools/1.convert_AG_to_CT.py:87-92).
    """
    prepend = pos > 0 or pos0 == "shift"
    if prepend:
        new_pos = max(pos - 1, 0)
        s = list("N" + seq)
        q = [40] + list(quals)
    else:
        new_pos = pos
        s = list(seq)
        q = list(quals)
    L = len(s)
    # the reference upper-cases its fetch (tools/1.convert_AG_to_CT.py:107)
    ref = genome[new_pos : new_pos + L + 1].upper()
    ref += "N" * (L + 1 - len(ref))
    if prepend:
        s[0] = ref[0]
    i = 0
    while i < L:
        b, r = s[i], ref[i]
        if b == "A":
            if r == "G":
                s[i] = "G"
        elif b == "C":
            if r == "C" and ref[i + 1] == "G":
                if i + 1 < L and s[i + 1] == "A":
                    s[i] = "T"
                    s[i + 1] = "G"
                    i += 1
            else:
                s[i] = "T"
        i += 1
    rd = 0
    if ref[L] == "G" and s and s[-1] == "C":
        s.pop()
        q.pop()
        rd = 1
    return "".join(s), q, new_pos, int(prepend), rd


def oracle_extend_group(reads: dict) -> dict:
    """Scalar oracle for gap extension (SURVEY.md §3.3).

    reads: {flag: {'seq': str, 'qual': list[int], 'pos': int,
                   'la': int, 'rd': int}} for flags among (99, 163, 83, 147).
    Returns the updated dict (copies). Pairs (99,163) and (83,147); the read
    with flag in {83,163} is the 'left' (converted) one. LA(left)==1 prepends
    left's first base to the right read (start-1); RD(left)==1 appends the
    right read's last base to the left read.
    """
    out = {f: dict(r) for f, r in reads.items()}
    for left_flag, right_flag in ((163, 99), (83, 147)):
        if left_flag not in out or right_flag not in out:
            continue
        left, right = out[left_flag], out[right_flag]
        if left["la"] == 1:
            right["seq"] = left["seq"][0] + right["seq"]
            right["qual"] = [left["qual"][0]] + list(right["qual"])
            right["pos"] -= 1
        if left["rd"] == 1:
            left["seq"] = left["seq"] + right["seq"][-1]
            left["qual"] = list(left["qual"]) + [right["qual"][-1]]
    return out


def oracle_molecular_family(bases, quals, params) -> dict:
    """Whole family [T][2][W] -> {'base','qual','depth','errors'}: [2][W]."""
    if params.consensus_call_overlapping_bases:
        bases, quals = oracle_overlap_cocall(bases, quals)
    T = len(bases)
    W = len(bases[0][0])
    out = {k: [[0] * W, [0] * W] for k in ("base", "qual", "depth", "errors")}
    for role in range(2):
        for w in range(W):
            col_b = [bases[t][role][w] for t in range(T)]
            col_q = [quals[t][role][w] for t in range(T)]
            b, q, d, e = oracle_column_vote(
                col_b,
                col_q,
                params.error_rate_pre_umi,
                params.error_rate_post_umi,
                params.min_input_base_quality,
                params.min_consensus_base_quality,
            )
            out["base"][role][w] = b
            out["qual"][role][w] = q
            out["depth"][role][w] = d
            out["errors"][role][w] = e
    return out
