"""graftmethyl: fused on-chip methylation extraction.

The subsystem the consensus engine exists to feed: per-column methylation
calls fall out of the duplex vote as a fused epilogue (methyl.context),
per-batch tallies reduce through a contig-sharded spill accumulator
(methyl.tally), and the aggregate emits bedMethyl / CX cytosine reports
(methyl.emit). Chemistry modes (bisulfite | emseq | none) gate the
conversion transform upstream; the epilogue itself is chemistry-invariant
because it reads the RAW pre-conversion planes.
"""

from bsseqconsensusreads_tpu.methyl.context import (  # noqa: F401
    CTX_NONE,
    CTX_NAMES,
    methyl_epilogue,
    methyl_epilogue_host,
    methyl_wire_words,
    unpack_methyl_planes,
)
from bsseqconsensusreads_tpu.methyl.tally import (  # noqa: F401
    MethylAccumulator,
    extract_tallies,
    merge_tallies,
)
