"""Per-column methylation epilogue on the duplex vote kernels.

Bisulfite (and EM-seq) conversion leaves methylated cytosines as C and
converts unmethylated ones to T — so after the duplex engine has grouped a
family's four reads (rows 99/163/83/147) into window space, every reference
cytosine column already holds the complete methylation evidence for that
molecule, and extraction is a per-column classify-and-count over tensors the
vote kernel is ALREADY holding in registers. That is the fusion argument:
no re-scan of the consensus BAM, no per-read host loop (the shape
analysis/rules_methyl.py exists to forbid) — one epilogue on the same
arrays, shipped as two extra u8 planes per family.

Semantics (the mini-genome oracle in tests/test_methyl.py pins these):

  * A site is a reference C (top-strand cytosine, evidence read directly by
    the NON-converted rows: raw C = methylated, raw T = unmethylated) or a
    reference G (bottom-strand cytosine, evidence carried by the
    CONVERT-MASK rows: raw G = methylated, raw A = unmethylated). The
    epilogue consumes the RAW pre-conversion planes — ops.convert erases
    exactly this signal (that is its job).
  * Context is classified from a bounded reference extension ref_ext
    [F, W + 4] with ref_ext[j] = genome[window_start - 2 + j]:
    CpG / CHG / CHH on the + strand from the two FOLLOWING bases, on the
    - strand from the two PRECEDING bases (reverse-complement symmetry).
    Any needed base that is N (including out-of-contig columns — the
    bounded gather yields N there) suppresses the call.
  * An observation counts when the cell is covered and its input quality
    passes params.min_input_base_quality — the same observation gate the
    vote itself applies.
  * A column only reports when the duplex consensus CALLED a base there in
    at least one role — uncalled columns carry no consensus evidence.

Outputs per family: ctx u8 [F, W] (0 = no site; 1/2/3 = CpG/CHG/CHH on +;
4/5/6 = CpG/CHG/CHH on -) and counts u8 [F, W] nibble-packed
meth | unmeth << 4 (each <= 4 rows of evidence). Both the jit epilogue and
the numpy host twin are pure integer pipelines over the same formulas, so
the bit-identity contract is structural, not numerical.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from bsseqconsensusreads_tpu.alphabet import NBASE

#: ctx plane codes. 0 reserved for "no callable site".
CTX_NONE = 0
#: code -> (context name, strand char) for the emit surface.
CTX_NAMES = {
    1: ("CpG", "+"), 2: ("CHG", "+"), 3: ("CHH", "+"),
    4: ("CpG", "-"), 5: ("CHG", "-"), 6: ("CHH", "-"),
}

_A, _C, _G, _T = 0, 1, 2, 3


def _classify(xp, r_m2, r_m1, r_0, r_p1, r_p2):
    """Shared context classification: identical formula for jnp and numpy.

    + strand (ref C): CpG when next is G; CHG when next is a non-N non-G
    and next-but-one is G; CHH when both followers are non-N non-G.
    - strand (ref G): the mirror over the preceding bases with C.
    """
    p1g, p1n = r_p1 == _G, r_p1 == NBASE
    p2g, p2n = r_p2 == _G, r_p2 == NBASE
    ctx_p = xp.where(
        p1g, 1, xp.where(p1n, 0, xp.where(p2g, 2, xp.where(p2n, 0, 3)))
    )
    m1c, m1n = r_m1 == _C, r_m1 == NBASE
    m2c, m2n = r_m2 == _C, r_m2 == NBASE
    ctx_m = xp.where(
        m1c, 4, xp.where(m1n, 0, xp.where(m2c, 5, xp.where(m2n, 0, 6)))
    )
    return xp.where(
        r_0 == _C, ctx_p, xp.where(r_0 == _G, ctx_m, 0)
    )


def _epilogue(xp, bases, quals, cover, convert_mask, cons_base, ref_ext,
              min_q):
    """One implementation, two array namespaces (jnp on device, numpy as
    the host twin) — the layout-independence and engine-parity tests pin
    the outputs byte-identical, and sharing the formula makes that a
    structural property rather than a maintained one."""
    w = bases.shape[-1]
    q = quals.astype(xp.float32)
    obs = cover & (q >= min_q)  # [F, 4, W]
    cm = convert_mask.astype(bool)[:, :, None]  # [F, 4, 1]
    r_m2 = ref_ext[:, 0:w]
    r_m1 = ref_ext[:, 1 : w + 1]
    r_0 = ref_ext[:, 2 : w + 2]
    r_p1 = ref_ext[:, 3 : w + 3]
    r_p2 = ref_ext[:, 4 : w + 4]
    ctx = _classify(xp, r_m2, r_m1, r_0, r_p1, r_p2)
    called = (cons_base[:, 0, :] != NBASE) | (cons_base[:, 1, :] != NBASE)
    ctx = xp.where(called, ctx, 0).astype(xp.uint8)
    # evidence: top-strand sites read the untreated rows as-is; bottom-
    # strand sites read the convert-mask rows (the reads whose C->T
    # treatment happened on the OTHER strand, so their G/A carries the
    # bottom-strand cytosine state)
    obs_p = obs & ~cm
    obs_m = obs & cm
    meth_p = xp.sum(obs_p & (bases == _C), axis=1)
    unme_p = xp.sum(obs_p & (bases == _T), axis=1)
    meth_m = xp.sum(obs_m & (bases == _G), axis=1)
    unme_m = xp.sum(obs_m & (bases == _A), axis=1)
    top = r_0 == _C
    meth = xp.where(top, meth_p, meth_m).astype(xp.uint8)
    unme = xp.where(top, unme_p, unme_m).astype(xp.uint8)
    valid = ctx != 0
    counts = xp.where(valid, meth | (unme << 4), 0).astype(xp.uint8)
    return ctx, counts


def methyl_epilogue(bases, quals, cover, convert_mask, cons_base, ref_ext,
                    min_q: float):
    """Device epilogue (jit-traceable): returns planes u8 [F, 2, W] —
    row 0 = ctx codes, row 1 = nibble-packed counts (meth | unmeth << 4).

    bases/quals/cover are the RAW batch planes [F, 4, W] (pre-conversion),
    convert_mask bool [F, 4], cons_base int8 [F, 2, W] (the vote output),
    ref_ext int8 [F, W + 4] (ops.refstore bounded extension gather).
    """
    ctx, counts = _epilogue(
        jnp, bases, quals, cover, convert_mask, cons_base, ref_ext,
        jnp.float32(min_q),
    )
    return jnp.stack([ctx, counts], axis=1)


def methyl_epilogue_host(bases, quals, cover, convert_mask, cons_base,
                         ref_ext, min_q: float) -> np.ndarray:
    """numpy host twin of methyl_epilogue — byte-identical planes.

    Engaged on the mesh-sharded path and under
    BSSEQ_TPU_METHYL_ENGINE=host (the differential leg the acceptance
    byte-compare drives); also the degrade path's implementation.
    """
    ctx, counts = _epilogue(
        np,
        np.asarray(bases),
        np.asarray(quals),
        np.asarray(cover, dtype=bool),
        np.asarray(convert_mask, dtype=bool),
        np.asarray(cons_base),
        np.asarray(ref_ext),
        np.float32(min_q),
    )
    return np.stack([ctx, counts], axis=1)


def methyl_wire_words(planes):
    """Device-side pack of the methyl planes [F, 2, W] u8 into flat u32
    words for the output wire — appended AFTER the b0 + la/rd sections so
    the existing wire prefix parses unchanged (ops.reconstruct)."""
    return jax.lax.bitcast_convert_type(
        planes.reshape(-1, 4), jnp.uint32
    ).reshape(-1)


def unpack_methyl_planes(words, f: int, w: int) -> np.ndarray:
    """numpy inverse of methyl_wire_words -> u8 [f, 2, w]."""
    u8 = np.asarray(words)
    if u8.dtype != np.uint8:
        u8 = u8.view(np.uint8)
    return u8[: f * 2 * w].reshape(f, 2, w)
