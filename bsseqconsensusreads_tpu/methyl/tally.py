"""Contig-sharded methylation tally accumulator.

Per-batch methyl planes (methyl.context) reduce into global per-site
(methylated, unmethylated) sums keyed by the site's GLOBAL genome offset
(ops.refstore's concatenated coordinate — already contig-major, so sorted
global offsets ARE (contig, pos) order and the emit never re-sorts).

Crash consistency rides the duplex checkpoint's watermark protocol:

  * add() is idempotent per batch index — a watchdog-redispatched batch
    recomputes identical tallies, so replacing the pending entry (or
    ignoring a batch at/below the committed watermark) never double-counts;
  * flush(watermark) — wired as pipeline.checkpoint.BatchCheckpoint's
    on_flush hook, called after the shard write succeeds and BEFORE the
    manifest commits — spills every pending batch <= watermark into one
    CRC'd run file recorded in a sidecar manifest
    (<output>.methyl.runs.json) whose entries carry their `upto` watermark;
  * resume(batches_done) keeps the longest manifest prefix whose `upto`
    does not exceed the checkpoint's committed batch count, verifies CRCs,
    and deletes orphan run files — batches above the kept watermark replay
    through the engine exactly like the consensus stream itself.

Tally sums are commutative integers, so the final bedMethyl/CX bytes are
independent of run boundaries — the kill/resume chaos drill
(methyl_spill_io_error_resume) pins byte-identity, not just row equality.

The run-write attempt fires the `extsort_spill` failpoint with
stage="methyl" (the accumulator IS a spill client of the extsort
machinery), so fault schedules can target methyl spills without touching
the sort engine's own runs.

The merge pass itself (merge_tallies) has a native wirepack sweep
(native/wirepack.cpp methyl_tally_merge) with the numpy
argsort + reduceat twin below as the pinned parity reference
(BSSEQ_TPU_METHYL_MERGE=python forces the twin).
"""

from __future__ import annotations

import json
import os
import struct
import threading

import numpy as np

from bsseqconsensusreads_tpu.faults import failpoints as _failpoints
from bsseqconsensusreads_tpu.faults import integrity as _integrity
from bsseqconsensusreads_tpu.faults import retry as _faultretry
from bsseqconsensusreads_tpu.utils import observe

_RUN_MAGIC = b"BSMT"
_RUN_VERSION = 1


def merge_tallies(sites, ctx, meth, unmeth, engine: str = "auto"):
    """Reduce (possibly duplicated) site tallies to sorted unique sums.

    sites int64 [n] global genome offsets, ctx u8 [n] (a pure function of
    the site, so any occurrence's value is THE value), meth/unmeth u32 [n].
    Returns the same four arrays, sites strictly increasing. engine:
    'auto' (native wirepack when built), 'native', 'python';
    BSSEQ_TPU_METHYL_MERGE overrides.
    """
    engine = os.environ.get("BSSEQ_TPU_METHYL_MERGE", engine)
    sites = np.ascontiguousarray(sites, dtype=np.int64)
    ctx = np.ascontiguousarray(ctx, dtype=np.uint8)
    meth = np.ascontiguousarray(meth, dtype=np.uint32)
    unmeth = np.ascontiguousarray(unmeth, dtype=np.uint32)
    if engine != "python":
        from bsseqconsensusreads_tpu.io import wirepack

        if wirepack.available():
            return wirepack.methyl_tally_merge(sites, ctx, meth, unmeth)
        if engine == "native":
            raise RuntimeError(
                "BSSEQ_TPU_METHYL_MERGE=native but the wirepack library "
                "is not built (native/Makefile)"
            )
    if not sites.size:
        return sites, ctx, meth, unmeth
    order = np.argsort(sites, kind="stable")
    s = sites[order]
    first = np.concatenate([[True], s[1:] != s[:-1]])
    idx = np.nonzero(first)[0]
    return (
        s[idx],
        ctx[order][idx],
        np.add.reduceat(meth[order].astype(np.uint64), idx).astype(np.uint32),
        np.add.reduceat(unmeth[order].astype(np.uint64), idx).astype(
            np.uint32
        ),
    )


def extract_tallies(planes, metas, refstore, rid_map=None):
    """Sparse per-batch tallies from the dense methyl planes.

    planes u8 [F, 2, W] (ctx, nibble counts), metas the batch's FamilyMeta
    list, refstore an ops.refstore.RefStore (global offset arithmetic).
    rid_map (refstore.contig_indices over the BAM header names) translates
    each meta's ref_id into a STORE contig index — the header's contig
    order is not the store's, and a raw ref_id would land the sites on the
    wrong contig. Families without a reference (unknown contig / negative
    start) carry no sites. One vectorized nonzero over the batch — no
    per-record loop.
    """
    planes = np.asarray(planes)
    f, _, w = planes.shape
    rid = np.asarray([m.ref_id for m in metas], dtype=np.int64)
    if rid_map is not None:
        rid_map = np.asarray(rid_map, dtype=np.int64)
        known = (rid >= 0) & (rid < len(rid_map))
        rid = np.where(known, rid_map[np.where(known, rid, 0)], -1)
    ws = np.asarray([m.window_start for m in metas], dtype=np.int64)
    ok = (rid >= 0) & (rid < len(refstore.names)) & (ws >= 0)
    gstart = np.where(ok, refstore.offsets[np.where(ok, rid, 0)] + ws, -1)
    ctx_plane = planes[:, 0, :]
    cnt_plane = planes[:, 1, :]
    mask = (ctx_plane != 0) & (cnt_plane != 0) & ok[:, None]
    fi, col = np.nonzero(mask)
    cnt = cnt_plane[fi, col]
    return (
        gstart[fi] + col,
        ctx_plane[fi, col],
        (cnt & 0xF).astype(np.uint32),
        (cnt >> 4).astype(np.uint32),
    )


def _write_run_payload(path: str, entries) -> int:
    """One run-file write attempt (the retry unit): header + the four
    tally arrays of every pending entry, concatenated and pre-merged."""
    sites = np.concatenate([e[0] for e in entries])
    ctx = np.concatenate([e[1] for e in entries])
    meth = np.concatenate([e[2] for e in entries])
    unmeth = np.concatenate([e[3] for e in entries])
    sites, ctx, meth, unmeth = merge_tallies(sites, ctx, meth, unmeth)
    with open(path, "wb") as fh:
        fh.write(_RUN_MAGIC)
        fh.write(struct.pack("<IQ", _RUN_VERSION, sites.size))
        fh.write(sites.tobytes())
        fh.write(ctx.tobytes())
        fh.write(meth.tobytes())
        fh.write(unmeth.tobytes())
    return int(sites.size)


def _read_run_file(path: str):
    with open(path, "rb") as fh:
        magic = fh.read(4)
        if magic != _RUN_MAGIC:
            raise _integrity.IntegrityError(
                f"{path}: bad methyl run magic {magic!r}"
            )
        version, n = struct.unpack("<IQ", fh.read(12))
        if version != _RUN_VERSION:
            raise _integrity.IntegrityError(
                f"{path}: methyl run version {version} != {_RUN_VERSION}"
            )
        sites = np.frombuffer(fh.read(8 * n), dtype=np.int64)
        ctx = np.frombuffer(fh.read(n), dtype=np.uint8)
        meth = np.frombuffer(fh.read(4 * n), dtype=np.uint32)
        unmeth = np.frombuffer(fh.read(4 * n), dtype=np.uint32)
    if unmeth.size != n:
        raise _integrity.IntegrityError(f"{path}: truncated methyl run")
    return sites, ctx, meth, unmeth


class MethylAccumulator:
    """Thread-safe tally sink for one duplex stage run.

    bed_path / cx_path select the emit formats (either may be None, not
    both). Run files spill next to the first output. When a
    BatchCheckpoint drives flush(), spills happen ONLY at its committed
    watermarks (resume safety: a run can never contain a batch the replay
    would skip AND the manifest would drop); without a checkpoint, a size
    threshold (spill_sites) bounds pending memory instead.
    """

    def __init__(self, refstore, bed_path: str | None = None,
                 cx_path: str | None = None, *, metrics=None,
                 spill_sites: int = 1 << 22):
        if bed_path is None and cx_path is None:
            raise ValueError("MethylAccumulator needs bed_path or cx_path")
        self.refstore = refstore
        self.bed_path = bed_path
        self.cx_path = cx_path
        self.metrics = metrics
        self.spill_sites = spill_sites
        target = bed_path if bed_path is not None else cx_path
        self._base = target
        self._manifest_path = target + ".methyl.runs.json"
        self._lock = threading.Lock()
        self._pending: dict[int, tuple] = {}
        self._pending_sites = 0
        self._watermark = 0
        self._runs: list[dict] = []
        self._checkpointed = False
        self._rid_map = None  # set by bind_names (BAM ref_id -> store idx)
        self.sites_out = 0  # final unique site count (set by finalize)

    def bind_names(self, ref_names) -> None:
        """Pin the BAM-header ref_id -> store contig translation that
        add_planes' global-offset arithmetic needs (the header order and
        the store order are independent)."""
        self._rid_map = self.refstore.contig_indices(ref_names)

    # ---- ingestion ----------------------------------------------------

    def add(self, batch_index: int, sites, ctx, meth, unmeth) -> None:
        """Record one batch's tallies. Idempotent per batch index: a
        duplicate add (watchdog redispatch) replaces the identical pending
        entry or — at/below the committed watermark — is ignored."""
        with self._lock:
            if batch_index <= self._watermark:
                return
            prev = self._pending.get(batch_index)
            if prev is not None:
                self._pending_sites -= prev[0].size
            self._pending[batch_index] = (
                np.asarray(sites, dtype=np.int64),
                np.asarray(ctx, dtype=np.uint8),
                np.asarray(meth, dtype=np.uint32),
                np.asarray(unmeth, dtype=np.uint32),
            )
            self._pending_sites += self._pending[batch_index][0].size
            over = (
                not self._checkpointed
                and self._pending_sites > self.spill_sites
            )
            if over:
                self._spill_locked(max(self._pending))

    def add_planes(self, batch_index: int, planes, metas) -> None:
        self.add(
            batch_index,
            *extract_tallies(planes, metas, self.refstore, self._rid_map),
        )

    # ---- spill / watermark protocol ------------------------------------

    def attach_checkpoint(self, ck) -> None:
        """Wire this accumulator as the checkpoint's on_flush hook and
        restore the committed run chain for a resumed run."""
        self._checkpointed = True
        self.resume(ck.batches_done)
        ck.on_flush = self.flush

    def flush(self, watermark: int) -> None:
        """Spill every pending batch <= watermark into one run file.
        Called by BatchCheckpoint._flush AFTER its shard write succeeds
        and BEFORE the manifest commits — a crash between the two leaves
        a run the next resume drops as above-watermark, never a hole."""
        with self._lock:
            self._spill_locked(watermark)

    def _spill_locked(self, watermark: int) -> None:
        take = [bi for bi in self._pending if bi <= watermark]
        if not take:
            return
        take.sort()
        entries = [self._pending[bi] for bi in take]
        run_index = len(self._runs)
        path = f"{self._base}.methyl.run.{run_index:04d}"

        def write_attempt() -> int:
            _failpoints.fire("extsort_spill", stage="methyl", run=run_index)
            return _write_run_payload(path, entries)

        n = _faultretry.guarded(
            write_attempt,
            metrics=self.metrics, stage="extsort_spill", batch=run_index,
        )
        crc = _integrity.file_crc32(path)
        self._runs.append(
            {
                "file": os.path.basename(path),
                "crc": crc,
                "upto": watermark,
                "records": n,
            }
        )
        self._save_manifest()
        for bi in take:
            # graftlint: disable=thread-unsafe-mutation -- _spill_locked
            # runs under the caller's self._lock (flush / add)
            self._pending_sites -= self._pending[bi][0].size
            del self._pending[bi]
        # graftlint: disable=thread-unsafe-mutation -- same lock as above
        self._watermark = max(self._watermark, watermark)
        if self.metrics is not None:
            self.metrics.count("methyl_spill_runs")
            self.metrics.count("methyl_spill_sites", n)
        observe.emit(
            "methyl_spill",
            {"run": run_index, "sites": n, "upto": watermark},
        )

    def _save_manifest(self) -> None:
        tmp = self._manifest_path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump({"runs": self._runs}, fh)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self._manifest_path)

    def resume(self, batches_done: int) -> None:
        """Restore the committed run chain: keep the longest manifest
        prefix with upto <= batches_done and verified CRCs; delete
        everything after it (orphan runs from a crashed spill — their
        batches replay through the engine)."""
        if not os.path.exists(self._manifest_path):
            return
        with open(self._manifest_path) as fh:
            manifest = json.load(fh)
        keep: list[dict] = []
        base_dir = os.path.dirname(self._base) or "."
        for run in manifest.get("runs", ()):
            path = os.path.join(base_dir, run["file"])
            if run["upto"] > batches_done:
                break
            try:
                _integrity.verify_file_crc32(path, run["crc"], run["file"])
            except _integrity.IntegrityError:
                break
            keep.append(run)
        for run in manifest.get("runs", ())[len(keep):]:
            path = os.path.join(base_dir, run["file"])
            if os.path.exists(path):
                os.unlink(path)
        dropped = len(manifest.get("runs", ())) - len(keep)
        self._runs = keep
        self._watermark = keep[-1]["upto"] if keep else 0
        if dropped or keep:
            observe.emit(
                "methyl_resume",
                {
                    "runs_kept": len(keep),
                    "runs_dropped": dropped,
                    "watermark": self._watermark,
                },
            )
        if dropped:
            self._save_manifest()

    # ---- finalize ------------------------------------------------------

    def finalize(self) -> dict:
        """Merge the run chain + still-pending tallies and write the emit
        formats. Returns {"sites": n, "bed": path?, "cx": path?}."""
        from bsseqconsensusreads_tpu.methyl import emit as _emit

        with self._lock:
            parts = []
            base_dir = os.path.dirname(self._base) or "."
            for run in self._runs:
                path = os.path.join(base_dir, run["file"])
                _integrity.verify_file_crc32(path, run["crc"], run["file"])
                parts.append(_read_run_file(path))
            for bi in sorted(self._pending):
                parts.append(self._pending[bi])
            if parts:
                sites = np.concatenate([p[0] for p in parts])
                ctx = np.concatenate([p[1] for p in parts])
                meth = np.concatenate([p[2] for p in parts])
                unmeth = np.concatenate([p[3] for p in parts])
            else:
                sites = np.zeros(0, np.int64)
                ctx = np.zeros(0, np.uint8)
                meth = unmeth = np.zeros(0, np.uint32)
            sites, ctx, meth, unmeth = merge_tallies(
                sites, ctx, meth, unmeth
            )
            self.sites_out = int(sites.size)
            out: dict = {"sites": self.sites_out}
            if self.bed_path is not None:
                _emit.write_bedmethyl(
                    self.bed_path, self.refstore, sites, ctx, meth, unmeth
                )
                out["bed"] = self.bed_path
            if self.cx_path is not None:
                _emit.write_cx_report(
                    self.cx_path, self.refstore, sites, ctx, meth, unmeth
                )
                out["cx"] = self.cx_path
            for run in self._runs:
                path = os.path.join(base_dir, run["file"])
                if os.path.exists(path):
                    os.unlink(path)
            if os.path.exists(self._manifest_path):
                os.unlink(self._manifest_path)
            self._runs = []
            self._pending.clear()
            self._pending_sites = 0
            observe.emit("methyl_finalize", out)
            return out
