"""Methylation output formats: bedMethyl and CX cytosine report.

Both render the merged global-offset tallies (methyl.tally) back into
contig coordinates via the RefStore's offset table. Sites arrive sorted by
global offset — contig-major — so output order is (contig, pos) without a
sort. Both surfaces cover OBSERVED sites only (coverage >= 1); the classic
bismark CX report enumerates every genomic cytosine, covered or not — the
covered-only scoping here is deliberate (PARITY.md) so output size scales
with data, not genome.

bedMethyl (ENCODE-style 11 columns):
  chrom  start0  end  context  score(min(1000, cov))  strand
  thickStart  thickEnd  0,0,0  coverage  methyl%% (integer floor)

CX report (bismark-style columns, covered sites only):
  chrom  pos1  strand  count_meth  count_unmeth  context  trinucleotide

The per-site python loop below is the COLD finalize path (once per run,
after all batches) — the hot path ships dense planes; graftlint's
unfused-methyl-scan rule guards the hot side, not this one.
"""

from __future__ import annotations

import numpy as np

from bsseqconsensusreads_tpu.methyl.context import CTX_NAMES

_CODE_CHAR = "ACGTN"
_COMP_CHAR = "TGCAN"


def _site_coords(refstore, sites):
    """(contig index, local pos) arrays for sorted global offsets."""
    rid = (
        np.searchsorted(refstore.offsets, sites, side="right") - 1
        if sites.size
        else np.zeros(0, np.int64)
    )
    pos = sites - refstore.offsets[rid] if sites.size else sites
    return rid, pos


def _trinucleotide(refstore, rid: int, pos: int, minus: bool) -> str:
    """Reference trinucleotide 5'->3' on the site's own strand; N where the
    contig ends inside the window (context never needs those columns, the
    report shows them as unresolved)."""
    length = int(refstore.lengths[rid])
    off = int(refstore.offsets[rid])
    out = []
    for k in range(3):
        p = pos - k if minus else pos + k
        if 0 <= p < length:
            code = int(refstore.codes[off + p])
            out.append(_COMP_CHAR[code] if minus else _CODE_CHAR[code])
        else:
            out.append("N")
    return "".join(out)


def write_bedmethyl(path: str, refstore, sites, ctx, meth, unmeth) -> None:
    rid, pos = _site_coords(refstore, sites)
    with open(path, "wb") as fh:
        for i in range(sites.size):
            name, strand = CTX_NAMES[int(ctx[i])]
            m, u = int(meth[i]), int(unmeth[i])
            cov = m + u
            p = int(pos[i])
            chrom = refstore.names[int(rid[i])]
            fh.write(
                (
                    f"{chrom}\t{p}\t{p + 1}\t{name}\t{min(1000, cov)}\t"
                    f"{strand}\t{p}\t{p + 1}\t0,0,0\t{cov}\t"
                    f"{(100 * m) // cov}\n"
                ).encode()
            )


def write_cx_report(path: str, refstore, sites, ctx, meth, unmeth) -> None:
    rid, pos = _site_coords(refstore, sites)
    with open(path, "wb") as fh:
        for i in range(sites.size):
            name, strand = CTX_NAMES[int(ctx[i])]
            r = int(rid[i])
            p = int(pos[i])
            tri = _trinucleotide(refstore, r, p, strand == "-")
            fh.write(
                (
                    f"{refstore.names[r]}\t{p + 1}\t{strand}\t"
                    f"{int(meth[i])}\t{int(unmeth[i])}\t{name}\t{tri}\n"
                ).encode()
            )
