"""Multi-host execution: the family axis across processes (DCN-ready).

The reference has no distributed backend — its processes communicate
exclusively through BAM files on a shared filesystem (SURVEY.md §5.8,
main.snake.py shell rules). This module is the TPU framework's scale-out
equivalent, built on jax.distributed + jax.sharding instead of NCCL/MPI:

* each host process ingests its own slice of the input (files are already
  the pipeline's durable inter-stage boundary, so per-host BAM shards come
  for free from the checkpoint layer);
* the global mesh places every host's devices on the family ('data') axis,
  host-major, so a host's family rows land only on its own devices —
  `jax.make_array_from_process_local_data` then builds the global batch
  without moving a byte off-host;
* the consensus kernels contain zero cross-family operators
  (parallel.sharding), so NOTHING crosses DCN per batch: compilation-time
  coordination is the only cross-host traffic. Deep families (template-axis
  psum, parallel.deep_family) stay on one host's ICI domain by
  construction — their dedicated mesh is built from that host's devices.

Single-chip/single-process runs degenerate cleanly: process_count == 1
makes every helper a thin alias of the parallel.mesh equivalents.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bsseqconsensusreads_tpu.parallel.mesh import DATA_AXIS, READS_AXIS


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Join the multi-host job (thin wrapper over jax.distributed).

    On TPU pods the three arguments auto-detect from the environment; on
    CPU/test clusters pass them explicitly. Must run before any backend
    init. No-op when called with num_processes=1."""
    if num_processes == 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def multihost_family_mesh() -> Mesh:
    """All global devices on the family axis, host-major.

    jax.devices() orders devices by process; keeping that order makes each
    process's family rows map onto its own local devices, which is what
    lets make_array_from_process_local_data assemble global batches with
    zero cross-host transfers."""
    devices = np.array(jax.devices()).reshape(-1, 1)
    return Mesh(devices, (DATA_AXIS, READS_AXIS))


def local_family_count(n_global_families: int, mesh: Mesh) -> tuple[int, int]:
    """(this process's family count, its starting global row) under an even
    split of n_global_families over the mesh's data axis. n_global_families
    must divide evenly by the data size (use parallel.mesh.pad_families on
    the concatenated global count, or pad per host with equal shares)."""
    data_size = mesh.shape[DATA_AXIS]
    if n_global_families % data_size:
        raise ValueError(
            f"{n_global_families} families do not split evenly over "
            f"{data_size} devices; pad first (parallel.mesh.pad_families)"
        )
    per_dev = n_global_families // data_size
    local_devs = [
        d for d in mesh.devices[:, 0] if d.process_index == jax.process_index()
    ]
    first_row = min(
        int(np.argwhere(mesh.devices[:, 0] == d)[0, 0]) for d in local_devs
    )
    return per_dev * len(local_devs), per_dev * first_row


def global_family_batch(local_arrays, n_global_families: int, mesh: Mesh):
    """Assemble global device arrays from per-process local family rows.

    local_arrays: tuple of numpy arrays whose leading axis is this
    process's family share (local_family_count rows, in global order).
    Returns jax Arrays with global shape [n_global_families, ...], sharded
    over the mesh's data axis, each shard resident on its own host."""
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    out = []
    for a in local_arrays:
        global_shape = (n_global_families,) + a.shape[1:]
        out.append(
            jax.make_array_from_process_local_data(sharding, a, global_shape)
        )
    return tuple(out)


def local_rows(global_array, n_local: int) -> np.ndarray:
    """Fetch this process's rows of a data-sharded output array, in global
    row order, without touching other hosts' shards."""
    shards = sorted(
        global_array.addressable_shards, key=lambda s: s.index[0].start or 0
    )
    parts = [np.asarray(s.data) for s in shards]
    got = np.concatenate(parts, axis=0)
    if got.shape[0] != n_local:
        raise ValueError(
            f"expected {n_local} local rows, found {got.shape[0]}"
        )
    return got
