"""Multi-host execution: the family axis across processes (DCN-ready).

The reference has no distributed backend — its processes communicate
exclusively through BAM files on a shared filesystem (SURVEY.md §5.8,
main.snake.py shell rules). This module is the TPU framework's scale-out
equivalent, built on jax.distributed + jax.sharding instead of NCCL/MPI:

* each host process ingests its own slice of the input (files are already
  the pipeline's durable inter-stage boundary, so per-host BAM shards come
  for free from the checkpoint layer);
* the global mesh places every host's devices on the family ('data') axis,
  host-major, so a host's family rows land only on its own devices —
  `jax.make_array_from_process_local_data` then builds the global batch
  without moving a byte off-host;
* the consensus kernels contain zero cross-family operators
  (parallel.sharding), so NOTHING crosses DCN per batch: compilation-time
  coordination is the only cross-host traffic. Deep families (template-axis
  psum, parallel.deep_family) stay on one host's ICI domain by
  construction — their dedicated mesh is built from that host's devices.

Single-chip/single-process runs degenerate cleanly: process_count == 1
makes every helper a thin alias of the parallel.mesh equivalents.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bsseqconsensusreads_tpu.faults import failpoints as _failpoints
from bsseqconsensusreads_tpu.parallel.mesh import DATA_AXIS, READS_AXIS
from bsseqconsensusreads_tpu.utils import observe


class WorkerHeartbeat:
    """Per-process liveness for multi-host runs: 'worker_heartbeat' ledger
    events carrying (process_index, process_count, seq, phase).

    A stalled host in a multi-host job is invisible from the other hosts'
    logs — the coordinator only notices at the next collective. beat() is
    called at the cross-host synchronization points (distributed init,
    per-batch global assembly); start() adds a daemon thread beating every
    BSSEQ_TPU_HEARTBEAT_S seconds (default 30) so even a host wedged
    outside the batch loop keeps announcing itself. All emission rides the
    run ledger: free when BSSEQ_TPU_STATS is unset."""

    def __init__(self, component: str = "multihost"):
        self.component = component
        self._seq = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    @staticmethod
    def _process_info() -> tuple[int, int]:
        try:
            return jax.process_index(), jax.process_count()
        except Exception:  # noqa: BLE001 — liveness must never crash a run
            return 0, 1

    def beat(self, phase: str = "alive", **extra) -> None:
        with self._lock:
            self._seq += 1
            seq = self._seq
        pi, pc = self._process_info()
        if _failpoints.ARMED:
            try:
                _failpoints.fire("multihost_heartbeat", phase=phase)
            except Exception:  # injected heartbeat LOSS: the beat never
                # reaches the ledger (the firing itself was ledgered) —
                # what a wedged/partitioned host looks like from outside
                return
        observe.emit(
            "worker_heartbeat",
            {
                "component": self.component,
                "process_index": pi,
                "process_count": pc,
                "seq": seq,
                "phase": phase,
                **extra,
            },
        )

    def start(self, interval_s: float | None = None) -> None:
        if self._thread is not None:
            return
        if interval_s is None:
            try:
                interval_s = float(os.environ.get("BSSEQ_TPU_HEARTBEAT_S", 30))
            except ValueError:
                interval_s = 30.0

        def run() -> None:
            while not self._stop.wait(interval_s):
                self.beat("alive")

        # armed once from the owning control thread before the worker
        # starts; the worker only reads self._stop (an Event)
        # graftlint: disable=thread-unsafe-mutation -- armed pre-start
        self._thread = threading.Thread(
            target=run, name="bsseq-heartbeat", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self._stop = threading.Event()


#: Module-level heartbeat the multihost helpers beat through; jobs wanting
#: the periodic announcer call heartbeat().start() after init_distributed.
_HEARTBEAT = WorkerHeartbeat()


def heartbeat() -> WorkerHeartbeat:
    """This process's multihost heartbeat (ledger-backed liveness)."""
    return _HEARTBEAT


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Join the multi-host job (thin wrapper over jax.distributed).

    On TPU pods the three arguments auto-detect from the environment; on
    CPU/test clusters pass them explicitly. Must run before any backend
    init. No-op when called with num_processes=1."""
    if num_processes == 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _HEARTBEAT.beat("distributed_init")


def multihost_family_mesh() -> Mesh:
    """All global devices on the family axis, host-major.

    jax.devices() orders devices by process; keeping that order makes each
    process's family rows map onto its own local devices, which is what
    lets make_array_from_process_local_data assemble global batches with
    zero cross-host transfers."""
    devices = np.array(jax.devices()).reshape(-1, 1)
    return Mesh(devices, (DATA_AXIS, READS_AXIS))


def local_family_count(n_global_families: int, mesh: Mesh) -> tuple[int, int]:
    """(this process's family count, its starting global row) under an even
    split of n_global_families over the mesh's data axis. n_global_families
    must divide evenly by the data size (use parallel.mesh.pad_families on
    the concatenated global count, or pad per host with equal shares)."""
    data_size = mesh.shape[DATA_AXIS]
    if n_global_families % data_size:
        raise ValueError(
            f"{n_global_families} families do not split evenly over "
            f"{data_size} devices; pad first (parallel.mesh.pad_families)"
        )
    per_dev = n_global_families // data_size
    local_devs = [
        d for d in mesh.devices[:, 0] if d.process_index == jax.process_index()
    ]
    first_row = min(
        int(np.argwhere(mesh.devices[:, 0] == d)[0, 0]) for d in local_devs
    )
    return per_dev * len(local_devs), per_dev * first_row


def global_family_batch(local_arrays, n_global_families: int, mesh: Mesh):
    """Assemble global device arrays from per-process local family rows.

    local_arrays: tuple of numpy arrays whose leading axis is this
    process's family share (local_family_count rows, in global order).
    Returns jax Arrays with global shape [n_global_families, ...], sharded
    over the mesh's data axis, each shard resident on its own host."""
    # the per-batch collective boundary: a stall here simulates a
    # cross-host timeout, a raise a dead coordinator — recovery is the
    # crash-only path (die, resume from the checkpoint layer)
    _failpoints.fire("multihost_collective", families=n_global_families)
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    out = []
    t0 = time.monotonic()
    for a in local_arrays:
        global_shape = (n_global_families,) + a.shape[1:]
        out.append(
            jax.make_array_from_process_local_data(sharding, a, global_shape)
        )
    # the per-batch cross-host sync point: a host that stops beating here
    # is the one wedging the job
    _HEARTBEAT.beat(
        "batch_assembled",
        families=n_global_families,
        assemble_s=round(time.monotonic() - t0, 4),
    )
    return tuple(out)


def local_rows(global_array, n_local: int) -> np.ndarray:
    """Fetch this process's rows of a data-sharded output array, in global
    row order, without touching other hosts' shards."""
    shards = sorted(
        global_array.addressable_shards, key=lambda s: s.index[0].start or 0
    )
    parts = [np.asarray(s.data) for s in shards]
    got = np.concatenate(parts, axis=0)
    if got.shape[0] != n_local:
        raise ValueError(
            f"expected {n_local} local rows, found {got.shape[0]}"
        )
    return got
