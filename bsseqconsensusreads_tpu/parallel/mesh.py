"""Device mesh construction and batch padding helpers."""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

#: Mesh axis names: 'data' shards the MI-family axis (embarrassingly
#: parallel); 'reads' shards the template axis of deep families.
DATA_AXIS = "data"
READS_AXIS = "reads"


def shard_map(mesh, in_specs, out_specs, check_vma: bool | None = None):
    """Version-portable `jax.shard_map` decorator.

    jax moved shard_map out of jax.experimental (and renamed check_rep to
    check_vma) across the versions this framework targets; this is the ONE
    resolution both the family-sharding wrappers and the deep-family
    reduction decorate through."""
    if hasattr(jax, "shard_map"):
        kw = {} if check_vma is None else {"check_vma": check_vma}
        return jax.shard_map(
            mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )
    from jax.experimental.shard_map import shard_map as _sm

    kw = {} if check_vma is None else {"check_rep": check_vma}
    return lambda f: _sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
    )


def make_mesh(
    n_data: int | None = None,
    n_reads: int = 1,
    devices=None,
) -> Mesh:
    """A (data, reads) mesh over the given (default: all) devices.

    n_data defaults to n_devices // n_reads. For single-chip runs this is a
    (1, 1) mesh and shard_map degenerates to plain execution.
    """
    devices = list(devices if devices is not None else jax.devices())
    if n_data is None:
        n_data = len(devices) // n_reads
    need = n_data * n_reads
    if need > len(devices):
        raise ValueError(
            f"mesh ({n_data} x {n_reads}) needs {need} devices, "
            f"have {len(devices)}"
        )
    grid = np.array(devices[:need]).reshape(n_data, n_reads)
    return Mesh(grid, (DATA_AXIS, READS_AXIS))


def default_mesh() -> Mesh:
    """All devices on the data axis — the right default for this workload
    (families are independent; SURVEY.md §5.8)."""
    return make_mesh()


def pad_families(arrays: dict | tuple, n_families: int, multiple: int):
    """Pad the leading family axis of every array to a multiple of the mesh's
    data-axis size (shard_map needs even shards). Pad rows are empty families
    (bases stay at the N sentinel via zero/NBASE fill chosen per dtype).

    Returns (padded_arrays, padded_n). Callers slice outputs back to
    n_families.
    """
    pad_to = ((n_families + multiple - 1) // multiple) * multiple
    extra = pad_to - n_families

    def pad(a):
        if extra == 0:
            return a
        widths = [(0, extra)] + [(0, 0)] * (a.ndim - 1)
        fill = 4 if a.dtype == np.int8 else (False if a.dtype == bool else 0)
        return np.pad(a, widths, constant_values=fill)

    if isinstance(arrays, dict):
        return {k: pad(v) for k, v in arrays.items()}, pad_to
    return tuple(pad(a) for a in arrays), pad_to
