"""Host-parallel batch engine: bounded workers, batch-ordered retirement.

Round-5 evidence (SCALECPU_r05.json / SCALERAWCPU_r05.json) moved the
wall from the chip to the HOST: the exact-ce/strand rawize pass alone was
242-277 s of a ~550-650 s duplex stage, and every pure-host phase —
encode/pack, the duplex rawize tag passes, record emit/serialize —
executed serialized on the single dispatch thread. This module is the
executor those phases run on instead:

* **Bounded workers** — `BSSEQ_TPU_HOST_WORKERS` (default
  `min(4, cores-1)`; 0 disables and restores the fully inline path).
* **Deterministic, batch-ordered retirement** — tasks are submitted in
  batch order and joined in batch order (pipeline.calling's `_pipelined`
  retires strictly in event order), so output bytes are IDENTICAL for
  any worker count. Emit math runs against per-task shadow stats whose
  integer fields merge into the stage stats at the ordered join
  (pipeline.calling._hp_stats_merge) — no counter ever races.
* **Ledger-attributed phases** — tasks time their phases on the stage's
  own locked `observe.Metrics`, so `host_s` attribution (rawize / emit /
  encode seconds) survives parallelism; worker-emitted ledger lines
  carry the thread name.
* **graftfault semantics carry over** — every task body runs inside the
  bounded retry executor (`faults.retry.guarded`) with the
  `hostpool_task` failpoint INSIDE the retried unit, so an injected
  fault in host work is retried/recovered exactly like a device fault
  (tools/chaos_drill.py drills it).

pipeline.extsort's double-buffered background spill writer gates on the
same `host_workers()` knob, keeping one story for "may the host use
extra threads".
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor

from bsseqconsensusreads_tpu.faults import failpoints as _failpoints
from bsseqconsensusreads_tpu.faults import retry as _faultretry
from bsseqconsensusreads_tpu.utils import observe

ENV_WORKERS = "BSSEQ_TPU_HOST_WORKERS"

#: Failpoint site fired inside every host-pool task (registered in
#: faults.failpoints.SITES).
FAILPOINT_SITE = "hostpool_task"


def host_workers() -> int:
    """Worker count for the host-parallel engine.

    `BSSEQ_TPU_HOST_WORKERS` overrides (0 disables); the default is
    `min(4, cores-1)` — one core stays with the dispatch thread, and
    beyond ~4 workers the ordered retire queue (not compute) bounds the
    stage on every host measured so far. On a 1-core host the default
    is 0: threads would only add contention there."""
    env = os.environ.get(ENV_WORKERS)
    if env is not None:
        try:
            return max(0, int(env))
        except ValueError:  # graftlint: disable=swallowed-exception -- a malformed worker-count env var falls back to the cpu-count default by design; not a worker failure
            pass
    cores = os.cpu_count() or 1
    return min(4, max(0, cores - 1))


class HostPool:
    """Bounded executor for the pure-host phases of the batch hot path.

    The pool itself imposes no ordering — determinism comes from the
    caller submitting in batch order and joining results in the same
    order (`pipeline.calling._pipelined`). `submit` wraps every task in
    the bounded retry executor with the `hostpool_task` failpoint inside
    the retried unit; tasks must therefore be idempotent (the calling
    layer re-derives per-task state — e.g. shadow stats — inside the
    task body)."""

    def __init__(self, workers: int, metrics=None, stage: str = ""):
        if workers < 1:
            raise ValueError(f"HostPool needs >=1 worker, got {workers}")
        self.workers = workers
        self.metrics = metrics
        self.stage = stage
        self._pool = ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="bsseq-host"
        )

    def submit(self, fn, *args, batch=None, degrade=None):
        """Schedule fn(*args) under the retry executor; returns a Future.

        A RETRYABLE failure (including an armed `hostpool_task`
        failpoint) re-runs the whole task after backoff; exhaustion
        falls to `degrade()` when given, else the error surfaces at the
        caller's ordered join."""

        def unit():
            _failpoints.fire(FAILPOINT_SITE, stage=self.stage, batch=batch)
            return fn(*args)

        return self._pool.submit(
            _faultretry.guarded,
            unit,
            degrade=degrade,
            metrics=self.metrics,
            stage=self.stage or "hostpool",
            batch=batch,
        )

    def shutdown(self) -> None:
        self._pool.shutdown(wait=True, cancel_futures=True)


def make_pool(metrics=None, stage: str = "") -> HostPool | None:
    """A HostPool per `host_workers()`, or None when disabled (0
    workers). Either way the decision is LOUD: an enable event with the
    worker count (+ the `host_pool_workers` counter) or a disable event
    with the reason — a run summary can always say whether host phases
    ran parallel."""
    n = host_workers()
    if n <= 0:
        reason = (
            f"{ENV_WORKERS} explicit disable"
            if os.environ.get(ENV_WORKERS) is not None
            else "single-core host: no idle core for host workers"
        )
        observe.emit("host_pool_disabled", {"stage": stage, "reason": reason})
        return None
    if metrics is not None:
        metrics.count("host_pool_workers", n)
    observe.emit("host_pool_enabled", {"stage": stage, "workers": n})
    return HostPool(n, metrics, stage)
