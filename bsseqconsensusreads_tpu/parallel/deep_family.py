"""Deep-family consensus: split the template axis across devices.

BASELINE.json config 3 calls out targeted panels with >500 reads per MI.
A single such family's [T, 2, W] tensor can dominate one device while the
rest idle; here the template axis is sharded over the mesh's 'reads' axis and
the vote's partial sums are combined with psum — the framework's segmented
reduction (SURVEY.md §5.7: "splitting deep families across devices with a
segmented reduction" is this workload's analog of sequence parallelism).

The vote decomposes exactly: log-likelihood, depth, and error counts are all
sums over reads (models.molecular.vote_partials / count_errors), so each
device computes its shard's partials, psums them over the reads axis, and
finalizes identically (replicated argmax/posterior — no further traffic).
The family axis is simultaneously sharded over 'data', making this the 2D
(dp x sp) configuration of the framework.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from bsseqconsensusreads_tpu.models.molecular import (
    _vote_finalize_dispatch,
    count_errors,
    errors_from_counts,
    narrow_outputs,
    overlap_cocall,
    vote_finalize,
    vote_partials,
    vote_partials_segments,
)
from bsseqconsensusreads_tpu.models.params import ConsensusParams
from bsseqconsensusreads_tpu.parallel.mesh import (
    DATA_AXIS,
    READS_AXIS,
    shard_map,
)


@functools.lru_cache(maxsize=16)
def deep_family_consensus(mesh: Mesh, params: ConsensusParams = ConsensusParams()):
    """Molecular consensus with families over 'data' AND templates over
    'reads'. bases/quals: [F, T, 2, W]; F divisible by the data-axis size,
    T by the reads-axis size. Returns the molecular_consensus output dict.
    """
    in_spec = P(DATA_AXIS, READS_AXIS)
    out_spec = P(DATA_AXIS)

    @jax.jit
    @shard_map(mesh=mesh, in_specs=(in_spec, in_spec), out_specs=out_spec)
    def fn(bases, quals):
        quals = quals.astype(jnp.float32)
        if params.consensus_call_overlapping_bases:
            # co-call is within-template: local to each reads shard
            bases, quals = overlap_cocall(bases, quals)

        def one_family(b, q):
            # b, q: [T_local, 2, W]
            outs = []
            for role in range(2):
                ll, depth = vote_partials(b[:, role, :], q[:, role, :], params)
                ll = jax.lax.psum(ll, READS_AXIS)
                depth = jax.lax.psum(depth, READS_AXIS)
                cons, qual = vote_finalize(ll, depth, params)
                errors = jax.lax.psum(
                    count_errors(b[:, role, :], q[:, role, :], cons, params),
                    READS_AXIS,
                )
                outs.append(
                    {"base": cons, "qual": qual, "depth": depth, "errors": errors}
                )
            return jax.tree.map(lambda a, c: jnp.stack([a, c]), outs[0], outs[1])

        return narrow_outputs(jax.vmap(one_family)(bases, quals))

    return fn


@functools.lru_cache(maxsize=16)
def deep_family_consensus_rows(
    mesh: Mesh,
    params: ConsensusParams = ConsensusParams(),
    vote_kernel: str = "xla",
):
    """deep_family_consensus on the segment-packed row layout.

    Same sharding contract — bases/quals [F, T, 2, W], families over
    'data', templates over 'reads' — but each device votes its local
    template slab as packed rows (seg = family id per row, ONE
    segment-sum for the whole shard) instead of vmapping a per-family
    vote, then psums the partial ll/count/depth planes over the reads
    axis exactly like the padded deep route. Template-pad rows stay in
    the row set: _vote_contrib gives unobserved cells exact-0.0
    contributions, the same zeros the padded sum adds, so the packed
    deep route is bit-identical to deep_family_consensus (and carries
    the same documented qual ±1 relaxation vs the single-device kernel
    — the finalize runs on psum'd sums either way). The errors plane
    derives from the psum'd per-base counts (errors_from_counts), which
    drops the padded route's second reads-axis sweep + third psum.
    """
    in_spec = P(DATA_AXIS, READS_AXIS)
    out_spec = P(DATA_AXIS)

    # check_vma=False: the only collectives are the explicit psums; the
    # pallas finalize leg's outputs carry no vma metadata for the checker
    @jax.jit
    @shard_map(
        mesh=mesh, in_specs=(in_spec, in_spec), out_specs=out_spec,
        check_vma=False,
    )
    def fn(bases, quals):
        quals = quals.astype(jnp.float32)
        if params.consensus_call_overlapping_bases:
            # co-call is within-template: local to each reads shard
            bases, quals = overlap_cocall(bases, quals)
        f, t, _, w = bases.shape
        seg = jnp.repeat(jnp.arange(f, dtype=jnp.int32), t)
        ll, cnt, depth = vote_partials_segments(
            bases.reshape(f * t, 2, w), quals.reshape(f * t, 2, w),
            seg, f, params,
        )
        ll = jax.lax.psum(ll, READS_AXIS)
        cnt = jax.lax.psum(cnt, READS_AXIS)
        depth = jax.lax.psum(depth, READS_AXIS)
        cons, qual = _vote_finalize_dispatch(ll, depth, params, vote_kernel)
        errors = errors_from_counts(cnt, depth, cons)
        return narrow_outputs(
            {"base": cons, "qual": qual, "depth": depth, "errors": errors}
        )

    return fn
