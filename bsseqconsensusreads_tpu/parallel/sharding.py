"""shard_map wrappers: the family axis across the mesh's data axis.

Families are independent (no operator couples them — SURVEY.md §2.3), so
these wrappers contain zero collectives: each device runs the identical
kernel on its family shard. XLA therefore overlaps nothing but the initial
scatter / final gather of batch arrays, which ride ICI.
"""

from __future__ import annotations

import functools

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bsseqconsensusreads_tpu.models.duplex import (
    duplex_call_pipeline,
    duplex_call_pipeline_packed,
)
from bsseqconsensusreads_tpu.models.molecular import (
    molecular_consensus,
    molecular_consensus_packed,
    pack_molecular_outputs,
)
from bsseqconsensusreads_tpu.models.params import ConsensusParams
from bsseqconsensusreads_tpu.parallel.mesh import (
    DATA_AXIS,
    READS_AXIS,
    shard_map,
)


def family_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for [F, ...] batch arrays: families over the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


@functools.lru_cache(maxsize=64)
def sharded_molecular_consensus(
    mesh: Mesh,
    params: ConsensusParams = ConsensusParams(),
    kernel_fn=None,
):
    """molecular_consensus sharded over families. F must divide evenly by the
    data-axis size (use parallel.mesh.pad_families). kernel_fn swaps in an
    alternative per-shard kernel with the same signature (e.g. the Pallas
    vote, ops.pallas_vote.molecular_consensus_pallas)."""
    kernel_fn = kernel_fn or molecular_consensus
    spec = P(DATA_AXIS)

    # check_vma=False: the map is collective-free (each shard independent),
    # and pallas_call outputs don't carry vma metadata for the checker.
    @jax.jit
    @shard_map(
        mesh=mesh, in_specs=(spec, spec), out_specs=spec, check_vma=False
    )
    def fn(bases, quals):
        return kernel_fn(bases, quals, params)

    return fn


@functools.lru_cache(maxsize=64)
def sharded_molecular_outwire(
    mesh: Mesh,
    params: ConsensusParams = ConsensusParams(),
    kernel_fn=None,
):
    """sharded_molecular_consensus with the packed planar OUTPUT wire
    (models.molecular.pack_molecular_outputs): each device packs its family
    shard, and the family-major layout makes the gathered concatenation
    identical to a single-device pack — one D2H array instead of four.

    Naming note: "outwire" is the transport pack of the result planes.
    The segment-packed INPUT layout (ragged rows, no [F, T, 2, W]
    envelope) is sharded_molecular_rows below — the two "packed" senses
    used to share this function's old name, sharded_molecular_packed,
    which survives as a deprecated alias.
    """
    kernel_fn = kernel_fn or molecular_consensus
    spec = P(DATA_AXIS)

    # check_vma=False: same rationale as sharded_molecular_consensus
    @jax.jit
    @shard_map(
        mesh=mesh, in_specs=(spec, spec), out_specs=spec, check_vma=False
    )
    def fn(bases, quals):
        return pack_molecular_outputs(kernel_fn(bases, quals, params))

    return fn


@functools.lru_cache(maxsize=64)
def sharded_molecular_rows(
    mesh: Mesh,
    fams_per_shard: int,
    params: ConsensusParams = ConsensusParams(),
    vote_kernel: str = "xla",
):
    """Segment-packed molecular consensus over a family-sharded row plan.

    Takes ops.encode.shard_packed_rows arrays — bases int8 [S, R, 2, W],
    quals [S, R, 2, W], seg int32 [S, R] of LOCAL family ids — with the
    shard axis split over the mesh's data axis. Every shard owns whole
    families (the plan cuts the packed row axis at family boundaries), so
    each device runs the stock single-device segment-sum on its slice:
    zero collectives, bit-identical to the unsharded packed kernel, and
    no [F, T, 2, W] envelope anywhere. Returns the 12-plane output wire
    concatenated family-major — [S * fams_per_shard, 12, W], the same
    bytes unpack_molecular_outputs expects from the outwire path.
    """
    spec = P(DATA_AXIS)

    # check_vma=False: collective-free map (same rationale as above)
    @jax.jit
    @shard_map(
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    def fn(bases, quals, seg):
        return pack_molecular_outputs(
            molecular_consensus_packed(
                bases[0], quals[0], seg[0], fams_per_shard, params,
                vote_kernel,
            )
        )

    return fn


@functools.lru_cache(maxsize=64)
def sharded_duplex_outwire(
    mesh: Mesh,
    params: ConsensusParams = ConsensusParams(min_reads=0),
    vote_kernel: str = "xla",
    layout: str = "padded",
):
    """duplex_call_pipeline_packed (the production fused duplex stage with
    packed transport outputs) sharded over families — what
    pipeline.calling.call_duplex_batches dispatches on a multi-device
    backend. Returns (packed, la, rd), all family-sharded.

    layout selects the merge layout per shard ('packed' = the segment
    pair-sum merge, duplex_consensus_packed); the wire bytes are identical
    either way. See sharded_molecular_outwire for the "outwire" naming.
    """
    spec = P(DATA_AXIS)

    # check_vma=False: collective-free map; pallas_call outputs carry no
    # vma metadata for the checker (same rationale as the molecular wrap)
    @jax.jit
    @shard_map(
        mesh=mesh,
        in_specs=(spec, spec, spec, spec, spec, spec),
        out_specs=(spec, spec, spec),
        check_vma=False,
    )
    def fn(bases, quals, cover, ref, convert_mask, extend_eligible):
        return duplex_call_pipeline_packed(
            bases, quals, cover, ref, convert_mask, extend_eligible,
            params=params, vote_kernel=vote_kernel, layout=layout,
        )

    return fn


#: Deprecated aliases (pre-PR-13 names): "packed" here always meant the
#: transport pack of the OUTPUT planes, not the segment-packed row layout.
sharded_molecular_packed = sharded_molecular_outwire
sharded_duplex_packed = sharded_duplex_outwire


def sharded_duplex_pipeline(
    mesh: Mesh, params: ConsensusParams = ConsensusParams(min_reads=0)
):
    """The fused convert+extend+duplex stage sharded over families."""
    spec = P(DATA_AXIS)

    @jax.jit
    @shard_map(
        mesh=mesh,
        in_specs=(spec, spec, spec, spec, spec, spec),
        out_specs=spec,
    )
    def fn(bases, quals, cover, ref, convert_mask, extend_eligible):
        return duplex_call_pipeline(
            bases, quals, cover, ref, convert_mask, extend_eligible, params=params
        )

    return fn
