"""Multi-chip execution: device meshes, family-axis sharding, deep-family
segmented reductions.

The reference has no distributed layer at all (SURVEY.md §2.3, §5.8 — its
only parallelism is Snakemake core scheduling and per-process threads). The
TPU design shards the embarrassingly-parallel MI-family axis over the mesh's
'data' axis with shard_map (zero collectives), and splits very deep families
(>500 reads, BASELINE.json config 3) over a 'reads' axis whose partial vote
sums are combined with psum — the framework's segmented reduction. All
collectives ride ICI within a slice; nothing crosses DCN per batch.
"""

from bsseqconsensusreads_tpu.parallel.mesh import (  # noqa: F401
    default_mesh,
    make_mesh,
    pad_families,
)
from bsseqconsensusreads_tpu.parallel.sharding import (  # noqa: F401
    sharded_duplex_pipeline,
    sharded_molecular_consensus,
    sharded_molecular_packed,
)
from bsseqconsensusreads_tpu.parallel.deep_family import (  # noqa: F401
    deep_family_consensus,
)
from bsseqconsensusreads_tpu.parallel import multihost  # noqa: F401
from bsseqconsensusreads_tpu.parallel.hostpool import (  # noqa: F401
    HostPool,
    host_workers,
)
