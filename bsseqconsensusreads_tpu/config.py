"""Framework configuration.

Same YAML surface as the reference's config.yaml (genome_dir,
genome_fasta_file_name, tmp, external tool paths — reference config.yaml:1-11)
plus the keys the reference hardcodes in rule bodies, promoted to config as
SURVEY.md §5.6 prescribes: the consensus error model, backend selection
(`backend: tpu|cpu`), and the alignment mode.
"""

from __future__ import annotations

import dataclasses
import os

import yaml

from bsseqconsensusreads_tpu.models.params import ConsensusParams


@dataclasses.dataclass
class FrameworkConfig:
    # reference-compatible keys (config.yaml:1-11)
    genome_dir: str = "."
    genome_fasta_file_name: str = "genome.fa"
    tmp: str = "/tmp"
    bwameth: str = ""  # external aligner path; empty = not available
    samtools: str = ""  # kept for interop; unused by the native pipeline

    # framework keys (promoted from hardcoded rule bodies, SURVEY.md §5.6)
    backend: str = "tpu"  # tpu | cpu (cpu = same JAX kernels on host)
    aligner: str = "self"  # self | bwameth | none
    batch_families: int = 512
    max_window: int = 4096
    #: MI-group streaming strategy: 'coordinate' bounds host memory on
    #: coordinate-sorted input; 'adjacent' for MI-grouped input; 'gather'
    #: holds everything (any order). See pipeline.calling.stream_mi_groups.
    grouping: str = "coordinate"
    #: molecular-stage chunk composition: 'bucketed' groups families into
    #: depth-homogeneous kernel batches (bounded pad waste, stable shapes —
    #: pipeline.calling._group_batches_bucketed); 'sequential' chunks in
    #: input order (pre-bucketing behavior / output order).
    batching: str = "bucketed"
    #: intra-stage checkpoint interval in kernel batches (0 = rule-boundary
    #: checkpoints only, the reference's granularity). When > 0, consensus
    #: stages write durable shards every N batches and resume mid-stage
    #: after a crash (pipeline.checkpoint; SURVEY.md §5.4).
    checkpoint_every: int = 0
    #: indel-read handling in the molecular stage: 'drop' = parity (the
    #: reference drops any read with I/D CIGAR ops,
    #: tools/1.convert_AG_to_CT.py:79-80); 'align' = recover them with the
    #: banded intra-family aligner (ops.banded, above-parity).
    indel_policy: str = "drop"
    #: spill threshold (records) for the external-merge sorts backing every
    #: sort/zip step (pipeline.extsort) — the bounded-memory replacement for
    #: the reference's 60-100 GB in-RAM sorts (main.snake.py:106,152).
    sort_buffer_records: int = 100_000
    #: consensus-stage record ingest: 'native' streams flat columnar arrays
    #: from the C++ decoder (pipeline.ingest — skips per-record Python
    #: object construction on the hot path), 'python' uses the pure-Python
    #: BamReader, 'auto' picks native when the library is built. Under
    #: 'auto' the duplex stage falls back to python ingest when
    #: duplex_passthrough is set (native views carry only MI/RX, not the
    #: full tag set leftovers must keep) and grouping='gather' forces the
    #: python reader; an EXPLICIT 'native' in those configurations raises
    #: instead of silently measuring the wrong engine.
    ingest: str = "auto"
    #: consensus-stage record emission: 'native' serializes whole kernel
    #: batches to BAM bytes in C++ (io.wirepack.emit_consensus_records —
    #: byte-identical to the Python path, skips per-record object building
    #: and encode), 'python' builds BamRecord objects, 'auto' picks native
    #: when built. The 'self' aligner mode coordinate-sorts the blobs
    #: directly (pipeline.extsort.external_sort_raw).
    emit: str = "auto"
    #: raw coordinate-sort engine for the 'self' stage outputs — the same
    #: auto|native|python contract as `emit`, plus 'bucket': 'native'
    #: keys, sorts, and k-way-merges the encoded record blobs in C
    #: (pipeline.extsort.resolve_sort_engine; merge BGZF compression rides
    #: the mt-writer threadpool), 'python' keeps the blob-generator +
    #: heapq parity twin, 'bucket' drops the merge tail entirely —
    #: records route into coordinate-range buckets at emit time, each
    #: bucket sorts independently (in-core, hostpool-parallel) and the
    #: output concatenates sorted-by-construction (pipeline.bucketemit),
    #: 'auto' picks native when built. Output bytes are identical across
    #: all engines. BSSEQ_TPU_SORT_ENGINE overrides.
    sort_engine: str = "auto"
    #: bucket count for sort_engine='bucket' (0 = the engine default,
    #: pipeline.bucketemit.DEFAULT_BUCKETS). Boundaries are planned at
    #: equal cumulative-genome-length strides from the header's reference
    #: dictionary; output bytes are identical for ANY count — this only
    #: trades in-core sort size against per-bucket bookkeeping.
    #: BSSEQ_TPU_SORT_BUCKETS overrides.
    sort_buckets: int = 0
    #: inter-stage streaming under sort_engine='bucket' (stretch knob,
    #: off by default): when the molecular stage's output buckets are
    #: sorted in-core, their records can flow straight into duplex
    #: grouping per bucket while the molecular BAM writes, skipping the
    #: intermediate read-back (pipeline.stages). Requires the narrow
    #: configuration the fused path supports (self aligner, no
    #: mid-stage checkpoint) — anything else falls back LOUDLY to the
    #: two-pass path. Output bytes are identical either way.
    stream_interstage: bool = False
    #: BGZF deflate level for INTERMEDIATE stage outputs — the durable
    #: rule-boundary checkpoints between stages (e.g. the molecular output
    #: feeding the duplex stage), which stay on disk like the reference's
    #: but are re-read only once on the happy path. Level 1 deflates ~1.9x
    #: faster than the default 6 for ~10% more bytes (measured on this
    #: image's zlib; samtools' `-l1` pipeline convention). The final
    #: workflow target always writes at the standard level 6; set 6 here
    #: to keep long-retained checkpoints small.
    intermediate_level: int = 1
    #: consensus-stage device transport: 'wire' packs each batch into ONE
    #: u32 array per direction (and, on the duplex stage, gathers reference
    #: windows from the device-resident genome, ops.refstore — the
    #: tunnel-optimal path bench.py measures; lossless, byte-identical
    #: output); on multi-device runs 'wire' round-robins whole batches
    #: across the devices (zero collectives, genome uploaded once per
    #: device). 'unpacked' ships plain tensors (+ host-fetched ref windows
    #: on duplex); 'auto' picks wire on single-device accelerator runs
    #: ONLY — on the CPU backend there is no transfer to save, and on a
    #: multi-device mesh 'auto' resolves to the sharded unpacked path
    #: (round-robin wire must be requested explicitly with 'wire'; see
    #: pipeline.calling._resolve_transport).
    transport: str = "auto"
    #: UMI grouping pre-stage (fgbio GroupReadsByUmi equivalent,
    #: pipeline.group_umi) — the step the reference requires its USER to
    #: have run (README.md:7,51-55). 'auto' probes the input's first
    #: records (up to 50) and prepends the stage when they carry raw-UMI
    #: tags but no MI; 'always' / 'never' force it. The
    #: grouped output is MI-contiguous, so the molecular stage streams it
    #: in 'adjacent' mode — exact for any template geometry (cross-contig
    #: and wide-insert pairs included) — through the C grouper's
    #: MI-change-delimited fast path.
    group_umis: str = "auto"
    #: GroupReadsByUmi knobs: strategy (identity|edit|adjacency|paired),
    #: max UMI mismatches merged within a position group, and the minimum
    #: MAPQ a template needs to be grouped.
    group_strategy: str = "paired"
    group_edits: int = 1
    group_min_map_q: int = 1
    #: tag holding the raw UMI (fgbio --raw-tag; also what 'auto' probes).
    group_raw_tag: str = "RX"
    #: optional consensus-filter stage on the unaligned molecular path —
    #: the reference ships this variant as a DEAD rule (a consensus_to_fq
    #: reading {s}_unalignedConsensus_molecular_filtered.bam that nothing
    #: produces, main.snake.py:70-80); setting a dict of
    #: pipeline.filter.FilterParams fields (e.g. {min_reads: [3]})
    #: inserts the producing rule. None (default) keeps the reference's
    #: live unfiltered-only chain. Under aligner 'self' the filter runs
    #: on the final duplex output instead (name-sort -> filter ->
    #: coordinate-sort, bounded memory); duplex depth tags carry RAW
    #: per-strand read depths (threaded from the molecular cd/ce tags),
    #: so fgbio-style floors like min_reads [3, 2, 1] apply directly.
    filter: dict | None = None
    #: reference-parity emission of off-vocabulary records at the duplex
    #: stage: True writes leftover records (flag 0, non-4-group members, …)
    #: through to the output the way the reference chain would
    #: (tools/1.convert_AG_to_CT.py:70-73, tools/2.extend_gap.py:114-115);
    #: False (default) drops them, counted in stats.leftover_records.
    duplex_passthrough: bool = False
    #: conversion-prepend behavior for convert-flag reads mapped at
    #: reference position 0: 'skip' (default) skips the prepend — the
    #: documented sane deviation (ops/convert.py) — while 'shift'
    #: reproduces the reference exactly, register shift included
    #: (tools/1.convert_AG_to_CT.py:87-92); 'shift' keeps the duplex
    #: encode on the Python placement path.
    pos0: str = "skip"
    #: molecular-stage cB raw base histogram tags (exact duplex ce input —
    #: models.molecular.molecular_base_counts); disable to shave tag bytes
    #: when no duplex stage follows.
    base_count_tags: bool = True
    #: duplex-stage ac/bc per-strand consensus call string tags (fgbio
    #: surface; FilterConsensusReads --require-single-strand-agreement
    #: input — pipeline.calling._duplex_rawize).
    duplex_strand_tags: bool = True
    #: library chemistry: 'bisulfite' (reference parity) and 'emseq'
    #: (enzymatic conversion — computationally identical C->T readout,
    #: recorded as provenance in stage reports and serve job stats);
    #: 'none' declares an UNCONVERTED plain duplex library (fgbio-style):
    #: the convert transform is disabled wholesale (the flag-derived
    #: convert mask is cleared after encode) and the identical engine
    #: runs everything downstream. 'none' refuses the conversion-coupled
    #: surfaces (duplex_passthrough, pos0='shift', methyl extraction) —
    #: pipeline.calling.call_duplex_batches validates the combinations.
    chemistry: str = "bisulfite"
    #: fused methylation extraction at the duplex stage (methyl/):
    #: 'off' (default), 'bedmethyl', 'cx', or 'both' — per-column
    #: classify-and-count epilogue on the vote kernels, contig-sharded
    #: tally accumulation riding the duplex checkpoint's watermark
    #: protocol, outputs next to the duplex target (<target>.bedmethyl /
    #: <target>.CX_report.txt, or `methyl_out` as the base path).
    methyl: str = "off"
    #: base path for the methylation outputs (''= derive from the duplex
    #: stage target).
    methyl_out: str = ""
    #: single-strand consensus mode: stop after the molecular stage
    #: (molecular emit without duplex pairing — libraries whose protocol
    #: never forms ab/ba duplex pairs). Incompatible with methyl
    #: extraction (which is a duplex-stage epilogue).
    single_strand: bool = False
    molecular: ConsensusParams = dataclasses.field(
        default_factory=lambda: ConsensusParams(min_reads=1)
    )
    duplex: ConsensusParams = dataclasses.field(
        default_factory=lambda: ConsensusParams(min_reads=0)
    )

    @property
    def genome_fasta(self) -> str:
        return os.path.join(self.genome_dir, self.genome_fasta_file_name)

    @classmethod
    def from_yaml(cls, path: str, **overrides) -> "FrameworkConfig":
        with open(path) as fh:
            raw = yaml.safe_load(fh) or {}
        raw.update(overrides)
        kw = {}
        for f in dataclasses.fields(cls):
            if f.name in ("molecular", "duplex"):
                continue
            if f.name in raw:
                kw[f.name] = raw[f.name]
        cfg = cls(**kw)
        for side in ("molecular", "duplex"):
            if side in raw:
                base = getattr(cfg, side)
                setattr(cfg, side, base.replace(**raw[side]))
        return cfg
