"""Single-strand ("molecular") consensus kernel.

TPU-native equivalent of `fgbio CallMolecularConsensusReads` as invoked by the
reference (main.snake.py:54): per MI family, a per-column quality-weighted
log-likelihood vote with the fgbio error model surface
(--error-rate-pre-umi / --error-rate-post-umi / --min-input-base-quality /
--min-consensus-base-quality / --consensus-call-overlapping-bases).

Model (documented fgbio semantics, re-derived — no fgbio code consulted):
 1. Raw base error p = 10^(-q/10) is combined with the post-UMI error prior
    via the two-independent-trials rule (ops.phred.prob_error_two_trials).
 2. Optionally, overlapping R1/R2 bases of the same template are co-called
    first: agreement keeps the base with summed quality; disagreement keeps
    the higher-quality base with the quality difference (a tie masks both).
 3. Per window column, per candidate base b: LL(b) = sum over observations of
    log(1-p) if obs==b else log(p/3). Consensus base = argmax; its error
    probability is the posterior 1 - softmax(LL)[argmax].
 4. The consensus error is combined with the pre-UMI error prior (two-trials
    again), clamped to Phred [2, 93].

Deviation from fgbio (documented, deliberate): the vote runs in genome window
space over softclip-trimmed reads (indel/hardclip reads dropped), mirroring
what the reference pipeline itself does to reads before duplex calling
(tools/1.convert_AG_to_CT.py:79-83, tools/2.extend_gap.py:160-176), rather
than in raw read space. Kernels are vmap'd over the family axis and safe
under jit/shard_map.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp

from bsseqconsensusreads_tpu.alphabet import NBASE, NUM_BASES
from bsseqconsensusreads_tpu.models.params import ConsensusParams
from bsseqconsensusreads_tpu.ops import phred
from bsseqconsensusreads_tpu.ops.phred import NO_CALL_QUAL

#: Absolute log-LL band treated as a vote tie (see vote_finalize): above
#: float32 one-ulp summation noise at working magnitudes, below the
#: 3e-6 likelihood-ratio margin the golden suites treat as distinct.
#: ops.pallas_vote shares it so both kernels break ties identically.
ARGMAX_TIE_TOL = 2.5e-6


def overlap_cocall(bases, quals):
    """Co-call overlapping R1/R2 bases within each template.

    bases: int8 [..., 2, W]; quals: float32 [..., 2, W]. Returns updated
    (bases, quals). Columns covered by both roles:
      * agreement   -> both keep the base, quality = q1 + q2 (capped later)
      * disagreement-> both take the higher-quality base, quality = |q1 - q2|;
                       an exact tie masks the column on both roles (no winner).
    Implements --consensus-call-overlapping-bases=true (main.snake.py:54,163).
    """
    b1, b2 = bases[..., 0, :], bases[..., 1, :]
    q1, q2 = quals[..., 0, :], quals[..., 1, :]
    both = (b1 != NBASE) & (b2 != NBASE)
    agree = both & (b1 == b2)
    disagree = both & (b1 != b2)
    qsum = q1 + q2
    qdiff = jnp.abs(q1 - q2)
    winner = jnp.where(q1 >= q2, b1, b2)
    tie = disagree & (qdiff == 0)
    new_b = jnp.where(agree, b1, jnp.where(disagree, winner, -1))
    new_q = jnp.where(agree, qsum, jnp.where(disagree, qdiff, 0.0))
    out_b1 = jnp.where(both, jnp.where(tie, NBASE, new_b), b1)
    out_b2 = jnp.where(both, jnp.where(tie, NBASE, new_b), b2)
    out_q1 = jnp.where(both, new_q, q1)
    out_q2 = jnp.where(both, new_q, q2)
    return (
        jnp.stack([out_b1, out_b2], axis=-2).astype(bases.dtype),
        jnp.stack([out_q1, out_q2], axis=-2),
    )


def vote_partials(bases, quals, params: ConsensusParams):
    """Per-column partial sums of the vote, reduced over the reads axis.

    bases: int8 [R, W] (4 = no observation), quals: float32 [R, W] Phred.
    Returns (ll [W, 4], depth [W]) — pure sums over reads, so shards of the
    reads axis can compute these locally and psum them (the deep-family
    segmented reduction in parallel.deep_family rides exactly this split).
    """
    observed = (bases != NBASE) & (quals >= params.min_input_base_quality)
    p_err = phred.adjust_quals_post_umi(quals, params.error_rate_post_umi)
    log_ok, log_err = phred.log_likelihoods(p_err)
    onehot = jax.nn.one_hot(bases, NUM_BASES, dtype=jnp.float32)  # [R, W, 4]
    w_obs = jnp.where(observed, 1.0, 0.0)[..., None]
    # LL[w, b] = sum_r obs * (onehot * log_ok + (1 - onehot) * log_err)
    ll = jnp.sum(
        w_obs * (onehot * log_ok[..., None] + (1.0 - onehot) * log_err[..., None]),
        axis=0,
    )  # [W, 4]
    depth = jnp.sum(observed, axis=0).astype(jnp.int32)  # [W]
    return ll, depth


def vote_finalize(ll, depth, params: ConsensusParams):
    """Turn reduced vote sums into (base, qual): argmax + posterior + pre-UMI
    adjustment. Deterministic given (ll, depth) — replicas holding identical
    psum results finalize identically.

    The posterior denominator sums the candidate exponentials in ASCENDING
    VALUE order (not slot order), so the consensus quality is invariant
    under any permutation of which bases the observations happened to be —
    the property ops.reconstruct's (qa, qb, agreement)-indexed qual tables
    rely on — and slightly more accurate (small-to-large summation).
    utils.oracle.oracle_column_vote mirrors the same canonical order.

    Tied columns call the LOWEST base index (fgbio semantics, the
    oracle's `max(range(4), key=...)`): two candidates with identical
    observation multisets are an exact LL tie in real arithmetic, but
    float32 summation order can leave them ulps apart — so the argmax
    runs over a small band below the max rather than raw values. The
    band is an ABSOLUTE log-LL width (a log difference d is a
    likelihood ratio e^-d — the tie criterion is scale-free): 2.5e-6
    sits above one-ulp summation noise at the vote's operating
    magnitudes (ulp(|ll|~20) ~ 1.9e-6, the observed exact-tie wobble)
    and below the 3e-6 ratio the differential suites certify as a
    genuine distinction (tests/fgbio_second_opinion.tied_candidates).
    Columns whose |ll| is large enough that one ulp exceeds the band
    (very deep families) keep the raw argmax — on a true tie there,
    either pick is a correct call; only the canonical choice is
    best-effort.

    The ascending order is produced by a 5-comparator sorting network
    over ll - m BEFORE the exp, not a general sort after it: exp is
    monotone, so sorting the exponents commutes with exponentiating
    them (bitwise — equal inputs give equal outputs, distinct inputs
    keep their order), and the largest exponent is exp(0) == 1.0
    exactly (the max's own slot), so only three exps are evaluated.
    A 4-wide jnp.sort lowers to a general comparator sort that
    dominated the whole finalize on the CPU backend (~8x the network's
    cost at rehearsal shapes); the network is the same ascending-sum
    contract at min/max cost. ops.pallas_vote._finalize runs the same
    network on the same values.
    """
    called = depth > 0
    m = jnp.max(ll, axis=-1, keepdims=True)
    cons = jnp.argmax(ll >= m - ARGMAX_TIE_TOL, axis=-1)  # first near-max [W]
    d = ll - m  # [..., 4], every entry <= 0, the max's slot exactly 0
    a, b = jnp.minimum(d[..., 0], d[..., 1]), jnp.maximum(d[..., 0], d[..., 1])
    c, e = jnp.minimum(d[..., 2], d[..., 3]), jnp.maximum(d[..., 2], d[..., 3])
    a, c = jnp.minimum(a, c), jnp.maximum(a, c)
    b, e = jnp.minimum(b, e), jnp.maximum(b, e)
    b, c = jnp.minimum(b, c), jnp.maximum(b, c)
    # ascending a <= b <= c <= e with e == 0: denom sums small-to-large
    # and the top term is exp(0) == 1.0 exactly
    denom = ((jnp.exp(a) + jnp.exp(b)) + jnp.exp(c)) + 1.0
    # exp(ll[cons] - m) == 1 exactly (cons is the argmax), so the posterior
    # of the call is 1/denom
    p_cons = 1.0 - 1.0 / denom
    p_final = phred.prob_error_two_trials(
        p_cons, phred.phred_to_prob(params.error_rate_pre_umi)
    )
    qual = phred.prob_to_phred(p_final)
    low = qual < params.min_consensus_base_quality
    cons = jnp.where(called & ~low, cons, NBASE).astype(jnp.int8)
    qual = jnp.where(called & ~low, qual, float(NO_CALL_QUAL))
    qual = jnp.round(qual).astype(jnp.uint8)
    return cons, qual


def count_errors(bases, quals, cons, params: ConsensusParams):
    """Per-column count of observations disagreeing with the consensus —
    also a pure sum over reads (psum-able). int32 while reducing; callers
    narrow for transport."""
    observed = (bases != NBASE) & (quals >= params.min_input_base_quality)
    disagree = observed & (cons[..., None, :] != NBASE) & (bases != cons[..., None, :])
    return jnp.sum(jnp.where(disagree, 1, 0), axis=-2).astype(jnp.int32)


def _vote_contrib(bases, quals, params: ConsensusParams):
    """Per-observation vote contributions, 8 channels: LL contribution per
    candidate base (4) then the observation's one-hot count (4).

    bases int8 [..., W], quals float32 [..., W]. Unobserved cells (NBASE or
    below min input qual) contribute exact 0.0 in every channel, so padding
    rows are free to ride any reduction. Kept UNFACTORED (w * (onehot *
    log_ok + (1 - onehot) * log_err)) — the same per-read term
    vote_partials sums — so any order-preserving reduction over these
    contributions reproduces the padded kernel's ll bits exactly.
    """
    observed = (bases != NBASE) & (quals >= params.min_input_base_quality)
    p_err = phred.adjust_quals_post_umi(quals, params.error_rate_post_umi)
    log_ok, log_err = phred.log_likelihoods(p_err)
    onehot = jax.nn.one_hot(bases, NUM_BASES, dtype=jnp.float32)
    w_obs = jnp.where(observed, 1.0, 0.0)[..., None]
    contrib = w_obs * (
        onehot * log_ok[..., None] + (1.0 - onehot) * log_err[..., None]
    )
    return jnp.concatenate([contrib, onehot * w_obs], axis=-1)  # [..., W, 8]


def _split_contrib_sums(sums):
    """(ll, cnt, depth) from reduced 8-channel contribution sums."""
    ll = sums[..., :NUM_BASES]
    cnt = sums[..., NUM_BASES:]
    # per-base counts are exact small integers in float32; their sum is the
    # padded kernel's depth (count of observations) exactly
    depth = jnp.sum(cnt, axis=-1).astype(jnp.int32)
    return ll, cnt, depth


def vote_partials_segments(bases, quals, seg, num_segments: int,
                           params: ConsensusParams):
    """Segment-packed twin of vote_partials: one dense read-row axis for
    ALL families in the batch instead of a padded per-family axis.

    bases int8 [N, ..., W], quals float32 [N, ..., W], seg int32 [N] —
    ascending family ids (padding rows carry the sentinel id
    num_segments - 1 so their exact-zero contributions land in a slice-away
    segment). Returns (ll [S, ..., W, 4], cnt [S, ..., W, 4],
    depth [S, ..., W] int32).

    Bit-identity with the padded path: segment_sum over sorted ids adds
    contributions in row order — the same order jnp.sum reduces the padded
    [T, W] read axis — and unobserved cells contribute exact 0.0
    (_vote_contrib), so the packed ll/cnt/depth match the vmap'd
    vote_partials bit for bit. cnt additionally carries the per-base
    tallies that let errors_from_counts replace the padded path's second
    reads-axis sweep (count_errors).
    """
    sums = jax.ops.segment_sum(
        _vote_contrib(bases, quals, params), seg,
        num_segments=num_segments, indices_are_sorted=True,
    )
    return _split_contrib_sums(sums)


def errors_from_counts(cnt, depth, cons):
    """errors = depth - cnt[consensus] where called — the count trick.

    Integer-exact twin of count_errors: every observation either agrees
    with the consensus (counted in cnt[cons]) or disagrees (an error), so
    the disagreement count is the difference — no second pass over the
    reads axis. Uncalled columns (cons == NBASE) report 0 errors, exactly
    as count_errors' `cons != NBASE` conjunct decides.
    """
    cnt_cons = jnp.take_along_axis(
        cnt, jnp.clip(cons, 0, 3)[..., None].astype(jnp.int32), axis=-1
    )[..., 0]
    return jnp.where(
        cons != NBASE, depth - cnt_cons.astype(jnp.int32), 0
    ).astype(jnp.int32)


def narrow_outputs(out: dict) -> dict:
    """Narrow count dtypes for the device->host hop (the tunnel hop is the
    bottleneck on this hardware — SURVEY.md §6 HBM/host budget): depths and
    errors fit int16 (family depth is bounded by the template bucket, max
    1024), per-strand coverage fits int8."""
    narrow = {"depth": jnp.int16, "errors": jnp.int16, "a_depth": jnp.int8,
              "b_depth": jnp.int8, "a_err": jnp.int8, "b_err": jnp.int8}
    return {k: (v.astype(narrow[k]) if k in narrow else v) for k, v in out.items()}


def column_vote(bases, quals, params: ConsensusParams):
    """Quality-weighted log-likelihood vote.

    bases: int8 [R, W] (4 = no observation), quals: float32 [R, W] Phred.
    Returns dict with per-column consensus arrays (length W):
      base (int8, 4 where uncalled), qual (uint8), depth (int32),
      errors (int32).
    """
    ll, depth = vote_partials(bases, quals, params)
    cons, qual = vote_finalize(ll, depth, params)
    errors = count_errors(bases, quals, cons, params)
    return {"base": cons, "qual": qual, "depth": depth, "errors": errors}


def _family_consensus(bases, quals, params: ConsensusParams):
    """One family [T, 2, W] -> per-role consensus [2, W] dict."""
    quals = quals.astype(jnp.float32)
    if params.consensus_call_overlapping_bases:
        bases, quals = overlap_cocall(bases, quals)
    r1 = column_vote(bases[:, 0, :], quals[:, 0, :], params)
    r2 = column_vote(bases[:, 1, :], quals[:, 1, :], params)
    return jax.tree.map(lambda a, b: jnp.stack([a, b], axis=0), r1, r2)


@partial(jax.jit, static_argnames=("params",))
def molecular_consensus(bases, quals, params: ConsensusParams = ConsensusParams()):
    """Batched molecular consensus.

    bases: int8 [F, T, 2, W], quals: uint8/float32 [F, T, 2, W].
    Returns dict of [F, 2, W] arrays: base, qual, depth (int16),
    errors (int16). min_reads is a family-level filter (fgbio drops whole
    families below it); apply it host-side on meta.n_templates — this kernel
    always emits.
    """
    out = jax.vmap(lambda b, q: _family_consensus(b, q, params))(bases, quals)
    return narrow_outputs(out)


def _vote_finalize_dispatch(ll, depth, params: ConsensusParams,
                            vote_kernel: str):
    """Finalize either on the stock XLA lowering or via the Pallas
    epilogue (ops.pallas_vote.vote_finalize_groups — the same network,
    bit-identical). ONE resolution shared by the packed molecular and
    duplex kernels so the matrix of (layout, vote_kernel) legs can never
    disagree on what 'pallas' means for a packed batch."""
    if vote_kernel == "pallas":
        from bsseqconsensusreads_tpu.ops.pallas_vote import (
            vote_finalize_groups,
        )

        return vote_finalize_groups(ll, depth, params)
    if vote_kernel != "xla":
        raise ValueError(
            f"unknown vote kernel {vote_kernel!r} (want 'xla'|'pallas')"
        )
    return vote_finalize(ll, depth, params)


@partial(jax.jit, static_argnames=("num_families", "params", "vote_kernel"))
def molecular_consensus_packed(
    bases, quals, seg, num_families: int,
    params: ConsensusParams = ConsensusParams(),
    vote_kernel: str = "xla",
):
    """Segment-packed molecular consensus: the ragged-layout twin of
    molecular_consensus, byte-identical output.

    bases int8 [N, 2, W] — every family's template rows concatenated along
    one dense axis (ops.encode.pack_molecular_rows builds it from a padded
    batch); quals uint8/float32 [N, 2, W]; seg int32 [N] ascending family
    ids, padding rows carrying the sentinel id `num_families` (their sums
    land in a sentinel segment sliced away here). Returns the
    molecular_consensus dict of [num_families, 2, W] planes.

    Three structural differences against the padded program, all
    bit-preserving: the vote reduces a segment-sum instead of a
    vmap-over-families sum (same add order — vote_partials_segments), the
    errors plane derives from the per-base counts instead of a second
    reads-axis sweep (errors_from_counts), and no [F, T, 2, W] padding
    envelope is ever materialized on device — issued cells track real
    reads, not the bucket ceiling.
    """
    quals = quals.astype(jnp.float32)
    if params.consensus_call_overlapping_bases:
        bases, quals = overlap_cocall(bases, quals)
    ll, cnt, depth = vote_partials_segments(
        bases, quals, seg, num_families + 1, params
    )
    ll, cnt, depth = ll[:num_families], cnt[:num_families], depth[:num_families]
    cons, qual = _vote_finalize_dispatch(ll, depth, params, vote_kernel)
    errors = errors_from_counts(cnt, depth, cons)
    return narrow_outputs(
        {"base": cons, "qual": qual, "depth": depth, "errors": errors}
    )


@lru_cache(maxsize=8)
def _segment_kernel_cached(vote_kernel: str):
    @partial(jax.jit, static_argnames=("num_families", "params"))
    def fn(bases, quals, seg, num_families: int,
           params: ConsensusParams = ConsensusParams()):
        return pack_molecular_outputs(
            molecular_consensus_packed(
                bases, quals, seg, num_families, params, vote_kernel
            )
        )

    return fn


def packed_molecular_segment_kernel(vote_kernel: str = "xla"):
    """Jitted `fn(rows_b, rows_q, seg, num_families, params) -> packed u32
    wire` for the segment-packed layout — the packed twin of
    packed_molecular_kernel, same 12-plane output wire
    (pack_molecular_outputs), so the retire path is shared verbatim.
    Compiled once per (rows bucket, family bucket, window bucket) shape —
    the shape-bucketing contract that keeps recompiles bounded."""
    return _segment_kernel_cached(vote_kernel)


def _overlap_cocall_np(bases, quals):
    """numpy twin of overlap_cocall for [..., 2, W] tensors.

    Exact for integer-valued quals in ANY dtype: every operation is a
    comparison, sum, or absolute difference of integers, identical in
    int16 and in the jit op's float32. Callers pass int16 — Phreds <= 93
    sum within range and the narrow dtype halves the memory traffic of
    this (host-bound) pass."""
    import numpy as np

    b1, b2 = bases[..., 0, :], bases[..., 1, :]
    q1, q2 = quals[..., 0, :], quals[..., 1, :]
    both = (b1 != NBASE) & (b2 != NBASE)
    agree = both & (b1 == b2)
    disagree = both & (b1 != b2)
    qsum = q1 + q2
    qdiff = np.abs(q1 - q2)
    winner = np.where(q1 >= q2, b1, b2)
    tie = disagree & (qdiff == 0)
    new_b = np.where(agree, b1, np.where(disagree, winner, -1))
    zero = quals.dtype.type(0)
    new_q = np.where(agree, qsum, np.where(disagree, qdiff, zero))
    out_b1 = np.where(both, np.where(tie, NBASE, new_b), b1)
    out_b2 = np.where(both, np.where(tie, NBASE, new_b), b2)
    out_q1 = np.where(both, new_q, q1)
    out_q2 = np.where(both, new_q, q2)
    return (
        np.stack([out_b1, out_b2], axis=-2).astype(bases.dtype),
        np.stack([out_q1, out_q2], axis=-2),
    )


def singleton_consensus_host(bases, quals,
                             params: ConsensusParams = ConsensusParams(),
                             vote_kernel: str = "xla",
                             with_histogram: bool = False) -> dict:
    """Host fast path for T == 1 batches: numerically identical to
    molecular_consensus on [F, 1, 2, W] with no device round trip.

    ~70% of real cfDNA families are singletons (BASELINE config 5 / the
    SCALE mixture); their "vote" is the R1/R2 overlap co-call followed by
    a single-observation finalize — a pure function of the (possibly
    summed) qual, served from the kernel-built single-obs tables
    (ops.reconstruct.qual_tables, so XLA-vs-Pallas rounding is captured).
    At scale these families skip encode-to-device, the wire, and the
    kernel entirely. The tables also carry the kernel's two non-obvious
    base verdicts: the masked call (N) and the low-qual ARGMAX FLIP —
    an observation with post-UMI error probability > 0.75 makes every
    other base likelier, so the call becomes the lowest-index other base
    with one counted error, exactly as the device kernel decides.
    """
    import numpy as np

    f, t, _, w = bases.shape
    if t != 1:
        raise ValueError(f"singleton path needs T == 1 batches, got T={t}")
    from bsseqconsensusreads_tpu.ops.reconstruct import qual_tables

    t_single, _a, _d, t_masked, t_flip = qual_tables(params, vote_kernel)
    b = np.asarray(bases)[:, 0]  # [F, 2, W]
    q = np.asarray(quals)[:, 0].astype(np.int16)
    if params.consensus_call_overlapping_bases:
        b, q = _overlap_cocall_np(b, q)
    observed = (b != NBASE) & (q >= params.min_input_base_quality)
    # co-called quals are sums of two Phreds <= 93 each: always < 256
    qi = np.clip(q, 0, 255).astype(np.uint8)
    masked = t_masked[qi]
    flip = t_flip[qi]
    # argmax ties across the three other bases resolve to the lowest index
    call = np.where(flip, np.where(b == 0, 1, 0), b)
    called = observed & ~masked
    from bsseqconsensusreads_tpu.ops.phred import NO_CALL_QUAL

    out = {
        "base": np.where(called, call, NBASE).astype(np.int8),
        "qual": np.where(called, t_single[qi], NO_CALL_QUAL).astype(np.uint8),
        "depth": observed.astype(np.int16),
        "errors": (called & flip).astype(np.int16),
    }
    if with_histogram:
        # the cB tag payload from THIS pass's cocalled observations —
        # identical to molecular_base_counts(bases, quals) on the T == 1
        # batch, without a second cocall+filter sweep in the emit span
        # (the r5 ledger's molecular-emit wall was exactly that rework)
        counts = np.empty(b.shape[:2] + (NUM_BASES, b.shape[-1]), np.uint16)
        for x in range(NUM_BASES):
            counts[:, :, x, :] = observed & (b == x)
        out["bcount"] = counts
    return out


def pack_molecular_outputs(out: dict):
    """Pack the molecular output dict into one family-major planar u32 wire.

    Same rationale as models.duplex.pack_duplex_outputs: the tunneled D2H
    hop pays a fixed cost per array and compresses byte streams, so the
    four per-column arrays ride ONE flat array as per-family byte planes
    ([F, 12, W] u8 rows): 0-1 base, 2-3 qual, 4-5 depth lo, 6-7 depth hi,
    8-9 errors lo, 10-11 errors hi (role-major within each pair; u16
    counts split into byte planes — the hi planes are ~all zero at normal
    depths, which the tunnel's compressor collapses). The family axis
    stays leading so shard_map concatenation preserves the layout.
    Unpack host-side with unpack_molecular_outputs.
    """
    d8 = jax.lax.bitcast_convert_type(
        out["depth"].astype(jnp.uint16), jnp.uint8
    )  # [..., F, 2, W, 2] little-endian
    e8 = jax.lax.bitcast_convert_type(out["errors"].astype(jnp.uint16), jnp.uint8)
    planes = jnp.concatenate(
        [
            out["base"].astype(jnp.uint8),
            out["qual"].astype(jnp.uint8),
            d8[..., 0], d8[..., 1],
            e8[..., 0], e8[..., 1],
        ],
        axis=-2,
    )  # [..., F, 12, W]
    return jax.lax.bitcast_convert_type(
        planes.reshape(-1, 4), jnp.uint32
    ).reshape(-1)


def unpack_molecular_outputs(wire, f: int, w: int) -> dict:
    """numpy inverse of pack_molecular_outputs -> dict of [f, 2, w] arrays
    (host side)."""
    import numpy as np

    wire = np.asarray(wire)
    u8 = wire.view(np.uint8) if wire.dtype != np.uint8 else wire
    planes = u8[: f * 12 * w].reshape(f, 12, w)
    depth = (
        planes[:, 4:6].astype(np.uint16)
        | (planes[:, 6:8].astype(np.uint16) << 8)
    ).astype(np.int16)
    errors = (
        planes[:, 8:10].astype(np.uint16)
        | (planes[:, 10:12].astype(np.uint16) << 8)
    ).astype(np.int16)
    return {
        "base": planes[:, 0:2].astype(np.int8),
        "qual": planes[:, 2:4].copy(),
        "depth": depth,
        "errors": errors,
    }


@lru_cache(maxsize=64)
def _packed_kernel_cached(kernel_fn):
    @partial(jax.jit, static_argnames=("params",))
    def fn(bases, quals, params: ConsensusParams = ConsensusParams()):
        return pack_molecular_outputs(kernel_fn(bases, quals, params))

    return fn


def packed_molecular_kernel(kernel_fn=None):
    """Jitted `kernel_fn(bases, quals, params) -> packed u32 wire` for any
    molecular-consensus kernel (stock XLA vote or the Pallas one). Cached
    per kernel so repeated pipeline batches reuse one compiled program."""
    return _packed_kernel_cached(kernel_fn or molecular_consensus)


def pack_molecular_slim_outputs(out: dict):
    """Tunnel-wire pack: base + qual planes ONLY ([F, 4, W] u8 rows —
    base of R1/R2 then qual of R1/R2 — flattened to u32).

    A third of pack_molecular_outputs' bytes: per-column depth and error
    counts are pure integer tallies over the observation tensors the
    host itself encoded, so the wire-path retire recomputes them exactly
    (recompute_molecular_counts) instead of shipping 8 count byte-planes
    through the tunnel."""
    planes = jnp.concatenate(
        [out["base"].astype(jnp.uint8), out["qual"].astype(jnp.uint8)],
        axis=-2,
    )  # [..., F, 4, W]
    return jax.lax.bitcast_convert_type(
        planes.reshape(-1, 4), jnp.uint32
    ).reshape(-1)


def unpack_molecular_slim_outputs(wire, f: int, w: int) -> dict:
    """numpy inverse of pack_molecular_slim_outputs -> base/qual [f, 2, w];
    complete the dict with recompute_molecular_counts."""
    import numpy as np

    wire = np.asarray(wire)
    u8 = wire.view(np.uint8) if wire.dtype != np.uint8 else wire
    planes = u8[: f * 4 * w].reshape(f, 4, w)
    return {
        "base": planes[:, 0:2].astype(np.int8),
        "qual": planes[:, 2:4].copy(),
    }


def recompute_molecular_counts(out: dict, bases, quals,
                               params: ConsensusParams,
                               with_histogram: bool = False) -> dict:
    """Fill depth/errors from the host's own input tensors — exact.

    depth and errors are integer counts over exact comparisons (the
    overlap co-call twin _overlap_cocall_np mirrors the jit op on
    integer-valued quals), so no float rounding is involved: the result
    is bit-identical to the kernel's shipped planes, at a few numpy
    passes per batch instead of 8 tunnel byte-planes.

    with_histogram: also stash the cB raw base histogram in
    out['bcount'] and DERIVE depth/errors from it (depth = counts summed
    over bases; errors = depth - counts[consensus] where called) — one
    cocall+filter pass instead of two when the emit path needs the
    histogram anyway (the r5 exact-ce tag surface).
    """
    import numpy as np

    b = np.asarray(bases)  # [F, T, 2, W]
    q = np.asarray(quals).astype(np.int16)
    if params.consensus_call_overlapping_bases:
        b, q = _overlap_cocall_np(b, q)
    observed = (b != NBASE) & (q >= params.min_input_base_quality)
    cons = np.asarray(out["base"])  # [F, 2, W]
    out = dict(out)
    if with_histogram:
        counts = _base_histogram(b, observed)
        out["bcount"] = counts
        depth = counts.sum(axis=2, dtype=np.int32).astype(np.int16)
        cnt_cons = np.take_along_axis(
            counts, np.clip(cons, 0, 3)[:, :, None, :].astype(np.int64),
            axis=2,
        )[:, :, 0, :].astype(np.int16)
        out["depth"] = depth
        out["errors"] = np.where(cons != NBASE, depth - cnt_cons, 0).astype(
            np.int16
        )
        return out
    out["depth"] = observed.sum(axis=1).astype(np.int16)
    out["errors"] = (
        (observed & (cons[:, None] != NBASE) & (b != cons[:, None]))
        .sum(axis=1).astype(np.int16)
    )
    return out


def _base_histogram(b, observed):
    """uint16 [F, 2, 4, W] per-base counts over co-called observations —
    the ONE tally shared by molecular_base_counts and the slim-wire
    retire (recompute_molecular_counts with_histogram), so the cB tag
    payload and the kernel-identical depth/errors derivation can never
    desynchronize."""
    import numpy as np

    f, _t, _r, w = b.shape
    counts = np.empty((f, 2, NUM_BASES, w), np.uint16)
    for x in range(NUM_BASES):
        counts[:, :, x, :] = (observed & (b == x)).sum(axis=1)
    return counts


def molecular_base_counts(bases, quals, params: ConsensusParams) -> "np.ndarray":
    """Per-column raw base histogram: uint16 [F, 2, 4, W].

    counts[f, role, x, i] = observations of base x at column i, under the
    SAME observation filter as the vote (post overlap-cocall, min input
    qual) — so counts.sum over x == the kernel's depth plane exactly, and
    depth - counts[consensus] == the kernel's errors plane wherever the
    consensus called. This is the payload of the molecular emitters' cB
    tag: the duplex stage consumes it to count raw reads against the
    DUPLEX call exactly (pipeline.calling._duplex_rawize), closing the
    round-4 ce approximation (PARITY.md row 6). Host-side numpy — the
    integer tallies need no device round trip (same rationale as
    recompute_molecular_counts).
    """
    import numpy as np

    b = np.asarray(bases)  # [F, T, 2, W]
    q = np.asarray(quals).astype(np.int16)
    if params.consensus_call_overlapping_bases:
        b, q = _overlap_cocall_np(b, q)
    observed = (b != NBASE) & (q >= params.min_input_base_quality)
    return _base_histogram(b, observed)


def sparsify_base_counts(counts, base) -> "np.ndarray":
    """Zero the CONSENSUS-CALL plane of the cB histogram (new array).

    The call plane is derivable (cd - ce at called columns) and carries
    ~all of the histogram's mass; storing it zero makes the cB tag a
    sparse DISSENT histogram that deflates to almost nothing in the
    intermediate BAM (the dense form doubled the molecular stage output
    at scale). Columns whose consensus is masked (NBASE) keep all four
    planes — nothing is derivable there. The duplex exact-ce consumer
    (pipeline.calling._exact_strand_errors) only ever reads dissent
    cells, so no reconstruction is needed downstream."""
    import numpy as np

    counts = np.asarray(counts).copy()  # [F, 2, 4, W]
    base = np.asarray(base)  # [F, 2, W]
    called = base != NBASE
    sel = np.clip(base, 0, 3)[:, :, None, :].astype(np.int64)
    plane = np.take_along_axis(counts, sel, axis=2)
    np.put_along_axis(
        counts, sel, np.where(called[:, :, None, :], 0, plane), axis=2
    )
    return counts


@lru_cache(maxsize=64)
def _wire_kernel_cached(kernel_fn):
    @partial(jax.jit, static_argnames=("f", "t", "w", "params", "qual_mode"))
    def fn(
        words, f: int, t: int, w: int,
        params: ConsensusParams = ConsensusParams(),
        qual_mode: str = "q8",
    ):
        from bsseqconsensusreads_tpu.ops.wire import (
            split_duplex_wire,
            unpack_duplex_inputs,
        )

        r = t * 2
        nib, qual, meta, _starts, _limits = split_duplex_wire(
            words, f, w, r=r, qual_mode=qual_mode
        )
        bases, quals, _cover, _cm, _el = unpack_duplex_inputs(
            nib, qual, meta, f, w, r=r, qual_mode=qual_mode
        )
        out = kernel_fn(
            bases.reshape(f, t, 2, w), quals.reshape(f, t, 2, w), params
        )
        return pack_molecular_slim_outputs(out)

    return fn


def molecular_wire_kernel(kernel_fn=None):
    """Jitted `fn(words, f, t, w, params, qual_mode) -> packed u32 wire`:
    the tunnel-optimal molecular stage — ONE u32 array each way. Input is
    ops.wire.pack_molecular_inputs' 2T-row wire (4 bits/cell bases, the
    adaptive qual codebook) split and unpacked on device; output is the
    SLIM planar wire (pack_molecular_slim_outputs: base+qual planes only
    — the retire side recomputes the count planes exactly with
    recompute_molecular_counts). ~4x fewer H2D bytes than the unpacked
    [F,T,2,W] int8+uint8 pair and 3x fewer D2H bytes than the full
    packed wire on a transfer-bound link, bit-identical results (the
    codebook is lossless, the counts are exact integer tallies)."""
    return _wire_kernel_cached(kernel_fn or molecular_consensus)


@lru_cache(maxsize=8)
def _rows_wire_kernel_cached(vote_kernel: str):
    @partial(jax.jit, static_argnames=(
        "n_rows", "num_families", "w", "params", "qual_mode"
    ))
    def fn(
        words, n_rows: int, num_families: int, w: int,
        params: ConsensusParams = ConsensusParams(),
        qual_mode: str = "q8",
    ):
        from bsseqconsensusreads_tpu.ops.wire import (
            split_molecular_rows_wire,
            unpack_rows_wire_inputs,
        )

        nib, qual, seg, _offsets = split_molecular_rows_wire(
            words, n_rows, num_families, w, qual_mode=qual_mode
        )
        bases, quals = unpack_rows_wire_inputs(
            nib, qual, n_rows, w, qual_mode=qual_mode
        )
        out = molecular_consensus_packed(
            bases, quals, seg.astype(jnp.int32), num_families, params,
            vote_kernel,
        )
        return pack_molecular_slim_outputs(out)

    return fn


def molecular_wire_packed_kernel(vote_kernel: str = "xla"):
    """Jitted `fn(words, n_rows, num_families, w, params, qual_mode) ->
    slim u32 wire`: the wire route on the segment-packed row layout.

    Input is ops.wire.pack_molecular_rows_wire's v2 wire (header +
    offsets/seg planes + the dense-row nib/qual body) — the wire's cell
    count tracks real reads instead of the [F, T, 2, W] bucket ceiling,
    so round-robin dispatch ships and votes only what was sequenced. The
    vote is the stock segment-sum kernel (molecular_consensus_packed,
    bit-identical to the padded envelope); the output is the same SLIM
    wire as molecular_wire_kernel, so the retire path
    (recompute_molecular_counts against the host envelope) is shared
    verbatim across wire versions."""
    return _rows_wire_kernel_cached(vote_kernel)
