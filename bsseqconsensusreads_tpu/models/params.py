"""Consensus error-model parameters.

The reference hardcodes these numbers in Snakemake rule bodies
(reference: main.snake.py:54,163); this framework promotes them to config
(SURVEY.md §5.6). Defaults reproduce the reference's exact flag values:

  --error-rate-pre-umi=45 --error-rate-post-umi=30
  --min-input-base-quality=0 --min-consensus-base-quality=0
  --consensus-call-overlapping-bases=true
  --min-reads=1 (molecular, main.snake.py:54) / 0 (duplex, main.snake.py:163)
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class ConsensusParams:
    """Hashable (usable as a jit static arg) consensus parameter set."""

    error_rate_pre_umi: float = 45.0
    error_rate_post_umi: float = 30.0
    min_input_base_quality: int = 0
    min_consensus_base_quality: int = 0
    consensus_call_overlapping_bases: bool = True
    min_reads: int = 1

    def replace(self, **kw) -> "ConsensusParams":
        return dataclasses.replace(self, **kw)


MOLECULAR_DEFAULTS = ConsensusParams(min_reads=1)
DUPLEX_DEFAULTS = ConsensusParams(min_reads=0)
