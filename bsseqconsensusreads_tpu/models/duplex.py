"""Duplex consensus kernel: merge A- and B-strand single-strand consensi.

TPU-native equivalent of `fgbio CallDuplexConsensusReads` as invoked by the
reference (main.snake.py:163): per MI group, combine the converted,
coordinate-harmonized strand reads into one duplex read pair, with
--min-reads=0 semantics — emit everything, including groups where only one
strand survived (README.md:9 "not filtered").

After convert_ag_to_ct + extend_gap, a duplex family is a [4, W] window
tensor with rows (99, 163, 83, 147). The duplex R1 merges rows (99, 163)
(the two forward-mapped strand reads covering the top-strand window); the
duplex R2 merges rows (83, 147). Each merge is the same quality-weighted
log-likelihood vote as the molecular stage, with depth <= 2 — reproducing
the reference pipeline's configuration, which feeds molecular-consensus reads
back through the same fgbio error model (error-rate-pre-umi=45,
error-rate-post-umi=30) a second time.

Strand bookkeeping for tags: rows 99/147 are A-strand, rows 163/83 are
B-strand; per-column per-strand depths are emitted so the writer can produce
aD/bD-style annotations alongside cD/cM/cE.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from bsseqconsensusreads_tpu.alphabet import NBASE
from bsseqconsensusreads_tpu.models.molecular import (
    _split_contrib_sums,
    _vote_contrib,
    _vote_finalize_dispatch,
    column_vote,
    errors_from_counts,
    narrow_outputs,
)
from bsseqconsensusreads_tpu.models.params import ConsensusParams
from bsseqconsensusreads_tpu.ops.convert import convert_ag_to_ct
from bsseqconsensusreads_tpu.ops.extend import (
    ROW_83,
    ROW_99,
    ROW_147,
    ROW_163,
    extend_gap,
)

# (rows merged, A-strand row, B-strand row) for duplex R1 and R2.
R1_ROWS = (ROW_99, ROW_163)
R2_ROWS = (ROW_83, ROW_147)
A_ROWS = (ROW_99, ROW_147)
#: (a_row, b_row) per emitted role — the single derivation the host-side
#: raw-depth threading (pipeline.calling) and qual reconstruction
#: (ops.reconstruct) both import, so they can never desync from _merge.
ROLE_STRAND_ROWS = tuple(
    (rr[0], rr[1]) if rr[0] in A_ROWS else (rr[1], rr[0])
    for rr in (R1_ROWS, R2_ROWS)
)


def _merge(bases, quals, rows, params):
    b = jnp.stack([bases[..., r, :] for r in rows], axis=-2)
    q = jnp.stack([quals[..., r, :] for r in rows], axis=-2)
    out = column_vote(b, q, params)
    a_row, b_row = (rows[0], rows[1]) if rows[0] in A_ROWS else (rows[1], rows[0])
    # per-strand depths use the same observation filter as the vote, so
    # a_depth + b_depth == depth always (the packed wire format relies on
    # it); per-strand error bits split count_errors the same way, so
    # a_err + b_err == errors — the wire ships the per-strand bits and
    # derives the totals host-side
    for key, err, row in (
        ("a_depth", "a_err", a_row), ("b_depth", "b_err", b_row)
    ):
        obs = (
            (bases[..., row, :] != NBASE)
            & (quals[..., row, :] >= params.min_input_base_quality)
        )
        out[key] = obs.astype(jnp.int32)
        out[err] = (
            obs
            & (out["base"] != NBASE)
            & (bases[..., row, :] != out["base"])
        ).astype(jnp.int32)
    return out


def _family_duplex(bases, quals, params):
    r1 = _merge(bases, quals, R1_ROWS, params)
    r2 = _merge(bases, quals, R2_ROWS, params)
    return jax.tree.map(lambda a, b: jnp.stack([a, b], axis=0), r1, r2)


@partial(jax.jit, static_argnames=("params",))
def duplex_consensus(bases, quals, params: ConsensusParams = ConsensusParams(min_reads=0)):
    """Batched duplex merge.

    bases: int8 [F, 4, W] (rows 99/163/83/147, NBASE where uncovered),
    quals: float32/uint8 [F, 4, W].
    Returns dict of [F, 2, W] arrays: base, qual, depth, errors,
    a_depth, b_depth. Roles: 0 = duplex R1, 1 = duplex R2.
    """
    quals = quals.astype(jnp.float32)
    out = jax.vmap(lambda b, q: _family_duplex(b, q, params))(bases, quals)
    return narrow_outputs(out)


#: Flat row order of the packed duplex layout: the two R1 merge rows then
#: the two R2 merge rows — matching _merge's stack order per role, so the
#: packed pair-sum adds observations in the same order as the padded vote.
_PACKED_ROW_ORDER = R1_ROWS + R2_ROWS


@partial(jax.jit, static_argnames=("params", "vote_kernel"))
def duplex_consensus_packed(
    bases, quals,
    params: ConsensusParams = ConsensusParams(min_reads=0),
    vote_kernel: str = "xla",
):
    """Segment-packed duplex merge: byte-identical to duplex_consensus.

    bases int8 [F, 4, W] / quals [F, 4, W] (same input as duplex_consensus
    — duplex groups always carry exactly 4 rows, so 'packing' here is the
    layout recast, not a gather): the rows regroup as merge pairs
    [F * 2 groups, 2 rows, W] (_PACKED_ROW_ORDER) and ONE dense pair-axis
    reduction votes every group, replacing the vmap-over-families
    stack-and-vote (_merge -> column_vote) with the shared contribution
    sum. A 2-row segment is a fixed-size segment, so the segment-sum
    degenerates to a plain axis sum — same add order, no scatter. The
    finalize is the shared sorting-network epilogue
    (molecular._vote_finalize_dispatch: 'xla' inline or the Pallas
    epilogue), the errors plane the count trick (errors_from_counts), and
    the per-strand planes stay elementwise XLA exactly as _merge computes
    them.
    """
    quals = quals.astype(jnp.float32)
    f, _, w = bases.shape
    order = list(_PACKED_ROW_ORDER)
    b = bases[:, order, :].reshape(f * 2, 2, w)
    q = quals[:, order, :].reshape(f * 2, 2, w)
    # [F*2, 2, W, 8] contributions summed over the in-group row axis: row
    # order inside each pair matches _merge's stack order, so the two adds
    # land in the padded kernel's order
    ll, cnt, depth = _split_contrib_sums(
        jnp.sum(_vote_contrib(b, q, params), axis=1)
    )
    cons, qual = _vote_finalize_dispatch(ll, depth, params, vote_kernel)
    errors = errors_from_counts(cnt, depth, cons)
    out = {
        "base": cons.reshape(f, 2, w),
        "qual": qual.reshape(f, 2, w),
        "depth": depth.reshape(f, 2, w),
        "errors": errors.reshape(f, 2, w),
    }
    # per-strand presence/error planes: elementwise over the original rows
    # (the same observation filter as the vote — _merge's contract that
    # a_depth + b_depth == depth and a_err + b_err == errors)
    for key, err, rows in (
        ("a_depth", "a_err", [rr[0] for rr in ROLE_STRAND_ROWS]),
        ("b_depth", "b_err", [rr[1] for rr in ROLE_STRAND_ROWS]),
    ):
        rb = bases[:, rows, :]  # [F, 2(role), W]
        rq = quals[:, rows, :]
        obs = (rb != NBASE) & (rq >= params.min_input_base_quality)
        out[key] = obs.astype(jnp.int32)
        out[err] = (
            obs & (out["base"] != NBASE) & (rb != out["base"])
        ).astype(jnp.int32)
    return narrow_outputs(out)


@partial(jax.jit, static_argnames=("params", "vote_kernel", "layout"))
def duplex_call_pipeline(
    bases, quals, cover, ref, convert_mask, extend_eligible=None,
    params: ConsensusParams = ConsensusParams(min_reads=0),
    vote_kernel: str = "xla",
    layout: str = "padded",
):
    """The fused TPU duplex stage: AG->CT conversion -> gap extension ->
    duplex merge, one compiled program per batch shape.

    Replaces the reference's four-process chain convert_Bstrain -> extend ->
    groupsort_convert -> callduplex (main.snake.py:121-164): the
    TemplateCoordinate sort is obviated because families are already grouped
    on the family axis. Inputs are DuplexBatch arrays; returns the
    duplex_consensus output dict plus 'la'/'rd' [F, 4] for parity inspection.

    vote_kernel: 'xla' (stock lowering) or 'pallas' for the merge step;
    convert/extend stay XLA either way.

    layout: 'packed' (duplex_consensus_packed — the segment-packed merge,
    pipeline.calling's default via BSSEQ_TPU_KERNEL_LAYOUT) or 'padded'
    (the vmap-over-families vote; with vote_kernel='pallas' this is
    ops.pallas_vote.duplex_consensus_pallas, the fused VMEM-streaming
    reduction). Byte-identical outputs on every leg.
    """
    b, q, c, la, rd = convert_ag_to_ct(bases, quals, cover, ref, convert_mask)
    b, q, c = extend_gap(b, q, c, la, rd, extend_eligible)
    b = jnp.where(c, b, NBASE)
    if layout == "packed":
        out = duplex_consensus_packed(b, q, params, vote_kernel)
    elif layout != "padded":
        raise ValueError(
            f"unknown kernel layout {layout!r} (want 'packed'|'padded')"
        )
    elif vote_kernel == "pallas":
        from bsseqconsensusreads_tpu.ops.pallas_vote import (
            duplex_consensus_pallas,
        )

        out = duplex_consensus_pallas(b, q, params)
    elif vote_kernel == "xla":
        out = duplex_consensus(b, q, params)
    else:
        raise ValueError(f"unknown vote kernel {vote_kernel!r} (want 'xla'|'pallas')")
    out["la"] = la
    out["rd"] = rd
    return out


def _duplex_b0(out: dict):
    """The duplex per-column byte: base(3b) | a_depth<<3 | b_depth<<4 |
    a_err<<5 | b_err<<6 (bit 7 spare).  depth/errors are derived sums, so
    one byte carries the complete per-column call except the qual — which
    the wire format omits entirely (ops.reconstruct rebuilds it host-side
    from the shipped strand bits + the host's own input quals, exactly)."""
    return (
        out["base"].astype(jnp.uint8)
        | (out["a_depth"].astype(jnp.uint8) << 3)
        | (out["b_depth"].astype(jnp.uint8) << 4)
        | (out["a_err"].astype(jnp.uint8) << 5)
        | (out["b_err"].astype(jnp.uint8) << 6)
    )


def _decode_b0(b0, np):
    a_depth = ((b0 >> 3) & 0x1).astype(np.int8)
    b_depth = ((b0 >> 4) & 0x1).astype(np.int8)
    a_err = ((b0 >> 5) & 0x1).astype(np.int8)
    b_err = ((b0 >> 6) & 0x1).astype(np.int8)
    return {
        "base": (b0 & 0x7).astype(np.int8),
        "depth": (a_depth + b_depth).astype(np.int16),
        "errors": (a_err + b_err).astype(np.int16),
        "a_depth": a_depth,
        "b_depth": b_depth,
        "a_err": a_err,
        "b_err": b_err,
    }


def pack_duplex_outputs(out: dict):
    """Pack the per-column duplex outputs into one planar u32 wire array.

    The device->host hop on tunneled TPU hosts is latency- and
    bandwidth-bound (~66 ms/fetch + ~25-34 MB/s measured, entropy-dependent:
    the tunnel compresses); six separate array fetches per batch dominate
    the stage. Duplex columns fit 2 bytes, laid out FAMILY-MAJOR PLANAR —
    per family, the byte0 planes of both roles then the qual planes
    ([F, 4, W] u8: rows 0-1 = b0 of R1/R2 (_duplex_b0 layout), rows 2-3 =
    qual of R1/R2).

    Planar order groups same-distribution bytes into W-length runs, which
    the tunnel's compressor exploits — both planes draw from small value
    sets, so separating them raises the compression ratio and with it the
    effective D2H rate. The family axis stays leading so shard_map's
    per-device concatenation (parallel.sharding.sharded_duplex_packed)
    preserves the layout. la/rd ride separately (tiny [..., 4] int8).
    Unpack host-side with unpack_duplex_outputs.

    This is the NON-wire packed format (used where the transfer is free,
    e.g. the CPU backend's sharded path — the qual plane costs nothing
    there and saves the host reconstruction); the tunnel wire ships
    pack_duplex_b0_outputs instead, at half the bytes.
    """
    planar = jnp.concatenate(
        [_duplex_b0(out), out["qual"].astype(jnp.uint8)], axis=-2
    )  # [..., F, 4, W]
    # Flatten to 1D u32 for the wire: the tunnel moves 1D word-sized arrays
    # ~2x faster than small-minor-dim u8 arrays (measured 34 vs 18 MB/s).
    return jax.lax.bitcast_convert_type(
        planar.reshape(-1, 4), jnp.uint32
    ).reshape(-1)


def pack_duplex_b0_outputs(out: dict):
    """Tunnel-wire pack: the b0 planes ONLY ([..., F, 2, W] u8 -> flat u32).

    Half the D2H bytes of pack_duplex_outputs: consensus quals are a
    deterministic function of (the observation quals the host already
    holds, the per-strand presence/error bits in b0), so they are
    reconstructed host-side (ops.reconstruct) instead of shipped — the
    output direction drops below the input direction, flipping the
    tunnel bottleneck back to H2D (BENCH wire metrics track both).
    """
    return jax.lax.bitcast_convert_type(
        _duplex_b0(out).reshape(-1, 4), jnp.uint32
    ).reshape(-1)


def unpack_duplex_outputs(packed, f: int, w: int) -> dict:
    """Inverse of pack_duplex_outputs (host side): family-major planar
    u32/u8 wire -> dict of [f, 2, w] arrays. Uses the native C++ sweep
    (io.wirepack) when available; numpy otherwise."""
    import numpy as np

    packed = np.asarray(packed)
    u8 = packed.view(np.uint8) if packed.dtype != np.uint8 else packed
    from bsseqconsensusreads_tpu.io import wirepack

    if wirepack.available():
        return wirepack.unpack_duplex_outputs(u8, f=f, w=w)
    planes = u8[: f * 4 * w].reshape(f, 4, w)
    out = _decode_b0(planes[:, :2, :], np)
    out["qual"] = planes[:, 2:, :]
    return out


def unpack_duplex_b0_outputs(packed, f: int, w: int) -> dict:
    """Inverse of pack_duplex_b0_outputs (host side) -> [f, 2, w] arrays;
    no 'qual' key — reconstruct it with ops.reconstruct. Native C++ sweep
    when built, numpy fallback otherwise."""
    import numpy as np

    packed = np.asarray(packed)
    u8 = packed.view(np.uint8) if packed.dtype != np.uint8 else packed
    from bsseqconsensusreads_tpu.io import wirepack

    if wirepack.available():
        return wirepack.unpack_duplex_b0(u8, f=f, w=w)
    return _decode_b0(u8[: f * 2 * w].reshape(f, 2, w), np)


@partial(jax.jit, static_argnames=(
    "f", "w", "params", "qual_mode", "vote_kernel", "layout"
))
def duplex_call_wire(
    nib, qual, meta, starts, limits, genome,
    f: int, w: int,
    params: ConsensusParams = ConsensusParams(min_reads=0),
    qual_mode: str = "q8",
    vote_kernel: str = "xla",
    layout: str = "padded",
):
    """The tunnel-optimal fused duplex stage: ONE flat u32 array each way.

    Inputs are the ops.wire packed arrays plus the device-resident genome
    (ops.refstore) — per-family reference windows are gathered on device, so
    the wire carries 4 bits/cell of bases+cover, 1 B/cell of quals, and
    8 B/family of offsets instead of the ~5 B/cell of the unpacked path.

    Returns one u32 wire array: pack_duplex_b0_outputs columns
    [f*2*w/4 words] followed by la/rd bytes [ceil(f/4) words]; split
    host-side with unpack_duplex_wire_outputs (quals are reconstructed
    there, not shipped — see pack_duplex_b0_outputs).
    """
    from bsseqconsensusreads_tpu.ops.refstore import gather_windows
    from bsseqconsensusreads_tpu.ops.wire import pack_lard, unpack_duplex_inputs

    bases, quals, cover, convert_mask, eligible = unpack_duplex_inputs(
        nib, qual, meta, f, w, qual_mode=qual_mode
    )
    ref = gather_windows(genome, starts, limits, w + 1)
    out = duplex_call_pipeline(
        bases, quals, cover, ref, convert_mask, eligible, params=params,
        vote_kernel=vote_kernel, layout=layout,
    )
    packed = pack_duplex_b0_outputs(out)
    return jnp.concatenate([packed, pack_lard(out["la"], out["rd"])])


@partial(jax.jit, static_argnames=(
    "f", "w", "params", "qual_mode", "r", "vote_kernel", "layout"
))
def duplex_call_wire_fused(
    words, genome, f: int, w: int,
    params: ConsensusParams = ConsensusParams(min_reads=0),
    qual_mode: str = "q8",
    r: int = 4,
    vote_kernel: str = "xla",
    layout: str = "padded",
):
    """duplex_call_wire with ONE u32 input array (DuplexWire.to_words()).

    The five wire sections (starts, limits, meta, nib, qual) ride a single
    H2D transfer and are split on device at static offsets — the tunnel's
    ~0.1 s-class fixed cost per transfer is paid once per direction per
    batch, completing the one-array-per-direction design this module's wire
    format exists for.
    """
    from bsseqconsensusreads_tpu.ops.wire import split_duplex_wire

    if r != 4:
        raise ValueError(
            f"duplex windows have 4 rows (flags 99/163/83/147); got r={r}"
        )
    nib, qual, meta, starts, limits = split_duplex_wire(
        words, f, w, r=r, qual_mode=qual_mode
    )
    return duplex_call_wire(
        nib, qual, meta, starts, limits, genome, f, w, params, qual_mode,
        vote_kernel, layout,
    )


def unpack_duplex_wire_outputs(wire, f: int, w: int) -> dict:
    """numpy split+unpack of the duplex_call_wire result (host side).

    No 'qual' key — the wire ships b0 planes only; callers reconstruct
    quals with ops.reconstruct.reconstruct_duplex_quals."""
    from bsseqconsensusreads_tpu.ops.wire import unpack_lard
    import numpy as np

    wire = np.asarray(wire)
    b0_words = f * 2 * w // 4
    out = unpack_duplex_b0_outputs(wire[:b0_words], f=f, w=w)
    out["la"], out["rd"] = unpack_lard(wire[b0_words:], f)
    return out


@partial(jax.jit, static_argnames=("params", "vote_kernel", "layout"))
def duplex_call_pipeline_packed(
    bases, quals, cover, ref, convert_mask, extend_eligible,
    params: ConsensusParams = ConsensusParams(min_reads=0),
    vote_kernel: str = "xla",
    layout: str = "padded",
):
    """duplex_call_pipeline with per-column outputs packed for one fetch.

    Returns (packed uint32 [F*2*W*2/4] wire array, la int8 [F, 4],
    rd int8 [F, 4]); unpack with unpack_duplex_outputs(packed, f, w).
    layout selects the merge layout (see duplex_call_pipeline) — the wire
    bytes are identical either way.
    """
    out = duplex_call_pipeline(
        bases, quals, cover, ref, convert_mask, extend_eligible, params=params,
        vote_kernel=vote_kernel, layout=layout,
    )
    return pack_duplex_outputs(out), out["la"], out["rd"]


# ---- methylation epilogue variants (methyl/context.py) -------------------
#
# Each mirrors its plain counterpart with the fused per-column methylation
# epilogue bolted onto the SAME traced program: the epilogue reads the RAW
# pre-conversion planes (ops.convert erases the bottom-strand signal) plus
# the vote's base plane, so fusing it here costs two extra u8 planes of
# output and no extra pass over the batch.


@partial(jax.jit, static_argnames=("params", "vote_kernel", "layout"))
def duplex_call_pipeline_packed_methyl(
    bases, quals, cover, ref, convert_mask, extend_eligible, ref_ext,
    params: ConsensusParams = ConsensusParams(min_reads=0),
    vote_kernel: str = "xla",
    layout: str = "padded",
):
    """duplex_call_pipeline_packed + fused methyl epilogue.

    ref_ext int8 [F, W + 4]: the bounded extension window
    (ops.refstore.gather_windows_ext / host_windows_ext — host-gathered on
    this path, where the transfer is local). Returns
    (packed, la, rd, planes u8 [F, 2, W])."""
    from bsseqconsensusreads_tpu.methyl.context import methyl_epilogue

    out = duplex_call_pipeline(
        bases, quals, cover, ref, convert_mask, extend_eligible,
        params=params, vote_kernel=vote_kernel, layout=layout,
    )
    planes = methyl_epilogue(
        bases, quals, cover, convert_mask, out["base"], ref_ext,
        params.min_input_base_quality,
    )
    return pack_duplex_outputs(out), out["la"], out["rd"], planes


@partial(jax.jit, static_argnames=(
    "f", "w", "params", "qual_mode", "r", "vote_kernel", "layout"
))
def duplex_call_wire_fused_methyl(
    words, genome, f: int, w: int,
    params: ConsensusParams = ConsensusParams(min_reads=0),
    qual_mode: str = "q8",
    r: int = 4,
    vote_kernel: str = "xla",
    layout: str = "padded",
):
    """duplex_call_wire_fused + fused methyl epilogue, one wire each way.

    Input wire = DuplexWire.to_words() ++ los u32 [f] (each family's contig
    origin — gather_windows_ext's lower bound), appended at the END so the
    existing five-section prefix parses unchanged. Output wire = the plain
    b0 ++ la/rd words ++ methyl planes (f*2*w/4 words) appended after the
    lard section, so ops.reconstruct.retire_duplex_wire consumes the
    prefix as-is and the planes peel off the tail
    (methyl.context.unpack_methyl_planes)."""
    from bsseqconsensusreads_tpu.methyl.context import (
        methyl_epilogue,
        methyl_wire_words,
    )
    from bsseqconsensusreads_tpu.ops.refstore import (
        gather_windows,
        gather_windows_ext,
    )
    from bsseqconsensusreads_tpu.ops.wire import (
        pack_lard,
        split_duplex_wire,
        unpack_duplex_inputs,
        wire_section_sizes,
    )

    if r != 4:
        raise ValueError(
            f"duplex windows have 4 rows (flags 99/163/83/147); got r={r}"
        )
    base_words = sum(wire_section_sizes(f, w, r, qual_mode))
    nib, qual, meta, starts, limits = split_duplex_wire(
        words[:base_words], f, w, r=r, qual_mode=qual_mode
    )
    los = words[base_words : base_words + f]
    bases, quals, cover, convert_mask, eligible = unpack_duplex_inputs(
        nib, qual, meta, f, w, qual_mode=qual_mode
    )
    ref = gather_windows(genome, starts, limits, w + 1)
    ref_ext = gather_windows_ext(genome, starts, los, limits, w + 4)
    out = duplex_call_pipeline(
        bases, quals, cover, ref, convert_mask, eligible, params=params,
        vote_kernel=vote_kernel, layout=layout,
    )
    planes = methyl_epilogue(
        bases, quals, cover, convert_mask, out["base"], ref_ext,
        params.min_input_base_quality,
    )
    return jnp.concatenate(
        [
            pack_duplex_b0_outputs(out),
            pack_lard(out["la"], out["rd"]),
            methyl_wire_words(planes),
        ]
    )
