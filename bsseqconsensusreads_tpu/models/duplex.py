"""Duplex consensus kernel: merge A- and B-strand single-strand consensi.

TPU-native equivalent of `fgbio CallDuplexConsensusReads` as invoked by the
reference (main.snake.py:163): per MI group, combine the converted,
coordinate-harmonized strand reads into one duplex read pair, with
--min-reads=0 semantics — emit everything, including groups where only one
strand survived (README.md:9 "not filtered").

After convert_ag_to_ct + extend_gap, a duplex family is a [4, W] window
tensor with rows (99, 163, 83, 147). The duplex R1 merges rows (99, 163)
(the two forward-mapped strand reads covering the top-strand window); the
duplex R2 merges rows (83, 147). Each merge is the same quality-weighted
log-likelihood vote as the molecular stage, with depth <= 2 — reproducing
the reference pipeline's configuration, which feeds molecular-consensus reads
back through the same fgbio error model (error-rate-pre-umi=45,
error-rate-post-umi=30) a second time.

Strand bookkeeping for tags: rows 99/147 are A-strand, rows 163/83 are
B-strand; per-column per-strand depths are emitted so the writer can produce
aD/bD-style annotations alongside cD/cM/cE.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from bsseqconsensusreads_tpu.alphabet import NBASE
from bsseqconsensusreads_tpu.models.molecular import column_vote
from bsseqconsensusreads_tpu.models.params import ConsensusParams
from bsseqconsensusreads_tpu.ops.convert import convert_ag_to_ct
from bsseqconsensusreads_tpu.ops.extend import (
    ROW_83,
    ROW_99,
    ROW_147,
    ROW_163,
    extend_gap,
)

# (rows merged, A-strand row, B-strand row) for duplex R1 and R2.
R1_ROWS = (ROW_99, ROW_163)
R2_ROWS = (ROW_83, ROW_147)
A_ROWS = (ROW_99, ROW_147)


def _merge(bases, quals, rows, params):
    b = jnp.stack([bases[..., r, :] for r in rows], axis=-2)
    q = jnp.stack([quals[..., r, :] for r in rows], axis=-2)
    out = column_vote(b, q, params)
    a_row, b_row = (rows[0], rows[1]) if rows[0] in A_ROWS else (rows[1], rows[0])
    out["a_depth"] = (bases[..., a_row, :] != NBASE).astype(jnp.int32)
    out["b_depth"] = (bases[..., b_row, :] != NBASE).astype(jnp.int32)
    return out


def _family_duplex(bases, quals, params):
    r1 = _merge(bases, quals, R1_ROWS, params)
    r2 = _merge(bases, quals, R2_ROWS, params)
    return jax.tree.map(lambda a, b: jnp.stack([a, b], axis=0), r1, r2)


@partial(jax.jit, static_argnames=("params",))
def duplex_consensus(bases, quals, params: ConsensusParams = ConsensusParams(min_reads=0)):
    """Batched duplex merge.

    bases: int8 [F, 4, W] (rows 99/163/83/147, NBASE where uncovered),
    quals: float32/uint8 [F, 4, W].
    Returns dict of [F, 2, W] arrays: base, qual, depth, errors,
    a_depth, b_depth. Roles: 0 = duplex R1, 1 = duplex R2.
    """
    quals = quals.astype(jnp.float32)
    return jax.vmap(lambda b, q: _family_duplex(b, q, params))(bases, quals)


@partial(jax.jit, static_argnames=("params",))
def duplex_call_pipeline(
    bases, quals, cover, ref, convert_mask, extend_eligible=None,
    params: ConsensusParams = ConsensusParams(min_reads=0),
):
    """The fused TPU duplex stage: AG->CT conversion -> gap extension ->
    duplex merge, one compiled program per batch shape.

    Replaces the reference's four-process chain convert_Bstrain -> extend ->
    groupsort_convert -> callduplex (main.snake.py:121-164): the
    TemplateCoordinate sort is obviated because families are already grouped
    on the family axis. Inputs are DuplexBatch arrays; returns the
    duplex_consensus output dict plus 'la'/'rd' [F, 4] for parity inspection.
    """
    b, q, c, la, rd = convert_ag_to_ct(bases, quals, cover, ref, convert_mask)
    b, q, c = extend_gap(b, q, c, la, rd, extend_eligible)
    b = jnp.where(c, b, NBASE)
    out = duplex_consensus(b, q, params)
    out["la"] = la
    out["rd"] = rd
    return out
