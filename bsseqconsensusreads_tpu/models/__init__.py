"""The consensus model family: molecular (single-strand) and duplex callers.

These are the TPU-native re-implementations of the two JVM consensus engines
the reference shells out to (fgbio CallMolecularConsensusReads at
main.snake.py:54 and CallDuplexConsensusReads at main.snake.py:163), exposed
as jit/vmap-able functions over family tensors.
"""

from bsseqconsensusreads_tpu.models.params import ConsensusParams  # noqa: F401
