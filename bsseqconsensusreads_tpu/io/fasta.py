"""Indexed FASTA reader — replacement for pysam.FastaFile.

The reference fetches per-read reference windows during B-strand conversion
(reference: tools/1.convert_AG_to_CT.py:35,107). This reader supports .fai
faidx indexes (building one on the fly when absent) and random-access fetch.
"""

from __future__ import annotations

import os


class FastaError(IOError):
    pass


class FastaFile:
    """Random-access FASTA with faidx semantics.

    fetch(name, start, end) returns the [start, end) slice (0-based,
    end-exclusive), clamped to the sequence length — matching
    pysam.FastaFile.fetch used by the reference.
    """

    def __init__(self, path: str):
        self._path = path
        self._fh = open(path, "rb")
        fai = path + ".fai"
        if os.path.exists(fai):
            self._index = self._load_fai(fai)
        else:
            self._index = self._build_index()
            try:
                self._save_fai(fai)
            except OSError:
                pass  # read-only dir: index stays in-memory

    @staticmethod
    def _load_fai(path: str) -> dict[str, tuple[int, int, int, int]]:
        index: dict[str, tuple[int, int, int, int]] = {}
        with open(path) as fh:
            for line in fh:
                name, length, offset, linebases, linewidth = line.rstrip("\n").split("\t")[:5]
                index[name] = (int(length), int(offset), int(linebases), int(linewidth))
        return index

    def _save_fai(self, path: str) -> None:
        with open(path, "w") as fh:
            for name, (length, offset, linebases, linewidth) in self._index.items():
                fh.write(f"{name}\t{length}\t{offset}\t{linebases}\t{linewidth}\n")

    def _build_index(self) -> dict[str, tuple[int, int, int, int]]:
        index: dict[str, tuple[int, int, int, int]] = {}
        self._fh.seek(0)
        name = None
        length = offset = linebases = linewidth = 0
        blank_seen = False
        pos = 0
        for raw in self._fh:
            line_len = len(raw)
            line = raw.rstrip(b"\r\n")
            if line.startswith(b">"):
                if name is not None:
                    index[name] = (length, offset, linebases, linewidth)
                name = line[1:].split()[0].decode("ascii") if len(line) > 1 else ""
                length = linebases = linewidth = 0
                blank_seen = False
                offset = pos + line_len
            elif not line:
                blank_seen = True
            elif name is not None:
                if blank_seen:
                    # A blank line inside a sequence body breaks the
                    # offset arithmetic; refuse like samtools faidx.
                    raise FastaError(
                        f"{self._path}: blank line inside sequence {name!r}"
                    )
                if linebases == 0:
                    linebases = len(line)
                    linewidth = line_len
                elif length % linebases != 0:
                    # The previous line was short but not final: offsets would
                    # be wrong from here on. samtools faidx rejects this too.
                    raise FastaError(
                        f"{self._path}: non-uniform line length in sequence {name!r}"
                    )
                elif len(line) > linebases:
                    raise FastaError(
                        f"{self._path}: line longer than first line in sequence {name!r}"
                    )
                length += len(line)
            pos += line_len
        if name is not None:
            index[name] = (length, offset, linebases, linewidth)
        if not index:
            raise FastaError(f"{self._path}: no sequences found")
        return index

    @property
    def references(self) -> list[str]:
        return list(self._index)

    def get_reference_length(self, name: str) -> int:
        return self._index[name][0]

    def fetch(self, name: str, start: int = 0, end: int | None = None) -> str:
        if name not in self._index:
            raise KeyError(name)
        length, offset, linebases, linewidth = self._index[name]
        if end is None or end > length:
            end = length
        start = max(start, 0)
        if start >= end:
            return ""
        # File offset of base i: offset + (i // linebases) * linewidth + i % linebases
        first = offset + (start // linebases) * linewidth + start % linebases
        last = offset + ((end - 1) // linebases) * linewidth + (end - 1) % linebases
        self._fh.seek(first)
        raw = self._fh.read(last - first + 1)
        return raw.replace(b"\n", b"").replace(b"\r", b"").decode("ascii")

    def close(self) -> None:
        self._fh.close()

    def __enter__(self) -> "FastaFile":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
