"""BAM record model and codec (pure Python).

First-party replacement for the pysam.AlignmentFile surface the reference uses
(reference: tools/1.convert_AG_to_CT.py:67-68, tools/2.extend_gap.py:149-152):
streaming reader, template-based writer, record field/tag access and mutation.

BAM layout (SAM spec §4): BGZF-compressed stream of
  magic "BAM\\1" | l_text | text | n_ref | (l_name name l_ref)*
then per alignment:
  block_size refID pos l_read_name mapq bin n_cigar_op flag l_seq
  next_refID next_pos tlen read_name\\0 cigar[u32*] seq[nibbles] qual[u8*] tags
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Any, Iterable, Iterator

import numpy as np

from bsseqconsensusreads_tpu.faults.guard import (
    StreamGuardError,
    check_record_body,
)
from bsseqconsensusreads_tpu.io.bgzf import BgzfReader, BgzfWriter

BAM_MAGIC = b"BAM\x01"

#: block_size sanity bounds shared with native/bamio.cpp's
#: read_record_body — an untrusted 32-bit field must never size a read.
MIN_RECORD_SIZE = 32
MAX_RECORD_SIZE = 1 << 28

# CIGAR op codes and letters (SAM spec order).
CIGAR_OPS = "MIDNSHP=X"
CMATCH, CINS, CDEL, CREF_SKIP, CSOFT_CLIP, CHARD_CLIP, CPAD, CEQUAL, CDIFF = range(9)
_CONSUMES_REF = (True, False, True, True, False, False, False, True, True)
_CONSUMES_QUERY = (True, True, False, False, True, False, False, True, True)

# 4-bit base codes.
SEQ_NT16 = "=ACMGRSVTWYHKDBN"
_NT16_OF = {c: i for i, c in enumerate(SEQ_NT16)}
for _c in "acmgrsvtwyhkdbn":
    _NT16_OF[_c] = _NT16_OF[_c.upper()]
# Byte -> two-base string table so seq decode is one dict-free pass per byte.
_NT16_PAIRS = [SEQ_NT16[b >> 4] + SEQ_NT16[b & 0xF] for b in range(256)]
# char byte -> 4-bit code table for the encode path (unknown chars -> N=15).
_NT16_CODE = np.full(256, 15, dtype=np.uint8)
for _ch, _code in _NT16_OF.items():
    _NT16_CODE[ord(_ch)] = _code

# SAM flag bits.
FPAIRED, FPROPER_PAIR, FUNMAP, FMUNMAP = 0x1, 0x2, 0x4, 0x8
FREVERSE, FMREVERSE, FREAD1, FREAD2 = 0x10, 0x20, 0x40, 0x80
FSECONDARY, FQCFAIL, FDUP, FSUPPLEMENTARY = 0x100, 0x200, 0x400, 0x800


class BamError(StreamGuardError):
    """BAM framing/format error. Subclasses the graftguard typed
    stream error (itself an IOError, preserving ancestry) so input-
    caused failures are always faults.guard.GuardError instances."""


@dataclass
class BamHeader:
    """SAM header text plus the binary reference dictionary."""

    text: str = ""
    references: list[tuple[str, int]] = field(default_factory=list)

    def ref_id(self, name: str) -> int:
        for i, (n, _) in enumerate(self.references):
            if n == name:
                return i
        return -1

    def ref_name(self, rid: int) -> str:
        if 0 <= rid < len(self.references):
            return self.references[rid][0]
        return "*"

    def copy(self) -> "BamHeader":
        return BamHeader(self.text, list(self.references))

    def with_sort_order(self, so: str, ss: str | None = None) -> "BamHeader":
        """A copy whose @HD line declares SO:`so` (and optionally a
        SS:`ss` sub-sort, the convention fgbio's TemplateCoordinate sort
        uses) — samtools sort / fgbio SortBam rewrite this on every sort,
        and downstream validators trust it. Other @HD fields survive; a
        stale SS from a previous sort is dropped unless replaced."""
        lines = self.text.splitlines()
        out = []
        replaced = False
        for line in lines:
            if line.startswith("@HD"):
                fields = [
                    f for f in line.split("\t")[1:]
                    if not f.startswith(("SO:", "SS:"))
                ]
                hd = "\t".join(["@HD", *fields, f"SO:{so}"])
                if ss:
                    hd += f"\tSS:{ss}"
                out.append(hd)
                replaced = True
            else:
                out.append(line)
        if not replaced:
            hd = f"@HD\tVN:1.6\tSO:{so}"
            if ss:
                hd += f"\tSS:{ss}"
            out.insert(0, hd)
        return BamHeader(
            "\n".join(out) + ("\n" if out else ""), list(self.references)
        )

    def with_pg(
        self,
        program: str,
        version: str = "",
        command_line: str = "",
    ) -> "BamHeader":
        """A copy with an @PG provenance line appended, chained to the
        previous program via PP — what samtools/fgbio do on every step the
        reference runs (the reference even opts out once with --no-PG,
        main.snake.py:106; downstream tooling expects the chain)."""
        ids = []
        for line in self.text.splitlines():
            if line.startswith("@PG"):
                for part in line.split("\t")[1:]:
                    if part.startswith("ID:"):
                        ids.append(part[3:])
        pg_id = program
        n = 1
        while pg_id in ids:
            pg_id = f"{program}.{n}"
            n += 1
        fields = [f"@PG\tID:{pg_id}", f"PN:{program}"]
        if ids:
            fields.append(f"PP:{ids[-1]}")
        if version:
            fields.append(f"VN:{version}")
        if command_line:
            fields.append(f"CL:{command_line}")
        text = self.text
        if text and not text.endswith("\n"):
            text += "\n"
        return BamHeader(text + "\t".join(fields) + "\n", list(self.references))


@dataclass
class BamRecord:
    """One alignment record. pos is 0-based; qual holds raw Phred ints.

    tags maps 2-char keys to (type_char, value); type chars follow the SAM tag
    grammar (A c C s S i I f Z H B). For 'B', value is (subtype_char, list).
    """

    qname: str = "*"
    flag: int = 0
    ref_id: int = -1
    pos: int = -1
    mapq: int = 0
    cigar: list[tuple[int, int]] = field(default_factory=list)
    next_ref_id: int = -1
    next_pos: int = -1
    tlen: int = 0
    seq: str = ""
    qual: bytes | None = None
    tags: dict[str, tuple[str, Any]] = field(default_factory=dict)

    # -- flag predicates -------------------------------------------------
    @property
    def is_paired(self) -> bool:
        return bool(self.flag & FPAIRED)

    @property
    def is_unmapped(self) -> bool:
        return bool(self.flag & FUNMAP)

    @property
    def is_reverse(self) -> bool:
        return bool(self.flag & FREVERSE)

    @property
    def is_read1(self) -> bool:
        return bool(self.flag & FREAD1)

    @property
    def is_read2(self) -> bool:
        return bool(self.flag & FREAD2)

    @property
    def is_secondary(self) -> bool:
        return bool(self.flag & FSECONDARY)

    @property
    def is_supplementary(self) -> bool:
        return bool(self.flag & FSUPPLEMENTARY)

    # -- geometry --------------------------------------------------------
    @property
    def reference_length(self) -> int:
        return sum(ln for op, ln in self.cigar if _CONSUMES_REF[op])

    @property
    def reference_end(self) -> int:
        """0-based exclusive end (pos + ref-consumed length)."""
        return self.pos + self.reference_length

    @property
    def query_length(self) -> int:
        return sum(ln for op, ln in self.cigar if _CONSUMES_QUERY[op])

    # -- tags ------------------------------------------------------------
    def get_tag(self, key: str) -> Any:
        return self.tags[key][1]

    def has_tag(self, key: str) -> bool:
        return key in self.tags

    def set_tag(self, key: str, value: Any, type_char: str | None = None) -> None:
        if type_char is None:
            if isinstance(value, int):
                type_char = "i"
            elif isinstance(value, float):
                type_char = "f"
            elif isinstance(value, str):
                type_char = "Z"
            else:
                raise TypeError(f"cannot infer tag type for {value!r}")
        self.tags[key] = (type_char, value)

    def cigar_string(self) -> str:
        if not self.cigar:
            return "*"
        return "".join(f"{ln}{CIGAR_OPS[op]}" for op, ln in self.cigar)

    def copy(self) -> "BamRecord":
        return BamRecord(
            self.qname, self.flag, self.ref_id, self.pos, self.mapq,
            list(self.cigar), self.next_ref_id, self.next_pos, self.tlen,
            self.seq, self.qual, dict(self.tags),
        )


def reg2bin(beg: int, end: int) -> int:
    """BAI binning (SAM spec §5.3)."""
    end -= 1
    if end < 0:
        end = 0
    if beg < 0:
        beg = 0
    if beg >> 14 == end >> 14:
        return ((1 << 15) - 1) // 7 + (beg >> 14)
    if beg >> 17 == end >> 17:
        return ((1 << 12) - 1) // 7 + (beg >> 17)
    if beg >> 20 == end >> 20:
        return ((1 << 9) - 1) // 7 + (beg >> 20)
    if beg >> 23 == end >> 23:
        return ((1 << 6) - 1) // 7 + (beg >> 23)
    if beg >> 26 == end >> 26:
        return ((1 << 3) - 1) // 7 + (beg >> 26)
    return 0


_TAG_FMT = {"c": "<b", "C": "<B", "s": "<h", "S": "<H", "i": "<i", "I": "<I", "f": "<f"}
#: B-subtype -> little-endian numpy dtype for the vectorized array-tag
#: encode (byte-identical to the struct.pack path for in-range values).
_TAG_NP_DTYPE = {
    "c": "<i1", "C": "<u1", "s": "<i2", "S": "<u2",
    "i": "<i4", "I": "<u4", "f": "<f4",
}


def skip_tag(data: bytes, off: int) -> int:
    """Offset just past the tag starting at data[off] (key + type char +
    value) — the single source of tag byte widths for raw no-decode
    walkers (e.g. pipeline.group_umi's MI splice); _decode_tags consumes
    the same layout."""
    tc = chr(data[off + 2])
    off += 3
    if tc == "A":
        return off + 1
    if tc in _TAG_FMT:
        return off + struct.calcsize(_TAG_FMT[tc])
    if tc in ("Z", "H"):
        return data.index(0, off) + 1
    if tc == "B":
        sub = chr(data[off])
        count = struct.unpack_from("<I", data, off + 1)[0]
        return off + 5 + count * struct.calcsize(_TAG_FMT[sub])
    raise BamError(f"unknown tag type {tc!r}")


def tag_region_offset(blob: bytes) -> int:
    """Byte offset of the tag region inside an encoded record blob
    (including its leading block_size prefix): fixed fields + qname +
    cigar + 4-bit seq + qual."""
    l_qname = blob[12]
    (n_cigar,) = struct.unpack_from("<H", blob, 16)
    (l_seq,) = struct.unpack_from("<i", blob, 20)
    return 36 + l_qname + 4 * n_cigar + (l_seq + 1) // 2 + l_seq


def _decode_tags(data: bytes, off: int) -> dict[str, tuple[str, Any]]:
    try:
        return _decode_tags_inner(data, off)
    except (ValueError, struct.error, IndexError, UnicodeDecodeError) as exc:
        # untrusted tag bytes: a lying count/unterminated Z string must
        # surface as the typed stream error, not a bare struct.error
        if isinstance(exc, BamError):
            raise
        raise BamError(f"corrupt record tags: {exc}") from None


def _decode_tags_inner(data: bytes, off: int) -> dict[str, tuple[str, Any]]:
    tags: dict[str, tuple[str, Any]] = {}
    n = len(data)
    while off < n:
        if off + 3 > n:
            raise BamError("corrupt record tags: truncated tag header")
        key = data[off : off + 2].decode("ascii")
        tc = chr(data[off + 2])
        off += 3
        if tc == "A":
            tags[key] = ("A", chr(data[off]))
            off += 1
        elif tc in _TAG_FMT:
            fmt = _TAG_FMT[tc]
            tags[key] = (tc, struct.unpack_from(fmt, data, off)[0])
            off += struct.calcsize(fmt)
        elif tc in ("Z", "H"):
            end = data.index(0, off)
            tags[key] = (tc, data[off:end].decode("ascii"))
            off = end + 1
        elif tc == "B":
            sub = chr(data[off])
            count = struct.unpack_from("<I", data, off + 1)[0]
            off += 5
            fmt = _TAG_FMT[sub]
            size = struct.calcsize(fmt)
            vals = list(struct.unpack_from(f"<{count}{fmt[1]}", data, off))
            tags[key] = ("B", (sub, vals))
            off += count * size
        else:
            raise BamError(f"unknown tag type {tc!r} for {key}")
    return tags


def _encode_tags(tags: dict[str, tuple[str, Any]]) -> bytes:
    out = bytearray()
    for key, (tc, val) in tags.items():
        out += key.encode("ascii")
        if tc == "A":
            out += b"A" + ord(val).to_bytes(1, "little")
        elif tc in _TAG_FMT:
            out += tc.encode("ascii") + struct.pack(_TAG_FMT[tc], val)
        elif tc in ("Z", "H"):
            out += tc.encode("ascii") + val.encode("ascii") + b"\x00"
        elif tc == "B":
            sub, vals = val
            if isinstance(vals, np.ndarray):
                # vectorized: one astype+tobytes instead of a per-element
                # struct.pack — the emit twin passes its per-base tag
                # arrays through without .tolist() (ISSUE 6 satellite 1)
                out += b"B" + sub.encode("ascii")
                out += struct.pack("<I", vals.size)
                out += vals.astype(_TAG_NP_DTYPE[sub], copy=False).tobytes()
            else:
                out += b"B" + sub.encode("ascii")
                out += struct.pack("<I", len(vals))
                out += struct.pack(f"<{len(vals)}{_TAG_FMT[sub][1]}", *vals)
        else:
            raise BamError(f"unknown tag type {tc!r} for {key}")
    return bytes(out)


def _select_bgzf(engine: str, native_factory, python_factory):
    """Shared engine selection for reader and writer paths.

    'auto' prefers the native C++ codec when built; 'native' demands it
    (raising with the recorded build/load diagnostic when absent); 'python'
    forces the pure codec. Anything else is an error, not a silent
    fallback. File-level errors from the chosen factory propagate as-is.
    """
    if engine not in ("auto", "native", "python"):
        raise ValueError(f"unknown engine {engine!r}; use auto|native|python")
    if engine in ("auto", "native"):
        from bsseqconsensusreads_tpu.io import native

        if native.available():
            return native_factory()
        if engine == "native":
            raise OSError(f"native codec unavailable: {native.load_error()}")
    return python_factory()


def _open_bgzf(path: str, engine: str, threads: int | None = None):
    def native_factory():
        from bsseqconsensusreads_tpu.io.native import NativeBgzfReader

        return NativeBgzfReader(path, threads=threads)

    return _select_bgzf(engine, native_factory, lambda: BgzfReader.open(path))


def _create_bgzf(path: str, engine: str, level: int):
    def native_factory():
        from bsseqconsensusreads_tpu.io.native import NativeBgzfWriter

        return NativeBgzfWriter(path, level)

    def python_factory():
        # the python codec tier shards deflate across the hostpool when
        # workers are available (io.pbgzf; BSSEQ_TPU_PBGZF overrides) —
        # byte-identical to the serial BgzfWriter for any worker count
        from bsseqconsensusreads_tpu.io import pbgzf

        workers = pbgzf.default_workers()
        if workers >= 2:
            return pbgzf.PBgzfWriter.open(path, level=level, workers=workers)
        return BgzfWriter.open(path, level=level)

    return _select_bgzf(engine, native_factory, python_factory)


def attach_codec_metrics(writer: "BamWriter", metrics) -> None:
    """Point a writer's parallel-deflate codec (io.pbgzf) at a stage's
    metrics so its worker-busy seconds and block counts land in the
    ledger ('sort_write.deflate' sub-phase, pbgzf_* counters). No-op for
    the serial python codec and the native codec (the native mt writer
    accounts its own threads C-side)."""
    codec = getattr(writer, "_bgzf", None)
    if codec is not None and hasattr(codec, "workers") \
            and hasattr(codec, "metrics"):
        codec.metrics = metrics


_REC_FIXED = struct.Struct("<iiBBHHHIiii")  # refID..tlen after block_size (32 bytes)


def read_bam_header(bgzf, path: str) -> BamHeader:
    """Parse the BAM header from an open BGZF reader with every
    untrusted length field bounds-checked — a lying l_text/n_ref must
    raise a typed BamError, not size a giant read or escape as a bare
    struct.error. Shared by BamReader and the native header skip
    (io.native._skip_header reproduces the same bounds)."""

    def _u32(what: str) -> int:
        raw = bgzf.read(4)
        if len(raw) < 4:
            raise BamError(f"corrupt BAM header (truncated {what})")
        return struct.unpack("<i", raw)[0]

    magic = bgzf.read(4)
    if magic != BAM_MAGIC:
        raise BamError(f"{path}: not a BAM file")
    l_text = _u32("l_text")
    if l_text < 0 or l_text > MAX_RECORD_SIZE:
        raise BamError("corrupt BAM header (bad l_text)")
    text_raw = bgzf.read(l_text)
    if len(text_raw) < l_text:
        raise BamError("corrupt BAM header (truncated text)")
    text = text_raw.decode("utf-8", "replace").rstrip("\x00")
    n_ref = _u32("n_ref")
    if n_ref < 0 or n_ref > (1 << 24):
        raise BamError("corrupt BAM header (bad n_ref)")
    refs = []
    for _ in range(n_ref):
        l_name = _u32("l_name")
        if l_name < 1 or l_name > (1 << 16):
            raise BamError("corrupt BAM header (bad l_name)")
        name_raw = bgzf.read(l_name)
        if len(name_raw) < l_name:
            raise BamError("corrupt BAM header (truncated name)")
        try:
            name = name_raw[:-1].decode("ascii")
        except UnicodeDecodeError:
            raise BamError("corrupt BAM header (non-ASCII name)") from None
        l_ref = _u32("l_ref")
        if l_ref < 0:
            raise BamError("corrupt BAM header (bad l_ref)")
        refs.append((name, l_ref))
    return BamHeader(text, refs)


def decode_record(data: bytes) -> BamRecord:
    """Decode one alignment from its variable-size data (sans block_size)."""
    (ref_id, pos, l_qname, mapq, _bin, n_cigar, flag, l_seq, next_ref, next_pos, tlen) = _REC_FIXED.unpack_from(data, 0)
    off = 32
    try:
        qname = data[off : off + l_qname - 1].decode("ascii")
    except UnicodeDecodeError:
        raise BamError("corrupt record qname (non-ASCII bytes)") from None
    off += l_qname
    cigar = []
    for _ in range(n_cigar):
        v = struct.unpack_from("<I", data, off)[0]
        cigar.append((v & 0xF, v >> 4))
        off += 4
    nbytes = (l_seq + 1) // 2
    pairs = _NT16_PAIRS
    seq = "".join([pairs[b] for b in data[off : off + nbytes]])[:l_seq]
    off += nbytes
    qual_raw = data[off : off + l_seq]
    qual = None if (l_seq == 0 or (qual_raw and qual_raw[0] == 0xFF)) else qual_raw
    off += l_seq
    tags = _decode_tags(data, off)
    return BamRecord(qname, flag, ref_id, pos, mapq, cigar, next_ref, next_pos, tlen, seq, qual, tags)


def encode_record(rec: BamRecord) -> bytes:
    """Encode one alignment including its leading block_size field."""
    qname_b = rec.qname.encode("ascii") + b"\x00"
    l_seq = len(rec.seq)
    body = bytearray()
    body += _REC_FIXED.pack(
        rec.ref_id,
        rec.pos,
        len(qname_b),
        rec.mapq,
        reg2bin(rec.pos if rec.pos >= 0 else 0, rec.reference_end if rec.cigar else (rec.pos + 1 if rec.pos >= 0 else 1)),
        len(rec.cigar),
        rec.flag,
        l_seq,
        rec.next_ref_id,
        rec.next_pos,
        rec.tlen,
    )
    body += qname_b
    if rec.cigar:
        body += struct.pack(
            f"<{len(rec.cigar)}I", *((ln << 4) | op for op, ln in rec.cigar)
        )
    codes = _NT16_CODE[np.frombuffer(rec.seq.encode("ascii"), dtype=np.uint8)]
    if l_seq % 2:
        codes = np.append(codes, 0)
    body += ((codes[0::2] << 4) | codes[1::2]).astype(np.uint8).tobytes()
    if rec.qual is None:
        body += b"\xff" * l_seq
    else:
        if len(rec.qual) != l_seq:
            raise BamError(
                f"qual length {len(rec.qual)} != seq length {l_seq} for {rec.qname}"
            )
        body += rec.qual
    body += _encode_tags(rec.tags)
    return struct.pack("<i", len(body)) + bytes(body)


class BamReader:
    """Streaming BAM reader (iterate to get BamRecords).

    engine: 'auto' uses the native C++ BGZF codec when built (native/
    libbamio.so), falling back to the pure-Python codec; 'python'/'native'
    force one. threads: BGZF inflate workers (native engine; None = the
    shared io.native default) — pass 1 for readers opened in bulk, e.g.
    external-merge fan-in.
    """

    def __init__(self, path: str, engine: str = "auto",
                 threads: int | None = None):
        self._bgzf = _open_bgzf(path, engine, threads=threads)
        #: records handed out so far — the `record #N` of every typed
        #: stream error (0-based index of the record that failed)
        self.records_read = 0
        try:
            self.header = read_bam_header(self._bgzf, path)
        except BaseException:
            self._bgzf.close()
            raise

    def _voffset(self) -> int | None:
        return getattr(self._bgzf, "last_block_offset", None)

    def _next_blob(self, validate: bool = True) -> bytes | None:
        """Read one record body (sans prefix); None at clean EOF. Every
        refusal is a typed BamError carrying the record index (and
        block offset when the engine tracks one) — same rules, same
        record index as native/bamio.cpp.

        validate=False skips the structural body check (framing and
        bounds stay): raw_records() replays internal streams — e.g. the
        UMI grouper's composite-key spill blobs, which are NOT BAM
        record bodies — whose integrity is the CRC layer's job
        (faults.integrity), not input validation's."""
        raw = self._bgzf.read(4)
        if not raw:
            return None
        if len(raw) < 4:
            raise BamError(
                "truncated record size", record_index=self.records_read,
                voffset=self._voffset(),
            )
        (block_size,) = struct.unpack("<i", raw)
        if block_size < MIN_RECORD_SIZE or block_size > MAX_RECORD_SIZE:
            raise BamError(
                "corrupt record size", record_index=self.records_read,
                voffset=self._voffset(),
            )
        data = self._bgzf.read(block_size)
        if len(data) < block_size:
            raise BamError(
                "truncated record body", record_index=self.records_read,
                voffset=self._voffset(),
            )
        if validate:
            reason = check_record_body(data)
            if reason is not None:
                raise BamError(
                    reason, record_index=self.records_read,
                    voffset=self._voffset(),
                )
        self.records_read += 1
        return data

    def __iter__(self) -> Iterator[BamRecord]:
        while True:
            data = self._next_blob()
            if data is None:
                return
            yield decode_record(data)

    def raw_records(self, validate: bool = False) -> Iterator[bytes]:
        """Stream encoded record blocks (incl. their block_size prefix)
        WITHOUT decoding — for record-preserving copies (e.g. checkpoint
        shard concatenation) where parse+re-encode is pure waste.
        Structural validation is off by default: raw streams include
        internal non-BAM spill formats (the UMI grouper's composite
        blobs) whose integrity the CRC layer owns; pass validate=True
        when replaying actual record bytes from an untrusted source."""
        while True:
            data = self._next_blob(validate=validate)
            if data is None:
                return
            yield struct.pack("<i", len(data)) + data

    def get_reference_name(self, rid: int) -> str:
        return self.header.ref_name(rid)

    def close(self) -> None:
        self._bgzf.close()

    def __enter__(self) -> "BamReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class _FramingGap(Exception):
    """Internal: the BGZF layer resynced past corrupt blocks; record
    framing must be re-found before reading on."""


class GuardedBamReader:
    """graftguard record reader: BamReader's surface (header + record
    iteration) with the guard's policy applied per record.

    * strict — every structural refusal is a typed BamError and every
      semantic violation a RecordGuardError, both carrying `record #N`
      (and the BGZF block offset on the python engine).
    * quarantine/lenient — runs on the pure-python BGZF engine with
      block resync armed: corrupt blocks are skipped (ledgered
      `stream_gap`), record framing is re-found by scanning for the
      next structurally-plausible record boundary, corrupt records go
      to the sidecar, truncated tails end the stream cleanly
      (`stream_truncated`). The iterator itself never raises for
      anything past the header.

    Records yielded are fully validated — the guard's
    `records_prevalidated` flag tells the family-level pass
    (faults.guard.guard_groups) not to re-check them.
    """

    #: decompressed bytes scanned for a plausible record boundary after
    #: a framing gap before declaring the tail lost
    FRAME_SCAN_LIMIT = 1 << 20

    def __init__(self, path: str, guard, engine: str = "auto"):
        self.guard = guard
        if guard.resilient:
            # resync needs the python block codec (seek + re-inflate)
            self._bgzf = BgzfReader.open(
                path, resync=True, on_event=self._stream_event
            )
        else:
            self._bgzf = _open_bgzf(path, engine)
        self.records_read = 0
        self._pending = b""  # decompressed pushback from frame scans
        try:
            self.header = read_bam_header(self._bgzf, path)
        except BaseException:
            self._bgzf.close()
            raise
        guard.bind(path, self.header)
        guard.records_prevalidated = True

    # -- plumbing ---------------------------------------------------------

    def _stream_event(self, kind: str, payload: dict) -> None:
        self.guard.stream_event(kind, payload)

    def _voffset(self) -> int | None:
        return getattr(self._bgzf, "last_block_offset", None)

    def _read(self, n: int) -> bytes:
        if self._pending:
            take, self._pending = self._pending[:n], self._pending[n:]
            if len(take) == n:
                return take
            return take + self._bgzf.read(n - len(take))
        return self._bgzf.read(n)

    def _gap_pending(self) -> bool:
        return bool(getattr(self._bgzf, "gap_pending", False))

    def _next_blob(self) -> bytes | None:
        """One structurally-valid record body, or None at clean EOF.
        Raises BamError (typed) on refusal and _FramingGap when the
        BGZF layer resynced mid-record."""
        raw = self._read(4)
        if not raw:
            if self._gap_pending():
                raise _FramingGap()
            return None
        if len(raw) < 4:
            if self._gap_pending():
                raise _FramingGap()
            raise BamError(
                "truncated record size", record_index=self.records_read,
                voffset=self._voffset(),
            )
        (block_size,) = struct.unpack("<i", raw)
        if block_size < MIN_RECORD_SIZE or block_size > MAX_RECORD_SIZE:
            raise BamError(
                "corrupt record size", record_index=self.records_read,
                voffset=self._voffset(),
            )
        data = self._read(block_size)
        if len(data) < block_size:
            if self._gap_pending():
                raise _FramingGap()
            raise BamError(
                "truncated record body", record_index=self.records_read,
                voffset=self._voffset(),
            )
        reason = check_record_body(data)
        if reason is not None:
            exc = BamError(
                reason, record_index=self.records_read,
                voffset=self._voffset(),
            )
            exc.blob = data  # framing survives: the blob is quarantinable
            raise exc
        return data

    def _find_frame(self) -> bool:
        """Scan the post-gap decompressed stream for the next offset
        where a structurally-valid record starts (its declared size
        fits, its body checks out, and — when enough bytes are buffered
        — the following record's size field is plausible too). Locks
        the stream there; False when no boundary exists in
        FRAME_SCAN_LIMIT bytes (tail lost)."""
        if hasattr(self._bgzf, "ack_gap"):
            self._bgzf.ack_gap()
        buf = self._pending + self._bgzf.read(self.FRAME_SCAN_LIMIT)
        self._pending = b""
        for off in range(0, max(len(buf) - MIN_RECORD_SIZE - 4, 0)):
            (bs,) = struct.unpack_from("<i", buf, off)
            if bs < MIN_RECORD_SIZE or bs > MAX_RECORD_SIZE:
                continue
            end = off + 4 + bs
            if end > len(buf):
                continue
            if check_record_body(buf[off + 4 : end]) is not None:
                continue
            if end + 4 <= len(buf):  # corroborate with the next size
                (bs2,) = struct.unpack_from("<i", buf, end)
                if bs2 != 0 and (
                    bs2 < MIN_RECORD_SIZE or bs2 > MAX_RECORD_SIZE
                ):
                    continue
            self.guard.stream_event("frame_resync", {
                "discarded_bytes": off, "voffset": self._voffset(),
            })
            self._pending = buf[off:]
            return True
        self.guard.stream_event("stream_truncated", {
            "error": "no record boundary after stream gap",
            "scanned": len(buf),
        })
        return False

    # -- iteration --------------------------------------------------------

    def __iter__(self) -> Iterator[BamRecord]:
        g = self.guard
        while True:
            try:
                data = self._next_blob()
            except _FramingGap:
                if not self._find_frame():
                    return
                continue
            except BamError as exc:
                if not g.resilient:
                    raise
                blob = getattr(exc, "blob", None)
                if blob is not None:
                    # framing intact: quarantine this record, read on
                    g.quarantine_blob(
                        blob, self.records_read, exc.reason,
                        voffset=self._voffset(),
                    )
                    self.records_read += 1
                    g.count("records_seen")
                    continue
                if exc.reason == "record-truncated":
                    g.stream_event(
                        "stream_truncated", {"error": str(exc)}
                    )
                    return
                # corrupt size field: framing lost, re-find a boundary
                g.stream_event("frame_lost", {"error": str(exc)})
                if not self._find_frame():
                    return
                continue
            if data is None:
                return
            index = self.records_read
            self.records_read += 1
            g.count("records_seen")
            try:
                rec = decode_record(data)
            except BamError as exc:
                if not g.resilient:
                    exc.record_index = index
                    raise
                g.quarantine_blob(
                    data, index, exc.reason, voffset=self._voffset()
                )
                continue
            rec = self._validate(rec, index)
            if rec is not None:
                yield rec

    def _validate(self, rec: BamRecord, index: int) -> BamRecord | None:
        from bsseqconsensusreads_tpu.faults import guard as _guard

        g = self.guard
        if g.resilient and not rec.has_tag("MI"):
            g.quarantine_record(rec, index, "missing-mi")
            return None
        v = _guard.record_violation(
            rec, n_ref=g.n_ref, ref_lens=g.ref_lens,
            max_read_len=g.max_read_len,
        )
        if v is None:
            return rec
        reason, repairable = v
        if g.strict:
            raise _guard.RecordGuardError(
                f"record failed input validation: {reason}",
                reason=reason, record_index=index, qname=rec.qname,
            )
        if g.lenient and repairable:
            fixed = _guard.repair_record(rec)
            if fixed:
                g.repaired(rec, index, fixed)
                return rec
        g.quarantine_record(rec, index, reason)
        return None

    def close(self) -> None:
        self._bgzf.close()

    def __enter__(self) -> "GuardedBamReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class RawRecords:
    """A block of pre-encoded BAM records (native batch emit output).

    Batch streams may carry these alongside BamRecord objects; writers
    append the blob verbatim (write_items). count keeps record accounting
    (checkpoint manifests, stage stats) without decoding."""

    __slots__ = ("blob", "count")

    def __init__(self, blob: bytes, count: int):
        self.blob = blob
        self.count = count


def write_items(writer: "BamWriter", items) -> int:
    """Write a mixed sequence of BamRecord / RawRecords; returns the record
    count written."""
    n = 0
    for item in items:
        if isinstance(item, RawRecords):
            writer.write_raw(item.blob)
            n += item.count
        else:
            writer.write(item)
            n += 1
    return n


class BamWriter:
    """Streaming BAM writer; pass the header (e.g. reader.header) up front.

    engine as in BamReader ('auto' prefers the native C++ codec)."""

    def __init__(self, path: str, header: BamHeader, level: int = 6, engine: str = "auto"):
        self.header = header
        self._bgzf = _create_bgzf(path, engine, level)
        try:
            text = header.text.encode("utf-8")
            out = bytearray(BAM_MAGIC)
            out += struct.pack("<i", len(text))
            out += text
            out += struct.pack("<i", len(header.references))
            for name, length in header.references:
                nb = name.encode("ascii") + b"\x00"
                out += struct.pack("<i", len(nb)) + nb + struct.pack("<i", length)
            self._bgzf.write(bytes(out))
        except BaseException:
            self._bgzf.close()
            raise

    def write(self, rec: BamRecord) -> None:
        self._bgzf.write(encode_record(rec))

    def write_raw(self, blob: bytes) -> None:
        """Append pre-encoded record bytes (one or more complete records,
        each with its block_size prefix) — the native batch emitter
        (io.wirepack.emit_consensus_records) and raw_records() produce
        these."""
        self._bgzf.write(blob)

    def write_raw_many(self, blobs: Iterable[bytes], chunk: int = 1 << 20) -> int:
        """Append a stream of pre-encoded record blobs, coalesced into
        ~`chunk`-byte writes. The external sort and final-output paths move
        millions of small blobs; per-blob write calls (each a ctypes hop
        into the native codec) dominated their wall clock. Returns the
        number of blobs written."""
        buf = bytearray()
        n = 0
        for blob in blobs:
            buf += blob
            n += 1
            if len(buf) >= chunk:
                self._bgzf.write(bytes(buf))
                buf.clear()
        if buf:
            self._bgzf.write(bytes(buf))
        return n

    def write_all(self, recs: Iterable[BamRecord]) -> None:
        for rec in recs:
            self.write(rec)

    def close(self) -> None:
        self._bgzf.close()

    def __enter__(self) -> "BamWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
