"""Shared build-on-first-use loader for the native C++ libraries.

Both io.native (libbamio) and io.wirepack (libwirepack) need the same
scaffold: locate the .so under native/, build its explicit make target if
missing (so one library's compile failure can't block the other), load it
with ctypes, and degrade gracefully when no compiler exists. This module
holds that logic once.
"""

from __future__ import annotations

import ctypes as C
import os
import subprocess

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
NATIVE_DIR = os.path.join(REPO_ROOT, "native")


def load_library(
    so_name: str,
    source_name: str,
    env_flag: str | None = None,
) -> tuple[C.CDLL | None, str | None]:
    """Load native/<so_name>, building `make <so_name>` on first use.

    Returns (lib, None) on success or (None, reason) on any failure —
    callers cache both outcomes. env_flag names an environment variable
    that disables the library when set to "0".
    """
    if env_flag and os.environ.get(env_flag, "1") == "0":
        return None, f"disabled via {env_flag}=0"
    so_path = os.path.join(NATIVE_DIR, so_name)
    if not os.path.exists(so_path):
        if os.path.exists(os.path.join(NATIVE_DIR, source_name)):
            try:
                subprocess.run(
                    ["make", "-C", NATIVE_DIR, so_name],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
            except Exception as e:  # no compiler / make failure
                return None, f"native build failed: {e}"
        else:
            return None, "native sources not found"
    try:
        return C.CDLL(so_path), None
    except OSError as e:
        return None, f"cannot load {so_path}: {e}"
