"""Shared build-on-first-use loader for the native C++ libraries.

Both io.native (libbamio) and io.wirepack (libwirepack) need the same
scaffold: locate the .so under native/, build its explicit make target if
missing (so one library's compile failure can't block the other), load it
with ctypes, and degrade gracefully when no compiler exists. This module
holds that logic once.
"""

from __future__ import annotations

import ctypes as C
import os
import subprocess

from bsseqconsensusreads_tpu.faults import failpoints as _failpoints

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
NATIVE_DIR = os.path.join(REPO_ROOT, "native")


def _build(so_name: str) -> str | None:
    try:
        subprocess.run(
            ["make", "-C", NATIVE_DIR, so_name],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return None
    except Exception as e:  # no compiler / make failure
        return f"native build failed: {e}"


def load_library(
    so_name: str,
    source_name: str,
    env_flag: str | None = None,
    required_symbols: tuple[str, ...] = (),
) -> tuple[C.CDLL | None, str | None]:
    """Load native/<so_name>, building `make <so_name>` on first use.

    Returns (lib, None) on success or (None, reason) on any failure —
    callers cache both outcomes. env_flag names an environment variable
    that disables the library when set to "0". required_symbols guards
    against a stale pre-upgrade .so (the .so is gitignored, so an existing
    checkout can hold one missing newly added entry points): when any
    symbol is absent the .so is rebuilt once and reloaded, and a still-
    incomplete library loads as unavailable instead of raising
    AttributeError out of the caller's binding code.
    """
    if env_flag and os.environ.get(env_flag, "1") == "0":
        return None, f"disabled via {env_flag}=0"
    if _failpoints.ARMED:
        try:
            _failpoints.fire("native_load", so=so_name)
        except Exception as exc:  # injected load failure: degrade to the
            # pure-Python codec paths exactly like a missing compiler
            return None, f"failpoint injected: {exc}"
    so_path = os.path.join(NATIVE_DIR, so_name)
    have_source = os.path.exists(os.path.join(NATIVE_DIR, source_name))
    if not os.path.exists(so_path):
        if not have_source:
            return None, "native sources not found"
        err = _build(so_name)
        if err:
            return None, err
    try:
        lib = C.CDLL(so_path)
    except OSError as e:
        return None, f"cannot load {so_path}: {e}"
    missing = [s for s in required_symbols if not hasattr(lib, s)]
    if missing and have_source:
        # stale build: force a rebuild (make alone may consider the .so
        # fresh if checkout mtimes are skewed) and reload
        try:
            os.unlink(so_path)
        except OSError:  # graftlint: disable=swallowed-exception -- best-effort unlink; a real failure resurfaces as the rebuild error below
            pass
        err = _build(so_name)
        if err:
            return None, f"stale {so_name} missing {missing[0]}; {err}"
        try:
            lib = C.CDLL(so_path)
        except OSError as e:
            return None, f"cannot load rebuilt {so_path}: {e}"
        missing = [s for s in required_symbols if not hasattr(lib, s)]
    if missing:
        return None, f"{so_name} lacks required symbols: {', '.join(missing)}"
    return lib, None
