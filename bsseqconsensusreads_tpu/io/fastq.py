"""FASTQ writing — replacement for Picard SamToFastq.

The reference shells out to `java -jar picard SamToFastq I=… F=… F2=…`
(reference: main.snake.py:67,79,176) to split an unaligned consensus BAM into
a gzipped R1/R2 FASTQ pair. This module does the same from BamRecords:
read1 -> F, read2 -> F2, reverse-strand records are reverse-complemented back
to sequencing orientation (Picard's default behavior).
"""

from __future__ import annotations

import gzip
from typing import Iterable

from bsseqconsensusreads_tpu.io.bam import BamRecord, FREAD2, FREVERSE

_COMPLEMENT = str.maketrans("ACGTNacgtn", "TGCANtgcan")


def reverse_complement(seq: str) -> str:
    return seq.translate(_COMPLEMENT)[::-1]


def qual_to_ascii(qual: bytes | None, length: int) -> str:
    if qual is None:
        return "!" * length
    return "".join(chr(min(q, 93) + 33) for q in qual)


def sam_to_fastq(records: Iterable[BamRecord], fq1_path: str, fq2_path: str) -> tuple[int, int]:
    """Split records into paired gzipped FASTQs; returns (n_r1, n_r2)."""
    n1 = n2 = 0
    with gzip.open(fq1_path, "wt") as f1, gzip.open(fq2_path, "wt") as f2:
        for rec in records:
            if rec.flag & 0x900:  # secondary/supplementary never exported
                continue
            seq, qual = rec.seq, qual_to_ascii(rec.qual, len(rec.seq))
            if rec.flag & FREVERSE:
                seq = reverse_complement(seq)
                qual = qual[::-1]
            if rec.flag & FREAD2:
                f2.write(f"@{rec.qname}/2\n{seq}\n+\n{qual}\n")
                n2 += 1
            else:
                f1.write(f"@{rec.qname}/1\n{seq}\n+\n{qual}\n")
                n1 += 1
    return n1, n2
