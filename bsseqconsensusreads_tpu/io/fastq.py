"""FASTQ writing — replacement for Picard SamToFastq.

The reference shells out to `java -jar picard SamToFastq I=… F=… F2=…`
(reference: main.snake.py:67,79,176) to split an unaligned consensus BAM into
a gzipped R1/R2 FASTQ pair. This module does the same from BamRecords:
read1 -> F, read2 -> F2, reverse-strand records are reverse-complemented back
to sequencing orientation (Picard's default behavior).
"""

from __future__ import annotations

import gzip
from typing import Iterable

from bsseqconsensusreads_tpu.io.bam import BamRecord, FREAD2, FREVERSE

_COMPLEMENT = str.maketrans("ACGTNacgtn", "TGCANtgcan")


def reverse_complement(seq: str) -> str:
    return seq.translate(_COMPLEMENT)[::-1]


def qual_to_ascii(qual: bytes | None, length: int) -> str:
    if qual is None:
        return "!" * length
    return "".join(chr(min(q, 93) + 33) for q in qual)


def _fq_entry(rec: BamRecord, role: int) -> str:
    seq, qual = rec.seq, qual_to_ascii(rec.qual, len(rec.seq))
    if rec.flag & FREVERSE:
        seq = reverse_complement(seq)
        qual = qual[::-1]
    return f"@{rec.qname}/{role}\n{seq}\n+\n{qual}\n"


def sam_to_fastq(records: Iterable[BamRecord], fq1_path: str, fq2_path: str) -> tuple[int, int]:
    """Split records into paired gzipped FASTQs; returns (n_r1, n_r2).

    Pairs are matched by qname and written IN STEP: the two files always
    hold the same templates at the same line offsets, because downstream
    paired aligners (bwameth, main.snake.py:93,188) pair entries
    positionally — one orphan record written to only one file would shift
    and silently mispair everything after it. Records without a same-name
    mate of the opposite read-of-pair (orphans, e.g. duplex passthrough
    leftovers) are therefore skipped, like Picard SamToFastq refuses
    incomplete pairs rather than emitting desynchronized files.
    """
    n1 = n2 = 0
    pending: dict[str, BamRecord] = {}
    with gzip.open(fq1_path, "wt") as f1, gzip.open(fq2_path, "wt") as f2:
        for rec in records:
            if rec.flag & 0x900:  # secondary/supplementary never exported
                continue
            mate = pending.get(rec.qname)
            if mate is None or bool(mate.flag & FREAD2) == bool(rec.flag & FREAD2):
                pending[rec.qname] = rec  # first of the pair (or duplicate)
                continue
            del pending[rec.qname]
            r1, r2 = (mate, rec) if rec.flag & FREAD2 else (rec, mate)
            f1.write(_fq_entry(r1, 1))
            f2.write(_fq_entry(r2, 2))
            n1 += 1
            n2 += 1
    return n1, n2
