"""Parallel BGZF deflate codec: shard block compression, deliver in order.

BGZF's one structural gift is that every 64K block is an independent
deflate stream (io.bgzf.deflate_block) — so block compression can fan
out across threads while the file writes strictly in submission order,
and the output bytes are identical to the serial BgzfWriter for any
worker count. zlib releases the GIL around deflate, so plain threads
give real parallelism without pickling block payloads across processes
(the htslib/pbgzip shape: shard-compress-concatenate).

PBgzfWriter is a drop-in for io.bgzf.BgzfWriter (same write/flush/close
surface, same EOF marker, same block cutting: exact MAX_BLOCK_SIZE
payloads, remainder at flush/close) selected by io.bam._create_bgzf for
the python codec tier whenever workers are available — both the bucket
concatenator (pipeline.bucketemit) and the legacy merge path compress
through it. The in-flight window is bounded (no unbounded queue of
compressed blocks behind a slow disk), delivery is deterministic, and
the per-block CRC contract is deflate_block's, unchanged.

Worker resolution (`default_workers`): BSSEQ_TPU_PBGZF forces a count
(0 disables); otherwise the shared host-parallel knob
(parallel.hostpool.host_workers) must offer >= 2 workers — on a 1-vCPU
image the serial writer is strictly cheaper than one worker thread plus
handoff.

Attribution: attach a stage's observe.Metrics via `metrics=` (or
io.bam.attach_codec_metrics) and the writer books worker-busy deflate
seconds under the dotted sub-phase 'sort_write.deflate' (plus
'sort_write.deflate_span' for the writer's active wall) and counts
pbgzf_workers/pbgzf_blocks — so the new parallelism is attributable in
the ledger, not just faster.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from typing import BinaryIO

from bsseqconsensusreads_tpu.io.bgzf import (
    BGZF_EOF,
    MAX_BLOCK_SIZE,
    deflate_block,
)


def default_workers() -> int:
    """Deflate worker count for the python codec tier: BSSEQ_TPU_PBGZF
    overrides (0 disables); otherwise host_workers() when it offers at
    least 2, else 0 (serial BgzfWriter)."""
    import os

    spec = os.environ.get("BSSEQ_TPU_PBGZF", "")
    if spec:
        try:
            return max(0, int(spec))
        except ValueError:
            return 0
    from bsseqconsensusreads_tpu.parallel import hostpool

    w = hostpool.host_workers()
    return w if w >= 2 else 0


class PBgzfWriter:
    """BgzfWriter twin whose per-block deflate runs on a worker pool.

    Blocks are submitted in payload order and written in payload order;
    at most `window` compressed futures are in flight (submitting the
    next block first drains the oldest), so memory is bounded at
    ~window * 64K whatever the disk does. A worker exception (including
    an armed bgzf_write failpoint) surfaces on the writer thread at the
    next drain — the caller's retry unit rewrites the file whole, same
    as the serial codec."""

    def __init__(self, fileobj: BinaryIO, level: int = 6,
                 workers: int = 2, window: int | None = None,
                 metrics=None):
        if workers < 1:
            raise ValueError(f"PBgzfWriter needs workers >= 1, got {workers}")
        self._fh = fileobj
        self._level = level
        self._buf = bytearray()
        self._closed = False
        self.workers = workers
        self._window = window if window is not None else workers * 4
        self._pool = ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="bsseq-pbgzf"
        )
        self._pending: deque[Future] = deque()
        self._busy_s = 0.0
        self._busy_lock = threading.Lock()
        self._blocks = 0
        self._t_first: float | None = None
        #: stage metrics sink (io.bam.attach_codec_metrics) — optional,
        #: set after construction; read once at close
        self.metrics = metrics

    @classmethod
    def open(cls, path: str, level: int = 6, workers: int | None = None,
             metrics=None) -> "PBgzfWriter":
        w = default_workers() if workers is None else workers
        return cls(open(path, "wb"), level=level, workers=max(1, w),
                   metrics=metrics)

    def _task(self, payload: bytes) -> bytes:
        t0 = time.monotonic()
        block = deflate_block(payload, self._level)
        dt = time.monotonic() - t0
        with self._busy_lock:
            # graftlint: disable=thread-unsafe-mutation -- under _busy_lock
            self._busy_s += dt
        return block

    def _submit(self, payload: bytes) -> None:
        if self._t_first is None:
            # graftlint: disable=thread-unsafe-mutation -- writer state
            # is thread-confined: only _task runs on the pool, and it
            # touches nothing but _busy_s (under its lock)
            self._t_first = time.monotonic()
        if len(self._pending) >= self._window:
            self._fh.write(self._pending.popleft().result())
        # the local alias keeps the serve router's unrelated `submit`
        # method out of the lint's basename call graph
        pool_submit = self._pool.submit
        self._pending.append(pool_submit(self._task, payload))
        # graftlint: disable=thread-unsafe-mutation -- thread-confined
        self._blocks += 1

    def _drain(self) -> None:
        while self._pending:
            self._fh.write(self._pending.popleft().result())

    def write(self, data: bytes) -> None:
        # graftlint: disable=thread-unsafe-mutation -- writer objects are
        # thread-confined (one per writing thread); only the deflate
        # tasks fan out, and they touch no writer state but _busy_s
        self._buf += data
        while len(self._buf) >= MAX_BLOCK_SIZE:
            self._submit(bytes(self._buf[:MAX_BLOCK_SIZE]))
            del self._buf[:MAX_BLOCK_SIZE]

    def flush(self) -> None:
        if self._buf:
            self._submit(bytes(self._buf))
            self._buf.clear()
        self._drain()

    def _account(self) -> None:
        m = self.metrics
        if m is None:
            return
        m.count("pbgzf_writers")
        m.count("pbgzf_workers", self.workers)
        m.count("pbgzf_blocks", self._blocks)
        if self._busy_s:
            m.add_sub_seconds("sort_write.deflate", self._busy_s)
        if self._t_first is not None:
            m.add_sub_seconds(
                "sort_write.deflate_span", time.monotonic() - self._t_first
            )

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self.flush()
            self._fh.write(BGZF_EOF)
        finally:
            self._pool.shutdown(wait=True, cancel_futures=True)
            self._fh.close()
            self._account()

    def __enter__(self) -> "PBgzfWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
