"""ctypes bindings for the native C++ BGZF/BAM codec (native/bamio.cpp).

Loads native/libbamio.so (building it with `make -C native` on first use if a
compiler is available). Every entry point has a pure-Python fallback in
io.bgzf / io.bam; callers use `available()` or the factory functions which
degrade gracefully.
"""

from __future__ import annotations

import ctypes as C
import os

import numpy as np

from bsseqconsensusreads_tpu.faults.guard import (
    GuardError,
    MissingTagError,
    classify_stream_error,
)
from bsseqconsensusreads_tpu.io._nativelib import load_library

_lib = None
_load_error: str | None = None


def _try_load():
    global _lib, _load_error
    if _lib is not None or _load_error is not None:
        return
    lib, _load_error = load_library(
        # BSSEQ_TPU_BAMIO_SO selects an alternate build of the same ABI —
        # e.g. libbamio_tsan.so for the ThreadSanitizer stress run
        # (tools/tsan_stress.py); the make target is named after the .so
        os.environ.get("BSSEQ_TPU_BAMIO_SO", "libbamio.so"),
        "bamio.cpp",
        required_symbols=(
            "bamio_open", "bamio_read", "bamio_error", "bamio_close",
            "bamio_create", "bamio_write", "bamio_writer_error",
            "bamio_finish", "bamio_create_mt", "bamio_write_mt",
            "bamio_writer_error_mt", "bamio_finish_mt",
            "bamio_parse_records4", "bamio_parse_grouped3",
            "bamio_group_start", "bamio_group_error",
            "bamio_group_refragmented", "bamio_group_free",
            "bamio_encode_scan", "bamio_encode_fill",
            "bamio_duplex_scan", "bamio_duplex_fill",
            "bamio_open_mt", "bamio_merge_runs",
        ),
    )
    if lib is None:
        return
    lib.bamio_open.restype = C.c_void_p
    lib.bamio_open.argtypes = [C.c_char_p, C.c_char_p, C.c_int]
    lib.bamio_open_mt.restype = C.c_void_p
    lib.bamio_open_mt.argtypes = [C.c_char_p, C.c_int, C.c_char_p, C.c_int]
    lib.bamio_read.restype = C.c_int64
    lib.bamio_read.argtypes = [C.c_void_p, C.c_void_p, C.c_int64]
    lib.bamio_error.restype = C.c_char_p
    lib.bamio_error.argtypes = [C.c_void_p]
    lib.bamio_close.argtypes = [C.c_void_p]
    lib.bamio_create.restype = C.c_void_p
    lib.bamio_create.argtypes = [C.c_char_p, C.c_int, C.c_char_p, C.c_int]
    lib.bamio_write.restype = C.c_int
    lib.bamio_write.argtypes = [C.c_void_p, C.c_void_p, C.c_int64]
    lib.bamio_writer_error.restype = C.c_char_p
    lib.bamio_writer_error.argtypes = [C.c_void_p]
    lib.bamio_finish.restype = C.c_int
    lib.bamio_finish.argtypes = [C.c_void_p]
    lib.bamio_create_mt.restype = C.c_void_p
    lib.bamio_create_mt.argtypes = [
        C.c_char_p, C.c_int, C.c_int, C.c_char_p, C.c_int
    ]
    lib.bamio_write_mt.restype = C.c_int
    lib.bamio_write_mt.argtypes = [C.c_void_p, C.c_void_p, C.c_int64]
    lib.bamio_writer_error_mt.restype = C.c_char_p
    lib.bamio_writer_error_mt.argtypes = [C.c_void_p]
    lib.bamio_finish_mt.restype = C.c_int
    lib.bamio_finish_mt.argtypes = [C.c_void_p]
    lib.bamio_parse_records4.restype = C.c_int64
    lib.bamio_parse_records4.argtypes = [
        C.c_void_p, C.c_int64,
        C.c_void_p, C.c_void_p, C.c_void_p, C.c_void_p,
        C.c_void_p, C.c_void_p, C.c_void_p, C.c_void_p,
        C.c_void_p,
        C.c_void_p, C.c_void_p, C.c_int64, C.c_void_p,
        C.c_void_p, C.c_int64, C.c_void_p,
        C.c_char_p, C.c_int, C.c_char_p, C.c_int, C.c_char_p, C.c_int,
        C.c_void_p, C.c_void_p, C.c_void_p, C.c_void_p,
        C.c_void_p, C.c_int64, C.c_void_p, C.c_void_p,
    ]
    lib.bamio_group_start.restype = C.c_void_p
    lib.bamio_group_start.argtypes = [C.c_int64, C.c_int]
    lib.bamio_group_error.restype = C.c_char_p
    lib.bamio_group_error.argtypes = [C.c_void_p]
    lib.bamio_group_refragmented.restype = C.c_int64
    lib.bamio_group_refragmented.argtypes = [C.c_void_p]
    lib.bamio_group_free.argtypes = [C.c_void_p]
    lib.bamio_parse_grouped3.restype = C.c_int64
    lib.bamio_parse_grouped3.argtypes = (
        [C.c_void_p, C.c_void_p, C.c_int64]  # Reader*, Grouper*, max_records
        + lib.bamio_parse_records4.argtypes[2:]
        + [C.c_char_p, C.c_int, C.c_void_p, C.c_int64, C.c_void_p]
    )
    lib.bamio_encode_scan.restype = C.c_int64
    lib.bamio_encode_scan.argtypes = (
        [C.c_int64, C.c_void_p, C.c_void_p]        # n_fam, fam_start, fam_nrec
        + [C.c_void_p] * 8                          # flag..cigar_flags
        + [C.c_void_p, C.c_int32, C.c_void_p, C.c_int32]  # qname/w, rx/w
        + [C.c_int32, C.c_int64]                    # indel_policy, band
        + [C.c_void_p] * 10                         # outputs
    )
    lib.bamio_encode_fill.restype = C.c_int64
    lib.bamio_encode_fill.argtypes = (
        [C.c_int64] + [C.c_void_p] * 14 + [C.c_int64, C.c_int64]
        + [C.c_void_p, C.c_void_p]
    )
    lib.bamio_duplex_scan.restype = C.c_int64
    lib.bamio_duplex_scan.argtypes = (
        [C.c_int64, C.c_void_p, C.c_void_p]  # n_fam, fam_start, fam_nrec
        + [C.c_void_p] * 7                    # flag..cigar_flags
        + [C.c_void_p, C.c_int32]             # rx, rx_w
        + [C.c_void_p] * 8                    # outputs
    )
    lib.bamio_duplex_fill.restype = C.c_int64
    lib.bamio_duplex_fill.argtypes = (
        [C.c_int64] + [C.c_void_p] * 12 + [C.c_int64]
        + [C.c_void_p] * 3
    )
    lib.bamio_merge_runs.restype = C.c_int64
    lib.bamio_merge_runs.argtypes = [
        C.POINTER(C.c_void_p), C.c_int32, C.c_void_p, C.c_int32,
        C.c_char_p, C.c_int32, C.POINTER(C.c_double),
    ]
    _lib = lib


def available() -> bool:
    _try_load()
    return _lib is not None


def _bgzf_threads(threads: int | None) -> int:
    """Shared reader/writer worker-count policy: explicit value wins, else
    BSSEQ_TPU_BGZF_THREADS, else min(4, cpu count)."""
    if threads is not None:
        return threads
    default = min(4, os.cpu_count() or 1)
    try:
        return int(os.environ.get("BSSEQ_TPU_BGZF_THREADS", str(default)))
    except ValueError:
        return default


def load_error() -> str | None:
    _try_load()
    return _load_error


class NativeBgzfReader:
    """Drop-in for io.bgzf.BgzfReader backed by the C++ codec.

    Reads cross the ctypes boundary in 4 MiB chunks and are served from a
    Python-side buffer — per-record 4-byte reads would otherwise pay a
    ctypes round trip each.

    threads > 1 inflates BGZF blocks on a worker pool with in-order
    delivery (bamio_open_mt) — identical byte stream, the read-side twin
    of the MT writer; inflate is the ingest wall on multi-core hosts.
    Default: min(4, cpu count), overridable via BSSEQ_TPU_BGZF_THREADS
    (shared with the writer)."""

    _CHUNK = 1 << 22

    def __init__(self, path: str, threads: int | None = None):
        _try_load()
        if _lib is None:
            raise OSError(_load_error or "native codec unavailable")
        err = C.create_string_buffer(256)
        self._h = _lib.bamio_open_mt(
            path.encode(), _bgzf_threads(threads), err, 256
        )
        if not self._h:
            raise IOError(err.value.decode())
        self._buf = b""
        self._off = 0

    def _fill(self) -> bool:
        buf = C.create_string_buffer(self._CHUNK)
        got = _lib.bamio_read(self._h, buf, self._CHUNK)
        if got < 0:
            # typed stream error (same canonical reason as io.bgzf's
            # python wording — faults.guard pins the mapping)
            raise classify_stream_error(_lib.bamio_error(self._h).decode())
        if got == 0:
            return False
        # graftlint: disable=thread-unsafe-mutation -- reader state is
        # thread-confined (one reader per thread; the extsort background
        # writer's CRC pass opens its own — faults.integrity.file_crc32)
        self._buf = buf.raw[:got]
        # graftlint: disable=thread-unsafe-mutation -- confined
        self._off = 0
        return True

    def read(self, n: int) -> bytes:
        avail = len(self._buf) - self._off
        if avail >= n:  # fast path: serve from buffer
            out = self._buf[self._off : self._off + n]
            # graftlint: disable=thread-unsafe-mutation -- confined reader
            self._off += n
            return out
        parts = [self._buf[self._off :]]
        need = n - avail
        self._buf, self._off = b"", 0
        while need > 0:
            if not self._fill():
                break
            take = min(need, len(self._buf))
            parts.append(self._buf[:take])
            # graftlint: disable=thread-unsafe-mutation -- confined reader
            self._off = take
            need -= take
        return b"".join(parts)

    def read_unbuffered(self, n: int) -> bytes:
        """Exact read through ctypes with NO Python-side buffering — required
        before handing self._h to bamio_parse_records (which reads from the
        native stream position and must not skip buffered bytes)."""
        if self._off != len(self._buf):
            # a bare assert here would vanish under `python -O` and let
            # buffered bytes silently vanish from the record stream
            # (graftlint assert-on-input)
            raise GuardError("unbuffered read after buffered read")
        buf = C.create_string_buffer(n)
        got = _lib.bamio_read(self._h, buf, n)
        if got < 0:
            raise classify_stream_error(_lib.bamio_error(self._h).decode())
        return buf.raw[:got]

    def read_all(self, chunk: int = 1 << 22) -> bytes:
        parts = []
        while True:
            b = self.read(chunk)
            if not b:
                return b"".join(parts)
            parts.append(b)

    def close(self) -> None:
        if self._h:
            _lib.bamio_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class NativeBgzfWriter:
    """Drop-in for io.bgzf.BgzfWriter backed by the C++ codec.

    threads > 1 compresses BGZF blocks on a worker pool with in-order
    writes — byte-identical output to the single-threaded path (each 64 KB
    block is an independent deflate stream). Default: min(4, cpu count),
    overridable via BSSEQ_TPU_BGZF_THREADS; deflate is the write-side wall
    at 100M-read scale once record encode is native (io.wirepack)."""

    def __init__(self, path: str, level: int = 6, threads: int | None = None):
        _try_load()
        if _lib is None:
            raise OSError(_load_error or "native codec unavailable")
        threads = _bgzf_threads(threads)
        self._mt = threads > 1
        err = C.create_string_buffer(256)
        if self._mt:
            self._h = _lib.bamio_create_mt(path.encode(), level, threads, err, 256)
        else:
            self._h = _lib.bamio_create(path.encode(), level, err, 256)
        if not self._h:
            raise IOError(err.value.decode())

    def write(self, data: bytes) -> None:
        fn = _lib.bamio_write_mt if self._mt else _lib.bamio_write
        if fn(self._h, data, len(data)) != 0:
            errfn = (
                _lib.bamio_writer_error_mt if self._mt else _lib.bamio_writer_error
            )
            raise IOError(errfn(self._h).decode())

    def flush(self) -> None:
        pass  # blocks flush on finish; partial flush not needed

    def close(self) -> None:
        if self._h:
            rc = (
                _lib.bamio_finish_mt(self._h)
                if self._mt
                else _lib.bamio_finish(self._h)
            )
            self._h = None
            if rc != 0:
                raise IOError("bamio_finish failed")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class ColumnarBatch:
    """One parsed batch of records as flat numpy arrays.

    seq codes are already in the framework alphabet (A=0..T=3, N=4); per
    record i the bases/quals live at var_off[i] : var_off[i]+l_seq[i] and the
    cigar at cigar_off[i] : cigar_off[i]+n_cigar[i] (u32, len<<4|op).
    """

    __slots__ = (
        "n", "ref_id", "pos", "flag", "mapq", "l_seq", "next_ref",
        "next_pos", "tlen", "n_cigar", "seq", "qual", "var_off",
        "cigar", "cigar_off", "qname", "mi", "rx",
        "ref_span", "left_clip", "right_clip", "cigar_flags",
        "aux", "aux_off", "aux_len",
        # graftguard per-batch semantic-violation cache
        # (faults.guard.batch_violations, computed at most once)
        "guard_bad",
    )

    def __init__(self, n, **arrays):
        self.n = n
        for k, v in arrays.items():
            setattr(self, k, v)


def _skip_header(r: "NativeBgzfReader", path: str) -> None:
    """Skip the BAM header on a fresh native stream, with the same
    untrusted-length bounds as io.bam.read_bam_header (a lying l_text
    must raise typed, not size a giant read)."""
    import struct

    from bsseqconsensusreads_tpu.io.bam import (
        MAX_RECORD_SIZE,
        BamError,
    )

    def _i32(what: str) -> int:
        raw = r.read_unbuffered(4)
        if len(raw) < 4:
            raise BamError(f"corrupt BAM header (truncated {what})")
        return struct.unpack("<i", raw)[0]

    magic = r.read_unbuffered(4)
    if magic != b"BAM\x01":
        raise BamError(f"{path}: not a BAM file")
    l_text = _i32("l_text")
    if l_text < 0 or l_text > MAX_RECORD_SIZE:
        raise BamError("corrupt BAM header (bad l_text)")
    if len(r.read_unbuffered(l_text)) < l_text:
        raise BamError("corrupt BAM header (truncated text)")
    n_ref = _i32("n_ref")
    if n_ref < 0 or n_ref > (1 << 24):
        raise BamError("corrupt BAM header (bad n_ref)")
    for _ in range(n_ref):
        l_name = _i32("l_name")
        if l_name < 1 or l_name > (1 << 16):
            raise BamError("corrupt BAM header (bad l_name)")
        if len(r.read_unbuffered(l_name + 4)) < l_name + 4:
            raise BamError("corrupt BAM header (truncated name)")


def _alloc_batch(n: int, var_bytes: int, qname_width: int, tag_width: int):
    """Batch buffers + the ctypes argument list bamio_parse_records4 /
    bamio_parse_grouped3 share (from max_records onward)."""
    bufs = {
        "ref_id": np.empty(n, np.int32),
        "pos": np.empty(n, np.int32),
        "flag": np.empty(n, np.uint16),
        "mapq": np.empty(n, np.uint8),
        "l_seq": np.empty(n, np.int32),
        "next_ref": np.empty(n, np.int32),
        "next_pos": np.empty(n, np.int32),
        "tlen": np.empty(n, np.int32),
        "n_cigar": np.empty(n, np.uint16),
        "seq": np.empty(var_bytes, np.uint8),
        "qual": np.empty(var_bytes, np.uint8),
        "var_off": np.empty(n, np.int64),
        "cigar": np.empty(var_bytes // 16, np.uint32),
        "cigar_off": np.empty(n, np.int64),
        # calloc-backed numpy buffers: create_string_buffer would memset
        # ~20 MB per batch eagerly, dominating small files
        "qname": np.zeros(n * qname_width, np.uint8),
        "mi": np.zeros(n * tag_width, np.uint8),
        "rx": np.zeros(n * tag_width, np.uint8),
        "ref_span": np.empty(n, np.int32),
        "left_clip": np.empty(n, np.int32),
        "right_clip": np.empty(n, np.int32),
        "cigar_flags": np.empty(n, np.uint8),
        # cd/ce(/cB) aux planes (consensus-input ingest): per record, cd
        # then ce values (n u16 each) at aux[aux_off[i]], plus the 4n cB
        # histogram when aux_len[i] carries the 1<<30 flag bit (see
        # native/bamio.cpp kAuxHasCb / pipeline.ingest). Sized
        # 6*var_bytes ELEMENTS so a var-capacity fit implies an aux fit
        # even with every record carrying cB; np.empty is lazy, raw-read
        # inputs without the tags never commit these pages.
        "aux": np.empty(6 * var_bytes, np.uint16),
        "aux_off": np.empty(n, np.int64),
        "aux_len": np.empty(n, np.int32),
    }
    p = lambda k: bufs[k].ctypes.data_as(C.c_void_p)  # noqa: E731
    args = [
        p("ref_id"), p("pos"), p("flag"), p("mapq"), p("l_seq"),
        p("next_ref"), p("next_pos"), p("tlen"), p("n_cigar"),
        p("seq"), p("qual"), var_bytes, p("var_off"),
        p("cigar"), var_bytes // 16, p("cigar_off"),
        bufs["qname"].ctypes.data_as(C.c_char_p), qname_width,
        bufs["mi"].ctypes.data_as(C.c_char_p), tag_width,
        bufs["rx"].ctypes.data_as(C.c_char_p), tag_width,
        p("ref_span"), p("left_clip"), p("right_clip"), p("cigar_flags"),
        p("aux"), 6 * var_bytes, p("aux_off"), p("aux_len"),
    ]
    return bufs, args


def _batch_from(bufs, got: int, qname_width: int, tag_width: int):
    fixed_keys = (
        "ref_id", "pos", "flag", "mapq", "l_seq", "next_ref", "next_pos",
        "tlen", "n_cigar",
    )
    return ColumnarBatch(
        int(got),
        **{k: bufs[k][:got] for k in fixed_keys},
        seq=bufs["seq"],
        qual=bufs["qual"],
        var_off=bufs["var_off"][:got],
        cigar=bufs["cigar"],
        cigar_off=bufs["cigar_off"][:got],
        qname=bufs["qname"].view(f"S{qname_width}")[:got],
        mi=bufs["mi"].view(f"S{tag_width}")[:got],
        rx=bufs["rx"].view(f"S{tag_width}")[:got],
        ref_span=bufs["ref_span"][:got],
        left_clip=bufs["left_clip"][:got],
        right_clip=bufs["right_clip"][:got],
        cigar_flags=bufs["cigar_flags"][:got],
        aux=bufs["aux"],
        aux_off=bufs["aux_off"][:got],
        aux_len=bufs["aux_len"][:got],
    )


def read_columnar(
    path: str,
    batch_records: int = 1 << 16,
    var_bytes: int = 1 << 25,
    qname_width: int = 256,
    tag_width: int = 48,
):
    # qname_width=256 covers the BAM format's hard limit (l_read_name is a
    # uint8: <=254 chars + NUL), so the parser's clamp can never truncate a
    # legal qname — truncation would silently merge distinct templates that
    # share a prefix (encode pairs R1/R2 by qname).
    """Stream a BAM file as ColumnarBatches (header is parsed separately by
    BamReader — this starts from a fresh native stream and skips the header).
    """
    r = NativeBgzfReader(path)
    total = 0
    try:
        _skip_header(r, path)
        while True:
            bufs, args = _alloc_batch(
                batch_records, var_bytes, qname_width, tag_width
            )
            got = _lib.bamio_parse_records4(r._h, batch_records, *args)
            # graftguard error protocol: a mid-batch corruption returns
            # the already-parsed prefix with the error pending in
            # bamio_error, so the typed raise carries the exact failing
            # record index — the same index the python engine reports
            msg = _lib.bamio_error(r._h).decode()
            if got > 0:
                total += got
                yield _batch_from(bufs, got, qname_width, tag_width)
            if msg:
                raise classify_stream_error(msg, record_index=total)
            if got <= 0:
                return
            # a short batch means either EOF or a capacity stop with a
            # pending record; the next parse call distinguishes (got==0 ends)
    finally:
        r.close()


def read_grouped_columnar(
    path: str,
    flush_margin: int = 10_000,
    strip_suffix: bool = False,
    batch_records: int = 1 << 16,
    var_bytes: int = 1 << 25,
    qname_width: int = 256,
    tag_width: int = 48,
):
    """Stream ColumnarBatches whose records are reordered into CONTIGUOUS
    whole-MI-family runs by the C-side coordinate grouper
    (bamio_parse_grouped3 — the native equivalent of
    pipeline.calling.stream_mi_groups grouping='coordinate').

    Yields (batch, fam_mi bytes array [nf], fam_nrec int32 [nf],
    refragmented_delta). Raises ValueError on a record without an MI tag
    (reference parity: tools/2.extend_gap.py:180). A single family larger
    than the buffers grows them and retries.
    """
    _try_load()
    if _lib is None:
        raise OSError(_load_error or "native codec unavailable")
    r = NativeBgzfReader(path)
    g = _lib.bamio_group_start(flush_margin, int(strip_suffix))
    refrag_prev = 0
    records_seen = 0
    try:
        _skip_header(r, path)
        while True:
            bufs, args = _alloc_batch(
                batch_records, var_bytes, qname_width, tag_width
            )
            fam_cap = batch_records
            fam_mi = np.zeros(fam_cap * tag_width, np.uint8)
            fam_nrec = np.empty(fam_cap, np.int32)
            n_fams = C.c_int64(0)
            got = _lib.bamio_parse_grouped3(
                r._h, g, batch_records, *args,
                fam_mi.ctypes.data_as(C.c_char_p), tag_width,
                fam_nrec.ctypes.data_as(C.c_void_p), fam_cap,
                C.byref(n_fams),
            )
            if got == -1:
                raise classify_stream_error(
                    _lib.bamio_error(r._h).decode(),
                    record_index=records_seen,
                )
            if got == -2:
                qn = _lib.bamio_group_error(g).decode()
                raise MissingTagError(qn)
            if got == -3:  # one family exceeds the buffers: grow and retry
                batch_records *= 2
                var_bytes *= 2
                continue
            if got == 0:
                return
            nf = n_fams.value
            records_seen += int(got)
            refrag = int(_lib.bamio_group_refragmented(g))
            delta, refrag_prev = refrag - refrag_prev, refrag
            yield (
                _batch_from(bufs, got, qname_width, tag_width),
                fam_mi.view(f"S{tag_width}")[:nf],
                fam_nrec[:nf],
                delta,
            )
    finally:
        _lib.bamio_group_free(g)
        r.close()


def _vp(a: np.ndarray) -> C.c_void_p:
    return a.ctypes.data_as(C.c_void_p)


def encode_scan(
    batch, fam_start: np.ndarray, fam_nrec: np.ndarray,
    indel_policy: str, indel_band: int,
) -> dict[str, np.ndarray]:
    """Run the C molecular-encode scan (bamio_encode_scan) over contiguous
    family runs of one ColumnarBatch. Returns the per-family digest and
    per-record placement arrays ops.encode consumes; semantics mirror
    encode_molecular_families pass 1 exactly (see native/bamio.cpp)."""
    nf = len(fam_start)
    n = batch.n
    out = {
        "lo": np.empty(nf, np.int64),
        "window": np.empty(nf, np.int64),
        "ntpl": np.empty(nf, np.int32),
        "ntpl_est": np.empty(nf, np.int32),
        "rolerev": np.empty(nf, np.uint8),
        "refid": np.empty(nf, np.int32),
        "rx_rec": np.empty(nf, np.int64),
        "ti": np.empty(n, np.int32),
        "role": np.empty(n, np.uint8),
        "keep": np.empty(n, np.uint8),
    }
    qname_w = batch.qname.dtype.itemsize
    rx_w = batch.rx.dtype.itemsize
    rc = _lib.bamio_encode_scan(
        nf, _vp(fam_start), _vp(fam_nrec),
        _vp(batch.flag), _vp(batch.pos), _vp(batch.ref_id),
        _vp(batch.l_seq), _vp(batch.var_off),
        _vp(batch.left_clip), _vp(batch.right_clip), _vp(batch.cigar_flags),
        _vp(batch.qname.view(np.uint8)), qname_w,
        _vp(batch.rx.view(np.uint8)), rx_w,
        0 if indel_policy == "drop" else 1, indel_band,
        _vp(out["lo"]), _vp(out["window"]),
        _vp(out["ntpl"]), _vp(out["ntpl_est"]),
        _vp(out["rolerev"]), _vp(out["refid"]), _vp(out["rx_rec"]),
        _vp(out["ti"]), _vp(out["role"]), _vp(out["keep"]),
    )
    if rc != 0:
        raise RuntimeError(f"bamio_encode_scan failed: rc={rc}")
    return out


def encode_fill(
    batch, scan: dict[str, np.ndarray],
    fam_start: np.ndarray, fam_nrec: np.ndarray,
    rows: np.ndarray, lo: np.ndarray,
    bases: np.ndarray, quals: np.ndarray,
) -> int:
    """Write one segment's direct-placed reads into the [*, T, 2, W] batch
    tensors via bamio_encode_fill. Returns records written."""
    t_pad, _, w_pad = bases.shape[1:]
    got = _lib.bamio_encode_fill(
        len(fam_start), _vp(fam_start), _vp(fam_nrec),
        _vp(rows), _vp(lo),
        _vp(batch.pos), _vp(batch.l_seq), _vp(batch.var_off),
        _vp(batch.left_clip), _vp(batch.right_clip),
        _vp(batch.seq), _vp(batch.qual),
        _vp(scan["ti"]), _vp(scan["role"]), _vp(scan["keep"]),
        t_pad, w_pad, _vp(bases), _vp(quals),
    )
    if got < 0:
        raise RuntimeError(
            "bamio_encode_fill: read outside its family window "
            "(scan/fill mismatch)"
        )
    return int(got)


def duplex_scan(
    batch, fam_start: np.ndarray, fam_nrec: np.ndarray
) -> dict[str, np.ndarray]:
    """Run the C duplex-encode scan (bamio_duplex_scan) over contiguous
    family runs of one ColumnarBatch; mirrors encode_duplex_families
    pass 1 (see native/bamio.cpp)."""
    nf = len(fam_start)
    out = {
        "start": np.empty(nf, np.int64),
        "window": np.empty(nf, np.int64),
        "rowmask": np.empty(nf, np.uint8),
        "gsize": np.empty(nf, np.int32),
        "refid": np.empty(nf, np.int32),
        "rx_rec": np.empty(nf, np.int64),
        "nleft": np.empty(nf, np.int32),
        "row": np.empty(batch.n, np.int8),
    }
    rc = _lib.bamio_duplex_scan(
        nf, _vp(fam_start), _vp(fam_nrec),
        _vp(batch.flag), _vp(batch.pos), _vp(batch.ref_id),
        _vp(batch.l_seq), _vp(batch.left_clip), _vp(batch.right_clip),
        _vp(batch.cigar_flags),
        _vp(batch.rx.view(np.uint8)), batch.rx.dtype.itemsize,
        _vp(out["start"]), _vp(out["window"]), _vp(out["rowmask"]),
        _vp(out["gsize"]), _vp(out["refid"]), _vp(out["rx_rec"]),
        _vp(out["nleft"]), _vp(out["row"]),
    )
    if rc != 0:
        raise RuntimeError(f"bamio_duplex_scan failed: rc={rc}")
    return out


def merge_runs(readers: "list[NativeBgzfReader]",
               writer: "NativeBgzfWriter") -> tuple[int, float]:
    """k-way native merge of sorted spill runs (bamio_merge_runs).

    readers: NativeBgzfReaders positioned just past their BAM headers
    (io.native._skip_header — which reads unbuffered, so no Python-side
    bytes can be stranded). writer: an open NativeBgzfWriter the merged
    record stream is appended to (header already written by the caller).
    Returns (records merged, seconds spent inside the writer's
    deflate/write calls — the sort_write.merge_bgzf attribution).
    Ordering and tie-breaks are raw_coordinate_key + run-index stable,
    byte-identical to heapq.merge over the Python engine's runs.
    """
    _try_load()
    if _lib is None:
        raise OSError(_load_error or "native codec unavailable")
    for i, r in enumerate(readers):
        if r._off != len(r._buf):
            raise GuardError(
                f"merge run {i}: reader holds Python-buffered bytes; "
                "open it fresh and skip the header unbuffered"
            )
    handles = (C.c_void_p * len(readers))(
        *[C.c_void_p(r._h) for r in readers]
    )
    err = C.create_string_buffer(256)
    write_s = C.c_double(0.0)
    n = _lib.bamio_merge_runs(
        handles, len(readers), writer._h, int(writer._mt),
        err, 256, C.byref(write_s),
    )
    if n < 0:
        raise IOError(f"native merge failed: {err.value.decode()}")
    return int(n), write_s.value


def duplex_fill(
    batch, scan: dict[str, np.ndarray],
    fam_start: np.ndarray, fam_nrec: np.ndarray,
    rows: np.ndarray, starts: np.ndarray,
    bases: np.ndarray, quals: np.ndarray, cover: np.ndarray,
) -> int:
    """Write one segment's placed duplex reads into the [*, 4, W] batch
    tensors via bamio_duplex_fill. Returns records written."""
    w_pad = bases.shape[-1]
    got = _lib.bamio_duplex_fill(
        len(fam_start), _vp(fam_start), _vp(fam_nrec),
        _vp(rows), _vp(starts),
        _vp(batch.pos), _vp(batch.l_seq), _vp(batch.var_off),
        _vp(batch.left_clip), _vp(batch.right_clip),
        _vp(batch.seq), _vp(batch.qual),
        _vp(scan["row"]), w_pad,
        _vp(bases), _vp(quals), _vp(cover),
    )
    if got < 0:
        raise RuntimeError(
            "bamio_duplex_fill: read outside its family window "
            "(scan/fill mismatch)"
        )
    return int(got)
