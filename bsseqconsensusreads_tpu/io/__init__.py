"""First-party sequencing-data codecs: BGZF, BAM, FASTA, FASTQ.

The reference delegates all record I/O to pysam/htslib and samtools
(reference: tools/1.convert_AG_to_CT.py:25-26, main.snake.py:93). This package
implements the formats directly in a pure-Python codec, with a native C++
fast path for the hot decode/emit paths (native/bamio.cpp, native/wirepack.cpp
via io.native / io.wirepack) that is preferred automatically when built; the
pure-Python codec is the reference implementation and the fallback.
"""

from bsseqconsensusreads_tpu.io.bam import (  # noqa: F401
    BamHeader,
    BamReader,
    BamRecord,
    BamWriter,
    CIGAR_OPS,
    CDEL,
    CHARD_CLIP,
    CINS,
    CMATCH,
    CSOFT_CLIP,
)
from bsseqconsensusreads_tpu.io.bgzf import BgzfReader, BgzfWriter  # noqa: F401
from bsseqconsensusreads_tpu.io.fasta import FastaFile  # noqa: F401
