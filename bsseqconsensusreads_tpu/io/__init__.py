"""First-party sequencing-data codecs: BGZF, BAM, FASTA, FASTQ.

The reference delegates all record I/O to pysam/htslib and samtools
(reference: tools/1.convert_AG_to_CT.py:25-26, main.snake.py:93). This package
implements the formats directly in a pure-Python codec. (A native C++ codec
for the hot decode path is planned under native/ and will be preferred when
built; until then this is the only codec.)
"""

from bsseqconsensusreads_tpu.io.bam import (  # noqa: F401
    BamHeader,
    BamReader,
    BamRecord,
    BamWriter,
    CIGAR_OPS,
    CDEL,
    CHARD_CLIP,
    CINS,
    CMATCH,
    CSOFT_CLIP,
)
from bsseqconsensusreads_tpu.io.bgzf import BgzfReader, BgzfWriter  # noqa: F401
from bsseqconsensusreads_tpu.io.fasta import FastaFile  # noqa: F401
