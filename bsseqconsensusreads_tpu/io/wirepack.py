"""ctypes bindings for the native wire packer (native/wirepack.cpp).

The duplex tunnel stage's host time is dominated by the numpy input pack
(~130 ms/batch at F=16384: codebook detection + 2-bit index packing over
~10M cells) and output unpack (~20 ms). The C++ sweep does the same work
byte-for-byte in single-digit milliseconds, so host serialization stops
competing with the device transfer for wall clock.

Same loading contract as io.native: build on first use, degrade to the
numpy implementations in ops.wire / models.duplex when no compiler exists.
"""

from __future__ import annotations

import ctypes as C

import numpy as np

from bsseqconsensusreads_tpu.io._nativelib import load_library

_lib = None
_load_error: str | None = None

# Error codes from native/wirepack.cpp.
_ERR_TOO_MANY_LEVELS = -2
_ERR_QUAL_TOO_HIGH = -3


def _try_load():
    global _lib, _load_error
    if _lib is not None or _load_error is not None:
        return
    lib, _load_error = load_library(
        "libwirepack.so", "wirepack.cpp", env_flag="BSSEQ_TPU_NATIVE_WIRE"
    )
    if lib is None:
        return
    lib.wirepack_pack_duplex.restype = C.c_int
    lib.wirepack_pack_duplex.argtypes = [
        C.c_void_p, C.c_void_p, C.c_void_p, C.c_void_p, C.c_void_p,
        C.c_int64, C.c_int64, C.c_int64, C.c_int,
        C.c_void_p, C.c_void_p, C.c_void_p, C.c_void_p, C.c_void_p,
    ]
    lib.wirepack_unpack_duplex_outputs.restype = None
    lib.wirepack_unpack_duplex_outputs.argtypes = [
        C.c_void_p, C.c_int64, C.c_int64,
        C.c_void_p, C.c_void_p, C.c_void_p, C.c_void_p, C.c_void_p,
        C.c_void_p,
    ]
    _lib = lib


def available() -> bool:
    _try_load()
    return _lib is not None


def load_error() -> str | None:
    _try_load()
    return _load_error


_MODE_BITS = {"q8": 8, "q4": 4, "q2": 2, "auto": 0}
_BITS_MODE = {8: "q8", 4: "q4", 2: "q2"}


def pack_duplex(bases, quals, cover, convert_mask, eligible, qual_mode):
    """Native pack of a duplex batch -> (nib, qual, meta u32 arrays, mode).

    Inputs as ops.wire.pack_duplex_inputs; returns the three packed wire
    sections plus the resolved qual mode. Raises the same ValueErrors as the
    numpy path for codebook overflow / out-of-range quals.
    """
    _try_load()
    if _lib is None:
        raise OSError(_load_error or "native wirepack unavailable")
    f, r, w = bases.shape
    cells = f * r * w
    bases = np.ascontiguousarray(bases, dtype=np.int8)
    quals = np.ascontiguousarray(quals, dtype=np.uint8)
    cover = np.ascontiguousarray(cover, dtype=np.uint8)
    cmask = np.ascontiguousarray(convert_mask, dtype=np.uint8)
    elig = np.ascontiguousarray(eligible, dtype=np.uint8)
    nib = np.empty((cells // 2 + 3) // 4 * 4, dtype=np.uint8)
    meta = np.empty((f + 3) // 4 * 4, dtype=np.uint8)
    qual = np.empty(cells + 24, dtype=np.uint8)
    qual_len = C.c_int64(0)
    nlevels = C.c_int(0)
    bits = _lib.wirepack_pack_duplex(
        bases.ctypes.data_as(C.c_void_p),
        quals.ctypes.data_as(C.c_void_p),
        cover.ctypes.data_as(C.c_void_p),
        cmask.ctypes.data_as(C.c_void_p),
        elig.ctypes.data_as(C.c_void_p),
        f, r, w, _MODE_BITS[qual_mode],
        nib.ctypes.data_as(C.c_void_p),
        meta.ctypes.data_as(C.c_void_p),
        qual.ctypes.data_as(C.c_void_p),
        C.byref(qual_len),
        C.byref(nlevels),
    )
    if bits == _ERR_QUAL_TOO_HIGH:
        raise ValueError(
            "covered qual > 93 (BAM printable max) cannot ride a "
            f"{qual_mode} codebook; use qual_mode='q8' or 'auto'"
        )
    if bits == _ERR_TOO_MANY_LEVELS:
        raise ValueError(
            f"{nlevels.value} distinct covered quals exceed {qual_mode}'s "
            f"{1 << _MODE_BITS[qual_mode]}-entry codebook; use "
            "qual_mode='auto'"
        )
    if bits < 0:
        raise ValueError(f"native wirepack error {bits}")
    # zero the nib/meta word padding the C side never touches
    nib[cells // 2 :] = 0
    meta[f:] = 0
    return (
        nib.view(np.uint32),
        qual[: qual_len.value].view(np.uint32).copy(),
        meta.view(np.uint32),
        _BITS_MODE[bits],
    )


def unpack_duplex_outputs(wire_u8: np.ndarray, f: int, w: int) -> dict:
    """Native unpack of the family-major planar output wire ([f, 4, w] u8:
    b0 planes then qual planes per family) -> dict of [f, 2, w] arrays."""
    _try_load()
    if _lib is None:
        raise OSError(_load_error or "native wirepack unavailable")
    cols = f * 2 * w
    wire_u8 = np.ascontiguousarray(wire_u8[: 2 * cols], dtype=np.uint8)
    out = {
        "base": np.empty(cols, np.int8),
        "qual": np.empty(cols, np.uint8),
        "depth": np.empty(cols, np.int16),
        "errors": np.empty(cols, np.int16),
        "a_depth": np.empty(cols, np.int8),
        "b_depth": np.empty(cols, np.int8),
    }
    _lib.wirepack_unpack_duplex_outputs(
        wire_u8.ctypes.data_as(C.c_void_p), f, w,
        out["base"].ctypes.data_as(C.c_void_p),
        out["qual"].ctypes.data_as(C.c_void_p),
        out["depth"].ctypes.data_as(C.c_void_p),
        out["errors"].ctypes.data_as(C.c_void_p),
        out["a_depth"].ctypes.data_as(C.c_void_p),
        out["b_depth"].ctypes.data_as(C.c_void_p),
    )
    return {k: v.reshape(f, 2, w) for k, v in out.items()}
