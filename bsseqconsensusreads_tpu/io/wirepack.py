"""ctypes bindings for the native wire packer (native/wirepack.cpp).

The duplex tunnel stage's host time is dominated by the numpy input pack
(~130 ms/batch at F=16384: codebook detection + 2-bit index packing over
~10M cells) and output unpack (~20 ms). The C++ sweep does the same work
byte-for-byte in single-digit milliseconds, so host serialization stops
competing with the device transfer for wall clock.

Same loading contract as io.native: build on first use, degrade to the
numpy implementations in ops.wire / models.duplex when no compiler exists.
"""

from __future__ import annotations

import ctypes as C
import os

import numpy as np

from bsseqconsensusreads_tpu.io._nativelib import load_library

_lib = None
_load_error: str | None = None

# Error codes from native/wirepack.cpp.
_ERR_TOO_MANY_LEVELS = -2
_ERR_QUAL_TOO_HIGH = -3
_ERR_QNAME_TOO_LONG = -5


def _try_load():
    global _lib, _load_error
    if _lib is not None or _load_error is not None:
        return
    lib, _load_error = load_library(
        # BSSEQ_TPU_WIREPACK_SO selects an alternate build of the same ABI
        # (e.g. libwirepack_asan.so for tools/sanitize_native.py)
        os.environ.get("BSSEQ_TPU_WIREPACK_SO", "libwirepack.so"),
        "wirepack.cpp",
        env_flag="BSSEQ_TPU_NATIVE_WIRE",
        required_symbols=(
            "wirepack_pack_duplex",
            "wirepack_pack_rows",
            "wirepack_unpack_duplex_outputs",
            "wirepack_unpack_duplex_b0",
            "wirepack_duplex_rawize",
            "wirepack_duplex_retire",
            "wirepack_emit_consensus_records_v4",
            "wirepack_sort_raw_records",
            "wirepack_bucket_assign",
            "wirepack_bucket_scatter",
            "wirepack_strand_calls",
            "wirepack_bcount_sparse",
            "wirepack_methyl_tally_merge",
        ),
    )
    if lib is None:
        return
    lib.wirepack_pack_duplex.restype = C.c_int
    lib.wirepack_pack_duplex.argtypes = [
        C.c_void_p, C.c_void_p, C.c_void_p, C.c_void_p, C.c_void_p,
        C.c_int64, C.c_int64, C.c_int64, C.c_int,
        C.c_void_p, C.c_void_p, C.c_void_p, C.c_void_p, C.c_void_p,
    ]
    lib.wirepack_pack_rows.restype = C.c_int
    lib.wirepack_pack_rows.argtypes = [
        C.c_void_p, C.c_void_p, C.c_int64, C.c_int64, C.c_int,
        C.c_void_p, C.c_void_p, C.c_void_p, C.c_void_p,
    ]
    lib.wirepack_unpack_duplex_outputs.restype = None
    lib.wirepack_unpack_duplex_outputs.argtypes = [
        C.c_void_p, C.c_int64, C.c_int64,
        C.c_void_p, C.c_void_p, C.c_void_p, C.c_void_p, C.c_void_p,
        C.c_void_p, C.c_void_p, C.c_void_p,
    ]
    lib.wirepack_unpack_duplex_b0.restype = None
    lib.wirepack_unpack_duplex_b0.argtypes = [
        C.c_void_p, C.c_int64, C.c_int64,
        C.c_void_p, C.c_void_p, C.c_void_p, C.c_void_p, C.c_void_p,
        C.c_void_p, C.c_void_p,
    ]
    lib.wirepack_duplex_retire.restype = None
    lib.wirepack_duplex_retire.argtypes = (
        [C.c_void_p, C.c_int64, C.c_int64]  # b0, f, w
        + [C.c_void_p] * 6  # cover, quals_pre, la, rd, eligible, role_rows
        + [C.c_void_p] * 3  # t_single, t_agree, t_dis
        + [C.c_void_p] * 8  # base, qual, depth, errors, a/b presence+err
    )
    lib.wirepack_duplex_rawize.restype = None
    lib.wirepack_duplex_rawize.argtypes = [
        C.c_int64, C.c_int64,
        C.c_void_p, C.c_void_p, C.c_void_p, C.c_void_p,
        C.c_void_p, C.c_void_p, C.c_void_p, C.c_void_p, C.c_void_p,
        C.c_void_p,
        C.c_void_p, C.c_void_p, C.c_void_p, C.c_void_p, C.c_void_p,
        C.c_void_p,
    ]
    lib.wirepack_emit_consensus_records_v4.restype = C.c_int
    lib.wirepack_emit_consensus_records_v4.argtypes = (
        # planes: base..b_depth, a/b_ss_err, ss_valid, bcount, a/b_call
        [C.c_void_p] * 12
        + [C.c_int64, C.c_int64]
        + [C.c_void_p] * 10
        + [C.c_int, C.c_int, C.c_void_p, C.c_int64]
        + [C.c_void_p] * 3
    )
    lib.wirepack_sort_raw_records.restype = C.c_int64
    lib.wirepack_sort_raw_records.argtypes = [
        C.c_void_p, C.c_int64, C.c_void_p,
        C.POINTER(C.c_double), C.POINTER(C.c_double),
    ]
    lib.wirepack_bucket_assign.restype = C.c_int64
    lib.wirepack_bucket_assign.argtypes = [
        C.c_void_p, C.c_int64, C.c_void_p, C.c_int32,
        C.c_int64, C.c_void_p, C.c_void_p, C.c_void_p,
    ]
    lib.wirepack_bucket_scatter.restype = C.c_int64
    lib.wirepack_bucket_scatter.argtypes = [
        C.c_void_p, C.c_int64, C.c_void_p, C.c_void_p, C.c_void_p,
        C.c_int32, C.c_void_p, C.c_int64, C.c_void_p,
    ]
    lib.wirepack_strand_calls.restype = None
    lib.wirepack_strand_calls.argtypes = (
        [C.c_void_p] * 5 + [C.c_int64, C.c_int64, C.c_void_p]
    )
    lib.wirepack_bcount_sparse.restype = None
    lib.wirepack_bcount_sparse.argtypes = [
        C.c_void_p, C.c_void_p, C.c_int64, C.c_int64, C.c_int64,
        C.c_void_p, C.c_int, C.c_int, C.c_void_p,
    ]
    lib.wirepack_methyl_tally_merge.restype = C.c_int64
    lib.wirepack_methyl_tally_merge.argtypes = [
        C.c_void_p, C.c_void_p, C.c_void_p, C.c_void_p, C.c_int64,
        C.c_void_p, C.c_void_p, C.c_void_p, C.c_void_p,
    ]
    _lib = lib


def available() -> bool:
    _try_load()
    return _lib is not None


def load_error() -> str | None:
    _try_load()
    return _load_error


_MODE_BITS = {"q8": 8, "q4": 4, "q2": 2, "auto": 0}
_BITS_MODE = {8: "q8", 4: "q4", 2: "q2"}


def pack_duplex(bases, quals, cover, convert_mask, eligible, qual_mode):
    """Native pack of a duplex batch -> (nib, qual, meta u32 arrays, mode).

    Inputs as ops.wire.pack_duplex_inputs; returns the three packed wire
    sections plus the resolved qual mode. Raises the same ValueErrors as the
    numpy path for codebook overflow / out-of-range quals.
    """
    _try_load()
    if _lib is None:
        raise OSError(_load_error or "native wirepack unavailable")
    f, r, w = bases.shape
    cells = f * r * w
    if cells % 2:
        # the C nibble loop reads bases[i+1]; an odd cell count would read
        # one byte past the buffer (ops.wire guards w%2 for its callers,
        # direct callers are guarded here)
        raise ValueError(f"duplex wire pack needs an even f*r*w, got {cells}")
    bases = np.ascontiguousarray(bases, dtype=np.int8)
    quals = np.ascontiguousarray(quals, dtype=np.uint8)
    cover = np.ascontiguousarray(cover, dtype=np.uint8)
    cmask = np.ascontiguousarray(convert_mask, dtype=np.uint8)
    elig = np.ascontiguousarray(eligible, dtype=np.uint8)
    nib = np.empty((cells // 2 + 3) // 4 * 4, dtype=np.uint8)
    meta = np.empty((f + 3) // 4 * 4, dtype=np.uint8)
    qual = np.empty(cells + 24, dtype=np.uint8)
    qual_len = C.c_int64(0)
    nlevels = C.c_int(0)
    bits = _lib.wirepack_pack_duplex(
        bases.ctypes.data_as(C.c_void_p),
        quals.ctypes.data_as(C.c_void_p),
        cover.ctypes.data_as(C.c_void_p),
        cmask.ctypes.data_as(C.c_void_p),
        elig.ctypes.data_as(C.c_void_p),
        f, r, w, _MODE_BITS[qual_mode],
        nib.ctypes.data_as(C.c_void_p),
        meta.ctypes.data_as(C.c_void_p),
        qual.ctypes.data_as(C.c_void_p),
        C.byref(qual_len),
        C.byref(nlevels),
    )
    if bits == _ERR_QUAL_TOO_HIGH:
        raise ValueError(
            "covered qual > 93 (BAM printable max) cannot ride a "
            f"{qual_mode} codebook; use qual_mode='q8' or 'auto'"
        )
    if bits == _ERR_TOO_MANY_LEVELS:
        raise ValueError(
            f"{nlevels.value} distinct covered quals exceed {qual_mode}'s "
            f"{1 << _MODE_BITS[qual_mode]}-entry codebook; use "
            "qual_mode='auto'"
        )
    if bits < 0:
        raise ValueError(f"native wirepack error {bits}")
    # zero the nib/meta word padding the C side never touches
    nib[cells // 2 :] = 0
    meta[f:] = 0
    return (
        nib.view(np.uint32),
        qual[: qual_len.value].view(np.uint32).copy(),
        meta.view(np.uint32),
        _BITS_MODE[bits],
    )


def pack_rows(bases, quals, qual_mode):
    """Native pack of segment-packed rows -> (nib, qual u32 arrays, mode).

    bases int8 [n, 2, w], quals uint8 [n, 2, w]; cover is derived in the C
    sweep (base != NBASE) so no [n, 2, w] bool plane is ever materialized.
    The nib/qual bytes are identical to pack_duplex on the same rows with
    derived cover — the ops.wire packed wire v2 body sections.
    """
    _try_load()
    if _lib is None:
        raise OSError(_load_error or "native wirepack unavailable")
    n, _, w = bases.shape
    cells = n * 2 * w
    if cells % 2:
        raise ValueError(f"rows wire pack needs an even n*2*w, got {cells}")
    bases = np.ascontiguousarray(bases, dtype=np.int8)
    quals = np.ascontiguousarray(quals, dtype=np.uint8)
    nib = np.empty((cells // 2 + 3) // 4 * 4, dtype=np.uint8)
    qual = np.empty(cells + 24, dtype=np.uint8)
    qual_len = C.c_int64(0)
    nlevels = C.c_int(0)
    bits = _lib.wirepack_pack_rows(
        bases.ctypes.data_as(C.c_void_p),
        quals.ctypes.data_as(C.c_void_p),
        n, w, _MODE_BITS[qual_mode],
        nib.ctypes.data_as(C.c_void_p),
        qual.ctypes.data_as(C.c_void_p),
        C.byref(qual_len),
        C.byref(nlevels),
    )
    if bits == _ERR_QUAL_TOO_HIGH:
        raise ValueError(
            "covered qual > 93 (BAM printable max) cannot ride a "
            f"{qual_mode} codebook; use qual_mode='q8' or 'auto'"
        )
    if bits == _ERR_TOO_MANY_LEVELS:
        raise ValueError(
            f"{nlevels.value} distinct covered quals exceed {qual_mode}'s "
            f"{1 << _MODE_BITS[qual_mode]}-entry codebook; use "
            "qual_mode='auto'"
        )
    if bits < 0:
        raise ValueError(f"native wirepack error {bits}")
    nib[cells // 2 :] = 0
    return (
        nib.view(np.uint32),
        qual[: qual_len.value].view(np.uint32).copy(),
        _BITS_MODE[bits],
    )


def unpack_duplex_outputs(wire_u8: np.ndarray, f: int, w: int) -> dict:
    """Native unpack of the family-major planar output wire ([f, 4, w] u8:
    v2 b0 planes then qual planes per family) -> dict of [f, 2, w] arrays."""
    _try_load()
    if _lib is None:
        raise OSError(_load_error or "native wirepack unavailable")
    cols = f * 2 * w
    wire_u8 = np.ascontiguousarray(wire_u8[: 2 * cols], dtype=np.uint8)
    out = {
        "base": np.empty(cols, np.int8),
        "qual": np.empty(cols, np.uint8),
        "depth": np.empty(cols, np.int16),
        "errors": np.empty(cols, np.int16),
        "a_depth": np.empty(cols, np.int8),
        "b_depth": np.empty(cols, np.int8),
        "a_err": np.empty(cols, np.int8),
        "b_err": np.empty(cols, np.int8),
    }
    _lib.wirepack_unpack_duplex_outputs(
        wire_u8.ctypes.data_as(C.c_void_p), f, w,
        out["base"].ctypes.data_as(C.c_void_p),
        out["qual"].ctypes.data_as(C.c_void_p),
        out["depth"].ctypes.data_as(C.c_void_p),
        out["errors"].ctypes.data_as(C.c_void_p),
        out["a_depth"].ctypes.data_as(C.c_void_p),
        out["b_depth"].ctypes.data_as(C.c_void_p),
        out["a_err"].ctypes.data_as(C.c_void_p),
        out["b_err"].ctypes.data_as(C.c_void_p),
    )
    return {k: v.reshape(f, 2, w) for k, v in out.items()}


def unpack_duplex_b0(wire_u8: np.ndarray, f: int, w: int) -> dict:
    """Native unpack of the b0-only tunnel wire ([f, 2, w] u8) -> dict of
    [f, 2, w] arrays; no 'qual' key (ops.reconstruct rebuilds it)."""
    _try_load()
    if _lib is None:
        raise OSError(_load_error or "native wirepack unavailable")
    cols = f * 2 * w
    wire_u8 = np.ascontiguousarray(wire_u8[:cols], dtype=np.uint8)
    out = {
        "base": np.empty(cols, np.int8),
        "depth": np.empty(cols, np.int16),
        "errors": np.empty(cols, np.int16),
        "a_depth": np.empty(cols, np.int8),
        "b_depth": np.empty(cols, np.int8),
        "a_err": np.empty(cols, np.int8),
        "b_err": np.empty(cols, np.int8),
    }
    _lib.wirepack_unpack_duplex_b0(
        wire_u8.ctypes.data_as(C.c_void_p), f, w,
        out["base"].ctypes.data_as(C.c_void_p),
        out["depth"].ctypes.data_as(C.c_void_p),
        out["errors"].ctypes.data_as(C.c_void_p),
        out["a_depth"].ctypes.data_as(C.c_void_p),
        out["b_depth"].ctypes.data_as(C.c_void_p),
        out["a_err"].ctypes.data_as(C.c_void_p),
        out["b_err"].ctypes.data_as(C.c_void_p),
    )
    return {k: v.reshape(f, 2, w) for k, v in out.items()}


def duplex_retire(b0_u8: np.ndarray, f: int, w: int, cover, quals_pre,
                  la, rd, eligible, role_rows,
                  t_single, t_agree, t_dis) -> dict:
    """One-pass native duplex retire: b0 decode + qual reconstruction
    (wirepack_duplex_retire; ops.reconstruct holds the numpy reference).
    Returns the full output dict minus la/rd (the caller splits those)."""
    _try_load()
    if _lib is None:
        raise OSError(_load_error or "native wirepack unavailable")
    cols = f * 2 * w
    b0_u8 = np.ascontiguousarray(b0_u8[:cols], dtype=np.uint8)
    cover = np.ascontiguousarray(cover, dtype=np.uint8)
    quals_pre = np.ascontiguousarray(quals_pre, dtype=np.float32)
    la = np.ascontiguousarray(la, dtype=np.int8)
    rd = np.ascontiguousarray(rd, dtype=np.int8)
    eligible = np.ascontiguousarray(eligible, dtype=np.uint8)
    role_rows = np.ascontiguousarray(role_rows, dtype=np.int32)
    t_single = np.ascontiguousarray(t_single, dtype=np.uint8)
    t_agree = np.ascontiguousarray(t_agree, dtype=np.uint8)
    t_dis = np.ascontiguousarray(t_dis, dtype=np.uint8)
    out = {
        "base": np.empty(cols, np.int8),
        "qual": np.empty(cols, np.uint8),
        "depth": np.empty(cols, np.int16),
        "errors": np.empty(cols, np.int16),
        "a_depth": np.empty(cols, np.int8),
        "b_depth": np.empty(cols, np.int8),
        "a_err": np.empty(cols, np.int8),
        "b_err": np.empty(cols, np.int8),
    }
    p = lambda a: a.ctypes.data_as(C.c_void_p)  # noqa: E731
    _lib.wirepack_duplex_retire(
        p(b0_u8), f, w,
        p(cover), p(quals_pre), p(la), p(rd), p(eligible), p(role_rows),
        p(t_single),
        p(t_agree), p(t_dis), p(out["base"]),
        p(out["qual"]), p(out["depth"]), p(out["errors"]),
        p(out["a_depth"]), p(out["b_depth"]), p(out["a_err"]),
        p(out["b_err"]),
    )
    return {k: v.reshape(f, 2, w) for k, v in out.items()}


def duplex_rawize(out: dict, row_pos, row_off, row_len, aux, window_start,
                  role_rows) -> dict:
    """Native raw-unit conversion of duplex presence planes (the C twin of
    pipeline.calling's fallback loop — see wirepack_duplex_rawize).

    out: unpacked b0 dict; row_* int64/int64/int32 [f*4]; aux u16 flat
    cd/ce buffer; window_start int64 [f]; role_rows int32 [4]. Returns a
    new dict with int16 raw planes.
    """
    _try_load()
    if _lib is None:
        raise OSError(_load_error or "native wirepack unavailable")
    a_p = np.ascontiguousarray(out["a_depth"], dtype=np.int8)
    b_p = np.ascontiguousarray(out["b_depth"], dtype=np.int8)
    a_e = np.ascontiguousarray(out["a_err"], dtype=np.int8)
    b_e = np.ascontiguousarray(out["b_err"], dtype=np.int8)
    f, _, w = a_p.shape
    # pre-fill with presence units: the C pass only overwrites sidecar rows
    ad = a_p.astype(np.int16)
    bd = b_p.astype(np.int16)
    ae = a_e.astype(np.int16)
    be = b_e.astype(np.int16)
    depth = np.empty((f, 2, w), np.int16)
    errors = np.empty((f, 2, w), np.int16)
    row_pos = np.ascontiguousarray(row_pos, dtype=np.int64)
    row_off = np.ascontiguousarray(row_off, dtype=np.int64)
    row_len = np.ascontiguousarray(row_len, dtype=np.int32)
    aux = np.ascontiguousarray(aux, dtype=np.uint16)
    window_start = np.ascontiguousarray(window_start, dtype=np.int64)
    role_rows = np.ascontiguousarray(role_rows, dtype=np.int32)
    p = lambda a: a.ctypes.data_as(C.c_void_p)  # noqa: E731
    _lib.wirepack_duplex_rawize(
        f, w, p(a_p), p(b_p), p(a_e), p(b_e),
        p(row_pos), p(row_off), p(row_len), p(aux), p(window_start),
        p(role_rows),
        p(ad), p(bd), p(ae), p(be), p(depth), p(errors),
    )
    new = dict(out)
    new["a_depth"], new["b_depth"] = ad, bd
    # raw-unit per-strand error planes (the C pass computes them for the
    # errors sum anyway): pipeline.calling's exact-ce pass refines these
    # wherever the cB histogram exists
    new["a_err"], new["b_err"] = ae, be
    new["depth"], new["errors"] = depth, errors
    return new


def methyl_tally_merge(sites, ctx, meth, unmeth):
    """Native merge of methylation site tallies -> sorted unique summed
    rows (methyl.tally.merge_tallies holds the pinned numpy twin)."""
    _try_load()
    if _lib is None:
        raise OSError(_load_error or "native wirepack unavailable")
    sites = np.ascontiguousarray(sites, dtype=np.int64)
    ctx = np.ascontiguousarray(ctx, dtype=np.uint8)
    meth = np.ascontiguousarray(meth, dtype=np.uint32)
    unmeth = np.ascontiguousarray(unmeth, dtype=np.uint32)
    n = sites.size
    out_sites = np.empty(n, np.int64)
    out_ctx = np.empty(n, np.uint8)
    out_meth = np.empty(n, np.uint32)
    out_unmeth = np.empty(n, np.uint32)
    p = lambda a: a.ctypes.data_as(C.c_void_p)  # noqa: E731
    m = _lib.wirepack_methyl_tally_merge(
        p(sites), p(ctx), p(meth), p(unmeth), n,
        p(out_sites), p(out_ctx), p(out_meth), p(out_unmeth),
    )
    return (
        out_sites[:m].copy(), out_ctx[:m].copy(),
        out_meth[:m].copy(), out_unmeth[:m].copy(),
    )


def _string_blob(strings: list[str]):
    """(blob u8, offsets i32, lengths i32) for a list of ascii strings."""
    lens = np.fromiter(
        (len(s) for s in strings), dtype=np.int32, count=len(strings)
    )
    offs = np.zeros(len(strings), dtype=np.int32)
    if len(strings) > 1:
        np.cumsum(lens[:-1], out=offs[1:])
    if strings:
        blob = np.frombuffer(
            "".join(strings).encode("ascii"), dtype=np.uint8
        ).copy()
    else:
        blob = np.zeros(0, np.uint8)
    return blob, offs, lens


def emit_consensus_records(
    out: dict,
    *,
    ref_id,
    window_start,
    n_reads,
    role_reverse,
    mi: list[str],
    rx: list[str],
    min_reads: int,
    mode_self: bool,
    duplex: bool,
    bcount=None,
    strand_calls=None,
    strand_err=None,
) -> tuple[bytes, int, int]:
    """Native batch emit: kernel output planes -> BAM record bytes.

    out: dict of [f, 2, w] arrays (base int8, qual uint8, depth/errors
    int16, plus a_depth/b_depth int16 when duplex). Per-family metadata as
    documented on wirepack_emit_consensus_records_v4 (native/wirepack.cpp).
    rx entries may be "" (no RX tag). bcount (uint16 [f, 2, 4, w]) adds
    the molecular cB histogram tag; strand_calls ((a_call, b_call) int8
    [f, 2, w]) adds the duplex ac/bc strand-call string tags; strand_err
    ((a_ss_err, b_ss_err, ss_valid) — int16 [f, 2, w] x2 + bool [f, 2])
    adds the fgbio aE/bE rates + ae/be per-base strand-error arrays on
    records whose ss_valid gate is set. Returns
    (record bytes, n_records, n_families_skipped); the bytes are ready
    for BamWriter.write_raw — byte-identical to the Python emit +
    encode_record path
    (pipeline.calling cites: _emit_molecular_batch/_emit_duplex_batch).
    """
    _try_load()
    if _lib is None:
        raise OSError(_load_error or "native wirepack unavailable")
    base = np.ascontiguousarray(out["base"], dtype=np.int8)
    qual = np.ascontiguousarray(out["qual"], dtype=np.uint8)
    depth = np.ascontiguousarray(out["depth"], dtype=np.int16)
    errors = np.ascontiguousarray(out["errors"], dtype=np.int16)
    f, _, w = base.shape
    if duplex:
        a_depth = np.ascontiguousarray(out["a_depth"], dtype=np.int16)
        b_depth = np.ascontiguousarray(out["b_depth"], dtype=np.int16)
        a_ptr = a_depth.ctypes.data_as(C.c_void_p)
        b_ptr = b_depth.ctypes.data_as(C.c_void_p)
    else:
        a_ptr = b_ptr = None
    if bcount is not None:
        bcount = np.ascontiguousarray(bcount, dtype=np.uint16)
        bc_ptr = bcount.ctypes.data_as(C.c_void_p)
    else:
        bc_ptr = None
    if strand_calls is not None:
        a_call = np.ascontiguousarray(strand_calls[0], dtype=np.int8)
        b_call = np.ascontiguousarray(strand_calls[1], dtype=np.int8)
        ac_ptr = a_call.ctypes.data_as(C.c_void_p)
        bcall_ptr = b_call.ctypes.data_as(C.c_void_p)
    else:
        ac_ptr = bcall_ptr = None
    if strand_err is not None:
        a_se = np.ascontiguousarray(strand_err[0], dtype=np.int16)
        b_se = np.ascontiguousarray(strand_err[1], dtype=np.int16)
        ss_valid = np.ascontiguousarray(strand_err[2], dtype=np.uint8)
        ase_ptr = a_se.ctypes.data_as(C.c_void_p)
        bse_ptr = b_se.ctypes.data_as(C.c_void_p)
        ssv_ptr = ss_valid.ctypes.data_as(C.c_void_p)
    else:
        ase_ptr = bse_ptr = ssv_ptr = None
    ref_id = np.ascontiguousarray(ref_id, dtype=np.int32)
    window_start = np.ascontiguousarray(window_start, dtype=np.int64)
    n_reads = np.ascontiguousarray(n_reads, dtype=np.int32)
    role_reverse = np.ascontiguousarray(role_reverse, dtype=np.uint8)
    mi_blob, mi_off, mi_len = _string_blob(mi)
    rx_blob, rx_off, rx_len = _string_blob(rx)
    mi_max = int(mi_len.max()) if len(mi) else 0
    rx_max = int(rx_len.max()) if len(rx) else 0
    per_col = (
        10
        + 4 * duplex
        + (8 if bcount is not None else 0)
        + (2 if strand_calls is not None else 0)
        + (4 if strand_err is not None else 0)
    )
    cap = int(f) * 2 * (per_col * int(w) + 2 * mi_max + rx_max + 220)
    buf = np.empty(max(cap, 4096), dtype=np.uint8)
    out_len = C.c_int64(0)
    n_records = C.c_int64(0)
    n_skipped = C.c_int64(0)
    rc = _lib.wirepack_emit_consensus_records_v4(
        base.ctypes.data_as(C.c_void_p),
        qual.ctypes.data_as(C.c_void_p),
        depth.ctypes.data_as(C.c_void_p),
        errors.ctypes.data_as(C.c_void_p),
        a_ptr, b_ptr, ase_ptr, bse_ptr, ssv_ptr, bc_ptr, ac_ptr, bcall_ptr,
        f, w,
        ref_id.ctypes.data_as(C.c_void_p),
        window_start.ctypes.data_as(C.c_void_p),
        n_reads.ctypes.data_as(C.c_void_p),
        role_reverse.ctypes.data_as(C.c_void_p),
        mi_blob.ctypes.data_as(C.c_void_p),
        mi_off.ctypes.data_as(C.c_void_p),
        mi_len.ctypes.data_as(C.c_void_p),
        rx_blob.ctypes.data_as(C.c_void_p),
        rx_off.ctypes.data_as(C.c_void_p),
        rx_len.ctypes.data_as(C.c_void_p),
        int(min_reads), int(bool(mode_self)),
        buf.ctypes.data_as(C.c_void_p), buf.size,
        C.byref(out_len), C.byref(n_records), C.byref(n_skipped),
    )
    if rc == _ERR_QNAME_TOO_LONG:
        raise ValueError(
            "an MI qname exceeds BAM's 254-char l_read_name limit"
        )
    if rc != 0:
        raise ValueError(
            f"native record emit overflowed its {buf.size}-byte buffer"
        )
    # tobytes() trims the used span out of the (deliberately oversized)
    # scratch buffer so downstream holders don't pin the full capacity
    return buf[: out_len.value].tobytes(), n_records.value, n_skipped.value


def sort_raw_records(blob) -> tuple[bytes, int, float, float]:
    """Native in-RAM sort of one spill run of encoded record blobs.

    blob: a bytes-like of concatenated encoded records (each with its
    4-byte block_size prefix). Returns (sorted bytes, n_records,
    key_extract_seconds, sort_gather_seconds). The ordering is exactly
    pipeline.extsort.raw_coordinate_key over a stable sort — the Python
    engine's `buf.sort(key=raw_coordinate_key)` twin.
    """
    _try_load()
    if _lib is None:
        raise OSError(_load_error or "native wirepack unavailable")
    src = np.frombuffer(blob, dtype=np.uint8)
    out = np.empty(src.size, dtype=np.uint8)
    key_s = C.c_double(0.0)
    sort_s = C.c_double(0.0)
    n = _lib.wirepack_sort_raw_records(
        src.ctypes.data_as(C.c_void_p), src.size,
        out.ctypes.data_as(C.c_void_p), C.byref(key_s), C.byref(sort_s),
    )
    if n < 0:
        raise ValueError(
            "native raw-record sort found a malformed record frame "
            f"(rc={n}) — the emit stream is corrupt"
        )
    return out.tobytes(), int(n), key_s.value, sort_s.value


def bucket_split(blob, boundaries: np.ndarray) -> tuple[list[bytes], np.ndarray]:
    """Native bucket pass for one routing chunk (pipeline.bucketemit).

    blob: concatenated encoded record frames (4-byte block_size prefix
    each). boundaries: int64 ascending combined-key lower bounds
    (boundaries[0] == 0; combined key = mapped_ref * 2^31 + mapped_pos,
    the (ref, pos) prefix of raw_coordinate_key). Returns
    (per-bucket byte strings preserving input order, per-bucket record
    counts int64[nbuckets]) — one frame scan (wirepack_bucket_assign)
    plus one gather (wirepack_bucket_scatter), no per-record Python.
    """
    _try_load()
    if _lib is None:
        raise OSError(_load_error or "native wirepack unavailable")
    src = np.frombuffer(blob, dtype=np.uint8)
    bounds = np.ascontiguousarray(boundaries, dtype=np.int64)
    nbuckets = int(bounds.size)
    cap = src.size // 36 + 1  # min frame = 4-byte prefix + 32-byte record
    offs = np.empty(cap, np.int64)
    sizes = np.empty(cap, np.int32)
    buckets = np.empty(cap, np.int32)
    n = _lib.wirepack_bucket_assign(
        src.ctypes.data_as(C.c_void_p), src.size,
        bounds.ctypes.data_as(C.c_void_p), nbuckets,
        cap, offs.ctypes.data_as(C.c_void_p),
        sizes.ctypes.data_as(C.c_void_p),
        buckets.ctypes.data_as(C.c_void_p),
    )
    if n < 0:
        raise ValueError(
            "native bucket assign found a malformed record frame "
            f"(rc={n}) — the emit stream is corrupt"
        )
    n = int(n)
    offs, sizes, buckets = offs[:n], sizes[:n], buckets[:n]
    byte_totals = np.bincount(
        buckets, weights=sizes, minlength=nbuckets
    ).astype(np.int64)
    counts = np.bincount(buckets, minlength=nbuckets).astype(np.int64)
    starts = np.zeros(nbuckets, np.int64)
    np.cumsum(byte_totals[:-1], out=starts[1:])
    out = np.empty(src.size, np.uint8)
    rc = _lib.wirepack_bucket_scatter(
        src.ctypes.data_as(C.c_void_p), n,
        offs.ctypes.data_as(C.c_void_p),
        sizes.ctypes.data_as(C.c_void_p),
        buckets.ctypes.data_as(C.c_void_p),
        nbuckets, starts.ctypes.data_as(C.c_void_p),
        out.size, out.ctypes.data_as(C.c_void_p),
    )
    if rc != 0:
        raise ValueError(f"native bucket scatter failed (rc={rc})")
    ends = starts + byte_totals
    parts = [
        out[starts[b] : ends[b]].tobytes() if byte_totals[b] else b""
        for b in range(nbuckets)
    ]
    return parts, counts


def bcount_sparse(bases, quals, cons, params) -> np.ndarray:
    """Native one-pass sparse cB dissent histogram for one molecular
    batch: overlap co-call + observation filter + per-base tally +
    call-plane sparsification (the numpy chain _overlap_cocall_np ->
    _base_histogram -> sparsify_base_counts, integer-exact — the emit
    span's tag-build prologue). bases int8 [f, t, 2, w], quals uint8,
    cons int8 [f, 2, w] -> uint16 [f, 2, 4, w]."""
    _try_load()
    if _lib is None:
        raise OSError(_load_error or "native wirepack unavailable")
    bases = np.ascontiguousarray(bases, dtype=np.int8)
    quals = np.ascontiguousarray(quals, dtype=np.uint8)
    cons = np.ascontiguousarray(cons, dtype=np.int8)
    f, t, _, w = bases.shape
    out = np.empty((f, 2, 4, w), np.uint16)
    _lib.wirepack_bcount_sparse(
        bases.ctypes.data_as(C.c_void_p),
        quals.ctypes.data_as(C.c_void_p),
        f, t, w,
        cons.ctypes.data_as(C.c_void_p),
        int(params.min_input_base_quality),
        int(bool(params.consensus_call_overlapping_bases)),
        out.ctypes.data_as(C.c_void_p),
    )
    return out


def strand_calls(bases, cover, ref, convert_mask, eligible) -> np.ndarray:
    """Native twin of ops.hosttwin.strand_call_planes (calls plane only).

    bases int8 [f, 4, w], cover bool/u8 [f, 4, w], ref int8 [f, w+1],
    convert_mask bool/u8 [f, 4], eligible bool/u8 [f] -> int8 [f, 4, w]
    post-transform per-strand consensus calls, NBASE where the
    transformed row has no coverage. Byte-identical to the numpy twin
    (tests/test_wirepack.py pins it); the duplex rawize pass's hot path.
    """
    _try_load()
    if _lib is None:
        raise OSError(_load_error or "native wirepack unavailable")
    bases = np.ascontiguousarray(bases, dtype=np.int8)
    cover = np.ascontiguousarray(cover, dtype=np.uint8)
    ref = np.ascontiguousarray(ref, dtype=np.int8)
    cmask = np.ascontiguousarray(convert_mask, dtype=np.uint8)
    elig = np.ascontiguousarray(eligible, dtype=np.uint8)
    f, r, w = bases.shape
    if r != 4 or ref.shape != (f, w + 1):
        raise ValueError(
            f"strand_calls wants [f, 4, w] bases and [f, w+1] ref; got "
            f"{bases.shape} / {ref.shape}"
        )
    out = np.empty((f, 4, w), np.int8)
    p = lambda a: a.ctypes.data_as(C.c_void_p)  # noqa: E731
    _lib.wirepack_strand_calls(
        p(bases), p(cover), p(ref), p(cmask), p(elig), f, w, p(out)
    )
    return out
