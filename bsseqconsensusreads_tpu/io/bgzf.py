"""BGZF (blocked gzip) codec — the container format of BAM.

Pure-Python implementation over zlib. The reference reads/writes BGZF only
through htslib (via pysam / samtools); this is a first-party replacement so the
framework has no dependency on either. The hot decode path has a native C++
codec (native/bamio.cpp multi-threaded inflate via io.native); this module is
the reference implementation and the fallback when the native library is not
built.

Format: a BGZF file is a sequence of gzip members, each with an FEXTRA "BC"
subfield carrying BSIZE (total member size - 1), uncompressed payload at most
65280 bytes, terminated by a fixed 28-byte empty block (EOF marker).
"""

from __future__ import annotations

import struct
import zlib
from typing import BinaryIO, Iterator

from bsseqconsensusreads_tpu.faults import failpoints as _failpoints
from bsseqconsensusreads_tpu.faults.guard import StreamGuardError

# Largest uncompressed payload per block (htslib convention: 64KiB minus slop).
MAX_BLOCK_SIZE = 65280

# The canonical 28-byte BGZF EOF marker (an empty block).
BGZF_EOF = bytes.fromhex(
    "1f8b08040000000000ff0600424302001b0003000000000000000000"
)

_HEADER = struct.Struct("<4BI2BH")  # magic(2) CM FLG MTIME XFL OS XLEN — 12 bytes


class BgzfError(StreamGuardError):
    """BGZF framing/integrity error. Subclasses the graftguard typed
    stream error (which is an IOError, preserving the historical
    ancestry) so every corruption an input stream can cause is a
    faults.guard.GuardError — the fuzz contract's 'clean typed error'."""


def _parse_block_size(extra: bytes) -> int:
    """Scan FEXTRA subfields for the BC subfield and return BSIZE+1."""
    off = 0
    while off + 4 <= len(extra):
        si1, si2, slen = extra[off], extra[off + 1], struct.unpack_from("<H", extra, off + 2)[0]
        if si1 == 0x42 and si2 == 0x43 and slen == 2:  # 'B','C'
            if off + 6 > len(extra):  # BSIZE itself truncated away
                break
            return struct.unpack_from("<H", extra, off + 4)[0] + 1
        off += 4 + slen
    raise BgzfError("BGZF block missing BC extra subfield")


class BgzfReader:
    """Streaming BGZF decompressor with a file-like read() interface.

    resync=True arms the graftguard stream-resilience mode: a corrupt
    or truncated block raises nothing — the reader scans forward for
    the next block that parses AND inflates cleanly (CRC-checked),
    resumes there, and flags the discontinuity via `gap_pending` (the
    record layer must re-find a record boundary; io.bam's guarded
    iterator does). A truncated tail (missing EOF marker / partial
    final block with no later block) becomes a clean end-of-stream,
    also flagged. `on_event(kind, payload)` receives one callback per
    resync/truncation so the guard can ledger and count it.
    """

    #: bytes scanned forward for the next valid block before giving up
    RESYNC_SCAN_LIMIT = 1 << 22

    def __init__(self, fileobj: BinaryIO, resync: bool = False,
                 on_event=None):
        self._fh = fileobj
        self._buf = b""
        self._buf_off = 0
        self._eof = False
        self._last_block_empty = False
        self._resync = resync
        self._on_event = on_event
        #: file offset of the most recent block's first byte (None when
        #: the underlying file object is not seekable)
        self.last_block_offset: int | None = 0
        #: a resync skipped bytes and record framing is lost; cleared
        #: by the consumer via ack_gap()
        self.gap_pending = False
        self._gap_just = False

    @classmethod
    def open(cls, path: str, resync: bool = False,
             on_event=None) -> "BgzfReader":
        return cls(open(path, "rb"), resync=resync, on_event=on_event)

    def _event(self, kind: str, payload: dict) -> None:
        if self._on_event is not None:
            self._on_event(kind, payload)

    def _tell(self) -> int | None:
        try:
            return self._fh.tell()
        except (OSError, AttributeError):
            return None

    def _read_block(self) -> bytes | None:
        if not self._resync:
            return self._read_block_raw()
        try:
            return self._read_block_raw()
        except BgzfError as exc:
            return self._resync_block(exc)

    def _resync_block(self, exc: BgzfError) -> bytes | None:
        """Skip-to-next-block recovery: scan forward from just past the
        corrupt block's start for the next gzip member that parses as
        BGZF and inflates with a matching CRC. No candidate within
        RESYNC_SCAN_LIMIT (or an unseekable stream) ends the stream as
        a truncated tail instead."""
        start = self.last_block_offset
        if start is None or not self._fh.seekable():
            self._event("stream_truncated", {"error": str(exc)})
            # suppress the EOF-marker raise; reader state is confined to
            # the one ingest thread that owns this reader
            # graftlint: disable=thread-unsafe-mutation -- confined
            self._last_block_empty = True
            return None
        scan_from = start + 1
        self._fh.seek(scan_from)
        window = self._fh.read(self.RESYNC_SCAN_LIMIT)
        off = 0
        while True:
            hit = window.find(b"\x1f\x8b\x08\x04", off)
            if hit < 0:
                self._event("stream_truncated", {
                    "error": str(exc), "scanned": len(window),
                })
                self._fh.seek(0, 2)  # consume: later reads see EOF
                # graftlint: disable=thread-unsafe-mutation -- confined
                self._last_block_empty = True
                return None
            self._fh.seek(scan_from + hit)
            try:
                data = self._read_block_raw()
            except BgzfError:
                off = hit + 1
                continue
            self._event("stream_gap", {
                "error": str(exc),
                "gap_start": start,
                "resumed_at": scan_from + hit,
                "skipped_bytes": scan_from + hit - start,
            })
            # graftlint: disable=thread-unsafe-mutation -- confined
            self._gap_just = True
            # graftlint: disable=thread-unsafe-mutation -- confined
            self.gap_pending = True
            return data

    def ack_gap(self) -> None:
        """Consumer acknowledges a framing gap (after re-finding a
        record boundary in the post-gap bytes)."""
        # graftlint: disable=thread-unsafe-mutation -- confined
        self.gap_pending = False

    def _read_block_raw(self) -> bytes | None:
        # graftlint: disable=thread-unsafe-mutation -- confined
        self.last_block_offset = self._tell()
        head = self._fh.read(12)
        if not head:
            # A well-formed BGZF stream ends with an empty block (the 28-byte
            # EOF marker). Reaching physical EOF without one means the writer
            # was killed between flush and close — data may be missing.
            if not self._last_block_empty:
                raise BgzfError("BGZF EOF marker missing (file truncated?)")
            return None
        if len(head) < 12:
            raise BgzfError("truncated BGZF block header")
        magic1, magic2, cm, flg, _mtime, _xfl, _os, xlen = _HEADER.unpack(head)
        if magic1 != 0x1F or magic2 != 0x8B or cm != 8 or not (flg & 4):
            raise BgzfError("not a BGZF stream (bad gzip/FEXTRA header)")
        extra = self._fh.read(xlen)
        bsize = _parse_block_size(extra)
        cdata_len = bsize - 12 - xlen - 8
        if cdata_len < 0:  # untrusted 16-bit field vs declared XLEN
            raise BgzfError("corrupt BGZF BSIZE")
        cdata = self._fh.read(cdata_len)
        tail = self._fh.read(8)
        if len(cdata) < cdata_len or len(tail) < 8:
            raise BgzfError("truncated BGZF block")
        crc, isize = struct.unpack("<II", tail)
        if _failpoints.ARMED:  # guarded: this runs once per 64K block
            _failpoints.fire("bgzf_inflate")
        try:
            data = zlib.decompress(cdata, wbits=-15)
        except zlib.error as exc:  # corrupt deflate stream, typed
            raise BgzfError(f"BGZF inflate failed: {exc}") from None
        if len(data) != isize:
            raise BgzfError("BGZF ISIZE mismatch")
        if zlib.crc32(data) != crc:
            raise BgzfError("BGZF CRC mismatch")
        # graftlint: disable=thread-unsafe-mutation -- reader state is
        # thread-confined: every BgzfReader is created and consumed by
        # one thread (the extsort background writer's CRC pass opens
        # its own reader inside the task — faults.integrity.file_crc32)
        self._last_block_empty = len(data) == 0
        return data

    def read(self, n: int) -> bytes:
        """Read exactly n bytes unless EOF intervenes (then fewer)."""
        parts = []
        need = n
        while need > 0:
            avail = len(self._buf) - self._buf_off
            if avail == 0:
                if self._eof:
                    break
                block = self._read_block()
                if block is None:
                    # graftlint: disable=thread-unsafe-mutation -- see
                    # _read_block: readers are thread-confined
                    self._eof = True
                    break
                # graftlint: disable=thread-unsafe-mutation -- confined
                self._buf = block
                # graftlint: disable=thread-unsafe-mutation -- confined
                self._buf_off = 0
                if self._gap_just:
                    # a resync happened: never splice pre- and post-gap
                    # bytes into one logical read — return short and
                    # leave the post-gap block buffered for the record
                    # layer's re-framing pass
                    # graftlint: disable=thread-unsafe-mutation -- confined
                    self._gap_just = False
                    break
                continue
            take = min(avail, need)
            parts.append(self._buf[self._buf_off : self._buf_off + take])
            # graftlint: disable=thread-unsafe-mutation -- confined
            self._buf_off += take
            need -= take
        return b"".join(parts)

    def read_all(self) -> bytes:
        parts = [self._buf[self._buf_off :]]
        self._buf = b""
        self._buf_off = 0
        while True:
            block = self._read_block()
            if block is None:
                break
            parts.append(block)
        self._eof = True
        return b"".join(parts)

    def close(self) -> None:
        self._fh.close()

    def __enter__(self) -> "BgzfReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def deflate_block(data: bytes, level: int = 6) -> bytes:
    """Compress one <=MAX_BLOCK_SIZE payload into a complete framed BGZF
    block (gzip member with the BC/BSIZE FEXTRA subfield + CRC32/ISIZE
    footer). THE one block encoder — BgzfWriter and the parallel codec
    (io.pbgzf) both call it, so the incompressible-payload retry and the
    frame bytes cannot drift between the serial and sharded paths: the
    same payload sequence always produces the same file bytes, whatever
    codec or worker count wrote it. Each block is an independent deflate
    stream, which is exactly what makes sharding deflate across threads
    byte-identical to the serial writer."""
    if _failpoints.ARMED:  # guarded: this runs once per 64K block
        _failpoints.fire("bgzf_write")
    co = zlib.compressobj(level, zlib.DEFLATED, -15)
    cdata = co.compress(data) + co.flush()
    bsize = len(cdata) + 12 + 6 + 8  # header + xtra + footer
    if bsize > 65536:
        # Incompressible payload: store with minimal compression instead.
        co = zlib.compressobj(0, zlib.DEFLATED, -15)
        cdata = co.compress(data) + co.flush()
        bsize = len(cdata) + 12 + 6 + 8
    return (
        _HEADER.pack(0x1F, 0x8B, 8, 4, 0, 0, 0xFF, 6)
        + struct.pack("<2BHH", 0x42, 0x43, 2, bsize - 1)
        + cdata
        + struct.pack("<II", zlib.crc32(data), len(data))
    )


class BgzfWriter:
    """Streaming BGZF compressor; writes the EOF marker on close."""

    def __init__(self, fileobj: BinaryIO, level: int = 6):
        self._fh = fileobj
        self._level = level
        self._buf = bytearray()
        self._closed = False

    @classmethod
    def open(cls, path: str, level: int = 6) -> "BgzfWriter":
        return cls(open(path, "wb"), level=level)

    def write(self, data: bytes) -> None:
        # graftlint: disable=thread-unsafe-mutation -- writer objects are
        # thread-confined (one per writing thread); the shared-writer
        # variant is native MtWriter, covered by the TSan/ASan harnesses
        self._buf += data
        while len(self._buf) >= MAX_BLOCK_SIZE:
            self._flush_block(bytes(self._buf[:MAX_BLOCK_SIZE]))
            del self._buf[:MAX_BLOCK_SIZE]

    def _flush_block(self, data: bytes) -> None:
        self._fh.write(deflate_block(data, self._level))

    def flush(self) -> None:
        if self._buf:
            self._flush_block(bytes(self._buf))
            self._buf.clear()

    def close(self) -> None:
        if self._closed:
            return
        self.flush()
        self._fh.write(BGZF_EOF)
        self._fh.close()
        self._closed = True

    def __enter__(self) -> "BgzfWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def is_bgzf(path: str) -> bool:
    with open(path, "rb") as fh:
        head = fh.read(18)
    return (
        len(head) >= 18
        and head[0] == 0x1F
        and head[1] == 0x8B
        and head[3] & 4 != 0
        and head[12] == 0x42
        and head[13] == 0x43
    )


def iter_blocks(fileobj: BinaryIO) -> Iterator[bytes]:
    """Yield decompressed BGZF blocks (used by the parallel decoder)."""
    reader = BgzfReader(fileobj)
    while True:
        block = reader._read_block()
        if block is None:
            return
        yield block
