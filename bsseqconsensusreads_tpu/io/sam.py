"""SAM text format interop.

Needed where the reference pipes `bwameth … | samtools view -h -b`
(main.snake.py:93,188): bwameth emits SAM on stdout; this module converts the
text stream to BamRecords (and back, for debugging/interop).
"""

from __future__ import annotations

from typing import Iterable, Iterator, TextIO

from bsseqconsensusreads_tpu.io.bam import (
    BamHeader,
    BamRecord,
    CIGAR_OPS,
)

_OP_OF = {c: i for i, c in enumerate(CIGAR_OPS)}
_TAG_CAST = {"i": int, "f": float, "A": str, "Z": str, "H": str}
_B_CAST = {"c": int, "C": int, "s": int, "S": int, "i": int, "I": int, "f": float}


def parse_cigar(text: str) -> list[tuple[int, int]]:
    if text == "*":
        return []
    out = []
    n = 0
    for ch in text:
        if ch.isdigit():
            n = n * 10 + ord(ch) - 48
        else:
            out.append((_OP_OF[ch], n))
            n = 0
    return out


def _parse_tag(field: str) -> tuple[str, tuple]:
    key, tc, val = field.split(":", 2)
    if tc == "B":
        sub = val[0]
        vals = [_B_CAST[sub](v) for v in val[1:].split(",") if v]
        return key, ("B", (sub, vals))
    return key, (tc, _TAG_CAST[tc](val))


def parse_sam_line(line: str, header: BamHeader) -> BamRecord:
    f = line.rstrip("\n").split("\t")
    qname, flag, rname, pos, mapq, cigar, rnext, pnext, tlen, seq, qual = f[:11]
    rec = BamRecord(
        qname=qname,
        flag=int(flag),
        ref_id=header.ref_id(rname) if rname != "*" else -1,
        pos=int(pos) - 1,
        mapq=int(mapq),
        cigar=parse_cigar(cigar),
        next_ref_id=(
            header.ref_id(rnext)
            if rnext not in ("*", "=")
            else (header.ref_id(rname) if rnext == "=" else -1)
        ),
        next_pos=int(pnext) - 1,
        tlen=int(tlen),
        seq="" if seq == "*" else seq,
        qual=None if qual == "*" else bytes(ord(c) - 33 for c in qual),
    )
    for field in f[11:]:
        key, tv = _parse_tag(field)
        rec.tags[key] = tv
    return rec


def read_sam(stream: TextIO) -> tuple[BamHeader, Iterator[BamRecord]]:
    """Parse a SAM text stream; returns (header, record iterator)."""
    header_lines: list[str] = []
    refs: list[tuple[str, int]] = []
    first_record: str | None = None
    for line in stream:
        if line.startswith("@"):
            header_lines.append(line)
            if line.startswith("@SQ"):
                name, ln = "", 0
                for part in line.rstrip("\n").split("\t")[1:]:
                    if part.startswith("SN:"):
                        name = part[3:]
                    elif part.startswith("LN:"):
                        ln = int(part[3:])
                refs.append((name, ln))
        else:
            first_record = line
            break
    header = BamHeader("".join(header_lines), refs)

    def records() -> Iterator[BamRecord]:
        if first_record is not None and first_record.strip():
            yield parse_sam_line(first_record, header)
        for line in stream:
            if line.strip():
                yield parse_sam_line(line, header)

    return header, records()


def format_sam_record(rec: BamRecord, header: BamHeader) -> str:
    qual = "*" if rec.qual is None else "".join(chr(min(q, 93) + 33) for q in rec.qual)
    cigar = rec.cigar_string()
    fields = [
        rec.qname,
        str(rec.flag),
        header.ref_name(rec.ref_id),
        str(rec.pos + 1),
        str(rec.mapq),
        cigar,
        header.ref_name(rec.next_ref_id) if rec.next_ref_id != rec.ref_id or rec.ref_id < 0 else "=",
        str(rec.next_pos + 1),
        str(rec.tlen),
        rec.seq or "*",
        qual,
    ]
    for key, (tc, val) in rec.tags.items():
        if tc == "B":
            sub, vals = val
            fields.append(f"{key}:B:{sub}," + ",".join(str(v) for v in vals))
        else:
            fields.append(f"{key}:{tc}:{val}")
    return "\t".join(fields)


def write_sam(records: Iterable[BamRecord], header: BamHeader, stream: TextIO) -> None:
    if header.text:
        stream.write(header.text if header.text.endswith("\n") else header.text + "\n")
    for name, length in header.references:
        if f"SN:{name}" not in header.text:
            stream.write(f"@SQ\tSN:{name}\tLN:{length}\n")
    for rec in records:
        stream.write(format_sam_record(rec, header) + "\n")
