"""Pallas TPU kernel for the consensus column vote.

The vote (models/molecular.py column_vote) is the framework's hot op: for
every window column, reduce [reads] observations into per-candidate-base
log-likelihood sums, pick the argmax, and convert its posterior into a Phred
quality (fgbio error-model semantics; reference flag surface at
main.snake.py:54,163). The stock XLA lowering materializes a one-hot
[reads, W, 4] float32 tensor per family; this kernel instead streams read
chunks HBM->VMEM and keeps only the [4, W] accumulators resident, fusing the
whole reduction + finalize into one pass:

  grid = (G/GB, T/TC)        G = independent vote groups (family x role),
                             T = reads axis, W = window columns
  per step: load [GB, TC, W] bases+quals, accumulate
    ll[GB, 4, W]  += quality-weighted log-likelihood partials
    cnt[GB, 4, W] += per-base observation counts
  epilogue (last T chunk): argmax/softmax finalize, errors = depth - cnt[cons]

The count trick makes the disagreement tally (models/molecular.count_errors)
a free epilogue lookup instead of a second pass over the reads axis.

Numerics are the exact jnp expressions of ops/phred.py; results match the
XLA kernel exactly on every column whose argmax is unambiguous. On exact-tie
columns (two candidate bases with equal log-likelihood — equal posterior, so
either pick is correct) summation-order ulps may break the tie differently;
tests/test_pallas.py compares tie-aware. The kernel is selected via
pipeline.calling's vote_kernel argument or BSSEQ_TPU_VOTE_KERNEL=pallas|xla.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bsseqconsensusreads_tpu.alphabet import NBASE, NUM_BASES
from bsseqconsensusreads_tpu.models.molecular import ARGMAX_TIE_TOL
from bsseqconsensusreads_tpu.models.params import ConsensusParams
from bsseqconsensusreads_tpu.ops import phred

GB = 8  # vote groups per grid step (f32 sublane tile)
TC = 128  # max reads per chunk streamed through VMEM
WC = 512  # max window columns per grid step (VMEM: 8*128*512*4 B = 2 MB/block)


def _vote_kernel(bases_ref, quals_ref, base_out, qual_out, depth_out, err_out,
                 ll_acc, cnt_acc, *, params: ConsensusParams, num_t: int):
    """Grid step (i, j, t): accumulate group block i / column tile j's read
    chunk t (t is the innermost grid axis, so the scratch accumulators belong
    to one (i, j) tile at a time).

    All vector ops are 2D [TC, W] / [4, W] / [1, W] — Mosaic's layout engine
    rejects 3D i1 relayouts and >2D gathers, so the group dim is a static
    python unroll and the argmax lookups are 4-way selects.
    Scratch rows g*4+b hold group g's accumulator for candidate base b.
    """
    t = pl.program_id(2)

    @pl.when(t == 0)
    def _init():
        ll_acc[:] = jnp.zeros_like(ll_acc)
        cnt_acc[:] = jnp.zeros_like(cnt_acc)

    for g in range(GB):
        # Widen bases to f32 at load: the VPU has no i8 vector compare, and
        # base codes 0..4 are exact in f32.
        bases = bases_ref[g].astype(jnp.float32)  # [TC, W]
        quals = quals_ref[g]  # [TC, W] f32
        # Mask-free accumulate: Mosaic's layout engine rejects relayouts of
        # full-size i1 vectors, so masks become exact {0,1} f32 indicator
        # products (x*1 and x*0 are exact; log terms are finite after the
        # phred clip, so 0*log never produces nan).
        w_obs = (bases != float(NBASE)).astype(jnp.float32) * (
            quals >= params.min_input_base_quality
        ).astype(jnp.float32)
        p_err = phred.adjust_quals_post_umi(quals, params.error_rate_post_umi)
        log_ok, log_err = phred.log_likelihoods(p_err)
        # Factor the candidate-independent miss term out of the 4-way loop:
        #   LL(b) = sum w*(hit_b*log_ok + (1-hit_b)*log_err)
        #         = sum w*hit_b*(log_ok-log_err)  +  sum w*log_err
        # The shared sum is computed once per chunk instead of four times —
        # same float adds in the same order per term, so numerics match the
        # unfactored form up to the usual summation-order ulps the tie
        # comparison already absorbs.
        log_diff = (log_ok - log_err) * w_obs
        shared = jnp.sum(log_err * w_obs, axis=0, keepdims=True)  # [1, W]
        for b in range(NUM_BASES):
            hit = (bases == float(b)).astype(jnp.float32)
            row = slice(g * NUM_BASES + b, g * NUM_BASES + b + 1)
            ll_acc[row, :] += jnp.sum(hit * log_diff, axis=0, keepdims=True) + shared
            cnt_acc[row, :] += jnp.sum(hit * w_obs, axis=0, keepdims=True)

    @pl.when(t == num_t - 1)
    def _finalize():
        for g in range(GB):
            rows = slice(g * NUM_BASES, (g + 1) * NUM_BASES)
            ll = ll_acc[rows, :]  # [4, W]
            cnt = cnt_acc[rows, :]  # [4, W] f32 (exact: counts < 2^24)
            depth = jnp.sum(cnt, axis=0, keepdims=True)  # [1, W]
            called = depth > 0
            # Tie-canonical argmax (models/molecular.vote_finalize): the
            # lowest base index within ARGMAX_TIE_TOL of the max wins,
            # so exact-tie columns call identically to the XLA kernel and
            # the fgbio-semantics oracle regardless of summation order.
            mx = jnp.max(ll, axis=0, keepdims=True)
            cons = jnp.argmax(
                ll >= mx - ARGMAX_TIE_TOL, axis=0, keepdims=True
            )  # [1, W]

            def pick(arr, idx):
                out = jnp.zeros_like(arr[0:1, :])
                for b in range(NUM_BASES):
                    out = jnp.where(idx == b, arr[b : b + 1, :], out)
                return out

            # posterior with the canonical ascending-order denominator
            # (models/molecular.vote_finalize): a 4-row sorting network
            # (5 compare-exchanges) keeps everything 2D [1, W] for Mosaic
            m = jnp.max(ll, axis=0, keepdims=True)
            e0, e1, e2, e3 = (
                jnp.exp(ll[b : b + 1, :] - m) for b in range(NUM_BASES)
            )
            a, b_ = jnp.minimum(e0, e1), jnp.maximum(e0, e1)
            c, d = jnp.minimum(e2, e3), jnp.maximum(e2, e3)
            a, c = jnp.minimum(a, c), jnp.maximum(a, c)
            b_, d = jnp.minimum(b_, d), jnp.maximum(b_, d)
            b_, c = jnp.minimum(b_, c), jnp.maximum(b_, c)
            denom = ((a + b_) + c) + d
            p_cons = 1.0 - 1.0 / denom
            p_final = phred.prob_error_two_trials(
                p_cons, phred.phred_to_prob(params.error_rate_pre_umi)
            )
            qual = phred.prob_to_phred(p_final)
            low = qual < params.min_consensus_base_quality
            keep = called & ~low
            cons = jnp.where(keep, cons, NBASE)
            qual = jnp.where(keep, qual, float(phred.NO_CALL_QUAL))
            agree = pick(cnt, cons)
            out_row = slice(g, g + 1)
            base_out[out_row, :] = cons.astype(jnp.int32)
            qual_out[out_row, :] = jnp.round(qual).astype(jnp.int32)
            depth_out[out_row, :] = depth.astype(jnp.int32)
            err_out[out_row, :] = jnp.where(
                cons != NBASE, depth - agree, 0.0
            ).astype(jnp.int32)


def _finalize_kernel(ll_ref, depth_ref, base_out, qual_out, *,
                     params: ConsensusParams):
    """Grid step (i, j): finalize group block i / column tile j from
    precomputed accumulators — the epilogue half of _vote_kernel, lifted
    out so the SEGMENT-PACKED layout can pair it with XLA's segment-sum
    partials (models.molecular.vote_partials_segments): the ragged
    reduction stays a dense XLA scatter-less segment sum, and the
    transcendental-heavy finalize runs here. Mirrors
    models.molecular.vote_finalize op for op (tie-canonical argmax, the
    5-comparator ascending network on ll - m BEFORE the exp, the exact
    1.0 top term), so the packed Pallas leg is bit-identical to the
    packed XLA leg."""
    for g in range(GB):
        ll = ll_ref[g]  # [4, wc]
        depth = depth_ref[g : g + 1, :]  # [1, wc] i32
        called = depth > 0
        mx = jnp.max(ll, axis=0, keepdims=True)
        cons = jnp.argmax(ll >= mx - ARGMAX_TIE_TOL, axis=0, keepdims=True)
        d0, d1, d2, d3 = (ll[b : b + 1, :] - mx for b in range(NUM_BASES))
        a, b_ = jnp.minimum(d0, d1), jnp.maximum(d0, d1)
        c, e = jnp.minimum(d2, d3), jnp.maximum(d2, d3)
        a, c = jnp.minimum(a, c), jnp.maximum(a, c)
        b_, e = jnp.minimum(b_, e), jnp.maximum(b_, e)
        b_, c = jnp.minimum(b_, c), jnp.maximum(b_, c)
        denom = ((jnp.exp(a) + jnp.exp(b_)) + jnp.exp(c)) + 1.0
        p_cons = 1.0 - 1.0 / denom
        p_final = phred.prob_error_two_trials(
            p_cons, phred.phred_to_prob(params.error_rate_pre_umi)
        )
        qual = phred.prob_to_phred(p_final)
        low = qual < params.min_consensus_base_quality
        keep = called & ~low
        cons = jnp.where(keep, cons, NBASE)
        qual = jnp.where(keep, qual, float(phred.NO_CALL_QUAL))
        out_row = slice(g, g + 1)
        base_out[out_row, :] = cons.astype(jnp.int32)
        qual_out[out_row, :] = jnp.round(qual).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("params", "interpret"))
def vote_finalize_groups(ll, depth, params: ConsensusParams,
                         interpret: bool | None = None):
    """Pallas finalize epilogue over precomputed vote accumulators.

    ll: float32 [..., W, 4] summed log-likelihoods, depth: int32 [..., W]
    observation counts — exactly vote_partials_segments' outputs. Returns
    (base int8 [..., W], qual uint8 [..., W]) matching
    models.molecular.vote_finalize bit for bit (same network, same tie
    band). Group/column tiles ride the same GB/WC blocking as the full
    vote kernel; interpret=None compiles on accelerators and interprets
    on the CPU test mesh. Padding tiles finalize garbage-free (ll 0 /
    depth 0 -> uncalled) and are sliced away.
    """
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    lead = ll.shape[:-2]
    w = ll.shape[-2]
    g = 1
    for n in lead:
        g *= n
    ll2 = ll.reshape(g, w, NUM_BASES).transpose(0, 2, 1)  # [G, 4, W]
    dep2 = depth.reshape(g, w)
    wc = min(WC, w)
    ll2 = _pad_to(_pad_to(ll2, 0, GB, 0.0), 2, wc, 0.0)
    dep2 = _pad_to(_pad_to(dep2, 0, GB, 0), 1, wc, 0)
    gp, _, wp = ll2.shape
    outs = pl.pallas_call(
        functools.partial(_finalize_kernel, params=params),
        grid=(gp // GB, wp // wc),
        in_specs=[
            pl.BlockSpec((GB, NUM_BASES, wc), lambda i, j: (i, 0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((GB, wc), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((GB, wc), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM)
        ] * 2,
        out_shape=[jax.ShapeDtypeStruct((gp, wp), jnp.int32)] * 2,
        interpret=interpret,
    )(ll2, dep2)
    base = outs[0][:g, :w].reshape(*lead, w).astype(jnp.int8)
    qual = outs[1][:g, :w].reshape(*lead, w).astype(jnp.uint8)
    return base, qual


def _pad_to(x, axis: int, mult: int, fill):
    pad = (-x.shape[axis]) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=fill)


@functools.partial(jax.jit, static_argnames=("params", "interpret"))
def column_vote_groups(bases, quals, params: ConsensusParams,
                       interpret: bool | None = None):
    """Pallas column vote over independent groups.

    bases: int8 [G, T, W] (NBASE = no observation), quals: float32 [G, T, W].
    Returns dict of [G, W] arrays matching models.molecular.column_vote:
    base (int8), qual (uint8), depth (int32), errors (int32).
    interpret=None compiles on accelerators (incl. the tunneled 'axon' TPU
    backend) and interprets on the CPU test mesh.
    """
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    g, t, w = bases.shape
    quals = quals.astype(jnp.float32)
    # Chunk sizes adapt to the input: shallow families (t=1-2, the cfDNA
    # common case) pad reads only to the 8-row sublane tile instead of a full
    # TC chunk, and wide windows tile the column axis so VMEM blocks stay
    # bounded (max_window=4096 would otherwise need 16 MB/block).
    tc = min(TC, max(8, -(-t // 8) * 8))
    wc = min(WC, w)
    bases = _pad_to(_pad_to(bases, 0, GB, NBASE), 1, tc, NBASE)
    quals = _pad_to(_pad_to(quals, 0, GB, 0.0), 1, tc, 0.0)
    bases = _pad_to(bases, 2, wc, NBASE)
    quals = _pad_to(quals, 2, wc, 0.0)
    gp, tp, wp = bases.shape
    num_t = tp // tc
    grid = (gp // GB, wp // wc, num_t)  # t innermost: accumulators are per (i, j)
    out_spec = pl.BlockSpec((GB, wc), lambda i, j, t_: (i, j),
                            memory_space=pltpu.VMEM)
    outs = pl.pallas_call(
        functools.partial(_vote_kernel, params=params, num_t=num_t),
        grid=grid,
        in_specs=[
            pl.BlockSpec((GB, tc, wc), lambda i, j, t_: (i, t_, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((GB, tc, wc), lambda i, j, t_: (i, t_, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[out_spec] * 4,
        out_shape=[jax.ShapeDtypeStruct((gp, wp), jnp.int32)] * 4,
        scratch_shapes=[
            pltpu.VMEM((GB * NUM_BASES, wc), jnp.float32),
            pltpu.VMEM((GB * NUM_BASES, wc), jnp.float32),
        ],
        interpret=interpret,
    )(bases, quals)
    base, qual, depth, errors = (o[:g, :w] for o in outs)
    return {
        "base": base.astype(jnp.int8),
        "qual": qual.astype(jnp.uint8),
        "depth": depth,
        "errors": errors,
    }


@functools.partial(jax.jit, static_argnames=("params", "interpret"))
def molecular_consensus_pallas(bases, quals,
                               params: ConsensusParams = ConsensusParams(),
                               interpret: bool | None = None):
    """Pallas-backed models.molecular.molecular_consensus.

    bases: int8 [F, T, 2, W], quals: uint8/f32 [F, T, 2, W]; returns the same
    narrowed dict of [F, 2, W] arrays. The R1/R2 overlap co-call stays in XLA
    (cheap elementwise); the reads-axis vote reduction runs in the kernel.
    """
    from bsseqconsensusreads_tpu.models.molecular import (
        narrow_outputs,
        overlap_cocall,
    )

    f, t, _, w = bases.shape
    quals = quals.astype(jnp.float32)
    if params.consensus_call_overlapping_bases:
        bases, quals = jax.vmap(
            lambda b, q: overlap_cocall(b, q)
        )(bases, quals)
    # [F, T, 2, W] -> [F*2 groups, T, W]: roles vote independently.
    gb = bases.transpose(0, 2, 1, 3).reshape(f * 2, t, w)
    gq = quals.transpose(0, 2, 1, 3).reshape(f * 2, t, w)
    out = column_vote_groups(gb, gq, params, interpret=interpret)
    out = {k: v.reshape(f, 2, w) for k, v in out.items()}
    return narrow_outputs(out)


@functools.partial(jax.jit, static_argnames=("params", "interpret"))
def duplex_consensus_pallas(bases, quals,
                            params: ConsensusParams = ConsensusParams(min_reads=0),
                            interpret: bool | None = None):
    """Pallas-backed models.duplex.duplex_consensus.

    bases: int8 [F, 4, W] (rows 99/163/83/147), quals uint8/f32 [F, 4, W];
    returns the same narrowed dict of [F, 2, W] arrays. The duplex merge is
    the molecular column vote at depth 2 (models/duplex.py _merge), so the
    same fused kernel serves: duplex R1 votes rows (99, 163), R2 votes
    (83, 147) — [F*2 groups, 2, W]. The per-strand depth planes (a_depth/
    b_depth) are cheap elementwise XLA, as in the reference kernel.
    """
    from bsseqconsensusreads_tpu.models.duplex import A_ROWS, R1_ROWS, R2_ROWS
    from bsseqconsensusreads_tpu.models.molecular import narrow_outputs

    f, r, w = bases.shape
    if r != 4:
        raise ValueError(f"duplex families have 4 rows, got {r}")
    quals = quals.astype(jnp.float32)
    rows = (R1_ROWS, R2_ROWS)
    gb = jnp.stack([bases[:, rr, :] for rr in rows], axis=1).reshape(f * 2, 2, w)
    gq = jnp.stack([quals[:, rr, :] for rr in rows], axis=1).reshape(f * 2, 2, w)
    out = column_vote_groups(gb, gq, params, interpret=interpret)
    out = {k: v.reshape(f, 2, w) for k, v in out.items()}
    strand = {}
    for role, rr in enumerate(rows):
        a_row, b_row = (rr[0], rr[1]) if rr[0] in A_ROWS else (rr[1], rr[0])
        cons = out["base"][:, role, :]
        for key, err, row in (
            ("a_depth", "a_err", a_row), ("b_depth", "b_err", b_row)
        ):
            obs = (
                (bases[:, row, :] != NBASE)
                & (quals[:, row, :] >= params.min_input_base_quality)
            )
            strand.setdefault(key, []).append(obs.astype(jnp.int32))
            strand.setdefault(err, []).append(
                (
                    obs & (cons != NBASE) & (bases[:, row, :] != cons)
                ).astype(jnp.int32)
            )
    for key, planes in strand.items():
        out[key] = jnp.stack(planes, axis=1)  # [F, 2, W]
    return narrow_outputs(out)
