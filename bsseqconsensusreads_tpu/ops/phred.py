"""Phred-scale probability arithmetic for the consensus error model.

The reference's consensus engines (fgbio CallMolecularConsensusReads /
CallDuplexConsensusReads, invoked at main.snake.py:54,163) parameterize their
error model with Phred-scaled rates: --error-rate-pre-umi=45 (errors in the
source molecule before UMI attachment) and --error-rate-post-umi=30 (errors
introduced between UMI attachment and sequencing, e.g. PCR). This module is
the same arithmetic as jit-friendly jnp ops.

All functions accept and return jnp arrays (float32) and are safe inside jit.
"""

from __future__ import annotations

import jax.numpy as jnp

# Phred bounds used for emitted qualities: htslib caps printable quals at 93
# ('~'); 2 ('#') is the conventional no-call / minimum quality.
MAX_PHRED = 93.0
MIN_PHRED = 2.0
NO_CALL_QUAL = 2

# Base alphabet re-exported from the single definition in alphabet.py.
from bsseqconsensusreads_tpu.alphabet import A, C, G, N, NUM_BASES, T  # noqa: F401,E402


def phred_to_prob(q):
    """Error probability for a Phred score: 10^(-q/10)."""
    return jnp.power(10.0, -jnp.asarray(q, jnp.float32) / 10.0)


def prob_to_phred(p, min_q: float = MIN_PHRED, max_q: float = MAX_PHRED):
    """Phred score for an error probability, clamped to [min_q, max_q]."""
    p = jnp.clip(jnp.asarray(p, jnp.float32), 1e-12, 1.0)
    return jnp.clip(-10.0 * jnp.log10(p), min_q, max_q)


def prob_error_two_trials(p1, p2):
    """Probability the final base is wrong after two independent error
    processes with per-trial error probabilities p1 then p2.

    Exactly one trial errs -> wrong; both err -> wrong unless the second error
    lands back on the original base (1/3 chance under a uniform substitution
    model): p1(1-p2) + (1-p1)p2 + (2/3)p1p2.
    """
    p1 = jnp.asarray(p1, jnp.float32)
    p2 = jnp.asarray(p2, jnp.float32)
    return p1 * (1.0 - p2) + (1.0 - p1) * p2 + (2.0 / 3.0) * p1 * p2


def adjust_quals_post_umi(quals, error_rate_post_umi):
    """Fold the post-UMI error prior into raw base qualities.

    Raw quality only models the sequencer; amplification errors after UMI
    attachment are an independent error process, so the effective per-base
    error is prob_error_two_trials(p_base, p_post).
    """
    p = phred_to_prob(quals)
    p_post = phred_to_prob(error_rate_post_umi)
    return prob_error_two_trials(p, p_post)


def log_likelihoods(p_err):
    """(log P[obs | true==obs], log P[obs | true!=obs]) per observation."""
    p_err = jnp.clip(p_err, 1e-12, 1.0 - 1e-7)
    return jnp.log1p(-p_err), jnp.log(p_err / 3.0)
