"""Banded intra-family alignment for indel-bearing reads (above-parity).

The reference simply DROPS any read whose CIGAR contains an insertion,
deletion, or hardclip (tools/1.convert_AG_to_CT.py:79-80,
tools/2.extend_gap.py:160-161) — those reads never contribute to consensus.
This op recovers them: a banded Needleman-Wunsch in window space aligns the
read against its family's anchor sequence (the per-column majority of the
directly-placed reads), so PCR-stutter/homopolymer indel reads add depth
instead of vanishing. Parity mode keeps the reference's drop behavior
(ops.encode indel_policy='drop', the default).

Design (TPU-first):
 * The DP is a jit/vmap'd lax.scan over read positions. Band coordinates
   d = col - (offset + i - 1) ∈ [-B, B]: a row's three moves become two
   vectorized shifts plus a cummax closure over the deletion chain —
   no data-dependent control flow, fixed [L, 2B+1] shapes.
 * Traceback is host-side numpy, vectorized over the read batch: indel
   reads are a small minority of real libraries, and the path walk is
   O(L + 2B) fancy-indexed steps regardless of batch size.
 * Scoring is bisulfite-aware: read T over anchor C and read A over anchor
   G are the expected conversion signals on the two strands, scored as
   neutral rather than mismatch.

Output is window-space (bases, quals, cover) rows ready to drop into the
family tensor: matched chars land on their column, inserted chars vanish
(no column), deleted columns stay uncovered — exactly the "no observation"
semantics the consensus vote (models.molecular) already has.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from bsseqconsensusreads_tpu.alphabet import A, C, G, NBASE, T

NEG = -1e9  # effectively -inf for f32 score cells


@functools.partial(
    jax.jit, static_argnames=("band", "match", "mismatch", "gap", "bs_neutral")
)
def banded_scores(reads, ref, offsets, band: int = 8,
                  match: float = 4.0, mismatch: float = -6.0,
                  gap: float = -8.0, bs_neutral: float = 1.0):
    """Banded NW score matrices.

    reads: int8 [N, L] (NBASE-padded), ref: int8 [N, W] window anchor codes
    (NBASE = uncovered column), offsets: int32 [N] expected window column of
    each read's first char. Returns M float32 [N, L+1, 2B+1]:
    M[n, i, d] = best score of consuming i read chars with char i at window
    column offsets[n] + i - 1 + (d - B). Padded chars (NBASE) keep rows
    constant so one scan serves mixed lengths.
    """
    n, l = reads.shape
    w = ref.shape[-1]
    width = 2 * band + 1
    ds = jnp.arange(width) - band  # [width]

    def sub_score(x, r):
        """Score of read char x over anchor char r (both int8)."""
        is_n = (x == NBASE) | (r == NBASE)
        bs = ((x == T) & (r == C)) | ((x == A) & (r == G))
        return jnp.where(
            is_n, 0.0,
            jnp.where(x == r, match, jnp.where(bs, bs_neutral, mismatch)),
        )

    def row(prev, xi_i):
        xi, i = xi_i  # [N] char codes, scalar position (1-based)
        cols = offsets[:, None] + (i - 1) + ds[None, :]  # [N, width]
        in_win = (cols >= 0) & (cols < w)
        ref_d = jnp.take_along_axis(
            ref, jnp.clip(cols, 0, w - 1), axis=-1
        )  # [N, width]
        diag = prev + jnp.where(in_win, sub_score(xi[:, None], ref_d), NEG)
        up = (
            jnp.concatenate([prev[:, 1:], jnp.full((n, 1), NEG)], axis=-1) + gap
        )
        pre = jnp.maximum(diag, up)
        # deletion-chain closure: M[d] = max_{k<=d} pre[k] + gap*(d-k)
        shifted = jax.lax.cummax(pre - gap * ds[None, :], axis=1)
        closed = shifted + gap * ds[None, :]
        # padded chars: carry the previous row through unchanged
        out = jnp.where((xi == NBASE)[:, None], prev, closed)
        return out, out

    init = gap * jnp.abs(ds)[None, :].repeat(n, axis=0)  # net start shift
    _, rows = jax.lax.scan(
        row, init, (reads.T.astype(jnp.int8), jnp.arange(1, l + 1))
    )
    return jnp.concatenate([init[None], rows], axis=0).transpose(1, 0, 2)


def _sub_np(x, r, match, mismatch, bs_neutral):
    is_n = (x == NBASE) | (r == NBASE)
    bs = ((x == T) & (r == C)) | ((x == A) & (r == G))
    return np.where(
        is_n, 0.0, np.where(x == r, match, np.where(bs, bs_neutral, mismatch))
    )


def banded_align(reads, quals, ref, offsets, band: int = 8,
                 match: float = 4.0, mismatch: float = -6.0,
                 gap: float = -8.0, bs_neutral: float = 1.0,
                 min_score_per_base: float = 0.0):
    """Align indel reads into window space.

    reads int8 [N, L] (NBASE-padded), quals uint8 [N, L], ref int8 [N, W]
    anchors, offsets int32 [N]. Returns (bases int8 [N, W], quals uint8
    [N, W], ok bool [N]): window rows with aligned chars on their columns
    (NBASE elsewhere), and ok=False for reads whose best banded score is
    below min_score_per_base * length (unalignable within the band — caller
    keeps the drop behavior for those).

    The DP runs on device (banded_scores); the traceback walks the score
    matrix host-side, vectorized over the batch.
    """
    reads = np.asarray(reads, dtype=np.int8)
    quals = np.asarray(quals, dtype=np.uint8)
    ref = np.asarray(ref, dtype=np.int8)
    offsets = np.asarray(offsets, dtype=np.int32)
    n, l = reads.shape
    w = ref.shape[-1]
    width = 2 * band + 1
    m = np.asarray(
        banded_scores(reads, ref, offsets, band, match, mismatch, gap, bs_neutral)
    )  # [N, L+1, width]

    lens = (reads != NBASE).sum(axis=-1)
    out_b = np.full((n, w), NBASE, dtype=np.int8)
    out_q = np.zeros((n, w), dtype=np.uint8)
    # NBASE chars (pad AND mid-read Ns) carry the scan row through unchanged,
    # so the last row is every read's final row: start traceback at i=l.
    best_d = np.argmax(m[:, l], axis=-1)
    best = m[np.arange(n), l, best_d]
    ok = best >= min_score_per_base * np.maximum(lens, 1)

    i = np.full(n, l)  # current read position (1-based char index)
    d = best_d.astype(np.int64)
    active = ok.copy()
    rows = np.arange(n)
    ds = np.arange(width) - band
    eps = 1e-4
    for _ in range(2 * l + 2 * width + 4):
        if not active.any():
            break
        cur = m[rows, i, d]
        cols = offsets + (i - 1) + ds[d]
        xi = np.take_along_axis(reads, np.maximum(i - 1, 0)[:, None], 1)[:, 0]
        # NBASE char rows were carried through: step i without moving d
        is_pad = (i > 0) & (xi == NBASE)
        in_win = (cols >= 0) & (cols < w)
        ref_d = ref[rows, np.clip(cols, 0, w - 1)]
        diag = np.where(
            (i > 0) & in_win,
            m[rows, np.maximum(i - 1, 0), d]
            + _sub_np(xi, ref_d, match, mismatch, bs_neutral),
            NEG,
        )
        up = np.where(
            (i > 0) & (d + 1 < width), m[rows, np.maximum(i - 1, 0), np.minimum(d + 1, width - 1)] + gap, NEG
        )
        left = np.where(d > 0, m[rows, i, np.maximum(d - 1, 0)] + gap, NEG)

        # Move priority on exact score ties: left (deletion) > diag > up.
        # Within a repeat run (e.g. an AA dinucleotide) every gap placement
        # scores identically. Walking BACKWARDS, taking the deletion move
        # first pins the gap at the rightmost tied column — a fixed,
        # deterministic convention — whereas diag-first drifts the gap one
        # column left per tie, parking the preceding base on a column it was
        # not observed at (the depth-misplacement bug this ordering fixes:
        # a 19M 1D 20M read lost its base adjacent to the deletion).
        take_pad = active & is_pad
        take_left = active & ~is_pad & (np.abs(left - cur) <= eps)
        take_diag = active & ~is_pad & ~take_left & (np.abs(diag - cur) <= eps)
        take_up = (
            active & ~is_pad & ~take_left & ~take_diag
            & (np.abs(up - cur) <= eps)
        )
        # No move matches the cell score (numerical drift / invalid band
        # edge): deactivate and mark the read unaligned rather than spinning
        # to the iteration cap with a partially placed row still ok=True.
        no_move = active & ~is_pad & ~take_left & ~take_diag & ~take_up
        ok[no_move] = False
        out_b[no_move] = NBASE
        out_q[no_move] = 0
        active = active & ~no_move

        # diag: char i-1 (0-based) sits at column cols
        place = take_diag & in_win
        out_b[rows[place], cols[place]] = np.take_along_axis(
            reads, (i - 1)[:, None], 1
        )[:, 0][place]
        out_q[rows[place], cols[place]] = np.take_along_axis(
            quals, (i - 1)[:, None], 1
        )[:, 0][place]

        i = np.where(take_pad | take_diag | take_up, np.maximum(i - 1, 0), i)
        d = np.where(take_up, np.minimum(d + 1, width - 1), d)
        d = np.where(take_left, np.maximum(d - 1, 0), d)
        active = active & (i > 0)

    cover = out_b != NBASE
    out_b[~cover] = NBASE
    return out_b, out_q, ok
