"""Host-side reconstruction of duplex consensus quals from the b0 wire.

The tunnel's D2H direction is the duplex stage's measured bottleneck
(BENCH wire metrics; ~20-30 MB/s through the compressing tunnel). The
round-3 wire shipped 2 bytes per output column per role — a b0 call byte
plus the consensus qual byte. But the qual byte is REDUNDANT: a duplex
column merges at most two strand observations, and its consensus quality
is a deterministic function of

  (the two observation quals, which strand(s) were observed, whether each
   agreed with the called base)

— everything after the first item is in the b0 byte, and the observation
quals are the host's OWN input quals evolved through the convert/extend
edge ops (whose la/rd decisions also ride the wire). So the round-4 wire
ships b0 only (models.duplex.pack_duplex_b0_outputs, half the D2H bytes)
and this module rebuilds the qual plane exactly:

* evolve_duplex_quals — a vectorized numpy mirror of the EDGE effects of
  ops.convert + ops.extend on (cover, quals): the conversion prepend
  (qual 40 'I'), the trailing-C trim, and the extend-gap boundary copies.
  Window-space makes this cheap: neither op shifts interior columns, so
  the evolution is a handful of per-row index updates, not a re-run of
  the transforms. The base rewrites don't matter here — only quals and
  coverage feed the vote's quality arithmetic.
* qual_tables — three lookup tables (single / agree / disagree, indexed
  by the uint8 observation quals in A-then-B strand order) built by
  running the PRODUCTION vote kernel itself over every (qa, qb) pair
  once per (params, vote_kernel) and caching the fetched results. The
  tables are exact by construction: every reconstructed value was
  computed by the same kernel + backend that produced the batch, so
  kernel-specific rounding (XLA vs Pallas) is captured, not modeled.
* reconstruct_duplex_quals — the per-batch lookup pass.

The reference has no analog (its quals are computed where its records
are, on the host); this is the TPU design's answer to a link that is
~10^3 slower than HBM: ship decisions, not derivable bytes.
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np

from bsseqconsensusreads_tpu.alphabet import NBASE
from bsseqconsensusreads_tpu.models.duplex import ROLE_STRAND_ROWS
from bsseqconsensusreads_tpu.models.params import ConsensusParams
from bsseqconsensusreads_tpu.ops.convert import PREPEND_QUAL
from bsseqconsensusreads_tpu.ops.phred import NO_CALL_QUAL


def evolve_duplex_quals(cover, quals, la, rd, eligible=None):
    """Observation quals/coverage after convert+extend edge effects.

    cover: bool [f, 4, w] (pre-transform), quals: [f, 4, w] integer-valued,
    la/rd: int8 [f, 4] as returned over the wire (la/rd are nonzero only
    on rows where ops.convert acted, so no convert_mask is needed),
    eligible: bool [f] extend gate (None = all eligible).

    Returns (quals uint8 [f, 4, w], cover bool [f, 4, w]) matching the
    device arrays entering the duplex merge, exactly:
      * la row: gains its column one left of its first covered column,
        qual 40 (ops/convert.py PREPEND_QUAL);
      * rd row: loses its last covered column;
      * extend pairs (163->99, 83->147): la copies the left row's first
        column into the right row; rd copies the right row's last column
        into the left row (same column, cross-row — window space never
        shifts interiors). Gates mirror ops.extend.extend_gap.
    """
    f, r, w = cover.shape
    cov = np.asarray(cover).copy()
    q = np.asarray(quals).astype(np.uint8).copy()

    # conversion prepend (la == 1 implies first > 0 by construction)
    fam, row = np.nonzero(np.asarray(la) == 1)
    if fam.size:
        first = cov[fam, row].argmax(-1)
        q[fam, row, first - 1] = int(PREPEND_QUAL)
        cov[fam, row, first - 1] = True
    # trailing trim (prepend only changes the left edge, so the row's last
    # covered column is the same before and after it)
    fam, row = np.nonzero(np.asarray(rd) == 1)
    if fam.size:
        last = w - 1 - cov[fam, row, ::-1].argmax(-1)
        cov[fam, row, last] = False

    # extend-gap boundary copies (ops/extend.py PAIRS, post-convert state)
    has = cov.any(-1)
    first = cov.argmax(-1)
    last = w - 1 - cov[..., ::-1].argmax(-1)
    la = np.asarray(la)
    rd = np.asarray(rd)
    for left, right in ((1, 0), (2, 3)):
        both = has[:, left] & has[:, right]
        if eligible is not None:
            both = both & np.asarray(eligible)
        idx = np.nonzero(both & (la[:, left] == 1))[0]
        if idx.size:
            c = first[idx, left]
            q[idx, right, c] = q[idx, left, c]
            cov[idx, right, c] = True
        idx = np.nonzero(both & (rd[:, left] == 1))[0]
        if idx.size:
            c = last[idx, right]
            q[idx, left, c] = q[idx, right, c]
            cov[idx, left, c] = True
    return q, cov


@lru_cache(maxsize=16)
def _qual_tables_cached(params: ConsensusParams, vote_kernel: str):
    """(T_single [256], T_agree [256, 256], T_disagree [256, 256],
    T_single_masked bool [256], T_single_flip bool [256]) — quals uint8.

    Built by the production duplex vote itself: one [256, 4, 520] batch
    whose role-0 columns enumerate every case — family index = the
    A-strand qual, columns 0-255 = agreeing pair vs B qual, 256-511 =
    disagreeing pair, 512 = A-strand singleton. The two bool tables
    carry the kernel's base verdict for a lone observation, which the
    singleton host fast path (models.molecular.singleton_consensus_host)
    must reproduce: T_single_masked = call masked to N
    (min_consensus_base_quality); T_single_flip = the log-likelihood
    argmax FLIPPED away from the observed base (post-UMI error
    probability > 0.75, i.e. raw quals 0-1 under the production error
    model — the call becomes the lowest-index other base and the column
    counts one error). One small device call per (params, kernel),
    cached for the session.
    """
    import jax.numpy as jnp

    n = 256
    w = 520  # 256 agree + 256 disagree + 1 single, padded even
    bases = np.full((n, 4, w), NBASE, dtype=np.int8)
    quals = np.zeros((n, 4, w), dtype=np.float32)
    qa = np.arange(n, dtype=np.float32)[:, None]
    # row 0 = A strand (flag 99), row 1 = B strand (flag 163), role 0
    bases[:, 0, :513] = 0  # base A
    quals[:, 0, :513] = qa
    bases[:, 1, 0:256] = 0  # agree: B also base A
    bases[:, 1, 256:512] = 1  # disagree: B base C
    quals[:, 1, 0:512] = np.tile(np.arange(256, dtype=np.float32), 2)[None, :]

    if vote_kernel == "pallas":
        from bsseqconsensusreads_tpu.ops.pallas_vote import (
            duplex_consensus_pallas,
        )

        out = duplex_consensus_pallas(jnp.asarray(bases), jnp.asarray(quals),
                                      params)
    else:
        from bsseqconsensusreads_tpu.models.duplex import duplex_consensus

        out = duplex_consensus(jnp.asarray(bases), jnp.asarray(quals), params)
    # graftlint: disable=host-sync -- one-time table build (lru_cached by
    # caller): the sync happens once per params set at startup, not per batch
    qual = np.asarray(out["qual"])[:, 0, :]  # [256, w]
    base = np.asarray(out["base"])[:, 0, :]  # graftlint: disable=host-sync -- same one-time table build
    single_base = base[:, 512]  # observation was base A (0)
    return (
        np.ascontiguousarray(qual[:, 512].astype(np.uint8)),
        np.ascontiguousarray(qual[:, 0:256].astype(np.uint8)),
        np.ascontiguousarray(qual[:, 256:512].astype(np.uint8)),
        np.ascontiguousarray(single_base == NBASE),
        np.ascontiguousarray((single_base != NBASE) & (single_base != 0)),
    )


def qual_tables(params: ConsensusParams, vote_kernel: str = "xla"):
    return _qual_tables_cached(params, vote_kernel)


def retire_duplex_wire(host_wire, f: int, w: int, cover, quals, eligible,
                       params: ConsensusParams,
                       vote_kernel: str = "xla") -> dict:
    """Full host retire of the duplex b0 wire: split la/rd, decode the b0
    planes, and reconstruct the qual plane — in ONE native C pass when
    the library is built (io.wirepack.duplex_retire; the numpy route
    below is the reference and fallback). The numpy retire was the
    largest serial block of the on-chip stage wall (~0.8 s per 4k-family
    batch vs ~0.03 s native)."""
    from bsseqconsensusreads_tpu.io import wirepack
    from bsseqconsensusreads_tpu.ops.wire import unpack_lard

    wire = np.asarray(host_wire)
    b0_words = f * 2 * w // 4
    la, rd = unpack_lard(wire[b0_words:], f)
    if wirepack.available():
        t_single, t_agree, t_dis = qual_tables(params, vote_kernel)[:3]
        role_rows = np.asarray(
            [r for pair in ROLE_STRAND_ROWS for r in pair], np.int32
        )
        u8 = wire[:b0_words].view(np.uint8)
        out = wirepack.duplex_retire(
            u8, f, w, cover, quals, la, rd, eligible, role_rows,
            t_single, t_agree.reshape(-1), t_dis.reshape(-1),
        )
        out["la"], out["rd"] = la, rd
        return out
    from bsseqconsensusreads_tpu.models.duplex import unpack_duplex_b0_outputs

    out = unpack_duplex_b0_outputs(wire[:b0_words], f=f, w=w)
    out["la"], out["rd"] = la, rd
    evolved, _cov = evolve_duplex_quals(cover, quals, la, rd, eligible)
    out["qual"] = reconstruct_duplex_quals(out, evolved, params, vote_kernel)
    return out


def reconstruct_duplex_quals(out: dict, evolved_quals: np.ndarray,
                             params: ConsensusParams,
                             vote_kernel: str = "xla") -> np.ndarray:
    """Rebuild the consensus qual plane [f, 2, w] from the b0 fields.

    out: unpacked b0 dict (base/a_depth/b_depth/a_err/b_err [f, 2, w]);
    evolved_quals: uint8 [f, 4, w] from evolve_duplex_quals. Exact: every
    value comes from the qual_tables the production kernel filled.
    """
    t_single, t_agree, t_dis = qual_tables(params, vote_kernel)[:3]
    base = np.asarray(out["base"])
    f, _, w = base.shape
    qual = np.full((f, 2, w), NO_CALL_QUAL, np.uint8)
    for role, (a_row, b_row) in enumerate(ROLE_STRAND_ROWS):
        qa = evolved_quals[:, a_row, :]
        qb = evolved_quals[:, b_row, :]
        ap = np.asarray(out["a_depth"])[:, role, :] > 0
        bp = np.asarray(out["b_depth"])[:, role, :] > 0
        erred = (
            (np.asarray(out["a_err"])[:, role, :] > 0)
            | (np.asarray(out["b_err"])[:, role, :] > 0)
        )
        masked = base[:, role, :] == NBASE
        res = qual[:, role, :]
        m = ap & ~bp & ~masked
        res[m] = t_single[qa[m]]
        m = bp & ~ap & ~masked
        res[m] = t_single[qb[m]]
        both = ap & bp
        m = both & ~erred & ~masked
        res[m] = t_agree[qa[m], qb[m]]
        m = both & erred  # called base exists by construction (err bits
        # require cons != NBASE), so no ~masked needed
        res[m] = t_dis[qa[m], qb[m]]
        # remaining covered cells are masked calls (base == NBASE): the
        # kernel wrote NO_CALL_QUAL for every one of them — already filled
    return qual
