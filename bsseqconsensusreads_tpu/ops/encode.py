"""Tensorization: UMI-family records -> padded family tensors.

The reference's consensus engines walk per-read Python/JVM loops; the TPU
design instead packs each MI family into fixed-shape arrays laid out in
*genome window space* (offset = pos - window_start), so every downstream
transform (overlap co-call, consensus vote, AG->CT conversion, gap extension,
duplex merge) is a dense per-column tensor op.

Bucketed padding bounds pad waste across the 1-2-read cfDNA tail and deep
(>500 read) families (SURVEY.md §5.7): template counts round up to powers of
two and window lengths to multiples of WINDOW_GRAN (sized for wire bytes —
see the granularity note below).
"""

from __future__ import annotations

import dataclasses
from collections import defaultdict
from typing import Iterable, Sequence

import numpy as np

from bsseqconsensusreads_tpu.io.bam import (
    BamRecord,
    CHARD_CLIP,
    CINS,
    CDEL,
    CSOFT_CLIP,
    FREAD2,
    FREVERSE,
)

from bsseqconsensusreads_tpu.alphabet import BASE_CHAR, BASE_CODE, NBASE, NUM_BASES
from bsseqconsensusreads_tpu.utils.flags import CONVERT_FLAGS, GROUP_ORDER

# Padding granularities. Template counts bucket to powers of two. Window
# widths bucket to 32 columns: the wire format (ops.wire) ships exactly the
# bucketed width, and on the tunnel-bound hot path wire bytes cost far more
# than the VMEM lane padding XLA adds internally (a 153-col duplex window
# buckets to 160 on the wire; XLA pads the minor dim to 128-lane tiles on
# device either way).
LANE = 128
WINDOW_GRAN = 32
MAX_TEMPLATES_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)


def seq_to_codes(seq: str) -> np.ndarray:
    return BASE_CODE[np.frombuffer(seq.encode("ascii"), dtype=np.uint8)]


def codes_to_seq(codes: np.ndarray) -> str:
    return BASE_CHAR[np.clip(codes, 0, NBASE)].tobytes().decode("ascii")


def trim_softclips(rec: BamRecord) -> tuple[np.ndarray, np.ndarray, int] | None:
    """Return (codes, quals, pos) with soft clips removed, or None when the
    read must be dropped (indel or hardclip CIGAR ops — the reference drops
    these too: tools/1.convert_AG_to_CT.py:79-80, tools/2.extend_gap.py:160).
    """
    trimmed = trim_softclips_keep_indels(rec)
    if trimmed is None or trimmed[3]:
        return None
    return trimmed[:3]


def trim_softclips_keep_indels(
    rec: BamRecord,
) -> tuple[np.ndarray, np.ndarray, int, bool] | None:
    """Like trim_softclips but indel reads survive: returns (codes, quals,
    pos, has_indel). Hardclipped reads still return None (their bases are
    physically absent from the record). Used by indel_policy='align'
    (ops.banded — above-parity recovery of reads the reference drops)."""
    # columnar ingest fast path (pipeline.ingest.ColumnarRecordView): the C
    # parser pre-digested the CIGAR (clips/indel/hardclip) and the base
    # codes/quals are buffer views — no cigar list, no string round-trip
    info = getattr(rec, "clip_info", None)
    if info is not None:
        start, rclip, has_indel, has_hard = info
        if has_hard:
            return None
        codes, quals = rec.codes_quals
        end = len(codes) - rclip
        return codes[start:end], quals[start:end], rec.pos, has_indel
    cigar = rec.cigar
    if any(op == CHARD_CLIP for op, _ in cigar):
        return None
    has_indel = any(op in (CINS, CDEL) for op, _ in cigar)
    precoded = getattr(rec, "codes_quals", None)
    if precoded is not None:
        codes, quals = precoded
    else:
        codes = seq_to_codes(rec.seq)
        quals = (
            np.frombuffer(rec.qual, dtype=np.uint8)
            if rec.qual is not None
            else np.zeros(len(rec.seq), dtype=np.uint8)
        )
    start, end = 0, len(codes)
    if cigar and cigar[0][0] == CSOFT_CLIP:
        start = cigar[0][1]
    if cigar and cigar[-1][0] == CSOFT_CLIP:
        end -= cigar[-1][1]
    return codes[start:end], quals[start:end], rec.pos, has_indel


@dataclasses.dataclass
class FamilyMeta:
    """Host-side metadata for one encoded family (one MI group, one strand)."""

    mi: str
    ref_id: int
    window_start: int
    n_templates: int
    rx: str = ""
    #: majority mapped-orientation per role (R1, R2): True = reverse strand.
    #: Needed to emit unaligned consensus in sequencing orientation.
    role_reverse: tuple = (False, True)


@dataclasses.dataclass
class MolecularBatch:
    """[F, T, 2, W] family tensors for the molecular consensus kernel.

    bases==4 marks "no observation" (pad, N, or no coverage); role axis is
    (R1, R2). All arrays are numpy; the kernel takes them as device arrays.
    """

    bases: np.ndarray  # int8 [F, T, 2, W]
    quals: np.ndarray  # uint8 [F, T, 2, W]
    meta: list[FamilyMeta]
    #: indel_policy='align' accounting: reads recovered by the banded
    #: aligner / reads it refused (unalignable within the band or no anchor)
    indel_aligned: int = 0
    indel_dropped: int = 0
    #: segment-packed twin (pack_molecular_rows), filled by the encode phase
    #: when the packed kernel layout is active; None under layout=padded
    packed: "PackedRows | None" = None
    #: mesh-sharded split of `packed` (shard_packed_rows), filled by the
    #: encode phase when the packed layout dispatches on a sharded mesh;
    #: None on single-device / wire routes and under layout=padded
    packed_shards: "ShardedPackedRows | None" = None

    @property
    def shape(self) -> tuple[int, int, int]:
        f, t, _, w = self.bases.shape
        return f, t, w


@dataclasses.dataclass
class PackedRows:
    """Segment-packed twin of a MolecularBatch: every real template's read
    pair concatenated on one dense row axis, plus the per-row family id.

    The padding envelope is gone — a 70%-singleton mixture that padded to
    T=4 issues 4x the data FLOPs in [F, T, 2, W] form but exactly N rows
    here. Rows are sorted by family (seg ascending), so the kernel's
    segment-sum adds in the same order as the padded vmap+sum and stays
    bit-identical. Row count and family count are both padded to power-of-
    two buckets (compile count stays bounded by the bucket grid, riding the
    persistent compile cache): pad rows carry no observation (bases NBASE,
    quals 0) and the sentinel family id `num_families`, whose garbage
    segment the kernel slices away.
    """

    bases: np.ndarray  # int8 [N, 2, W], N power-of-two bucketed
    quals: np.ndarray  # uint8 [N, 2, W]
    seg: np.ndarray  # int32 [N] ascending family ids; pad rows = num_families
    num_families: int  # pow2-bucketed family count the kernel is called with
    n_real_rows: int  # rows carrying data (before the row-bucket pad)


#: Row-bucket floor: batches below this pad up to one shared tiny shape, so
#: straggler flushes don't each mint a compile. Kept small — a production
#: batch is hundreds-to-thousands of rows, and a large floor would inflate
#: tail batches' issued cells for no compile saving (pow2 bucketing already
#: bounds the shape count below the floor).
MIN_PACKED_ROWS = 16


def bucket_pow2(n: int, floor: int = 1) -> int:
    """Smallest power of two >= max(n, floor)."""
    n = max(n, floor, 1)
    return 1 << (n - 1).bit_length()


def pack_molecular_rows(batch: "MolecularBatch") -> PackedRows | None:
    """Build the segment-packed view of an encoded molecular batch.

    Both encoders (python and native fill) place each family's real
    templates in slots [0, n_templates), so the pack is a boolean-mask
    gather — no per-family Python loop. Returns None for an empty batch
    (nothing to dispatch).
    """
    f, t, _, w = batch.bases.shape
    if f == 0:
        return None
    n_tpl = np.fromiter((m.n_templates for m in batch.meta), np.int32, f)
    keep = np.arange(t, dtype=np.int32)[None, :] < n_tpl[:, None]  # [F, T]
    rows_b = batch.bases[keep]  # [N, 2, W]
    rows_q = batch.quals[keep]
    seg = np.repeat(np.arange(f, dtype=np.int32), n_tpl)
    n = int(rows_b.shape[0])
    f_pad = bucket_pow2(f)
    n_pad = bucket_pow2(n, MIN_PACKED_ROWS)
    if n_pad > n:
        fill = n_pad - n
        rows_b = np.concatenate(
            [rows_b, np.full((fill, 2, w), NBASE, np.int8)]
        )
        rows_q = np.concatenate([rows_q, np.zeros((fill, 2, w), np.uint8)])
        seg = np.concatenate([seg, np.full(fill, f_pad, np.int32)])
    else:
        seg = seg.copy()
    # real-family ids stay < f <= f_pad; only pad rows use the sentinel
    return PackedRows(rows_b, rows_q, seg, f_pad, n)


@dataclasses.dataclass
class ShardedPackedRows:
    """A PackedRows plan split across mesh devices at FAMILY boundaries.

    Shard s owns the contiguous family range [s * fams_per_shard,
    (s + 1) * fams_per_shard); because PackedRows.seg is ascending, each
    shard's rows are one contiguous slice of the packed row axis — no
    family ever straddles a device split, so every shard runs the plain
    single-device segment-sum on LOCAL family ids with zero collectives
    and the reduction stays bit-identical to the unsharded pack. Shards
    share one row bucket (the pow2 ceiling of the fullest shard): uneven
    shards pad with sentinel rows exactly like the single-device pack.
    """

    bases: np.ndarray  # int8 [S, R, 2, W]
    quals: np.ndarray  # uint8 [S, R, 2, W]
    seg: np.ndarray  # int32 [S, R] LOCAL ids; pad rows = fams_per_shard
    fams_per_shard: int  # families each shard votes (pow2-bucket / S, ceil)
    n_shards: int
    total_families: int  # n_shards * fams_per_shard — what the fetch trims
    n_real_rows: int  # rows carrying data across all shards


def shard_packed_rows(packed: PackedRows, n_shards: int) -> ShardedPackedRows:
    """Split a packed plan across `n_shards` devices at family boundaries.

    Row ranges come from one searchsorted over the ascending seg ids; the
    original plan's trailing sentinel rows are dropped and each shard
    re-pads to the shared row bucket. Local ids are global ids minus the
    shard's family offset, so concatenating the per-shard outputs
    family-major reproduces the single-device output order exactly.
    """
    n = packed.n_real_rows
    seg = packed.seg[:n]
    _, _, w = packed.bases.shape
    fs = -(-packed.num_families // n_shards)  # ceil: every family owned once
    cuts = np.searchsorted(
        seg, np.arange(n_shards + 1, dtype=np.int64) * fs, side="left"
    )
    widest = int(np.max(cuts[1:] - cuts[:-1])) if n else 0
    r = bucket_pow2(widest, MIN_PACKED_ROWS)
    # graftlint: disable=padded-batch-flops -- this IS the packed plan:
    # the row axis is dense reads (bucket-rounded), not a template envelope
    bases = np.full((n_shards, r, 2, w), NBASE, np.int8)
    # graftlint: disable=padded-batch-flops -- same packed-plan allocation
    quals = np.zeros((n_shards, r, 2, w), np.uint8)
    seg_out = np.full((n_shards, r), fs, np.int32)
    for s in range(n_shards):
        i, j = int(cuts[s]), int(cuts[s + 1])
        bases[s, : j - i] = packed.bases[i:j]
        quals[s, : j - i] = packed.quals[i:j]
        seg_out[s, : j - i] = seg[i:j] - s * fs
    return ShardedPackedRows(
        bases, quals, seg_out, fs, n_shards, fs * n_shards, n
    )


def _round_up(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def bucket_templates(t: int) -> int:
    for b in MAX_TEMPLATES_BUCKETS:
        if t <= b:
            return b
    return _round_up(t, 1024)


def bucket_window(w: int) -> int:
    return max(WINDOW_GRAN, _round_up(w, WINDOW_GRAN))


#: Families deeper than this are skipped AND reported (never silent):
#: keeps counts inside the int16 transport dtypes (narrow_outputs) with a
#: wide margin; real UMI families this deep are degenerate-UMI artifacts.
MAX_TEMPLATES = 4096


#: band half-width for indel_policy='align'; also the extra window margin
#: reserved for deletions pushing an indel read's reference span past its
#: query length.
INDEL_BAND = 8


def scan_matches(group, policy: str) -> bool:
    """True when `group` is a pipeline.ingest.FamilyRun carrying a C encode
    digest computed under `policy` — the single gate for every native fast
    path (the bucketed batcher, the deep-family splitter, and the encoders
    must classify a group identically or families silently fall onto the
    per-record path)."""
    return (
        getattr(group, "scan", None) is not None
        and getattr(group, "scan_policy", None) == policy
    )


def _iter_batch_segments(fams: list):
    """(i, j) index ranges of maximal same-ColumnarBatch runs — one native
    fill call each (fill pointers are per batch)."""
    i, n = 0, len(fams)
    while i < n:
        j = i
        b = fams[i].batch
        while j < n and fams[j].batch is b:
            j += 1
        yield i, j
        i = j


def _segment_runs(fams: list, i: int, j: int) -> tuple[np.ndarray, np.ndarray]:
    """(fam_start, fam_nrec) arrays for one same-batch segment."""
    return (
        np.fromiter((g.start for g in fams[i:j]), np.int64, j - i),
        np.fromiter((g.n for g in fams[i:j]), np.int32, j - i),
    )


def _run_multi_ref(fam) -> bool:
    """True when a FamilyRun's records span more than one contig (mapped
    records only — ref_id -1 is ignored, matching the python encoders'
    `rid >= 0` guard so both engines skip identically)."""
    run_refs = fam.batch.ref_id[fam.start : fam.start + fam.n]
    mapped = run_refs[run_refs >= 0]
    return bool(mapped.size and (mapped != mapped[0]).any())


def _decode_fixed(raw: bytes) -> str:
    """Decode a NUL-padded fixed-width field (ColumnarBatch qname/mi/rx)."""
    return raw.rstrip(b"\x00").decode("ascii", "replace")


def encode_molecular_families(
    families: Sequence[tuple[str, Sequence[BamRecord]]],
    max_window: int = 4096,
    max_templates: int = MAX_TEMPLATES,
    indel_policy: str = "drop",
) -> tuple[MolecularBatch, list[str]]:
    """Encode MI families (already grouped, e.g. by io streaming) into one
    padded batch. Families whose window exceeds max_window or whose template
    count exceeds max_templates are skipped and reported (never silently
    dropped — SURVEY.md §7.3 'no silent caps').

    indel_policy: 'drop' (parity — the reference drops indel reads,
    tools/1.convert_AG_to_CT.py:79-80) or 'align' (above-parity: recover
    them via the banded intra-family aligner, ops.banded, against the
    per-column majority of the directly-placed reads).

    Returns (batch, skipped_mi_list).
    """
    if indel_policy not in ("drop", "align"):
        raise ValueError(f"indel_policy must be 'drop'|'align', got {indel_policy!r}")
    fams = families if isinstance(families, list) else list(families)
    if fams and all(scan_matches(f, indel_policy) for f in fams):
        return _encode_molecular_native(
            fams, max_window, max_templates, indel_policy
        )
    families = fams
    placed = []
    skipped: list[str] = []
    indel_dropped = 0
    max_t = 1
    max_w = LANE
    for mi, records in families:
        templates: dict[str, dict[int, tuple]] = defaultdict(dict)
        ref_id = -1
        rx_counts: dict[str, int] = defaultdict(int)
        lo, hi = None, None
        multi_ref = False
        for rec in records:
            rid = rec.ref_id
            if rid >= 0:
                if ref_id < 0:
                    ref_id = rid
                elif rid != ref_id:
                    multi_ref = True
            trimmed = trim_softclips_keep_indels(rec)
            if trimmed is None:
                continue
            codes, quals, pos, has_indel = trimmed
            if has_indel and indel_policy == "drop":
                continue
            if len(codes) == 0:
                continue
            role = 1 if rec.flag & FREAD2 else 0
            # qname_key (columnar views): raw bytes, no per-record decode —
            # only template identity matters here
            templates[getattr(rec, "qname_key", None) or rec.qname][role] = (
                codes, quals, pos, bool(rec.flag & FREVERSE), has_indel
            )
            try:  # one tag parse, not a has_tag/get_tag pair
                rx_counts[rec.get_tag("RX")] += 1
            except KeyError:
                pass
            lo = pos if lo is None else min(lo, pos)
            e = pos + len(codes) + (INDEL_BAND if has_indel else 0)
            hi = e if hi is None else max(hi, e)
        if lo is None:
            skipped.append(mi)
            continue
        window = hi - lo
        # multi_ref: a window is one contiguous interval of ONE contig; a
        # chimeric family whose mates land on different refs cannot be
        # windowed and is skipped+counted like an over-wide one
        if window > max_window or len(templates) > max_templates or multi_ref:
            skipped.append(mi)
            continue
        rx = max(rx_counts, key=rx_counts.get) if rx_counts else ""
        # majority orientation over the records actually kept (one vote per
        # (template, role) slot; duplicates overwrite, so vote the survivor)
        rev_votes = [[0, 0], [0, 0]]
        for roles in templates.values():
            for role, (_, _, _, rev, _hi) in roles.items():
                rev_votes[role][1 if rev else 0] += 1
        role_rev = (rev_votes[0][1] > rev_votes[0][0], rev_votes[1][1] > rev_votes[1][0])
        placed.append((mi, ref_id, lo, window, rx, templates, role_rev))
        max_t = max(max_t, len(templates))
        max_w = max(max_w, window)

    f = len(placed)
    t_pad = bucket_templates(max_t)
    w_pad = bucket_window(max_w)
    # graftlint: disable=padded-batch-flops -- sanctioned envelope: the mesh
    # and wire transports ship this shape, and pack_molecular_rows derives
    # the packed twin from it at the encode phase (see README, Kernel layout)
    bases = np.full((f, t_pad, 2, w_pad), NBASE, dtype=np.int8)
    # graftlint: disable=padded-batch-flops -- quals plane of the same envelope
    quals = np.zeros((f, t_pad, 2, w_pad), dtype=np.uint8)
    meta: list[FamilyMeta] = []
    pending: list[tuple[int, int, int, np.ndarray, np.ndarray, int]] = []
    for fi, (mi, ref_id, lo, window, rx, templates, role_rev) in enumerate(placed):
        for ti, (qname, roles) in enumerate(templates.items()):
            for role, (codes, q, pos, _rev, has_indel) in roles.items():
                off = pos - lo
                if has_indel:
                    pending.append((fi, ti, role, codes, q, off))
                    continue
                bases[fi, ti, role, off : off + len(codes)] = codes
                quals[fi, ti, role, off : off + len(codes)] = q
        meta.append(FamilyMeta(mi, ref_id, lo, len(templates), rx, role_reverse=role_rev))
    indel_aligned = 0
    if pending:
        indel_aligned, n_refused = _align_pending(bases, quals, pending)
        indel_dropped += n_refused
    return (
        MolecularBatch(bases, quals, meta, indel_aligned, indel_dropped),
        skipped,
    )


def _align_pending(bases, quals, pending) -> tuple[int, int]:
    """Banded-align indel reads against their family/role anchors and write
    the window-space rows into the batch arrays. Returns (aligned, refused)."""
    from bsseqconsensusreads_tpu.ops.banded import banded_align

    w = bases.shape[-1]
    n = len(pending)
    lmax = max(len(p[3]) for p in pending)
    r_codes = np.full((n, lmax), NBASE, dtype=np.int8)
    r_quals = np.zeros((n, lmax), dtype=np.uint8)
    anchors = np.empty((n, w), dtype=np.int8)
    offsets = np.zeros(n, dtype=np.int32)
    for i, (fi, ti, role, codes, q, off) in enumerate(pending):
        r_codes[i, : len(codes)] = codes
        r_quals[i, : len(codes)] = q
        offsets[i] = off
        # anchor: per-column majority of the directly-placed reads of this
        # (family, role); NBASE where nothing is placed
        fam = bases[fi, :, role, :]  # [T, W]
        counts = (fam[:, :, None] == np.arange(NUM_BASES)[None, None, :]).sum(0)
        depth = counts.sum(-1)
        anchors[i] = np.where(depth > 0, counts.argmax(-1), NBASE).astype(np.int8)
    out_b, out_q, ok = banded_align(
        r_codes, r_quals, anchors, offsets, band=INDEL_BAND,
        min_score_per_base=1.0,
    )
    for i, (fi, ti, role, codes, q, off) in enumerate(pending):
        if not ok[i]:
            continue
        cov = out_b[i] != NBASE
        bases[fi, ti, role, cov] = out_b[i][cov]
        quals[fi, ti, role, cov] = out_q[i][cov]
    aligned = int(ok.sum())
    return aligned, n - aligned


def _encode_molecular_native(
    fams: list,
    max_window: int,
    max_templates: int,
    indel_policy: str,
) -> tuple[MolecularBatch, list[str]]:
    """encode_molecular_families over pipeline.ingest.FamilyRun inputs: the
    per-record pass already ran in C at ingest time (io.native.encode_scan,
    semantics documented at native/bamio.cpp bamio_encode_scan), so this
    reads per-family digests and fills the tensors with one C call per
    contiguous batch segment (io.native.encode_fill). Output is identical
    to the Python path — tests/test_native_encode.py fuzzes the parity."""
    from bsseqconsensusreads_tpu.io import native

    skipped: list[str] = []
    placed: list = []
    rows = np.empty(len(fams), np.int64)
    max_t, max_w = 1, LANE
    for i, fam in enumerate(fams):
        s, k = fam.scan, fam.fidx
        ntpl = int(s["ntpl"][k])
        window = int(s["window"][k])
        if (
            ntpl == 0 or window > max_window or ntpl > max_templates
            or _run_multi_ref(fam)
        ):
            skipped.append(fam.mi)
            rows[i] = -1
            continue
        rows[i] = len(placed)
        placed.append(fam)
        if ntpl > max_t:
            max_t = ntpl
        if window > max_w:
            max_w = window

    f = len(placed)
    t_pad = bucket_templates(max_t)
    w_pad = bucket_window(max_w)
    # graftlint: disable=padded-batch-flops -- sanctioned envelope: the native
    # scan's encode_fill writes slot-addressed (fi, ti, role) rows, and the
    # packed twin is derived from this batch downstream (pack_molecular_rows)
    bases = np.full((f, t_pad, 2, w_pad), NBASE, dtype=np.int8)
    # graftlint: disable=padded-batch-flops -- quals plane of the same envelope
    quals = np.zeros((f, t_pad, 2, w_pad), dtype=np.uint8)
    for i, j in _iter_batch_segments(fams):
        scan = fams[i].scan
        fam_start, fam_nrec = _segment_runs(fams, i, j)
        native.encode_fill(
            fams[i].batch, scan, fam_start, fam_nrec, rows[i:j],
            np.ascontiguousarray(scan["lo"][[g.fidx for g in fams[i:j]]]),
            bases, quals,
        )

    meta: list[FamilyMeta] = []
    pending: list[tuple[int, int, int, np.ndarray, np.ndarray, int]] = []
    for row, fam in enumerate(placed):
        s, k, b = fam.scan, fam.fidx, fam.batch
        rx = ""
        rxr = int(s["rx_rec"][k])
        if rxr >= 0:
            rx = _decode_fixed(b.rx[rxr])
        rr = int(s["rolerev"][k])
        meta.append(FamilyMeta(
            fam.mi, int(s["refid"][k]), int(s["lo"][k]), int(s["ntpl"][k]),
            rx, role_reverse=(bool(rr & 1), bool(rr & 2)),
        ))
        if indel_policy != "align":
            continue
        keep = s["keep"][fam.start : fam.start + fam.n]
        for dj in np.nonzero(keep == 2)[0]:
            j2 = fam.start + int(dj)
            lc, rc = int(b.left_clip[j2]), int(b.right_clip[j2])
            vo = int(b.var_off[j2])
            length = int(b.l_seq[j2]) - lc - rc
            codes = b.seq[vo + lc : vo + lc + length].view(np.int8)
            q = b.qual[vo + lc : vo + lc + length]
            if b.qual[vo] == 0xFF:
                q = np.zeros(length, np.uint8)
            pending.append((
                row, int(s["ti"][j2]), int(s["role"][j2]), codes, q,
                int(b.pos[j2]) - int(s["lo"][k]),
            ))
    indel_aligned = indel_dropped = 0
    if pending:
        indel_aligned, indel_dropped = _align_pending(bases, quals, pending)
    return (
        MolecularBatch(bases, quals, meta, indel_aligned, indel_dropped),
        skipped,
    )


#: Flags the duplex stage accepts, and their row in the family tensor —
#: derived from the single flag vocabulary in utils.flags (GROUP_ORDER is the
#: reference's output order, tools/2.extend_gap.py:136). The conversion tool
#: passes 0/99/147 through, converts 1/83/163, and silently drops everything
#: else (tools/1.convert_AG_to_CT.py:70-73).
DUPLEX_ROW_OF_FLAG = {f: i for i, f in enumerate(GROUP_ORDER)}
CONVERT_ROWS = tuple(
    i for i, f in enumerate(GROUP_ORDER) if f in CONVERT_FLAGS
)  # rows for flags 163 and 83: B-strand reads needing AG->CT


@dataclasses.dataclass
class DuplexBatch:
    """[F, 4, W] family tensors for the convert -> extend -> duplex stages.

    Row order (99, 163, 83, 147); ref carries W+1 reference codes per family
    (one extra column for the CpG / trailing-trim lookahead). convert_mask
    marks B-strand rows that are present.
    """

    bases: np.ndarray  # int8 [F, 4, W]
    quals: np.ndarray  # float32 [F, 4, W]
    cover: np.ndarray  # bool [F, 4, W]
    ref: np.ndarray  # int8 [F, W+1]
    convert_mask: np.ndarray  # bool [F, 4]
    extend_eligible: np.ndarray  # bool [F] — group had exactly 4 reads
    meta: list[FamilyMeta]


def encode_duplex_families(
    families: Sequence[tuple[str, Sequence[BamRecord]]],
    ref_fetch,
    ref_names: Sequence[str],
    max_window: int = 4096,
    fetch_ref: bool = True,
    pos0: str = "skip",
) -> tuple[DuplexBatch, list[BamRecord], list[str]]:
    """Encode duplex MI groups (strand suffix already stripped) for the fused
    convert+extend+duplex TPU stage.

    ref_fetch(name, start, end) -> str is a FastaFile.fetch-compatible
    callable; a failed fetch falls back to all-N, matching the reference
    (tools/1.convert_AG_to_CT.py:106-109).

    Returns (batch, leftovers, skipped): leftovers are records this stage
    cannot tensorize (flags outside {99,163,83,147}, duplicate flags, indel
    reads, or reads empty after softclip trimming) for the caller to handle
    host-side; skipped lists MI groups dropped entirely (window too large /
    no usable reads).

    Reference-parity gate: the reference only harmonizes groups of exactly 4
    reads, passing every other group through unextended
    (tools/2.extend_gap.py:114-115). Group size counts reads surviving the
    hardclip drop, like the reference's grouping pass; the resulting
    per-family extend_eligible flag gates extend_gap downstream.

    fetch_ref=False leaves batch.ref all-N — for the wire transport, whose
    kernel gathers the windows from the device-resident genome
    (ops.refstore) instead of shipping them from the host.

    pos0: what a convert-row read mapped at reference position 0 does about
    the conversion prepend (there is no column to its left).  'skip' (the
    default) skips the prepend — the sane behavior documented in
    ops/convert.py.  'shift' reproduces the reference exactly
    (tools/1.convert_AG_to_CT.py:87-92: prepend anyway, clamp pos to 0,
    shifting the whole read one base out of register): the read is placed
    one window column right, so the standard prepend path then writes the
    reference base at its original start column and every comparison runs
    at the reference's shifted register.  'shift' disables the native
    duplex encode scan (the rare-parity mode stays on the Python
    placement path).
    """
    if pos0 not in ("skip", "shift"):
        raise ValueError(f"pos0 must be 'skip'|'shift', got {pos0!r}")
    fams = families if isinstance(families, list) else list(families)
    if (
        pos0 == "skip"
        and fams
        and all(scan_matches(f, "duplex") for f in fams)
    ):
        return _encode_duplex_native(
            fams, ref_fetch, ref_names, max_window, fetch_ref
        )
    families = fams
    placed = []
    leftovers: list[BamRecord] = []
    skipped: list[str] = []
    max_w = LANE
    for mi, records in families:
        rows: dict[int, tuple] = {}
        rx = ""
        ref_id = -1
        lo, hi = None, None
        group_size = 0
        multi_ref = False
        for rec in records:
            rid = rec.ref_id
            if rid >= 0:
                if ref_id < 0:
                    ref_id = rid
                elif rid != ref_id:
                    multi_ref = True
            info = getattr(rec, "clip_info", None)  # columnar CIGAR digest
            if (
                info[3]
                if info is not None
                else any(op == CHARD_CLIP for op, _ in rec.cigar)
            ):
                continue  # reference drops hardclipped reads (2.extend_gap.py:160)
            group_size += 1
            row = DUPLEX_ROW_OF_FLAG.get(rec.flag)
            trimmed = trim_softclips(rec)
            if row is None or row in rows or trimmed is None or len(trimmed[0]) == 0:
                leftovers.append(rec)
                continue
            codes, quals, pos = trimmed
            if pos0 == "shift" and pos == 0 and row in CONVERT_ROWS:
                # reference pos-0 register shift (see docstring): place one
                # column right; the conversion prepend then fills column 0
                pos = 1
            rows[row] = (codes, quals, pos)
            if not rx:
                try:  # one tag parse, not a has_tag/get_tag pair
                    rx = rec.get_tag("RX")
                except KeyError:
                    pass
            lo = pos if lo is None else min(lo, pos)
            e = pos + len(codes)
            hi = e if hi is None else max(hi, e)
        if lo is None:
            skipped.append(mi)
            continue
        start = max(lo - 1, 0)  # one margin column for the conversion prepend
        window = hi - start
        # multi_ref: same one-contig window-space rule as the molecular
        # encoder — chimeric groups skip+count, never a cross-ref window
        if window > max_window or multi_ref:
            skipped.append(mi)
            continue
        placed.append((mi, ref_id, start, window, rows, rx, group_size == 4))
        max_w = max(max_w, window)

    f = len(placed)
    w_pad = bucket_window(max_w)
    bases = np.full((f, 4, w_pad), NBASE, dtype=np.int8)
    quals = np.zeros((f, 4, w_pad), dtype=np.float32)
    cover = np.zeros((f, 4, w_pad), dtype=bool)
    ref = np.full((f, w_pad + 1), NBASE, dtype=np.int8)
    convert_mask = np.zeros((f, 4), dtype=bool)
    eligible = np.zeros(f, dtype=bool)
    meta: list[FamilyMeta] = []
    for fi, (mi, ref_id, start, window, rows, rx, is_4) in enumerate(placed):
        eligible[fi] = is_4
        for row, (codes, q, pos) in rows.items():
            off = pos - start
            bases[fi, row, off : off + len(codes)] = codes
            quals[fi, row, off : off + len(codes)] = q
            cover[fi, row, off : off + len(codes)] = True
            if row in CONVERT_ROWS:
                convert_mask[fi, row] = True
        name = (
            ref_names[ref_id]
            if fetch_ref and 0 <= ref_id < len(ref_names)
            else None
        )
        if name is not None:
            try:
                # Only window+1 columns are ever read by the kernels (the
                # rest stay N-padded); don't fetch the whole bucket width.
                ref_str = ref_fetch(name, start, start + window + 1)
            except Exception:
                ref_str = ""
            codes = seq_to_codes(ref_str)
            ref[fi, : len(codes)] = codes
        meta.append(FamilyMeta(mi, ref_id, start, len(rows), rx))
    return (
        DuplexBatch(bases, quals, cover, ref, convert_mask, eligible, meta),
        leftovers,
        skipped,
    )


def _encode_duplex_native(
    fams: list, ref_fetch, ref_names: Sequence[str], max_window: int,
    fetch_ref: bool = True,
) -> tuple["DuplexBatch", list, list[str]]:
    """encode_duplex_families over pipeline.ingest.FamilyRun inputs carrying
    the C duplex-scan digest (io.native.duplex_scan): per-family start/
    window/rowmask and per-record row placement were computed at ingest
    time, so only leftover records (row == -1) ever materialize per-record
    views, and the tensors fill with one C call per contiguous batch
    segment. Output identical to the Python path (tests/test_native_encode
    fuzzes the parity); reference fetching stays host-side per family."""
    from bsseqconsensusreads_tpu.io import native
    from bsseqconsensusreads_tpu.pipeline.ingest import ColumnarRecordView

    skipped: list[str] = []
    leftovers: list = []
    placed: list = []
    rows = np.empty(len(fams), np.int64)
    max_w = LANE
    for i, fam in enumerate(fams):
        s, k = fam.scan, fam.fidx
        window = int(s["window"][k])
        # leftovers accumulate from every family, skipped or not (the
        # Python pass appends them before the family-level gates); the
        # scan's per-family count keeps the common zero case index-scan-free
        if int(s["nleft"][k]):
            row_of = s["row"][fam.start : fam.start + fam.n]
            for dj in np.nonzero(row_of == -1)[0]:
                leftovers.append(
                    ColumnarRecordView(fam.batch, fam.start + int(dj))
                )
        if window < 0 or window > max_window or _run_multi_ref(fam):
            skipped.append(fam.mi)
            rows[i] = -1
            continue
        rows[i] = len(placed)
        placed.append(fam)
        if window > max_w:
            max_w = window

    f = len(placed)
    w_pad = bucket_window(max_w)
    bases = np.full((f, 4, w_pad), NBASE, dtype=np.int8)
    quals = np.zeros((f, 4, w_pad), dtype=np.float32)
    cover = np.zeros((f, 4, w_pad), dtype=bool)
    ref = np.full((f, w_pad + 1), NBASE, dtype=np.int8)
    convert_mask = np.zeros((f, 4), dtype=bool)
    eligible = np.zeros(f, dtype=bool)
    for i, j in _iter_batch_segments(fams):
        scan = fams[i].scan
        fam_start, fam_nrec = _segment_runs(fams, i, j)
        native.duplex_fill(
            fams[i].batch, scan, fam_start, fam_nrec, rows[i:j],
            np.ascontiguousarray(scan["start"][[g.fidx for g in fams[i:j]]]),
            bases, quals, cover.view(np.uint8),
        )

    meta: list[FamilyMeta] = []
    for row, fam in enumerate(placed):
        s, k, b = fam.scan, fam.fidx, fam.batch
        mask = int(s["rowmask"][k])
        eligible[row] = int(s["gsize"][k]) == 4
        for r in CONVERT_ROWS:
            convert_mask[row, r] = bool(mask & (1 << r))
        rx = ""
        rxr = int(s["rx_rec"][k])
        if rxr >= 0:
            rx = _decode_fixed(b.rx[rxr])
        ref_id = int(s["refid"][k])
        start = int(s["start"][k])
        window = int(s["window"][k])
        name = (
            ref_names[ref_id]
            if fetch_ref and 0 <= ref_id < len(ref_names)
            else None
        )
        if name is not None:
            try:
                ref_str = ref_fetch(name, start, start + window + 1)
            except Exception:
                ref_str = ""
            codes = seq_to_codes(ref_str)
            ref[row, : len(codes)] = codes
        meta.append(
            FamilyMeta(fam.mi, ref_id, start, bin(mask).count("1"), rx)
        )
    return (
        DuplexBatch(bases, quals, cover, ref, convert_mask, eligible, meta),
        leftovers,
        skipped,
    )


def iter_mi_groups(records: Iterable[BamRecord], strip_suffix: bool = False):
    """Group a record stream by MI tag, preserving first-seen order.

    strip_suffix drops the /A |/B strand suffix (like tools/2.extend_gap.py:166)
    so both strands of a duplex land in one group. Records without an MI tag
    raise, matching the reference (tools/2.extend_gap.py:180).
    """
    groups: dict[str, list[BamRecord]] = {}
    for rec in records:
        if not rec.has_tag("MI"):
            raise ValueError(f"{rec.qname} does not have MI tag.")
        mi = str(rec.get_tag("MI"))
        if strip_suffix:
            mi = mi.split("/")[0]
        groups.setdefault(mi, []).append(rec)
    return list(groups.items())
