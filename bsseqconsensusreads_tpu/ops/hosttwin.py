"""Host (numpy) twins of the duplex window transforms.

The duplex stage's raw-unit accounting (pipeline.calling._duplex_rawize)
needs two things the device does not ship back: the POST-transform strand
base per column (the per-strand consensus calls fgbio stows in its ac/bc
extension tags), and the per-column mapping raw base -> converted base
(to count, exactly, how many raw reads agree with the duplex call — the
molecular stage's cB histogram is in RAW space, the duplex call in
converted space).

Both are integer-only functions of tensors the host already holds
(batch bases/cover/convert_mask/eligible + the reference window), so they
are recomputed here rather than shipped: zero wire bytes, and exact —
every operation below is a comparison or select on int8 planes, mirroring
ops.convert.convert_ag_to_ct / ops.extend.extend_gap term for term
(reference semantics: tools/1.convert_AG_to_CT.py:87-171,
tools/2.extend_gap.py:58-110). tests/test_hosttwin.py pins equality
against the jit ops on random batches; the same precedent as
models.molecular._overlap_cocall_np / recompute_molecular_counts.

Quals are deliberately NOT mirrored: no rule below depends on them, and
the callers only consume bases/cover.
"""

from __future__ import annotations

import numpy as np

from bsseqconsensusreads_tpu.alphabet import A, C, G, NBASE
from bsseqconsensusreads_tpu.ops.extend import PAIRS

#: T's base code (ops.convert uses the literal for int8 select typing).
_T = 3


def _span_np(cover):
    """First/last covered column per read ([..., W] bool) — argmax twins."""
    w = cover.shape[-1]
    first = np.argmax(cover, axis=-1)
    last = w - 1 - np.argmax(cover[..., ::-1], axis=-1)
    return first, last


def convert_np(bases, cover, ref, convert_mask):
    """Base/cover half of ops.convert.convert_ag_to_ct, in numpy.

    bases: int8 [..., R, W]; cover: bool [..., R, W]; ref: int8 [..., W+1];
    convert_mask: bool [..., R]. Returns (bases, cover, la, rd) with la/rd
    int8 [..., R] — exactly the jit op's outputs minus the qual plane.
    """
    bases = np.asarray(bases).copy()
    cover = np.asarray(cover).copy()
    ref = np.asarray(ref)
    w = bases.shape[-1]
    idx = np.arange(w)
    has = cover.any(axis=-1)
    first, _ = _span_np(cover)
    act = np.asarray(convert_mask, bool) & has

    # prepend: one column left of the read, value = reference base there
    can_pre = act & (first > 0)
    pre_col = np.maximum(first - 1, 0)
    pre_hot = (idx == pre_col[..., None]) & can_pre[..., None]
    ref_w = ref[..., :w]
    bases = np.where(pre_hot, np.broadcast_to(ref_w[..., None, :], bases.shape), bases)
    cover = cover | pre_hot

    # per-column rewrite (vectorized select over the original values)
    ref_next = ref[..., 1 : w + 1]
    read_next = np.concatenate(
        [bases[..., 1:], np.full_like(bases[..., :1], NBASE)], axis=-1
    )
    next_cov = np.concatenate(
        [cover[..., 1:], np.zeros_like(cover[..., :1])], axis=-1
    )
    is_cpg = (ref_w == C) & (ref_next == G)
    a_rule = (bases == A) & (ref_w[..., None, :] == G)
    cpg_here = is_cpg[..., None, :]
    c_pair = (bases == C) & cpg_here & next_cov & (read_next == A)
    c_plain = (bases == C) & ~cpg_here
    out = np.where(a_rule, G, bases)
    out = np.where(c_pair | c_plain, np.where(bases == C, _T, out), out)
    gate = act[..., None] & cover
    bases = np.where(gate, out, bases).astype(np.int8)

    # trailing trim: ref past the end is G and the read now ends in C
    _, last = _span_np(cover)
    last_base = np.take_along_axis(bases, last[..., None], axis=-1)[..., 0]
    ref_after = np.take_along_axis(
        np.broadcast_to(ref_next[..., None, :], bases.shape),
        last[..., None], axis=-1,
    )[..., 0]
    trim = act & (ref_after == G) & (last_base == C)
    last_hot = (idx == last[..., None]) & trim[..., None]
    cover = cover & ~last_hot
    bases = np.where(last_hot, NBASE, bases).astype(np.int8)
    return bases, cover, can_pre.astype(np.int8), trim.astype(np.int8)


def extend_np(bases, cover, la, rd, eligible=None):
    """Base/cover half of ops.extend.extend_gap, in numpy.

    One-hot boundary-column copies between the strand rows of each pair
    (left=converted row): LA copies left's first column into the partner,
    RD copies the partner's last column into the left row."""
    bases = np.asarray(bases).copy()
    cover = np.asarray(cover).copy()
    w = bases.shape[-1]
    idx = np.arange(w)
    for left, right in PAIRS:
        has_l = cover[..., left, :].any(axis=-1)
        has_r = cover[..., right, :].any(axis=-1)
        both = has_l & has_r
        if eligible is not None:
            both = both & np.asarray(eligible, bool)
        first_l = np.argmax(cover[..., left, :], axis=-1)
        last_r = w - 1 - np.argmax(cover[..., right, ::-1], axis=-1)
        for src, dst, col, gate in (
            (left, right, first_l, both & (np.asarray(la)[..., left] == 1)),
            (right, left, last_r, both & (np.asarray(rd)[..., left] == 1)),
        ):
            hot = (idx == col[..., None]) & gate[..., None]
            src_b = np.take_along_axis(
                bases[..., src, :], col[..., None], axis=-1
            )
            bases[..., dst, :] = np.where(hot, src_b, bases[..., dst, :])
            cover[..., dst, :] = cover[..., dst, :] | hot
    return bases.astype(np.int8), cover


def strand_call_planes(bases, cover, ref, convert_mask, eligible=None):
    """Post-transform strand rows: (bases int8 [..., R, W], cover bool).

    The per-strand consensus call the duplex merge actually voted with —
    NBASE where the transformed row has no coverage. This is the content
    of the fgbio-style ac/bc tags (duplex emitters) and the basis of
    FilterConsensusReads --require-single-strand-agreement."""
    b, c, la, rd = convert_np(bases, cover, ref, convert_mask)
    b, c = extend_np(b, c, la, rd, eligible)
    return np.where(c, b, NBASE).astype(np.int8), c


def convert_cell(x, act, refc, refn, nxt, nxtcov):
    """THE elementwise conversion rule, broadcastable over any shape:
    what base x becomes at a column with reference base refc, next
    reference base refn, the read's own raw next base nxt (coverage
    nxtcov), on a convert row (act). Shared by conv_base_map (plane
    domain) and the duplex exact-ce dissent pass
    (pipeline.calling._exact_strand_errors, gather domain) so the rule
    exists ONCE — a drifted copy would silently desynchronize the
    exact-ce counts from the pinned twin."""
    m = np.where(act & (x == A) & (refc == G), G, x)
    conv_c = np.where(
        (refc == C) & (refn == G),
        np.where(nxtcov & (nxt == A), _T, C),
        _T,
    )
    return np.where(act & (x == C), conv_c, m).astype(np.int8)


def conv_base_map(bases, cover, ref, convert_mask):
    """Per-column raw->converted base map M: int8 [4, ..., R, W].

    M[x, ..., r, i] = what base x at column i of row r would have become
    under the conversion the strand read went through, holding the read's
    OWN context fixed (its raw next base, the reference window). For
    non-convert rows the map is the identity. Used to count raw reads
    (the molecular cB histogram) against the converted-space duplex call:
    per-read joint identities are gone at this stage (fgbio's duplex
    caller in the reference flow never had them either — it sees one
    converted consensus read per strand), so the dissenting bases are
    converted under the strand read's context — the only exact,
    well-defined mapping available, documented in PARITY.md.

    The prepend/trim edge columns carry no raw reads; callers halo-fill
    them from the nearest raw column like every other raw-unit plane."""
    bases = np.asarray(bases)
    cover = np.asarray(cover, bool)
    ref = np.asarray(ref)
    w = bases.shape[-1]
    ref_w = np.broadcast_to(ref[..., None, :w], bases.shape)
    ref_next = np.broadcast_to(ref[..., None, 1 : w + 1], bases.shape)
    read_next = np.concatenate(
        [bases[..., 1:], np.full_like(bases[..., :1], NBASE)], axis=-1
    )
    next_cov = np.concatenate(
        [cover[..., 1:], np.zeros_like(cover[..., :1])], axis=-1
    )
    act = np.asarray(convert_mask, bool)[..., None]
    out = np.empty((4,) + bases.shape, np.int8)
    for x in range(4):
        out[x] = convert_cell(
            np.int8(x), act, ref_w, ref_next, read_next, next_cov
        )
    return out
