"""Pure-JAX array transforms and consensus math.

These replace the per-read Python loops and JVM consensus engines of the
reference with jit/vmap tensor programs: phred-space error arithmetic,
family tensorization, the AG->CT B-strand conversion
(reference: tools/1.convert_AG_to_CT.py), gap extension
(reference: tools/2.extend_gap.py), and the consensus vote kernels.
"""

from bsseqconsensusreads_tpu.ops.phred import (  # noqa: F401
    phred_to_prob,
    prob_to_phred,
    prob_error_two_trials,
)
