"""Duplex coordinate harmonization ("gap extension") as a window-space op.

TPU-native equivalent of the reference's tools/2.extend_gap.py: after B-strand
conversion, the converted reads (flags 163/83) start one base earlier (LA=1)
and may end one base earlier (RD=1) than their unconverted duplex partners
(99/147). This op copies the boundary bases across so both reads of each pair
span identical reference columns — the precondition for the duplex merge
(in the reference, for fgbio's TemplateCoordinate sort + duplex call,
main.snake.py:144-164).

Reference semantics reproduced (tools/2.extend_gap.py:58-110):
 * pair (99, 163): left read = 163 (the converted one), right = 99;
   pair (83, 147): left read = 83, right = 147 (:61-64);
 * LA(left)==1 -> right read gets left's first base+qual prepended, its start
   decremented, CIGAR 1M prepended (:70-80);
 * RD(left)==1 -> left read gets right's LAST base+qual appended, CIGAR 1M
   appended (:92-101 — the comment there says "from left read" but the code
   takes right_read.query_sequence[-1]; code is authoritative, SURVEY §3.3);
 * groups that don't have exactly 4 reads pass through unchanged (:114-115) —
   enforced by the stage encoder host-side, not here.

In window space both rules are one-hot column copies: LA copies column
first(left) from left into right; RD copies column last(right) from right
into left. The reference's whole-BAM-in-RAM dict (tools/2.extend_gap.py:
155-178, the 100 GB hotspot) disappears: families stream through in batches.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Row layout of a duplex family tensor: (99, 163, 83, 147) — the output order
# the reference uses (tools/2.extend_gap.py:136).
ROW_99, ROW_163, ROW_83, ROW_147 = 0, 1, 2, 3
# (left=converted row, right=partner row) per pair:
PAIRS = ((ROW_163, ROW_99), (ROW_83, ROW_147))


def _copy_column(bases, quals, cover, src_row, dst_row, col, gate):
    """Copy (base, qual, cover) at `col` from src_row into dst_row when gate."""
    w = bases.shape[-1]
    hot = (jnp.arange(w) == col[..., None]) & gate[..., None]  # [..., W]
    src_b = jnp.take_along_axis(bases[..., src_row, :], col[..., None], axis=-1)
    src_q = jnp.take_along_axis(quals[..., src_row, :], col[..., None], axis=-1)
    dst_b = jnp.where(hot, src_b, bases[..., dst_row, :])
    dst_q = jnp.where(hot, src_q, quals[..., dst_row, :])
    dst_c = cover[..., dst_row, :] | hot
    bases = bases.at[..., dst_row, :].set(dst_b)
    quals = quals.at[..., dst_row, :].set(dst_q)
    cover = cover.at[..., dst_row, :].set(dst_c)
    return bases, quals, cover


@jax.jit
def extend_gap(bases, quals, cover, la, rd, eligible=None):
    """bases/quals/cover: [..., 4, W] rows ordered (99, 163, 83, 147);
    la/rd: int8 [..., 4] from convert_ag_to_ct (nonzero only on rows 163/83);
    eligible: optional bool [...] — the reference only harmonizes groups of
    exactly 4 reads (tools/2.extend_gap.py:114-115); pass
    DuplexBatch.extend_eligible to reproduce that gate (None = all eligible).

    Returns updated (bases, quals, cover). Missing reads (no coverage) are
    left untouched.
    """
    quals = quals.astype(jnp.float32)
    w = bases.shape[-1]
    for left, right in PAIRS:
        has_l = cover[..., left, :].any(axis=-1)
        has_r = cover[..., right, :].any(axis=-1)
        both = has_l & has_r
        if eligible is not None:
            both = both & eligible
        first_l = jnp.argmax(cover[..., left, :], axis=-1)
        last_r = w - 1 - jnp.argmax(cover[..., right, ::-1], axis=-1)
        la_gate = both & (la[..., left] == 1)
        rd_gate = both & (rd[..., left] == 1)
        bases, quals, cover = _copy_column(
            bases, quals, cover, left, right, first_l, la_gate
        )
        bases, quals, cover = _copy_column(
            bases, quals, cover, right, left, last_r, rd_gate
        )
    return bases, quals, cover
