"""B-strand AG->CT conversion as a pure-JAX window-space transform.

TPU-native equivalent of the reference's per-read Python loop
(tools/1.convert_AG_to_CT.py:69-186): rewrite aligned B-strand reads
(flags 83/163/1) from A/G space into C/T space using the reference genome, so
the two duplex strands become directly comparable. Pass-through flags
(0/99/147) are untouched; other flags never reach this op (the stage encoder
drops them, matching the reference's silent drop).

Semantics reproduced exactly (reference line cites):
 * prepend one base whose value is the reference base there, quality 40
   ('I'), shifting pos one left (tools/1.convert_AG_to_CT.py:87-121,174-177);
   LA tag = 1 when prepended;
 * per-base rewrite (:122-150):
     read A over ref G -> G (bisulfite-converted signal; restore G)
     read C at a ref CpG with next read base A -> T (and the next base
       becomes G via the A-over-G rule)
     read C at a ref CpG otherwise -> stays C
     read C not in CpG context -> T (in-silico full conversion)
     everything else unchanged;
 * if the reference base just past the read end is G and the converted read
   now ends in C, trim that trailing C (methylation state unknowable);
   RD tag = 1 (:155-171).

The reference's sequential loop is position-parallel: its only cross-position
mutation (setting base i+1 to G inside the CpG pair rule) coincides exactly
with the standalone A-over-ref-G rule at that position, and the skipped
iteration would have been a no-op (G stays G). Hence this op is a single
vectorized select over (read, ref, ref-shifted, read-shifted).

Documented deviation (default): a read mapped at reference position 0 cannot
be prepended (no column to the left). The reference still prepends there,
shifting the whole read one base out of register (a faithful-but-wrong
translation, tools/1.convert_AG_to_CT.py:87-92); by default we skip the
prepend and set LA=0. Exact parity is available: pos0='shift' at the encode
layer (ops.encode.encode_duplex_families, config.pos0) places the read one
window column right, after which this op's ordinary prepend path reproduces
the reference's register shift bit-for-bit — this op itself needs no mode.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from bsseqconsensusreads_tpu.alphabet import A, C, G, NBASE

PREPEND_QUAL = 40.0  # 'I' (tools/1.convert_AG_to_CT.py:177)


def _span(cover):
    """First and last covered column index per read ([..., W] bool)."""
    w = cover.shape[-1]
    first = jnp.argmax(cover, axis=-1)
    last = w - 1 - jnp.argmax(cover[..., ::-1], axis=-1)
    return first, last


@partial(jax.jit, static_argnames=())
def convert_ag_to_ct(bases, quals, cover, ref, convert_mask):
    """Vectorized conversion over a family window.

    bases:  int8  [..., R, W]  base codes in genome-forward orientation
    quals:  f32/u8 [..., R, W]
    cover:  bool  [..., R, W]  contiguous covered span per read
    ref:    int8  [..., W+1]   reference codes for the window + 1 extra column
    convert_mask: bool [..., R]  True for B-strand reads (flags 83/163/1)

    Returns (bases, quals, cover, la, rd) with la/rd int8 [..., R].
    """
    quals = quals.astype(jnp.float32)
    w = bases.shape[-1]
    idx = jnp.arange(w)
    has = cover.any(axis=-1)
    first, last = _span(cover)
    act = convert_mask & has

    # -- prepend: one column left of the read, value = reference base there.
    can_pre = act & (first > 0)
    pre_col = jnp.maximum(first - 1, 0)
    pre_hot = (idx == pre_col[..., None]) & can_pre[..., None]
    ref_w = ref[..., :w]
    bases = jnp.where(pre_hot, ref_w[..., None, :], bases)
    quals = jnp.where(pre_hot, PREPEND_QUAL, quals)
    cover = cover | pre_hot
    first = jnp.where(can_pre, pre_col, first)

    # -- per-column rewrite.
    ref_next = ref[..., 1 : w + 1]
    pad_base = jnp.full_like(bases[..., :1], NBASE)
    read_next = jnp.concatenate([bases[..., 1:], pad_base], axis=-1)
    next_cov = jnp.concatenate(
        [cover[..., 1:], jnp.zeros_like(cover[..., :1])], axis=-1
    )
    is_cpg = (ref_w == C) & (ref_next == G)
    a_rule = (bases == A) & (ref_w[..., None, :] == G)
    cpg_here = is_cpg[..., None, :]
    c_pair = (bases == C) & cpg_here & next_cov & (read_next == A)
    c_plain = (bases == C) & ~cpg_here
    out = jnp.where(a_rule, G, bases)
    out = jnp.where(c_pair | c_plain, jnp.where(bases == C, 3, out), out)
    # (3 == T; using literal keeps the select int8-typed)
    gate = (act[..., None] & cover)
    bases = jnp.where(gate, out, bases)

    # -- trailing trim: ref base past the end is G and read now ends in C.
    last_base = jnp.take_along_axis(bases, last[..., None], axis=-1)[..., 0]
    ref_after = jnp.take_along_axis(
        jnp.broadcast_to(ref_next[..., None, :], bases.shape), last[..., None], axis=-1
    )[..., 0]
    trim = act & (ref_after == G) & (last_base == C)
    last_hot = (idx == last[..., None]) & trim[..., None]
    cover = cover & ~last_hot
    bases = jnp.where(last_hot, NBASE, bases)
    quals = jnp.where(last_hot, 0.0, quals)

    la = can_pre.astype(jnp.int8)
    rd = trim.astype(jnp.int8)
    return bases, quals, cover, la, rd
