"""Packed wire formats for the host<->device tunnel.

On tunneled TPU hosts the device link is the stage bottleneck, with three
measured pathologies (see BASELINE.md / bench.py):

  * D2H of computed arrays runs ~25 MB/s (entropy-dependent — the tunnel
    compresses) with ~0.1 s fixed cost per fetch, and briefly degrades the
    H2D direction afterwards;
  * many small transfers pay the fixed cost repeatedly;
  * multi-dim narrow-dtype arrays move slower than flat word-sized ones.

So every hot-path tensor crosses the wire as ONE flat uint32 array per
direction, packed to its information content:

  input  nib:  4 bits/cell  = base code (3b) | cover (1b), 2 cells/byte
  input  qual: adaptive codebook — current Illumina instruments emit 4
               (RTA3: {2,12,23,37}) or 8 quality levels, so the covered
               cells' distinct Phred values usually fit a tiny codebook:
               'q2' = 2 bits/cell + 4-entry codebook, 'q4' = 4 bits/cell +
               16-entry codebook, 'q8' = raw 8 bits/cell fallback.
               Uncovered cells carry codebook[0]; their qualities are
               never observed (bases there are NBASE, outside every mask).
  input  meta: 8 bits/family = convert_mask rows (4b) | extend_eligible (1b)
  output wire: pack_duplex_outputs columns (2 B/col, planar: byte0 plane
               then qual plane — see models/duplex.py) ++ la/rd (1 B/family)

The host-side pack/unpack sweeps have a native C++ fast path
(native/wirepack.cpp via io.wirepack, byte-identical, ~10x) with this
module's numpy implementations as the reference and fallback.

The reference streams everything through BAM files between processes
(SURVEY.md §3.1); this module is the equivalent "serialization boundary" of
the TPU design, sized for the tunnel instead of the filesystem.
"""

from __future__ import annotations

import dataclasses

import numpy as np

import jax
import jax.numpy as jnp


def _pad_to_words(flat_u8: np.ndarray) -> np.ndarray:
    pad = (-flat_u8.size) % 4
    if pad:
        flat_u8 = np.concatenate([flat_u8, np.zeros(pad, dtype=np.uint8)])
    return flat_u8.view(np.uint32)


QUAL_MODE_BITS = {"q2": 2, "q4": 4}


def _qual_codebook_words(mode: str) -> int:
    return (1 << QUAL_MODE_BITS[mode]) // 4


_QUAL_SENTINEL = 255  # > max legal Phred (93): marks uncovered cells


def _masked_quals(quals: np.ndarray, cover: np.ndarray) -> np.ndarray:
    """Flat quals with uncovered cells replaced by the sentinel — shared by
    level detection and index packing so the batch is traversed once each."""
    return np.where(cover.reshape(-1), quals.reshape(-1), _QUAL_SENTINEL)


def _qual_levels(masked: np.ndarray, n_uncovered: int):
    """(distinct covered Phred values, covered-cells-carry-255 flag).

    bincount beats np.unique ~10x on the 10M-cell hot-path batches: one
    pass, no sort. A covered 255 is indistinguishable from the sentinel in
    `masked`, so it is detected by count: the 255 bin exceeding the
    uncovered-cell population means real 0xff quals are present."""
    counts = np.bincount(masked, minlength=256)
    levels = np.nonzero(counts[:_QUAL_SENTINEL])[0].astype(np.uint8)
    if not levels.size:
        levels = np.zeros(1, np.uint8)
    return levels, int(counts[_QUAL_SENTINEL]) > n_uncovered


def _pack_qual_codes(masked: np.ndarray, mode: str, levels: np.ndarray):
    """Codebook-encode quals: returns u32 [codebook ++ packed indices].

    Only covered cells' values enter the codebook; the sentinel (uncovered)
    maps to index 0 — never observed downstream, see module docstring."""
    bits = QUAL_MODE_BITS[mode]
    if len(levels) > (1 << bits):
        raise ValueError(
            f"{len(levels)} distinct covered quals exceed {mode}'s "
            f"{1 << bits}-entry codebook; use qual_mode='auto'"
        )
    if levels.size and int(levels[-1]) > 93:
        raise ValueError(
            f"covered qual {int(levels[-1])} > 93 (BAM printable max) cannot "
            "ride a codebook mode; use qual_mode='q8' or 'auto'"
        )
    book = np.zeros(1 << bits, dtype=np.uint8)
    book[: len(levels)] = levels
    # 256-entry LUT instead of searchsorted: one gather over the batch,
    # and lut[sentinel] = 0 handles uncovered cells for free
    lut = np.zeros(256, dtype=np.uint8)
    lut[levels] = np.arange(len(levels), dtype=np.uint8)
    idx = lut[masked]
    per = 8 // bits
    pad = (-idx.size) % per
    if pad:
        idx = np.concatenate([idx, np.zeros(pad, dtype=np.uint8)])
    idx = idx.reshape(-1, per)
    packed = np.zeros(len(idx), dtype=np.uint8)
    for i in range(per):
        packed |= idx[:, i] << (bits * i)
    return np.concatenate([book.view(np.uint32), _pad_to_words(packed)])


def _unpack_qual_codes(words, f: int, w: int, r: int, mode: str):
    """Device-side inverse of _pack_qual_codes -> uint8 [f, r, w]."""
    bits = QUAL_MODE_BITS[mode]
    nbook = 1 << bits
    book_u8 = jax.lax.bitcast_convert_type(
        words[: nbook // 4], jnp.uint8
    ).reshape(-1)
    packed = jax.lax.bitcast_convert_type(
        words[nbook // 4 :], jnp.uint8
    ).reshape(-1)
    per = 8 // bits
    mask = nbook - 1
    idx = jnp.stack(
        [(packed >> (bits * i)) & mask for i in range(per)], axis=-1
    ).reshape(-1)[: f * r * w]
    return jnp.take(book_u8, idx, axis=0).reshape(f, r, w)


@dataclasses.dataclass
class DuplexWire:
    """Host-side packed input batch for duplex_call_wire."""

    nib: np.ndarray  # uint32 [F*R*W/8]   base|cover nibbles
    qual: np.ndarray  # uint32 — q8: [F*R*W/4] raw Phred bytes; q2/q4:
    #                   codebook words ++ [F*R*W*bits/32] packed indices
    meta: np.ndarray  # uint32 [ceil(F/4)] convert_mask|eligible bytes
    starts: np.ndarray  # uint32 [F] global genome offset of window (NO_REF = all-N)
    limits: np.ndarray  # uint32 [F] global genome offset one past the contig end
    f: int
    w: int
    qual_mode: str = "q8"  # 'q2'/'q4' codebook or raw 'q8' (see module doc)
    r: int = 4  # reads per family (duplex window rows)

    def to_words(self) -> np.ndarray:
        """ONE flat u32 array for the whole input direction — a single H2D
        transfer instead of five, so the tunnel's fixed per-transfer cost is
        paid once per batch. Section order/sizes are static given
        (f, w, r, qual_mode); split on device with split_duplex_wire."""
        return np.concatenate(
            [self.starts, self.limits, self.meta, self.nib, self.qual]
        )


def wire_section_sizes(
    f: int, w: int, r: int = 4, qual_mode: str = "q8"
) -> tuple[int, ...]:
    """u32 word counts of the to_words() sections, in order:
    starts, limits, meta, nib, qual."""
    cells = f * r * w
    if qual_mode == "q8":
        qual_words = -(-cells // 4)
    else:
        bits = QUAL_MODE_BITS[qual_mode]
        qual_words = _qual_codebook_words(qual_mode) + -(-(cells * bits) // 32)
    return (f, f, (f + 3) // 4, -(-(cells // 2) // 4), qual_words)


def split_duplex_wire(words, f: int, w: int, r: int = 4, qual_mode: str = "q8"):
    """Device-side (jit-traceable) split of DuplexWire.to_words() back into
    the (nib, qual, meta, starts, limits) section arrays.

    Version refusal: a packed-rows wire (v2, pack_molecular_rows_wire)
    leads with PACKED_WIRE_MAGIC where a v1 wire carries starts[0]; when
    called host-side with a numpy array the magic is rejected here instead
    of parsing the v2 header planes as genome offsets. Under jit the
    argument is a tracer (not np.ndarray), so the traced program is
    unchanged — the guard runs where the bytes are still host-visible.
    """
    if isinstance(words, np.ndarray) and words.size and (
        int(words[0]) == PACKED_WIRE_MAGIC
    ):
        raise ValueError(
            "packed rows wire (v2 magic word) passed to the v1 duplex wire "
            "splitter; unpack with split_molecular_rows_wire"
        )
    sizes = wire_section_sizes(f, w, r, qual_mode)
    offs = [0]
    for s in sizes:
        offs.append(offs[-1] + s)
    starts, limits, meta, nib, qual = (
        words[offs[i] : offs[i + 1]] for i in range(5)
    )
    return nib, qual, meta, starts, limits


def pack_duplex_inputs(
    bases: np.ndarray,
    quals: np.ndarray,
    cover: np.ndarray,
    convert_mask: np.ndarray,
    eligible: np.ndarray,
    starts: np.ndarray,
    limits: np.ndarray,
    qual_mode: str = "q8",
) -> DuplexWire:
    """numpy pack of a DuplexBatch into flat u32 wire arrays.

    bases int8/uint8 [F, R, W] (NBASE where uncovered), quals uint8 [F, R, W],
    cover bool [F, R, W], convert_mask bool [F, R], eligible bool [F].
    W must be even. qual_mode 'auto' picks the smallest codebook the covered
    cells' distinct qual values fit ('q2' <= 4 levels, 'q4' <= 16, else
    'q8' raw bytes); the default stays raw 'q8' so pack/unpack defaults
    round-trip — the chosen mode travels in DuplexWire.qual_mode and MUST be
    passed to the unpack/duplex_call_wire side.
    """
    f, r, w = bases.shape
    if w % 2:
        raise ValueError(f"window width must be even, got {w}")
    if qual_mode not in ("q8", "auto", "q2", "q4"):
        raise ValueError(
            f"qual_mode must be one of 'q8', 'auto', 'q2', 'q4'; "
            f"got {qual_mode!r}"
        )
    from bsseqconsensusreads_tpu.io import wirepack as _native

    if _native.available():
        # single-sweep C++ pack (native/wirepack.cpp): byte-identical to the
        # numpy path below, ~10x faster on production-size batches
        nib, qual, meta, resolved = _native.pack_duplex(
            bases, quals, cover, convert_mask, eligible, qual_mode
        )
        return DuplexWire(
            nib=nib, qual=qual, meta=meta,
            starts=np.asarray(starts, dtype=np.uint32),
            limits=np.asarray(limits, dtype=np.uint32),
            f=f, w=w, qual_mode=resolved, r=r,
        )
    masked = levels = None
    if qual_mode != "q8":
        n_uncovered = int(cover.size - np.count_nonzero(cover))
    if qual_mode == "auto":
        masked = _masked_quals(np.asarray(quals, dtype=np.uint8), cover)
        levels, has_255 = _qual_levels(masked, n_uncovered)
        n = len(levels)
        # Phred > 93 is outside the BAM printable range ('~'); 255 would
        # collide with the uncovered-cell sentinel — raw bytes are always safe
        if n > 16 or has_255 or int(levels[-1]) > 93:
            qual_mode = "q8"
        else:
            qual_mode = "q2" if n <= 4 else "q4"
    nib = (bases.astype(np.uint8) & 0x7) | (cover.astype(np.uint8) << 3)
    nib = nib.reshape(f * r * w // 2, 2)
    nib_packed = (nib[:, 0] | (nib[:, 1] << 4)).astype(np.uint8)
    meta = np.zeros(f, dtype=np.uint8)
    for row in range(min(r, 4)):
        meta |= convert_mask[:, row].astype(np.uint8) << row
    meta |= eligible.astype(np.uint8) << 4
    if qual_mode == "q8":
        qual_words = _pad_to_words(quals.astype(np.uint8).reshape(-1))
    else:
        if masked is None:
            masked = _masked_quals(np.asarray(quals, dtype=np.uint8), cover)
            levels, has_255 = _qual_levels(masked, n_uncovered)
            if has_255:
                raise ValueError(
                    "covered qual 255 (> 93, BAM printable max) cannot ride "
                    f"a {qual_mode} codebook; use qual_mode='q8' or 'auto'"
                )
        qual_words = _pack_qual_codes(masked, qual_mode, levels)
    return DuplexWire(
        nib=_pad_to_words(nib_packed),
        qual=qual_words,
        meta=_pad_to_words(meta),
        starts=np.asarray(starts, dtype=np.uint32),
        limits=np.asarray(limits, dtype=np.uint32),
        f=f,
        w=w,
        qual_mode=qual_mode,
        r=r,
    )


def pack_molecular_inputs(
    bases: np.ndarray, quals: np.ndarray, qual_mode: str = "auto"
) -> DuplexWire:
    """Pack a MolecularBatch's [F, T, 2, W] tensors as a 2T-row input wire.

    Reuses the duplex wire format with r = 2T: NBASE rides the nibble's 3
    base bits (cover = observed, derived from bases), and the duplex-only
    meta/starts/limits sections carry zeros — a few bytes per family
    against the MB-scale nib/qual planes, cheaper than a second format.
    Unpack with unpack_duplex_inputs(r=2T) and reshape to [F, T, 2, W]
    (models.molecular.molecular_wire_kernel does both on device).
    """
    f, t, two, w = bases.shape
    r = t * two
    b2 = np.ascontiguousarray(bases.reshape(f, r, w))
    from bsseqconsensusreads_tpu.alphabet import NBASE

    return pack_duplex_inputs(
        b2,
        np.ascontiguousarray(quals.reshape(f, r, w)),
        b2 != NBASE,
        np.zeros((f, r), dtype=bool),
        np.zeros(f, dtype=bool),
        np.zeros(f, dtype=np.uint32),
        np.zeros(f, dtype=np.uint32),
        qual_mode=qual_mode,
    )


# ---- packed wire v2: segment-packed rows ---------------------------------
#
# The v1 wire above ships the [F, T, 2, W] padding envelope (r = 2T rows
# per family, pad templates and all). v2 ships the segment-packed row plan
# instead: a version-tagged header, the per-family row-offset plane, the
# per-row segment-id plane, then the v1 nib/qual body for the dense
# [N, 2, W] row axis — the wire's cell count tracks real reads, not the
# bucket ceiling. v1 wires still parse everywhere they did (nothing about
# their layout changed); the two formats refuse each other by the magic
# word (split_duplex_wire / split_molecular_rows_wire guards).

#: Leading word of every packed-rows wire ("2QSB" little-endian — chosen
#: never to collide with a v1 MOLECULAR wire, whose first word is
#: starts[0] == 0 by construction in pack_molecular_inputs).
PACKED_WIRE_MAGIC = 0x42535132

#: Header words: magic, n_rows, num_families, n_real_rows, w, qual-mode
#: code (_ROWS_QUAL_CODE), 2 reserved zeros.
PACKED_WIRE_HDR = 8

_ROWS_QUAL_CODE = {"q8": 0, "q2": 1, "q4": 2}
_ROWS_CODE_QUAL = {v: k for k, v in _ROWS_QUAL_CODE.items()}


def rows_wire_section_sizes(
    n_rows: int, num_families: int, w: int, qual_mode: str = "q8"
) -> tuple[int, ...]:
    """u32 word counts of the packed-rows wire sections, in order:
    header, row offsets, segment ids, nib, qual."""
    v1 = wire_section_sizes(n_rows, w, r=2, qual_mode=qual_mode)
    return (PACKED_WIRE_HDR, num_families + 1, n_rows, v1[3], v1[4])


def pack_molecular_rows_wire(
    bases: np.ndarray,
    quals: np.ndarray,
    seg: np.ndarray,
    num_families: int,
    n_real_rows: int,
    qual_mode: str = "auto",
) -> tuple[np.ndarray, str]:
    """Pack a segment-packed row plan (ops.encode.PackedRows arrays) into
    ONE flat u32 wire — the packed wire v2.

    bases int8 [N, 2, W] (row-bucketed, pad rows all-NBASE), quals uint8
    [N, 2, W], seg int32 [N] ascending family ids (pad rows carry the
    sentinel `num_families`). Returns (words, resolved_qual_mode); the
    resolved mode plus (N, num_families, w) are the static split keys the
    device kernel needs (models.molecular.molecular_wire_packed_kernel) —
    the header carries them too, for host-side validation.

    Layout: header ++ row offsets u32 [num_families + 1] (family i's rows
    are [off[i], off[i+1]); off[num_families] == n_real_rows) ++ seg u32
    [N] ++ the v1 nib/qual body of the [N, 2, W] rows (native
    wirepack_pack_rows sweep when built — cover derives from the bases, so
    no bool plane is materialized; numpy pack_duplex_inputs otherwise).
    """
    n, _, w = bases.shape
    if qual_mode not in ("q8", "auto", "q2", "q4"):
        raise ValueError(
            f"qual_mode must be one of 'q8', 'auto', 'q2', 'q4'; "
            f"got {qual_mode!r}"
        )
    seg = np.ascontiguousarray(seg, dtype=np.int32)
    offsets = np.searchsorted(
        seg, np.arange(num_families + 1, dtype=np.int64), side="left"
    ).astype(np.uint32)
    from bsseqconsensusreads_tpu.io import wirepack as _native

    if _native.available():
        nib, qual, resolved = _native.pack_rows(bases, quals, qual_mode)
    else:
        from bsseqconsensusreads_tpu.alphabet import NBASE

        dw = pack_duplex_inputs(
            bases, quals, bases != NBASE,
            np.zeros((n, 2), dtype=bool), np.zeros(n, dtype=bool),
            np.zeros(n, dtype=np.uint32), np.zeros(n, dtype=np.uint32),
            qual_mode=qual_mode,
        )
        nib, qual, resolved = dw.nib, dw.qual, dw.qual_mode
    header = np.array(
        [
            PACKED_WIRE_MAGIC, n, num_families, n_real_rows, w,
            _ROWS_QUAL_CODE[resolved], 0, 0,
        ],
        dtype=np.uint32,
    )
    return (
        np.concatenate([header, offsets, seg.astype(np.uint32), nib, qual]),
        resolved,
    )


def split_molecular_rows_wire(
    words, n_rows: int, num_families: int, w: int, qual_mode: str = "q8"
):
    """Device-side (jit-traceable) split of a packed-rows wire (v2) into
    (nib, qual, seg u32 [n_rows], offsets u32 [num_families + 1]).

    Version refusal: called host-side with a numpy array, a wire whose
    leading word is not PACKED_WIRE_MAGIC (e.g. a v1 DuplexWire) or whose
    header disagrees with the static split keys is rejected before any
    section is mis-sliced. Under jit the words are a tracer and the traced
    slicing is unconditional — validate at the host boundary.
    """
    if isinstance(words, np.ndarray):
        if not words.size or int(words[0]) != PACKED_WIRE_MAGIC:
            raise ValueError(
                "not a packed rows wire (v2): leading magic word missing "
                "— v1 wires unpack with split_duplex_wire"
            )
        hdr = (int(words[1]), int(words[2]), int(words[4]),
               _ROWS_CODE_QUAL.get(int(words[5])))
        want = (n_rows, num_families, w, qual_mode)
        if hdr != want:
            raise ValueError(
                f"packed rows wire header {hdr} does not match the split "
                f"keys {want}"
            )
    sizes = rows_wire_section_sizes(n_rows, num_families, w, qual_mode)
    offs = [0]
    for s in sizes:
        offs.append(offs[-1] + s)
    _, offsets, seg, nib, qual = (
        words[offs[i] : offs[i + 1]] for i in range(5)
    )
    return nib, qual, seg, offsets


def unpack_rows_wire_inputs(nib, qual, n_rows: int, w: int,
                            qual_mode: str = "q8"):
    """Device-side unpack of the v2 body -> (bases int8 [n_rows, 2, w],
    quals uint8 [n_rows, 2, w]). The meta/cover planes of the duplex
    unpack don't exist here: observation is NBASE-coded in the bases."""
    bases, quals, _, _, _ = unpack_duplex_inputs(
        nib, qual, jnp.zeros((n_rows + 3) // 4, jnp.uint32), n_rows, w,
        r=2, qual_mode=qual_mode,
    )
    return bases, quals


def unpack_duplex_inputs(nib, qual, meta, f: int, w: int, r: int = 4,
                         qual_mode: str = "q8"):
    """Device-side (jit-traceable) inverse of pack_duplex_inputs.

    Returns (bases int8 [f,r,w], quals uint8 [f,r,w], cover bool [f,r,w],
    convert_mask bool [f,r], eligible bool [f]). Uncovered cells' quals are
    codebook[0] under q2/q4 (never observed — bases there are NBASE)."""
    nib_u8 = jax.lax.bitcast_convert_type(nib, jnp.uint8).reshape(-1)[
        : f * r * w // 2
    ]
    lo = nib_u8 & 0xF
    hi = nib_u8 >> 4
    cells = jnp.stack([lo, hi], axis=-1).reshape(f, r, w)
    bases = (cells & 0x7).astype(jnp.int8)
    cover = (cells >> 3).astype(jnp.bool_)
    if qual_mode == "q8":
        quals = jax.lax.bitcast_convert_type(qual, jnp.uint8).reshape(-1)[
            : f * r * w
        ].reshape(f, r, w)
    else:
        quals = _unpack_qual_codes(qual, f, w, r, qual_mode)
    meta_u8 = jax.lax.bitcast_convert_type(meta, jnp.uint8).reshape(-1)[:f]
    convert_mask = jnp.stack(
        [(meta_u8 >> row) & 1 for row in range(min(r, 4))], axis=-1
    ).astype(jnp.bool_)
    eligible = ((meta_u8 >> 4) & 1).astype(jnp.bool_)
    return bases, quals, cover, convert_mask, eligible


def pack_lard(la, rd):
    """Device-side pack of la/rd [..., F, 4] int8 into u32 words (1 B/family)."""
    bits = jnp.zeros(la.shape[:-1], dtype=jnp.uint8)
    for row in range(la.shape[-1]):
        bits = bits | (la[..., row].astype(jnp.uint8) << row)
        bits = bits | (rd[..., row].astype(jnp.uint8) << (4 + row))
    flat = bits.reshape(-1)
    pad = (-flat.shape[0]) % 4
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros(pad, dtype=jnp.uint8)])
    return jax.lax.bitcast_convert_type(flat.reshape(-1, 4), jnp.uint32)


def unpack_lard(words: np.ndarray, f: int, r: int = 4):
    """numpy inverse of pack_lard -> (la, rd) int8 [f, r]."""
    bits = np.asarray(words).view(np.uint8)[:f]
    la = np.stack([(bits >> row) & 1 for row in range(r)], axis=-1)
    rd = np.stack([(bits >> (4 + row)) & 1 for row in range(r)], axis=-1)
    return la.astype(np.int8), rd.astype(np.int8)
