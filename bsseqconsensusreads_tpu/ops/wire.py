"""Packed wire formats for the host<->device tunnel.

On tunneled TPU hosts the device link is the stage bottleneck, with three
measured pathologies (see BASELINE.md / bench.py):

  * D2H of computed arrays runs ~25 MB/s (entropy-dependent — the tunnel
    compresses) with ~0.1 s fixed cost per fetch, and briefly degrades the
    H2D direction afterwards;
  * many small transfers pay the fixed cost repeatedly;
  * multi-dim narrow-dtype arrays move slower than flat word-sized ones.

So every hot-path tensor crosses the wire as ONE flat uint32 array per
direction, packed to its information content:

  input  nib:  4 bits/cell  = base code (3b) | cover (1b), 2 cells/byte
  input  qual: 8 bits/cell  (Phred 0..93)
  input  meta: 8 bits/family = convert_mask rows (4b) | extend_eligible (1b)
  output wire: pack_duplex_outputs columns (2 B/col) ++ la/rd (1 B/family)

The reference streams everything through BAM files between processes
(SURVEY.md §3.1); this module is the equivalent "serialization boundary" of
the TPU design, sized for the tunnel instead of the filesystem.
"""

from __future__ import annotations

import dataclasses

import numpy as np

import jax
import jax.numpy as jnp


def _pad_to_words(flat_u8: np.ndarray) -> np.ndarray:
    pad = (-flat_u8.size) % 4
    if pad:
        flat_u8 = np.concatenate([flat_u8, np.zeros(pad, dtype=np.uint8)])
    return flat_u8.view(np.uint32)


@dataclasses.dataclass
class DuplexWire:
    """Host-side packed input batch for duplex_call_wire."""

    nib: np.ndarray  # uint32 [F*R*W/8]   base|cover nibbles
    qual: np.ndarray  # uint32 [F*R*W/4]  Phred bytes
    meta: np.ndarray  # uint32 [ceil(F/4)] convert_mask|eligible bytes
    starts: np.ndarray  # uint32 [F] global genome offset of window (NO_REF = all-N)
    limits: np.ndarray  # uint32 [F] global genome offset one past the contig end
    f: int
    w: int


def pack_duplex_inputs(
    bases: np.ndarray,
    quals: np.ndarray,
    cover: np.ndarray,
    convert_mask: np.ndarray,
    eligible: np.ndarray,
    starts: np.ndarray,
    limits: np.ndarray,
) -> DuplexWire:
    """numpy pack of a DuplexBatch into flat u32 wire arrays.

    bases int8/uint8 [F, R, W] (NBASE where uncovered), quals uint8 [F, R, W],
    cover bool [F, R, W], convert_mask bool [F, R], eligible bool [F].
    W must be even.
    """
    f, r, w = bases.shape
    if w % 2:
        raise ValueError(f"window width must be even, got {w}")
    nib = (bases.astype(np.uint8) & 0x7) | (cover.astype(np.uint8) << 3)
    nib = nib.reshape(f * r * w // 2, 2)
    nib_packed = (nib[:, 0] | (nib[:, 1] << 4)).astype(np.uint8)
    meta = np.zeros(f, dtype=np.uint8)
    for row in range(min(r, 4)):
        meta |= convert_mask[:, row].astype(np.uint8) << row
    meta |= eligible.astype(np.uint8) << 4
    return DuplexWire(
        nib=_pad_to_words(nib_packed),
        qual=_pad_to_words(quals.astype(np.uint8).reshape(-1)),
        meta=_pad_to_words(meta),
        starts=np.asarray(starts, dtype=np.uint32),
        limits=np.asarray(limits, dtype=np.uint32),
        f=f,
        w=w,
    )


def unpack_duplex_inputs(nib, qual, meta, f: int, w: int, r: int = 4):
    """Device-side (jit-traceable) inverse of pack_duplex_inputs.

    Returns (bases int8 [f,r,w], quals uint8 [f,r,w], cover bool [f,r,w],
    convert_mask bool [f,r], eligible bool [f])."""
    nib_u8 = jax.lax.bitcast_convert_type(nib, jnp.uint8).reshape(-1)[
        : f * r * w // 2
    ]
    lo = nib_u8 & 0xF
    hi = nib_u8 >> 4
    cells = jnp.stack([lo, hi], axis=-1).reshape(f, r, w)
    bases = (cells & 0x7).astype(jnp.int8)
    cover = (cells >> 3).astype(jnp.bool_)
    quals = jax.lax.bitcast_convert_type(qual, jnp.uint8).reshape(-1)[
        : f * r * w
    ].reshape(f, r, w)
    meta_u8 = jax.lax.bitcast_convert_type(meta, jnp.uint8).reshape(-1)[:f]
    convert_mask = jnp.stack(
        [(meta_u8 >> row) & 1 for row in range(min(r, 4))], axis=-1
    ).astype(jnp.bool_)
    eligible = ((meta_u8 >> 4) & 1).astype(jnp.bool_)
    return bases, quals, cover, convert_mask, eligible


def pack_lard(la, rd):
    """Device-side pack of la/rd [..., F, 4] int8 into u32 words (1 B/family)."""
    bits = jnp.zeros(la.shape[:-1], dtype=jnp.uint8)
    for row in range(la.shape[-1]):
        bits = bits | (la[..., row].astype(jnp.uint8) << row)
        bits = bits | (rd[..., row].astype(jnp.uint8) << (4 + row))
    flat = bits.reshape(-1)
    pad = (-flat.shape[0]) % 4
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros(pad, dtype=jnp.uint8)])
    return jax.lax.bitcast_convert_type(flat.reshape(-1, 4), jnp.uint32)


def unpack_lard(words: np.ndarray, f: int, r: int = 4):
    """numpy inverse of pack_lard -> (la, rd) int8 [f, r]."""
    bits = np.asarray(words).view(np.uint8)[:f]
    la = np.stack([(bits >> row) & 1 for row in range(r)], axis=-1)
    rd = np.stack([(bits >> (4 + row)) & 1 for row in range(r)], axis=-1)
    return la.astype(np.int8), rd.astype(np.int8)
