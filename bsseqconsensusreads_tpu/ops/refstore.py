"""Device-resident reference genome with on-device window gather.

The reference pipeline fetches a reference window per read on the host
(tools/1.convert_AG_to_CT.py:102-107, via pysam.FastaFile). Shipping those
windows to the device costs wire bytes every batch; instead the genome is
uploaded ONCE as a flat int8 code array (one byte per base, contigs
concatenated) and each batch sends only an int32 start offset per family —
the [F, W+1] window tensor is gathered on device.

A human-scale genome is ~3.1 GB as int8, well within a v4 chip's HBM next to
the batch tensors. Out-of-range windows (start < 0, or columns past the
contig limit) gather NBASE, reproducing the reference's all-N fallback for
failed fetches (tools/1.convert_AG_to_CT.py:106-109) and its N-padding for
short fetches (:116-117).
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from bsseqconsensusreads_tpu.alphabet import BASE_CODE, NBASE


#: starts value meaning "no reference for this family" (all-N window).
#: uint32 so a human-scale (~3.1 Gbp > 2**31) concatenated genome indexes
#: without overflow; the genome length cap is 2**32 - 2**16.
NO_REF = np.uint32(0xFFFFFFFF)
MAX_GENOME = (1 << 32) - (1 << 16)


@partial(jax.jit, static_argnames=("width",))
def gather_windows(genome, starts, limits, width: int):
    """Gather [F, width] reference windows from the flat genome on device.

    genome: int8 [G] (all contigs concatenated); starts/limits: uint32 [F]
    global offsets (start of window / one past the end of its contig).
    starts == NO_REF yields an all-N row; columns at/past `limits` yield N.
    """
    starts = starts.astype(jnp.uint32)
    idx = starts[:, None] + jnp.arange(width, dtype=jnp.uint32)
    valid = (starts[:, None] != NO_REF) & (idx < limits[:, None].astype(jnp.uint32))
    safe = jnp.minimum(idx, jnp.uint32(genome.shape[0] - 1))
    ref = jnp.take(genome, safe, axis=0)
    return jnp.where(valid, ref, jnp.int8(NBASE))


@partial(jax.jit, static_argnames=("width",))
def gather_windows_ext(genome, starts, los, limits, width: int):
    """Bounded EXTENSION gather: [F, width] windows starting 2 bases BEFORE
    each family's window (ref_ext[j] = genome[start - 2 + j]).

    Unlike gather_windows, this needs a LOWER bound too: start - 2 can fall
    before the family's contig, and the methylation context classifier must
    see N there, not the previous contig's trailing bases. los: uint32 [F]
    global offset of the contig's first base. uint32 wrap arithmetic makes
    pre-genome columns land above `limits` (the offset cap leaves 2**16
    headroom below 2**32), so the two range checks cover underflow as well.
    """
    starts = starts.astype(jnp.uint32) - jnp.uint32(2)
    idx = starts[:, None] + jnp.arange(width, dtype=jnp.uint32)
    valid = (
        (starts[:, None] != NO_REF - jnp.uint32(2))
        & (idx >= los[:, None].astype(jnp.uint32))
        & (idx < limits[:, None].astype(jnp.uint32))
    )
    safe = jnp.minimum(idx, jnp.uint32(genome.shape[0] - 1))
    ref = jnp.take(genome, safe, axis=0)
    return jnp.where(valid, ref, jnp.int8(NBASE))


class RefStore:
    """Concatenated genome codes + per-contig offsets, uploaded to device once."""

    def __init__(self, names, seqs=None, codes=None, lengths=None):
        self.names = list(names)
        if codes is None:
            parts = [
                BASE_CODE[np.frombuffer(s.encode("ascii"), dtype=np.uint8)]
                for s in seqs
            ]
            lengths = [len(p) for p in parts]
            codes = (
                np.concatenate(parts) if parts else np.zeros(0, dtype=np.int8)
            )
        self.lengths = np.asarray(lengths, dtype=np.int64)
        self.offsets = np.concatenate([[0], np.cumsum(self.lengths)])[:-1]
        self._index = {n: i for i, n in enumerate(self.names)}
        self.codes = np.ascontiguousarray(codes, dtype=np.int8)
        if self.codes.size > MAX_GENOME:
            raise ValueError(
                f"genome of {self.codes.size} bases exceeds the uint32 "
                f"offset cap {MAX_GENOME}; shard contigs across RefStores"
            )
        self._device = None
        # overlap workers (pipeline.calling) hit the lazy upload
        # concurrently; without the lock both would device_put the whole
        # genome over the tunnel
        import threading

        self._device_lock = threading.Lock()

    @classmethod
    def from_fasta(cls, path: str) -> "RefStore":
        from bsseqconsensusreads_tpu.io.fasta import FastaFile

        with FastaFile(path) as fa:
            names = fa.references
            seqs = [fa.fetch(n) for n in names]
        return cls(names, seqs=seqs)

    @property
    def device_codes(self):
        """The genome on device (uploaded lazily, once — thread-safe)."""
        if self._device is None:
            with self._device_lock:
                if self._device is None:
                    self._device = jax.device_put(self.codes)
        return self._device

    def contig_indices(self, names) -> np.ndarray:
        """Map contig NAMES (e.g. a BAM header's reference order, which need
        not match the FASTA's) to this store's contig indices; unknown names
        map to -1 (-> NO_REF rows from window_offsets)."""
        return np.asarray(
            [self._index.get(n, -1) for n in names], dtype=np.int64
        )

    def host_windows(self, starts, limits, width: int) -> np.ndarray:
        """numpy twin of gather_windows over the HOST copy of the genome:
        int8 [F, width] windows with the same NO_REF / past-limit N
        semantics. The duplex raw-unit accounting uses this when the wire
        transport skipped the per-family host reference fetch
        (pipeline.calling._duplex_rawize needs the window to evaluate the
        conversion context host-side)."""
        starts = np.asarray(starts, dtype=np.uint32)
        limits = np.asarray(limits, dtype=np.uint32)
        idx = starts[:, None].astype(np.int64) + np.arange(width)
        valid = (starts[:, None] != NO_REF) & (
            idx < limits[:, None].astype(np.int64)
        )
        safe = np.minimum(idx, max(self.codes.size - 1, 0))
        ref = (
            self.codes[safe]
            if self.codes.size
            else np.zeros(idx.shape, np.int8)
        )
        return np.where(valid, ref, np.int8(NBASE))

    def host_windows_ext(self, starts, los, limits, width: int) -> np.ndarray:
        """numpy twin of gather_windows_ext over the HOST genome copy:
        int8 [F, width] extension windows (start - 2), N outside
        [los, limits). int64 arithmetic replaces the device's uint32 wrap —
        pre-genome columns are simply negative and fail the lower bound."""
        starts = np.asarray(starts, dtype=np.uint32)
        idx = starts[:, None].astype(np.int64) - 2 + np.arange(width)
        valid = (
            (starts[:, None] != NO_REF)
            & (idx >= np.asarray(los, dtype=np.uint32)[:, None].astype(np.int64))
            & (idx < np.asarray(limits, dtype=np.uint32)[:, None].astype(np.int64))
        )
        safe = np.clip(idx, 0, max(self.codes.size - 1, 0))
        ref = (
            self.codes[safe]
            if self.codes.size
            else np.zeros(idx.shape, np.int8)
        )
        return np.where(valid, ref, np.int8(NBASE))

    def window_origins(self, ref_ids) -> np.ndarray:
        """uint32 [F] global offset of each family's contig FIRST base —
        the lower bound of gather_windows_ext. Invalid ref_ids map to 0
        (their starts are NO_REF / limits 0, so the bound never engages)."""
        rid = np.asarray(ref_ids, dtype=np.int64)
        ok = (rid >= 0) & (rid < len(self.names))
        return np.where(ok, self.offsets[np.where(ok, rid, 0)], 0).astype(
            np.uint32
        )

    def window_offsets(self, ref_ids, window_starts):
        """Vectorized (starts, limits) uint32 arrays for gather_windows.

        ref_ids outside [0, n_contigs) or window_starts < 0 map to
        start = NO_REF (all-N row — the reference's failed-fetch fallback,
        tools/1.convert_AG_to_CT.py:106-109). Offset math runs in int64 and
        is range-checked before the uint32 narrowing."""
        rid = np.asarray(ref_ids, dtype=np.int64)
        ws = np.asarray(window_starts, dtype=np.int64)
        ok = (rid >= 0) & (rid < len(self.names)) & (ws >= 0)
        safe = np.where(ok, rid, 0)
        starts = self.offsets[safe] + ws
        ok &= starts < MAX_GENOME
        starts = np.where(ok, starts, np.int64(NO_REF))
        limits = np.where(ok, self.offsets[safe] + self.lengths[safe], 0)
        return starts.astype(np.uint32), limits.astype(np.uint32)
