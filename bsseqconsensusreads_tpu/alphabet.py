"""The single definition of the base alphabet used across the framework.

A C G T = 0..3 are vote candidates; N = 4 means "no observation" (pad, N call,
or no coverage). Every module (host encoders, JAX kernels, oracles) imports
these — never redefine them locally.
"""

import numpy as np

A, C, G, T, N = 0, 1, 2, 3, 4
NBASE = N
NUM_BASES = 4  # N is not a vote candidate

# char byte -> code (lowercase folded; anything else -> N)
BASE_CODE = np.full(256, NBASE, dtype=np.int8)
for _i, _b in enumerate(b"ACGT"):
    BASE_CODE[_b] = _i
    BASE_CODE[_b + 32] = _i
# code -> char byte
BASE_CHAR = np.frombuffer(b"ACGTN", dtype=np.uint8)

COMPLEMENT = np.array([T, G, C, A, N], dtype=np.int8)
