"""Interop/compat layer: run third-party pysam scripts on the first-party
io stack (no pysam/htslib in this environment).

The point (SURVEY.md §4 plan item 1): golden differential testing — execute
the ACTUAL reference tools (tools/1.convert_AG_to_CT.py,
tools/2.extend_gap.py, pure Python+pysam) against synthetic BAMs via this
shim and diff their output record-for-record against the framework's JAX
transforms, removing the shared-blind-spot risk of self-authored oracles.
"""

from bsseqconsensusreads_tpu.compat.pysam_shim import install_shim
from bsseqconsensusreads_tpu.compat.refrunner import run_pysam_script

__all__ = ["install_shim", "run_pysam_script"]
