"""Load and invoke a pysam/click CLI script through the pysam shim.

Built for the golden-differential tests: the reference's tools
(tools/1.convert_AG_to_CT.py with CLI at :29-33, tools/2.extend_gap.py at
:142-145) are plain Python scripts whose ``main`` is a click command; this
loads such a script as a module (shim pre-installed) and calls the
undecorated callback directly, so the ACTUAL third-party code runs against
first-party BAM/FASTA I/O.
"""

from __future__ import annotations

import importlib.util
import os
import sys

from bsseqconsensusreads_tpu.compat.pysam_shim import install_shim


def load_pysam_script(path: str, module_name: str | None = None):
    """Import a pysam-dependent script file with the shim active."""
    install_shim()
    if module_name is None:
        base = os.path.basename(path)
        module_name = "refshim_" + "".join(
            c if c.isalnum() else "_" for c in base
        )
    spec = importlib.util.spec_from_file_location(module_name, path)
    if spec is None or spec.loader is None:
        raise ImportError(f"cannot load {path}")
    mod = importlib.util.module_from_spec(spec)
    # register before exec so decorators resolving __module__ work
    sys.modules[module_name] = mod
    spec.loader.exec_module(mod)
    return mod


def run_pysam_script(path: str, /, **kwargs):
    """Run the script's ``main`` (click command or plain function) with
    keyword arguments matching its parameters. Returns the callback's
    return value."""
    mod = load_pysam_script(path)
    main = getattr(mod, "main")
    fn = getattr(main, "callback", main)  # unwrap a click.Command
    return fn(**kwargs)
