"""Minimal pysam-compatible module over the first-party io stack.

Covers exactly the API surface the reference's two tools use
(tools/1.convert_AG_to_CT.py, tools/2.extend_gap.py):

* ``AlignmentFile(path, 'rb')`` — iterate ``AlignedSegment``s, ``.header``,
  ``get_reference_name``, context manager, ``close``;
* ``AlignmentFile(path, 'wb', template=... | header=...)`` — ``write``;
* ``AlignedSegment`` — flag / pos / reference_start / reference_id /
  reference_end / query_name / query_sequence / seq / qual /
  query_qualities / cigartuples / get_tag / set_tag / has_tag, with
  pysam's mutation semantics (assigning a sequence clears the stored
  qualities — tools/2.extend_gap.py depends on restoring them afterwards
  via ``.qual = ...``);
* ``FastaFile.fetch(name, start, end)`` with pysam's end-clamping;
* CIGAR op constants and a ``bcftools`` placeholder
  (tools/1.convert_AG_to_CT.py imports it and never uses it).

This is NOT a general pysam replacement; unsupported attributes raise
AttributeError so a parity test can never silently diverge.
"""

from __future__ import annotations

import sys
import types

from bsseqconsensusreads_tpu.io import fasta as _fasta
from bsseqconsensusreads_tpu.io.bam import BamHeader, BamReader, BamRecord, BamWriter

# pysam/htslib CIGAR op codes
CMATCH = 0
CINS = 1
CDEL = 2
CREF_SKIP = 3
CSOFT_CLIP = 4
CHARD_CLIP = 5
CPAD = 6
CEQUAL = 7
CDIFF = 8

_REF_CONSUMING = {CMATCH, CDEL, CREF_SKIP, CEQUAL, CDIFF}


class AlignedSegment:
    """Mutable record view with pysam attribute names and semantics."""

    def __init__(self, rec: BamRecord | None = None):
        rec = rec if rec is not None else BamRecord()
        self.query_name = rec.qname
        self.flag = rec.flag
        self.reference_id = rec.ref_id
        self.reference_start = rec.pos
        self.mapping_quality = rec.mapq
        self.next_reference_id = rec.next_ref_id
        self.next_reference_start = rec.next_pos
        self.template_length = rec.tlen
        self._seq = rec.seq or ""
        # BAM stores raw phred; pysam exposes them as an int sequence
        self._quals: list[int] | None = list(rec.qual) if rec.qual else None
        self._cigar = list(rec.cigar) if rec.cigar else []
        self._tags = dict(rec.tags)

    # --- positions ---------------------------------------------------------

    @property
    def pos(self) -> int:
        return self.reference_start

    @pos.setter
    def pos(self, value: int) -> None:
        self.reference_start = value

    @property
    def reference_end(self):
        if self.reference_start < 0 or not self._cigar:
            return None
        span = sum(n for op, n in self._cigar if op in _REF_CONSUMING)
        return self.reference_start + span

    # --- sequence / qualities ---------------------------------------------

    @property
    def query_sequence(self) -> str:
        return self._seq

    @query_sequence.setter
    def query_sequence(self, value) -> None:
        # pysam semantics: assigning a sequence invalidates the stored
        # qualities (the caller must re-assign them)
        self._seq = value or ""
        self._quals = None

    @property
    def seq(self) -> str:
        return self._seq

    @seq.setter
    def seq(self, value) -> None:
        self.query_sequence = value

    @property
    def query_qualities(self):
        return self._quals

    @query_qualities.setter
    def query_qualities(self, value) -> None:
        self._quals = None if value is None else [int(q) for q in value]

    @property
    def qual(self):
        """Phred+33 string view (legacy pysam accessor the tools use)."""
        if self._quals is None:
            return None
        return "".join(chr(q + 33) for q in self._quals)

    @qual.setter
    def qual(self, value) -> None:
        self._quals = None if value is None else [ord(c) - 33 for c in value]

    # --- cigar -------------------------------------------------------------

    @property
    def cigartuples(self):
        return self._cigar if self._cigar else None

    @cigartuples.setter
    def cigartuples(self, value) -> None:
        self._cigar = [(int(op), int(n)) for op, n in value] if value else []

    @property
    def cigar(self):
        """Legacy pysam alias (tools/1.convert_AG_to_CT.py:181 assigns it)."""
        return self.cigartuples

    @cigar.setter
    def cigar(self, value) -> None:
        self.cigartuples = value

    # --- tags --------------------------------------------------------------

    def get_tag(self, name: str):
        return self._tags[name][1]

    def has_tag(self, name: str) -> bool:
        return name in self._tags

    def set_tag(self, name: str, value, value_type: str = "i") -> None:
        if value is None:
            self._tags.pop(name, None)
            return
        if value_type == "i":
            value = int(value)
        self._tags[name] = (value_type, value)

    # --- conversion --------------------------------------------------------

    def to_record(self) -> BamRecord:
        quals = self._quals
        if quals is None:
            # BAM convention for absent qualities: 0xFF fill
            qual_bytes = bytes([0xFF] * len(self._seq))
        else:
            qual_bytes = bytes(int(q) & 0xFF for q in quals)
        return BamRecord(
            qname=self.query_name,
            flag=self.flag,
            ref_id=self.reference_id,
            pos=self.reference_start,
            mapq=self.mapping_quality,
            cigar=list(self._cigar),
            next_ref_id=self.next_reference_id,
            next_pos=self.next_reference_start,
            tlen=self.template_length,
            seq=self._seq,
            qual=qual_bytes,
            tags=dict(self._tags),
        )


class AlignmentFile:
    def __init__(self, path: str, mode: str = "rb", template=None, header=None):
        self._path = path
        self._mode = mode
        if mode == "rb":
            self._reader = BamReader(path)
            self.header = self._reader.header
            self._writer = None
        elif mode == "wb":
            if header is None and template is not None:
                header = template.header
            if header is None:
                raise ValueError("AlignmentFile('wb') needs template= or header=")
            if not isinstance(header, BamHeader):
                raise TypeError(f"unsupported header object {type(header)!r}")
            self.header = header
            self._writer = BamWriter(path, header)
            self._reader = None
        else:
            raise ValueError(f"unsupported mode {mode!r}")

    def __iter__(self):
        for rec in self._reader:
            yield AlignedSegment(rec)

    def get_reference_name(self, rid: int) -> str:
        return self.header.ref_name(rid)

    def write(self, seg: AlignedSegment) -> None:
        self._writer.write(seg.to_record())

    def close(self) -> None:
        if self._reader is not None:
            self._reader.close()
        if self._writer is not None:
            self._writer.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class FastaFile:
    def __init__(self, path: str):
        self._fa = _fasta.FastaFile(path)

    def fetch(self, reference: str, start: int = 0, end: int | None = None) -> str:
        # first-party fetch already clamps end past the contig like pysam
        return self._fa.fetch(reference, start, end)

    @property
    def references(self):
        return self._fa.references

    def close(self) -> None:
        self._fa.close()


def build_module() -> types.ModuleType:
    """A module object that quacks like ``pysam`` for the reference tools."""
    mod = types.ModuleType("pysam")
    mod.AlignmentFile = AlignmentFile
    mod.AlignedSegment = AlignedSegment
    mod.FastaFile = FastaFile
    for name in (
        "CMATCH", "CINS", "CDEL", "CREF_SKIP", "CSOFT_CLIP", "CHARD_CLIP",
        "CPAD", "CEQUAL", "CDIFF",
    ):
        setattr(mod, name, globals()[name])
    # imported (never used) by tools/1.convert_AG_to_CT.py
    mod.bcftools = types.ModuleType("pysam.bcftools")
    return mod


def install_shim() -> types.ModuleType:
    """Register the shim as ``pysam`` (and ``rich_click`` -> click, which is
    API-compatible for the decorators the tools use) in sys.modules.
    No-op when a real pysam is importable (installed OR already imported) —
    never shadow a real installation process-wide."""
    if "pysam" not in sys.modules:
        import importlib.util

        if importlib.util.find_spec("pysam") is None:
            mod = build_module()
            sys.modules["pysam"] = mod
            sys.modules["pysam.bcftools"] = mod.bcftools
        else:
            import pysam  # noqa: F401  (real installation wins)
    if "rich_click" not in sys.modules:
        try:
            import rich_click  # noqa: F401
        except ImportError:
            import click

            sys.modules["rich_click"] = click
    return sys.modules["pysam"]
