"""graftlint rules for TPU/jax-hostile code: unaccounted host syncs on
the hot path, jit recompile hazards, tracer leaks, and set-order shapes.

All four rules work from the same premise as the run ledger: the batch
loop's time must be attributable. A host sync the ledger can't see
(`host-sync`), a silent recompile (`jit-recompile`), a trace-time crash
(`tracer-leak`), or a shape that changes with hash seed
(`unordered-shape-iter`) each breaks that in a different way.
"""

from __future__ import annotations

import ast
from typing import Iterator

from bsseqconsensusreads_tpu.analysis.engine import (
    Finding,
    PackageIndex,
    Rule,
    SourceFile,
    call_basename,
    is_jit_expr,
)

#: Call basenames that force a device->host synchronization when handed
#: a device value.
SYNC_CONVERTERS = frozenset(
    {"asarray", "array", "device_get", "float", "int", "bool"}
)

#: Expression markers that make a derived value host/static (shapes,
#: dtypes and lengths are Python ints even on tracers).
_STATIC_ATTRS = frozenset({"shape", "ndim", "dtype", "size"})


def _names_in(expr: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}


def _assign_targets(node: ast.AST) -> list[str]:
    """Plain-name targets of an Assign/AugAssign/For/comprehension/with."""
    out: list[str] = []

    def grab(t: ast.AST) -> None:
        if isinstance(t, ast.Name):
            out.append(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                grab(e)
        elif isinstance(t, ast.Starred):
            grab(t.value)

    if isinstance(node, ast.Assign):
        for t in node.targets:
            grab(t)
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        grab(node.target)
    elif isinstance(node, (ast.For, ast.AsyncFor, ast.comprehension)):
        grab(node.target)
    elif isinstance(node, ast.withitem) and node.optional_vars is not None:
        grab(node.optional_vars)
    return out


def _walk_tree(func: ast.AST) -> Iterator[ast.AST]:
    """Whole nested tree of a function (closures share its scope)."""
    yield from ast.walk(func)


def _device_names(func: ast.AST, index: PackageIndex) -> set[str]:
    """Names in `func`'s scope (closures included) bound to device
    values: results of calls to jit-decorated functions, to locals bound
    from jit-callable factories, or to jax.device_put. Propagates
    through assignments, tuple packs, and iteration (``for v in
    out.items()`` taints v), but stops at .shape/len()-style reads."""
    jit_defs = index.jit_def_basenames
    factories = index.factory_basenames

    jit_callables: set[str] = set()
    device: set[str] = set()
    # two passes: callable bindings settle first, then value taint flows
    # through straight-line and (second pass) loop-carried assignments
    for _ in range(2):
        for node in _walk_tree(func):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                value = node.value
                targets = _assign_targets(node)
                if isinstance(value, ast.Name) and value.id in (
                    jit_defs | jit_callables
                ):
                    jit_callables.update(targets)
                    continue
                if isinstance(value, ast.Call):
                    base = call_basename(value)
                    if base in factories:
                        jit_callables.update(targets)
                        continue
                produces = False
                for sub in ast.walk(value):
                    if isinstance(sub, ast.Call):
                        base = call_basename(sub)
                        if base in jit_defs or base in jit_callables or (
                            base == "device_put"
                        ):
                            produces = True
                if produces or (_names_in(value) & device and not _is_static_read(value)):
                    device.update(targets)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                if _names_in(node.iter) & device:
                    device.update(_assign_targets(node))
            elif isinstance(node, ast.comprehension):
                if _names_in(node.iter) & device:
                    device.update(_assign_targets(node))
    return device


def _is_static_read(expr: ast.AST) -> bool:
    """True when expr only reads host/static facts off a value: shapes,
    dtypes, len(), isinstance()."""
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Attribute) and sub.attr in _STATIC_ATTRS:
            return True
        if isinstance(sub, ast.Call):
            if call_basename(sub) in ("len", "isinstance"):
                return True
    return False


def check_host_sync(sf: SourceFile, index: PackageIndex) -> Iterator[Finding]:
    """host-sync: device->host synchronization on a batch-loop-reachable
    path outside an accounted ledger span."""
    seen_funcs: set[ast.AST] = set()
    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        fi = index.info(node)
        if fi is None or fi.qualname not in index.hot_reachable:
            continue
        # analyze at top-level-function granularity: nested defs share
        # the enclosing scope's bindings
        if sf.enclosing_functions(node):
            continue
        if node in seen_funcs:
            continue
        seen_funcs.add(node)
        device = _device_names(node, index)
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            if sf.in_accounted_span(sub):
                continue
            base = call_basename(sub)
            flagged = None
            if (
                isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "block_until_ready"
            ):
                flagged = "block_until_ready() outside a ledger span"
            elif (
                isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "item"
                and _names_in(sub.func.value) & device
            ):
                flagged = ".item() on a device value"
            elif base in SYNC_CONVERTERS and sub.args and (
                _names_in(sub.args[0]) & device
            ):
                flagged = f"{base}() on a device value"
            if flagged:
                yield Finding(
                    rule="host-sync",
                    path=sf.display,
                    line=sub.lineno,
                    col=sub.col_offset,
                    message=(
                        f"{flagged} in batch-loop-reachable code — the "
                        "chip stalls here invisibly; move it under "
                        "`with metrics.timed(\"device_wait\")` (or "
                        "another accounted span) or off the hot path"
                    ),
                )


def check_jit_recompile(sf: SourceFile, index: PackageIndex) -> Iterator[Finding]:
    """jit-recompile: per-iteration jax.jit, closures over mutated
    Python values, unhashable static args."""
    for node in ast.walk(sf.tree):
        # (a) jax.jit(...) lexically inside a loop: a fresh callable (and
        # compile cache entry) per iteration
        if isinstance(node, ast.Call) and is_jit_expr(node):
            cur = sf.parents.get(node)
            while cur is not None:
                if isinstance(cur, (ast.For, ast.While, ast.AsyncFor)):
                    yield Finding(
                        rule="jit-recompile",
                        path=sf.display,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            "jax.jit called inside a loop — every "
                            "iteration builds a fresh callable and "
                            "recompiles; hoist the jit (or cache it, cf. "
                            "models.molecular._packed_kernel_cached)"
                        ),
                    )
                    break
                cur = sf.parents.get(cur)

        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        fi = index.info(node)
        if fi is None or not fi.is_jit:
            continue

        # (c) static param with an unhashable default
        args = node.args
        defaults = dict(
            zip([a.arg for a in args.args][len(args.args) - len(args.defaults):],
                args.defaults)
        )
        defaults.update(
            {a.arg: d for a, d in zip(args.kwonlyargs, args.kw_defaults)
             if d is not None}
        )
        for name in fi.static_names:
            d = defaults.get(name)
            if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                yield Finding(
                    rule="jit-recompile",
                    path=sf.display,
                    line=d.lineno,
                    col=d.col_offset,
                    message=(
                        f"static arg {name!r} defaults to an unhashable "
                        f"{type(d).__name__.lower()} — jit static args "
                        "must hash; use a tuple/frozen value"
                    ),
                )

        # (b) jitted closure over a name the enclosing scope mutates
        enclosing = sf.enclosing_functions(node)
        if not enclosing:
            continue
        outer = enclosing[0]
        bound = set()
        for a in node.args.posonlyargs + node.args.args + node.args.kwonlyargs:
            bound.add(a.arg)
        for sub in ast.walk(node):
            bound.update(_assign_targets(sub))
        free = {
            n.id
            for n in ast.walk(node)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
        } - bound
        for sub in ast.walk(outer):
            if sub is node:
                continue
            if isinstance(sub, ast.AugAssign):
                hits = set(_assign_targets(sub)) & free
                for name in hits:
                    yield Finding(
                        rule="jit-recompile",
                        path=sf.display,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"jitted function closes over {name!r}, which "
                            "the enclosing scope mutates — the traced "
                            "value is baked at first call (stale results, "
                            "or a retrace per cache miss); pass it as an "
                            "argument instead"
                        ),
                    )


def _annotation_is_hostlike(ann: ast.AST | None) -> bool:
    """Annotated params are treated as non-traced unless the annotation
    names an array type — config objects, ints and strs under jit are
    (or must be) static."""
    if ann is None:
        return False
    src = ast.unparse(ann)
    return not ("Array" in src or "ndarray" in src or "Tensor" in src)


def _is_none_check(test: ast.AST) -> bool:
    """`x is None` / `x is not None` (and and/or/not combinations of
    them) test argument *structure*, not traced values — the standard
    jax idiom for optional operands (cf. ops.extend.extend_gap's
    `eligible` gate)."""
    if isinstance(test, ast.Compare):
        return all(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops)
    if isinstance(test, ast.BoolOp):
        return all(_is_none_check(v) for v in test.values)
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _is_none_check(test.operand)
    return False


def check_tracer_leak(sf: SourceFile, index: PackageIndex) -> Iterator[Finding]:
    """tracer-leak: Python control flow / bool coercion on traced values
    inside jit-decorated functions."""
    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        fi = index.info(node)
        if fi is None or not fi.is_jit:
            continue
        traced: set[str] = set()
        for a in node.args.posonlyargs + node.args.args + node.args.kwonlyargs:
            if a.arg in fi.static_names or _annotation_is_hostlike(a.annotation):
                continue
            traced.add(a.arg)
        # propagate through assignments, stopping at static reads
        for _ in range(2):
            for sub in ast.walk(node):
                if isinstance(sub, (ast.Assign, ast.AugAssign)):
                    if _names_in(sub.value) & traced and not _is_static_read(
                        sub.value
                    ):
                        traced.update(_assign_targets(sub))
        for sub in ast.walk(node):
            test = None
            what = None
            if isinstance(sub, (ast.If, ast.While)):
                test, what = sub.test, type(sub).__name__.lower()
            elif isinstance(sub, ast.Assert):
                test, what = sub.test, "assert"
            elif isinstance(sub, ast.Call) and call_basename(sub) == "bool":
                test, what = sub, "bool()"
            if test is None:
                continue
            if _is_static_read(test) or _is_none_check(test):
                continue
            hits = _names_in(test) & traced
            if hits:
                yield Finding(
                    rule="tracer-leak",
                    path=sf.display,
                    line=sub.lineno,
                    col=sub.col_offset,
                    message=(
                        f"Python {what} on traced value(s) "
                        f"{sorted(hits)} inside a jitted function — "
                        "this raises TracerBoolConversionError at trace "
                        "time (or silently bakes one branch); use "
                        "jnp.where / lax.cond"
                    ),
                )


def _setish(expr: ast.AST, set_names: set[str]) -> bool:
    if isinstance(expr, (ast.Set, ast.SetComp)):
        return True
    if isinstance(expr, ast.Call) and call_basename(expr) in ("set", "frozenset"):
        return True
    if isinstance(expr, ast.Name) and expr.id in set_names:
        return True
    if isinstance(expr, ast.BinOp):  # s1 | s2 unions
        return _setish(expr.left, set_names) or _setish(expr.right, set_names)
    return False


def check_unordered_iter(sf: SourceFile, index: PackageIndex) -> Iterator[Finding]:
    """unordered-shape-iter: iterating a set on a hot/jit-reachable path
    — order varies with hash seed, so anything shape-bearing downstream
    (bucket boundaries, pad widths, device placement) recompiles or
    diverges between hosts of a multi-host job."""
    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        fi = index.info(node)
        if fi is None:
            continue
        if (
            fi.qualname not in index.hot_reachable
            and fi.qualname not in index.jit_reachable
        ):
            continue
        set_names: set[str] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) and _setish(sub.value, set_names):
                set_names.update(_assign_targets(sub))
        for sub in ast.walk(node):
            iters = []
            if isinstance(sub, (ast.For, ast.AsyncFor)):
                iters = [sub.iter]
            elif isinstance(sub, ast.comprehension):
                iters = [sub.iter]
            for it in iters:
                if _setish(it, set_names):
                    yield Finding(
                        rule="unordered-shape-iter",
                        path=sf.display,
                        line=it.lineno,
                        col=it.col_offset,
                        message=(
                            "iterating a set on a hot/jit-reachable path "
                            "— iteration order follows the hash seed, so "
                            "downstream batch shapes and device placement "
                            "become run-dependent; iterate "
                            "sorted(...) instead"
                        ),
                    )


RULES = [
    Rule(
        name="host-sync",
        summary="device->host sync on the batch loop outside an "
        "accounted ledger span",
        check=check_host_sync,
    ),
    Rule(
        name="jit-recompile",
        summary="per-iteration jax.jit, mutated closure, or unhashable "
        "static arg",
        check=check_jit_recompile,
    ),
    Rule(
        name="tracer-leak",
        summary="Python control flow or bool() on a traced value under jit",
        check=check_tracer_leak,
    ),
    Rule(
        name="unordered-shape-iter",
        summary="set iteration feeding shapes on a hot/jit path",
        check=check_unordered_iter,
    ),
]
