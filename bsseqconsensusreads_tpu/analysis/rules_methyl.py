"""graftlint rule guarding the fused methylation extraction (PR 10).

`unfused-methyl-scan` flags a host-side per-record scan over consensus
base planes on a methyl-reachable hot path: a Python `for` loop that
subscripts a plane array (`bases` / `planes` / `cover` / ...) with its
own loop variable, one record or one site at a time. The methyl
subsystem's contract is that per-column classification and counting
happen INSIDE the vote kernel epilogue (methyl.context.methyl_epilogue,
device or vectorized numpy twin) and only dense [F, 2, W] tallies cross
to the host — a per-record loop re-deriving calls from the planes is
the unfused scan the subsystem exists to delete, and it serializes the
batch loop behind Python interpretation of device-shaped data.

Scope is deliberately narrow: the loop must be hot-path-reachable
(batch-loop roots, engine.HOT_PATH_ROOTS) AND methyl-scoped — in a
`methyl` package file or inside a function whose name says methyl.
The cold emit surface (methyl/emit.py's per-site text writers) runs
once at finalize, off the batch loop, and stays clean by scoping, not
by suppression.
"""

from __future__ import annotations

import ast
import os
from typing import Iterator

from bsseqconsensusreads_tpu.analysis.engine import (
    Finding,
    PackageIndex,
    Rule,
    SourceFile,
)

#: Array names that carry per-column consensus evidence ([F, R, W] base
#: planes and their methyl products). Subscripting one of these with a
#: loop variable is the per-record scan signature.
_PLANE_NAMES = frozenset(
    {"bases", "planes", "mplanes", "quals", "cover", "cons", "cons_base"}
)

#: Function-name fragment that marks methyl scope outside the package.
_SCOPE_FRAGMENT = "methyl"


def _in_methyl_file(sf: SourceFile) -> bool:
    parts = sf.display.replace(os.sep, "/").split("/")
    return "methyl" in parts[:-1]


def _in_scope(sf: SourceFile, node: ast.AST) -> bool:
    if _in_methyl_file(sf):
        return True
    return any(
        _SCOPE_FRAGMENT in func.name.lower()
        for func in sf.enclosing_functions(node)
    )


def _loop_target_names(target: ast.AST) -> set[str]:
    return {
        sub.id for sub in ast.walk(target) if isinstance(sub, ast.Name)
    }


def _plane_base_name(value: ast.AST) -> str | None:
    """`planes[...]` and `self.planes[...]` both count; deeper chains
    (`batch.meta[i]`) resolve by the final attribute name."""
    if isinstance(value, ast.Name):
        return value.id
    if isinstance(value, ast.Attribute):
        return value.attr
    return None


def check_unfused_methyl_scan(
    sf: SourceFile, index: PackageIndex
) -> Iterator[Finding]:
    """unfused-methyl-scan: hot-path `for` loop subscripting a consensus
    plane array with its loop variable inside methyl scope."""
    for loop in ast.walk(sf.tree):
        if not isinstance(loop, ast.For):
            continue
        if not _in_scope(sf, loop):
            continue
        if not index.in_hot_path(sf, loop):
            continue
        targets = _loop_target_names(loop.target)
        if not targets:
            continue
        for node in ast.walk(loop):
            if not isinstance(node, ast.Subscript):
                continue
            if _plane_base_name(node.value) not in _PLANE_NAMES:
                continue
            idx_names = {
                sub.id
                for sub in ast.walk(node.slice)
                if isinstance(sub, ast.Name)
            }
            if not (idx_names & targets):
                continue
            yield Finding(
                rule="unfused-methyl-scan",
                path=sf.display,
                line=node.lineno,
                col=node.col_offset,
                message=(
                    "host-side per-record scan over consensus base "
                    "planes on a methyl-reachable hot path: "
                    "classification and counting belong in the fused "
                    "kernel epilogue (methyl.context.methyl_epilogue) "
                    "or its vectorized numpy twin — only dense tallies "
                    "should cross the batch loop"
                ),
            )
            break  # one finding per loop


RULES = [
    Rule(
        name="unfused-methyl-scan",
        summary="per-record Python loop over consensus base planes on a "
        "methyl hot path",
        check=check_unfused_methyl_scan,
    ),
]
