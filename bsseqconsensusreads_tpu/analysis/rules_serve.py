"""graftlint serve-discipline rule: blocking scheduler loops.

The failure class the resident engine (serve/) introduces: a scheduler,
retire, or accept loop that parks on an unbounded blocking primitive —
`time.sleep` instead of an interruptible `Event.wait(timeout)`, a
`queue.Queue()` with no maxsize (one slow tenant backlogs the process
into OOM instead of exerting backpressure at submit), or a
`.get()`/`.put()`/`.join()`/`.wait()`/`.acquire()` with no timeout
inside a polling loop (drain and SIGTERM can then never preempt the
wait, so "graceful shutdown" hangs forever). The sanctioned shapes are
bounded queues, `get_nowait` + wake events, and timeout-sliced waits
re-checked against stop/drain flags each lap.

Scope: files under a `serve` package directory, plus functions anywhere
whose name says they are a scheduler/serve/retire loop. Loops outside
that scope are other rules' business — a worker thread may legitimately
block forever on its feed queue.

A second rule covers the shutdown half of the same failure class:
`unbounded-drain-wait` flags blocking primitives with no timeout bound
inside drain-, preemption-, or signal-reachable functions anywhere in
the tree — a graceful-exit path that can park forever converts a
bounded-handoff guarantee into a hang the supervisor must SIGKILL out
of, losing the checkpoint flush the drain existed to protect.
"""

from __future__ import annotations

import ast
import os
from typing import Iterator

from bsseqconsensusreads_tpu.analysis.engine import (
    Finding,
    PackageIndex,
    Rule,
    SourceFile,
    call_basename,
)

#: Function-name fragments that mark a scheduler/serve/retire loop
#: wherever it lives.
_SCOPE_NAME_FRAGMENTS = ("scheduler", "serve", "retire")

#: Blocking primitives that must carry a timeout inside a polling loop.
#: (`accept`/`recv` are deliberately absent: socket loops bound those
#: with `settimeout` on the socket, which this AST pass cannot see.)
_BLOCKING_ATTRS = frozenset({"get", "put", "join", "wait", "acquire"})

#: Positional-argument count at which the call is bounded even without
#: a `timeout=` keyword (e.g. `ev.wait(0.25)`, `q.get(True, 0.25)`).
_BOUND_BY_ARGC = {"wait": 1, "join": 1, "get": 2, "put": 3, "acquire": 2}


def _in_serve_file(sf: SourceFile) -> bool:
    parts = sf.display.replace(os.sep, "/").split("/")
    return "serve" in parts[:-1]


def _scoped_function(name: str) -> bool:
    low = name.lower()
    return any(frag in low for frag in _SCOPE_NAME_FRAGMENTS)


def _in_scope(sf: SourceFile, node: ast.AST) -> bool:
    if _in_serve_file(sf):
        return True
    return any(
        _scoped_function(func.name)
        for func in sf.enclosing_functions(node)
    )


def _is_bounded(call: ast.Call, attr: str) -> bool:
    if any(kw.arg == "timeout" for kw in call.keywords):
        return True
    return len(call.args) >= _BOUND_BY_ARGC.get(attr, 99)


def check_blocking_scheduler_loop(
    sf: SourceFile, index: PackageIndex
) -> Iterator[Finding]:
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call):
            # unbounded queue anywhere in scope: no maxsize, no capacity
            if (
                call_basename(node) == "Queue"
                and not node.args
                and not any(kw.arg == "maxsize" for kw in node.keywords)
                and _in_scope(sf, node)
            ):
                yield Finding(
                    rule="blocking-scheduler-loop",
                    path=sf.display,
                    line=node.lineno,
                    col=node.col_offset,
                    message=(
                        "unbounded queue.Queue() on a serve path — with "
                        "no maxsize a slow tenant backlogs the resident "
                        "process into OOM; give the queue a capacity so "
                        "backpressure lands at submit time"
                    ),
                )
            continue
        if not isinstance(node, ast.While):
            continue
        if not _in_scope(sf, node):
            continue
        for sub in PackageIndex._own_nodes(node):
            if not isinstance(sub, ast.Call):
                continue
            base = call_basename(sub)
            if base == "sleep":
                yield Finding(
                    rule="blocking-scheduler-loop",
                    path=sf.display,
                    line=sub.lineno,
                    col=sub.col_offset,
                    message=(
                        "time.sleep inside a scheduler/retire loop — "
                        "drain and SIGTERM cannot preempt a sleep; poll "
                        "with Event.wait(timeout) and re-check the stop "
                        "flag each lap"
                    ),
                )
            elif (
                isinstance(sub.func, ast.Attribute)
                and sub.func.attr in _BLOCKING_ATTRS
                and not _is_bounded(sub, sub.func.attr)
            ):
                yield Finding(
                    rule="blocking-scheduler-loop",
                    path=sf.display,
                    line=sub.lineno,
                    col=sub.col_offset,
                    message=(
                        f".{sub.func.attr}() with no timeout inside a "
                        "scheduler/retire loop — an unbounded wait here "
                        "wedges graceful drain; pass timeout= and loop "
                        "on the deadline"
                    ),
                )


#: Function-name fragments that mark a drain-/preemption-/signal-
#: reachable path wherever it lives. Deliberately narrower than "stop":
#: a `stop()` may block on work completion by design, but anything
#: named for drain, preemption, or signal handling has promised a
#: bounded exit.
_DRAIN_NAME_FRAGMENTS = (
    "drain",
    "preempt",
    "shutdown",
    "sigterm",
    "sigint",
    "on_signal",
    "reap",
    "handoff",
    "teardown",
)


def _drain_scoped(sf: SourceFile, node: ast.AST) -> bool:
    return any(
        any(frag in func.name.lower() for frag in _DRAIN_NAME_FRAGMENTS)
        for func in sf.enclosing_functions(node)
    )


def check_unbounded_drain_wait(
    sf: SourceFile, index: PackageIndex
) -> Iterator[Finding]:
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        if not (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _BLOCKING_ATTRS
        ):
            continue
        if _is_bounded(node, node.func.attr):
            continue
        if node.func.attr == "get" and node.args:
            # q.get() is the canonical unbounded form; a positional
            # argument here is almost always a mapping key — dict.get
            # lookups are not blocking waits
            continue
        if not _drain_scoped(sf, node):
            continue
        yield Finding(
            rule="unbounded-drain-wait",
            path=sf.display,
            line=node.lineno,
            col=node.col_offset,
            message=(
                f".{node.func.attr}() with no timeout on a drain/"
                "preempt/signal path — a graceful exit that can park "
                "forever forfeits the bounded-handoff guarantee and "
                "ends in SIGKILL; pass timeout= and escalate on lapse"
            ),
        )


RULES = [
    Rule(
        name="blocking-scheduler-loop",
        summary="unbounded queue / blocking wait / sleep inside "
        "scheduler, retire, or serve loops",
        check=check_blocking_scheduler_loop,
    ),
    Rule(
        name="unbounded-drain-wait",
        summary="blocking wait with no timeout inside drain-, "
        "preemption-, or signal-reachable functions",
        check=check_unbounded_drain_wait,
    ),
]
