"""graftlint rules for thread discipline in worker callables.

The overlap engine (pipeline.calling's ThreadPoolExecutor), the
heartbeat daemon (parallel.multihost.WorkerHeartbeat) and the native
codec drivers all run Python code off the main thread. Two rules guard
the two failure modes reviews keep finding there: shared state mutated
without the lock, and exceptions that die silently inside a worker
(the pool swallows them until .result(), a bare Thread forever).
"""

from __future__ import annotations

import ast
from typing import Iterator

from bsseqconsensusreads_tpu.analysis.engine import (
    Finding,
    PackageIndex,
    Rule,
    SourceFile,
)
from bsseqconsensusreads_tpu.analysis.rules_jax import _assign_targets

#: Attribute-chain substrings that mark sanctioned per-thread storage
#: (threading.local and friends) — mutation there is the *fix* for
#: shared state, not an instance of it.
_THREAD_LOCAL_MARKERS = ("tls", "thread_local", "threadlocal", "_local")


def _attr_base_name(target: ast.AST) -> tuple[str | None, str]:
    """For an Attribute target, the base-most Name and the full dotted
    source ('self._seq' -> ('self', 'self._seq'))."""
    src = ast.unparse(target)
    cur = target
    while isinstance(cur, ast.Attribute):
        cur = cur.value
    if isinstance(cur, ast.Name):
        return cur.id, src
    return None, src


def _local_names(func: ast.AST) -> set[str]:
    """Names bound inside the function: params + assignment/for/with
    targets (nested defs included — they share the worker's frame only
    via closure, but a name bound anywhere local is not shared state)."""
    out: set[str] = set()
    assert isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef))
    for a in (
        func.args.posonlyargs
        + func.args.args
        + func.args.kwonlyargs
        + ([func.args.vararg] if func.args.vararg else [])
        + ([func.args.kwarg] if func.args.kwarg else [])
    ):
        out.add(a.arg)
    for sub in ast.walk(func):
        out.update(_assign_targets(sub))
        if isinstance(sub, ast.withitem):
            out.update(_assign_targets(sub))
    return out


def check_thread_mutation(sf: SourceFile, index: PackageIndex) -> Iterator[Finding]:
    """thread-unsafe-mutation: attribute assignment on shared objects
    (self, closures, globals) inside worker-reachable code without an
    enclosing `with <lock>:`."""
    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        fi = index.info(node)
        if fi is None or fi.qualname not in index.worker_reachable:
            continue
        if node.name in ("__init__", "__post_init__", "__setattr__") or any(
            isinstance(d, ast.Attribute) and d.attr == "setter"
            for d in node.decorator_list
        ):
            # constructors and property setters mutate the object they
            # were handed — confinement there is the caller's contract,
            # not this function's
            continue
        local = _local_names(node)
        for sub in PackageIndex._own_nodes(node):
            if isinstance(sub, ast.Assign):
                targets = sub.targets
            elif isinstance(sub, ast.AugAssign):
                targets = [sub.target]
            else:
                continue
            for t in targets:
                if not isinstance(t, ast.Attribute):
                    continue
                base, dotted = _attr_base_name(t)
                if base is None:
                    continue
                lowered = dotted.lower()
                if any(m in lowered for m in _THREAD_LOCAL_MARKERS):
                    continue  # threading.local storage is per-thread
                shared = base == "self" or base not in local
                if not shared:
                    continue
                if sf.in_lock_block(sub):
                    continue
                yield Finding(
                    rule="thread-unsafe-mutation",
                    path=sf.display,
                    line=sub.lineno,
                    col=sub.col_offset,
                    message=(
                        f"assignment to shared attribute {dotted!r} in "
                        "worker-reachable code without holding a lock — "
                        "concurrent workers race here; guard it with the "
                        "owning object's lock (cf. observe.Metrics."
                        "_accumulate) or move the write to the main "
                        "thread"
                    ),
                )


def check_swallowed_exception(
    sf: SourceFile, index: PackageIndex
) -> Iterator[Finding]:
    """swallowed-exception: an except handler whose body is only
    pass/continue inside worker-reachable code — the pool already defers
    exceptions to .result(); a handler that also eats them leaves no
    trace anywhere."""
    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        fi = index.info(node)
        if fi is None or fi.qualname not in index.worker_reachable:
            continue
        for sub in PackageIndex._own_nodes(node):
            if not isinstance(sub, ast.ExceptHandler):
                continue
            body = [s for s in sub.body if not isinstance(s, ast.Expr) or not (
                isinstance(s.value, ast.Constant)  # docstring-style comment
            )]
            if body and all(
                isinstance(s, (ast.Pass, ast.Continue)) for s in body
            ):
                what = (
                    ast.unparse(sub.type) if sub.type is not None else "BaseException"
                )
                yield Finding(
                    rule="swallowed-exception",
                    path=sf.display,
                    line=sub.lineno,
                    col=sub.col_offset,
                    message=(
                        f"except {what} swallowed (body is only "
                        "pass/continue) in worker-reachable code — a "
                        "failing worker dies silently; record it (ledger "
                        "event, collected error list like "
                        "tools/tsan_stress.py) or re-raise"
                    ),
                )


RULES = [
    Rule(
        name="thread-unsafe-mutation",
        summary="unlocked shared-attribute mutation in worker callables",
        check=check_thread_mutation,
    ),
    Rule(
        name="swallowed-exception",
        summary="except-pass in worker-reachable code",
        check=check_swallowed_exception,
    ),
]
