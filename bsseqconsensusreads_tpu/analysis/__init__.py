"""graftlint — first-party static analysis for TPU-hostile and
thread-unsafe code.

The run ledger (PR 1) can *measure* a stalled chip; this package exists
to *prevent* the code classes that stall it. Eight AST-based checkers
target the failure modes this codebase actually has (host syncs hiding
outside accounted ledger spans, jit recompile hazards, tracer leaks,
unlocked shared mutation in the overlap pool's worker callables,
blocking I/O inside device spans, set-order-dependent shapes, bare
stderr prints, swallowed worker exceptions).

Entry points:
  * `python -m bsseqconsensusreads_tpu.cli lint [paths...]` — CLI
  * run_lint(paths, rules=...) -> list[Finding]            — library
  * tests/test_graftlint.py                                — per-rule
    seeded-violation fixtures + the tier-1 self-application gate

Suppression syntax (inline, rule name mandatory):
    x = float(out)  # graftlint: disable=host-sync -- singleton batch,
                    # value is host numpy by construction
A standalone `# graftlint: disable=<rule>` comment line applies to the
next code line. `# graftlint: disable-file=<rule>` anywhere disables a
rule for the whole file. Unknown rule names are a hard error — a typo'd
suppression must not silently disable nothing.
"""

from bsseqconsensusreads_tpu.analysis.engine import (  # noqa: F401
    Finding,
    LintError,
    all_rules,
    run_lint,
)
