"""graftlint tracing-discipline rule: untraced transport send.

The failure class grafttrace (trace-context propagation) introduces: a
process hands WORK — a job spec, a slice, a chunk — to another process
over the framed transport without a trace context in scope. The
receiver then mints a fresh trace for work that already has one, the
causal tree breaks at the process boundary, and `observe trace` cannot
attribute the receiver's wall back to the sender's job/slice — exactly
the cross-process blindness the tracing plane exists to remove. The
sanctioned shape: the dispatching scope binds the work's trace context
(`observe.bind_trace(...) as trace_ctx`, `slice_trace = sl["trace"]`,
...) so transport.request ships it as the `_trace` wire field.

Scope: files that import `serve.transport`. A `request`/`send_message`
call is flagged when a dict-literal argument carries a work-payload key
("spec", "slice", "chunk") and the enclosing function binds no name
containing 'trace'. Control-plane sends (ping, wait, status, lease
polls, heartbeats) carry no work key and stay clean; payloads passed as
bare variables are conservatively skipped — the rule targets the
literal dispatch sites where the work being shipped is visible.
"""

from __future__ import annotations

import ast
from typing import Iterator

from bsseqconsensusreads_tpu.analysis.engine import (
    Finding,
    PackageIndex,
    Rule,
    SourceFile,
)
from bsseqconsensusreads_tpu.analysis.rules_elastic import (
    _bound_names,
    _imports_serve_transport,
    _SEND_NAMES,
)

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)

#: Dict-literal keys that mark a payload as carrying WORK (not control
#: traffic): a serve job spec, an elastic slice, a batch chunk.
_WORK_KEYS = frozenset({"spec", "slice", "chunk"})


def _work_keys_in(call: ast.Call) -> set[str]:
    """Work-payload keys among the dict LITERALS of this call's
    arguments (bare-variable payloads are skipped by construction)."""
    found: set[str] = set()
    for arg in (*call.args, *(kw.value for kw in call.keywords)):
        for node in ast.walk(arg):
            if not isinstance(node, ast.Dict):
                continue
            for key in node.keys:
                if (
                    isinstance(key, ast.Constant)
                    and isinstance(key.value, str)
                    and key.value in _WORK_KEYS
                ):
                    found.add(key.value)
    return found


def _holds_trace(names: set[str]) -> bool:
    return any("trace" in n.lower() for n in names)


def _scope_of(sf: SourceFile, node: ast.AST) -> ast.AST:
    for func in sf.enclosing_functions(node):
        return func
    return sf.tree


def check_untraced_transport_send(
    sf: SourceFile, index: PackageIndex
) -> Iterator[Finding]:
    if not _imports_serve_transport(sf):
        return
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        name = (
            func.attr if isinstance(func, ast.Attribute)
            else func.id if isinstance(func, ast.Name)
            else ""
        )
        if name not in _SEND_NAMES:
            continue
        keys = _work_keys_in(node)
        if not keys:
            continue
        scope = _scope_of(sf, node)
        if isinstance(scope, _FUNCS) and _holds_trace(_bound_names(scope)):
            continue
        yield Finding(
            rule="untraced-transport-send",
            path=sf.display,
            line=node.lineno,
            col=node.col_offset,
            message=(
                f"work payload ({', '.join(sorted(keys))}) handed to a "
                "transport send with no trace context in scope — the "
                "receiver cannot join the sender's causal tree and "
                "`observe trace` loses the cross-process attribution; "
                "bind the work's context first "
                "(observe.bind_trace(...) as trace_ctx) so the `_trace` "
                "wire field ships with the request"
            ),
        )


RULES = [
    Rule(
        name="untraced-transport-send",
        summary="job/slice/chunk payload sent over the transport with "
        "no trace context bound in the dispatching scope",
        check=check_untraced_transport_send,
    ),
]
