"""graftlint transport-discipline rule: unframed socket reads.

The failure class the fleet's TCP transport (serve/transport.py)
introduces: reading a socket with raw ``.recv()`` / ``.readline()``
instead of the length-framed, bounded, guard-typed reader. A raw recv
trusts the peer for the record boundary AND the size — on a TCP port
(no filesystem permission wall) that is an unbounded allocation driven
by hostile bytes, and a protocol desync surfaces as a crash or a hang
instead of a typed `TransportError` refusal. The sanctioned shape is
`serve.transport.recv_message` / `request`, whose frame header is
admitted against MAX_FRAME before any payload byte is buffered; the
two `conn.recv` calls inside transport.py itself carry reviewed
suppressions — they ARE the framed reader.

Scope: files that import `socket` (anything else calling `.readline()`
is reading files, not wires — other rules' business).
"""

from __future__ import annotations

import ast
from typing import Iterator

from bsseqconsensusreads_tpu.analysis.engine import (
    Finding,
    PackageIndex,
    Rule,
    SourceFile,
)

#: Raw stream-read methods that bypass frame admission on a socket.
_RAW_READS = frozenset({"recv", "recv_into", "recvfrom", "readline"})


def _imports_socket(sf: SourceFile) -> bool:
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Import):
            if any(a.name.split(".")[0] == "socket" for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            if (node.module or "").split(".")[0] == "socket":
                return True
    return False


def check_unframed_socket_read(
    sf: SourceFile, index: PackageIndex
) -> Iterator[Finding]:
    if not _imports_socket(sf):
        return
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not isinstance(func, ast.Attribute) or func.attr not in _RAW_READS:
            continue
        yield Finding(
            rule="unframed-socket-read",
            path=sf.display,
            line=node.lineno,
            col=node.col_offset,
            message=(
                f".{func.attr}() on a socket path without the "
                "length-framed guarded reader — the peer controls the "
                "record boundary and the size, so garbage or hostile "
                "frames become unbounded buffering or a crash instead "
                "of a typed TransportError; read through "
                "serve.transport.recv_message/request"
            ),
        )


RULES = [
    Rule(
        name="unframed-socket-read",
        summary="raw recv/readline on socket paths instead of the "
        "length-framed guarded transport reader",
        check=check_unframed_socket_read,
    ),
]
