"""graftlint I/O-discipline rules: blocking I/O inside device spans and
bare stderr prints.

`io-in-device-span` keeps the ledger's DEVICE_PHASES honest: a span
named kernel/device_wait/fetch is *defined* as chip/tunnel time
(utils.observe phase classification), so a file write or sleep inside
one silently inflates chip_busy. `stderr-print` is the AST successor of
the PR-1 regex guard in tests/test_observe.py — package diagnostics go
through the run ledger or observe.stderr_line, never raw stderr.
"""

from __future__ import annotations

import ast
import os
from typing import Iterator

from bsseqconsensusreads_tpu.analysis.engine import (
    Finding,
    PackageIndex,
    Rule,
    SourceFile,
    call_basename,
    timed_span_name,
)

#: Spans classified as device/tunnel time by the ledger
#: (utils.observe.DEVICE_PHASES) — blocking host I/O in these corrupts
#: the chip_busy accounting.
DEVICE_SPANS = frozenset({"kernel", "device_wait", "fetch"})

_BLOCKING_NAMES = frozenset({"open", "input", "print"})
_BLOCKING_ATTRS = frozenset(
    {
        "write",
        "read",
        "readline",
        "readlines",
        "flush",
        "sleep",
        "system",
        "popen",
        "communicate",
        "check_call",
        "check_output",
        "sendall",
        "recv",
    }
)

#: The one module allowed to touch sys.stderr directly — it *is* the
#: routing layer (observe.stderr_line and the ledger mirror).
_STDERR_ALLOWED_BASENAME = "observe.py"


def _innermost_device_span(sf: SourceFile, node: ast.AST) -> str | None:
    cur = sf.parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.With):
            for item in cur.items:
                name = timed_span_name(item.context_expr)
                if name is not None and name in DEVICE_SPANS:
                    return name
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return None  # spans don't cross function boundaries lexically
        cur = sf.parents.get(cur)
    return None


def check_io_in_device_span(
    sf: SourceFile, index: PackageIndex
) -> Iterator[Finding]:
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        span = _innermost_device_span(sf, node)
        if span is None:
            continue
        base = call_basename(node)
        hit = None
        if isinstance(node.func, ast.Name) and base in _BLOCKING_NAMES:
            hit = f"{base}()"
        elif isinstance(node.func, ast.Attribute) and base in _BLOCKING_ATTRS:
            hit = f".{base}()"
        if hit:
            yield Finding(
                rule="io-in-device-span",
                path=sf.display,
                line=node.lineno,
                col=node.col_offset,
                message=(
                    f"blocking call {hit} inside the {span!r} device span "
                    "— DEVICE_PHASES seconds are chip/tunnel time by "
                    "definition (observe.phase_summary); host I/O here "
                    "inflates chip_busy. Move it outside the span or "
                    "into its own host-phase timer"
                ),
            )


def check_stderr_print(sf: SourceFile, index: PackageIndex) -> Iterator[Finding]:
    if os.path.basename(sf.display) == _STDERR_ALLOWED_BASENAME:
        return
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        hit = None
        if isinstance(node.func, ast.Name) and node.func.id == "print":
            for kw in node.keywords:
                if kw.arg == "file" and ast.unparse(kw.value) == "sys.stderr":
                    hit = "print(..., file=sys.stderr)"
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in ("write", "flush")
            and ast.unparse(node.func.value) == "sys.stderr"
        ):
            hit = f"sys.stderr.{node.func.attr}(...)"
        if hit:
            yield Finding(
                rule="stderr-print",
                path=sf.display,
                line=node.lineno,
                col=node.col_offset,
                message=(
                    f"bare {hit} — route diagnostics through the run "
                    "ledger (observe.emit) or observe.stderr_line so "
                    "multi-thread output stays line-atomic and "
                    "ledger-mirrored"
                ),
            )


RULES = [
    Rule(
        name="io-in-device-span",
        summary="blocking I/O inside a kernel/device_wait/fetch span",
        check=check_io_in_device_span,
    ),
    Rule(
        name="stderr-print",
        summary="bare stderr print outside utils/observe.py",
        check=check_stderr_print,
    ),
]
