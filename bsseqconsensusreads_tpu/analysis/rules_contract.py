"""contract-drift: per-file used-but-undeclared surface check.

The whole-program direction of graftcontract (declared-but-unused,
emitted-but-never-consumed, README tables) only makes sense over the
full package and runs as ``cli lint --contracts``. But the *use* side
— an env read, a ledger emit, a failpoint fire, a transport refusal —
is checkable one file at a time against the registry, and that is what
this rule does, so an undeclared name fails the ordinary lint sweep at
the line that introduced it.

Scope is deliberately the four surfaces whose uses are unambiguous in
isolation. Client-side protocol-op literals are *not* checked here:
fixture files legitimately fabricate ops (fx_unleased_work_dispatch
ships an ``"assign"`` job to seed a different rule), and ops are a
cross-plane contract anyway — the whole-program pass owns them.

Files under the analysis subpackage are skipped: the registry and rule
patterns in there mention surface names as declarations, not uses.
"""

from __future__ import annotations

from typing import Iterator

from bsseqconsensusreads_tpu.analysis.engine import (
    Finding,
    PackageIndex,
    Rule,
    SourceFile,
)

_RULE = "contract-drift"


def _check(sf: SourceFile, index: PackageIndex) -> Iterator[Finding]:
    from bsseqconsensusreads_tpu.analysis import contracts

    if "analysis" in sf.module.split("."):
        return
    ex = contracts.Extraction()
    ex._scan_file(sf, index)
    reg = contracts.REGISTRY
    checks = (
        (ex.env_uses, reg.env_names(), "env var",
         "declare it in analysis.contracts ENV_VARS"),
        (ex.event_emits, reg.event_names(), "ledger event",
         "declare it in analysis.contracts EVENTS (and "
         "ledger_tools.EVENT_SCHEMA)"),
        (ex.fire_sites, reg.failpoint_sites, "failpoint site",
         "declare it in analysis.contracts FAILPOINT_SITES and "
         "faults.failpoints.SITES"),
        (ex.schedule_sites, reg.failpoint_sites, "failpoint site",
         "declare it in analysis.contracts FAILPOINT_SITES and "
         "faults.failpoints.SITES"),
        (ex.refusal_uses, reg.refusal_reasons, "refusal reason",
         "declare it in analysis.contracts REFUSAL_REASONS"),
    )
    for uses, declared, what, fix in checks:
        for name, sites in uses.items():
            if name in declared:
                continue
            for _path, line in sites:
                yield Finding(
                    rule=_RULE,
                    path=sf.display,
                    line=line,
                    col=0,
                    message=(
                        f"undeclared {what} {name!r} — not in the "
                        f"graftcontract registry; {fix}, or rename the "
                        f"use to a declared surface"
                    ),
                )


RULES = [
    Rule(
        name=_RULE,
        summary=(
            "use of a BSSEQ_TPU_* env var, ledger event, failpoint "
            "site, or transport refusal reason that the graftcontract "
            "registry does not declare — stringly-typed surfaces rot "
            "silently when emitter and consumer drift apart, so every "
            "name crossing a process or module boundary must be "
            "declared in analysis.contracts (whole-program drift "
            "directions run as `cli lint --contracts`)"
        ),
        check=_check,
    ),
]
