"""graftlint fencing-discipline rule: unfenced commit.

The failure class graftnet's epoch fencing exists to close: a process
that publishes work — a `publish` / `slice_push` commit frame — from a
scope that carries no fence epoch. Lease expiry alone cannot stop such
a sender: a worker partitioned away from the coordinator keeps
computing, the slice is requeued, and when the partition heals the
zombie's commit races the new holder's. The sanctioned shape is the
fence protocol: the committing scope holds the epoch its lease grant
minted (and echoes it in the frame), so the coordinator can refuse the
stale writer with `publish_fenced` and the worker can self-fence via
`fencing.revoke` the moment its renewal pump loses the lease.

Scope: files that import `serve.transport` (the elastic wire). A
transport send is flagged when its payload names a commit-shaped op
(`publish` / `slice_push` / `commit`) while the enclosing function
binds no fence-epoch name (`epoch` / `fence*`). Read-shaped ops
(`lease`, `heartbeat`, `slice_fetch`, `status`) commit nothing and are
never flagged.
"""

from __future__ import annotations

import ast
from typing import Iterator

from bsseqconsensusreads_tpu.analysis.engine import (
    Finding,
    PackageIndex,
    Rule,
    SourceFile,
)
from bsseqconsensusreads_tpu.analysis.rules_elastic import (
    _FUNCS,
    _bound_names,
    _imports_serve_transport,
)

#: Transport send entry points (same wire surface the elastic rule
#: watches).
_SEND_NAMES = frozenset({"request", "send_message"})

#: Op literals that make a frame a COMMIT: they transition durable
#: coordinator state (manifest commit, shipped-output bytes).
_COMMIT_OPS = frozenset({"publish", "slice_push", "commit"})


def _holds_fence(names: set[str]) -> bool:
    low = [n.lower() for n in names]
    return any("epoch" in n or "fence" in n for n in low)


def _commit_op(call: ast.Call) -> str | None:
    """The commit-shaped op literal a send's payload carries, if any."""
    for node in ast.walk(call):
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and node.value in _COMMIT_OPS
        ):
            return node.value
    return None


def _sends_outside_nested(scope: ast.AST) -> list[ast.Call]:
    """Transport send calls belonging to this scope (nested function
    bodies are their own scopes — a closure may bind its own epoch —
    and are visited separately)."""
    out: list[ast.Call] = []

    def visit(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FUNCS):
                continue
            if isinstance(child, ast.Call):
                func = child.func
                name = (
                    func.attr if isinstance(func, ast.Attribute)
                    else func.id if isinstance(func, ast.Name)
                    else ""
                )
                if name in _SEND_NAMES:
                    out.append(child)
            visit(child)

    visit(scope)
    return out


def check_unfenced_commit(
    sf: SourceFile, index: PackageIndex
) -> Iterator[Finding]:
    if not _imports_serve_transport(sf):
        return
    scopes: list[ast.AST] = [sf.tree]
    scopes.extend(n for n in ast.walk(sf.tree) if isinstance(n, _FUNCS))
    for scope in scopes:
        fenced = isinstance(scope, _FUNCS) and _holds_fence(
            _bound_names(scope)
        )
        if fenced:
            continue
        for node in _sends_outside_nested(scope):
            op = _commit_op(node)
            if op is None:
                continue
            yield Finding(
                rule="unfenced-commit",
                path=sf.display,
                line=node.lineno,
                col=node.col_offset,
                message=(
                    f"{op!r} frame sent with no fence epoch in scope — "
                    "a partitioned zombie holding this code path can "
                    "commit over the requeued holder after the "
                    "partition heals; carry the lease grant's "
                    "fence_epoch in the payload and abort locally via "
                    "fencing.revoke when the renewal pump loses the "
                    "lease"
                ),
            )


RULES = [
    Rule(
        name="unfenced-commit",
        summary="commit-shaped frame (publish/slice_push) sent without "
        "a fence epoch in scope (zombie writer can race the requeued "
        "holder)",
        check=check_unfenced_commit,
    ),
]
