"""graftlint retry-discipline rule: unbounded retry loops.

The failure class this PR's robustness review named (ROADMAP open item:
grow a rule per new failure class): a `while True:` loop that catches
an I/O or device error and spins again with neither an attempt bound
nor a backoff turns one persistent fault into a livelock — the batch
loop looks alive (the process spins), every ledger counter freezes, and
the run never crashes into the checkpoint layer that could actually
recover it. The sanctioned shape is the bounded executor
(faults.retry.guarded): capped attempts, exponential backoff, then
degrade or die.

A loop passes when any handler path terminates it (`raise` / `break` /
`return` — which is what an attempt-bound check compiles to) or at
least backs off (a sleep/wait call). Loops whose try body touches no
I/O- or device-shaped call are ignored — a pure-compute retry loop is
somebody else's bug.
"""

from __future__ import annotations

import ast
from typing import Iterator

from bsseqconsensusreads_tpu.analysis.engine import (
    Finding,
    PackageIndex,
    Rule,
    SourceFile,
    call_basename,
)

#: Call basenames that mark a try body as touching I/O or the device —
#: the operations whose transient failures invite retry loops.
_IO_DEVICE_CALLS = frozenset(
    {
        # filesystem / sockets / subprocess
        "open", "read", "readline", "readlines", "write", "flush",
        "fsync", "remove", "unlink", "rename", "replace", "recv",
        "send", "sendall", "connect", "communicate", "check_call",
        "check_output", "urlopen", "request",
        # device / executor
        "device_put", "device_get", "block_until_ready", "result",
        "submit",
    }
)

#: Handler calls that count as backing off before the next attempt.
_BACKOFF_CALLS = frozenset({"sleep", "wait", "backoff"})


def _const_true(test: ast.AST) -> bool:
    return isinstance(test, ast.Constant) and bool(test.value)


def _calls_in(nodes) -> Iterator[str]:
    for n in nodes:
        for sub in ast.walk(n):
            if isinstance(sub, ast.Call):
                base = call_basename(sub)
                if base:
                    yield base


def check_unbounded_retry(
    sf: SourceFile, index: PackageIndex
) -> Iterator[Finding]:
    for node in ast.walk(sf.tree):
        if not (isinstance(node, ast.While) and _const_true(node.test)):
            continue
        for sub in PackageIndex._own_nodes(node):
            if not isinstance(sub, ast.Try):
                continue
            if not any(
                base in _IO_DEVICE_CALLS for base in _calls_in(sub.body)
            ):
                continue
            for handler in sub.handlers:
                terminates = any(
                    isinstance(x, (ast.Raise, ast.Break, ast.Return))
                    for stmt in handler.body
                    for x in ast.walk(stmt)
                )
                if terminates:
                    continue
                if any(
                    base in _BACKOFF_CALLS
                    for base in _calls_in(handler.body)
                ):
                    continue
                what = (
                    ast.unparse(handler.type)
                    if handler.type is not None
                    else "BaseException"
                )
                yield Finding(
                    rule="unbounded-retry",
                    path=sf.display,
                    line=handler.lineno,
                    col=handler.col_offset,
                    message=(
                        f"`while True` retry around I/O/device calls "
                        f"swallows {what} with no attempt bound or "
                        "backoff — a persistent fault livelocks here "
                        "instead of crashing into recoverable state; "
                        "bound the attempts (cf. faults.retry.guarded) "
                        "or back off between tries"
                    ),
                )


RULES = [
    Rule(
        name="unbounded-retry",
        summary="while-True retry around I/O/device calls without "
        "attempt bound or backoff",
        check=check_unbounded_retry,
    ),
]
