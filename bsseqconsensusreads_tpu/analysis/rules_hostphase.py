"""graftlint host-phase-discipline rule: serialized-host-phase.

The failure class the PR-6 host-parallel review named (ROADMAP open
item: grow a rule per failure class found in review): a host-phase
ledger span — `timed('rawize')`, `timed('emit')`, any span the phase
summary books as host time — executed inline BETWEEN a batch's
`dispatch_kernel` and its `fetch_out` on a batch-loop-reachable path.
That host work serializes against the in-flight device batch: the chip
(or tunnel) finishes and then WAITS while the host grinds, which is
exactly the wall the round-5 scale artifacts measured (the rawize pass
alone was 242-277 s of the duplex stage). When a host pool is available
(`parallel/hostpool.py` — or any linted file defining `host_workers`),
such work belongs in a host-pool task retired in batch order, not on
the dispatch thread mid-flight.

The rule is lexical within one function: a host-phase `with ...timed()`
whose line falls after a `dispatch_kernel(...)` call and before a later
`fetch_out(...)` call. Host phases that run AFTER the fetch (the
sanctioned worker-side retire shape) or before the dispatch (pipelined
encode of the next batch) never match.
"""

from __future__ import annotations

import ast
import os
from typing import Iterator

from bsseqconsensusreads_tpu.analysis.engine import (
    Finding,
    PackageIndex,
    Rule,
    SourceFile,
    call_basename,
    timed_span_name,
)

#: Call basenames that put a batch in flight / retire it.
_DISPATCH_CALLS = frozenset({"dispatch_kernel"})
_FETCH_CALLS = frozenset({"fetch_out"})

#: Span names that are NOT host phases: device/tunnel time plus the
#: main-thread join on an overlapped batch (utils.observe DEVICE_PHASES
#: / STALL_PHASES). Everything else a timed() block names is host work.
_NON_HOST_SPANS = frozenset({"kernel", "device_wait", "fetch", "stall"})


def _host_pool_available(index: PackageIndex) -> bool:
    """Whether the linted file set ships a host pool to move the work
    to — parallel/hostpool.py itself, or any definition of its
    `host_workers` knob (fixtures seed the latter)."""
    if "host_workers" in index.functions:
        return True
    return any(
        os.path.basename(sf.display) == "hostpool.py" for sf in index.files
    )


def check_serialized_host_phase(
    sf: SourceFile, index: PackageIndex
) -> Iterator[Finding]:
    if not _host_pool_available(index):
        return
    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        fi = index.info(node)
        if fi is None or fi.qualname not in index.hot_reachable:
            continue
        events: list[tuple[int, int, str, str | None]] = []
        for sub in PackageIndex._own_nodes(node):
            if isinstance(sub, ast.Call):
                base = call_basename(sub)
                if base in _DISPATCH_CALLS:
                    events.append(
                        (sub.lineno, sub.col_offset, "dispatch", None)
                    )
                elif base in _FETCH_CALLS:
                    events.append((sub.lineno, sub.col_offset, "fetch", None))
            elif isinstance(sub, ast.With):
                for item in sub.items:
                    name = timed_span_name(item.context_expr)
                    if name is not None and name not in _NON_HOST_SPANS:
                        events.append(
                            (sub.lineno, sub.col_offset, "host", name)
                        )
        events.sort()
        fetch_lines = [ln for ln, _, kind, _ in events if kind == "fetch"]
        dispatched_at: int | None = None
        for line, col, kind, name in events:
            if kind == "dispatch":
                dispatched_at = line
            elif kind == "fetch":
                dispatched_at = None
            elif (
                kind == "host"
                and dispatched_at is not None
                and any(fl > line for fl in fetch_lines)
            ):
                yield Finding(
                    rule="serialized-host-phase",
                    path=sf.display,
                    line=line,
                    col=col,
                    message=(
                        f"host phase timed({name!r}) runs inline between "
                        "dispatch_kernel (line "
                        f"{dispatched_at}) and fetch_out on a batch-loop "
                        "path — it serializes host work against the "
                        "in-flight device batch. A host pool is available "
                        "(parallel.hostpool): submit the phase as a "
                        "host-pool task retired in batch order, or move "
                        "it after the fetch"
                    ),
                )


RULES = [
    Rule(
        name="serialized-host-phase",
        summary="host-phase timed() span inline between dispatch_kernel "
        "and fetch_out when a host pool is available",
        check=check_serialized_host_phase,
    ),
]
