"""graftlint rule guarding the segment-packed kernel layout (PR 9).

`padded-batch-flops` flags a padding-envelope allocation on the hot
path: a literal shape tuple densifying three or more ragged dimensions
at once (the [F, T, 2, W] signature — family count x templates x
window all padded to their batch maxima, so device FLOPs scale with
the worst family instead of the real read count). The packed layout
(ops.encode.pack_molecular_rows) replaced that envelope with one dense
row axis + segment ids; new hot-path code should pack, and the two
sanctioned fallback encoders carry reviewed suppressions.

Structural dims stay clean on purpose: `(f, 4, w)` (duplex strand
rows), `(n, 2, w)` (packed rows: read axis is dense, only the bucket
rounds), and `(f, 2, NUM_BASES, w)` (ALL_CAPS names count as
constants) each densify at most two ragged dims.
"""

from __future__ import annotations

import ast
from typing import Iterator

from bsseqconsensusreads_tpu.analysis.engine import (
    Finding,
    PackageIndex,
    Rule,
    SourceFile,
    call_basename,
)

#: Allocators that materialize the envelope. `concatenate`/`stack` grow
#: from real rows and are exempt; so is `empty` handed a computed shape
#: expression (not a literal tuple — those sites shape to an existing
#: array, not to batch maxima).
_ALLOCATORS = frozenset({"full", "zeros", "empty", "ones"})


def _ragged_dim(elt: ast.AST) -> bool:
    """A shape element is ragged when it reads a runtime value: any
    non-ALL_CAPS name anywhere in it (`t_pad`, `w_pad + 1`, `len(x)`).
    Constants and ALL_CAPS module constants (NUM_BASES, LANE) are
    structural."""
    for sub in ast.walk(elt):
        if isinstance(sub, ast.Name) and sub.id != sub.id.upper():
            return True
    return False


def _shape_tuple(call: ast.Call) -> ast.Tuple | None:
    if call.args and isinstance(call.args[0], ast.Tuple):
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "shape" and isinstance(kw.value, ast.Tuple):
            return kw.value
    return None


def check_padded_batch_flops(
    sf: SourceFile, index: PackageIndex
) -> Iterator[Finding]:
    """padded-batch-flops: >=3 ragged dims densified in one allocation
    on a batch-loop-reachable path."""
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        if call_basename(node) not in _ALLOCATORS:
            continue
        shape = _shape_tuple(node)
        if shape is None or len(shape.elts) < 3:
            continue
        if sum(1 for e in shape.elts if _ragged_dim(e)) < 3:
            continue
        if not index.in_hot_path(sf, node):
            continue
        yield Finding(
            rule="padded-batch-flops",
            path=sf.display,
            line=node.lineno,
            col=node.col_offset,
            message=(
                "padding-envelope allocation on the hot path: this "
                "shape densifies 3+ ragged dims to their batch maxima, "
                "so kernel FLOPs scale with the worst family — use the "
                "segment-packed layout (ops.encode.pack_molecular_rows: "
                "dense row axis + segment ids) instead"
            ),
        )


RULES = [
    Rule(
        name="padded-batch-flops",
        summary="3+ ragged dims padded to batch maxima in one hot-path "
        "allocation",
        check=check_padded_batch_flops,
    ),
]
