"""graftlint rule guarding the segment-packed kernel layout (PR 9).

`padded-batch-flops` flags a padding-envelope allocation on the hot
path: a literal shape tuple densifying three or more ragged dimensions
at once (the [F, T, 2, W] signature — family count x templates x
window all padded to their batch maxima, so device FLOPs scale with
the worst family instead of the real read count). The packed layout
(ops.encode.pack_molecular_rows) replaced that envelope with one dense
row axis + segment ids; new hot-path code should pack, and the two
sanctioned fallback encoders carry reviewed suppressions.

Structural dims stay clean on purpose: `(f, 4, w)` (duplex strand
rows), `(n, 2, w)` (packed rows: read axis is dense, only the bucket
rounds), and `(f, 2, NUM_BASES, w)` (ALL_CAPS names count as
constants) each densify at most two ragged dims.
"""

from __future__ import annotations

import ast
from typing import Iterator

from bsseqconsensusreads_tpu.analysis.engine import (
    Finding,
    PackageIndex,
    Rule,
    SourceFile,
    call_basename,
)

#: Allocators that materialize the envelope. `concatenate`/`stack` grow
#: from real rows and are exempt; so is `empty` handed a computed shape
#: expression (not a literal tuple — those sites shape to an existing
#: array, not to batch maxima).
_ALLOCATORS = frozenset({"full", "zeros", "empty", "ones"})


def _ragged_dim(elt: ast.AST) -> bool:
    """A shape element is ragged when it reads a runtime value: any
    non-ALL_CAPS name anywhere in it (`t_pad`, `w_pad + 1`, `len(x)`).
    Constants and ALL_CAPS module constants (NUM_BASES, LANE) are
    structural."""
    for sub in ast.walk(elt):
        if isinstance(sub, ast.Name) and sub.id != sub.id.upper():
            return True
    return False


def _shape_tuple(call: ast.Call) -> ast.Tuple | None:
    if call.args and isinstance(call.args[0], ast.Tuple):
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "shape" and isinstance(kw.value, ast.Tuple):
            return kw.value
    return None


def check_padded_batch_flops(
    sf: SourceFile, index: PackageIndex
) -> Iterator[Finding]:
    """padded-batch-flops: >=3 ragged dims densified in one allocation
    on a batch-loop-reachable path."""
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        if call_basename(node) not in _ALLOCATORS:
            continue
        shape = _shape_tuple(node)
        if shape is None or len(shape.elts) < 3:
            continue
        if sum(1 for e in shape.elts if _ragged_dim(e)) < 3:
            continue
        if not index.in_hot_path(sf, node):
            continue
        yield Finding(
            rule="padded-batch-flops",
            path=sf.display,
            line=node.lineno,
            col=node.col_offset,
            message=(
                "padding-envelope allocation on the hot path: this "
                "shape densifies 3+ ragged dims to their batch maxima, "
                "so kernel FLOPs scale with the worst family — use the "
                "segment-packed layout (ops.encode.pack_molecular_rows: "
                "dense row axis + segment ids) instead"
            ),
        )


#: Attributes that mark a segment-packed plan in scope on the batch object.
_PLAN_ATTRS = frozenset({"packed", "packed_shards"})


def _is_envelope_dispatcher(basename: str | None) -> bool:
    """Call basenames that ship input tensors to a multi-device or wire
    route: the sharded shard_map wrappers, the wire input packers, and
    the mesh family padder. Packed-aware callees ('packed'/'rows' in the
    name — pack_molecular_rows_wire, sharded_molecular_rows) are the fix,
    not the finding."""
    if not basename or "packed" in basename or "rows" in basename:
        return False
    return (
        basename.startswith(("sharded_", "pack_"))
        or "wire" in basename
        or basename == "pad_families"
    )


def _own_nodes(func: ast.AST):
    """Walk a function body without descending into nested defs — plan
    availability is judged per closure, not per module."""
    stack = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def check_padded_envelope_dispatch(
    sf: SourceFile, index: PackageIndex
) -> Iterator[Finding]:
    """padded-envelope-dispatch: a hot-path multi-device/wire dispatch
    handed the dense `[F, T, 2, W]` tensors (`<batch>.bases`) inside a
    function where that batch's segment-packed plan (`<batch>.packed` /
    `.packed_shards`) is available."""
    for func in ast.walk(sf.tree):
        if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        own = list(_own_nodes(func))
        plan_objs = {
            n.value.id
            for n in own
            if isinstance(n, ast.Attribute)
            and n.attr in _PLAN_ATTRS
            and isinstance(n.value, ast.Name)
        }
        if not plan_objs:
            continue
        for call in own:
            if not isinstance(call, ast.Call):
                continue
            if not _is_envelope_dispatcher(call_basename(call)):
                continue
            args = list(call.args) + [kw.value for kw in call.keywords]
            envelope = any(
                isinstance(sub, ast.Attribute)
                and sub.attr == "bases"
                and isinstance(sub.value, ast.Name)
                and sub.value.id in plan_objs
                for a in args
                for sub in ast.walk(a)
            )
            if not envelope or not index.in_hot_path(sf, call):
                continue
            yield Finding(
                rule="padded-envelope-dispatch",
                path=sf.display,
                line=call.lineno,
                col=call.col_offset,
                message=(
                    "padded-envelope dispatch: this multi-device/wire "
                    "call ships the dense [F, T, 2, W] tensors while the "
                    "batch's segment-packed plan (.packed) is in scope — "
                    "dispatch the packed rows instead "
                    "(parallel.sharding.sharded_molecular_rows / "
                    "ops.wire.pack_molecular_rows_wire)"
                ),
            )


RULES = [
    Rule(
        name="padded-batch-flops",
        summary="3+ ragged dims padded to batch maxima in one hot-path "
        "allocation",
        check=check_padded_batch_flops,
    ),
    Rule(
        name="padded-envelope-dispatch",
        summary="dense [F,T,2,W] tensors handed to a multi-device/wire "
        "dispatch while a segment-packed plan is in scope",
        check=check_padded_envelope_dispatch,
    ),
]
