"""graftlint engine: source model, suppressions, call graph, runner.

The engine is deliberately self-contained stdlib (ast + tokenize): it
must run in the tier-1 suite on every PR with zero extra deps, and it
must be able to lint arbitrary file sets (the seeded-violation fixtures
under tests/data/lint_fixtures/) — so all cross-file context (call
graph, hot-path/jit/worker reachability) is rebuilt from exactly the
files being linted, never from imports.

Two naming layers coexist:

* Reachability stays basename-level on purpose: `events()` calling
  `dispatch_fetch` resolves to pipeline.calling's nested def without a
  type system. That makes reachability generous (a shared basename
  links both definitions), which is the right bias for a linter gating
  a hot path — a missed edge hides a stall, a spurious edge costs at
  most one reviewed suppression.
* Extraction facts (the graftcontract pass in analysis.contracts)
  need the opposite bias: `observe.emit(...)` must attribute to
  utils.observe.emit and nowhere else, or a same-named helper would
  pollute the ledger-event census. For that, every SourceFile carries
  a module name derived from its display path plus import/alias maps
  (`import x as y`, `from m import n`, relative imports resolved
  against the module), and PackageIndex exposes a qualified function
  table and `resolve_call`, which returns the dotted target of a call
  when the aliases pin it down and None when they don't.
"""

from __future__ import annotations

import ast
import io
import os
import tokenize
from dataclasses import dataclass, field
from typing import Callable, Iterable, Iterator

SUPPRESS_TAG = "graftlint:"

#: Ledger span names under which a host sync is *accounted* — the
#: ledger's device/stall phases (utils.observe.DEVICE_PHASES) plus the
#: host-side spans the pipeline books synchronous waits under
#: ('stall' = main-thread join on an overlapped batch, 'host_vote' =
#: the T==1 path that never touches the device, 'degrade' = the
#: CPU-twin fallback of a persistently failing batch, faults.retry).
ACCOUNTED_SPANS = frozenset(
    {"kernel", "device_wait", "fetch", "stall", "host_vote", "degrade",
     "methyl"}
)

#: Functions treated as batch-loop roots for hot-path reachability: the
#: two stage drivers, their flat-record wrappers — and, by convention,
#: any function whose name starts with `hot_` (so new hot paths opt in
#: by naming, and fixtures can seed one without package knowledge).
HOT_PATH_ROOTS = frozenset(
    {
        "call_molecular_batches",
        "call_duplex_batches",
        "call_molecular",
        "call_duplex",
    }
)
HOT_PATH_PREFIX = "hot_"


class LintError(Exception):
    """Usage error: unknown rule name (in --rules or a suppression),
    unparseable file, bad path. Distinct from findings — the CLI exits
    2 for these, 1 for findings."""


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # display (relative) path
    line: int
    col: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}"

    def as_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }


@dataclass
class Rule:
    name: str
    summary: str
    check: Callable[["SourceFile", "PackageIndex"], Iterator[Finding]]


def module_name(display: str) -> str:
    """Dotted module name derived from a display path:
    `bsseqconsensusreads_tpu/utils/observe.py` -> the obvious dotted
    form, `pkg/__init__.py` -> `pkg`. Paths outside any package still
    get a stable dotted name (fixtures resolve against themselves)."""
    p = display.replace(os.sep, "/").lstrip("./")
    if p.endswith(".py"):
        p = p[:-3]
    parts = [seg for seg in p.split("/") if seg]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class SourceFile:
    """One parsed file: AST with parent links, suppression tables, and
    the import/alias maps qualified-name resolution reads."""

    def __init__(self, path: str, display: str, source: str,
                 known_rules: Iterable[str]):
        self.path = path
        self.display = display
        self.source = source
        try:
            self.tree = ast.parse(source, filename=display)
        except SyntaxError as exc:
            raise LintError(f"{display}: cannot parse: {exc}") from exc
        self.parents: dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self.module = module_name(display)
        #: local name -> dotted module (`import x.y as z` => z: x.y)
        self.import_aliases: dict[str, str] = {}
        #: local name -> (dotted module, original name) for
        #: `from m import n as k` => k: (m, n); relative imports are
        #: resolved against self.module
        self.from_imports: dict[str, tuple[str, str]] = {}
        #: top-level def/class names defined in this module
        self.toplevel_defs: set[str] = set()
        self._scan_imports()
        self.line_suppress: dict[int, set[str]] = {}
        self.file_suppress: set[str] = set()
        #: lines whose Thread(...) call is a declared single-owner
        #: thread (`# graftlint: owned-thread`) — not a worker root
        self.owned_thread_lines: set[int] = set()
        self._scan_suppressions(set(known_rules))

    # -- imports / qualified names ---------------------------------------

    def _resolve_relative(self, level: int, mod: str | None) -> str | None:
        """Anchor a `from ...x import y` against self.module. level=1 is
        the containing package; each extra level climbs one more."""
        parts = self.module.split(".")
        # self.module names the file itself unless it is an __init__
        # (module_name already stripped that), so the containing
        # package is everything but the last component
        base = parts[:-1] if parts else []
        climb = level - 1
        if climb > len(base):
            return None
        anchor = base[: len(base) - climb]
        if mod:
            anchor = anchor + mod.split(".")
        return ".".join(anchor) if anchor else None

    def _scan_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    target = alias.name if alias.asname else alias.name.split(".")[0]
                    self.import_aliases[local] = target
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    mod = self._resolve_relative(node.level, node.module)
                else:
                    mod = node.module
                if mod is None:
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    local = alias.asname or alias.name
                    self.from_imports[local] = (mod, alias.name)
        for node in self.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                self.toplevel_defs.add(node.name)

    def resolve_name(self, name: str) -> str | None:
        """Dotted target a bare name binds to in this module, when the
        import maps pin it down: a from-import resolves to module.orig,
        an `import x as y` alias to x, a top-level def to
        self.module.name. Unknown names resolve to None."""
        if name in self.from_imports:
            mod, orig = self.from_imports[name]
            return f"{mod}.{orig}"
        if name in self.import_aliases:
            return self.import_aliases[name]
        if name in self.toplevel_defs:
            return f"{self.module}.{name}"
        return None

    def resolve_expr(self, expr: ast.AST) -> str | None:
        """Dotted name for a Name/Attribute chain (`observe.emit`,
        `pkg.utils.observe.emit`), resolving the root through the
        import maps. None for anything else (calls, subscripts)."""
        if isinstance(expr, ast.Name):
            return self.resolve_name(expr.id)
        if isinstance(expr, ast.Attribute):
            base = self.resolve_expr(expr.value)
            if base is not None:
                return f"{base}.{expr.attr}"
        return None

    # -- suppressions ----------------------------------------------------

    def _scan_suppressions(self, known: set[str]) -> None:
        """tokenize pass: `# graftlint: disable=a,b` binds to its own
        line; on a standalone comment line it binds to the next code
        line instead. `disable-file=` covers the whole file. Unknown
        rule names raise — a typo must not silently disable nothing.

        `# graftlint: owned-thread -- why` on a Thread(...) call
        declares a single-owner thread: its target owns its state for
        the thread's whole life (a resident engine loop, a per-job
        reader), so the instance-blind worker-reachability closure must
        not treat it as one of N racing pool workers."""
        code_lines: set[int] = set()
        comments: list[tuple[int, bool, str]] = []  # line, standalone, text
        tokens = tokenize.generate_tokens(io.StringIO(self.source).readline)
        try:
            for tok in tokens:
                if tok.type == tokenize.COMMENT:
                    standalone = tok.line[: tok.start[1]].strip() == ""
                    comments.append((tok.start[0], standalone, tok.string))
                elif tok.type not in (
                    tokenize.NL,
                    tokenize.NEWLINE,
                    tokenize.INDENT,
                    tokenize.DEDENT,
                    tokenize.ENDMARKER,
                ):
                    for ln in range(tok.start[0], tok.end[0] + 1):
                        code_lines.add(ln)
        except tokenize.TokenError as exc:
            raise LintError(f"{self.display}: tokenize failed: {exc}") from exc

        for line, standalone, text in comments:
            body = text.lstrip("#").strip()
            if not body.startswith(SUPPRESS_TAG):
                continue
            directive = body[len(SUPPRESS_TAG):].strip()
            # allow a trailing justification after ` -- `
            directive = directive.split("--", 1)[0].strip()
            if directive.startswith("disable-file="):
                names = directive[len("disable-file="):]
                target: set[str] | None = self.file_suppress
            elif directive.startswith("disable="):
                names = directive[len("disable="):]
                target = None  # line-scoped, resolved below
            elif directive == "owned-thread":
                bind = line
                if standalone:  # applies to the next code line
                    later = [ln for ln in code_lines if ln > line]
                    bind = min(later) if later else line
                self.owned_thread_lines.add(bind)
                continue
            else:
                raise LintError(
                    f"{self.display}:{line}: bad graftlint directive "
                    f"{body!r} (want disable=<rule[,rule]>, "
                    f"disable-file=<rule[,rule]>, or owned-thread)"
                )
            rules = {n.strip() for n in names.split(",") if n.strip()}
            unknown = rules - known
            if not rules or unknown:
                raise LintError(
                    f"{self.display}:{line}: unknown graftlint rule(s) "
                    f"{sorted(unknown) if unknown else '<empty>'} in "
                    f"suppression (known: {', '.join(sorted(known))})"
                )
            if target is not None:
                target.update(rules)
                continue
            bind = line
            if standalone:  # applies to the next code line
                later = [ln for ln in code_lines if ln > line]
                bind = min(later) if later else line
            self.line_suppress.setdefault(bind, set()).update(rules)

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_suppress:
            return True
        return rule in self.line_suppress.get(line, set())

    # -- AST helpers -----------------------------------------------------

    def enclosing_functions(self, node: ast.AST) -> list[ast.AST]:
        """Innermost-first chain of def/asyncdef nodes containing node."""
        out = []
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(cur)
            cur = self.parents.get(cur)
        return out

    def in_accounted_span(self, node: ast.AST) -> bool:
        """True when node sits lexically inside `with <x>.timed("<name>")`
        for an ACCOUNTED_SPANS name — the ledger owns that wait."""
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.With):
                for item in cur.items:
                    name = timed_span_name(item.context_expr)
                    if name is not None and name in ACCOUNTED_SPANS:
                        return True
            cur = self.parents.get(cur)
        return False

    def in_lock_block(self, node: ast.AST) -> bool:
        """True when node sits inside a `with <lock>:` block — any
        context expression whose source mentions a lock/mutex name."""
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.With):
                for item in cur.items:
                    src = ast.unparse(item.context_expr).lower()
                    if "lock" in src or "mutex" in src:
                        return True
            cur = self.parents.get(cur)
        return False


def timed_span_name(expr: ast.AST) -> str | None:
    """`<anything>.timed("name")` -> "name" (literal args only)."""
    if (
        isinstance(expr, ast.Call)
        and isinstance(expr.func, ast.Attribute)
        and expr.func.attr == "timed"
        and expr.args
        and isinstance(expr.args[0], ast.Constant)
        and isinstance(expr.args[0].value, str)
    ):
        return expr.args[0].value
    return None


def call_basename(call: ast.Call) -> str | None:
    if isinstance(call.func, ast.Name):
        return call.func.id
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    return None


def is_jit_expr(expr: ast.AST) -> bool:
    """Matches jax.jit / jit / partial(jax.jit, ...) /
    functools.partial(jit, ...) expressions."""
    if isinstance(expr, ast.Attribute) and expr.attr == "jit":
        return True
    if isinstance(expr, ast.Name) and expr.id == "jit":
        return True
    if isinstance(expr, ast.Call):
        base = call_basename(expr)
        if base == "partial" and expr.args:
            return is_jit_expr(expr.args[0])
        if base == "jit":
            return True
    return False


def jit_static_names(deco: ast.AST, func: ast.AST) -> set[str]:
    """Parameter names declared static on a jit decorator
    (static_argnames literal, or static_argnums resolved positionally)."""
    out: set[str] = set()
    if not isinstance(deco, ast.Call):
        return out
    params = [a.arg for a in func.args.posonlyargs + func.args.args]
    for kw in deco.keywords:
        v = kw.value
        if kw.arg == "static_argnames":
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                out.add(v.value)
            elif isinstance(v, (ast.Tuple, ast.List, ast.Set)):
                out.update(
                    e.value
                    for e in v.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                )
        elif kw.arg == "static_argnums":
            nums = []
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                nums = [v.value]
            elif isinstance(v, (ast.Tuple, ast.List)):
                nums = [
                    e.value
                    for e in v.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, int)
                ]
            for n in nums:
                if 0 <= n < len(params):
                    out.add(params[n])
    return out


@dataclass
class FuncInfo:
    node: ast.FunctionDef | ast.AsyncFunctionDef
    sf: SourceFile
    qualname: str
    calls: set[str] = field(default_factory=set)  # called/ referenced basenames
    is_jit: bool = False
    static_names: set[str] = field(default_factory=set)

    @property
    def basename(self) -> str:
        return self.node.name


class PackageIndex:
    """Cross-file context rebuilt from the linted file set: function
    table, basename call graph, and the three reachability sets the
    rules consult (hot path, jit, worker)."""

    def __init__(self, files: list[SourceFile]):
        self.files = files
        self.functions: dict[str, list[FuncInfo]] = {}
        self._info_by_node: dict[ast.AST, FuncInfo] = {}
        #: dotted module name -> SourceFile (last one wins on collision)
        self.modules: dict[str, SourceFile] = {sf.module: sf for sf in files}
        #: fully-qualified dotted name -> FuncInfo for *top-level* defs
        #: (the targets import aliases can actually name)
        self.functions_qual: dict[str, FuncInfo] = {}
        for sf in files:
            self._index_file(sf)
        self.hot_reachable = self._reach(self._hot_roots())
        self.jit_reachable = self._reach(
            {fi.qualname for fis in self.functions.values() for fi in fis
             if fi.is_jit}
        )
        self.worker_roots = self._worker_roots()
        self.worker_reachable = self._reach(self.worker_roots)
        #: basenames with at least one jit-decorated definition
        self.jit_def_basenames = frozenset(
            name for name, fis in self.functions.items()
            if any(fi.is_jit for fi in fis)
        )
        #: basenames of jit-callable factories (computed once; the
        #: host-sync rule consults this on every hot function)
        self.factory_basenames = self._factory_basenames()

    def _index_file(self, sf: SourceFile) -> None:
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            qual = f"{sf.display}::{node.name}@{node.lineno}"
            fi = FuncInfo(node=node, sf=sf, qualname=qual)
            for deco in node.decorator_list:
                if is_jit_expr(deco):
                    fi.is_jit = True
                    fi.static_names |= jit_static_names(deco, node)
            # body-own statements only: nested defs index separately, and
            # their calls must not leak into the parent's edge set
            for sub in ast.walk(node):
                if sub is not node and isinstance(
                    sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    # still record the nested def as a referenced name so
                    # reachability descends into it
                    fi.calls.add(sub.name)
            for sub in self._own_nodes(node):
                if isinstance(sub, ast.Call):
                    base = call_basename(sub)
                    if base:
                        fi.calls.add(base)
                elif isinstance(sub, ast.Name) and isinstance(
                    sub.ctx, ast.Load
                ):
                    fi.calls.add(sub.id)  # functions passed as values
            self.functions.setdefault(node.name, []).append(fi)
            self._info_by_node[node] = fi
            if node in sf.tree.body or (
                isinstance(sf.parents.get(node), ast.ClassDef)
                and sf.parents[sf.parents[node]] is sf.tree
            ):
                dotted = (
                    f"{sf.module}.{sf.parents[node].name}.{node.name}"
                    if isinstance(sf.parents.get(node), ast.ClassDef)
                    else f"{sf.module}.{node.name}"
                )
                self.functions_qual.setdefault(dotted, fi)

    @staticmethod
    def _own_nodes(func: ast.AST) -> Iterator[ast.AST]:
        """Walk a function body without descending into nested defs."""
        stack = list(ast.iter_child_nodes(func))
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            stack.extend(ast.iter_child_nodes(node))

    def info(self, node: ast.AST) -> FuncInfo | None:
        return self._info_by_node.get(node)

    def resolve_call(self, sf: SourceFile, call: ast.Call) -> str | None:
        """Fully-qualified dotted name of a call target, when the
        module's import aliases pin it down: `observe.emit(...)` under
        `from ..utils import observe` resolves to
        `<pkg>.utils.observe.emit`; a bare `emit(...)` under
        `from .observe import emit` resolves the same way; a local
        top-level def resolves to `<module>.<name>`. Returns None when
        the target is dynamic (methods on instances, subscripts,
        shadowed names) — callers fall back to basename heuristics."""
        return sf.resolve_expr(call.func)

    def resolves_to(self, sf: SourceFile, call: ast.Call,
                    *dotted: str) -> bool:
        """True when resolve_call lands exactly on one of `dotted`."""
        target = self.resolve_call(sf, call)
        return target is not None and target in dotted

    def _factory_basenames(self) -> frozenset[str]:
        """Basenames of functions that return a jitted callable —
        directly (`return fn` where fn is a nested jit def) or via
        another factory (fixpoint over return-a-factory-call chains)."""
        returns: dict[str, list[ast.AST]] = {}
        nested_jit: dict[str, set[str]] = {}
        for name, fis in self.functions.items():
            for fi in fis:
                nested_jit.setdefault(name, set()).update(
                    sub.name
                    for sub in ast.walk(fi.node)
                    if sub is not fi.node
                    and isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and any(is_jit_expr(d) for d in sub.decorator_list)
                )
                for sub in self._own_nodes(fi.node):
                    if isinstance(sub, ast.Return) and sub.value is not None:
                        returns.setdefault(name, []).append(sub.value)
        factories: set[str] = set()
        changed = True
        while changed:
            changed = False
            for name, values in returns.items():
                if name in factories:
                    continue
                for v in values:
                    if isinstance(v, ast.Name) and v.id in nested_jit.get(
                        name, ()
                    ):
                        factories.add(name)
                        changed = True
                    elif isinstance(v, ast.Call):
                        base = call_basename(v)
                        if base in factories:
                            factories.add(name)
                            changed = True
        return frozenset(factories)

    def _hot_roots(self) -> set[str]:
        roots = set()
        for name, fis in self.functions.items():
            if name in HOT_PATH_ROOTS or name.startswith(HOT_PATH_PREFIX):
                roots.update(fi.qualname for fi in fis)
        return roots

    def _worker_roots(self) -> set[str]:
        """Functions handed to Thread(target=...) / pool.submit(f, ...)
        / pool.map(f, ...) anywhere in the linted set."""
        roots: set[str] = set()

        def resolve(expr: ast.AST) -> None:
            name = None
            if isinstance(expr, ast.Name):
                name = expr.id
            elif isinstance(expr, ast.Attribute):
                name = expr.attr
            if name:
                roots.update(fi.qualname for fi in self.functions.get(name, ()))

        for sf in self.files:
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                base = call_basename(node)
                if base == "Thread":
                    span = range(
                        node.lineno, (node.end_lineno or node.lineno) + 1
                    )
                    if any(ln in sf.owned_thread_lines for ln in span):
                        continue  # declared single-owner, not a worker
                    for kw in node.keywords:
                        if kw.arg == "target":
                            resolve(kw.value)
                elif base in ("submit", "map", "apply_async") and isinstance(
                    node.func, ast.Attribute
                ):
                    if node.args:
                        resolve(node.args[0])
        return roots

    def _reach(self, roots: set[str]) -> set[str]:
        """BFS over basename edges from qualname roots -> qualname set."""
        by_qual = {
            fi.qualname: fi
            for fis in self.functions.values()
            for fi in fis
        }
        seen = set(roots)
        frontier = [by_qual[q] for q in roots if q in by_qual]
        while frontier:
            fi = frontier.pop()
            for callee in fi.calls:
                for nxt in self.functions.get(callee, ()):
                    if nxt.qualname not in seen:
                        seen.add(nxt.qualname)
                        frontier.append(nxt)
        return seen

    # -- membership helpers used by rules --------------------------------

    def _member(self, sf: SourceFile, node: ast.AST, pool: set[str]) -> bool:
        for func in sf.enclosing_functions(node):
            fi = self._info_by_node.get(func)
            if fi is not None and fi.qualname in pool:
                return True
        return False

    def in_hot_path(self, sf: SourceFile, node: ast.AST) -> bool:
        return self._member(sf, node, self.hot_reachable)

    def in_worker(self, sf: SourceFile, node: ast.AST) -> bool:
        return self._member(sf, node, self.worker_reachable)


# --------------------------------------------------------------------------
# registry + runner


def all_rules() -> dict[str, Rule]:
    from bsseqconsensusreads_tpu.analysis import (
        rules_contract,
        rules_deflate,
        rules_elastic,
        rules_emit,
        rules_fence,
        rules_hostphase,
        rules_input,
        rules_io,
        rules_jax,
        rules_methyl,
        rules_pack,
        rules_retry,
        rules_serve,
        rules_thread,
        rules_trace,
        rules_transport,
    )

    rules: dict[str, Rule] = {}
    for mod in (rules_jax, rules_thread, rules_io, rules_retry,
                rules_hostphase, rules_input, rules_emit, rules_serve,
                rules_pack, rules_methyl, rules_transport, rules_deflate,
                rules_elastic, rules_fence, rules_trace, rules_contract):
        for rule in mod.RULES:
            rules[rule.name] = rule
    return rules


def _collect_py(paths: Iterable[str]) -> list[tuple[str, str]]:
    """[(abs path, display path)] for every .py under the given paths."""
    out: list[tuple[str, str]] = []
    cwd = os.getcwd()
    for p in paths:
        ap = os.path.abspath(p)
        if os.path.isfile(ap):
            out.append((ap, os.path.relpath(ap, cwd)))
        elif os.path.isdir(ap):
            for root, dirs, names in os.walk(ap):
                dirs[:] = sorted(
                    d for d in dirs if d != "__pycache__" and not d.startswith(".")
                )
                for name in sorted(names):
                    if name.endswith(".py"):
                        fp = os.path.join(root, name)
                        out.append((fp, os.path.relpath(fp, cwd)))
        else:
            raise LintError(f"no such file or directory: {p}")
    return out


def run_lint(
    paths: Iterable[str],
    rules: Iterable[str] | None = None,
    include_suppressed: bool = False,
) -> list[Finding]:
    """Lint every .py under `paths` with the named rules (default all).

    Returns unsuppressed findings sorted by (path, line, rule); raises
    LintError for unknown rule names — whether given here or referenced
    by a `# graftlint: disable=` comment in the sources."""
    registry = all_rules()
    if rules is None:
        selected = list(registry.values())
    else:
        unknown = sorted(set(rules) - set(registry))
        if unknown:
            raise LintError(
                f"unknown rule(s) {unknown} (known: "
                f"{', '.join(sorted(registry))})"
            )
        selected = [registry[name] for name in rules]

    files = []
    for ap, display in _collect_py(paths):
        with open(ap, encoding="utf-8") as fh:
            source = fh.read()
        files.append(SourceFile(ap, display, source, registry))
    index = PackageIndex(files)

    findings: list[Finding] = []
    for sf in files:
        for rule in selected:
            for f in rule.check(sf, index):
                if include_suppressed or not sf.suppressed(f.rule, f.line):
                    findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
