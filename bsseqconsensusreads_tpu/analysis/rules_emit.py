"""graftlint record-path-discipline rule: per-record-alloc.

The failure class ISSUE 6 (native columnar record path) closed: Python
object construction executed ONCE PER RECORD on an emit- or
sort-reachable hot path. The r05 scale ledger put numbers on it — 121 s
of molecular `emit` and 411 s of `sort_write` were per-record
`BamRecord(...)` building, `.tolist()` tag conversion, and per-blob
generator hops, while the kernels cost 12 s. The native columnar path
(io.wirepack emit + pipeline.extsort native sort) exists precisely so no
such code runs between kernel retire and bytes-on-disk; this rule keeps
new per-record allocation from creeping back in.

Scope: functions that are (a) hot-path reachable (batch-loop roots,
analysis.engine.HOT_PATH_ROOTS) and (b) reachable from an emit/sort
root — a hot function whose basename contains 'emit' or 'sort'. Inside
any loop or comprehension there, the rule flags:

* ``BamRecord(...)`` / ``decode_record(...)`` — a Python record object
  per iteration;
* ``<x>.tolist()`` — a Python list (and boxed ints) per iteration;
* string concatenation with a literal (``"x" + y`` / ``y + "x"``) — a
  new str per iteration; builders belong at batch level.

The Python parity twins construct records per record BY DESIGN — but
their loops now pre-compute tag scalars at batch level and hand numpy
arrays through, so the package self-application stays CLEAN without
suppressions; a twin regression (a new `.tolist()` in the loop) is
exactly what this rule should catch.
"""

from __future__ import annotations

import ast
from typing import Iterator

from bsseqconsensusreads_tpu.analysis.engine import (
    Finding,
    PackageIndex,
    Rule,
    SourceFile,
    call_basename,
)

#: Call basenames that build one Python record object per call.
_RECORD_CTORS = frozenset({"BamRecord", "decode_record"})

_LOOPS = (
    ast.For,
    ast.While,
    ast.ListComp,
    ast.SetComp,
    ast.DictComp,
    ast.GeneratorExp,
)


def _emit_sort_reach(index: PackageIndex) -> set[str]:
    """Qualnames reachable from a hot emit/sort root (basename contains
    'emit' or 'sort'), via the same basename call graph the engine's
    other reachability sets use."""
    roots = {
        fi.qualname
        for name, fis in index.functions.items()
        if "emit" in name.lower() or "sort" in name.lower()
        for fi in fis
        if fi.qualname in index.hot_reachable
    }
    return index._reach(roots)


def _in_loop(sf: SourceFile, node: ast.AST, func: ast.AST) -> bool:
    """Whether node sits inside a loop/comprehension WITHIN func."""
    cur = sf.parents.get(node)
    while cur is not None and cur is not func:
        if isinstance(cur, _LOOPS):
            return True
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return False
        cur = sf.parents.get(cur)
    return False


def _is_str_concat(node: ast.BinOp) -> bool:
    """`"lit" + x` / `x + "lit"` — a per-iteration str build. Literal-
    anchored on purpose: numeric BinOps (offset math) are everywhere on
    hot paths and are not allocations of interest."""
    if not isinstance(node.op, ast.Add):
        return False
    return any(
        isinstance(side, ast.Constant) and isinstance(side.value, str)
        for side in (node.left, node.right)
    )


def check_per_record_alloc(
    sf: SourceFile, index: PackageIndex
) -> Iterator[Finding]:
    reach = _emit_sort_reach(index)
    if not reach:
        return
    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        fi = index.info(node)
        if (
            fi is None
            or fi.qualname not in reach
            or fi.qualname not in index.hot_reachable
        ):
            continue
        for sub in PackageIndex._own_nodes(node):
            what = None
            if isinstance(sub, ast.Call):
                base = call_basename(sub)
                if base in _RECORD_CTORS:
                    what = f"{base}(...) builds a record object"
                elif (
                    isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "tolist"
                ):
                    what = ".tolist() boxes an array into Python objects"
            elif isinstance(sub, ast.BinOp) and _is_str_concat(sub):
                what = "string concatenation builds a new str"
            if what is None or not _in_loop(sf, sub, node):
                continue
            yield Finding(
                rule="per-record-alloc",
                path=sf.display,
                line=sub.lineno,
                col=sub.col_offset,
                message=(
                    f"{what} once per loop iteration inside the emit/"
                    f"sort-reachable hot function {node.name!r} — "
                    "per-record Python allocation is the host record-"
                    "path wall (r05: 121 s emit / 411 s sort_write vs "
                    "12 s of kernels). Batch it: hand kernel output "
                    "planes to the native columnar emitter "
                    "(io.wirepack.emit_consensus_records), keep tag "
                    "arrays numpy (io.bam._encode_tags serializes them "
                    "vectorized), or precompute per-record scalars at "
                    "batch level (pipeline.calling._span_stats)"
                ),
            )


RULES = [
    Rule(
        name="per-record-alloc",
        summary="per-record Python object construction (BamRecord, "
        ".tolist(), str concat) in a loop on an emit/sort-reachable "
        "hot path",
        check=check_per_record_alloc,
    ),
]
