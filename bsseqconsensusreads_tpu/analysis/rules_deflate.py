"""graftlint codec-discipline rule: serial-deflate.

The failure class ISSUE 12's codec tier (io.pbgzf) closed: block
compression executed inline on a merge/emit-reachable hot path. The r06
scale ledger put numbers on it — 65 s of the molecular stage's 96.5 s
merge was `merge_bgzf`, serial deflate on the one thread that also runs
the k-way merge. The sanctioned shape is a writer from the codec tier:
`io.bam._create_bgzf` (which auto-selects `io.pbgzf.PBgzfWriter` when
workers are available) or `io.bgzf.BgzfWriter` for genuinely serial
contexts — never `zlib.compress`/`compressobj` or a hand-rolled
`deflate_block` call at the point of the merge/emit loop, where it pins
the deflate to the merge thread and starves the parallel tier.

Scope: functions that are (a) hot-path reachable and (b) reachable from
a hot merge/emit/sort root (basename contains 'merge', 'emit' or
'sort'). The codec tier itself — io/bgzf.py and io/pbgzf.py — IS the
sanctioned deflate site and is exempt by path.
"""

from __future__ import annotations

import ast
from typing import Iterator

from bsseqconsensusreads_tpu.analysis.engine import (
    Finding,
    PackageIndex,
    Rule,
    SourceFile,
    call_basename,
)

#: The codec tier: the only modules allowed to build deflate streams.
_CODEC_FILES = ("io/bgzf.py", "io/pbgzf.py")

#: zlib entry points that open a serial deflate stream.
_ZLIB_COMPRESS = frozenset({"compress", "compressobj"})


def _merge_emit_reach(index: PackageIndex) -> set[str]:
    """Qualnames reachable from a hot merge/emit/sort root, via the same
    basename call graph the engine's other reachability sets use."""
    roots = {
        fi.qualname
        for name, fis in index.functions.items()
        if any(k in name.lower() for k in ("merge", "emit", "sort"))
        for fi in fis
        if fi.qualname in index.hot_reachable
    }
    return index._reach(roots)


def _is_serial_deflate(node: ast.Call) -> str | None:
    func = node.func
    if (
        isinstance(func, ast.Attribute)
        and func.attr in _ZLIB_COMPRESS
        and isinstance(func.value, ast.Name)
        and func.value.id == "zlib"
    ):
        return f"zlib.{func.attr}(...)"
    if call_basename(node) == "deflate_block":
        return "deflate_block(...)"
    return None


def check_serial_deflate(
    sf: SourceFile, index: PackageIndex
) -> Iterator[Finding]:
    if sf.display.replace("\\", "/").endswith(_CODEC_FILES):
        return
    reach = _merge_emit_reach(index)
    if not reach:
        return
    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        fi = index.info(node)
        if (
            fi is None
            or fi.qualname not in reach
            or fi.qualname not in index.hot_reachable
        ):
            continue
        for sub in PackageIndex._own_nodes(node):
            if not isinstance(sub, ast.Call):
                continue
            what = _is_serial_deflate(sub)
            if what is None:
                continue
            yield Finding(
                rule="serial-deflate",
                path=sf.display,
                line=sub.lineno,
                col=sub.col_offset,
                message=(
                    f"{what} inline in the merge/emit-reachable hot "
                    f"function {node.name!r} — serial block compression "
                    "on the merge thread is the sort_write wall the "
                    "parallel codec tier removes (r06: 65 s of the "
                    "96.5 s molecular merge was merge_bgzf). Write "
                    "through a codec-tier writer instead: "
                    "io.bam._create_bgzf auto-selects the parallel "
                    "io.pbgzf.PBgzfWriter when workers are available"
                ),
            )


RULES = [
    Rule(
        name="serial-deflate",
        summary="inline zlib/BGZF block compression on merge/emit-"
        "reachable hot paths instead of the parallel codec tier",
        check=check_serial_deflate,
    ),
]
